"""Reliability units on fake clocks — retry policy, deadline, circuit
breaker, fault injector — plus the serving-facing behaviors they gate:
bounded-queue shedding (429 + Retry-After), deadline-capped parking, and
the engine's halved-batch degradation (docs/reliability.md)."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu import observability as obs
from mmlspark_tpu.reliability import (BreakerOpen, CircuitBreaker, Deadline,
                                      DeadlineExceeded, FaultInjector,
                                      InjectedFault, RetryPolicy, breaker_for,
                                      get_injector, reset_breakers)
from mmlspark_tpu.reliability.breaker import CLOSED, HALF_OPEN, OPEN


@pytest.fixture(autouse=True)
def _fresh_state():
    obs.reset_all()
    reset_breakers()
    get_injector().clear()
    yield
    get_injector().clear()
    reset_breakers()
    obs.reset_all()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t
        self.sleeps = []

    def __call__(self):
        return self.t

    def sleep(self, seconds):
        self.sleeps.append(seconds)
        self.t += seconds


def _series_value(snap, name, **labels):
    for s in snap[name]["series"]:
        if s["labels"] == labels:
            return s["value"]
    return 0.0


# ---------------------------------------------------------------------------
# RetryPolicy


def _flaky(failures, exc=ConnectionError):
    state = {"calls": 0}

    def fn():
        state["calls"] += 1
        if state["calls"] <= failures:
            raise exc(f"boom {state['calls']}")
        return "ok"

    return fn, state


def test_retry_succeeds_after_transient_failures():
    clk = FakeClock()
    policy = RetryPolicy(max_attempts=4, base_delay=0.1, clock=clk,
                         sleep=clk.sleep)
    fn, state = _flaky(2)
    assert policy.call(fn, site="unit") == "ok"
    assert state["calls"] == 3
    assert len(clk.sleeps) == 2
    # re-attempts are counted by site
    assert _series_value(obs.snapshot(), "mmlspark_retry_attempts_total",
                         site="unit") == 2


def test_retry_exhausts_max_attempts():
    clk = FakeClock()
    policy = RetryPolicy(max_attempts=3, clock=clk, sleep=clk.sleep)
    fn, state = _flaky(99)
    with pytest.raises(ConnectionError, match="boom 3"):
        policy.call(fn)
    assert state["calls"] == 3


def test_retry_full_jitter_bounded_by_exponential_ceiling():
    import random
    clk = FakeClock()
    policy = RetryPolicy(max_attempts=8, base_delay=0.1, max_delay=0.4,
                         clock=clk, sleep=clk.sleep, rng=random.Random(7))
    fn, _ = _flaky(7)
    policy.call(fn)
    ceilings = [0.1, 0.2, 0.4, 0.4, 0.4, 0.4, 0.4]
    assert len(clk.sleeps) == 7
    for delay, ceiling in zip(clk.sleeps, ceilings):
        assert 0.0 <= delay <= ceiling


def test_retry_giveup_predicate_short_circuits():
    policy = RetryPolicy(max_attempts=5,
                         giveup=lambda e: isinstance(e, ValueError),
                         sleep=lambda s: None)
    fn, state = _flaky(3, exc=ValueError)
    with pytest.raises(ValueError):
        policy.call(fn)
    assert state["calls"] == 1


def test_retry_respects_total_budget():
    clk = FakeClock()
    # backoff is deterministic 0.5 with a constant rng
    policy = RetryPolicy(max_attempts=100, base_delay=1.0, max_delay=1.0,
                         total_budget=1.2, clock=clk, sleep=clk.sleep)
    policy.rng = type("R", (), {"uniform": lambda self, a, b: 0.5})()
    fn, state = _flaky(99)
    with pytest.raises(ConnectionError):
        policy.call(fn)
    # 0.5 + 0.5 spent; a third re-attempt would cross 1.2
    assert state["calls"] == 3


def test_retry_respects_deadline():
    clk = FakeClock()
    policy = RetryPolicy(max_attempts=100, base_delay=1.0, max_delay=1.0,
                         clock=clk, sleep=clk.sleep)
    policy.rng = type("R", (), {"uniform": lambda self, a, b: 0.4})()
    deadline = Deadline.after(1.0, clock=clk)
    fn, state = _flaky(99)
    with pytest.raises(ConnectionError):
        policy.call(fn, deadline=deadline)
    # sleeps 0.4, 0.4; the next 0.4 would exceed the 0.2 remaining
    assert state["calls"] == 3


def test_retry_rejects_bad_max_attempts():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)


# ---------------------------------------------------------------------------
# Deadline


def test_deadline_remaining_and_expiry():
    clk = FakeClock()
    d = Deadline.after(2.0, clock=clk)
    assert d.remaining() == pytest.approx(2.0)
    assert not d.expired
    clk.t += 2.5
    assert d.remaining() == pytest.approx(-0.5)
    assert d.expired
    assert d.cap(10.0) == pytest.approx(-0.5)


def test_deadline_header_round_trip():
    clk = FakeClock()
    d = Deadline.after(2.0, clock=clk)
    clk.t += 0.5
    value = d.header_value()
    assert value == "1.500"
    d2 = Deadline.from_header(value, clock=clk)
    assert d2.remaining() == pytest.approx(1.5)


@pytest.mark.parametrize("garbage", ["", "abc", None, "nan", "inf", "1e999"])
def test_deadline_malformed_header_is_none(garbage):
    assert Deadline.from_header(garbage) is None


def test_deadline_header_value_never_negative():
    clk = FakeClock()
    d = Deadline.after(0.1, clock=clk)
    clk.t += 5.0
    assert d.header_value() == "0.000"


# ---------------------------------------------------------------------------
# CircuitBreaker


def _trip(brk, n):
    for _ in range(n):
        brk.record_failure()


def test_breaker_opens_at_failure_ratio_and_blocks():
    clk = FakeClock()
    brk = CircuitBreaker("p", window=10, min_calls=4, failure_ratio=0.5,
                         open_seconds=5.0, clock=clk)
    brk.record_success()
    brk.record_success()
    _trip(brk, 2)  # 2/4 = 0.5 → trips
    assert brk.state == OPEN
    assert not brk.allow()
    snap = obs.snapshot()
    assert _series_value(snap, "mmlspark_breaker_state", peer="p") == 1.0
    assert _series_value(snap, "mmlspark_breaker_transitions_total",
                         peer="p", to="open") == 1.0


def test_breaker_stays_closed_below_min_calls():
    brk = CircuitBreaker("p", min_calls=5, failure_ratio=0.5,
                         clock=FakeClock())
    _trip(brk, 4)
    assert brk.state == CLOSED and brk.allow()


def test_breaker_half_open_probe_success_closes():
    clk = FakeClock()
    brk = CircuitBreaker("p", window=10, min_calls=2, failure_ratio=0.5,
                         open_seconds=3.0, clock=clk)
    _trip(brk, 2)
    assert brk.state == OPEN
    clk.t += 3.1
    assert brk.allow()               # the single half-open probe
    assert brk.state == HALF_OPEN
    assert not brk.allow()           # concurrent calls stay blocked
    brk.record_success()
    assert brk.state == CLOSED and brk.allow()
    assert _series_value(obs.snapshot(), "mmlspark_breaker_state",
                         peer="p") == 0.0


def test_breaker_half_open_probe_failure_reopens():
    clk = FakeClock()
    brk = CircuitBreaker("p", min_calls=2, failure_ratio=0.5,
                         open_seconds=3.0, clock=clk)
    _trip(brk, 2)
    clk.t += 3.1
    assert brk.allow()
    brk.record_failure()
    assert brk.state == OPEN
    assert not brk.allow()           # open window restarted
    clk.t += 3.1
    assert brk.allow()               # and a new probe after it elapses


def test_breaker_registry_is_per_peer():
    a, b = breaker_for("addr-a"), breaker_for("addr-b")
    assert a is breaker_for("addr-a")
    assert a is not b
    assert isinstance(BreakerOpen("addr-a"), ConnectionError)


# ---------------------------------------------------------------------------
# FaultInjector


def test_fault_injector_disabled_is_passthrough():
    inj = FaultInjector()
    assert not inj.enabled
    assert inj.fire("peer_http", {"a": 1}) == {"a": 1}


def test_fault_error_rule_raises_and_counts():
    inj = FaultInjector()
    inj.add("peer_http", "error")
    with pytest.raises(InjectedFault) as err:
        inj.fire("peer_http")
    assert err.value.site == "peer_http"
    assert isinstance(err.value, ConnectionError)
    assert _series_value(obs.snapshot(), "mmlspark_faults_injected_total",
                         site="peer_http", kind="error") == 1.0


def test_fault_probability_is_seed_deterministic():
    def decisions(seed):
        inj = FaultInjector()
        inj.add("s", "error", p=0.5, seed=seed)
        out = []
        for _ in range(32):
            try:
                inj.fire("s")
                out.append(False)
            except InjectedFault:
                out.append(True)
        return out

    a, b = decisions(42), decisions(42)
    assert a == b                    # same seed → same schedule
    assert True in a and False in a  # and it's actually probabilistic
    assert decisions(43) != a


def test_fault_every_and_times_schedules():
    inj = FaultInjector()
    rule = inj.add("s", "error", every=3, times=2)
    fired = []
    for i in range(1, 10):
        try:
            inj.fire("s")
            fired.append(False)
        except InjectedFault:
            fired.append(True)
    # fires on calls 3 and 6, then the `times` cap stops call 9
    assert fired == [False, False, True, False, False, True,
                     False, False, False]
    assert rule.fires == 2


def test_fault_delay_uses_injected_sleep():
    slept = []
    inj = FaultInjector(sleep=slept.append)
    inj.add("s", "delay", seconds=0.25)
    inj.fire("s")
    assert slept == [0.25]


def test_fault_corrupt_payloads():
    inj = FaultInjector()
    inj.add("s", "corrupt")
    assert inj.fire("s", {"x": 1}) == {"x": 1, "_corrupted": True}
    assert inj.fire("s", b"abc") == b"ab"
    assert inj.fire("s", None) is None


def test_fault_env_spec_grammar():
    inj = FaultInjector()
    inj.configure("peer_http:error:p=0.3:seed=7; heartbeat:delay:every=3:"
                  "seconds=0.05;enqueue:error:times=2")
    rules = {r.site: r for r in inj.rules()}
    assert rules["peer_http"].p == 0.3 and rules["peer_http"].seed == 7
    assert rules["heartbeat"].every == 3
    assert rules["heartbeat"].seconds == 0.05
    assert rules["enqueue"].times == 2
    inj.clear()
    assert not inj.enabled and inj.rules() == []


@pytest.mark.parametrize("bad", ["peer_http", "s:explode", "s:error:p",
                                 "s:error:bogus=1", "s:error:p=abc"])
def test_fault_spec_rejects_bad_grammar(bad):
    with pytest.raises(ValueError):
        FaultInjector().configure(bad)


# ---------------------------------------------------------------------------
# serving integration: shedding, deadlines, engine degradation


def _post(url, payload, timeout=20.0, headers=()):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers)
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_full_queue_sheds_429_with_retry_after(transport):
    from mmlspark_tpu.serving.server import WorkerServer
    ws = WorkerServer(max_queue=1, reply_timeout=10.0, transport=transport,
                      shed_retry_after=2.5)
    try:
        parked = [None]
        t = threading.Thread(
            target=lambda: parked.__setitem__(0, _post(ws.address, {"n": 1})))
        t.start()
        deadline = time.time() + 5
        while not ws._queue.full() and time.time() < deadline:
            time.sleep(0.01)
        assert ws._queue.full(), "first request never parked"
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(ws.address, {"n": 2}, timeout=5.0)
        assert err.value.code == 429
        assert err.value.headers["Retry-After"] == "2.5"
        assert _series_value(obs.snapshot(),
                             "mmlspark_requests_shed_total") >= 1.0
        # the shed request must leave no routing-table entry behind
        assert ws.pending_count() == 1
        rid = next(iter(ws._routing))
        assert ws.reply_json(rid, {"ok": True})
        t.join(timeout=10)
        assert parked[0][0] == 200
    finally:
        ws.close()


def test_deadline_header_caps_park_time():
    from mmlspark_tpu.serving.server import WorkerServer
    ws = WorkerServer(reply_timeout=30.0)
    try:
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(ws.address, {"n": 1}, timeout=10.0,
                  headers={"X-Mmlspark-Deadline": "0.3"})
        assert err.value.code == 504
        # parked for ~the propagated budget, nowhere near reply_timeout
        assert time.monotonic() - t0 < 5.0
    finally:
        ws.close()


def test_closed_property_reflects_lifecycle():
    from mmlspark_tpu.serving.server import WorkerServer
    ws = WorkerServer()
    assert not ws.closed
    ws.close()
    assert ws.closed


def test_engine_retries_failed_batch_at_half_size():
    from mmlspark_tpu.core.dataframe import DataFrame, object_col
    from mmlspark_tpu.serving.engine import ServingEngine

    sizes = []

    def transform(df):
        sizes.append(len(df))
        if len(df) > 1:
            raise RuntimeError("synthetic whole-batch OOM")
        return DataFrame({"id": df["id"],
                          "reply": object_col([{"ok": True}])})

    engine = ServingEngine(transform, schema=None, poll_timeout=0.05,
                           reply_timeout=15.0)
    try:
        out = [None, None]
        threads = [threading.Thread(
            target=lambda i=i: out.__setitem__(
                i, _post(engine.address, {"n": i})))
            for i in range(2)]
        for t in threads:
            t.start()
        deadline = time.time() + 5
        while engine.server._queue.qsize() < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert engine.server._queue.qsize() == 2, "requests did not coalesce"
        engine.start()   # both park first → one batch of 2 → halves of 1
        for t in threads:
            t.join(timeout=15)
        assert [o[0] for o in out] == [200, 200]
        assert sizes[0] == 2 and sorted(sizes[1:]) == [1, 1]
        assert _series_value(obs.snapshot(), "mmlspark_retry_attempts_total",
                             site="engine_batch") == 2.0
    finally:
        engine.stop()


def test_engine_fails_rows_when_halves_also_fail():
    from mmlspark_tpu.serving.engine import ServingEngine

    def transform(df):
        raise RuntimeError("always broken")

    engine = ServingEngine(transform, schema=None, poll_timeout=0.05,
                           reply_timeout=15.0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(engine.address, {"n": 1}, timeout=10.0)
        assert err.value.code == 500
    finally:
        engine.stop()


def test_device_run_fault_site_degrades_gracefully():
    from mmlspark_tpu.core.dataframe import DataFrame, object_col
    from mmlspark_tpu.serving.engine import ServingEngine

    def transform(df):
        return DataFrame({"id": df["id"],
                          "reply": object_col([{"ok": True}] * len(df))})

    # one injected device fault kills the first (full) batch; the halved
    # retry answers both requests anyway
    get_injector().add("device_run", "error", times=1)
    engine = ServingEngine(transform, schema=None, poll_timeout=0.05,
                           reply_timeout=15.0)
    try:
        out = [None, None]
        threads = [threading.Thread(
            target=lambda i=i: out.__setitem__(
                i, _post(engine.address, {"n": i})))
            for i in range(2)]
        for t in threads:
            t.start()
        deadline = time.time() + 5
        while engine.server._queue.qsize() < 2 and time.time() < deadline:
            time.sleep(0.01)
        engine.start()
        for t in threads:
            t.join(timeout=15)
        assert [o[0] for o in out] == [200, 200]
    finally:
        engine.stop()
