"""Measurement-driven autotuning tests (ROADMAP item 4).

The contract under test: the observation store round-trips rows through its
append-only JSONL file and tolerates corrupt lines; the fitted cost model's
pick beats both endpoint configs of a synthetic skewed workload; a cold
model's measured sweep is bounded by the probe budget and every probe lands
in the store; ``BatchRunner(tuning="auto")`` applies the store's pick
end-to-end with ZERO steady-state recompiles after warming exactly the
chosen vocabulary (asserted through the compile-cache counters); and the
decision is reproducible from the persisted store alone.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.models.runner import BatchRunner
from mmlspark_tpu.ops.compile_cache import (M_STEADY_RECOMPILES,
                                            M_WARMUP_BUCKETS,
                                            warm_up_jitted)
from mmlspark_tpu.tuning import (CostModel, Observation, ObservationStore,
                                 candidate_configs, import_bench_records,
                                 measured_sweep, probe_budget, set_store)
from mmlspark_tpu.tuning.cost_model import (M_PROBES, PROBE_BUDGET_ENV,
                                            resolve_tuning)
from mmlspark_tpu.tuning.observations import harvest_samples


@pytest.fixture
def store():
    """A fresh in-memory store installed as the process-global one, so
    runner harvests and sweep probes in a test never leak across tests."""
    s = ObservationStore()
    set_store(s)
    yield s
    set_store(None)


def linear_rows(sig, *, alpha=0.01, beta=1e-4, prep=1e-5,
                buckets=(64, 128), batches=10):
    """Per-bucket samples lying exactly on sec/batch = alpha + beta*bucket."""
    out = []
    for b in buckets:
        out.append(Observation(
            sig=sig, source="runner", bucket=b, rows=b * batches,
            batches=batches, seconds=(alpha + beta * b) * batches,
            prep_seconds=prep * b * batches))
    return out


# ---------------------------------------------------------------------------
# observation store
# ---------------------------------------------------------------------------

class TestObservationStore:
    def test_round_trip(self, tmp_path):
        s1 = ObservationStore(str(tmp_path))
        s1.record_many(linear_rows("m1"))
        s1.record(Observation(sig="m2", source="probe", rows_per_sec=123.4,
                              config={"mini_batch_size": 32,
                                      "prefetch_depth": 1, "buckets": None}))
        # a second store over the same directory sees every row
        s2 = ObservationStore(str(tmp_path))
        assert len(s2) == 3
        assert s2.rows(sig="m1") == s1.rows(sig="m1")
        assert s2.rows(sig="m2")[0]["rows_per_sec"] == 123.4
        assert s2.signatures() == ["m1", "m2"]
        assert s2.corrupt_lines == 0

    def test_corrupt_lines_tolerated(self, tmp_path):
        s1 = ObservationStore(str(tmp_path))
        s1.record_many(linear_rows("m1"))
        path = os.path.join(str(tmp_path), "observations.jsonl")
        with open(path, "a", encoding="utf-8") as fh:
            fh.write("not json at all\n")
            fh.write(json.dumps({"no": "sig"}) + "\n")   # missing keys
            fh.write('{"sig": "torn", "source": "runn')  # torn tail
        s2 = ObservationStore(str(tmp_path))
        assert len(s2) == 2                 # the good rows survive
        assert s2.corrupt_lines == 3
        # the log is not poisoned: appends still work after a bad load
        s2.record(Observation(sig="m1", source="runner", bucket=32,
                              rows=32, batches=1, seconds=0.01))
        assert len(ObservationStore(str(tmp_path))) == 3

    def test_record_validates_required_keys(self, store):
        with pytest.raises(ValueError):
            store.record({"source": "runner"})          # no sig
        with pytest.raises(ValueError):
            store.record({"sig": "x"})                  # no source

    def test_filters(self, store):
        store.record_many(linear_rows("a"))
        store.record(Observation(sig="a", source="probe", placement="chip1",
                                 rows_per_sec=10.0))
        assert len(store.rows(sig="a", source="probe")) == 1
        assert len(store.rows(sig="a", placement="chip1")) == 1
        assert store.rows(sig="missing") == []

    def test_import_bench_records(self, tmp_path, store):
        wrapper = {"n": 4, "cmd": "python bench.py", "rc": 0, "tail": "",
                   "parsed": {"metric": "resnet50_onnx_images_per_sec_per_chip",
                              "value": 268.09, "platform": "tpu",
                              "stage_counters": {
                                  "compile": {"calls": 3, "seconds": 9.0}}}}
        raw = {"metric": "resnet50_onnx_images_per_sec_per_chip",
               "value": 9.13, "platform": "cpu"}
        crashed = {"n": 1, "rc": 1, "tail": "boom", "parsed": None}
        for name, payload in (("BENCH_r04.json", wrapper),
                              ("BENCH_r03.json", raw),
                              ("BENCH_r01.json", crashed)):
            with open(tmp_path / name, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
        n = import_bench_records(
            [str(tmp_path / f) for f in
             ("BENCH_r01.json", "BENCH_r03.json", "BENCH_r04.json",
              "BENCH_r99_missing.json")], store)
        assert n == 2                       # crashed + missing are skipped
        rows = store.rows(source="bench")
        assert sorted(r["rows_per_sec"] for r in rows) == [9.13, 268.09]
        assert rows[1]["compiles"] == 3 or rows[0]["compiles"] == 3

    def test_generation_observations_carry_paged_attn_impl(
            self, tmp_path, store):
        """Records with a generation phase yield an extra 'generation'
        observation stamped with the paged-attention impl, and
        compare_paged_attn turns them into per-placement speedups."""
        from mmlspark_tpu.tuning import compare_paged_attn

        def rec(val, tps, impl):
            return {"metric": "resnet50_onnx_images_per_sec_per_chip",
                    "value": val, "platform": "cpu", "device": "cpu",
                    "generation": {"tok_per_sec": tps, "tokens": 100,
                                   "wall_s": 1.0,
                                   "paged_attn": {"impl": impl}}}
        for name, payload in (("BENCH_r06.json", rec(5.0, 120.0, "kernel")),
                              ("BENCH_r07.json", rec(6.0, 80.0, "gather"))):
            with open(tmp_path / name, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
        n = import_bench_records(
            [str(tmp_path / "BENCH_r06.json"),
             str(tmp_path / "BENCH_r07.json")], store)
        assert n == 4                      # headline + generation per file
        gen = store.rows(sig="generation")
        assert sorted(r["paged_attn_impl"] for r in gen) \
            == ["gather", "kernel"]
        cmp = compare_paged_attn(store)
        assert cmp["cpu"]["kernel"]["tok_per_sec_mean"] == 120.0
        assert cmp["cpu"]["kernel_vs_gather_speedup"] == 1.5


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

class TestCostModel:
    def test_fit_recovers_linear_coefficients(self):
        m = CostModel.fit(linear_rows("s", alpha=0.02, beta=5e-4,
                                      buckets=(32, 64, 128, 256)))
        assert m.alpha == pytest.approx(0.02, rel=1e-6)
        assert m.beta == pytest.approx(5e-4, rel=1e-6)
        assert m.prep_rate > 0

    def test_single_bucket_degrades_to_pure_slope(self):
        m = CostModel.fit(linear_rows("s", buckets=(64,)))
        assert m.alpha == 0.0
        assert m.beta > 0.0

    def test_pick_beats_both_endpoints(self):
        """Skewed workload: runs of 66 rows. The endpoints both lose —
        tiny batches pay the per-dispatch intercept 5x per run, the
        power-of-two default pads 66 up to 128 — so the model must pick
        something strictly cheaper than either."""
        m = CostModel.fit(linear_rows("s", alpha=0.01, beta=1e-4))
        hist = {66: 4}
        cands = candidate_configs(hist, defaults=(64, 2))
        lo = min(c[0] for c in cands)
        hi = max(c[0] for c in cands)
        pick = m.choose(hist, defaults=(64, 2))
        sec_pick = m.predict_seconds(hist, pick.mini_batch_size,
                                     pick.prefetch_depth, pick.buckets)
        sec_lo = m.predict_seconds(hist, lo, 2, None)    # many dispatches
        sec_hi = m.predict_seconds(hist, hi, 2, None)    # pow2 pad waste
        assert sec_pick < sec_lo
        assert sec_pick <= sec_hi
        # the pick pads nothing: the exact ladder covers the run size
        assert pick.buckets is not None
        assert 66 in pick.vocabulary

    def test_probe_rows_outrank_the_fit(self):
        rows = linear_rows("s")
        rows.append(Observation(
            sig="s", source="probe", rows_per_sec=1e6,
            config={"mini_batch_size": 16, "prefetch_depth": 0,
                    "buckets": None}))
        m = CostModel.fit(rows)
        # the directly-measured config predicts from its measurement
        assert m.predict_seconds({64: 1}, 16, 0, None) \
            == pytest.approx(64 / 1e6)

    def test_decision_reproducible_from_persisted_store(self, tmp_path):
        """Acceptance criterion: delete the model, re-fit from the JSONL
        alone, same pick."""
        s1 = ObservationStore(str(tmp_path))
        s1.record_many(linear_rows("s", alpha=0.02))
        d1 = CostModel.fit(s1.rows(sig="s")).choose({66: 4})
        del s1
        s2 = ObservationStore(str(tmp_path))
        d2 = CostModel.fit(s2.rows(sig="s")).choose({66: 4})
        assert d1.as_dict() == d2.as_dict()

    def test_resolve_tuning_cold_store_returns_none(self, store):
        assert resolve_tuning("never-seen", "default", {64: 1}) is None


# ---------------------------------------------------------------------------
# runner helpers shared by the sweep / e2e / acceptance tests
# ---------------------------------------------------------------------------

def _apply(params, feeds):
    return {"y": feeds["x"] @ params["w"]}


def _make_runner_factory(n_rows, din=8, dout=4, seed=0, **extra):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n_rows, din)).astype(np.float32)
    params = {"w": jnp.asarray(
        rng.normal(0, 0.5, (din, dout)).astype(np.float32))}
    jitted = jax.jit(_apply)

    def make(mini_batch_size, prefetch_depth, buckets):
        def coerce(sl):
            return {"x": X[sl]}
        return BatchRunner(jitted, params, coerce, jax.device_put,
                           mini_batch_size=mini_batch_size,
                           prefetch_depth=prefetch_depth, buckets=buckets,
                           **extra)
    return make, jitted, params


# ---------------------------------------------------------------------------
# measured sweep
# ---------------------------------------------------------------------------

class TestMeasuredSweep:
    def test_probe_budget_env(self, monkeypatch):
        monkeypatch.setenv(PROBE_BUDGET_ENV, "3")
        assert probe_budget() == 3
        monkeypatch.setenv(PROBE_BUDGET_ENV, "garbage")
        assert probe_budget() == 6          # default survives bad input

    def test_sweep_bounded_by_budget(self, store):
        make, _, _ = _make_runner_factory(40)
        cands = candidate_configs({40: 1}, defaults=(16, 1))
        assert len(cands) > 3               # the budget actually binds
        before = M_PROBES.labels().get()
        decision = measured_sweep(make, 40, sig="sweep-sig", budget=3,
                                  store=store)
        assert M_PROBES.labels().get() - before == 3
        probes = store.rows(sig="sweep-sig", source="probe")
        assert len(probes) == 3             # every probe became a row
        assert all(r["rows_per_sec"] > 0 for r in probes)
        # the decision came from the store the probes landed in
        assert decision.mini_batch_size >= 1
        assert decision.source in ("probe", "model")

    def test_sweep_decision_refittable_from_probes(self, store):
        make, _, _ = _make_runner_factory(40)
        d1 = measured_sweep(make, 40, sig="resweep", budget=4, store=store)
        d2 = CostModel.fit(store.rows(sig="resweep")).choose(
            {40: 1}, defaults=(64, 2))
        assert (d1.mini_batch_size, d1.prefetch_depth, d1.buckets) \
            == (d2.mini_batch_size, d2.prefetch_depth, d2.buckets)


# ---------------------------------------------------------------------------
# warm-up respects the active ladder (the power-of-two over-compile fix)
# ---------------------------------------------------------------------------

class TestWarmupLadder:
    def test_ladder_skips_buckets_outside_it(self, store):
        make, jitted, params = _make_runner_factory(66)
        specs = {"x": (np.dtype(np.float32), (8,))}
        before = M_WARMUP_BUCKETS.labels().get()
        # sizes 5 and 66 both land in the single ladder bucket 66; the
        # power-of-two ladder would compile 8 AND 128
        stats = warm_up_jitted(jitted, params, specs, [5, 66],
                               buckets=(66,))
        assert stats["buckets"] == [66]
        assert M_WARMUP_BUCKETS.labels().get() - before == 1

    def test_default_ladder_unchanged(self):
        make, jitted, params = _make_runner_factory(66, seed=3)
        specs = {"x": (np.dtype(np.float32), (8,))}
        before = M_WARMUP_BUCKETS.labels().get()
        stats = warm_up_jitted(jitted, params, specs, [5, 66])
        assert stats["buckets"] == [8, 128]
        assert M_WARMUP_BUCKETS.labels().get() - before == 2


# ---------------------------------------------------------------------------
# BatchRunner(tuning="auto") end-to-end + the acceptance criterion
# ---------------------------------------------------------------------------

class TestBatchRunnerAuto:
    def test_harvest_lands_in_store(self, store):
        make, _, _ = _make_runner_factory(40, model_sig="harvest-sig")
        runner = make(16, 1, None)
        runner.run_and_drain(40)
        rows = store.rows(sig="harvest-sig", source="runner")
        assert rows, "drain did not harvest samples"
        assert {r["bucket"] for r in rows} == {16, 8}   # 16+16+8 rows
        assert sum(r["rows"] for r in rows) == 40
        cfg = rows[0]["config"]
        assert cfg["mini_batch_size"] == 16
        assert cfg["prefetch_depth"] == 1

    def test_auto_applies_store_pick_with_zero_recompiles(self, store):
        """The acceptance loop: seed the store, warm exactly the chosen
        vocabulary, then run with tuning="auto" — the runner must adopt
        the pick and pay zero steady-state recompiles."""
        sig = "auto-sig"
        store.record_many(linear_rows(sig, alpha=0.01, beta=1e-4))
        expected = resolve_tuning(sig, "default", {66: 1},
                                  defaults=(64, 2), store=store)
        assert expected is not None
        make, jitted, params = _make_runner_factory(
            66, model_sig=sig, tuning="auto")
        specs = {"x": (np.dtype(np.float32), (8,))}
        warm_up_jitted(jitted, params, specs, expected.warm_up_sizes,
                       buckets=expected.buckets)
        runner = make(64, 2, None)
        before = M_STEADY_RECOMPILES.labels().get()
        out = runner.run_and_drain(66)
        # the pick was applied (not the 64/2 defaults it was built with)
        assert runner.decision is not None
        assert runner.mini_batch_size == expected.mini_batch_size
        assert runner.prefetch_depth == expected.prefetch_depth
        assert runner.buckets == expected.buckets
        # zero steady-state recompiles: warm-up covered the vocabulary
        assert M_STEADY_RECOMPILES.labels().get() - before == 0
        assert sum(b for _, b in out) == 66

    def test_autotuned_beats_defaults_on_skewed_workload(self, store):
        """Acceptance criterion end-to-end: on a skewed row-size workload
        (runs of 66 rows), the autotuned (ladder, mini_batch_size,
        prefetch_depth) moves strictly more rows/s through the SAME
        BatchRunner machinery than the power-of-two + 64/2 defaults, with
        zero steady-state recompiles, and the pick reproduces from the
        persisted store alone."""
        sig = "acc-sig"
        n = 66
        store.record_many(linear_rows(sig, alpha=0.01, beta=1e-4))
        decision = resolve_tuning(sig, "default", {n: 1},
                                  defaults=(64, 2), store=store)
        assert decision is not None
        # the tuned config avoids both failure modes: one dispatch per run
        # (not two) and zero pad rows (not 66 -> 64+2 buckets)
        assert decision.mini_batch_size >= n
        assert decision.buckets is not None

        make, jitted, params = _make_runner_factory(n, model_sig=sig)
        specs = {"x": (np.dtype(np.float32), (8,))}
        # warm both configs so neither measurement pays a compile: the
        # 64/2 default splits 66 rows into dispatches of 64 and 2
        warm_up_jitted(jitted, params, specs, [64, 2])
        warm_up_jitted(jitted, params, specs, decision.warm_up_sizes,
                       buckets=decision.buckets)

        default_runner = make(64, 2, None)
        tuned_runner = make(decision.mini_batch_size,
                            decision.prefetch_depth, decision.buckets)

        def best_rate(runner, reps=25, tries=3):
            best = 0.0
            for _ in range(tries):
                t0 = time.perf_counter()
                for _ in range(reps):
                    runner.run_and_drain(n)
                el = time.perf_counter() - t0
                best = max(best, n * reps / el)
            return best

        before = M_STEADY_RECOMPILES.labels().get()
        default_rate = best_rate(default_runner)
        tuned_rate = best_rate(tuned_runner)
        assert M_STEADY_RECOMPILES.labels().get() - before == 0
        assert tuned_rate > default_rate, (
            f"tuned {tuned_rate:.0f} rows/s !> default "
            f"{default_rate:.0f} rows/s")

        # reproducible from the persisted store alone: write the same
        # training rows to disk, re-fit cold, same pick
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            disk = ObservationStore(d)
            disk.record_many(linear_rows(sig, alpha=0.01, beta=1e-4))
            refit = CostModel.fit(
                ObservationStore(d).rows(sig=sig)).choose(
                    {n: 1}, defaults=(64, 2))
            assert (refit.mini_batch_size, refit.prefetch_depth,
                    refit.buckets) == (decision.mini_batch_size,
                                       decision.prefetch_depth,
                                       decision.buckets)

    def test_onnx_signature_stable_across_builds(self):
        """Two builds of the same graph serialize with different auto node
        names (builder names derive from object ids), so the signature
        must hash semantic content, not raw bytes — otherwise persisted
        decisions never match across processes."""
        from mmlspark_tpu.models.onnx_model import ONNXModel
        from mmlspark_tpu.onnx import model_content_digest

        def build():
            import mmlspark_tpu.onnx as O
            rng = np.random.default_rng(7)
            w = rng.normal(0, 0.5, (8, 3)).astype(np.float32)
            nodes = [O.make_node("MatMul", ["x", "w"], ["logits"])]
            graph = O.make_graph(
                nodes, "m",
                inputs=[O.make_tensor_value_info("x", np.float32,
                                                 ["N", 8])],
                outputs=[O.make_tensor_value_info("logits", np.float32,
                                                  ["N", 3])],
                initializers={"w": w})
            return O.make_model(graph)

        b1, b2 = build(), build()
        assert b1 != b2                     # names really do differ
        assert model_content_digest(b1) == model_content_digest(b2)
        m1 = ONNXModel(b1, feed_dict={"x": "f"}, fetch_dict={"logits": "o"},
                       pin_devices=False)
        m2 = ONNXModel(b2, feed_dict={"x": "f"}, fetch_dict={"logits": "o"},
                       pin_devices=False)
        assert m1.tuning_signature() == m2.tuning_signature()
        # different weights = different model = different signature
        b3 = build()[:-4] + b"\x00\x00\x80\x3f"   # perturb initializer tail
        assert model_content_digest(b3) != model_content_digest(b1)

    def test_ladder_validation(self):
        make, jitted, params = _make_runner_factory(40)
        with pytest.raises(ValueError):
            make(64, 2, (8, 16))            # mini_batch_size > max bucket
        with pytest.raises(ValueError):
            BatchRunner(jitted, params, lambda sl: {}, jax.device_put,
                        tuning="bogus")
