"""Cost ledger: charge/apportion/overflow semantics, the SpaceSaving
heavy-hitter table, trace-context class resolution, mirrored
mmlspark_cost_* metrics, GET /debug/costs on both transports (with the
tenant header feeding the class), the ObservationStore harvest, and the
ledger-vs-runner-stage-counter reconciliation.
"""

import http.client
import json
import threading

import numpy as np
import pytest

from mmlspark_tpu.io.http.schema import (EntityData, HeaderData,
                                         HTTPResponseData, StatusLineData)
from mmlspark_tpu.observability import (activate, get_flight_recorder,
                                        reset_all, snapshot, start_trace)
from mmlspark_tpu.observability.ledger import (COST_WEIGHTS, RESOURCES,
                                               TOPK_ENV, CostLedger,
                                               get_ledger, reset_ledger,
                                               resolve_context, set_ledger)
from mmlspark_tpu.observability.slo import reset_tracker
from mmlspark_tpu.observability.watchdog import reset_watchdog
from mmlspark_tpu.reliability import get_injector
from mmlspark_tpu.reliability.breaker import reset_breakers
from mmlspark_tpu.serving.server import WorkerServer
from mmlspark_tpu.tuning import observations as obs_mod
from mmlspark_tpu.tuning.observations import (ObservationStore,
                                              harvest_costs)


@pytest.fixture(autouse=True)
def _clean_slate():
    reset_ledger()
    reset_tracker()
    reset_watchdog()
    reset_breakers()
    reset_all()
    get_injector().clear()
    obs_mod.set_store(ObservationStore())
    yield
    reset_ledger()
    reset_tracker()
    reset_watchdog()
    reset_breakers()
    get_injector().clear()
    obs_mod.reset_store()
    reset_all()


def _series_sum(name, **match):
    metric = snapshot().get(name)
    if not metric:
        return 0.0
    return sum(s["value"] for s in metric["series"]
               if all(s["labels"].get(k) == v for k, v in match.items()))


CLS_A = ("threaded", "api", "default", "default")
CLS_B = ("threaded", "api", "default", "acme")


# ---------------------------------------------------------------------------
# charging semantics


def test_charge_accumulates_per_class_and_snapshot_shape():
    led = CostLedger()
    led.charge("device_seconds", 0.25, cls=CLS_A, trace_id="t1")
    led.charge("device_seconds", 0.75, cls=CLS_A, trace_id="t2")
    led.charge("h2d_bytes", 1e6, cls=CLS_B, trace_id="t3")
    snap = led.snapshot()
    assert set(snap) == {"t", "top_k", "weights", "classes",
                         "heavy_hitters"}
    assert snap["weights"] == COST_WEIGHTS
    by_tenant = {c["tenant"]: c for c in snap["classes"]}
    assert by_tenant["default"]["resources"]["device_seconds"] == \
        pytest.approx(1.0)
    assert by_tenant["default"]["charges"] == 2
    assert by_tenant["acme"]["resources"]["h2d_bytes"] == pytest.approx(1e6)
    # weighted scalar cost follows the published weights
    assert by_tenant["default"]["weighted_cost"] == pytest.approx(1.0)
    assert by_tenant["acme"]["weighted_cost"] == pytest.approx(1e6 * 1e-9)
    json.dumps(snap)            # JSON-safe end to end


def test_unknown_resource_raises_and_nonpositive_is_dropped():
    led = CostLedger()
    with pytest.raises(ValueError):
        led.charge("gpu_seconds", 1.0, cls=CLS_A)
    led.charge("device_seconds", 0.0, cls=CLS_A)
    led.charge("device_seconds", -5.0, cls=CLS_A)
    assert led.snapshot()["classes"] == []


def test_class_cardinality_overflows_to_other():
    led = CostLedger(max_classes=2)
    led.charge("device_seconds", 1.0, cls=("a", "r", "m", "default"))
    led.charge("device_seconds", 1.0, cls=("b", "r", "m", "default"))
    led.charge("device_seconds", 1.0, cls=("c", "r", "m", "default"))
    led.charge("device_seconds", 1.0, cls=("d", "r", "m", "default"))
    totals = led.class_totals("device_seconds")
    assert totals[("other", "other", "other", "other")] == pytest.approx(2.0)
    assert len(totals) == 3


def test_charge_shares_apportions_by_weight():
    led = CostLedger()
    led.charge_shares("device_seconds", 1.0,
                      [(CLS_A, "t1", 3.0), (CLS_B, "t2", 1.0),
                       (("x", "r", "m", "default"), None, 0.0)])
    totals = led.class_totals("device_seconds")
    assert totals[CLS_A] == pytest.approx(0.75)
    assert totals[CLS_B] == pytest.approx(0.25)
    assert ("x", "r", "m", "default") not in totals
    # the whole measurement lands somewhere — nothing on the floor
    assert sum(totals.values()) == pytest.approx(1.0)


def test_charge_shares_empty_is_noop():
    led = CostLedger()
    led.charge_shares("device_seconds", 1.0, [])
    assert led.snapshot()["classes"] == []


# ---------------------------------------------------------------------------
# heavy hitters (SpaceSaving)


def test_heavy_hitters_rank_by_weighted_cost():
    led = CostLedger(top_k=8)
    led.charge("device_seconds", 5.0, cls=CLS_A, trace_id="big")
    led.charge("device_seconds", 1.0, cls=CLS_A, trace_id="small")
    led.charge("device_seconds", 3.0, cls=CLS_B, trace_id="mid")
    hh = led.snapshot()["heavy_hitters"]
    assert [e["trace_id"] for e in hh] == ["big", "mid", "small"]
    assert hh[0]["cost"] == pytest.approx(5.0)
    assert hh[0]["error"] == 0.0
    assert hh[1]["tenant"] == "acme"


def test_heavy_hitters_evict_min_with_error_floor():
    led = CostLedger(top_k=2)
    led.charge("device_seconds", 5.0, cls=CLS_A, trace_id="a")
    led.charge("device_seconds", 1.0, cls=CLS_A, trace_id="b")
    # table full: the newcomer evicts the cheapest entry (b) and inherits
    # its cost as the overestimation floor — Metwally's guarantee
    led.charge("device_seconds", 2.0, cls=CLS_A, trace_id="c")
    hh = {e["trace_id"]: e for e in led.snapshot()["heavy_hitters"]}
    assert set(hh) == {"a", "c"}
    assert hh["c"]["cost"] == pytest.approx(3.0)     # floor 1.0 + own 2.0
    assert hh["c"]["error"] == pytest.approx(1.0)
    assert len(hh) == 2


def test_topk_env_knob(monkeypatch):
    monkeypatch.setenv(TOPK_ENV, "3")
    led = CostLedger()
    for i in range(10):
        led.charge("device_seconds", float(i + 1), cls=CLS_A,
                   trace_id=f"t{i}")
    snap = led.snapshot()
    assert snap["top_k"] == 3
    assert len(snap["heavy_hitters"]) == 3


# ---------------------------------------------------------------------------
# trace-context resolution


def test_resolve_context_untraced():
    cls, tid = resolve_context()
    assert cls == ("untraced", "untraced", "default", "default")
    assert tid is None


def test_resolve_context_reads_root_span_attrs():
    span = start_trace("request", transport="threaded", url="/score?q=1",
                       model="bert", tenant="acme")
    with activate(span):
        cls, tid = resolve_context()
    assert cls == ("threaded", "api", "bert", "acme")
    assert tid == span.trace.trace_id


def test_module_level_charge_uses_ambient_context():
    from mmlspark_tpu.observability.ledger import charge
    span = start_trace("request", transport="threaded", route="api",
                       tenant="acme")
    with activate(span):
        charge("compile_seconds", 0.5)
    totals = get_ledger().class_totals("compile_seconds")
    assert totals[("threaded", "api", "default", "acme")] == \
        pytest.approx(0.5)


# ---------------------------------------------------------------------------
# mirrored metrics


def test_cost_metrics_mirror_charges():
    led = get_ledger()
    led.charge("device_seconds", 2.0, cls=CLS_A, trace_id="t1")
    led.charge("d2h_bytes", 100.0, cls=CLS_A, trace_id="t1")
    assert _series_sum("mmlspark_cost_total",
                       resource="device_seconds") == pytest.approx(2.0)
    assert _series_sum("mmlspark_cost_total",
                       resource="d2h_bytes") == pytest.approx(100.0)
    assert _series_sum("mmlspark_cost_charges_total") == 2
    assert _series_sum("mmlspark_cost_heavy_hitters") == 1


# ---------------------------------------------------------------------------
# ObservationStore harvest


def test_harvest_costs_row_shape_and_tenant_suffix():
    led = CostLedger()
    led.charge("device_seconds", 1.5, cls=CLS_A, trace_id="t1")
    led.charge("compile_seconds", 0.5, cls=CLS_B, trace_id="t2")
    store = ObservationStore()
    n = harvest_costs(led.snapshot(), store=store)
    assert n == 2
    rows = {r["sig"]: r for r in store.rows(source="cost_ledger")}
    assert set(rows) == {"cost:threaded/api/default",
                         "cost:threaded/api/default@acme"}
    row = rows["cost:threaded/api/default"]
    assert row["seconds"] == pytest.approx(1.5)
    assert row["rows"] == 1
    assert row["tenant"] == "default"
    assert row["cost"]["device_seconds"] == pytest.approx(1.5)
    acme = rows["cost:threaded/api/default@acme"]
    assert acme["tenant"] == "acme"
    assert acme["compile_seconds"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# GET /debug/costs over HTTP, both transports, tenant header


def _resp(payload, status=200):
    return HTTPResponseData(
        headers=[HeaderData("Content-Type", "application/json")],
        entity=EntityData.from_string(json.dumps(payload)),
        status_line=StatusLineData(status_code=status))


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_debug_costs_route_and_tenant_attribution(transport):
    ws = WorkerServer(transport=transport, reply_timeout=10.0)
    stop = threading.Event()

    def engine():
        while not stop.is_set():
            for c in ws.get_batch(16, timeout=0.05):
                ws.reply(c.request_id, _resp({"ok": True}))

    t = threading.Thread(target=engine, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
        for i in range(3):
            conn.request("POST", "/", json.dumps({"i": i}).encode(),
                         {"Content-Type": "application/json",
                          "X-Mmlspark-Tenant": "acme"})
            r = conn.getresponse()
            r.read()
            assert r.status == 200
        conn.request("GET", "/debug/costs")
        r = conn.getresponse()
        assert r.status == 200
        snap = json.loads(r.read())
        by_cls = {(c["transport"], c["route"], c["tenant"]): c
                  for c in snap["classes"]}
        cls = by_cls[(transport, "api", "acme")]
        # get_batch billed each request's park time to the tenant class
        assert cls["resources"]["queue_wait_seconds"] > 0.0
        assert cls["charges"] >= 3
        # heavy hitters join the flight recorder by trace id
        assert snap["heavy_hitters"]
        top = snap["heavy_hitters"][0]
        assert top["tenant"] == "acme"
        rec = get_flight_recorder().get(top["trace_id"])
        assert rec is not None
        # the render harvested itself into the tuning store
        assert snap["harvested"] >= 1
        rows = obs_mod.get_store().rows(source="cost_ledger")
        assert any(r["sig"] == f"cost:{transport}/api/default@acme"
                   for r in rows)
        # harvest=0 renders without appending more rows
        before = len(obs_mod.get_store())
        conn.request("GET", "/debug/costs?harvest=0")
        snap2 = json.loads(conn.getresponse().read())
        assert "harvested" not in snap2
        assert len(obs_mod.get_store()) == before
        conn.close()
    finally:
        stop.set()
        t.join(timeout=5)
        ws.close()


# ---------------------------------------------------------------------------
# ledger vs runner stage counters


def test_device_seconds_reconcile_with_runner_stage_counters():
    """The runner charges device/compile seconds with the SAME elapsed
    values it adds to its stage counters, so the ledger's untraced-class
    totals must reconcile with mmlspark_runner_stage_seconds_total."""
    import jax

    from mmlspark_tpu.models.runner import BatchRunner

    @jax.jit
    def jitted(params, feeds):
        return {"y": feeds["x"] * params["w"]}

    data = np.arange(64, dtype=np.float32)
    runner = BatchRunner(jitted, {"w": 2.0},
                         coerce=lambda sl: {"x": data[sl]},
                         put=jax.device_put, mini_batch_size=16)
    for _ in range(2):
        for out, b in runner.run_and_drain(64):
            assert np.allclose(out["y"][:b], data[:b] * 2.0) or True

    led = get_ledger()
    dev = sum(led.class_totals("device_seconds").values())
    comp = sum(led.class_totals("compile_seconds").values())
    stage_dispatch = _series_sum("mmlspark_runner_stage_seconds_total",
                                 stage="dispatch")
    stage_d2h = _series_sum("mmlspark_runner_stage_seconds_total",
                            stage="d2h")
    stage_compile = _series_sum("mmlspark_runner_stage_seconds_total",
                                stage="compile")
    assert dev == pytest.approx(stage_dispatch + stage_d2h, rel=1e-6)
    assert comp == pytest.approx(stage_compile, rel=1e-6)
    assert dev > 0.0
    # padding waste: 64 rows in 16-row buckets pad nothing; the charge
    # sites still ran (h2d/d2h bytes attributed to the untraced class)
    assert sum(led.class_totals("h2d_bytes").values()) > 0
    assert sum(led.class_totals("d2h_bytes").values()) > 0
    totals = led.class_totals("device_seconds")
    assert set(totals) == {("untraced", "untraced", "default", "default")}
