"""Parquet/Arrow IO tests (parity role: Spark's native parquet source +
the row-group → partition split model)."""

import os

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.io.parquet import read_csv, read_parquet, write_parquet

pytest.importorskip("pyarrow")


def _frame(n=12, npartitions=3, seed=0):
    rng = np.random.default_rng(seed)
    return DataFrame({
        "x": rng.normal(0, 1, n).astype(np.float32),
        "label": rng.integers(0, 3, n).astype(np.int64),
        "vec": [rng.normal(0, 1, 4).astype(np.float32) for _ in range(n)],
        "name": np.array([f"row{i}" for i in range(n)], dtype=object),
    }, npartitions=npartitions)


class TestParquet:
    def test_single_file_roundtrip(self, tmp_path):
        df = _frame()
        write_parquet(df, str(tmp_path / "t.parquet"))
        back = read_parquet(str(tmp_path / "t.parquet"))
        np.testing.assert_allclose(back["x"], df["x"], rtol=1e-6)
        np.testing.assert_array_equal(back["label"], df["label"])
        assert list(back["name"]) == list(df["name"])
        np.testing.assert_allclose(
            np.stack([np.asarray(v) for v in back["vec"]]),
            np.stack(list(df["vec"])), rtol=1e-6)

    def test_partitioned_write_preserves_partitioning(self, tmp_path):
        df = _frame(npartitions=3)
        paths = write_parquet(df, str(tmp_path / "parts"), partitioned=True)
        assert len(paths) == 3
        assert all(os.path.exists(p) for p in paths)
        back = read_parquet(str(tmp_path / "parts"))
        assert len(back) == 12 and back.npartitions == 3
        np.testing.assert_allclose(back["x"], df["x"], rtol=1e-6)

    def test_glob_and_columns(self, tmp_path):
        df = _frame()
        write_parquet(df, str(tmp_path / "parts"), partitioned=True)
        back = read_parquet(str(tmp_path / "parts" / "*.parquet"),
                            columns=["x", "label"])
        assert set(back.columns) == {"x", "label"}

    def test_row_group_partitioning(self, tmp_path):
        import pyarrow.parquet as pq
        df = _frame(n=20, npartitions=1)
        pq.write_table(df.to_arrow(), str(tmp_path / "rg.parquet"),
                       row_group_size=5)
        back = read_parquet(str(tmp_path / "rg.parquet"))
        assert back.npartitions == 4  # one partition per row group
        np.testing.assert_allclose(back["x"], df["x"], rtol=1e-6)

    def test_missing_file_raises(self):
        with pytest.raises(FileNotFoundError):
            read_parquet("/nonexistent/*.parquet")

    def test_pipeline_from_parquet(self, tmp_path):
        """The user path: parquet → fit → transform."""
        from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier
        rng = np.random.default_rng(1)
        n = 60
        df = DataFrame({
            "features": [rng.normal(0, 1, 5).astype(np.float32)
                         for _ in range(n)],
            "label": rng.integers(0, 2, n).astype(np.float64)})
        write_parquet(df, str(tmp_path / "train.parquet"))
        train = read_parquet(str(tmp_path / "train.parquet"))
        model = LightGBMClassifier(num_iterations=3, num_leaves=4).fit(train)
        out = model.transform(train)
        assert "prediction" in out.columns


class TestCsv:
    def test_read_csv(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("a,b\n1,x\n2,y\n")
        df = read_csv(str(p), npartitions=2)
        np.testing.assert_array_equal(df["a"], [1, 2])
        assert df.npartitions == 2


class TestArrowRoundtrip:
    def test_to_arrow_from_arrow(self):
        df = _frame()
        back = DataFrame.from_arrow(df.to_arrow())
        np.testing.assert_allclose(back["x"], df["x"], rtol=1e-6)
        assert list(back["name"]) == list(df["name"])


class TestReviewRegressions:
    def test_overwrite_with_fewer_partitions_truncates(self, tmp_path):
        d = str(tmp_path / "ds")
        write_parquet(_frame(n=10, npartitions=5), d, partitioned=True)
        write_parquet(_frame(n=4, npartitions=2, seed=9), d,
                      partitioned=True)
        back = read_parquet(d)
        assert len(back) == 4  # stale part files removed

    def test_uneven_row_groups_keep_exact_boundaries(self, tmp_path):
        import pyarrow.parquet as pq
        d = str(tmp_path)
        pq.write_table(_frame(n=10, npartitions=1).to_arrow(),
                       d + "/a.parquet")
        pq.write_table(_frame(n=2, npartitions=1, seed=3).to_arrow(),
                       d + "/b.parquet")
        back = read_parquet([d + "/a.parquet", d + "/b.parquet"])
        assert back.npartitions == 2
        sizes = [hi - lo for lo, hi in back.partition_bounds()]
        assert sizes == [10, 2]  # file boundaries, not equal ranges

    def test_invalid_partition_per_rejected(self, tmp_path):
        write_parquet(_frame(), str(tmp_path / "t.parquet"))
        with pytest.raises(ValueError, match="partition_per"):
            read_parquet(str(tmp_path / "t.parquet"),
                         partition_per="rowgroup")

    def test_dense_2d_column_roundtrips_dense(self):
        m = np.arange(12, dtype=np.float32).reshape(6, 2)
        df = DataFrame({"m": m})
        back = DataFrame.from_arrow(df.to_arrow())
        assert back["m"].dtype == np.float32 and back["m"].shape == (6, 2)
        np.testing.assert_allclose(back["m"], m)
