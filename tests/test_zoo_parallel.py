import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class TestResNet:
    def test_forward_shapes_small(self):
        from mmlspark_tpu.models.zoo.resnet import (ResNetConfig, init_resnet,
                                                    resnet_apply)
        cfg = ResNetConfig([1, 1], num_classes=7, width=8, dtype=jnp.float32)
        params = init_resnet(cfg, seed=0)
        x = np.random.default_rng(0).normal(0, 1, (2, 32, 32, 3)).astype(np.float32)
        logits = resnet_apply(params, jnp.asarray(x), cfg)
        assert logits.shape == (2, 7)
        feats = resnet_apply(params, jnp.asarray(x), cfg, features_only=True)
        assert feats.shape[0] == 2

    def test_onnx_export_matches_native(self):
        """The NCHW ONNX export and the native NHWC path agree numerically."""
        from mmlspark_tpu.models.zoo.resnet import (ResNetConfig,
                                                    export_resnet_onnx,
                                                    init_resnet, resnet_apply)
        from mmlspark_tpu.onnx import convert_model
        cfg = ResNetConfig([1, 1], num_classes=5, width=8, dtype=jnp.float32)
        params = init_resnet(cfg, seed=1)
        onnx_bytes = export_resnet_onnx(cfg, params=params, input_size=32)
        cm = convert_model(onnx_bytes)
        x = np.random.default_rng(1).normal(0, 1, (2, 3, 32, 32)).astype(np.float32)
        out = cm(cm.params, {"input": x})
        native = resnet_apply(params, jnp.asarray(np.transpose(x, (0, 2, 3, 1))),
                              cfg)
        np.testing.assert_allclose(np.asarray(out["logits"]),
                                   np.asarray(native), rtol=2e-3, atol=2e-3)


class TestTransformer:
    def test_forward_and_train_step_single(self):
        from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                         init_transformer,
                                                         train_step)
        cfg = TransformerConfig(vocab=64, layers=2, d_model=32, heads=4,
                                d_ff=64, max_len=16, dtype=jnp.float32)
        params = init_transformer(cfg)
        opt = jax.tree.map(jnp.zeros_like, params)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 64, (4, 12))
        labels = rng.integers(0, 64, (4, 12))
        step = jax.jit(functools.partial(train_step, cfg=cfg))
        p2, o2, loss = step(params, opt, ids, labels)
        assert np.isfinite(float(loss))
        # loss decreases over a few steps on a fixed batch
        for _ in range(5):
            p2, o2, loss2 = step(p2, o2, ids, labels)
        assert float(loss2) < float(loss)

    def test_sharded_matches_unsharded(self):
        from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                         init_transformer,
                                                         shardings_for,
                                                         transformer_apply)
        cfg = TransformerConfig(vocab=32, layers=1, d_model=32, heads=4,
                                d_ff=64, max_len=16, dtype=jnp.float32)
        params = init_transformer(cfg)
        rng = np.random.default_rng(1)
        ids = rng.integers(0, 32, (4, 8))
        ref = transformer_apply(params, jnp.asarray(ids), cfg)
        devs = jax.devices()[:4]
        mesh = Mesh(np.array(devs).reshape(2, 2), ("dp", "tp"))
        sharded_params = jax.device_put(params, shardings_for(params, mesh))
        ids_s = jax.device_put(jnp.asarray(ids), NamedSharding(mesh, P("dp", None)))
        out = jax.jit(functools.partial(transformer_apply, cfg=cfg, mesh=mesh))(
            sharded_params, ids_s)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=1e-4, atol=1e-4)


class TestRingAttention:
    def _qkv(self, B=2, H=4, S=32, D=16, seed=0):
        rng = np.random.default_rng(seed)
        return (rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
                for _ in range(3))

    def test_ring_matches_local(self):
        from mmlspark_tpu.parallel.mesh import make_mesh
        from mmlspark_tpu.parallel.ring import (local_attention,
                                                wrap_ring_attention)
        q, k, v = self._qkv()
        ref = local_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        mesh = make_mesh({"sp": 8})
        fn = wrap_ring_attention(mesh, "sp", impl="ring")
        out = fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=1e-4, atol=1e-4)

    def test_ulysses_matches_local(self):
        from mmlspark_tpu.parallel.mesh import make_mesh
        from mmlspark_tpu.parallel.ring import (local_attention,
                                                wrap_ring_attention)
        q, k, v = self._qkv(H=8, seed=2)
        ref = local_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        mesh = make_mesh({"sp": 8})
        fn = wrap_ring_attention(mesh, "sp", impl="ulysses")
        out = fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=1e-4, atol=1e-4)


class TestGraftEntry:
    def test_entry_compiles(self):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as ge
        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert np.asarray(out).shape == (8, 1000)

    def test_dryrun_multichip(self):
        import __graft_entry__ as ge
        ge.dryrun_multichip(8)


def test_moe_transformer_train_step():
    """Sparse (MoE) transformer variant: experts over dp, expert hidden over
    tp (GShard deployment), trained one step on the dp x tp mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                     init_transformer,
                                                     shardings_for,
                                                     train_step)

    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs).reshape(4, 2), ("dp", "tp"))
    cfg = TransformerConfig(vocab=64, layers=2, d_model=32, heads=4,
                            d_ff=64, max_len=16, dtype=jnp.float32,
                            moe_experts=4, moe_every=2)
    params = init_transformer(cfg, seed=0)
    assert "moe" in params["layers"][1] and "w1" in params["layers"][0]
    params = jax.device_put(params, shardings_for(params, mesh))
    opt = jax.tree.map(jnp.zeros_like, params)
    rng = np.random.default_rng(0)
    dp = mesh.shape["dp"]
    ids = jax.device_put(rng.integers(0, cfg.vocab, (2 * dp, 8)),
                         NamedSharding(mesh, P("dp", None)))
    labels = jax.device_put(rng.integers(0, cfg.vocab, (2 * dp, 8)),
                            NamedSharding(mesh, P("dp", None)))
    import functools
    step = jax.jit(functools.partial(train_step, cfg=cfg, mesh=mesh))
    p1, o1, loss1 = step(params, opt, ids, labels)
    p2, o2, loss2 = step(p1, o1, ids, labels)
    assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss1)  # it actually learns
    # expert weights received gradient
    g = np.asarray(p1["layers"][1]["moe"]["w1"]) - \
        np.asarray(params["layers"][1]["moe"]["w1"])
    assert np.abs(g).sum() > 0


class TestRingGradients:
    """All three sequence-parallel attentions must TRAIN: the ring-level
    custom VJP (a second ring pass with dk/dv accumulators traveling with
    their K/V blocks) must match dense-local gradients. Before the VJP,
    autodiff through the flash-inner stats merge produced silently WRONG
    gradients — these tests are the regression pin."""

    @pytest.mark.parametrize("impl", ["ring", "ring_flash", "ulysses"])
    def test_grads_match_local(self, impl):
        from mmlspark_tpu.parallel.mesh import make_mesh
        from mmlspark_tpu.parallel.ring import (local_attention,
                                                wrap_ring_attention)
        mesh = make_mesh({"sp": 4})
        B, H, S, D = 1, 4, 64, 8
        rng = np.random.default_rng(0)
        q = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
        k = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
        v = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
        fn = wrap_ring_attention(mesh, "sp", impl=impl)
        sh = NamedSharding(mesh, P(None, None, "sp", None))
        args = [jax.device_put(x, sh) for x in (q, k, v)]
        g = jax.jit(jax.grad(
            lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32)),
            argnums=(0, 1, 2)))(*args)
        ref = jax.grad(
            lambda a, b, c: jnp.sum(
                local_attention(a, b, c).astype(jnp.float32)),
            argnums=(0, 1, 2))(q, k, v)
        for gi, ri in zip(g, ref):
            np.testing.assert_allclose(np.asarray(gi), np.asarray(ri),
                                       rtol=2e-3, atol=2e-3)

    def test_bf16_grads_fp32_accumulated(self):
        """bf16 inputs: per-hop contributions must be computed/accumulated
        in fp32 (only the final grads quantize to bf16), so the ring result
        stays close to the fp32 local reference."""
        from mmlspark_tpu.parallel.mesh import make_mesh
        from mmlspark_tpu.parallel.ring import (local_attention,
                                                wrap_ring_attention)
        mesh = make_mesh({"sp": 4})
        B, H, S, D = 1, 2, 64, 8
        rng = np.random.default_rng(2)
        qf = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
        fn = wrap_ring_attention(mesh, "sp", impl="ring_flash")
        sh = NamedSharding(mesh, P(None, None, "sp", None))
        args = [jax.device_put(jnp.asarray(x, jnp.bfloat16), sh)
                for x in (qf, qf + 0.1, qf - 0.1)]
        g = jax.jit(jax.grad(
            lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32)),
            argnums=(0, 1, 2)))(*args)
        ref = jax.grad(
            lambda a, b, c: jnp.sum(
                local_attention(a, b, c).astype(jnp.float32)),
            argnums=(0, 1, 2))(qf, qf + 0.1, qf - 0.1)
        for gi, ri in zip(g, ref):
            np.testing.assert_allclose(
                np.asarray(gi, np.float32), np.asarray(ri),
                rtol=5e-2, atol=5e-2)   # one final bf16 quantization only

    def test_train_step_through_ring_flash(self):
        """One SGD step through ring_flash attention moves the loss —
        end-to-end trainability, not just gradient numerics."""
        from mmlspark_tpu.parallel.mesh import make_mesh
        from mmlspark_tpu.parallel.ring import wrap_ring_attention
        mesh = make_mesh({"sp": 4})
        B, H, S, D = 1, 2, 32, 8
        rng = np.random.default_rng(1)
        x = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
        w = jnp.asarray(rng.normal(0, 0.3, (D, D)), jnp.float32)
        target = jnp.asarray(rng.normal(0, 1, (B, H, S, D)), jnp.float32)
        fn = wrap_ring_attention(mesh, "sp", impl="ring_flash")
        sh = NamedSharding(mesh, P(None, None, "sp", None))
        xs = jax.device_put(x, sh)

        def loss(w):
            qkv = xs @ w
            out = fn(qkv, qkv, qkv)
            return jnp.mean((out - target) ** 2)

        l0, g = jax.jit(jax.value_and_grad(loss))(w)
        l1 = jax.jit(loss)(w - 0.1 * g)
        assert float(l1) < float(l0)
