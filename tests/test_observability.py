"""Unified telemetry layer: registry semantics (concurrency, bucket
edges, label/type guards), Prometheus exposition golden text, the JSON
event log, and end-to-end /metrics + /healthz on a live WorkerServer.

The e2e test primes the process-global registry through the real hot
paths (a jitted BatchRunner partition, then HTTP traffic) and asserts
the scrape output contains the acceptance families: request-latency
histogram, queue-depth gauge, runner stage counters, and compile-cache
hit/miss/recompile counters.
"""

import json
import logging
import re
import threading
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu import observability as obs
from mmlspark_tpu.observability.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def _fresh_series():
    # zero every series but keep import-time metric objects registered —
    # the contract that lets module-level metrics coexist with test runs
    obs.reset_all()
    yield
    obs.reset_all()


def _series_value(snap, name, **labels):
    for s in snap[name]["series"]:
        if s["labels"] == labels:
            return s
    raise AssertionError(f"{name}{labels} not in {snap[name]['series']}")


# ---------------------------------------------------------------------------
# registry semantics


def test_counter_concurrent_increment_is_exact():
    c = obs.counter("t_concurrent_total", "stress", ("worker",))
    threads, per_thread = 8, 10_000

    def bump(i):
        for _ in range(per_thread):
            c.inc(worker=str(i % 2))

    ts = [threading.Thread(target=bump, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = obs.snapshot()
    total = sum(s["value"]
                for s in snap["t_concurrent_total"]["series"])
    assert total == threads * per_thread
    assert _series_value(snap, "t_concurrent_total",
                         worker="0")["value"] == 4 * per_thread


def test_counter_rejects_negative_and_gauge_moves_both_ways():
    c = obs.counter("t_neg_total", "x")
    with pytest.raises(ValueError):
        c.inc(-1)
    g = obs.gauge("t_gauge", "x")
    g.set(5)
    g.inc(2)
    g.dec(4)
    assert _series_value(obs.snapshot(), "t_gauge")["value"] == 3


def test_histogram_bucket_edges_are_le_inclusive():
    h = obs.histogram("t_edges_seconds", "x", buckets=(0.1, 1.0, 10.0))
    h.observe(0.1)    # exactly on the first boundary -> le="0.1"
    h.observe(0.5)    # interior -> le="1.0"
    h.observe(1.0)    # exactly on the second boundary -> le="1.0"
    h.observe(99.0)   # overflow -> only +Inf
    s = _series_value(obs.snapshot(), "t_edges_seconds")
    assert s["buckets"] == {"0.1": 1, "1.0": 3, "10.0": 3, "+Inf": 4}
    assert s["count"] == 4
    assert s["sum"] == pytest.approx(100.6)


def test_histogram_timer_contextmanager():
    h = obs.histogram("t_timer_seconds", "x", ("phase",))
    with h.time(phase="p"):
        pass
    s = _series_value(obs.snapshot(), "t_timer_seconds", phase="p")
    assert s["count"] == 1 and s["sum"] >= 0.0


def test_registration_conflicts_raise():
    obs.counter("t_conflict_total", "x", ("a",))
    with pytest.raises(ValueError):
        obs.gauge("t_conflict_total", "x", ("a",))      # type mismatch
    with pytest.raises(ValueError):
        obs.counter("t_conflict_total", "x", ("b",))    # label mismatch
    # same (type, labelnames) is idempotent: returns the same object
    again = obs.counter("t_conflict_total", "x", ("a",))
    assert again is obs.counter("t_conflict_total", "x", ("a",))
    with pytest.raises(ValueError):
        obs.counter("t_conflict_total", "x", ("a",)).inc()  # missing label


def test_gauge_callback_sampled_at_scrape_and_removable():
    g = obs.gauge("t_cb_gauge", "x", ("port",))
    box = {"v": 7.0}
    g.set_function(lambda: box["v"], port="1234")
    assert _series_value(obs.snapshot(), "t_cb_gauge",
                         port="1234")["value"] == 7.0
    box["v"] = 9.0
    assert _series_value(obs.snapshot(), "t_cb_gauge",
                         port="1234")["value"] == 9.0
    g.remove(port="1234")
    assert obs.snapshot()["t_cb_gauge"]["series"] == []


def test_unlabeled_metrics_expose_zero_series_before_traffic():
    # acceptance detail: cache hit/miss counters must appear in /metrics
    # before the first dispatch, so dashboards see an explicit zero
    import mmlspark_tpu.ops.compile_cache  # noqa: F401  (registers metrics)
    text = obs.render()
    assert "mmlspark_compile_cache_hits_total 0" in text.splitlines()
    assert ("mmlspark_compile_cache_steady_state_recompiles_total 0"
            in text.splitlines())


def test_snapshot_is_json_serializable():
    obs.counter("t_snap_total", "x").inc(3)
    obs.histogram("t_snap_seconds", "x").observe(0.2)
    snap = json.loads(json.dumps(obs.snapshot()))
    assert snap["t_snap_total"]["series"][0]["value"] == 3


# ---------------------------------------------------------------------------
# Prometheus exposition golden test


def test_prometheus_exposition_golden():
    reg = MetricsRegistry()
    c = reg.counter("app_requests_total", "Requests served", ("code",))
    c.inc(3, code="200")
    c.inc(code="500")
    g = reg.gauge("app_queue_depth", "Queue depth")
    g.set(2.5)
    h = reg.histogram("app_latency_seconds", "Latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    expected = (
        "# HELP app_latency_seconds Latency\n"
        "# TYPE app_latency_seconds histogram\n"
        'app_latency_seconds_bucket{le="0.1"} 1\n'
        'app_latency_seconds_bucket{le="1"} 2\n'
        'app_latency_seconds_bucket{le="+Inf"} 3\n'
        "app_latency_seconds_sum 5.55\n"
        "app_latency_seconds_count 3\n"
        "# HELP app_queue_depth Queue depth\n"
        "# TYPE app_queue_depth gauge\n"
        "app_queue_depth 2.5\n"
        "# HELP app_requests_total Requests served\n"
        "# TYPE app_requests_total counter\n"
        'app_requests_total{code="200"} 3\n'
        'app_requests_total{code="500"} 1\n'
    )
    assert reg.render() == expected


def test_exposition_escapes_label_values_and_help():
    reg = MetricsRegistry()
    reg.counter("esc_total", 'has "quotes"\nand newline', ("p",)).inc(
        p='a"b\nc')
    text = reg.render()
    assert '# HELP esc_total has "quotes"\\nand newline' in text
    assert 'esc_total{p="a\\"b\\nc"} 1' in text


# ---------------------------------------------------------------------------
# structured event log


def test_event_log_emits_json_and_counts(caplog):
    with caplog.at_level(logging.DEBUG, logger=obs.LOGGER_NAME):
        obs.log_event("unit_test", level=logging.INFO, k=1, who="x")
    (rec,) = [r for r in caplog.records if r.name == obs.LOGGER_NAME]
    payload = json.loads(rec.getMessage())
    assert payload["event"] == "unit_test"
    assert payload["k"] == 1 and payload["who"] == "x"
    assert "ts" in payload
    snap = obs.snapshot()
    assert _series_value(snap, "mmlspark_events_total",
                         level="info")["value"] == 1


def test_event_counter_increments_even_when_level_suppressed(caplog):
    logger = logging.getLogger(obs.LOGGER_NAME)
    old = logger.level
    logger.setLevel(logging.WARNING)
    try:
        with caplog.at_level(logging.WARNING, logger=obs.LOGGER_NAME):
            obs.log_event("quiet", level=logging.DEBUG)
        assert not [r for r in caplog.records if r.name == obs.LOGGER_NAME]
    finally:
        logger.setLevel(old)
    assert _series_value(obs.snapshot(), "mmlspark_events_total",
                         level="debug")["value"] == 1


# ---------------------------------------------------------------------------
# end-to-end: /metrics + /healthz on a live WorkerServer


def _http_get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, dict(r.headers), r.read().decode()


_SAMPLE_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'            # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'    # optional {l="v",...}
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r' (-?[0-9.e+-]+|\+Inf|-Inf|NaN)$')


def _prime_runner_metrics():
    """Push a partition through the real BatchRunner so stage + cache
    counters carry traffic: run twice with the same shapes — the first
    pass compiles (miss + steady-state recompile), the second hits."""
    import jax

    from mmlspark_tpu.models.runner import BatchRunner

    @jax.jit
    def jitted(params, feeds):
        return {"y": feeds["x"] * params["w"]}

    data = np.arange(16, dtype=np.float32)
    runner = BatchRunner(jitted, {"w": 2.0},
                         coerce=lambda sl: {"x": data[sl]},
                         put=jax.device_put, mini_batch_size=16)
    for _ in range(2):
        (out, b), = runner.run_and_drain(16)
        assert b == 16 and np.allclose(out["y"], data * 2.0)


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_worker_server_metrics_and_healthz(transport):
    import requests

    from mmlspark_tpu.serving import WorkerServer

    _prime_runner_metrics()
    server = WorkerServer(transport=transport)
    try:
        base = f"http://127.0.0.1:{server.port}"

        # /healthz: 200, JSON body, identifies the transport
        status, headers, body = _http_get(base + "/healthz")
        assert status == 200
        assert headers["Content-Type"].startswith("application/json")
        health = json.loads(body)
        assert health["status"] == "ok"
        assert health["transport"] == transport
        assert health["port"] == server.port

        # push one real request through the queue so the latency
        # histogram sees a POST as well as the control-route GETs
        def _reply():
            while True:
                got = server.get_batch(10, timeout=0.2)
                if got:
                    server.reply_json(got[0].request_id, {"ok": True})
                    return

        t = threading.Thread(target=_reply, daemon=True)
        t.start()
        r = requests.post(base + "/", json={"x": 1.0}, timeout=10)
        t.join(timeout=10)
        assert r.status_code == 200 and r.json() == {"ok": True}

        status, headers, text = _http_get(base + "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")

        # acceptance families, with real traffic behind each
        assert re.search(
            r'mmlspark_serving_request_seconds_bucket\{transport="%s",'
            r'le="\+Inf"\} [1-9]' % transport, text)
        assert re.search(
            r'mmlspark_serving_requests_total\{transport="%s",'
            r'method="POST",code="200"\} 1' % transport, text)
        assert (f'mmlspark_serving_queue_depth{{port="{server.port}"}} 0'
                in text.splitlines())
        assert re.search(
            r'mmlspark_runner_stage_seconds_total\{stage="coerce"\} '
            r'[0-9.e+-]+', text)
        assert re.search(
            r"mmlspark_compile_cache_hits_total [1-9]", text)
        assert re.search(
            r"mmlspark_compile_cache_misses_total [1-9]", text)
        assert re.search(
            r"mmlspark_compile_cache_steady_state_recompiles_total [1-9]",
            text)

        # every non-comment line must be a well-formed sample
        for line in text.splitlines():
            if line and not line.startswith("#"):
                assert _SAMPLE_LINE.match(line), line
    finally:
        server.close()

    # closing the server retires its per-port callback gauges
    assert not any(
        s["labels"].get("port") == str(server.port)
        for s in obs.snapshot()["mmlspark_serving_queue_depth"]["series"])


def test_threaded_access_log_routes_through_event_log(caplog):
    from mmlspark_tpu.serving import WorkerServer

    server = WorkerServer(transport="threaded")
    try:
        with caplog.at_level(logging.DEBUG, logger=obs.LOGGER_NAME):
            _http_get(f"http://127.0.0.1:{server.port}/healthz")
        events = [json.loads(r.getMessage()) for r in caplog.records
                  if r.name == obs.LOGGER_NAME]
        access = [e for e in events if e["event"] == "http_access"]
        assert access and "GET /healthz" in access[0]["line"]
        assert access[0]["client"] == "127.0.0.1"
    finally:
        server.close()


def test_serving_engine_batch_metrics():
    import requests

    from mmlspark_tpu.serving import ServingEngine

    def pipeline(df):
        return df.with_column("reply", np.asarray(df["x"]) * 2.0)

    with ServingEngine(pipeline, schema={"x": float}) as eng:
        r = requests.post(eng.address, json={"x": 21.0}, timeout=10)
        assert r.status_code == 200
    snap = obs.snapshot()
    rows = _series_value(snap, "mmlspark_serving_batch_rows")
    assert rows["count"] >= 1 and rows["sum"] >= 1
    secs = _series_value(snap, "mmlspark_serving_batch_seconds")
    assert secs["count"] >= 1
