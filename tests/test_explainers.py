"""Tests for LIME/SHAP/ICE explainers (reference: explainers test split1-3)."""

import numpy as np

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.explainers import (ICETransformer, ImageLIME, ImageSHAP,
                                     TabularLIME, TabularSHAP, TextLIME,
                                     TextSHAP, VectorLIME, VectorSHAP,
                                     batched_lasso, batched_weighted_lstsq,
                                     slic_superpixels)
from mmlspark_tpu.models.linear import LogisticRegression


def _vector_df(n=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, 4))
    col = np.empty(n, dtype=object)
    for i in range(n):
        col[i] = X[i]
    return DataFrame({"features": col}), X


class _LinearModel(Transformer):
    """Deterministic scoring stub: f(x) = 3*x0 - 2*x1 (features 2,3 unused)."""
    def _transform(self, df):
        X = np.stack([np.asarray(v, dtype=np.float64) for v in df["features"]])
        return df.with_column("prediction", 3.0 * X[:, 0] - 2.0 * X[:, 1])


class _TabularModel(Transformer):
    def _transform(self, df):
        return df.with_column("prediction",
                              2.0 * df["a"].astype(float) - df["b"].astype(float))


class _TextModel(Transformer):
    """Score = 1 if 'good' appears, else 0."""
    def _transform(self, df):
        return df.with_column(
            "prediction",
            np.asarray([1.0 if "good" in str(t).split() else 0.0
                        for t in df["text"]]))


class _ImageModel(Transformer):
    """Score = mean brightness of the top-left quadrant."""
    def _transform(self, df):
        scores = [float(np.asarray(v)[:16, :16].mean()) for v in df["image"]]
        return df.with_column("prediction", np.asarray(scores))


def test_batched_solvers_recover_coefs():
    rng = np.random.default_rng(0)
    X = rng.normal(0, 1, (3, 50, 4))
    beta = np.array([1.0, -2.0, 0.0, 3.0])
    y = X @ beta
    w = np.ones((3, 50))
    coefs, inter = batched_weighted_lstsq(X, y, w)
    np.testing.assert_allclose(coefs, np.tile(beta, (3, 1)), atol=1e-3)
    coefs2, _ = batched_lasso(X, y, w, alpha=1e-4, steps=500)
    np.testing.assert_allclose(coefs2, np.tile(beta, (3, 1)), atol=0.1)


def test_vector_lime_identifies_important_features():
    df, X = _vector_df()
    lime = VectorLIME(model=_LinearModel(), target_col="prediction",
                      num_samples=200)
    out = lime.transform(df)
    exp = np.stack(list(out["explanation"]))
    # features 0 and 1 drive the model; 2 and 3 do not
    assert np.abs(exp[:, 0]).mean() > 5 * np.abs(exp[:, 2]).mean()
    assert np.abs(exp[:, 1]).mean() > 5 * np.abs(exp[:, 3]).mean()
    assert (exp[:, 0] > 0).all() and (exp[:, 1] < 0).all()


def test_vector_shap_efficiency():
    df, X = _vector_df(n=4)
    shap = VectorSHAP(model=_LinearModel(), target_col="prediction",
                      num_samples=128)
    out = shap.transform(df)
    phis = np.stack(list(out["explanation"]))  # [base, phi_0..phi_3]
    fx = 3.0 * X[:, 0] - 2.0 * X[:, 1]
    np.testing.assert_allclose(phis.sum(axis=1), fx, atol=0.05)
    assert np.abs(phis[:, 1]).mean() > 5 * np.abs(phis[:, 3]).mean()


def test_tabular_lime_and_shap():
    rng = np.random.default_rng(1)
    df = DataFrame({"a": rng.normal(0, 1, 6), "b": rng.normal(0, 1, 6),
                    "c": rng.normal(0, 1, 6)})
    lime = TabularLIME(model=_TabularModel(), target_col="prediction",
                       input_cols=["a", "b", "c"], num_samples=200)
    exp = np.stack(list(lime.transform(df)["explanation"]))
    assert np.abs(exp[:, 0]).mean() > 5 * np.abs(exp[:, 2]).mean()

    shap = TabularSHAP(model=_TabularModel(), target_col="prediction",
                       input_cols=["a", "b", "c"], num_samples=128)
    phis = np.stack(list(shap.transform(df)["explanation"]))
    fx = 2.0 * df["a"] - df["b"]
    np.testing.assert_allclose(phis.sum(axis=1), fx, atol=0.05)


def test_text_lime_and_shap():
    df = DataFrame({"text": ["good plot strong cast", "dull film bad cast"]})
    lime = TextLIME(model=_TextModel(), target_col="prediction",
                    num_samples=64)
    out = lime.transform(df)
    toks = out["tokens"][0]
    exp = out["explanation"][0]
    assert toks[int(np.argmax(exp))] == "good"

    shap = TextSHAP(model=_TextModel(), target_col="prediction",
                    num_samples=64)
    out2 = shap.transform(df)
    phis = out2["explanation"][0]  # [base, phi per token]
    assert out2["tokens"][0][int(np.argmax(phis[1:]))] == "good"


def test_image_lime_highlights_active_quadrant():
    rng = np.random.default_rng(0)
    img = rng.random((32, 32, 3)).astype(np.float32)
    col = np.empty(1, dtype=object)
    col[0] = img
    df = DataFrame({"image": col})
    lime = ImageLIME(model=_ImageModel(), target_col="prediction",
                     num_samples=64, cell_size=16)
    out = lime.transform(df)
    exp = out["explanation"][0]
    segs = out["superpixels"][0]
    assert segs.shape == (32, 32)
    # the superpixel covering the top-left quadrant must dominate
    tl_seg = segs[8, 8]
    assert exp[tl_seg] == exp.max()


def test_image_shap_efficiency():
    rng = np.random.default_rng(0)
    img = rng.random((32, 32, 3)).astype(np.float32)
    col = np.empty(1, dtype=object)
    col[0] = img
    df = DataFrame({"image": col})
    shap = ImageSHAP(model=_ImageModel(), target_col="prediction",
                     num_samples=64, cell_size=16)
    out = shap.transform(df)
    phis = out["explanation"][0]
    fx = float(img[:16, :16].mean())
    assert abs(phis.sum() - fx) < 0.05


def test_ice_transformer():
    rng = np.random.default_rng(2)
    df = DataFrame({"a": rng.normal(0, 1, 5), "b": rng.normal(0, 1, 5)})
    ice = ICETransformer(model=_TabularModel(), target_col="prediction",
                         numeric_features=["a"], num_splits=7)
    out = ice.transform(df)
    curves = out["a_dependence"]
    assert curves[0].shape == (7,)
    # f = 2a - b: each curve strictly increasing in a
    assert (np.diff(curves[0]) > 0).all()
    grid = out.column_metadata("a_dependence")["ice_grid"]
    assert len(grid) == 7

    pdp = ICETransformer(model=_TabularModel(), target_col="prediction",
                         numeric_features=["a"], kind="average",
                         num_splits=5).transform(df)
    np.testing.assert_allclose(pdp["a_dependence"][0],
                               pdp["a_dependence"][1])


def test_slic_superpixels_cover_image():
    img = np.zeros((32, 32, 3))
    img[:, 16:] = 1.0
    segs = slic_superpixels(img, cell_size=16)
    assert segs.shape == (32, 32)
    # left and right halves should never share a segment
    assert not (set(np.unique(segs[:, :8])) & set(np.unique(segs[:, 24:])))


def test_explainer_with_real_model():
    rng = np.random.default_rng(3)
    n = 60
    X = rng.normal(0, 1, (n, 3))
    y = (X[:, 0] > 0).astype(np.int64)
    col = np.empty(n, dtype=object)
    for i in range(n):
        col[i] = X[i]
    df = DataFrame({"features": col, "label": y})
    model = LogisticRegression(max_iter=200).fit(df)
    shap = VectorSHAP(model=model, target_col="probability",
                      target_classes=[1], num_samples=128)
    out = shap.transform(df.head(4))
    phis = np.stack(list(out["explanation"]))
    assert np.abs(phis[:, 1]).mean() > np.abs(phis[:, 2]).mean()


def test_shap_over_dense_multiclass_column():
    """ONNXModel-style dense (n, classes) target columns must reduce to the
    selected classes (regression: only object columns were handled)."""
    import numpy as np
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.core.pipeline import Model
    from mmlspark_tpu.explainers.shap import VectorSHAP

    class _DenseProbModel(Model):
        def _transform(self, df):
            X = np.stack([np.asarray(v) for v in df["features"]])
            z = 1 / (1 + np.exp(-(2.0 * X[:, 0])))
            probs = np.stack([1 - z, z], axis=1)  # dense (n, 2) column
            return df.with_column("probs", probs)

    rng = np.random.default_rng(0)
    X = rng.normal(0, 1, (6, 4)).astype(np.float32)
    df = DataFrame({"features": [x for x in X]})
    shap = VectorSHAP(model=_DenseProbModel(), target_col="probs",
                      target_classes=[1], num_samples=64)
    out = shap.transform(df)
    phis = np.stack(list(out["explanation"]))
    fx = 1 / (1 + np.exp(-(2.0 * X[:, 0])))
    np.testing.assert_allclose(phis.sum(axis=1), fx, atol=0.05)
    # feature 0 drives everything; feature 3 is noise
    assert np.abs(phis[:, 1]).mean() > 5 * np.abs(phis[:, 4]).mean()
