"""Draft distillation for speculative decoding (models/zoo/distill.py).

The invariant chain that makes speculative decoding worth having:
train_lm makes the target confident on a structured language →
distill_draft makes a smaller model agree with the target's greedy
choices → speculative acceptance jumps while outputs stay EXACTLY the
target's (the greedy-exactness contract of speculative.py).
"""

import numpy as np
import pytest

from mmlspark_tpu.models.zoo.distill import (distill_draft, markov_sampler,
                                             train_lm)
from mmlspark_tpu.models.zoo.speculative import generate_speculative_fused
from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                 generate_cached,
                                                 init_transformer)

T_CFG = TransformerConfig(vocab=64, layers=2, d_model=64, heads=4,
                          d_ff=128, max_len=128, causal=True,
                          norm="rmsnorm", position="rope")
D_CFG = TransformerConfig(vocab=64, layers=1, d_model=32, heads=2,
                          d_ff=64, max_len=128, causal=True,
                          norm="rmsnorm", position="rope")


@pytest.fixture(scope="module")
def trained():
    batch_fn = markov_sampler(T_CFG.vocab, batch=16, seq=32, seed=3)
    t0 = init_transformer(T_CFG, seed=0)
    t_params, hist = train_lm(t0, T_CFG, batch_fn, steps=80,
                              learning_rate=1e-3, log_every=40)
    d_params, d_hist = distill_draft(t_params, T_CFG, D_CFG, batch_fn,
                                     steps=80, learning_rate=2e-3)
    return batch_fn, t_params, d_params, hist, d_hist


class TestDistill:
    def test_lm_loss_decreases(self, trained):
        _, _, _, hist, _ = trained
        assert hist[-1] < hist[0]

    def test_kl_decreases(self, trained):
        _, _, _, _, d_hist = trained
        assert d_hist[-1] < 0.5 * d_hist[0]

    def test_distilled_draft_lifts_acceptance(self, trained):
        batch_fn, t_params, d_params, _, _ = trained
        prompt = batch_fn(999)[:1, :16]
        random_draft = init_transformer(D_CFG, seed=7)
        _, s_rand = generate_speculative_fused(
            t_params, random_draft, prompt, T_CFG, D_CFG,
            max_new_tokens=24, gamma=4)
        _, s_dist = generate_speculative_fused(
            t_params, d_params, prompt, T_CFG, D_CFG,
            max_new_tokens=24, gamma=4)
        acc_rand = s_rand["accepted_drafts"] / max(s_rand["rounds"], 1)
        acc_dist = s_dist["accepted_drafts"] / max(s_dist["rounds"], 1)
        assert acc_dist > acc_rand + 1.0          # > one extra token/round
        assert s_dist["target_forwards"] < s_rand["target_forwards"]

    def test_output_stays_target_exact(self, trained):
        batch_fn, t_params, d_params, _, _ = trained
        prompt = batch_fn(1234)[:1, :12]
        ref = generate_cached(t_params, prompt, T_CFG, max_new_tokens=20,
                              temperature=0.0)
        spec, _ = generate_speculative_fused(
            t_params, d_params, prompt, T_CFG, D_CFG,
            max_new_tokens=20, gamma=4)
        assert np.array_equal(np.asarray(ref), np.asarray(spec))

    def test_vocab_mismatch_rejected(self):
        bad = TransformerConfig(vocab=32, layers=1, d_model=32, heads=2,
                                d_ff=64, max_len=64, causal=True)
        with pytest.raises(ValueError, match="vocabulary"):
            distill_draft(init_transformer(T_CFG, 0), T_CFG, bad,
                          markov_sampler(64, 2, 8), steps=1)
