"""Portable model artifacts (mmlspark_tpu.mlflow).

Parity: the reference's generated PyTest saves every fitted model through
mlflow and reloads it as a generic pyfunc (``core/src/test/scala/com/
microsoft/azure/synapse/ml/core/test/fuzzing/Fuzzing.scala:135-140``).
These tests pin the artifact *format* (MLmodel descriptor parseable by real
YAML, pyfunc loader hook, mlruns layout) and the *capability* (reload in a
separate fresh process with identical predictions)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.pipeline import Pipeline
from mmlspark_tpu.featurize import ValueIndexer
from mmlspark_tpu.mlflow import (PyFuncModel, infer_signature, load_model,
                                 log_model, save_model, _load_pyfunc)
from mmlspark_tpu.train import TrainClassifier

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fitted_model_and_df():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 4))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
    df = DataFrame({"f0": X[:, 0], "f1": X[:, 1], "f2": X[:, 2],
                    "f3": X[:, 3], "label": y})
    est = TrainClassifier(label_col="label")
    return Pipeline([est]).fit(df), df


class TestSaveLoad:
    def test_roundtrip_identical_predictions(self, tmp_path):
        model, df = _fitted_model_and_df()
        ref = model.transform(df)
        p = str(tmp_path / "artifact")
        save_model(model, p, input_example=df)
        loaded = load_model(p)
        assert isinstance(loaded, PyFuncModel)
        out = loaded.predict(df)
        np.testing.assert_array_equal(np.asarray(ref["prediction"]),
                                      np.asarray(out["prediction"]))

    def test_predict_accepts_plain_dict(self, tmp_path):
        model, df = _fitted_model_and_df()
        p = str(tmp_path / "artifact")
        save_model(model, p)
        out = load_model(p).predict(
            {c: np.asarray(df[c]) for c in df.columns})
        assert "prediction" in out.columns

    def test_predict_pandas_in_pandas_out(self, tmp_path):
        """mlflow.pyfunc contract: pandas in → pandas out."""
        import pandas as pd
        model, df = _fitted_model_and_df()
        p = str(tmp_path / "artifact")
        save_model(model, p)
        pdf = pd.DataFrame({c: np.asarray(df[c]) for c in df.columns})
        out = load_model(p).predict(pdf)
        assert isinstance(out, pd.DataFrame)
        np.testing.assert_array_equal(
            out["prediction"].to_numpy(),
            np.asarray(model.transform(df)["prediction"]))

    def test_mlmodel_descriptor_is_valid_yaml_with_pyfunc_flavor(
            self, tmp_path):
        yaml = pytest.importorskip("yaml")
        model, df = _fitted_model_and_df()
        p = str(tmp_path / "artifact")
        save_model(model, p, input_example=df)
        with open(os.path.join(p, "MLmodel")) as fh:
            meta = yaml.safe_load(fh)
        pf = meta["flavors"]["python_function"]
        assert pf["loader_module"] == "mmlspark_tpu.mlflow"
        assert os.path.isdir(os.path.join(p, pf["data"]))
        assert "model_uuid" in meta
        # signature columns parse back as json (mlflow stores them encoded)
        sig = json.loads(meta["signature"]["inputs"])
        assert {c["name"] for c in sig} >= {"f0", "label"}
        assert os.path.exists(os.path.join(p, "requirements.txt"))

    def test_pyfunc_loader_hook(self, tmp_path):
        """_load_pyfunc(data_path) is what genuine mlflow.pyfunc calls."""
        model, df = _fitted_model_and_df()
        p = str(tmp_path / "artifact")
        save_model(model, p)
        wrapped = _load_pyfunc(os.path.join(p, "stage"))
        assert "prediction" in wrapped.predict(df).columns

    def test_fresh_process_reload(self, tmp_path):
        """The artifact is self-describing: a separate python process with
        no access to this test's state reloads and predicts identically."""
        model, df = _fitted_model_and_df()
        ref = np.asarray(model.transform(df)["prediction"])
        p = str(tmp_path / "artifact")
        save_model(model, p)
        np.save(str(tmp_path / "inputs.npy"),
                np.stack([np.asarray(df[c]) for c in
                          ("f0", "f1", "f2", "f3", "label")]))
        code = (
            "import os, sys, numpy as np\n"
            "os.environ.pop('JAX_PLATFORMS', None)\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from mmlspark_tpu.mlflow import load_model\n"
            f"cols = np.load({str(tmp_path / 'inputs.npy')!r})\n"
            "data = dict(zip(('f0','f1','f2','f3','label'), cols))\n"
            f"out = load_model({p!r}).predict(data)\n"
            "np.save(sys.argv[1], np.asarray(out['prediction']))\n")
        outp = str(tmp_path / "pred.npy")
        env = {**os.environ, "PYTHONPATH": REPO}
        r = subprocess.run([sys.executable, "-c", code, outp],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        np.testing.assert_array_equal(ref, np.load(outp))


class TestLogModel:
    def test_mlruns_layout(self, tmp_path):
        model, df = _fitted_model_and_df()
        dest = log_model(model, "model", tracking_dir=str(tmp_path / "mlruns"))
        # <tracking>/<run_id>/artifacts/model
        rel = os.path.relpath(dest, str(tmp_path / "mlruns"))
        parts = rel.split(os.sep)
        assert parts[1] == "artifacts" and parts[2] == "model"
        assert "prediction" in load_model(dest).predict(df).columns


class TestSignature:
    def test_infer_signature_shapes(self):
        df = DataFrame({"x": np.arange(4, dtype=np.float32),
                        "s": np.array(["a", "b", "c", "d"], dtype=object)})
        sig = infer_signature(df)
        byname = {c["name"]: c["type"] for c in sig["inputs"]}
        assert byname["x"] == "float32"

    def test_transformer_artifact(self, tmp_path):
        """Non-fitted transformers are artifacts too (any stage works)."""
        df = DataFrame({"cat": np.array(["a", "b", "a", "c"], dtype=object)})
        model = ValueIndexer(input_col="cat", output_col="idx").fit(df)
        p = str(tmp_path / "vi")
        save_model(model, p, input_example=df)
        out = load_model(p).predict(df)
        np.testing.assert_array_equal(np.asarray(out["idx"]),
                                      np.asarray(model.transform(df)["idx"]))


class TestOverwrite:
    def test_refuses_non_empty_path(self, tmp_path):
        model, df = _fitted_model_and_df()
        p = str(tmp_path / "artifact")
        save_model(model, p)
        with pytest.raises(FileExistsError, match="overwrite"):
            save_model(model, p)
        save_model(model, p, overwrite=True)    # replaces cleanly
        assert "prediction" in load_model(p).predict(df).columns

    def test_overwrite_clears_stale_files(self, tmp_path):
        model, df = _fitted_model_and_df()
        p = str(tmp_path / "artifact")
        save_model(model, p, input_example=df)
        assert os.path.exists(os.path.join(p, "input_example.json"))
        save_model(model, p, overwrite=True)    # no example this time
        assert not os.path.exists(os.path.join(p, "input_example.json"))

