"""Open-loop load generation: seeded arrival-process statistics, tenant
mix skew, scorecard math (fairness error, CO-corrected quantiles,
federated-counter parsing), the /debug/scenario route on both
transports, and the acceptance drill — a 3-worker ServingCluster under a
mixed-tenant open-loop scenario with seeded enqueue faults plus a
mid-run ungraceful worker restart, reconciled exactly against the
driver's federated counters with zero lost requests.
"""

import http.client
import json
import random
import statistics
import threading
import urllib.request

import pytest

from mmlspark_tpu.loadgen import (Arrival, TenantMix, cluster_echo_engine,
                                  diurnal_offsets, fairness_error,
                                  get_progress, get_scenario,
                                  heavy_tail_rows, interarrivals,
                                  merged_requests_total, plan,
                                  poisson_offsets, quantiles_ms,
                                  reset_progress, run_scenario)
from mmlspark_tpu.observability import reset_all
from mmlspark_tpu.observability.federation import FEDERATION_INTERVAL_ENV
from mmlspark_tpu.observability.ledger import reset_ledger
from mmlspark_tpu.observability.slo import reset_tracker
from mmlspark_tpu.observability.watchdog import reset_watchdog
from mmlspark_tpu.reliability import get_injector, reset_breakers
from mmlspark_tpu.serving.distributed import ServingCluster
from mmlspark_tpu.tuning.observations import ObservationStore, reset_store


@pytest.fixture(autouse=True)
def _clean_slate():
    for reset in (reset_ledger, reset_tracker, reset_watchdog,
                  reset_breakers, reset_store, reset_progress, reset_all):
        reset()
    get_injector().clear()
    yield
    for reset in (reset_ledger, reset_tracker, reset_watchdog,
                  reset_breakers, reset_store, reset_progress, reset_all):
        reset()
    get_injector().clear()


# ---------------------------------------------------------------------------
# arrival processes


def test_poisson_interarrival_mean_and_variance():
    rate = 50.0
    offs = poisson_offsets(rate, 40.0, random.Random(42))
    gaps = interarrivals(offs)
    assert len(gaps) > 1500
    mean = statistics.fmean(gaps)
    var = statistics.variance(gaps)
    # Exponential(rate): mean 1/rate, variance 1/rate^2
    assert mean == pytest.approx(1.0 / rate, rel=0.10)
    assert var == pytest.approx(1.0 / rate ** 2, rel=0.30)
    assert all(g > 0 for g in gaps)
    assert all(0 <= t < 40.0 for t in offs)


def test_poisson_seeded_determinism():
    assert poisson_offsets(20.0, 5.0, random.Random(7)) == \
        poisson_offsets(20.0, 5.0, random.Random(7))


def test_diurnal_modulation_shape():
    # period == duration: first half is the "day" (rate * (1+depth*sin)
    # above mean), second half the "night" — counts must separate hard
    duration = 20.0
    offs = diurnal_offsets(50.0, duration, random.Random(3), depth=0.8)
    first = sum(1 for t in offs if t < duration / 2)
    second = len(offs) - first
    assert first > second * 1.5
    # total volume stays near the base rate (the envelope integrates to
    # rate * duration over a full period)
    assert len(offs) == pytest.approx(50.0 * duration, rel=0.15)


def test_diurnal_zero_depth_is_plain_poisson_rate():
    offs = diurnal_offsets(40.0, 10.0, random.Random(5), depth=0.0)
    assert len(offs) == pytest.approx(400, rel=0.15)


def test_heavy_tail_rows_quantiles():
    rng = random.Random(11)
    xs = sorted(heavy_tail_rows(rng, median=8, alpha=1.6, cap=4096)
                for _ in range(20_000))
    med = xs[len(xs) // 2]
    p99 = xs[int(0.99 * len(xs))]
    assert 6 <= med <= 10                       # median lands where asked
    assert p99 >= 3 * med                       # the tail is actually heavy
    assert xs[-1] <= 4096 and xs[0] >= 1        # cap and floor hold


def test_tenant_mix_weights_and_prefix_skew():
    rng = random.Random(9)
    mix = TenantMix({"acme": 3.0, "beta": 1.0}, prefix_pool=4,
                    prefix_skew=1.1, keyed_fraction=0.75)
    picks = [mix.pick(rng) for _ in range(8000)]
    acme = sum(1 for t, _ in picks if t == "acme")
    assert acme / len(picks) == pytest.approx(0.75, abs=0.03)
    keyed = [p for _, p in picks if p is not None]
    assert len(keyed) / len(picks) == pytest.approx(0.75, abs=0.03)
    # Zipf skew: rank-1 prefixes are the hottest; keys are deterministic
    # "{tenant}-p{rank}" so affinity routing sees stable hot keys
    assert all(p.split("-p")[1].isdigit() for p in keyed)
    r1 = sum(1 for p in keyed if p.endswith("-p1"))
    r4 = sum(1 for p in keyed if p.endswith("-p4"))
    assert r1 > r4


def test_plan_is_deterministic_and_complete():
    sc = get_scenario("smoke")
    a, b = plan(sc), plan(sc)
    assert a == b and len(a) > 0
    assert [x.index for x in a] == list(range(len(a)))
    assert all(isinstance(x, Arrival) and x.rows >= 1 for x in a)
    assert {x.tenant for x in a} <= set(sc.tenants)
    assert {x.workload for x in a} <= set(sc.workloads)


# ---------------------------------------------------------------------------
# scorecard math


def test_fairness_error_known_shares():
    # achieved shares exactly proportional to weights → zero error
    assert fairness_error({"a": 30, "b": 10}, {"a": 3.0, "b": 1.0}) == 0.0
    # equal weights, one tenant starved: TV distance = 0.5
    assert fairness_error({"a": 40, "b": 0}, {"a": 1.0, "b": 1.0}) == \
        pytest.approx(0.5)
    # 60/40 against 50/50 → |0.6-0.5|/2 + |0.4-0.5|/2 = 0.1
    assert fairness_error({"a": 60, "b": 40}, {"a": 1.0, "b": 1.0}) == \
        pytest.approx(0.1)
    assert fairness_error({}, {}) == 0.0


def test_quantiles_ms_nearest_rank():
    assert quantiles_ms([]) is None
    q = quantiles_ms([i / 1000.0 for i in range(1, 101)])
    assert q["p50_ms"] == pytest.approx(51.0)
    assert q["p99_ms"] == pytest.approx(99.0)
    assert q["max_ms"] == pytest.approx(100.0)
    assert q["n"] == 100


def test_merged_requests_total_parses_federated_metrics():
    text = ("# HELP mmlspark_serving_requests_total h\n"
            'mmlspark_serving_requests_total{transport="threaded"} 12\n'
            'mmlspark_serving_requests_total{transport="async"} 30\n'
            'mmlspark_other_total{x="y"} 99\n')
    assert merged_requests_total(text) == 42.0


# ---------------------------------------------------------------------------
# /debug/scenario on both transports


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _get_json_port(port, path):
    # http.client, not urlopen: the async transport's keep-alive framing
    # and urllib don't get along (same convention as test_serving_async)
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    try:
        conn.request("GET", path)
        return json.loads(conn.getresponse().read().decode("utf-8"))
    finally:
        conn.close()


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_debug_scenario_route_both_transports(transport):
    from mmlspark_tpu.serving.server import WorkerServer
    server = WorkerServer(transport=transport)
    try:
        assert _get_json_port(server.port, "/debug/scenario")["state"] == \
            "idle"
        progress = get_progress()
        progress.begin("drill", 10)
        progress.note_sent(3)
        progress.note_done("ok")
        live = _get_json_port(server.port, "/debug/scenario")
        assert live["scenario"] == "drill" and live["state"] == "running"
        assert live["sent"] == 3 and live["ok"] == 1
        progress.finish({"ok": 1})
        done = _get_json_port(server.port, "/debug/scenario")
        assert done["state"] == "done" and done["summary"] == {"ok": 1}
    finally:
        server.close()


# ---------------------------------------------------------------------------
# acceptance: chaos scenario against a 3-worker cluster


def test_scenario_e2e_chaos_scorecard(monkeypatch):
    # federate telemetry on every heartbeat so the final quiesced
    # heartbeat sweep gives the driver an exact same-instant view
    monkeypatch.setenv(FEDERATION_INTERVAL_ENV, "0")
    store = ObservationStore()
    # tiny admission queues + a slow engine put the offered rate well
    # above capacity: 429s (shed), honored Retry-After retries, and —
    # with the seeded enqueue faults and the mid-run ungraceful restart —
    # client-side breaker flaps, all deterministic in kind (not count)
    scenario = get_scenario(
        "mixed-tenant-chaos", duration_s=1.5, rate=150.0,
        faults="enqueue:error:every=3:times=24",
        restart_at_s=0.7, restart_worker="worker-1",
        deadline_s=3.0, max_retries=2)
    # queue depth (3 workers x 4) below the sender concurrency (32), so
    # the open-loop burst MUST overflow admission into 429s
    cluster = ServingCluster(3, reply_timeout=5.0, max_queue=4)
    stop = threading.Event()
    engine = cluster_echo_engine(cluster, stop, service_s=0.04, batch=4)
    try:
        card = run_scenario(scenario, cluster, closed_loop_n=25,
                            senders=32, store=store, mesh_shape="single",
                            kv_dtype="int8")
        live = _get_json(cluster.workers[0].server.address
                         + "/debug/scenario")
    finally:
        stop.set()
        engine.join(timeout=2.0)
        cluster.close()

    # complete scorecard: every planned arrival ended somewhere
    assert card["arrivals"] > 100
    assert card["lost"] == 0
    assert card["ok"] + card["shed"] + card["errors"] == card["arrivals"]
    assert card["ok"] > 0

    # chaos left fingerprints: shed, retries (incl. honored Retry-After),
    # breaker transitions, injected faults
    assert card["shed"] > 0
    assert card["retry"]["retries"] > 0
    assert card["retry"]["amplification"] > 1.0
    assert card["retry"]["honored_retry_after"] > 0
    assert card["breaker"]["transitions"] > 0
    assert card["faults_injected"] > 0

    # the merged federated counter reconciles EXACTLY: every worker
    # heartbeat at the same quiesced instant, and the in-process cluster
    # shares one metrics registry, so merged == n_workers * global
    cl = card["cluster"]
    assert cl["reconciled"] is True
    assert cl["merged_requests_total"] == \
        cl["workers"] * cl["global_requests_total"]

    # coordinated omission is visible: the open-loop (scheduled-send)
    # p99 exceeds the closed-loop p99 on the same workload
    assert card["loop_mode"] == "open"
    assert card["closed_loop"]["loop_mode"] == "closed"
    assert card["latency_ms"]["p99_ms"] > \
        card["closed_loop"]["latency_ms"]["p99_ms"]

    # scorecard rows landed in the ObservationStore via the existing
    # slo_scorecard source (cost rows harvest server-side via /debug/costs)
    rows = store.rows(source="slo_scorecard")
    assert rows
    assert all(r["sig"].startswith("slo:") for r in rows)

    # bench stamps + tenant accounting rode along
    assert card["mesh_shape"] == "single" and card["kv_dtype"] == "int8"
    assert set(card["tenants"]) <= set(scenario.tenants)
    assert 0.0 <= card["fairness_error"] <= 1.0
    for row in card["tenants"].values():
        assert row["arrivals"] == row["ok"] + row["shed"] + row["errors"]

    # the live route saw the run finish
    assert live["state"] == "done" and live["scenario"] == scenario.name
    assert live["summary"]["lost"] == 0


def test_smoke_scenario_clean_run(monkeypatch):
    # the CI-facing path: no restart, light faults, ample capacity —
    # everything lands, mostly ok, reconciliation still exact
    monkeypatch.setenv(FEDERATION_INTERVAL_ENV, "0")
    scenario = get_scenario("smoke", duration_s=1.0, rate=25.0)
    cluster = ServingCluster(3, reply_timeout=5.0, max_queue=256)
    stop = threading.Event()
    engine = cluster_echo_engine(cluster, stop, batch=16)
    try:
        card = run_scenario(scenario, cluster, closed_loop_n=8)
    finally:
        stop.set()
        engine.join(timeout=2.0)
        cluster.close()
    assert card["lost"] == 0
    assert card["ok"] + card["shed"] + card["errors"] == card["arrivals"]
    assert card["ok"] >= card["arrivals"] * 0.8
    assert card["cluster"]["reconciled"] is True
    assert card["harvested"]["slo_rows"] > 0
