import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame, PipelineStage
from mmlspark_tpu.models.gbdt import (BinMapper, Booster, LightGBMClassifier,
                                      LightGBMRanker, LightGBMRegressor, train)


def make_binary(n=600, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, f))
    logit = X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (logit + rng.normal(0, 0.3, n) > 0).astype(np.float64)
    return X, y


class TestBinning:
    def test_roundtrip_monotone(self):
        rng = np.random.default_rng(0)
        X = rng.normal(0, 1, (500, 3))
        bm = BinMapper(max_bin=16).fit(X)
        xb = bm.transform(X)
        assert xb.dtype == np.uint8
        assert xb.min() >= 1  # no missing
        # binning preserves order within a feature
        j = 0
        order = np.argsort(X[:, j])
        assert (np.diff(xb[order, j].astype(int)) >= 0).all()

    def test_missing_to_bin0(self):
        X = np.array([[1.0], [np.nan], [2.0]])
        bm = BinMapper(max_bin=4).fit(X)
        xb = bm.transform(X)
        assert xb[1, 0] == 0 and xb[0, 0] >= 1

    def test_threshold_semantics(self):
        X = np.linspace(0, 1, 100).reshape(-1, 1)
        bm = BinMapper(max_bin=8).fit(X)
        xb = bm.transform(X)[:, 0]
        for b in range(1, xb.max()):
            t = bm.bin_threshold_value(0, b)
            lhs = X[:, 0][xb <= b]
            rhs = X[:, 0][xb > b]
            assert (lhs <= t + 1e-12).all() and (rhs > t - 1e-12).all()


class TestTrainCore:
    def test_regression_learns(self):
        rng = np.random.default_rng(1)
        X = rng.normal(0, 1, (800, 5))
        y = 3 * X[:, 0] + np.sin(3 * X[:, 1]) + 0.1 * rng.normal(size=800)
        b = train({"objective": "regression", "num_iterations": 60,
                   "learning_rate": 0.2, "num_leaves": 15,
                   "min_data_in_leaf": 5}, X, y)
        pred = b.predict(X)
        r2 = 1 - np.var(y - pred) / np.var(y)
        assert r2 > 0.9, r2

    def test_binary_auc_vs_sklearn(self):
        from sklearn.ensemble import GradientBoostingClassifier
        from sklearn.metrics import roc_auc_score
        X, y = make_binary()
        Xtr, ytr, Xte, yte = X[:400], y[:400], X[400:], y[400:]
        b = train({"objective": "binary", "num_iterations": 80,
                   "learning_rate": 0.15, "num_leaves": 15,
                   "min_data_in_leaf": 5}, Xtr, ytr)
        ours = roc_auc_score(yte, b.predict(Xte))
        skl = GradientBoostingClassifier(n_estimators=80, max_depth=4)
        skl.fit(Xtr, ytr)
        theirs = roc_auc_score(yte, skl.predict_proba(Xte)[:, 1])
        assert ours > 0.9
        assert ours > theirs - 0.05, (ours, theirs)

    def test_multiclass(self):
        rng = np.random.default_rng(2)
        n = 600
        X = rng.normal(0, 1, (n, 4))
        y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
        b = train({"objective": "multiclass", "num_class": 3,
                   "num_iterations": 40, "learning_rate": 0.3,
                   "num_leaves": 15, "min_data_in_leaf": 5}, X, y)
        p = b.predict(X)
        assert p.shape == (n, 3)
        np.testing.assert_allclose(p.sum(1), 1.0, atol=1e-5)
        acc = (p.argmax(1) == y).mean()
        assert acc > 0.9, acc

    def test_early_stopping(self):
        X, y = make_binary(seed=3)
        log = []
        b = train({"objective": "binary", "num_iterations": 200,
                   "learning_rate": 0.3, "num_leaves": 31,
                   "early_stopping_round": 5, "metric": "binary_logloss",
                   "min_data_in_leaf": 2},
                  X[:300], y[:300], valid_sets=[(X[300:], y[300:])],
                  eval_log=log)
        assert b.num_trees < 200
        assert b.best_iteration > 0

    def test_weights_respected(self):
        rng = np.random.default_rng(4)
        X = rng.normal(0, 1, (400, 2))
        y = (X[:, 0] > 0).astype(float)
        w = np.where(X[:, 1] > 0, 1.0, 1e-6)  # only care about x1>0 rows
        b = train({"objective": "binary", "num_iterations": 20,
                   "min_data_in_leaf": 1}, X, y, sample_weight=w)
        assert b.num_trees == 20

    def test_warm_start_early_stop_keeps_init_trees(self):
        X, y = make_binary(seed=15)
        b1 = train({"objective": "binary", "num_iterations": 15,
                    "min_data_in_leaf": 2}, X[:300], y[:300])
        b2 = train({"objective": "binary", "num_iterations": 100,
                    "learning_rate": 0.3, "early_stopping_round": 3,
                    "min_data_in_leaf": 2},
                   X[:300], y[:300], init_model=b1,
                   valid_sets=[(X[300:], y[300:])])
        assert b2.num_trees >= 15  # init trees never dropped
        # continued model should not be worse than init alone on train data
        from sklearn.metrics import roc_auc_score
        auc1 = roc_auc_score(y[:300], b1.predict(X[:300]))
        auc2 = roc_auc_score(y[:300], b2.predict(X[:300]))
        assert auc2 >= auc1 - 0.01

    def test_ranker_non_contiguous_groups_rejected(self):
        rng = np.random.default_rng(16)
        X = rng.normal(0, 1, (8, 2))
        y = rng.integers(0, 3, 8).astype(float)
        df = DataFrame({"features": [X[i] for i in range(8)], "label": y,
                        "group": np.array([0, 1, 0, 1, 0, 1, 0, 1])})
        with pytest.raises(ValueError, match="not contiguous"):
            LightGBMRanker(num_iterations=2).fit(df)

    def test_warm_start_merge(self):
        X, y = make_binary(seed=5)
        b1 = train({"objective": "binary", "num_iterations": 10}, X, y)
        b2 = train({"objective": "binary", "num_iterations": 10}, X, y,
                   init_model=b1)
        assert b2.num_trees == 20
        s = b2.to_string()
        b3 = Booster.from_string(s)
        np.testing.assert_allclose(b2.predict(X[:10]), b3.predict(X[:10]),
                                   rtol=1e-6)


class TestBoosterOutputs:
    def test_leaf_prediction(self):
        X, y = make_binary(seed=6)
        b = train({"objective": "binary", "num_iterations": 5}, X, y)
        leaves = b.predict_leaf(X[:20])
        assert leaves.shape == (20, 5)
        assert leaves.min() >= 0

    def test_shap_sums_to_prediction(self):
        X, y = make_binary(n=200, seed=7)
        b = train({"objective": "binary", "num_iterations": 8,
                   "num_leaves": 7, "min_data_in_leaf": 5}, X, y)
        sub = X[:32]
        shap = b.shap_values(sub)
        assert shap.shape == (32, X.shape[1] + 1)
        raw = b.predict(sub, raw_score=True)
        np.testing.assert_allclose(shap.sum(axis=1), raw, rtol=1e-4, atol=1e-4)

    def test_feature_importance(self):
        X, y = make_binary(seed=8)
        b = train({"objective": "binary", "num_iterations": 20}, X, y)
        imp = b.feature_importance("split")
        assert imp.sum() > 0 and imp[0] > 0
        gain = b.feature_importance("gain")
        # x0 is the dominant signal → top total gain
        assert gain[0] == gain.max()

    def test_nan_handling(self):
        rng = np.random.default_rng(9)
        X = rng.normal(0, 1, (400, 3))
        y = 2 * X[:, 0] + rng.normal(0, 0.1, 400)
        Xm = X.copy()
        Xm[::7, 0] = np.nan
        b = train({"objective": "regression", "num_iterations": 30,
                   "min_data_in_leaf": 3}, Xm, y)
        pred = b.predict(Xm)
        assert np.isfinite(pred).all()


class TestDistributed:
    def test_data_parallel_matches_serial(self):
        from mmlspark_tpu.parallel import make_mesh
        X, y = make_binary(n=500, seed=10)
        params = {"objective": "binary", "num_iterations": 10,
                  "learning_rate": 0.2, "num_leaves": 15,
                  "min_data_in_leaf": 5}
        b_serial = train(dict(params), X, y)
        mesh = make_mesh({"data": 8})
        b_dist = train(dict(params, tree_learner="data_parallel"), X, y,
                       mesh=mesh)
        np.testing.assert_allclose(b_serial.predict(X[:50]),
                                   b_dist.predict(X[:50]), rtol=1e-4,
                                   atol=1e-5)


class TestEstimators:
    def _df(self, X, y, extra=None):
        cols = {"features": [X[i] for i in range(len(X))], "label": y}
        if extra:
            cols.update(extra)
        return DataFrame(cols)

    def test_classifier_pipeline(self, tmp_save):
        X, y = make_binary(seed=11)
        df = self._df(X, y)
        clf = LightGBMClassifier(num_iterations=30, num_leaves=15,
                                 min_data_in_leaf=5)
        model = clf.fit(df)
        out = model.transform(df)
        assert "prediction" in out and "probability" in out
        acc = (np.asarray(out["prediction"]) == y).mean()
        assert acc > 0.9
        p0 = out["probability"][0]
        assert len(p0) == 2 and abs(p0.sum() - 1) < 1e-6
        model.save(tmp_save)
        m2 = PipelineStage.load(tmp_save)
        out2 = m2.transform(df)
        np.testing.assert_allclose(np.asarray(out["prediction"]),
                                   np.asarray(out2["prediction"]))

    def test_regressor_with_shap_cols(self):
        rng = np.random.default_rng(12)
        X = rng.normal(0, 1, (300, 4))
        y = X[:, 0] * 2 + rng.normal(0, 0.1, 300)
        df = self._df(X, y)
        reg = LightGBMRegressor(num_iterations=20, min_data_in_leaf=5,
                                leaf_prediction_col="leaves",
                                features_shap_col="shap")
        model = reg.fit(df)
        out = model.transform(df.head(10))
        assert len(out["leaves"][0]) == model.booster.num_trees
        assert len(out["shap"][0]) == 5

    def test_ranker(self):
        rng = np.random.default_rng(13)
        n_q, per_q = 30, 10
        X = rng.normal(0, 1, (n_q * per_q, 4))
        rel = np.clip((X[:, 0] * 2 + rng.normal(0, 0.5, n_q * per_q)).round(),
                      0, 3)
        qid = np.repeat(np.arange(n_q), per_q)
        df = self._df(X, rel, extra={"group": qid})
        rk = LightGBMRanker(num_iterations=20, num_leaves=7,
                            min_data_in_leaf=3)
        model = rk.fit(df)
        out = model.transform(df)
        # predicted order should correlate with relevance
        from scipy.stats import spearmanr
        rho = spearmanr(np.asarray(out["prediction"]), rel).statistic
        assert rho > 0.5, rho

    def test_validation_indicator_early_stop(self):
        X, y = make_binary(seed=14)
        is_val = np.zeros(len(y), dtype=bool)
        is_val[::4] = True
        df = self._df(X, y, extra={"isVal": is_val})
        clf = LightGBMClassifier(num_iterations=200, learning_rate=0.3,
                                 early_stopping_round=5,
                                 validation_indicator_col="isVal",
                                 min_data_in_leaf=2)
        model = clf.fit(df)
        assert model.booster.num_trees < 200


class TestRefit:
    """LightGBM ``Booster.refit``: keep structures, re-estimate leaves on
    new data with decay blending — the cheap domain-shift adaptation."""

    def _fit(self, X, y, **kw):
        return train({"objective": "regression", "num_iterations": 25,
                      "num_leaves": 15, "min_data_in_leaf": 5,
                      "learning_rate": 0.1, **kw}, X, y)

    def test_decay_one_is_identity(self):
        rng = np.random.default_rng(30)
        X = rng.normal(0, 1, (400, 4))
        y = 2 * X[:, 0] + rng.normal(0, 0.2, 400)
        b = self._fit(X, y)
        r = b.refit(X, y, decay_rate=1.0)
        np.testing.assert_allclose(r.predict(X), b.predict(X), rtol=1e-6)
        np.testing.assert_array_equal(r.feats, b.feats)

    def test_adapts_to_shifted_target(self):
        rng = np.random.default_rng(31)
        X = rng.normal(0, 1, (600, 4))
        y_old = 2 * X[:, 0] + rng.normal(0, 0.2, 600)
        y_new = y_old + 3.0                  # constant domain shift
        b = self._fit(X, y_old)
        r = b.refit(X, y_new, decay_rate=0.1, learning_rate=0.1)
        mse_before = np.mean((b.predict(X) - y_new) ** 2)
        mse_after = np.mean((r.predict(X) - y_new) ** 2)
        assert mse_after < 0.5 * mse_before, (mse_before, mse_after)
        # structures untouched, only leaf values moved
        np.testing.assert_array_equal(r.feats, b.feats)
        np.testing.assert_array_equal(r.thr_raw, b.thr_raw)
        assert np.abs(r.leaf_values - b.leaf_values).max() > 0

    def test_binary_objective_refit(self):
        rng = np.random.default_rng(32)
        X = rng.normal(0, 1, (400, 4))
        y = (X[:, 0] > 0).astype(np.float64)
        b = train({"objective": "binary", "num_iterations": 15,
                   "num_leaves": 7, "min_data_in_leaf": 5}, X, y)
        y_flip = 1.0 - y                     # adversarial shift
        r = b.refit(X, y_flip, decay_rate=0.0)
        acc = ((r.predict(X) > 0.5) == y_flip).mean()
        assert acc > 0.8, acc

    def test_validation(self):
        rng = np.random.default_rng(33)
        X = rng.normal(0, 1, (100, 3))
        y = X[:, 0]
        b = self._fit(X, y, num_iterations=3)
        with pytest.raises(ValueError, match="decay_rate"):
            b.refit(X, y, decay_rate=1.5)

    def test_multiclass_decay_one_is_identity(self):
        rng = np.random.default_rng(34)
        X = rng.normal(0, 1, (400, 4))
        y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
        b = train({"objective": "multiclass", "num_class": 3,
                   "num_iterations": 10, "num_leaves": 7,
                   "min_data_in_leaf": 5}, X, y)
        r = b.refit(X, y, decay_rate=1.0)
        np.testing.assert_allclose(r.predict(X), b.predict(X), rtol=1e-6)
        np.testing.assert_array_equal(r.feats, b.feats)

    def test_multiclass_adapts_to_relabeled_classes(self):
        # cyclic label permutation: structures must survive, per-class leaf
        # values must re-estimate (LightGBM Booster.refit on multiclass)
        rng = np.random.default_rng(35)
        X = rng.normal(0, 1, (600, 4))
        y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
        b = train({"objective": "multiclass", "num_class": 3,
                   "num_iterations": 15, "num_leaves": 7,
                   "min_data_in_leaf": 5, "learning_rate": 0.2}, X, y)
        y_new = (y + 1) % 3
        r = b.refit(X, y_new, decay_rate=0.0)
        acc_before = (np.argmax(b.predict(X), -1) == y_new).mean()
        acc_after = (np.argmax(r.predict(X), -1) == y_new).mean()
        assert acc_after > 0.8 > acc_before, (acc_before, acc_after)
        np.testing.assert_array_equal(r.thr_raw, b.thr_raw)


class TestImbalanceAndInitScore:
    """LightGBM scale_pos_weight / is_unbalance / init_score parity."""

    def _imbalanced(self, n=600, pos_frac=0.1, seed=40):
        rng = np.random.default_rng(seed)
        X = rng.normal(0, 1, (n, 4))
        logit = X[:, 0] * 2 - 2.2          # rare positives
        y = (logit + rng.normal(0, 0.5, n) > 0).astype(np.float64)
        return X, y

    def test_scale_pos_weight_raises_recall(self):
        X, y = self._imbalanced()
        base = {"objective": "binary", "num_iterations": 30,
                "num_leaves": 7, "min_data_in_leaf": 5}
        b0 = train(dict(base), X, y)
        b1 = train(dict(base, scale_pos_weight=8.0), X, y)
        rec0 = ((b0.predict(X) > 0.5) & (y == 1)).sum() / max(y.sum(), 1)
        rec1 = ((b1.predict(X) > 0.5) & (y == 1)).sum() / max(y.sum(), 1)
        assert rec1 > rec0

    def test_is_unbalance_matches_explicit_ratio(self):
        X, y = self._imbalanced()
        spw = float((y != 1).sum()) / float((y == 1).sum())
        base = {"objective": "binary", "num_iterations": 10,
                "num_leaves": 7, "min_data_in_leaf": 5}
        b_auto = train(dict(base, is_unbalance=True), X, y)
        b_spw = train(dict(base, scale_pos_weight=spw), X, y)
        np.testing.assert_allclose(b_auto.predict(X), b_spw.predict(X),
                                   rtol=1e-6)

    def test_imbalance_validation(self):
        X, y = self._imbalanced(n=100)
        with pytest.raises(ValueError, match="not both"):
            train({"objective": "binary", "num_iterations": 2,
                   "is_unbalance": True, "scale_pos_weight": 2.0}, X, y)
        with pytest.raises(ValueError, match="binary objective"):
            train({"objective": "regression", "num_iterations": 2,
                   "scale_pos_weight": 2.0}, X, y)

    def test_init_score_residual_fit(self):
        # a strong external margin: the booster only needs the residual,
        # and its raw predictions EXCLUDE the margin (LightGBM semantics)
        rng = np.random.default_rng(41)
        X = rng.normal(0, 1, (500, 4))
        margin = 3.0 * X[:, 0]
        y = margin + np.sin(2 * X[:, 1]) + rng.normal(0, 0.1, 500)
        b = train({"objective": "regression", "num_iterations": 40,
                   "num_leaves": 15, "min_data_in_leaf": 5},
                  X, y, init_score=margin)
        resid_pred = b.predict(X, raw_score=True)
        # model learned the residual, not the margin
        r2_resid = 1 - np.var((y - margin) - resid_pred) \
            / np.var(y - margin)
        assert r2_resid > 0.8, r2_resid
        full = margin + resid_pred
        assert 1 - np.var(y - full) / np.var(y) > 0.95

    def test_init_score_validation(self):
        rng = np.random.default_rng(42)
        X = rng.normal(0, 1, (100, 3))
        y = X[:, 0]
        with pytest.raises(ValueError, match="init_score shape"):
            train({"objective": "regression", "num_iterations": 2}, X, y,
                  init_score=np.zeros(50))
        b = train({"objective": "regression", "num_iterations": 2}, X, y)
        with pytest.raises(ValueError, match="warm-start"):
            train({"objective": "regression", "num_iterations": 2}, X, y,
                  init_model=b, init_score=np.zeros(100))

    def test_init_score_with_valid_sets(self):
        rng = np.random.default_rng(44)
        X = rng.normal(0, 1, (500, 4))
        margin = 2.0 * X[:, 0]
        y = margin + np.sin(2 * X[:, 1]) + rng.normal(0, 0.1, 500)
        with pytest.raises(ValueError, match="valid_init_scores"):
            train({"objective": "regression", "num_iterations": 4},
                  X[:400], y[:400], init_score=margin[:400],
                  valid_sets=[(X[400:], y[400:])])
        log = []
        b = train({"objective": "regression", "num_iterations": 40,
                   "num_leaves": 15, "min_data_in_leaf": 5,
                   "early_stopping_round": 8},
                  X[:400], y[:400], init_score=margin[:400],
                  valid_sets=[(X[400:], y[400:])],
                  valid_init_scores=[margin[400:]], eval_log=log)
        # eval at the proper margin: the final validation loss is small
        assert log[-1]["l2"] < 0.1, log[-1]
        with pytest.raises(ValueError, match="checkpoints"):
            train({"objective": "regression", "num_iterations": 2,
                   "checkpoint_dir": "/tmp/nope"}, X, y, init_score=margin)

    def test_is_unbalance_no_positives_rejected(self):
        rng = np.random.default_rng(45)
        X = rng.normal(0, 1, (100, 3))
        y = np.zeros(100)
        with pytest.raises(ValueError, match="no positive"):
            train({"objective": "binary", "num_iterations": 2,
                   "is_unbalance": True}, X, y)

    def test_estimator_init_score_col(self):
        from mmlspark_tpu.core import DataFrame
        rng = np.random.default_rng(43)
        X = rng.normal(0, 1, (300, 3)).astype(np.float32)
        margin = 2.0 * X[:, 0].astype(np.float64)
        y = margin + X[:, 1]
        col = np.empty(300, dtype=object)
        col[:] = list(X)
        df = DataFrame({"features": col, "label": y, "margin": margin})
        from mmlspark_tpu.models.gbdt import LightGBMRegressor
        m = LightGBMRegressor(num_iterations=25, num_leaves=15,
                              min_data_in_leaf=5,
                              init_score_col="margin").fit(df)
        resid = np.asarray(m.transform(df)["prediction"], dtype=np.float64)
        r2 = 1 - np.var((y - margin) - resid) / max(np.var(y - margin), 1e-9)
        assert r2 > 0.7, r2


def test_trees_to_dataframe():
    rng = np.random.default_rng(50)
    X = rng.normal(0, 1, (300, 4))
    y = 2 * X[:, 0] + rng.normal(0, 0.2, 300)
    b = train({"objective": "regression", "num_iterations": 3,
               "num_leaves": 7, "min_data_in_leaf": 5}, X, y)
    df = b.trees_to_dataframe()
    n_int, n_leaf = b.feats.shape[1], 2 ** b.depth
    assert len(df) == 3 * (n_int + n_leaf)
    t0 = df.filter(np.asarray(df["tree_index"]) == 0)
    # split rows carry real features/gains; stubs are NaN like leaves
    splits = np.asarray(t0["node_type"]) == "split"
    stubs = np.asarray(t0["node_type"]) == "stub"
    leaves = np.asarray(t0["node_type"]) == "leaf"
    assert splits.sum() >= 1 and leaves.sum() == n_leaf
    assert (np.asarray(t0["split_feature"])[splits] >= 0).all()
    thr = np.asarray(t0["threshold"], dtype=np.float64)
    assert np.isfinite(thr[splits]).all()
    if stubs.any():
        assert np.isnan(thr[stubs]).all()
    assert np.isfinite(np.asarray(t0["value"], dtype=np.float64)[leaves]).all()
    # root cover counts every training row
    assert float(np.asarray(t0["count"])[0]) == 300.0


def test_trees_to_dataframe_multiclass():
    rng = np.random.default_rng(51)
    X = rng.normal(0, 1, (300, 4))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    b = train({"objective": "multiclass", "num_class": 3,
               "num_iterations": 2, "num_leaves": 7,
               "min_data_in_leaf": 5}, X, y)
    df = b.trees_to_dataframe()
    leaves = np.asarray(df["node_type"]) == "leaf"
    classes = np.asarray(df["class_index"])[leaves]
    # one leaf row per class, per-class values preserved (no cross-class sum)
    assert set(classes.tolist()) == {0, 1, 2}
    n_leaf = 2 ** b.depth
    assert leaves.sum() == b.num_trees * 3 * n_leaf


def test_predict_num_iteration_cap():
    rng = np.random.default_rng(52)
    X = rng.normal(0, 1, (300, 4))
    y = 2 * X[:, 0] + rng.normal(0, 0.2, 300)
    b = train({"objective": "regression", "num_iterations": 20,
               "num_leaves": 7, "min_data_in_leaf": 5}, X, y)
    full = b.predict(X)
    k5 = b.predict(X, num_iteration=5)
    np.testing.assert_allclose(k5, b.truncated(5).predict(X), rtol=1e-6)
    assert np.abs(full - k5).max() > 0
    np.testing.assert_allclose(b.predict(X, num_iteration=0), full)
    np.testing.assert_allclose(b.predict(X, num_iteration=-1), full)
    # LightGBM semantics: None uses best_iteration when one exists
    b.best_iteration = 7
    np.testing.assert_allclose(b.predict(X),
                               b.truncated(7).predict(X), rtol=1e-6)
    np.testing.assert_allclose(b.predict(X, num_iteration=0), full)
    # multiclass counts iterations, not trees
    ym = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    bm = train({"objective": "multiclass", "num_class": 3,
                "num_iterations": 6, "num_leaves": 7,
                "min_data_in_leaf": 5}, X, ym)
    np.testing.assert_allclose(bm.predict(X, num_iteration=2),
                               bm.truncated(6).predict(X), rtol=1e-6)
