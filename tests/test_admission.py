"""Weighted-fair admission and prefix-affine placement primitives:
deficit-round-robin dequeue shares, per-tenant budgets and the
TenantOverBudget shed, drain-rate-scaled Retry-After hints, the
queue.Queue surface contract the worker server relies on, consistent-hash
ring stability/bounded-load/rebuild, and the server-level 429 path.
"""

import queue
import threading
import time
import types
import urllib.error
import urllib.request

import pytest

from mmlspark_tpu.observability import reset_all
from mmlspark_tpu.observability.ledger import reset_ledger
from mmlspark_tpu.observability.slo import reset_tracker
from mmlspark_tpu.reliability import get_injector, reset_breakers
from mmlspark_tpu.serving.admission import (AdmissionQueue,
                                            ConsistentHashRing,
                                            TenantOverBudget)
from mmlspark_tpu.serving.registry import reset_registry


@pytest.fixture(autouse=True)
def _clean_slate():
    reset_registry()
    reset_ledger()
    reset_tracker()
    reset_breakers()
    reset_all()
    get_injector().clear()
    yield
    reset_registry()
    reset_ledger()
    reset_tracker()
    reset_breakers()
    get_injector().clear()
    reset_all()


def _item(tenant="default"):
    return types.SimpleNamespace(tenant=tenant)


def _weights(table):
    return lambda t: table.get(t, 1.0)


# ---------------------------------------------------------------------------
# DRR fairness


def test_drr_shares_track_weights_exactly_under_backlog():
    q = AdmissionQueue(weight_fn=_weights({"a": 3.0, "b": 2.0, "c": 1.0}))
    for _ in range(12):
        for t in ("a", "b", "c"):
            q.put_nowait(_item(t))
    # while every tenant stays backlogged, each DRR round serves quanta
    # proportional to weights: 3 + 2 + 1 per round, so the first 24
    # dequeues split exactly 12 / 8 / 4
    drained = [q.get_nowait().tenant for _ in range(24)]
    counts = {t: drained.count(t) for t in ("a", "b", "c")}
    assert counts == {"a": 12, "b": 8, "c": 4}
    for t, want in (("a", 0.5), ("b", 1 / 3), ("c", 1 / 6)):
        assert abs(counts[t] / 24 - want) / want <= 0.15


def test_drr_preserves_fifo_within_a_tenant():
    q = AdmissionQueue()
    for i in range(5):
        it = _item("solo")
        it.seq = i
        q.put_nowait(it)
    assert [q.get_nowait().seq for _ in range(5)] == list(range(5))


def test_single_tenant_degenerates_to_plain_fifo_bound():
    q = AdmissionQueue(maxsize=4)
    for _ in range(4):
        q.put_nowait(_item())
    # a lone tenant's budget is >= maxsize: the global Full fires, never
    # the tenant budget
    with pytest.raises(queue.Full) as exc:
        q.put_nowait(_item())
    assert not isinstance(exc.value, TenantOverBudget)


def test_idle_tenant_banks_no_deficit():
    q = AdmissionQueue(weight_fn=_weights({"heavy": 5.0}))
    q.put_nowait(_item("heavy"))
    assert q.get_nowait().tenant == "heavy"
    # tenant drained -> retired from the round order; re-arriving later it
    # starts from zero deficit (no credit accrued while idle)
    q.put_nowait(_item("other"))
    q.put_nowait(_item("heavy"))
    assert q.snapshot()["deficits"]["heavy"] == 0.0


# ---------------------------------------------------------------------------
# budgets + shed


def test_tenant_over_budget_sheds_offender_before_global_full():
    q = AdmissionQueue(maxsize=12, burst=2.0)
    q.put_nowait(_item("b"))
    q.put_nowait(_item("c"))
    # three active tenants, equal weights: budget = 12 * (1/3) * 2 = 8
    for _ in range(8):
        q.put_nowait(_item("a"))
    with pytest.raises(TenantOverBudget) as exc:
        q.put_nowait(_item("a"))
    assert exc.value.tenant == "a"
    assert exc.value.depth == 8 and exc.value.budget == 8
    # other tenants still admit — capacity remains for them
    q.put_nowait(_item("b"))
    # and TenantOverBudget IS a queue.Full, so legacy shed paths catch it
    assert isinstance(exc.value, queue.Full)


def test_check_admit_is_advisory_twin_of_put_nowait():
    q = AdmissionQueue(maxsize=2)
    q.check_admit("t")          # room: no raise
    q.put_nowait(_item("t"))
    q.put_nowait(_item("u"))
    with pytest.raises(queue.Full):
        q.check_admit("t")


def test_put_bypasses_budgets_for_replay():
    q = AdmissionQueue(maxsize=2)
    for _ in range(5):
        q.put(_item("replayed"))    # rehydration must never drop
    assert q.qsize() == 5


# ---------------------------------------------------------------------------
# queue.Queue surface


def test_queue_surface_contract():
    q = AdmissionQueue(maxsize=3)
    assert q.empty() and not q.full() and q.qsize() == 0
    q.put_nowait(_item())
    assert not q.empty() and q.qsize() == 1
    with pytest.raises(queue.Empty):
        AdmissionQueue().get_nowait()
    with pytest.raises(queue.Empty):
        AdmissionQueue().get(timeout=0.01)
    assert q.get(timeout=0.1) is not None


def test_get_wakes_on_concurrent_put():
    q = AdmissionQueue()
    got = []

    def consumer():
        got.append(q.get(timeout=5.0))

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    q.put_nowait(_item("late"))
    t.join(timeout=5.0)
    assert got and got[0].tenant == "late"


# ---------------------------------------------------------------------------
# drain rate / Retry-After


def test_retry_after_floor_when_no_drain_observed():
    q = AdmissionQueue()
    assert q.drain_rate() == 0.0
    assert q.suggest_retry_after(floor=2.5) == 2.5


def test_retry_after_scales_with_backlog_and_offender_deficit():
    q = AdmissionQueue(maxsize=10, burst=1.0,
                       weight_fn=_weights({"hog": 1.0, "meek": 1.0}))
    for _ in range(8):
        q.put(_item("hog"))
    q.put(_item("meek"))
    # two dequeues ~20ms apart -> drain rate ~50/s, backlog 7
    q.get_nowait()
    time.sleep(0.02)
    q.get_nowait()
    assert q.drain_rate() > 0
    base = q.suggest_retry_after(floor=0.001)
    assert 0.001 <= base <= AdmissionQueue.MAX_RETRY_AFTER
    # the over-budget tenant's hint is scaled up by depth/budget
    hog = q.suggest_retry_after(floor=0.001, tenant="hog")
    assert hog >= base
    # and the floor always wins from below
    assert q.suggest_retry_after(floor=29.0) >= 29.0
    assert q.suggest_retry_after(floor=60.0) == \
        AdmissionQueue.MAX_RETRY_AFTER


def test_snapshot_is_json_safe_and_live():
    import json
    q = AdmissionQueue(maxsize=7)
    q.put_nowait(_item("x"))
    snap = q.snapshot()
    json.dumps(snap)
    assert snap["size"] == 1 and snap["maxsize"] == 7
    assert snap["tenants"] == {"x": 1}


# ---------------------------------------------------------------------------
# consistent-hash ring


def test_ring_rebuild_reports_membership_change():
    ring = ConsistentHashRing()
    assert ring.rebuild(["w0", "w1"]) is True
    assert ring.rebuild(["w1", "w0"]) is False     # same set, any order
    assert ring.rebuild(["w0", "w1", "w2"]) is True
    assert len(ring) == 3
    assert ring.nodes() == ("w0", "w1", "w2")


def test_ring_route_is_deterministic_and_total():
    ring = ConsistentHashRing()
    ring.rebuild(["w0", "w1", "w2"])
    keys = [f"prefix-{i}" for i in range(64)]
    owners = {k: ring.route(k) for k in keys}
    assert set(owners.values()) <= {"w0", "w1", "w2"}
    assert {k: ring.route(k) for k in keys} == owners
    # virtual nodes spread keys across every member
    assert len(set(owners.values())) == 3


def test_ring_membership_change_moves_only_a_fraction():
    ring = ConsistentHashRing()
    ring.rebuild(["w0", "w1", "w2"])
    keys = [f"prefix-{i}" for i in range(200)]
    before = {k: ring.route(k) for k in keys}
    ring.rebuild(["w0", "w1", "w2", "w3"])
    after = {k: ring.route(k) for k in keys}
    moved = sum(before[k] != after[k] for k in keys)
    # expected ~1/4 of the keyspace; hash(key) % n would move ~3/4
    assert moved / len(keys) < 0.5
    # every moved key landed on some node, none vanished
    assert set(after.values()) <= {"w0", "w1", "w2", "w3"}


def test_ring_bounded_load_walks_past_overloaded_owner():
    ring = ConsistentHashRing(load_factor=1.25)
    ring.rebuild(["w0", "w1", "w2"])
    key = "hot-prefix"
    owner = ring.route(key)
    order = ring.preferred(key)
    assert order[0] == owner and len(order) == 3
    # owner saturated, others idle: bounded load falls back to the next
    # ring position, keeping fallback deterministic too
    load = {owner: 100.0}
    assert ring.route(key, load=load) == order[1]
    # all uniformly overloaded: the affinity owner is still best (its
    # pool holds the prefix pages)
    flat = {n: 100.0 for n in order}
    assert ring.route(key, load=flat) == owner


def test_ring_empty_and_single_node():
    ring = ConsistentHashRing()
    assert ring.route("k") is None
    assert ring.preferred("k") == []
    ring.rebuild(["only"])
    assert ring.route("k") == "only"
    assert ring.preferred("k", n=5) == ["only"]


# ---------------------------------------------------------------------------
# server-level 429 (both transports carry the load-aware Retry-After)


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_server_429_carries_retry_after_at_least_floor(transport):
    from mmlspark_tpu.serving.server import WorkerServer
    server = WorkerServer(max_queue=1, shed_retry_after=2.0,
                          transport=transport)
    try:
        req = urllib.request.Request(
            server.address, data=b"{}",
            headers={"Content-Type": "application/json"})

        parked = {}

        def park():
            try:
                with urllib.request.urlopen(req, timeout=10.0) as r:
                    parked["status"] = r.status
            except urllib.error.HTTPError as e:
                parked["status"] = e.code

        t = threading.Thread(target=park, daemon=True)
        t.start()
        deadline = time.monotonic() + 5.0
        while server._queue.qsize() == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert server._queue.qsize() == 1
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10.0)
        assert exc.value.code == 429
        retry_after = float(exc.value.headers["Retry-After"])
        # no drain observed yet -> the static knob is the floor
        assert retry_after >= 2.0
        cached = server.get_batch(1, timeout=1.0)[0]
        from mmlspark_tpu.io.http.schema import (EntityData,
                                                 HTTPResponseData,
                                                 StatusLineData)
        server.reply(cached.request_id, HTTPResponseData(
            entity=EntityData.from_string("{}"),
            status_line=StatusLineData(status_code=200)))
        t.join(timeout=5.0)
        assert parked.get("status") == 200
    finally:
        server.close()
