"""Test harness: simulate an 8-device TPU topology on CPU.

Mirrors the reference's strategy of testing distributed behavior in-process on
a local-mode SparkSession (``core/src/test/.../base/SparkSessionFactory.scala``);
here an 8-device virtual CPU mesh stands in for a TPU slice
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``).
"""

import os

# The session env pins JAX_PLATFORMS to a real-TPU tunnel platform, and
# setting JAX_PLATFORMS=cpu via env hangs platform init under it — so drop the
# var entirely and select cpu through jax.config before any backend spins up.
os.environ.pop("JAX_PLATFORMS", None)

# jax.config does NOT propagate to subprocesses: a test-spawned child that
# imports jax does default plugin discovery, and with the TPU plugin's
# sitecustomize dir on PYTHONPATH it will CLAIM THE REAL CHIP (the claim is
# exclusive, and a hung/killed claimant wedges it for everyone — BASELINE.md
# postmortem). Strip plugin dirs from the inherited PYTHONPATH so every child
# of every test is CPU-only by construction.
_pp = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
       if p and "axon" not in p]
if _pp:
    os.environ["PYTHONPATH"] = os.pathsep.join(_pp)
else:
    os.environ.pop("PYTHONPATH", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def tmp_save(tmp_path):
    return str(tmp_path / "stage_save")
