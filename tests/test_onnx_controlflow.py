"""ONNX control-flow (If/Loop/Scan) and recurrent (LSTM/GRU) conversion.

These lower to XLA-native structured primitives (lax.cond / lax.scan)
instead of the interpreter loops an ORT-style runtime uses — the remaining
op families a torch/keras exporter emits that the importer lacked
(parity target: ONNXModel type coverage, ``ONNXModel.scala:195-245``).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import mmlspark_tpu.onnx as O


def _convert(graph):
    return O.convert_model(O.make_model(graph))


class TestIf:
    def _model(self):
        then_g = O.make_graph(
            [O.make_node("Mul", ["x", "two"], ["y"])], "then",
            inputs=[], outputs=[O.make_tensor_value_info("y", np.float32,
                                                         [3])],
            initializers={"two": np.float32(2.0).reshape(())})
        else_g = O.make_graph(
            [O.make_node("Neg", ["x"], ["y"])], "else",
            inputs=[], outputs=[O.make_tensor_value_info("y", np.float32,
                                                         [3])])
        g = O.make_graph(
            [O.make_node("If", ["cond"], ["out"], then_branch=then_g,
                         else_branch=else_g)],
            "ifg",
            inputs=[O.make_tensor_value_info("cond", np.bool_, []),
                    O.make_tensor_value_info("x", np.float32, [3])],
            outputs=[O.make_tensor_value_info("out", np.float32, [3])])
        return _convert(g)

    def test_static_predicate(self):
        cm = self._model()
        x = np.array([1.0, 2.0, 3.0], np.float32)
        out = cm(cm.params, {"cond": np.asarray(True), "x": x})
        np.testing.assert_allclose(np.asarray(out["out"]), x * 2)
        out = cm(cm.params, {"cond": np.asarray(False), "x": x})
        np.testing.assert_allclose(np.asarray(out["out"]), -x)

    def test_traced_predicate_under_jit(self):
        import jax
        cm = self._model()
        x = np.array([1.0, 2.0, 3.0], np.float32)
        jitted = jax.jit(lambda c, x: cm(cm.params, {"cond": c, "x": x}))
        np.testing.assert_allclose(
            np.asarray(jitted(jnp.asarray(True), x)["out"]), x * 2)
        np.testing.assert_allclose(
            np.asarray(jitted(jnp.asarray(False), x)["out"]), -x)


class TestLoop:
    def test_static_trip_count_with_scan_output(self):
        # body: (i, cond, acc) -> (cond, acc + x, acc + x)
        body = O.make_graph(
            [O.make_node("Add", ["acc_in", "x"], ["acc_out"]),
             O.make_node("Identity", ["cond_in"], ["cond_out"]),
             O.make_node("Identity", ["acc_out"], ["scan_out"])],
            "body",
            inputs=[O.make_tensor_value_info("iter", np.int64, []),
                    O.make_tensor_value_info("cond_in", np.bool_, []),
                    O.make_tensor_value_info("acc_in", np.float32, [2])],
            outputs=[O.make_tensor_value_info("cond_out", np.bool_, []),
                     O.make_tensor_value_info("acc_out", np.float32, [2]),
                     O.make_tensor_value_info("scan_out", np.float32, [2])])
        g = O.make_graph(
            [O.make_node("Loop", ["M", "", "acc0"], ["acc_final", "trace"],
                         body=body)],
            "loopg",
            inputs=[O.make_tensor_value_info("acc0", np.float32, [2]),
                    O.make_tensor_value_info("x", np.float32, [2])],
            outputs=[O.make_tensor_value_info("acc_final", np.float32, [2]),
                     O.make_tensor_value_info("trace", np.float32, [4, 2])],
            initializers={"M": np.int64(4).reshape(())})
        cm = _convert(g)
        x = np.array([1.0, 10.0], np.float32)
        out = cm(cm.params, {"acc0": np.zeros(2, np.float32), "x": x})
        np.testing.assert_allclose(np.asarray(out["acc_final"]), 4 * x)
        np.testing.assert_allclose(np.asarray(out["trace"]),
                                   np.stack([x, 2 * x, 3 * x, 4 * x]))

    def test_dynamic_trip_count_rejected(self):
        body = O.make_graph(
            [O.make_node("Identity", ["cond_in"], ["cond_out"]),
             O.make_node("Identity", ["v_in"], ["v_out"])],
            "body",
            inputs=[O.make_tensor_value_info("iter", np.int64, []),
                    O.make_tensor_value_info("cond_in", np.bool_, []),
                    O.make_tensor_value_info("v_in", np.float32, [1])],
            outputs=[O.make_tensor_value_info("cond_out", np.bool_, []),
                     O.make_tensor_value_info("v_out", np.float32, [1])])
        g = O.make_graph(
            [O.make_node("Loop", ["M", "", "v0"], ["v_final"], body=body)],
            "loopg",
            inputs=[O.make_tensor_value_info("M", np.int64, []),
                    O.make_tensor_value_info("v0", np.float32, [1])],
            outputs=[O.make_tensor_value_info("v_final", np.float32, [1])])
        cm = _convert(g)
        with pytest.raises(NotImplementedError, match="static trip count"):
            import jax
            jax.jit(lambda m, v: cm(cm.params, {"M": m, "v0": v}))(
                jnp.asarray(3, jnp.int32), jnp.zeros(1, jnp.float32))


class TestScan:
    def test_cumulative_sum_scan(self):
        body = O.make_graph(
            [O.make_node("Add", ["s_in", "x_t"], ["s_out"]),
             O.make_node("Identity", ["s_out"], ["y_t"])],
            "body",
            inputs=[O.make_tensor_value_info("s_in", np.float32, [3]),
                    O.make_tensor_value_info("x_t", np.float32, [3])],
            outputs=[O.make_tensor_value_info("s_out", np.float32, [3]),
                     O.make_tensor_value_info("y_t", np.float32, [3])])
        g = O.make_graph(
            [O.make_node("Scan", ["s0", "xs"], ["s_final", "ys"],
                         body=body, num_scan_inputs=1)],
            "scang",
            inputs=[O.make_tensor_value_info("s0", np.float32, [3]),
                    O.make_tensor_value_info("xs", np.float32, [5, 3])],
            outputs=[O.make_tensor_value_info("s_final", np.float32, [3]),
                     O.make_tensor_value_info("ys", np.float32, [5, 3])])
        cm = _convert(g)
        rng = np.random.default_rng(0)
        xs = rng.normal(0, 1, (5, 3)).astype(np.float32)
        out = cm(cm.params, {"s0": np.zeros(3, np.float32), "xs": xs})
        np.testing.assert_allclose(np.asarray(out["s_final"]),
                                   xs.sum(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(out["ys"]),
                                   np.cumsum(xs, axis=0), rtol=1e-5)


def _np_lstm(X, W, R, B, H):
    """Reference forward LSTM, ONNX iofc gate order."""
    T, Bt, _ = X.shape
    h = np.zeros((Bt, H), np.float32)
    c = np.zeros((Bt, H), np.float32)
    sig = lambda v: 1 / (1 + np.exp(-v))
    ys = []
    for t in range(T):
        gates = X[t] @ W.T + h @ R.T + B[:4 * H] + B[4 * H:]
        i, o, f, g = np.split(gates, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        ys.append(h)
    return np.stack(ys), h, c


class TestRecurrent:
    def _lstm_model(self, T=6, Bt=2, I=4, H=3, seed=0):
        rng = np.random.default_rng(seed)
        W = rng.normal(0, 0.4, (1, 4 * H, I)).astype(np.float32)
        R = rng.normal(0, 0.4, (1, 4 * H, H)).astype(np.float32)
        B = rng.normal(0, 0.1, (1, 8 * H)).astype(np.float32)
        g = O.make_graph(
            [O.make_node("LSTM", ["X", "W", "R", "B"], ["Y", "Y_h", "Y_c"],
                         hidden_size=H)],
            "lstm",
            inputs=[O.make_tensor_value_info("X", np.float32, [T, Bt, I])],
            outputs=[O.make_tensor_value_info("Y", np.float32,
                                              [T, 1, Bt, H]),
                     O.make_tensor_value_info("Y_h", np.float32, [1, Bt, H]),
                     O.make_tensor_value_info("Y_c", np.float32,
                                              [1, Bt, H])],
            initializers={"W": W, "R": R, "B": B})
        return _convert(g), (W, R, B, H, T, Bt, I)

    def test_lstm_matches_reference(self):
        cm, (W, R, B, H, T, Bt, I) = self._lstm_model()
        rng = np.random.default_rng(1)
        X = rng.normal(0, 1, (T, Bt, I)).astype(np.float32)
        out = cm(cm.params, {"X": X})
        ys, h, c = _np_lstm(X, W[0], R[0], B[0], H)
        np.testing.assert_allclose(np.asarray(out["Y"])[:, 0], ys,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(out["Y_h"])[0], h,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(out["Y_c"])[0], c,
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_bidirectional_shapes(self):
        T, Bt, I, H = 5, 2, 4, 3
        rng = np.random.default_rng(2)
        W = rng.normal(0, 0.4, (2, 4 * H, I)).astype(np.float32)
        R = rng.normal(0, 0.4, (2, 4 * H, H)).astype(np.float32)
        g = O.make_graph(
            [O.make_node("LSTM", ["X", "W", "R"], ["Y"],
                         hidden_size=H, direction="bidirectional")],
            "lstm",
            inputs=[O.make_tensor_value_info("X", np.float32, [T, Bt, I])],
            outputs=[O.make_tensor_value_info("Y", np.float32,
                                              [T, 2, Bt, H])],
            initializers={"W": W, "R": R})
        cm = _convert(g)
        X = rng.normal(0, 1, (T, Bt, I)).astype(np.float32)
        out = cm(cm.params, {"X": X})
        assert np.asarray(out["Y"]).shape == (T, 2, Bt, H)
        # reverse direction at t=0 must differ from forward at t=0
        y = np.asarray(out["Y"])
        assert not np.allclose(y[0, 0], y[0, 1])

    def test_gru_runs_and_gates_bound(self):
        T, Bt, I, H = 4, 2, 3, 5
        rng = np.random.default_rng(3)
        W = rng.normal(0, 0.4, (1, 3 * H, I)).astype(np.float32)
        R = rng.normal(0, 0.4, (1, 3 * H, H)).astype(np.float32)
        B = rng.normal(0, 0.1, (1, 6 * H)).astype(np.float32)
        g = O.make_graph(
            [O.make_node("GRU", ["X", "W", "R", "B"], ["Y", "Y_h"],
                         hidden_size=H, linear_before_reset=1)],
            "gru",
            inputs=[O.make_tensor_value_info("X", np.float32, [T, Bt, I])],
            outputs=[O.make_tensor_value_info("Y", np.float32,
                                              [T, 1, Bt, H]),
                     O.make_tensor_value_info("Y_h", np.float32,
                                              [1, Bt, H])],
            initializers={"W": W, "R": R, "B": B})
        cm = _convert(g)
        X = rng.normal(0, 1, (T, Bt, I)).astype(np.float32)
        out = cm(cm.params, {"X": X})
        y = np.asarray(out["Y"])
        assert y.shape == (T, 1, Bt, H)
        assert np.abs(y).max() <= 1.0 + 1e-5  # tanh-bounded state
        np.testing.assert_allclose(np.asarray(out["Y_h"])[0], y[-1, 0],
                                   rtol=1e-6)


def _np_gru_lbr0(X, W, R, B, H):
    """Reference GRU, ONNX zrh order, linear_before_reset=0 (default)."""
    T, Bt, _ = X.shape
    h = np.zeros((Bt, H), np.float32)
    sig = lambda v: 1 / (1 + np.exp(-v))
    wb, rb = B[:3 * H], B[3 * H:]
    for t in range(T):
        gx = X[t] @ W.T + wb
        gh = h @ R.T + rb
        z = sig(gx[:, :H] + gh[:, :H])
        r = sig(gx[:, H:2 * H] + gh[:, H:2 * H])
        n = np.tanh(gx[:, 2 * H:] + (r * h) @ R[2 * H:].T + rb[2 * H:])
        h = (1 - z) * n + z * h
    return h


class TestRecurrentSemantics:
    def test_gru_linear_before_reset_default_matches_reference(self):
        T, Bt, I, H = 5, 2, 3, 4
        rng = np.random.default_rng(9)
        W = rng.normal(0, 0.4, (1, 3 * H, I)).astype(np.float32)
        R = rng.normal(0, 0.4, (1, 3 * H, H)).astype(np.float32)
        B = rng.normal(0, 0.1, (1, 6 * H)).astype(np.float32)
        g = O.make_graph(
            [O.make_node("GRU", ["X", "W", "R", "B"], ["Y", "Y_h"],
                         hidden_size=H)],  # lbr defaults to 0
            "gru",
            inputs=[O.make_tensor_value_info("X", np.float32, [T, Bt, I])],
            outputs=[O.make_tensor_value_info("Y", np.float32,
                                              [T, 1, Bt, H]),
                     O.make_tensor_value_info("Y_h", np.float32,
                                              [1, Bt, H])],
            initializers={"W": W, "R": R, "B": B})
        cm = _convert(g)
        X = rng.normal(0, 1, (T, Bt, I)).astype(np.float32)
        out = cm(cm.params, {"X": X})
        np.testing.assert_allclose(np.asarray(out["Y_h"])[0],
                                   _np_gru_lbr0(X, W[0], R[0], B[0], H),
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_nondefault_activations_rejected(self):
        g = O.make_graph(
            [O.make_node("LSTM", ["X", "W", "R"], ["Y"], hidden_size=2,
                         activations=["HardSigmoid", "Tanh", "Tanh"])],
            "lstm",
            inputs=[O.make_tensor_value_info("X", np.float32, [3, 1, 2])],
            outputs=[O.make_tensor_value_info("Y", np.float32,
                                              [3, 1, 1, 2])],
            initializers={"W": np.zeros((1, 8, 2), np.float32),
                          "R": np.zeros((1, 8, 2), np.float32)})
        cm = _convert(g)
        with pytest.raises(NotImplementedError, match="activations"):
            cm(cm.params, {"X": np.zeros((3, 1, 2), np.float32)})


class TestLoopSemantics:
    def _counting_loop(self, M_val, with_break_at=None):
        """Loop body: v += 1 each iteration; optionally cond_out goes False
        once v reaches with_break_at."""
        nodes = [O.make_node("Add", ["v_in", "one"], ["v_out"])]
        if with_break_at is None:
            nodes.append(O.make_node("Identity", ["cond_in"], ["cond_out"]))
        else:
            nodes.append(O.make_node("Less", ["v_out", "limit"],
                                     ["cond_out"]))
        body = O.make_graph(
            nodes, "body",
            inputs=[O.make_tensor_value_info("iter", np.int64, []),
                    O.make_tensor_value_info("cond_in", np.bool_, []),
                    O.make_tensor_value_info("v_in", np.float32, [])],
            outputs=[O.make_tensor_value_info("cond_out", np.bool_, []),
                     O.make_tensor_value_info("v_out", np.float32, [])],
            initializers={"one": np.float32(1.0).reshape(()),
                          **({"limit": np.float32(with_break_at)
                              .reshape(())} if with_break_at else {})})
        g = O.make_graph(
            [O.make_node("Loop", ["M", "cond0", "v0"], ["v_final"],
                         body=body)],
            "loopg",
            inputs=[O.make_tensor_value_info("cond0", np.bool_, []),
                    O.make_tensor_value_info("v0", np.float32, [])],
            outputs=[O.make_tensor_value_info("v_final", np.float32, [])],
            initializers={"M": np.int64(M_val).reshape(())})
        return _convert(g)

    def test_initial_cond_false_runs_zero_iterations(self):
        cm = self._counting_loop(10)
        out = cm(cm.params, {"cond0": np.asarray(False),
                             "v0": np.float32(5.0)})
        assert float(np.asarray(out["v_final"])) == 5.0

    def test_body_cond_terminates_early(self):
        # v starts at 0, breaks when v >= 3 → final v == 3, not 10
        cm = self._counting_loop(10, with_break_at=3.0)
        out = cm(cm.params, {"cond0": np.asarray(True),
                             "v0": np.float32(0.0)})
        assert float(np.asarray(out["v_final"])) == 3.0
