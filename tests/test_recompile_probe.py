"""Micro-bench recompile probe (tier-1-safe, CPU).

The steady-state contract ``bench.py`` relies on, asserted as a fast test:
after the first batch of a padding bucket is served, further same-bucket
batches must be pure cache hits — zero XLA recompiles, no compile-stage
counter growth. A regression here (a jit signature that keys on batch
identity, a cache invalidated between calls, a pad size that drifts) would
silently turn every production batch into a multi-second compile.
"""

import numpy as np

import mmlspark_tpu.onnx as O
from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.models.onnx_model import ONNXModel
from mmlspark_tpu.ops.compile_cache import jit_cache_size


def _model(din=8, dout=3):
    rng = np.random.default_rng(0)
    w = rng.normal(0, 0.5, (din, dout)).astype(np.float32)
    b = np.zeros(dout, dtype=np.float32)
    graph = O.make_graph(
        [O.make_node("MatMul", ["x", "w"], ["h"]),
         O.make_node("Add", ["h", "b"], ["y"])],
        "probe",
        inputs=[O.make_tensor_value_info("x", np.float32, ["N", din])],
        outputs=[O.make_tensor_value_info("y", np.float32, ["N", dout])],
        initializers={"w": w, "b": b})
    return ONNXModel(O.make_model(graph), feed_dict={"x": "feats"},
                     fetch_dict={"y": "y"}, mini_batch_size=8,
                     pin_devices=False), (w, b)


def _df(n, din=8, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, din)).astype(np.float32)
    return DataFrame({"feats": [X[i] for i in range(n)]}), X


def test_second_same_bucket_batch_is_compile_free():
    m, (w, b) = _model()
    df1, X1 = _df(8, seed=1)
    df2, X2 = _df(8, seed=2)

    out1 = m.transform(df1)            # first batch pays the compile
    jitted = m._ensure_jitted()
    cache_after_first = jit_cache_size(jitted)
    assert cache_after_first is not None and cache_after_first >= 1
    compile_calls_after_first = \
        m.stage_counters.snapshot().get("compile", {}).get("calls", 0)

    out2 = m.transform(df2)            # same bucket → must be a cache hit
    snap = m.stage_counters.snapshot()
    assert jit_cache_size(jitted) == cache_after_first
    assert snap.get("compile", {}).get("calls", 0) \
        == compile_calls_after_first
    assert snap["dispatch"]["calls"] >= 1

    # and both batches computed the right thing
    np.testing.assert_allclose(np.stack(list(out1["y"])), X1 @ w + b,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.stack(list(out2["y"])), X2 @ w + b,
                               rtol=1e-4, atol=1e-4)


def test_warmed_model_first_batch_is_compile_free():
    m, _ = _model()
    m.warm_up(batch_sizes=[8])
    jitted = m._ensure_jitted()
    size = jit_cache_size(jitted)
    df, _ = _df(8)
    m.transform(df)
    assert jit_cache_size(jitted) == size
