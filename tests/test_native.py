"""Native fastpath extension: correctness vs pure-Python fallbacks, and
the fallback path itself (MMLSPARK_TPU_NO_NATIVE=1)."""

import numpy as np
import pytest

from mmlspark_tpu import native
from mmlspark_tpu.vw.murmur import _murmur3_32_py

VECTORS = [b"", b"a", b"hello", b"hello, world",
           b"The quick brown fox jumps over the lazy dog", b"\x00\xff" * 7]


def test_native_builds():
    assert native.available(), "g++ toolchain present; extension must build"


class TestBinColumns:
    """Native quantile binning == searchsorted(bounds, x, 'left') + 1 with
    NaN -> 0 (the GBDT dataset-construction hot loop, LightGBM's
    LGBM_DatasetCreateFromMat role)."""

    @staticmethod
    def _ref(X, bounds_list):
        n, f = X.shape
        out = np.zeros((n, f), np.int64)
        for j in range(f):
            col = X[:, j]
            b = np.searchsorted(bounds_list[j], col, side="left") + 1
            out[:, j] = np.where(np.isnan(col), 0, b)
        return out

    @staticmethod
    def _table(bounds_list):
        lengths = np.array([len(b) for b in bounds_list], np.int64)
        table = np.full((len(bounds_list), lengths.max()), np.inf)
        for j, b in enumerate(bounds_list):
            table[j, :len(b)] = b
        return table, lengths

    @pytest.mark.parametrize("gen", ["gauss", "cauchy", "const", "inf"])
    def test_matches_searchsorted(self, gen):
        # fixed seeds: hash(str) varies per process (PYTHONHASHSEED), which
        # would make a boundary failure unreproducible
        rng = np.random.default_rng(
            {"gauss": 11, "cauchy": 22, "const": 33, "inf": 44}[gen])
        n, f = 40_000, 5
        X = {"gauss": lambda: rng.normal(0, 1, (n, f)),
             "cauchy": lambda: rng.standard_cauchy((n, f)),
             "const": lambda: np.full((n, f), 2.5),
             "inf": lambda: np.where(rng.random((n, f)) < 0.05,
                                     np.inf * rng.choice([-1, 1], (n, f)),
                                     rng.normal(0, 1, (n, f)))}[gen]() \
            .astype(np.float32)
        X[rng.random((n, f)) < 0.03] = np.nan
        bounds = []
        for j in range(f):
            col = X[:, j]
            col = col[np.isfinite(col)]
            qs = (np.unique(np.quantile(col, np.linspace(0, 1, 100)))
                  if col.size else np.array([]))
            bounds.append(np.append(qs, np.inf))
        table, lengths = self._table(bounds)
        got = native.bin_columns(X, table, lengths, False)
        assert got.dtype == np.uint8
        assert np.array_equal(got.astype(np.int64), self._ref(X, bounds))

    def test_uint16_and_float64(self):
        rng = np.random.default_rng(7)
        X = rng.normal(0, 1, (5_000, 3)).astype(np.float64)
        bounds = [np.append(np.sort(rng.normal(0, 1, 500)), np.inf)
                  for _ in range(3)]
        table, lengths = self._table(bounds)
        got = native.bin_columns(X, table, lengths, True)
        assert got.dtype == np.uint16
        assert np.array_equal(got.astype(np.int64), self._ref(X, bounds))

    def test_fallback_matches_native(self, monkeypatch):
        rng = np.random.default_rng(9)
        X = rng.normal(0, 1, (2_000, 4)).astype(np.float32)
        bounds = [np.append(np.sort(rng.normal(0, 1, 30)), np.inf)
                  for _ in range(4)]
        table, lengths = self._table(bounds)
        a = native.bin_columns(X, table, lengths, False)
        monkeypatch.setattr(native, "_impl", False)
        b = native.bin_columns(X, table, lengths, False)
        monkeypatch.setattr(native, "_impl", None)
        assert np.array_equal(a, b)


@pytest.mark.parametrize("seed", [0, 1, 0xDEADBEEF])
def test_murmur3_matches_reference(seed):
    for v in VECTORS:
        assert native.murmur3(v, seed) == _murmur3_32_py(v, seed)


def test_murmur3_batch():
    got = native.murmur3_batch(VECTORS, 7, 0xFFFFF)
    want = [_murmur3_32_py(v, 7) & 0xFFFFF for v in VECTORS]
    assert got.dtype == np.uint32
    assert list(got) == want


def test_pad_sparse_matches_fallback():
    rng = np.random.default_rng(0)
    rows = []
    for _ in range(20):
        k = int(rng.integers(0, 6))
        rows.append((rng.integers(0, 1000, k).astype(np.uint32),
                     rng.random(k).astype(np.float32)))
    ni, nv = native.pad_sparse(rows, 6)
    impl = native._impl
    try:
        native._impl = False
        fi, fv = native.pad_sparse(rows, 6)
    finally:
        native._impl = impl
    np.testing.assert_array_equal(ni, fi)
    np.testing.assert_array_equal(nv, fv)


def test_stack_rows_pads_and_truncates():
    out = native.stack_rows([np.arange(3.0), np.arange(6.0)], 4)
    assert out.shape == (2, 4)
    assert out[0, 3] == 0.0 and out[1, 3] == 3.0


def test_featurizer_uses_batch_path_consistently():
    """String columns (batch-hashed) must produce identical features to the
    per-value path (hash compatibility native vs python)."""
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.vw import VowpalWabbitFeaturizer
    df = DataFrame({"t": np.array(["a b", "c", ""], dtype=object)})
    f = VowpalWabbitFeaturizer(input_cols=["t"], string_split_cols=["t"],
                               num_bits=14)
    out1 = f.transform(df)["features"]
    impl = native._impl
    try:
        native._impl = False
        import mmlspark_tpu.vw.murmur as mm
        mm._native_fn = False
        out2 = f.transform(df)["features"]
    finally:
        native._impl = impl
        import mmlspark_tpu.vw.murmur as mm
        mm._native_fn = None
    for (i1, v1), (i2, v2) in zip(out1, out2):
        np.testing.assert_array_equal(np.sort(i1), np.sort(i2))


def test_pad_sparse_malformed_row_clamps_both_paths():
    rows = [(np.array([1, 2, 3], np.uint32), np.array([0.5, 0.25], np.float32))]
    ni, nv = native.pad_sparse(rows, 3)
    impl = native._impl
    try:
        native._impl = False
        fi, fv = native.pad_sparse(rows, 3)
    finally:
        native._impl = impl
    np.testing.assert_array_equal(ni, fi)
    np.testing.assert_array_equal(nv, fv)
    assert nv[0, 2] == 0.0          # never reads past the values buffer


class TestParseLibsvm:
    DATA = (b"1 1:0.5 3:2.0 # trailing comment\n"
            b"\n"
            b"-1 2:1.5\n"
            b"0 qid:7 1:1.0 4:-2.5\n"
            b"# full-line comment\n"
            b"2.5\n")                       # label-only row (all-zero features)

    def _check(self, parse):
        labels, qids, indptr, indices, values = parse(self.DATA)
        np.testing.assert_allclose(labels, [1, -1, 0, 2.5])
        np.testing.assert_array_equal(qids, [-1, -1, 7, -1])
        np.testing.assert_array_equal(indptr, [0, 2, 3, 5, 5])
        np.testing.assert_array_equal(indices, [1, 3, 2, 1, 4])
        np.testing.assert_allclose(values, [0.5, 2.0, 1.5, 1.0, -2.5])

    def test_python_fallback(self, monkeypatch):
        import mmlspark_tpu.native as nat
        monkeypatch.setattr(nat, "_impl", False)
        self._check(nat.parse_libsvm)

    def test_native_if_available(self):
        import mmlspark_tpu.native as nat
        if not nat.available():
            pytest.skip("no native toolchain")
        self._check(nat.parse_libsvm)

    def test_native_matches_python(self):
        import mmlspark_tpu.native as nat
        if not nat.available():
            pytest.skip("no native toolchain")
        rng = np.random.default_rng(0)
        lines = []
        for i in range(200):
            feats = sorted(rng.choice(50, size=rng.integers(0, 8),
                                      replace=False))
            toks = [f"{rng.normal():.6f}"]
            if i % 3 == 0:
                toks.append(f"qid:{i // 10}")
            toks += [f"{f + 1}:{rng.normal():.6f}" for f in feats]
            lines.append(" ".join(toks))
        data = ("\n".join(lines)).encode()
        native = nat._load().parse_libsvm(data)
        prev, nat._impl = nat._impl, False
        try:
            pure = nat.parse_libsvm(data)
        finally:
            nat._impl = prev
        for a, b in zip(native, pure):
            np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_bad_token_raises(self):
        import mmlspark_tpu.native as nat
        with pytest.raises(ValueError):
            nat.parse_libsvm(b"1 nocolon\n")


class TestReadLibsvm:
    def test_roundtrip_to_gbdt(self, tmp_path):
        from mmlspark_tpu.io import read_libsvm
        from mmlspark_tpu.models.gbdt import LightGBMClassifier

        rng = np.random.default_rng(1)
        X = rng.normal(0, 1, (200, 6)).astype(np.float32)
        y = (X[:, 0] > 0).astype(int)
        p = tmp_path / "d.svm"
        with open(p, "w") as f:
            for i in range(len(X)):
                feats = " ".join(f"{j + 1}:{X[i, j]:.6f}" for j in range(6))
                f.write(f"{y[i]} {feats}\n")
        df = read_libsvm(str(p))
        assert df["features"][0].shape == (6,)
        np.testing.assert_allclose(
            np.stack(list(df["features"])), X, rtol=1e-5, atol=1e-6)
        m = LightGBMClassifier(num_iterations=10,
                               min_data_in_leaf=5).fit(df)
        acc = (np.asarray(m.transform(df)["prediction"])
               == np.asarray(df["label"])).mean()
        assert acc > 0.9

    def test_qid_becomes_group(self, tmp_path):
        from mmlspark_tpu.io import read_libsvm
        p = tmp_path / "r.svm"
        p.write_text("1 qid:1 1:0.5\n0 qid:1 1:0.1\n1 qid:2 1:0.9\n")
        df = read_libsvm(str(p))
        np.testing.assert_array_equal(df["group"], [1, 1, 2])

    def test_zero_based_autodetect(self, tmp_path):
        from mmlspark_tpu.io import read_libsvm
        p = tmp_path / "z.svm"
        p.write_text("1 0:2.0 2:3.0\n0 1:1.0\n")
        df = read_libsvm(str(p))
        np.testing.assert_allclose(df["features"][0], [2.0, 0.0, 3.0])


class TestLibsvmReviewRegressions:
    def test_out_of_range_index_errors_not_wraps(self):
        import mmlspark_tpu.native as nat
        if not nat.available():
            pytest.skip("no native toolchain")
        with pytest.raises((ValueError, OverflowError)):
            nat._load().parse_libsvm(b"1 4294967297:2.0\n")

    def test_partial_qid_coverage_rejected(self, tmp_path):
        from mmlspark_tpu.io import read_libsvm
        p = tmp_path / "p.svm"
        p.write_text("1 1:0.5\n0 qid:1 1:0.1\n")
        with pytest.raises(ValueError, match="lack qid"):
            read_libsvm(str(p))


def test_libsvm_truncated_qid_errors_native():
    import mmlspark_tpu.native as nat
    if not nat.available():
        pytest.skip("no native toolchain")
    with pytest.raises(ValueError):
        nat._load().parse_libsvm(b"1 qid:\n5 1:2.0\n")


def test_libsvm_negative_index_rejected_both_parsers():
    import mmlspark_tpu.native as nat
    with pytest.raises(ValueError):
        nat.parse_libsvm(b"1 -1:2.0\n")
    prev, nat._impl = nat._impl, False
    try:
        with pytest.raises(ValueError):
            nat.parse_libsvm(b"1 -1:2.0\n")
    finally:
        nat._impl = prev
