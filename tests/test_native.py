"""Native fastpath extension: correctness vs pure-Python fallbacks, and
the fallback path itself (MMLSPARK_TPU_NO_NATIVE=1)."""

import numpy as np
import pytest

from mmlspark_tpu import native
from mmlspark_tpu.vw.murmur import _murmur3_32_py

VECTORS = [b"", b"a", b"hello", b"hello, world",
           b"The quick brown fox jumps over the lazy dog", b"\x00\xff" * 7]


def test_native_builds():
    assert native.available(), "g++ toolchain present; extension must build"


@pytest.mark.parametrize("seed", [0, 1, 0xDEADBEEF])
def test_murmur3_matches_reference(seed):
    for v in VECTORS:
        assert native.murmur3(v, seed) == _murmur3_32_py(v, seed)


def test_murmur3_batch():
    got = native.murmur3_batch(VECTORS, 7, 0xFFFFF)
    want = [_murmur3_32_py(v, 7) & 0xFFFFF for v in VECTORS]
    assert got.dtype == np.uint32
    assert list(got) == want


def test_pad_sparse_matches_fallback():
    rng = np.random.default_rng(0)
    rows = []
    for _ in range(20):
        k = int(rng.integers(0, 6))
        rows.append((rng.integers(0, 1000, k).astype(np.uint32),
                     rng.random(k).astype(np.float32)))
    ni, nv = native.pad_sparse(rows, 6)
    impl = native._impl
    try:
        native._impl = False
        fi, fv = native.pad_sparse(rows, 6)
    finally:
        native._impl = impl
    np.testing.assert_array_equal(ni, fi)
    np.testing.assert_array_equal(nv, fv)


def test_stack_rows_pads_and_truncates():
    out = native.stack_rows([np.arange(3.0), np.arange(6.0)], 4)
    assert out.shape == (2, 4)
    assert out[0, 3] == 0.0 and out[1, 3] == 3.0


def test_featurizer_uses_batch_path_consistently():
    """String columns (batch-hashed) must produce identical features to the
    per-value path (hash compatibility native vs python)."""
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.vw import VowpalWabbitFeaturizer
    df = DataFrame({"t": np.array(["a b", "c", ""], dtype=object)})
    f = VowpalWabbitFeaturizer(input_cols=["t"], string_split_cols=["t"],
                               num_bits=14)
    out1 = f.transform(df)["features"]
    impl = native._impl
    try:
        native._impl = False
        import mmlspark_tpu.vw.murmur as mm
        mm._native_fn = False
        out2 = f.transform(df)["features"]
    finally:
        native._impl = impl
        import mmlspark_tpu.vw.murmur as mm
        mm._native_fn = None
    for (i1, v1), (i2, v2) in zip(out1, out2):
        np.testing.assert_array_equal(np.sort(i1), np.sort(i2))


def test_pad_sparse_malformed_row_clamps_both_paths():
    rows = [(np.array([1, 2, 3], np.uint32), np.array([0.5, 0.25], np.float32))]
    ni, nv = native.pad_sparse(rows, 3)
    impl = native._impl
    try:
        native._impl = False
        fi, fv = native.pad_sparse(rows, 3)
    finally:
        native._impl = impl
    np.testing.assert_array_equal(ni, fi)
    np.testing.assert_array_equal(nv, fv)
    assert nv[0, 2] == 0.0          # never reads past the values buffer
