"""ai.onnx.ml domain: tree ensembles, linear models, preprocessing — the
sklearn/LightGBM interchange surface, plus the booster→ONNX exporter.

Parity anchor: the reference's flagship ONNX demo converts a trained
LightGBM model to ONNX (TreeEnsembleClassifier) and serves it via
ONNXModel (``website/docs/features/onnx/about.md``). The round-trip tests
here close the same loop natively: train GBDT → export ONNX → run through
the converter / ONNXModel → predictions match the booster."""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.models.gbdt import LightGBMClassifier, LightGBMRegressor
from mmlspark_tpu.models.gbdt.onnx_export import booster_to_onnx
from mmlspark_tpu.models.onnx_model import ONNXModel
from mmlspark_tpu.onnx.builder import (make_graph, make_model, make_node,
                                       make_tensor_value_info)
from mmlspark_tpu.onnx.convert import convert_model


def _df(X, y=None):
    col = np.empty(len(X), dtype=object)
    for i, r in enumerate(X):
        col[i] = r.astype(np.float32)
    d = {"features": col}
    if y is not None:
        d["label"] = y.astype(np.float64)
    return DataFrame(d)


class TestBoosterRoundTrip:
    def test_binary_classifier(self):
        rng = np.random.default_rng(0)
        X = rng.normal(0, 1, (300, 6))
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.int64)
        m = LightGBMClassifier(num_iterations=12, num_leaves=8,
                               learning_rate=0.2).fit(_df(X, y))
        booster = m.booster
        cm = convert_model(booster_to_onnx(booster))
        Xq = rng.normal(0, 1, (64, 6)).astype(np.float32)
        out = cm(cm.params, {"features": Xq})
        probs = np.asarray(out["probabilities"])
        want_p1 = booster.predict(Xq)          # sigmoid(raw) for binary
        np.testing.assert_allclose(probs[:, 1], want_p1, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_array_equal(np.asarray(out["label"]),
                                      (want_p1 > 0.5).astype(np.int64))

    def test_multiclass_classifier(self):
        rng = np.random.default_rng(1)
        X = rng.normal(0, 1, (300, 5))
        y = np.argmax(X[:, :3] + 0.3 * rng.normal(size=(300, 3)), axis=1)
        m = LightGBMClassifier(num_iterations=8, num_leaves=6,
                               learning_rate=0.3).fit(_df(X, y))
        booster = m.booster
        cm = convert_model(booster_to_onnx(booster))
        Xq = rng.normal(0, 1, (50, 5)).astype(np.float32)
        probs = np.asarray(cm(cm.params, {"features": Xq})["probabilities"])
        want = booster.predict(Xq)             # softmax rows
        np.testing.assert_allclose(probs, want, rtol=1e-4, atol=1e-5)

    def test_regressor(self):
        rng = np.random.default_rng(2)
        X = rng.normal(0, 1, (300, 4))
        y = X[:, 0] * 2 - X[:, 1] + 0.1 * rng.normal(size=300)
        m = LightGBMRegressor(num_iterations=10, num_leaves=8).fit(_df(X, y))
        booster = m.booster
        cm = convert_model(booster_to_onnx(booster))
        Xq = rng.normal(0, 1, (40, 4)).astype(np.float32)
        got = np.asarray(cm(cm.params, {"features": Xq})["variable"])[:, 0]
        np.testing.assert_allclose(got, booster.predict(Xq), rtol=1e-4,
                                   atol=1e-4)

    def test_nan_routing_matches_booster(self):
        """NaN features go left in the trainer; the exported graph must
        route them identically (missing_value_tracks_true)."""
        rng = np.random.default_rng(3)
        X = rng.normal(0, 1, (200, 4))
        y = (X[:, 0] > 0).astype(np.int64)
        booster = LightGBMClassifier(num_iterations=6, num_leaves=6) \
            .fit(_df(X, y)).booster
        cm = convert_model(booster_to_onnx(booster))
        Xq = rng.normal(0, 1, (30, 4)).astype(np.float32)
        Xq[::3, 0] = np.nan
        got = np.asarray(cm(cm.params, {"features": Xq})["probabilities"])
        np.testing.assert_allclose(got[:, 1], booster.predict(Xq),
                                   rtol=1e-4, atol=1e-5)

    def test_through_onnx_model_stage(self):
        """Full user path: exported booster served by ONNXModel over a
        DataFrame — the reference's LightGBM→ONNX demo, natively."""
        rng = np.random.default_rng(4)
        X = rng.normal(0, 1, (200, 5))
        y = (X[:, 0] - X[:, 2] > 0).astype(np.int64)
        booster = LightGBMClassifier(num_iterations=8, num_leaves=8) \
            .fit(_df(X, y)).booster
        stage = ONNXModel(booster_to_onnx(booster),
                          feed_dict={"features": "features"},
                          fetch_dict={"proba": "probabilities",
                                      "pred": "label"},
                          mini_batch_size=64, pin_devices=False)
        Xq = rng.normal(0, 1, (48, 5)).astype(np.float32)
        out = stage.transform(_df(Xq))
        p1 = np.stack(list(out["proba"]))[:, 1]
        np.testing.assert_allclose(p1, booster.predict(Xq), rtol=1e-4,
                                   atol=1e-5)

    def test_cat_encoder_refused(self):
        rng = np.random.default_rng(5)
        X = rng.normal(0, 1, (100, 3))
        y = (X[:, 0] > 0).astype(np.int64)
        booster = LightGBMClassifier(num_iterations=3, num_leaves=4) \
            .fit(_df(X, y)).booster
        booster.cat_encoder = object()          # any non-None sentinel
        with pytest.raises(ValueError, match="categorical"):
            booster_to_onnx(booster)


class TestHandBuiltEnsembles:
    def test_ragged_trees_branch_modes_and_average(self):
        """Non-complete trees, mixed branch modes, AVERAGE aggregation —
        checked against a per-row python oracle."""
        # tree 0: root(f0 < 1.5) -> leaf1 / node2(f1 >= 0) -> leaf3/leaf4
        # tree 1: root(f0 > -1)  -> leaf1 / leaf2
        attrs = dict(
            nodes_treeids=[0, 0, 0, 0, 0, 1, 1, 1],
            nodes_nodeids=[0, 1, 2, 3, 4, 0, 1, 2],
            nodes_featureids=[0, 0, 1, 0, 0, 0, 0, 0],
            nodes_values=[1.5, 0, 0.0, 0, 0, -1.0, 0, 0],
            nodes_modes=["BRANCH_LT", "LEAF", "BRANCH_GTE", "LEAF", "LEAF",
                         "BRANCH_GT", "LEAF", "LEAF"],
            nodes_truenodeids=[1, 0, 3, 0, 0, 1, 0, 0],
            nodes_falsenodeids=[2, 0, 4, 0, 0, 2, 0, 0],
            nodes_missing_value_tracks_true=[1, 0, 0, 0, 0, 0, 0, 0],
            target_treeids=[0, 0, 0, 1, 1],
            target_nodeids=[1, 3, 4, 1, 2],
            target_ids=[0, 0, 0, 0, 0],
            target_weights=[10.0, 20.0, 30.0, 1.0, 2.0],
            n_targets=1, aggregate_function="AVERAGE")
        g = make_graph(
            [make_node("TreeEnsembleRegressor", ["x"], ["y"],
                       domain="ai.onnx.ml", **attrs)],
            "t", [make_tensor_value_info("x", np.float32, ["N", 2])],
            [make_tensor_value_info("y", np.float32, ["N", 1])])
        cm = convert_model(make_model(g, extra_opsets={"ai.onnx.ml": 3}))

        def oracle(row):
            # tree 0
            if np.isnan(row[0]) or row[0] < 1.5:
                t0 = 10.0
            else:
                t0 = 20.0 if row[1] >= 0 else 30.0
            t1 = 1.0 if row[0] > -1 else 2.0
            return (t0 + t1) / 2.0

        X = np.array([[0.0, 5.0], [2.0, 1.0], [2.0, -1.0],
                      [-3.0, 0.0], [np.nan, -2.0]], np.float32)
        got = np.asarray(cm(cm.params, {"x": X})["y"])[:, 0]
        want = np.array([oracle(r) for r in X], np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6)


class TestPreprocessingOps:
    def _run(self, op, inputs, outputs=1, **attrs):
        names = [f"i{k}" for k in range(len(inputs))]
        onames = [f"o{k}" for k in range(outputs)]
        g = make_graph(
            [make_node(op, names, onames, domain="ai.onnx.ml", **attrs)],
            "t", [make_tensor_value_info(n, np.asarray(v).dtype,
                                         list(np.asarray(v).shape))
                  for n, v in zip(names, inputs)],
            [make_tensor_value_info(o, np.float32, []) for o in onames])
        cm = convert_model(make_model(g, extra_opsets={"ai.onnx.ml": 3}))
        out = cm(cm.params, dict(zip(names, inputs)))
        return [np.asarray(out[o]) for o in onames]

    def test_scaler(self):
        x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        got, = self._run("Scaler", [x], offset=[1.0, 2.0], scale=[2.0, 0.5])
        np.testing.assert_allclose(got, [[0, 0], [4, 1]])

    def test_normalizer_l2(self):
        x = np.array([[3.0, 4.0]], np.float32)
        got, = self._run("Normalizer", [x], norm="L2")
        np.testing.assert_allclose(got, [[0.6, 0.8]], rtol=1e-6)

    def test_imputer_nan(self):
        x = np.array([[1.0, np.nan], [np.nan, 4.0]], np.float32)
        got, = self._run("Imputer", [x], imputed_value_floats=[9.0, 7.0])
        np.testing.assert_allclose(got, [[1, 7], [9, 4]])

    def test_binarizer(self):
        x = np.array([[0.2, 0.8]], np.float32)
        got, = self._run("Binarizer", [x], threshold=0.5)
        np.testing.assert_allclose(got, [[0.0, 1.0]])

    def test_array_feature_extractor(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        idx = np.array([3, 1], np.int64)
        got, = self._run("ArrayFeatureExtractor", [x, idx])
        np.testing.assert_allclose(got, x[:, [3, 1]])

    def test_feature_vectorizer(self):
        a = np.array([[1.0], [2.0]], np.float32)
        b = np.array([[3.0, 4.0], [5.0, 6.0]], np.float32)
        got, = self._run("FeatureVectorizer", [a, b],
                         inputdimensions=[1, 2])
        np.testing.assert_allclose(got, [[1, 3, 4], [2, 5, 6]])

    def test_label_encoder_int_to_float(self):
        x = np.array([5, 7, 9], np.int64)
        got, = self._run("LabelEncoder", [x], keys_int64s=[5, 7],
                         values_floats=[0.5, 0.7], default_float=-1.0)
        np.testing.assert_allclose(got, [0.5, 0.7, -1.0])

    def test_linear_classifier_binary(self):
        x = np.array([[1.0, 0.0], [-1.0, 0.0]], np.float32)
        labels, scores = self._run(
            "LinearClassifier", [x], outputs=2,
            coefficients=[2.0, 0.0], intercepts=[0.0],
            classlabels_ints=[0, 1], post_transform="LOGISTIC")
        p1 = 1 / (1 + np.exp(-np.array([2.0, -2.0])))
        np.testing.assert_allclose(scores[:, 1], p1, rtol=1e-5)
        np.testing.assert_array_equal(labels, [1, 0])

    def test_linear_regressor(self):
        x = np.array([[1.0, 2.0]], np.float32)
        got, = self._run("LinearRegressor", [x],
                         coefficients=[3.0, -1.0], intercepts=[0.5],
                         targets=1)
        np.testing.assert_allclose(got, [[1.5]])


class TestCoreStragglers:
    def _run(self, op, inputs, **attrs):
        names = [f"i{k}" for k in range(len(inputs))]
        g = make_graph(
            [make_node(op, names, ["o"], **attrs)],
            "t", [make_tensor_value_info(n, np.asarray(v).dtype,
                                         list(np.asarray(v).shape))
                  for n, v in zip(names, inputs)],
            [make_tensor_value_info("o", np.float32, [])])
        cm = convert_model(make_model(g))
        return np.asarray(cm(cm.params, dict(zip(names, inputs)))["o"])

    def test_mod(self):
        a = np.array([5, -5], np.int64)
        b = np.array([3, 3], np.int64)
        np.testing.assert_array_equal(self._run("Mod", [a, b]), [2, 1])
        np.testing.assert_array_equal(
            self._run("Mod", [a, b], fmod=1), [2, -2])

    def test_hardmax(self):
        x = np.array([[1.0, 3.0, 2.0]], np.float32)
        np.testing.assert_allclose(self._run("Hardmax", [x]),
                                   [[0.0, 1.0, 0.0]])

    def test_mish(self):
        x = np.array([0.0, 1.0], np.float32)
        want = x * np.tanh(np.log1p(np.exp(x)))
        np.testing.assert_allclose(self._run("Mish", [x]), want, rtol=1e-6)

    def test_scatter_elements_add(self):
        data = np.zeros((3, 3), np.float32)
        idx = np.array([[0, 1], [1, 2]], np.int64)
        upd = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        got = self._run("ScatterElements", [data, idx, upd], axis=1,
                        reduction="add")
        want = np.zeros((3, 3), np.float32)
        want[0, 0] += 1; want[0, 1] += 2; want[1, 1] += 3; want[1, 2] += 4
        np.testing.assert_allclose(got, want)


class TestSVM:
    def _run(self, op, x, outputs, **attrs):
        g = make_graph(
            [make_node(op, ["x"], [f"o{k}" for k in range(outputs)],
                       domain="ai.onnx.ml", **attrs)],
            "t", [make_tensor_value_info("x", np.float32, list(x.shape))],
            [make_tensor_value_info(f"o{k}", np.float32, [])
             for k in range(outputs)])
        cm = convert_model(make_model(g, extra_opsets={"ai.onnx.ml": 3}))
        out = cm(cm.params, {"x": x})
        return [np.asarray(out[f"o{k}"]) for k in range(outputs)]

    def test_svm_regressor_rbf(self):
        rng = np.random.default_rng(20)
        SV = rng.normal(0, 1, (3, 2)).astype(np.float32)
        coef = np.array([0.5, -1.0, 0.25], np.float32)
        gamma = 0.7
        X = rng.normal(0, 1, (5, 2)).astype(np.float32)
        got, = self._run("SVMRegressor", X, 1,
                         coefficients=coef.tolist(),
                         support_vectors=SV.reshape(-1).tolist(),
                         rho=[0.3], kernel_type="RBF",
                         kernel_params=[gamma, 0.0, 3.0])
        d2 = ((X[:, None] - SV[None]) ** 2).sum(-1)
        want = np.exp(-gamma * d2) @ coef + 0.3
        np.testing.assert_allclose(got[:, 0], want, rtol=1e-5, atol=1e-5)

    def test_svm_classifier_binary_linear(self):
        """Binary libsvm SVC: decision = K[:,sv1]@a + K[:,sv0]@a' - rho;
        label by the decision's sign."""
        SV = np.array([[1.0, 0.0], [-1.0, 0.0]], np.float32)  # class0, class1
        # dual coefs (C-1=1, M=2): y_i * alpha_i
        coefs = np.array([[1.0, -1.0]], np.float32)
        X = np.array([[2.0, 0.0], [-2.0, 0.0]], np.float32)
        labels, scores = self._run(
            "SVMClassifier", X, 2,
            classlabels_ints=[0, 1], vectors_per_class=[1, 1],
            support_vectors=SV.reshape(-1).tolist(),
            coefficients=coefs.reshape(-1).tolist(), rho=[0.5],
            kernel_type="LINEAR")
        # dec = K[:,sv_i]@A[j-1,si] + K[:,sv_j]@A[i,sj] + rho
        #     = (x@[1,0])*1 + (x@[-1,0])*(-1) + 0.5 = 2*x0 + 0.5
        # (rho holds sklearn's intercept_, ADDED — nonzero here to pin
        # the sign convention)
        np.testing.assert_allclose(scores[:, 0], [4.5, -3.5], rtol=1e-6)
        np.testing.assert_array_equal(labels, [0, 1])  # dec>0 → class i=0


def test_model_to_onnx_method():
    """The fitted model's to_onnx() convenience — the onnxmltools-flow
    entry point users of the reference expect."""
    rng = np.random.default_rng(6)
    X = rng.normal(0, 1, (150, 4))
    y = (X[:, 1] > 0).astype(np.int64)
    model = LightGBMClassifier(num_iterations=5, num_leaves=4).fit(_df(X, y))
    cm = convert_model(model.to_onnx())
    Xq = rng.normal(0, 1, (20, 4)).astype(np.float32)
    p1 = np.asarray(cm(cm.params, {"features": Xq})["probabilities"])[:, 1]
    np.testing.assert_allclose(p1, model.booster.predict(Xq), rtol=1e-4,
                               atol=1e-5)
