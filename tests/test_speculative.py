"""Speculative decoding (zoo/speculative.py) and the decode_window
primitive.

The load-bearing invariant: greedy speculative output is token-for-token
identical to the target model decoding alone — the draft changes cost,
never content.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                 decode_step,
                                                 decode_window,
                                                 generate_cached,
                                                 init_transformer,
                                                 prefill_cache)
from mmlspark_tpu.models.zoo.speculative import (generate_speculative,
                                                 generate_speculative_fused,
                                                 generate_speculative_paged)


def cfg_pair(position="rope", vocab=64):
    import jax.numpy as jnp
    target = TransformerConfig(vocab=vocab, d_model=32, heads=4, layers=3,
                               d_ff=64, max_len=128, causal=True,
                               position=position, dtype=jnp.float32)
    draft = TransformerConfig(vocab=vocab, d_model=16, heads=2, layers=1,
                              d_ff=32, max_len=128, causal=True,
                              position=position, dtype=jnp.float32)
    return target, draft


def make_models(position="rope", seed=0):
    t_cfg, d_cfg = cfg_pair(position)
    t_params = init_transformer(t_cfg, seed=seed)
    d_params = init_transformer(d_cfg, seed=seed + 100)
    return t_params, d_params, t_cfg, d_cfg


class TestDecodeWindow:
    def test_matches_stepwise_decode(self):
        t_params, _, t_cfg, _ = make_models()
        rng = np.random.default_rng(0)
        B, P, W, L = 2, 5, 4, 32
        prompt = jnp.asarray(rng.integers(0, t_cfg.vocab, (B, P)))
        win = jnp.asarray(rng.integers(0, t_cfg.vocab, (B, W)))
        lengths = jnp.full((B,), P, jnp.int32)
        _, cache0 = prefill_cache(t_params, prompt, lengths, t_cfg, L)
        # window forward
        wl, wcache = decode_window(t_params, win, P, cache0, t_cfg)
        # step-by-step
        cache = cache0
        step_logits = []
        for i in range(W):
            lg, cache = decode_step(t_params, win[:, i], P + i, cache,
                                    t_cfg)
            step_logits.append(lg)
        np.testing.assert_allclose(np.asarray(wl),
                                   np.stack(step_logits, axis=1),
                                   rtol=2e-4, atol=2e-4)
        for cw, cs in zip(wcache, cache):
            np.testing.assert_allclose(np.asarray(cw["k"]),
                                       np.asarray(cs["k"]),
                                       rtol=2e-4, atol=2e-4)

    def test_learned_positions(self):
        t_params, _, t_cfg, _ = make_models(position="learned")
        rng = np.random.default_rng(1)
        B, P, W, L = 1, 3, 3, 24
        prompt = jnp.asarray(rng.integers(0, t_cfg.vocab, (B, P)))
        win = jnp.asarray(rng.integers(0, t_cfg.vocab, (B, W)))
        _, cache0 = prefill_cache(t_params, prompt,
                                  jnp.full((B,), P, jnp.int32), t_cfg, L)
        wl, _ = decode_window(t_params, win, P, cache0, t_cfg)
        cache = cache0
        for i in range(W):
            lg, cache = decode_step(t_params, win[:, i], P + i, cache,
                                    t_cfg)
            np.testing.assert_allclose(np.asarray(wl[:, i]), np.asarray(lg),
                                       rtol=2e-4, atol=2e-4)


class TestSpeculative:
    @pytest.mark.parametrize("position", ["rope", "learned"])
    @pytest.mark.parametrize("gamma", [1, 3, 5])
    def test_exact_match_with_target_greedy(self, position, gamma):
        t_params, d_params, t_cfg, d_cfg = make_models(position)
        rng = np.random.default_rng(2)
        prompt = jnp.asarray(rng.integers(0, t_cfg.vocab, (2, 6)))
        ref = generate_cached(t_params, prompt, t_cfg, max_new_tokens=20,
                              temperature=0.0)
        spec, stats = generate_speculative(t_params, d_params, prompt,
                                           t_cfg, d_cfg,
                                           max_new_tokens=20, gamma=gamma)
        np.testing.assert_array_equal(np.asarray(spec), np.asarray(ref))
        assert stats["rounds"] >= 1

    def test_perfect_draft_accepts_everything(self):
        # draft == target: every proposal matches, so target forwards
        # collapse to ~max_new/(gamma+1)
        t_params, _, t_cfg, _ = make_models()
        rng = np.random.default_rng(3)
        prompt = jnp.asarray(rng.integers(0, t_cfg.vocab, (1, 4)))
        max_new, gamma = 24, 3
        spec, stats = generate_speculative(t_params, t_params, prompt,
                                           t_cfg, t_cfg,
                                           max_new_tokens=max_new,
                                           gamma=gamma)
        ref = generate_cached(t_params, prompt, t_cfg,
                              max_new_tokens=max_new, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(spec), np.asarray(ref))
        per_round = stats["accepted_drafts"] / max(stats["rounds"], 1)
        assert per_round > gamma - 0.5, stats     # near-total acceptance
        # 1 prefill + ceil((max_new-1)/(gamma+1)) verify rounds, give or
        # take the final-round cap
        assert stats["target_forwards"] <= 2 + (max_new - 1) // (gamma + 1) + 1, \
            stats

    @pytest.mark.parametrize("gamma", [1, 3, 5])
    def test_fused_matches_loop_and_target(self, gamma):
        t_params, d_params, t_cfg, d_cfg = make_models()
        rng = np.random.default_rng(4)
        prompt = jnp.asarray(rng.integers(0, t_cfg.vocab, (2, 5)))
        ref = generate_cached(t_params, prompt, t_cfg, max_new_tokens=17,
                              temperature=0.0)
        fused, fstats = generate_speculative_fused(
            t_params, d_params, prompt, t_cfg, d_cfg,
            max_new_tokens=17, gamma=gamma)
        np.testing.assert_array_equal(np.asarray(fused), np.asarray(ref))
        loop, lstats = generate_speculative(
            t_params, d_params, prompt, t_cfg, d_cfg,
            max_new_tokens=17, gamma=gamma)
        np.testing.assert_array_equal(np.asarray(fused), np.asarray(loop))
        assert fstats["rounds"] >= 1

    def test_fused_perfect_draft_forward_count(self):
        t_params, _, t_cfg, _ = make_models()
        rng = np.random.default_rng(5)
        prompt = jnp.asarray(rng.integers(0, t_cfg.vocab, (1, 4)))
        max_new, gamma = 24, 3
        fused, stats = generate_speculative_fused(
            t_params, t_params, prompt, t_cfg, t_cfg,
            max_new_tokens=max_new, gamma=gamma)
        ref = generate_cached(t_params, prompt, t_cfg,
                              max_new_tokens=max_new, temperature=0.0)
        np.testing.assert_array_equal(np.asarray(fused), np.asarray(ref))
        assert stats["target_forwards"] <= 2 + (max_new - 1) // (gamma + 1) + 1, \
            stats

    @pytest.mark.parametrize("page_size", [3, 8])
    def test_paged_matches_loop_and_target(self, page_size):
        """The paged-target variant (block-table gather, CoW-style page
        layout) is token-identical to the contiguous loop — paging moves
        bytes, never changes tokens."""
        t_params, d_params, t_cfg, d_cfg = make_models()
        rng = np.random.default_rng(5)
        prompt = jnp.asarray(rng.integers(0, t_cfg.vocab, (2, 7)))
        loop, lstats = generate_speculative(
            t_params, d_params, prompt, t_cfg, d_cfg,
            max_new_tokens=16, gamma=3)
        paged, pstats = generate_speculative_paged(
            t_params, d_params, prompt, t_cfg, d_cfg,
            max_new_tokens=16, gamma=3, page_size=page_size)
        assert np.array_equal(np.asarray(loop), np.asarray(paged))
        assert pstats["accepted_drafts"] == lstats["accepted_drafts"]
        target = generate_cached(t_params, prompt, t_cfg,
                                 max_new_tokens=16, temperature=0.0)
        assert np.array_equal(np.asarray(paged), np.asarray(target))

    def test_vocab_mismatch_rejected(self):
        t_params, d_params, t_cfg, d_cfg = make_models()
        d_cfg = d_cfg._replace(vocab=t_cfg.vocab + 1)
        with pytest.raises(ValueError, match="vocab"):
            generate_speculative(t_params, d_params,
                                 jnp.zeros((1, 2), jnp.int32),
                                 t_cfg, d_cfg)


class TestSpeculativeSampled:
    """Speculative SAMPLING (rejection-correction): the emitted sequence
    must be exactly target-distributed. Verified against ANALYTIC
    marginals — for the tiny vocab we can enumerate p(tok1) from the
    prefill logits and p(tok2) = Σ_t1 p(t1)·p(t2|t1) exactly, then
    check the empirical frequencies from thousands of independent rows.
    Deterministic (fixed seed), so the tolerances are not flaky."""

    TEMP = 1.3

    def _setup(self, vocab=32):
        t_cfg, d_cfg = cfg_pair(vocab=vocab)
        t_params = init_transformer(t_cfg, seed=1)
        d_params = init_transformer(d_cfg, seed=7)   # a DIFFERENT model
        prompt = np.asarray([[3, 11, 4, 17]], np.int32)
        return t_params, d_params, t_cfg, d_cfg, prompt

    def _exact_marginals(self, t_params, t_cfg, prompt):
        """(p(tok1), p(tok2)) by enumeration at temperature TEMP."""
        V = t_cfg.vocab
        P = prompt.shape[1]
        L = P + 4
        lengths = jnp.asarray([P], jnp.int32)
        logits, cache = prefill_cache(t_params, jnp.asarray(prompt),
                                      lengths, t_cfg, L)
        p1 = np.asarray(jax.nn.softmax(
            logits.astype(jnp.float32) / self.TEMP, -1))[0]      # (V,)
        # p(tok2 | tok1=v): batch all V candidates through one step
        cacheV = [{k: jnp.repeat(c[k], V, axis=0) for k in ("k", "v")}
                  for c in cache]
        l2, _ = decode_step(t_params, jnp.arange(V, dtype=jnp.int32),
                            P, cacheV, t_cfg)
        p2_given = np.asarray(jax.nn.softmax(
            l2.astype(jnp.float32) / self.TEMP, -1))             # (V, V)
        return p1, p1 @ p2_given

    def test_marginals_match_target_exactly(self):
        from mmlspark_tpu.models.zoo.speculative import \
            generate_speculative_sampled
        t_params, d_params, t_cfg, d_cfg, prompt = self._setup()
        N = 4096
        prompts = np.repeat(prompt, N, axis=0)
        ids, stats = generate_speculative_sampled(
            t_params, d_params, prompts, t_cfg, d_cfg,
            max_new_tokens=3, gamma=2, temperature=self.TEMP, seed=11)
        toks = np.asarray(ids)[:, prompt.shape[1]:]              # (N, 3)
        p1, p2 = self._exact_marginals(t_params, t_cfg, prompt)
        V = t_cfg.vocab
        emp1 = np.bincount(toks[:, 0], minlength=V) / N
        emp2 = np.bincount(toks[:, 1], minlength=V) / N
        # ~4 sigma for the largest bins at N=4096 is ~0.03
        assert np.abs(emp1 - p1).max() < 0.035, np.abs(emp1 - p1).max()
        assert np.abs(emp2 - p2).max() < 0.035, np.abs(emp2 - p2).max()
        # batch-min acceptance over 4096 independent rows is ~always 0,
        # so the per-round advance stays 1 — but both emission branches
        # (accepted-at-cut and rejected-resample) run per row inside;
        # the marginal checks above are what verify them

    def test_perfect_draft_high_acceptance_and_exact(self):
        from mmlspark_tpu.models.zoo.speculative import \
            generate_speculative_sampled
        t_params, _, t_cfg, _, prompt = self._setup()
        N = 2048
        ids, stats = generate_speculative_sampled(
            t_params, t_params, np.repeat(prompt, N, axis=0), t_cfg,
            t_cfg, max_new_tokens=6, gamma=2, temperature=self.TEMP,
            seed=3)
        toks = np.asarray(ids)[:, prompt.shape[1]:]
        p1, p2 = self._exact_marginals(t_params, t_cfg, prompt)
        V = t_cfg.vocab
        emp1 = np.bincount(toks[:, 0], minlength=V) / N
        emp2 = np.bincount(toks[:, 1], minlength=V) / N
        assert np.abs(emp1 - p1).max() < 0.045
        assert np.abs(emp2 - p2).max() < 0.045
        # identical models: ratio = 1, acceptance ~always (batch-min over
        # 2048 rows still accepts when every row does)
        assert stats["accepted_drafts"] >= stats["rounds"]

    def test_rows_are_independent_streams(self):
        from mmlspark_tpu.models.zoo.speculative import \
            generate_speculative_sampled
        t_params, d_params, t_cfg, d_cfg, prompt = self._setup()
        ids, _ = generate_speculative_sampled(
            t_params, d_params, np.repeat(prompt, 64, axis=0), t_cfg,
            d_cfg, max_new_tokens=4, gamma=2, temperature=self.TEMP,
            seed=5)
        toks = np.asarray(ids)[:, prompt.shape[1]:]
        assert len({tuple(r) for r in toks}) > 16   # not all identical

    def test_fresh_seeds_do_not_recompile(self):
        """Per-request seeds/temperatures are traced args — the r4
        verdict's per-call-recompile failure mode must not return."""
        from mmlspark_tpu.models.zoo import speculative as spec_mod
        t_params, d_params, t_cfg, d_cfg, prompt = self._setup()
        kw = dict(max_new_tokens=2, gamma=2)
        spec_mod.generate_speculative_sampled(
            t_params, d_params, prompt, t_cfg, d_cfg,
            temperature=0.9, seed=1, **kw)
        before = spec_mod._speculative_sampled_impl._cache_size()
        spec_mod.generate_speculative_sampled(
            t_params, d_params, prompt, t_cfg, d_cfg,
            temperature=1.1, seed=2, **kw)
        assert spec_mod._speculative_sampled_impl._cache_size() == before

    def test_validation(self):
        from mmlspark_tpu.models.zoo.speculative import \
            generate_speculative_sampled
        t_params, d_params, t_cfg, d_cfg, prompt = self._setup()
        with pytest.raises(ValueError, match="temperature"):
            generate_speculative_sampled(t_params, d_params, prompt,
                                         t_cfg, d_cfg, temperature=0.0)
        with pytest.raises(ValueError, match="vocab"):
            generate_speculative_sampled(
                t_params, d_params, prompt, t_cfg,
                d_cfg._replace(vocab=t_cfg.vocab + 1))

    def test_topk_marginals_match_warped_target(self):
        """top-k under speculative sampling: both distributions get the
        same warp, so marginals match the enumerated TOP-K-WARPED target
        exactly (and nothing outside the reachable support appears)."""
        from mmlspark_tpu.models.zoo.speculative import \
            generate_speculative_sampled
        t_params, d_params, t_cfg, d_cfg, prompt = self._setup()
        N, V, TOPK = 2048, t_cfg.vocab, 3
        ids, _ = generate_speculative_sampled(
            t_params, d_params, np.repeat(prompt, N, axis=0), t_cfg,
            d_cfg, max_new_tokens=3, gamma=2, temperature=self.TEMP,
            top_k=TOPK, seed=13)
        toks = np.asarray(ids)[:, prompt.shape[1]:]

        def warp(row):
            scaled = np.asarray(row, np.float64) / self.TEMP
            kth = np.sort(scaled)[::-1][TOPK - 1]
            e = np.where(scaled >= kth, np.exp(scaled - scaled.max()), 0.0)
            return e / e.sum()

        lengths = jnp.asarray([prompt.shape[1]], jnp.int32)
        logits, cache = prefill_cache(t_params, jnp.asarray(prompt),
                                      lengths, t_cfg, prompt.shape[1] + 4)
        p1 = warp(np.asarray(logits)[0])
        cacheV = [{k: jnp.repeat(c[k], V, axis=0) for k in ("k", "v")}
                  for c in cache]
        l2, _ = decode_step(t_params, jnp.arange(V, dtype=jnp.int32),
                            prompt.shape[1], cacheV, t_cfg)
        p2 = p1 @ np.stack([warp(r) for r in np.asarray(l2)])
        emp1 = np.bincount(toks[:, 0], minlength=V) / N
        emp2 = np.bincount(toks[:, 1], minlength=V) / N
        assert np.abs(emp1 - p1).max() < 0.045, np.abs(emp1 - p1).max()
        assert np.abs(emp2 - p2).max() < 0.045, np.abs(emp2 - p2).max()
        assert set(np.unique(toks[:, 0])) <= set(np.nonzero(p1)[0])
        assert set(np.unique(toks[:, 1])) <= set(np.nonzero(p2)[0])

    def test_topp_marginals_match_warped_target(self):
        """Nucleus warp through the zoo sampled path (top_k=0 keeps that
        half neutral, isolating the top_p plumbing)."""
        from mmlspark_tpu.models.zoo.speculative import \
            generate_speculative_sampled
        t_params, d_params, t_cfg, d_cfg, prompt = self._setup()
        N, V, TOPP = 2048, t_cfg.vocab, 0.55
        ids, _ = generate_speculative_sampled(
            t_params, d_params, np.repeat(prompt, N, axis=0), t_cfg,
            d_cfg, max_new_tokens=3, gamma=2, temperature=self.TEMP,
            top_p=TOPP, seed=17)
        toks = np.asarray(ids)[:, prompt.shape[1]:]

        def warp(row):
            scaled = np.asarray(row, np.float64) / self.TEMP
            probs = np.exp(scaled - scaled.max())
            probs /= probs.sum()
            order = np.argsort(-scaled)
            keep_n = int(np.sum(np.cumsum(probs[order]) < TOPP)) + 1
            kept = order[:keep_n]
            out = np.zeros_like(probs)
            out[kept] = probs[kept] / probs[kept].sum()
            return out

        lengths = jnp.asarray([prompt.shape[1]], jnp.int32)
        logits, cache = prefill_cache(t_params, jnp.asarray(prompt),
                                      lengths, t_cfg, prompt.shape[1] + 4)
        p1 = warp(np.asarray(logits)[0])
        cacheV = [{k: jnp.repeat(c[k], V, axis=0) for k in ("k", "v")}
                  for c in cache]
        l2, _ = decode_step(t_params, jnp.arange(V, dtype=jnp.int32),
                            prompt.shape[1], cacheV, t_cfg)
        p2 = p1 @ np.stack([warp(r) for r in np.asarray(l2)])
        emp1 = np.bincount(toks[:, 0], minlength=V) / N
        emp2 = np.bincount(toks[:, 1], minlength=V) / N
        assert np.abs(emp1 - p1).max() < 0.045, np.abs(emp1 - p1).max()
        assert np.abs(emp2 - p2).max() < 0.045, np.abs(emp2 - p2).max()
        assert set(np.unique(toks[:, 0])) <= set(np.nonzero(p1)[0])
        assert set(np.unique(toks[:, 1])) <= set(np.nonzero(p2)[0])
