"""CyberML tests: indexers, scalers, complement sampling, AccessAnomaly.

Mirrors the intent of the reference's cyber test suite: inter-cluster
accesses must score strictly higher (more anomalous) than intra-cluster
ones after CF training.
"""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.dataframe import object_col
from mmlspark_tpu.cyber import (AccessAnomaly, AccessAnomalyModel,
                                ComplementAccessTransformer, DataFactory,
                                IdIndexer, LinearScalarScaler, MultiIndexer,
                                StandardScalarScaler)


def _acc_df():
    return DataFrame({
        "tenant": object_col(["a", "a", "a", "b", "b"]),
        "user": object_col(["u1", "u2", "u1", "u1", "u3"]),
        "res": object_col(["r1", "r1", "r2", "r9", "r9"]),
        "likelihood": np.array([1.0, 2.0, 3.0, 4.0, 5.0]),
    })


def test_id_indexer_per_tenant():
    df = _acc_df()
    model = IdIndexer(input_col="user", output_col="uidx",
                      partition_key="tenant").fit(df)
    out = model.transform(df)
    # per-tenant contiguous, 1-based; tenant b restarts at 1
    assert list(out["uidx"]) == [1, 2, 1, 1, 2]
    # unseen id maps to 0
    q = DataFrame({"tenant": object_col(["a"]), "user": object_col(["zz"])})
    assert model.transform(q)["uidx"][0] == 0
    # undo_transform recovers names
    undo = model.undo_transform(out.select(["tenant", "uidx"]))
    assert list(undo["user"]) == ["u1", "u2", "u1", "u1", "u3"]


def test_multi_indexer_lookup():
    df = _acc_df()
    mi = MultiIndexer([
        IdIndexer(input_col="user", output_col="uidx", partition_key="tenant"),
        IdIndexer(input_col="res", output_col="ridx", partition_key="tenant"),
    ]).fit(df)
    out = mi.transform(df)
    assert "uidx" in out.columns and "ridx" in out.columns
    assert mi.get_model_by_input_col("res").get("output_col") == "ridx"


def test_standard_scaler_per_tenant():
    df = _acc_df()
    out = StandardScalarScaler(input_col="likelihood", output_col="z",
                               partition_key="tenant").fit(df).transform(df)
    za = out["z"][:3]
    assert abs(za.mean()) < 1e-9        # per-tenant zero mean
    assert abs(np.std(za) - 1.0) < 1e-9


def test_linear_scaler_range():
    df = _acc_df()
    out = LinearScalarScaler(input_col="likelihood", output_col="s",
                             partition_key="tenant",
                             min_required_value=5.0,
                             max_required_value=10.0).fit(df).transform(df)
    assert out["s"].min() == 5.0 and out["s"].max() == 10.0


def test_complement_access_excludes_observed():
    df = DataFrame({"u": np.array([1, 1, 2, 2]),
                    "r": np.array([1, 2, 1, 2])})
    # indices span 1..2 × 1..2, all 4 observed → complement is empty
    out = ComplementAccessTransformer(
        indexed_col_names=["u", "r"], complementset_factor=4).transform(df)
    assert len(out) == 0
    df2 = DataFrame({"u": np.array([1, 2, 3]), "r": np.array([1, 2, 3])})
    out2 = ComplementAccessTransformer(
        indexed_col_names=["u", "r"], complementset_factor=8,
        seed=1).transform(df2)
    seen = {(1, 1), (2, 2), (3, 3)}
    got = set(zip(out2["u"], out2["r"]))
    assert got and not (got & seen)


@pytest.fixture(scope="module")
def fitted():
    factory = DataFactory(num_hr_users=10, num_hr_resources=15,
                          num_fin_users=10, num_fin_resources=15, seed=2)
    train = factory.create_clustered_training_data(ratio=0.4)
    model = AccessAnomaly(rank_param=6, max_iter=15, seed=0).fit(train)
    return factory, train, model


def test_access_anomaly_separates_clusters(fitted):
    factory, train, model = fitted
    intra = model.transform(factory.create_clustered_intra_test_data(30))
    inter = model.transform(factory.create_clustered_inter_test_data(30))

    def scores(df):
        return np.array([s for s in df["anomaly_score"]
                         if s is not None and np.isfinite(s)])

    si, sx = scores(intra), scores(inter)
    assert len(si) > 5 and len(sx) > 5
    # inter-cluster (anomalous) accesses score clearly higher
    assert sx.mean() > si.mean() + 0.5


def test_access_anomaly_history_and_unknowns(fitted):
    factory, train, model = fitted
    out = model.transform(train.head(3))
    assert all(s == 0.0 for s in out["anomaly_score"])  # seen → 0
    q = DataFrame({"tenant": object_col(["t0"]),
                   "user": object_col(["nobody"]),
                   "res": object_col(["hr_res_0"])})
    assert model.transform(q)["anomaly_score"][0] is None


def test_access_anomaly_save_load(fitted, tmp_path):
    factory, train, model = fitted
    test = factory.create_clustered_inter_test_data(10)
    ref = model.transform(test)["anomaly_score"]
    p = str(tmp_path / "aa")
    model.save(p)
    again = AccessAnomalyModel.load(p)
    got = again.transform(test)["anomaly_score"]
    for a, b in zip(ref, got):
        if a is None:
            assert b is None
        else:
            assert abs(a - b) < 1e-6


def test_access_anomaly_explicit_mode():
    factory = DataFactory(num_hr_users=8, num_hr_resources=10,
                          num_fin_users=8, num_fin_resources=10, seed=3)
    train = factory.create_clustered_training_data(ratio=0.5)
    model = AccessAnomaly(rank_param=5, max_iter=10,
                          apply_implicit_cf=False, seed=0).fit(train)
    inter = model.transform(factory.create_clustered_inter_test_data(20))
    intra = model.transform(factory.create_clustered_intra_test_data(20))

    def scores(df):
        return np.array([s for s in df["anomaly_score"]
                         if s is not None and np.isfinite(s)])

    assert scores(inter).mean() > scores(intra).mean()


def test_id_indexer_numeric_ids_serializable(tmp_path):
    """Numeric id/tenant columns must produce a JSON-serializable vocab."""
    df = DataFrame({"tenant": np.array([1, 1, 2]),
                    "user": np.array([10, 20, 10])})
    model = IdIndexer(input_col="user", output_col="uidx",
                      partition_key="tenant").fit(df)
    out = model.transform(df)
    assert list(out["uidx"]) == [1, 2, 1]
    p = str(tmp_path / "ix")
    model.save(p)
    from mmlspark_tpu.cyber import IdIndexerModel
    again = IdIndexerModel.load(p)
    assert list(again.transform(df)["uidx"]) == [1, 2, 1]


def test_access_anomaly_numeric_tenant_save(tmp_path):
    df = DataFrame({
        "tenant": np.array([7] * 6),
        "user": object_col(["u1", "u2", "u3", "u1", "u2", "u3"]),
        "res": object_col(["r1", "r1", "r2", "r2", "r3", "r3"]),
        "likelihood": np.ones(6),
    })
    model = AccessAnomaly(rank_param=2, max_iter=3).fit(df)
    p = str(tmp_path / "aa_num")
    model.save(p)
    again = AccessAnomalyModel.load(p)
    out = again.transform(df)
    assert all(s == 0.0 for s in out["anomaly_score"])  # all seen


def test_complement_access_with_partition_key():
    df = DataFrame({"tenant": object_col(["a", "a", "b", "b"]),
                    "u": np.array([1, 2, 1, 3]),
                    "r": np.array([1, 2, 1, 3])})
    out = ComplementAccessTransformer(
        partition_key="tenant", indexed_col_names=["u", "r"],
        complementset_factor=6, seed=0).transform(df)
    assert "tenant" in out.columns
    for t, u, r in zip(out["tenant"], out["u"], out["r"]):
        assert (u, r) not in {(1, 1), (2, 2)} if t == "a" else True


def test_access_anomaly_zip_hostile_tenant_names(tmp_path):
    """Tenant names with '/' must survive save/load (ADVICE r1: npz archive
    entries were keyed by raw tenant name)."""
    factory = DataFactory(num_hr_users=6, num_hr_resources=8,
                          num_fin_users=6, num_fin_resources=8, seed=3)
    train = factory.create_clustered_training_data(ratio=0.5)
    weird = object_col([f"ten/ant:{t}" for t in train["tenant"]])
    train = train.with_column("tenant", weird)
    model = AccessAnomaly(rank_param=4, max_iter=5, seed=0).fit(train)
    test = train.head(5)
    ref = model.transform(test)["anomaly_score"]
    p = str(tmp_path / "aa_slash")
    model.save(p)
    got = AccessAnomalyModel.load(p).transform(test)["anomaly_score"]
    for a, b in zip(ref, got):
        assert (a is None) == (b is None)
        if a is not None:
            assert abs(a - b) < 1e-6
