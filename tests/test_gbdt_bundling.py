"""Exclusive Feature Bundling (EFB) — sparse histogram acceleration.

Parity surface: LightGBM's ``enable_bundle``/``max_conflict_rate``
(native C++ behind the reference's param passthrough,
``params/TrainParams.scala:10-100``). The TPU reformulation under test
(``models/gbdt/bundling.py`` + ``trees._debundle``): bundled scatter-add,
exact per-feature reconstruction via default-bin subtraction, bundle
decode during row routing.

Load-bearing invariant: with conflict budget 0 the bundling is LOSSLESS in
exact arithmetic — the debundled histogram equals the direct per-feature
histogram up to f32 summation-order noise (the default bin is
reconstructed as total − non-default, a different FP op order; LightGBM's
sibling-histogram subtraction has the same property). Tests therefore pin
(a) exact encode/decode, (b) histogram equality to f32 tolerance,
(c) identical trees on a shallow well-separated problem, and (d) quality
parity where ULP noise may flip near-tie splits at deep nodes.
"""

import numpy as np
import scipy.sparse as sp

from mmlspark_tpu.models.gbdt import train
from mmlspark_tpu.models.gbdt.binning import BinMapper
from mmlspark_tpu.models.gbdt.bundling import FeatureBundler, plan_bundles


def make_exclusive(n=400, groups=4, per_group=3, seed=0):
    """Features arranged in groups of mutually exclusive columns: each row
    holds a value in exactly one column per group (one-hot-with-values —
    the shape EFB exists for)."""
    rng = np.random.default_rng(seed)
    F = groups * per_group
    dense = np.zeros((n, F))
    for g in range(groups):
        which = rng.integers(0, per_group, n)
        vals = rng.normal(1, 1, n)          # mean 1: mostly non-default
        dense[np.arange(n), g * per_group + which] = vals
    return dense, sp.csr_matrix(dense)


def target_for(dense, seed=0):
    rng = np.random.default_rng(seed)
    return (dense[:, 0] + dense[:, 3] - dense[:, 1]
            + rng.normal(0, 0.2, len(dense)) > 0.4).astype(np.float64)


class TestPlanner:
    def test_exclusive_features_bundle(self):
        dense, csr = make_exclusive()
        mapper = BinMapper(max_bin=16).fit(csr)
        b = FeatureBundler(max_conflict_rate=0.0).fit(csr, mapper)
        # mutually exclusive groups must compress below F columns
        assert b.n_bundles < csr.shape[1]
        # every feature appears in exactly one bundle
        members = sorted(f for bb in b.bundles for f in bb)
        assert members == list(range(csr.shape[1]))

    def test_zero_budget_means_no_conflicts(self):
        dense, csr = make_exclusive(seed=3)
        mapper = BinMapper(max_bin=16).fit(csr)
        b = FeatureBundler(max_conflict_rate=0.0).fit(csr, mapper)
        for members in b.bundles:
            if len(members) < 2:
                continue
            occupancy = np.zeros(csr.shape[0], dtype=int)
            for f in members:
                col = dense[:, f]
                occupancy += (col != 0).astype(int)
            assert occupancy.max() <= 1, "conflicting features bundled"

    def test_dense_features_stay_separate(self):
        rng = np.random.default_rng(1)
        dense = rng.normal(1, 1, (200, 5))          # fully dense columns
        csr = sp.csr_matrix(dense)
        mapper = BinMapper(max_bin=16).fit(csr)
        b = FeatureBundler(max_conflict_rate=0.0).fit(csr, mapper)
        assert b.n_bundles == 5
        assert not b.worthwhile(5)

    def test_bundle_bin_cap_respected(self):
        nondefault = [np.array([i]) for i in range(10)]
        widths = np.full(10, 300)
        bundles = plan_bundles(nondefault, n_rows=20, widths=widths,
                               max_conflict_rate=0.0, max_bundle_bins=650)
        for members in bundles:
            assert 1 + sum(widths[f] for f in members) <= 650

    def test_sampled_planning_bounded_rows(self):
        # n above plan_sample_cnt: conflict counting runs on the sample,
        # and exclusive groups must still bundle
        dense, csr = make_exclusive(n=3000, seed=4)
        mapper = BinMapper(max_bin=16).fit(csr)
        b = FeatureBundler(0.0, plan_sample_cnt=500).fit(csr, mapper)
        assert b.n_bundles < csr.shape[1]
        xb = mapper.transform(csr)
        xb_b = b.transform(csr, mapper)
        for f in range(csr.shape[1]):
            bcol = xb_b[:, b.bundle_of[f]].astype(int)
            rel = bcol - b.offset_of[f]
            decoded = np.where((rel >= 0) & (rel < b.width_of[f]),
                               rel, b.zero_bin[f])
            np.testing.assert_array_equal(decoded, xb[:, f])

    def test_conflict_budget_allows_merges(self):
        # two features overlapping on exactly 2 of 100 rows
        r1 = np.arange(0, 50)
        r2 = np.concatenate([np.array([0, 1]), np.arange(50, 90)])
        nd = [r1, r2]
        assert len(plan_bundles(nd, 100, np.array([5, 5]), 0.0)) == 2
        assert len(plan_bundles(nd, 100, np.array([5, 5]), 0.02)) == 1


class TestEncoding:
    def test_encode_decode_exact(self):
        dense, csr = make_exclusive(seed=5)
        mapper = BinMapper(max_bin=16).fit(csr)
        b = FeatureBundler(0.0).fit(csr, mapper)
        xb_b = b.transform(csr, mapper)
        xb = mapper.transform(csr)
        assert xb_b.shape == (csr.shape[0], b.n_bundles)
        # decode every feature's bin back out of the bundle columns
        for f in range(csr.shape[1]):
            bcol = xb_b[:, b.bundle_of[f]].astype(int)
            rel = bcol - b.offset_of[f]
            decoded = np.where((rel >= 0) & (rel < b.width_of[f]),
                               rel, b.zero_bin[f])
            np.testing.assert_array_equal(decoded, xb[:, f])

    def test_nan_survives_bundling(self):
        dense, _ = make_exclusive(n=100, seed=6)
        dense[dense != 0] = np.where(
            np.random.default_rng(0).random((dense != 0).sum()) < 0.3,
            np.nan, dense[dense != 0])
        csr = sp.csr_matrix(dense)
        mapper = BinMapper(max_bin=16).fit(csr)
        b = FeatureBundler(0.0).fit(csr, mapper)
        xb_b = b.transform(csr, mapper)
        xb = mapper.transform(csr)
        for f in range(csr.shape[1]):
            bcol = xb_b[:, b.bundle_of[f]].astype(int)
            rel = bcol - b.offset_of[f]
            decoded = np.where((rel >= 0) & (rel < b.width_of[f]),
                               rel, b.zero_bin[f])
            np.testing.assert_array_equal(decoded, xb[:, f])


class TestDebundledHistogram:
    def test_histogram_matches_direct(self):
        import jax.numpy as jnp
        from mmlspark_tpu.models.gbdt.trees import (BundleTables, _debundle,
                                                    _level_histogram)
        dense, csr = make_exclusive()
        rng = np.random.default_rng(2)
        mapper = BinMapper(max_bin=16).fit(csr)
        b = FeatureBundler(0.0).fit(csr, mapper)
        xb = mapper.transform(csr)
        xb_b = b.transform(csr, mapper)
        n = csr.shape[0]
        g = jnp.asarray(rng.normal(0, 1, n).astype(np.float32))
        h = jnp.asarray(rng.random(n).astype(np.float32))
        w = jnp.ones(n, jnp.float32)
        # two levels' worth of node assignments
        for node in (jnp.zeros(n, jnp.int32),
                     jnp.asarray(rng.integers(0, 4, n).astype(np.int32))):
            n_nodes = int(np.asarray(node).max()) + 1
            direct = _level_histogram(jnp.asarray(xb), node, g, h, w,
                                      n_nodes, mapper.n_bins, None)
            hb = _level_histogram(jnp.asarray(xb_b), node, g, h, w,
                                  n_nodes, b.n_bundle_bins, None)
            tables = BundleTables(
                jnp.asarray(b.bundle_of), jnp.asarray(b.offset_of),
                jnp.asarray(b.width_of), jnp.asarray(b.zero_bin))
            deb = _debundle(hb, tables, mapper.n_bins)
            np.testing.assert_allclose(np.asarray(direct), np.asarray(deb),
                                       rtol=1e-4, atol=2e-3)


class TestLosslessTraining:
    def _shallow_params(self):
        # shallow + strongly-separated gains, and a min_gain floor away
        # from zero: f32 ULP noise (the default-bin subtraction) turns
        # exact-zero gains into ±ε, which would flip the `gain > 0`
        # validity test right at the boundary
        return {"objective": "binary", "num_iterations": 8,
                "num_leaves": 4, "min_data_in_leaf": 20,
                "min_gain_to_split": 1e-3}

    def test_bundled_training_identical_shallow(self):
        dense, csr = make_exclusive()
        y = target_for(dense)
        b_off = train(dict(self._shallow_params(), enable_bundle=False),
                      csr, y)
        b_on = train(dict(self._shallow_params(), enable_bundle=True),
                     csr, y)
        np.testing.assert_array_equal(b_off.feats, b_on.feats)
        np.testing.assert_array_equal(b_off.thr_raw, b_on.thr_raw)
        np.testing.assert_allclose(b_off.leaf_values, b_on.leaf_values,
                                   rtol=1e-4, atol=1e-6)

    @staticmethod
    def _logloss(y, p):
        p = np.clip(p, 1e-7, 1 - 1e-7)
        return float(-(y * np.log(p) + (1 - y) * np.log(1 - p)).mean())

    def test_bundled_quality_parity_deep(self):
        # deep trees: ULP noise may flip near-tie splits, so pin QUALITY
        dense, csr = make_exclusive(seed=7)
        y = target_for(dense, seed=7)
        params = {"objective": "binary", "num_iterations": 15,
                  "num_leaves": 15, "min_data_in_leaf": 5}
        b_off = train(dict(params, enable_bundle=False), csr, y)
        b_on = train(dict(params, enable_bundle=True), csr, y)
        ll_off = self._logloss(y, b_off.predict(csr))
        ll_on = self._logloss(y, b_on.predict(csr))
        assert abs(ll_off - ll_on) < 0.01, (ll_off, ll_on)

    def test_bundled_goss_multiclass_quality(self):
        dense, csr = make_exclusive(n=300, seed=8)
        rng = np.random.default_rng(8)
        y = np.argmax(dense[:, :3] + rng.normal(0, 0.1, (300, 3)),
                      axis=1).astype(np.float64)
        params = {"objective": "multiclass", "num_class": 3,
                  "num_iterations": 6, "num_leaves": 7,
                  "min_data_in_leaf": 5}
        b_off = train(dict(params, enable_bundle=False), csr, y)
        b_on = train(dict(params, enable_bundle=True), csr, y)
        acc_off = (np.argmax(b_off.predict(csr), 1) == y).mean()
        acc_on = (np.argmax(b_on.predict(csr), 1) == y).mean()
        assert abs(acc_off - acc_on) < 0.05, (acc_off, acc_on)

    def test_bundled_data_parallel_matches_serial(self):
        import jax
        from jax.sharding import Mesh
        devs = np.array(jax.devices()[:4])
        mesh = Mesh(devs, ("data",))
        dense, csr = make_exclusive()
        y = target_for(dense)
        b_serial = train(dict(self._shallow_params(), enable_bundle=True),
                         csr, y)
        b_dp = train(dict(self._shallow_params(), enable_bundle=True,
                          tree_learner="data_parallel"), csr, y, mesh=mesh)
        np.testing.assert_array_equal(b_serial.feats, b_dp.feats)
        np.testing.assert_allclose(b_serial.leaf_values, b_dp.leaf_values,
                                   rtol=1e-4, atol=1e-6)

    def test_conflicting_bundles_still_learn(self):
        # allow conflicts: approximation, but the model must still learn
        rng = np.random.default_rng(9)
        dense = np.where(rng.random((500, 20)) < 0.12,
                         rng.normal(1, 1, (500, 20)), 0.0)
        csr = sp.csr_matrix(dense)
        y = (dense[:, 0] + dense[:, 1] > 0.5).astype(np.float64)
        b = train({"objective": "binary", "num_iterations": 30,
                   "num_leaves": 15, "min_data_in_leaf": 5,
                   "max_conflict_rate": 0.05}, csr, y)
        pred = b.predict(csr)
        auc_ok = ((pred[y == 1].mean() - pred[y == 0].mean()) > 0.2)
        assert auc_ok

    def test_dense_input_ignores_bundling(self):
        dense, _ = make_exclusive(n=200)
        y = target_for(dense)
        params = {"objective": "binary", "num_iterations": 5,
                  "num_leaves": 7, "min_data_in_leaf": 5}
        b_d = train(dict(params), dense, y)          # dense: no bundler
        assert b_d.num_trees == 5
