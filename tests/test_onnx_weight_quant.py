"""int8 weight-only quantization in ONNXModel (quantize='int8').

2-D float weights live in HBM as symmetric per-column int8 + scale and
dequantize on device. Weight-only: activations and accumulation stay in
compute_dtype, so outputs match full precision within quantization error.
Parity context: the reference reaches quantized execution through ORT's
quantization tooling + QLinear ops (run natively by this importer,
``tests/test_onnx_quant_detect.py``); weight-only int8 is the
TPU-shaped serving variant (HBM bandwidth, not int8 matmul units).
"""

import numpy as np

import mmlspark_tpu.onnx as O
from mmlspark_tpu.core import DataFrame, PipelineStage
from mmlspark_tpu.models.onnx_model import ONNXModel


def mlp_bytes(din=16, dhid=64, dout=8, seed=0):
    rng = np.random.default_rng(seed)
    w1 = rng.normal(0, 0.5, (din, dhid)).astype(np.float32)
    b1 = rng.normal(0, 0.1, dhid).astype(np.float32)
    w2 = rng.normal(0, 0.5, (dhid, dout)).astype(np.float32)
    nodes = [
        O.make_node("MatMul", ["x", "w1"], ["h0"]),
        O.make_node("Add", ["h0", "b1"], ["h1"]),
        O.make_node("Relu", ["h1"], ["h2"]),
        O.make_node("MatMul", ["h2", "w2"], ["logits"]),
    ]
    g = O.make_graph(
        nodes, "mlp",
        inputs=[O.make_tensor_value_info("x", np.float32, ["N", din])],
        outputs=[O.make_tensor_value_info("logits", np.float32,
                                          ["N", dout])],
        initializers={"w1": w1, "b1": b1, "w2": w2})
    return O.make_model(g)


def frame(n=32, din=16, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, din)).astype(np.float32)
    col = np.empty(n, dtype=object)
    col[:] = list(X)
    return DataFrame({"x": col})


class TestWeightQuant:
    def test_outputs_close_and_argmax_stable(self):
        df = frame()
        kw = dict(feed_dict={"x": "x"}, fetch_dict={"logits": "logits"})
        full = ONNXModel(mlp_bytes(), **kw)
        quant = ONNXModel(mlp_bytes(), quantize="int8", **kw)
        a = np.stack([np.asarray(v) for v in full.transform(df)["logits"]])
        b = np.stack([np.asarray(v) for v in quant.transform(df)["logits"]])
        # int8 symmetric error bound: well under the logit spread
        assert np.abs(a - b).max() < 0.05 * np.abs(a).max()
        assert (a.argmax(1) == b.argmax(1)).mean() > 0.9

    def test_params_actually_packed(self):
        m = ONNXModel(mlp_bytes(), quantize="int8",
                      feed_dict={"x": "x"}, fetch_dict={"logits": "logits"})
        m.transform(frame(8))
        packed = next(iter(m._device_params.values()))
        assert isinstance(packed["w1"], dict)
        assert np.asarray(packed["w1"]["q"]).dtype == np.int8
        # 1-D bias stays full precision
        assert not isinstance(packed["b1"], dict)

    def test_composes_with_weights_override(self):
        import io
        m = ONNXModel(mlp_bytes(), quantize="int8",
                      feed_dict={"x": "x"}, fetch_dict={"logits": "logits"})
        df = frame(16)
        base = np.stack([np.asarray(v)
                         for v in m.transform(df)["logits"]])
        # zero out w2 via override: quantized output must go to zero too
        w2 = np.zeros((64, 8), np.float32)
        buf = io.BytesIO()
        np.savez(buf, w2=w2)
        m.set(weights_override=buf.getvalue())
        out = np.stack([np.asarray(v) for v in m.transform(df)["logits"]])
        assert np.abs(out).max() < 1e-6
        assert np.abs(base).max() > 0.1

    def test_toggling_quantize_takes_effect(self):
        # set(quantize=...) after a transform must invalidate the cached
        # device params in BOTH directions
        df = frame(8)
        m = ONNXModel(mlp_bytes(), feed_dict={"x": "x"},
                      fetch_dict={"logits": "logits"})
        m.transform(df)
        m.set(quantize="int8")
        m.transform(df)
        packed = next(iter(m._device_params.values()))
        assert isinstance(packed["w1"], dict)
        m.set(quantize="")
        m.transform(df)
        unpacked = next(iter(m._device_params.values()))
        assert not isinstance(unpacked["w1"], dict)

    def test_save_load_roundtrip(self, tmp_path):
        df = frame(8)
        m = ONNXModel(mlp_bytes(), quantize="int8",
                      feed_dict={"x": "x"}, fetch_dict={"logits": "logits"})
        a = np.stack([np.asarray(v) for v in m.transform(df)["logits"]])
        m.save(str(tmp_path / "m"))
        loaded = PipelineStage.load(str(tmp_path / "m"))
        assert loaded.quantize == "int8"
        b = np.stack([np.asarray(v)
                      for v in loaded.transform(df)["logits"]])
        np.testing.assert_allclose(a, b, rtol=1e-6)
