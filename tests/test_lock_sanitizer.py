"""Runtime lock-order sanitizer: cycle detection with both stacks, hold
budgets, RLock/Condition correctness, the disabled-is-free identity, the
watchdog bundle table, and the serving chaos drill re-run instrumented.

Everything here is deterministic: cycles are created by taking locks in
opposite orders *sequentially* (the graph sees the order inversion without
any actual deadlock), and the chaos drill reuses the seeded scenario from
test_serving_distributed.py.
"""

import json
import os
import threading
import time

import pytest

from mmlspark_tpu.reliability import lock_sanitizer as ls


@pytest.fixture(autouse=True)
def _fresh_sanitizer():
    ls.reset()
    yield
    ls.reset()


# ---------------------------------------------------------------------------
# cycle detection


def test_two_lock_cycle_reported_with_both_stacks():
    ls.configure(enabled=True)
    a = ls.new_lock("t.A")
    b = ls.new_lock("t.B")

    def forward_order():
        with a:
            with b:
                pass

    def backward_order():
        with b:
            with a:
                pass

    forward_order()
    t = threading.Thread(target=backward_order, name="backward")
    t.start()
    t.join()

    reports = ls.cycle_reports()
    assert len(reports) == 1
    (rep,) = reports
    assert set(rep["sites"]) == {"t.A", "t.B"}
    # both stacks present: the edge that closed the cycle and the one
    # that established the opposite order earlier
    assert rep["forward"]["order"] == "t.B -> t.A"
    assert any("backward_order" in line for line in rep["forward"]["stack"])
    assert rep["reverse"][0]["order"] == "t.A -> t.B"
    assert any("forward_order" in line
               for line in rep["reverse"][0]["stack"])
    # the cycle surfaced in metrics too
    from mmlspark_tpu.observability.registry import snapshot
    series = snapshot()["mmlspark_lock_order_cycles_total"]["series"]
    assert series and series[0]["value"] >= 1.0


def test_consistent_order_reports_nothing():
    ls.configure(enabled=True)
    a = ls.new_lock("t.A")
    b = ls.new_lock("t.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert ls.cycle_reports() == []


def test_three_lock_cycle_detected_via_path():
    # A->B, B->C, then C->A closes a length-3 cycle no pair check sees
    ls.configure(enabled=True)
    a, b, c = (ls.new_lock(s) for s in ("t3.A", "t3.B", "t3.C"))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:
            pass
    reports = ls.cycle_reports()
    assert len(reports) == 1
    assert set(reports[0]["sites"]) == {"t3.A", "t3.B", "t3.C"}


# ---------------------------------------------------------------------------
# hold budget


def test_long_hold_lands_in_metric_and_report():
    san = ls.configure(enabled=True, hold_budget=0.05)
    lock = ls.new_lock("t.slow")
    with lock:
        time.sleep(0.08)
    (rec,) = san.long_hold_reports()
    assert rec["site"] == "t.slow" and rec["held_seconds"] >= 0.05
    assert rec["stack"]   # where the hold started
    from mmlspark_tpu.observability.registry import snapshot
    snap = snapshot()["mmlspark_lock_held_seconds"]
    (series,) = [s for s in snap["series"]
                 if s["labels"].get("site") == "t.slow"]
    assert series["count"] == 1 and series["sum"] >= 0.05


def test_short_holds_stay_out_of_the_metric():
    san = ls.configure(enabled=True, hold_budget=10.0)
    lock = ls.new_lock("t.fast")
    for _ in range(50):
        with lock:
            pass
    assert san.long_hold_reports() == []


# ---------------------------------------------------------------------------
# re-entrant RLock + Condition correctness


def test_rlock_reentrancy_books_outermost_only():
    san = ls.configure(enabled=True)
    r = ls.new_rlock("t.R")
    with r:
        with r:
            assert r._is_owned()
            held = san.held_by_thread()
            (entries,) = held.values()
            assert [e["site"] for e in entries] == ["t.R"]
        assert r.locked()
    assert not r.locked()
    assert san.held_by_thread() == {}
    # re-acquiring the same lock is not an order edge
    assert ls.cycle_reports() == []


def test_condition_on_sanitized_rlock_wait_notify():
    ls.configure(enabled=True)
    cond = ls.new_condition("t.C")
    woke = []

    def waiter():
        with cond:
            woke.append(cond.wait(timeout=5.0))

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        with cond:
            if cond._waiters:         # waiter parked → lock released
                cond.notify_all()
                break
        time.sleep(0.01)
    t.join(timeout=5.0)
    assert woke == [True]


def test_release_from_non_owner_raises():
    ls.configure(enabled=True)
    r = ls.new_rlock("t.R2")
    with pytest.raises(RuntimeError):
        r.release()


# ---------------------------------------------------------------------------
# disabled = identity


def test_disabled_factories_return_plain_primitives():
    ls.configure(enabled=False)
    assert type(ls.new_lock("x")) is type(threading.Lock())
    assert type(ls.new_rlock("x")) is type(threading.RLock())
    assert isinstance(ls.new_condition("x"), threading.Condition)
    assert ls.cycle_reports() == []
    assert ls.held_by_thread() == {}


def test_env_var_enables(monkeypatch):
    monkeypatch.setenv(ls.SANITIZER_ENV, "1")
    ls.reset()
    assert ls.enabled()
    assert isinstance(ls.new_lock("x"), ls.SanitizedLock)
    monkeypatch.setenv(ls.SANITIZER_ENV, "0")
    ls.reset()
    assert not ls.enabled()


# ---------------------------------------------------------------------------
# watchdog bundle integration


def test_watchdog_bundle_carries_locks_held_table(tmp_path):
    from mmlspark_tpu.observability.watchdog import Watchdog

    ls.configure(enabled=True)
    lock = ls.new_lock("t.bundle")
    clock = {"t": 0.0}
    wd = Watchdog(enabled=True, diag_dir=str(tmp_path),
                  default_budget=1.0, clock=lambda: clock["t"])
    lock.acquire()
    try:
        with wd.watch("probe"):
            clock["t"] = 10.0
            (record,) = wd.scan_once()
    finally:
        lock.release()
        wd.stop()
    bundle = json.loads(open(record["bundle"]).read())
    table = bundle["locks_held"]
    assert any(e["site"] == "t.bundle"
               for entries in table.values() for e in entries)


# ---------------------------------------------------------------------------
# the serving chaos drill, instrumented


def test_chaos_drill_under_sanitizer_reports_zero_cycles(monkeypatch):
    """Acceptance: the 3-worker kill/re-register drill from
    test_serving_distributed.py runs with MMLSPARK_TPU_LOCK_SANITIZER=1
    and the dynamic acquisition graph stays acyclic — every lock the
    serving plane takes nests in one global order."""
    monkeypatch.setenv(ls.SANITIZER_ENV, "1")
    ls.reset()
    assert ls.enabled()
    from tests.test_serving_distributed import (
        test_chaos_faults_and_worker_restart_complete_every_request)

    try:
        test_chaos_faults_and_worker_restart_complete_every_request()

        assert ls.cycle_reports() == [], (
            "lock-order cycles under chaos:\n" + "\n".join(
                " -> ".join(r["sites"]) for r in ls.cycle_reports()))
    finally:
        # the drill sandboxes global state BEFORE it runs, not after (its
        # home module runs late in the alphabet); this file runs early, so
        # scrub the breakers/faults/metrics it leaves open — later suites
        # assert /healthz is "ok", not "degraded"
        from mmlspark_tpu import observability as obs
        from mmlspark_tpu.reliability import get_injector, reset_breakers
        obs.reset_all()
        reset_breakers()
        get_injector().clear()
