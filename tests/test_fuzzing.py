"""Registry-driven stage fuzzing + reflective coverage enforcement.

Parity surface: the reference's root-module ``FuzzingTest``
(``src/test/scala/.../core/test/fuzzing/FuzzingTest.scala``): reflectively
load every PipelineStage in the package and FAIL if any concrete stage has
neither a fuzzing TestObject nor an explicit exemption. Each registered
stage runs the experiment fuzzer (execution determinism) and the
serialization fuzzer (save/load round-trips) from ``fuzzing.py``.
"""

import importlib
import pkgutil

import numpy as np
import pytest

import mmlspark_tpu
from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.dataframe import object_col
from mmlspark_tpu.core.pipeline import Model, PipelineStage

from fuzzing import TestObject, experiment_fuzz, serialization_fuzz

# ---------------------------------------------------------------------------
# shared tiny frames
# ---------------------------------------------------------------------------

_RNG = np.random.default_rng(1234)


def _vec_col(X):
    out = np.empty(len(X), dtype=object)
    for i, r in enumerate(X):
        out[i] = np.asarray(r, dtype=np.float64)
    return out


def tab_df(n=24):
    rng = np.random.default_rng(7)
    X = rng.normal(0, 1, (n, 3))
    y = (X[:, 0] > 0).astype(np.float64)
    return DataFrame({
        "features": _vec_col(X),
        "num": X[:, 1].copy(),
        "num2": X[:, 2].copy(),
        "label": y,
        "cat": np.array(["a", "b"] * (n // 2), dtype=object),
        "text": np.array(["red fox jumps", "lazy dog sleeps"] * (n // 2),
                         dtype=object),
        "lst": object_col([[1, 2], [3]] * (n // 2)),
    })


def reco_df():
    rows = [(u, i) for u in range(4) for i in (0, 1)] + \
           [(u, i) for u in range(4, 8) for i in (2, 3)]
    return DataFrame({"user": [r[0] for r in rows],
                      "item": [r[1] for r in rows],
                      "rating": [1.0] * len(rows)})


def img_df(n=2, h=16, w=16):
    from mmlspark_tpu.image import make_image
    rng = np.random.default_rng(3)
    return DataFrame({"image": object_col(
        [make_image(rng.integers(0, 255, (h, w, 3)).astype(np.uint8),
                    origin=f"img{i}") for i in range(n)])})


def bin_img_df(n=2):
    from mmlspark_tpu.image import encode_image, make_image
    rng = np.random.default_rng(3)
    return DataFrame({"binary": object_col(
        [encode_image(make_image(rng.integers(0, 255, (8, 8, 3))
                                 .astype(np.uint8))) for _ in range(n)])})


def vw_df():
    from mmlspark_tpu.vw import VowpalWabbitFeaturizer
    df = tab_df()
    f = VowpalWabbitFeaturizer(input_cols=["text"], string_split_cols=["text"],
                               num_bits=12)
    return f.transform(df)


def scored_df():
    from mmlspark_tpu.models.linear import LogisticRegression
    df = tab_df()
    return LogisticRegression(max_iter=30).fit(df).transform(df)


def _fitted_lr():
    from mmlspark_tpu.models.linear import LogisticRegression
    df = tab_df()
    m = LogisticRegression(max_iter=30).fit(df)
    m.set(features_col="features")
    return m


# ---------------------------------------------------------------------------
# the registry: {class: factory() -> TestObject}
# ---------------------------------------------------------------------------

def _registry():
    from mmlspark_tpu.automl.hyperparam import (DiscreteHyperParam,
                                                HyperparamBuilder, RandomSpace)
    from mmlspark_tpu.automl.tune import FindBestModel, TuneHyperparameters
    from mmlspark_tpu.cyber import (AccessAnomaly as CyAccessAnomaly,
                                    ComplementAccessTransformer as CyComplement,
                                    DataFactory,
                                    IdIndexer as CyIdIndexer,
                                    LinearScalarScaler as CyLinearScaler,
                                    MultiIndexer as CyMultiIndexer,
                                    StandardScalarScaler as CyStandardScaler)
    from mmlspark_tpu.explainers.ice import ICETransformer
    from mmlspark_tpu.explainers.lime import (ImageLIME, TabularLIME,
                                              TextLIME, VectorLIME)
    from mmlspark_tpu.explainers.shap import (ImageSHAP, TabularSHAP,
                                              TextSHAP, VectorSHAP)
    from mmlspark_tpu.exploratory.balance import (AggregateBalanceMeasure,
                                                  DistributionBalanceMeasure,
                                                  FeatureBalanceMeasure)
    from mmlspark_tpu.featurize.clean_missing import CleanMissingData
    from mmlspark_tpu.featurize.count_selector import CountSelector
    from mmlspark_tpu.featurize.data_conversion import DataConversion
    from mmlspark_tpu.featurize.featurize import Featurize, VectorAssembler
    from mmlspark_tpu.featurize.tokenizer import BertTokenizer
    from mmlspark_tpu.featurize.text import (IDF, HashingTF, MultiNGram,
                                             NGram, PageSplitter,
                                             TextFeaturizer, Tokenizer)
    from mmlspark_tpu.featurize.value_indexer import IndexToValue, ValueIndexer
    from mmlspark_tpu.explainers.superpixel import SuperpixelTransformer
    from mmlspark_tpu.image.augment import ImageSetAugmenter
    from mmlspark_tpu.image.transforms import ImageTransformer, ResizeImage
    from mmlspark_tpu.image.unroll import (ResizeImageTransformer,
                                           UnrollBinaryImage, UnrollImage)
    from mmlspark_tpu.io.http.http_transformer import (HTTPTransformer,
                                                       SimpleHTTPTransformer)
    from mmlspark_tpu.io.http.parsers import (CustomInputParser,
                                              CustomOutputParser,
                                              JSONInputParser,
                                              JSONOutputParser,
                                              StringOutputParser)
    from mmlspark_tpu.isolationforest.iforest import IsolationForest
    from mmlspark_tpu.models.gbdt.estimators import (LightGBMClassifier,
                                                     LightGBMRanker,
                                                     LightGBMRegressor)
    from mmlspark_tpu.models.linear import LinearRegression, LogisticRegression
    from mmlspark_tpu.nn.knn import KNN, ConditionalKNN
    from mmlspark_tpu.recommendation.ranking import (RankingAdapter,
                                                     RankingEvaluator,
                                                     RankingTrainValidationSplit,
                                                     RecommendationIndexer)
    from mmlspark_tpu.recommendation.sar import SAR
    from mmlspark_tpu.serving.source import MakeReply, ParseRequest
    from mmlspark_tpu.stages.batching import (DynamicMiniBatchTransformer,
                                              FixedMiniBatchTransformer,
                                              FlattenBatch,
                                              TimeIntervalMiniBatchTransformer)
    from mmlspark_tpu.stages import misc as M
    from mmlspark_tpu.train.metrics import (ComputeModelStatistics,
                                            ComputePerInstanceStatistics)
    from mmlspark_tpu.train.train import TrainClassifier, TrainRegressor
    from mmlspark_tpu.vw import (VowpalWabbitClassifier,
                                 VowpalWabbitContextualBandit,
                                 VowpalWabbitFeaturizer,
                                 VowpalWabbitInteractions,
                                 VowpalWabbitRegressor)

    from mmlspark_tpu.models.onnx_estimator import ONNXEstimator

    df = tab_df()

    def _tiny_onnx_mlp():
        import mmlspark_tpu.onnx as O
        rng = np.random.default_rng(5)
        w = rng.normal(0, 0.5, (3, 2)).astype(np.float32)
        g = O.make_graph(
            [O.make_node("MatMul", ["x", "w"], ["logits"]),
             O.make_node("SoftmaxCrossEntropyLoss", ["logits", "labels"],
                         ["loss"])],
            "tiny",
            inputs=[O.make_tensor_value_info("x", np.float32, ["N", 3]),
                    O.make_tensor_value_info("labels", np.int64, ["N"])],
            outputs=[O.make_tensor_value_info("loss", np.float32, []),
                     O.make_tensor_value_info("logits", np.float32,
                                              ["N", 2])],
            initializers={"w": w})
        return O.make_model(g)

    def onnx_train_df():
        rng = np.random.default_rng(6)
        X = rng.normal(0, 1, (24, 3)).astype(np.float32)
        return DataFrame({"features": _vec_col(X),
                          "label": (X[:, 0] > 0).astype(np.int64)})

    def gbdt_rank_df():
        rng = np.random.default_rng(8)
        X = rng.normal(0, 1, (24, 3))
        return DataFrame({"features": _vec_col(X),
                          "label": rng.integers(0, 3, 24).astype(np.float64),
                          "group": np.repeat([0, 1, 2], 8)})

    def batched():
        return FixedMiniBatchTransformer(batch_size=4).transform(
            df.select(["num", "label"]))

    def space():
        return (HyperparamBuilder()
                .add_hyperparam("max_iter", DiscreteHyperParam([20, 40]))
                .build())

    # contextual bandit frame (hashed by hand, tiny)
    from mmlspark_tpu.vw.featurizer import NUM_BITS_KEY, sparse_column
    sh = sparse_column([(np.array([5], np.uint32), np.array([1.], np.float32))
                        for _ in range(8)])
    acts = sparse_column([[(np.array([9], np.uint32), np.array([1.], np.float32)),
                           (np.array([11], np.uint32), np.array([1.], np.float32))]
                          for _ in range(8)])
    bandit_df = DataFrame({"shared": sh, "features": acts,
                           "chosenAction": np.array([1, 2] * 4),
                           "label": np.array([0.1, 0.9] * 4, np.float32),
                           "probability": np.full(8, 0.5, np.float32)})
    bandit_df = bandit_df.with_column_metadata("features", {NUM_BITS_KEY: 10})

    R = {
        # featurize
        CleanMissingData: lambda: TestObject(
            CleanMissingData(["num"], ["num_clean"]),
            fit_df=df.with_column("num", np.where(df["num"] > 0, np.nan,
                                                  df["num"]))),
        CountSelector: lambda: TestObject(
            CountSelector(input_col="features", output_col="sel"), fit_df=df),
        DataConversion: lambda: TestObject(
            DataConversion(input_cols=["num"], convert_to="integer"),
            transform_df=df),
        Featurize: lambda: TestObject(Featurize(["num", "cat"]), fit_df=df),
        Tokenizer: lambda: TestObject(
            Tokenizer(input_col="text", output_col="toks"), transform_df=df),
        NGram: lambda: TestObject(
            NGram(input_col="toks", output_col="grams", n=2),
            transform_df=Tokenizer(input_col="text", output_col="toks")
            .transform(df)),
        MultiNGram: lambda: TestObject(
            MultiNGram(input_col="toks", output_col="grams", lengths=[1, 2]),
            transform_df=Tokenizer(input_col="text", output_col="toks")
            .transform(df)),
        HashingTF: lambda: TestObject(
            HashingTF(input_col="toks", output_col="tf", num_features=32),
            transform_df=Tokenizer(input_col="text", output_col="toks")
            .transform(df)),
        IDF: lambda: TestObject(
            IDF(input_col="tf", output_col="tfidf"),
            fit_df=HashingTF(input_col="toks", output_col="tf",
                             num_features=32).transform(
                Tokenizer(input_col="text", output_col="toks").transform(df))),
        TextFeaturizer: lambda: TestObject(
            TextFeaturizer(input_col="text", output_col="features2",
                           num_features=32), fit_df=df),
        PageSplitter: lambda: TestObject(
            PageSplitter(input_col="text", output_col="pages",
                         maximum_page_length=8), transform_df=df),
        BertTokenizer: lambda: TestObject(
            BertTokenizer(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
                           "a", "b", "##a"],
                          input_col="text", max_len=8), transform_df=df),
        ValueIndexer: lambda: TestObject(
            ValueIndexer(input_col="cat", output_col="idx"), fit_df=df),
        VectorAssembler: lambda: TestObject(
            VectorAssembler(input_cols=["num", "features"],
                            output_col="assembled"), transform_df=df),
        IndexToValue: lambda: TestObject(
            IndexToValue(input_col="idx", output_col="orig"),
            transform_df=ValueIndexer(input_col="cat", output_col="idx")
            .fit(df).transform(df)),
        # batching
        FixedMiniBatchTransformer: lambda: TestObject(
            FixedMiniBatchTransformer(batch_size=4),
            transform_df=df.select(["num", "label"])),
        DynamicMiniBatchTransformer: lambda: TestObject(
            DynamicMiniBatchTransformer(max_batch_size=4),
            transform_df=df.select(["num", "label"])),
        TimeIntervalMiniBatchTransformer: lambda: TestObject(
            TimeIntervalMiniBatchTransformer(millis_to_wait=1000),
            transform_df=df.select(["num"]), experiment=False),
        FlattenBatch: lambda: TestObject(FlattenBatch(),
                                         transform_df=batched()),
        # misc stages
        M.Cacher: lambda: TestObject(M.Cacher(), transform_df=df),
        M.DropColumns: lambda: TestObject(M.DropColumns(cols=["cat"]),
                                          transform_df=df),
        M.SelectColumns: lambda: TestObject(
            M.SelectColumns(cols=["num", "label"]), transform_df=df),
        M.RenameColumn: lambda: TestObject(
            M.RenameColumn(input_col="num", output_col="renamed"),
            transform_df=df),
        M.Repartition: lambda: TestObject(M.Repartition(n=2), transform_df=df),
        M.Explode: lambda: TestObject(
            M.Explode(input_col="lst", output_col="x"), transform_df=df),
        M.Lambda: lambda: TestObject(
            M.Lambda(transform_fn=lambda d: d.with_column(
                "doubled", d["num"] * 2)),
            transform_df=df, roundtrip_behavior=False),
        M.UDFTransformer: lambda: TestObject(
            M.UDFTransformer(input_col="num", output_col="sq",
                             udf=lambda v: v * v),
            transform_df=df, roundtrip_behavior=False),
        M.MultiColumnAdapter: lambda: TestObject(
            M.MultiColumnAdapter(
                base_stage=M.UnicodeNormalize(),
                input_cols=["text", "cat"], output_cols=["t2", "c2"]),
            transform_df=df),
        M.ClassBalancer: lambda: TestObject(
            M.ClassBalancer(input_col="label", output_col="w"), fit_df=df),
        M.EnsembleByKey: lambda: TestObject(
            M.EnsembleByKey(keys=["cat"], cols=["num"]), transform_df=df),
        M.PartitionConsolidator: lambda: TestObject(
            M.PartitionConsolidator(), transform_df=df),
        M.StratifiedRepartition: lambda: TestObject(
            M.StratifiedRepartition(label_col="label", seed=0),
            transform_df=df),
        M.SummarizeData: lambda: TestObject(M.SummarizeData(),
                                            transform_df=df),
        M.TextPreprocessor: lambda: TestObject(
            M.TextPreprocessor(input_col="text", output_col="clean",
                               map={"fox": "cat"}), transform_df=df),
        M.Timer: lambda: TestObject(
            M.Timer(stage=M.ClassBalancer(input_col="label", output_col="w")),
            fit_df=df),
        M.UnicodeNormalize: lambda: TestObject(
            M.UnicodeNormalize(input_col="text", output_col="norm"),
            transform_df=df),
        # train / automl
        TrainClassifier: lambda: TestObject(
            TrainClassifier(model=LogisticRegression(max_iter=30)),
            fit_df=df.select(["num", "num2", "label"])),
        TrainRegressor: lambda: TestObject(
            TrainRegressor(model=LinearRegression(max_iter=30)),
            fit_df=df.select(["num", "num2", "label"])),
        ComputeModelStatistics: lambda: TestObject(
            ComputeModelStatistics(label_col="label"),
            transform_df=scored_df()),
        ComputePerInstanceStatistics: lambda: TestObject(
            ComputePerInstanceStatistics(label_col="label"),
            transform_df=scored_df()),
        # search spaces are in-memory objects (not stages); save/load of a
        # configured tuner is not part of the parity surface
        TuneHyperparameters: lambda: TestObject(
            TuneHyperparameters(model=LogisticRegression(),
                                search_space=RandomSpace(space(), seed=3),
                                number_of_iterations=2,
                                evaluation_metric="accuracy",
                                label_col="label", parallelism=1),
            fit_df=df, serialization=False),
        FindBestModel: lambda: TestObject(
            FindBestModel([_fitted_lr()], label_col="label"), fit_df=df),
        # learners
        LogisticRegression: lambda: TestObject(
            LogisticRegression(max_iter=30), fit_df=df),
        LinearRegression: lambda: TestObject(
            LinearRegression(max_iter=30, label_col="num"), fit_df=df),
        LightGBMClassifier: lambda: TestObject(
            LightGBMClassifier(num_iterations=3, num_leaves=4,
                               min_data_in_leaf=2), fit_df=df),
        LightGBMRegressor: lambda: TestObject(
            LightGBMRegressor(num_iterations=3, num_leaves=4,
                              min_data_in_leaf=2, label_col="num"),
            fit_df=df),
        LightGBMRanker: lambda: TestObject(
            LightGBMRanker(num_iterations=3, num_leaves=4,
                           min_data_in_leaf=2), fit_df=gbdt_rank_df()),
        ONNXEstimator: lambda: TestObject(
            ONNXEstimator(_tiny_onnx_mlp(),
                          feed_dict={"x": "features"},
                          fetch_dict={"out": "logits"},
                          loss_output="loss", label_input="labels",
                          epochs=2, batch_size=8, learning_rate=0.05),
            fit_df=onnx_train_df()),
        # vw
        VowpalWabbitFeaturizer: lambda: TestObject(
            VowpalWabbitFeaturizer(input_cols=["text", "num"],
                                   string_split_cols=["text"], num_bits=12),
            transform_df=df),
        VowpalWabbitInteractions: lambda: TestObject(
            VowpalWabbitInteractions(input_cols=["features", "features"],
                                     output_col="inter", num_bits=12),
            transform_df=vw_df()),
        VowpalWabbitClassifier: lambda: TestObject(
            VowpalWabbitClassifier(num_passes=2, use_all_reduce=False),
            fit_df=vw_df()),
        VowpalWabbitRegressor: lambda: TestObject(
            VowpalWabbitRegressor(num_passes=2, label_col="num",
                                  use_all_reduce=False), fit_df=vw_df()),
        VowpalWabbitContextualBandit: lambda: TestObject(
            VowpalWabbitContextualBandit(num_passes=2), fit_df=bandit_df),
        # nn / reco / iforest / balance
        KNN: lambda: TestObject(
            KNN(k=2), fit_df=df.with_column("values",
                                            np.arange(len(df)))),
        ConditionalKNN: lambda: TestObject(
            ConditionalKNN(k=2),
            fit_df=df.with_column("values", np.arange(len(df)))
                     .with_column("labels", df["cat"]),
            transform_df=DataFrame({
                "features": df["features"][:3],
                "conditioner": object_col([["a"], ["b"], ["a", "b"]])})),
        SAR: lambda: TestObject(SAR(support_threshold=1), fit_df=reco_df(),
                                transform_df=reco_df()),
        RecommendationIndexer: lambda: TestObject(
            RecommendationIndexer(),
            fit_df=DataFrame({"user": ["u1", "u2"], "item": ["a", "b"]})),
        RankingAdapter: lambda: TestObject(
            RankingAdapter(recommender=SAR(support_threshold=1), k=2),
            fit_df=reco_df()),
        RankingTrainValidationSplit: lambda: TestObject(
            RankingTrainValidationSplit(recommender=SAR(support_threshold=1),
                                        train_ratio=0.7, k=2, seed=0),
            fit_df=reco_df()),
        RankingEvaluator: lambda: TestObject(
            RankingEvaluator(k=2),
            transform_df=DataFrame({
                "recommendations": object_col([[1, 2], [3, 4]]),
                "labels": object_col([[1], [9]])})),
        IsolationForest: lambda: TestObject(
            IsolationForest(num_estimators=8, max_samples=8), fit_df=df),
        FeatureBalanceMeasure: lambda: TestObject(
            FeatureBalanceMeasure(sensitive_cols=["cat"], label_col="label"),
            transform_df=df),
        DistributionBalanceMeasure: lambda: TestObject(
            DistributionBalanceMeasure(sensitive_cols=["cat"]),
            transform_df=df),
        AggregateBalanceMeasure: lambda: TestObject(
            AggregateBalanceMeasure(sensitive_cols=["cat"]),
            transform_df=df),
        # explainers
        TabularLIME: lambda: TestObject(
            TabularLIME(model=_fitted_lr(), target_col="probability",
                        target_classes=[0], input_cols=["num", "num2"],
                        num_samples=16, seed=0),
            transform_df=df.head(2), experiment=False),
        TabularSHAP: lambda: TestObject(
            TabularSHAP(model=_fitted_lr(), target_col="probability",
                        target_classes=[0], input_cols=["num", "num2"],
                        num_samples=16, seed=0),
            transform_df=df.head(2), experiment=False),
        VectorLIME: lambda: TestObject(
            VectorLIME(model=_fitted_lr(), target_col="probability",
                       target_classes=[0], input_col="features",
                       num_samples=16, seed=0),
            transform_df=df.head(2), experiment=False),
        VectorSHAP: lambda: TestObject(
            VectorSHAP(model=_fitted_lr(), target_col="probability",
                       target_classes=[0], input_col="features",
                       num_samples=16, seed=0),
            transform_df=df.head(2), experiment=False),
        TextLIME: lambda: TestObject(TextLIME(), experiment=False),
        TextSHAP: lambda: TestObject(TextSHAP(), experiment=False),
        ImageLIME: lambda: TestObject(ImageLIME(), experiment=False),
        ImageSHAP: lambda: TestObject(ImageSHAP(), experiment=False),
        ICETransformer: lambda: TestObject(
            ICETransformer(model=_fitted_lr(), target_col="probability",
                           target_classes=[0], numeric_features=["num"],
                           num_splits=3),
            transform_df=df.head(2), experiment=False),
        # image
        ImageTransformer: lambda: TestObject(
            ImageTransformer(stages=[ResizeImage(height=8, width=8)]),
            transform_df=img_df()),
        ResizeImageTransformer: lambda: TestObject(
            ResizeImageTransformer(height=8, width=8), transform_df=img_df()),
        UnrollImage: lambda: TestObject(UnrollImage(), transform_df=img_df()),
        UnrollBinaryImage: lambda: TestObject(
            UnrollBinaryImage(input_col="binary", height=8, width=8),
            transform_df=bin_img_df()),
        ImageSetAugmenter: lambda: TestObject(ImageSetAugmenter(),
                                              transform_df=img_df()),
        SuperpixelTransformer: lambda: TestObject(
            SuperpixelTransformer(input_col="image", cell_size=4),
            transform_df=img_df()),
        # io/http parsers & transformers (serialization only: need a server)
        JSONInputParser: lambda: TestObject(
            JSONInputParser(url="http://localhost:1/x", input_col="num",
                            output_col="req"),
            transform_df=df, experiment=False),
        JSONOutputParser: lambda: TestObject(
            JSONOutputParser(input_col="resp", output_col="out"),
            experiment=False),
        StringOutputParser: lambda: TestObject(
            StringOutputParser(input_col="resp", output_col="out"),
            experiment=False),
        CustomInputParser: lambda: TestObject(
            CustomInputParser(input_col="num", output_col="req",
                              udf=lambda v: None),
            experiment=False),
        CustomOutputParser: lambda: TestObject(
            CustomOutputParser(input_col="resp", output_col="out",
                               udf=lambda v: None),
            experiment=False),
        HTTPTransformer: lambda: TestObject(
            HTTPTransformer(input_col="req", output_col="resp"),
            experiment=False),
        SimpleHTTPTransformer: lambda: TestObject(
            SimpleHTTPTransformer(
                input_col="num", output_col="out",
                input_parser=JSONInputParser(url="http://localhost:1/x")),
            experiment=False),
        # cyber
        CyIdIndexer: lambda: TestObject(
            CyIdIndexer(input_col="cat", output_col="cidx",
                        partition_key="cat"), fit_df=df),
        CyMultiIndexer: lambda: TestObject(
            CyMultiIndexer([CyIdIndexer(input_col="cat", output_col="cidx")]),
            fit_df=df),
        CyStandardScaler: lambda: TestObject(
            CyStandardScaler(input_col="num", output_col="z"), fit_df=df),
        CyLinearScaler: lambda: TestObject(
            CyLinearScaler(input_col="num", output_col="s",
                           min_required_value=1.0, max_required_value=2.0),
            fit_df=df),
        CyComplement: lambda: TestObject(
            CyComplement(indexed_col_names=["iu", "ir"],
                         complementset_factor=2, seed=0),
            transform_df=DataFrame({"iu": np.array([1, 2, 3]),
                                    "ir": np.array([1, 2, 3])})),
        CyAccessAnomaly: lambda: TestObject(
            CyAccessAnomaly(rank_param=3, max_iter=4, seed=0),
            fit_df=DataFactory(num_hr_users=4, num_hr_resources=5,
                               num_fin_users=4, num_fin_resources=5,
                               seed=1).create_clustered_training_data(0.5)),
        # serving
        ParseRequest: lambda: TestObject(ParseRequest(), experiment=False),
        MakeReply: lambda: TestObject(MakeReply(value_col="out"),
                                      experiment=False),
    }

    # service transformers: constructible with a URL; behavior is covered by
    # the mock-server suite (test_services.py), so serialization-only here
    from mmlspark_tpu.services import anomaly as SA, face as SF, form as SFo, \
        search as SSe, text as ST, translate as STr, vision as SV

    def _svc(cls, **kw):
        return lambda: TestObject(cls(url="http://localhost:1/x", **kw),
                                  experiment=False)

    from mmlspark_tpu.services import geospatial as SG, mvad as SM, \
        speech as SSp

    # FormOntologyLearner runs fully locally → full experiment fuzz
    forms = object_col([
        {"analyzeResult": {"documentResults": [{"fields": {
            "Total": {"type": "number", "valueNumber": 1.0}}}]}}])
    R[SFo.FormOntologyLearner] = lambda: TestObject(
        SFo.FormOntologyLearner(input_col="form", output_col="onto"),
        fit_df=DataFrame({"form": forms}))
    R[SM.FitMultivariateAnomaly] = lambda: TestObject(
        SM.FitMultivariateAnomaly(url="http://localhost:1/x"),
        experiment=False)
    for cls in (ST.TextSentiment, ST.LanguageDetector, ST.EntityDetector,
                ST.KeyPhraseExtractor, ST.NER, ST.PII, ST.TextAnalyze,
                ST.Healthcare, ST.TextSentimentSDK, ST.LanguageDetectorSDK,
                ST.EntityDetectorSDK, ST.NERSDK, ST.KeyPhraseExtractorSDK,
                ST.PIISDK, ST.HealthcareSDK,
                SV.AnalyzeImage, SV.DescribeImage, SV.OCR, SV.TagImage,
                SV.RecognizeText, SV.ReadImage,
                SV.RecognizeDomainSpecificContent,
                SF.DetectFace, SF.GroupFaces, SF.IdentifyFaces,
                SF.VerifyFaces, SF.FindSimilarFace,
                SFo.AnalyzeInvoices, SFo.AnalyzeLayout, SFo.AnalyzeReceipts,
                SFo.AnalyzeBusinessCards, SFo.AnalyzeIDDocuments,
                SFo.ListCustomModels, SFo.GetCustomModel,
                SFo.AnalyzeCustomModel,
                STr.Translate, STr.Transliterate, STr.BreakSentence,
                STr.DetectLanguage, STr.DictionaryLookup,
                STr.DictionaryExamples,
                SSe.BingImageSearch,
                SA.DetectAnomalies, SA.DetectLastAnomaly,
                SA.SimpleDetectAnomalies,
                SSp.SpeechToText, SSp.SpeechToTextSDK, SSp.TextToSpeech,
                SSp.ConversationTranscription, SSe.AddDocuments,
                SG.AddressGeocoder, SG.ReverseAddressGeocoder,
                SG.CheckPointInPolygon, STr.DocumentTranslator):
        R[cls] = _svc(cls)
    R[SV.GenerateThumbnails] = _svc(SV.GenerateThumbnails, width=32,
                                    height=32)
    # streaming speech: experiment-fuzzed against a live fake ASR server in
    # test_speech_streaming; serialization-only here (url is ws://)
    from mmlspark_tpu.services.speech_streaming import SpeechToTextStreaming
    R[SpeechToTextStreaming] = lambda: TestObject(
        SpeechToTextStreaming(url="ws://localhost:1/x"), experiment=False)
    return R


#: concrete stages intentionally NOT fuzzed, with the reason
EXEMPTIONS = {
    "Pipeline": "exercised by every serialization fuzz (wrapping pipeline)",
    "PipelineModel": "produced & fuzzed via Pipeline fit round-trips",
}


def _all_stage_classes():
    for m in pkgutil.walk_packages(mmlspark_tpu.__path__, "mmlspark_tpu."):
        importlib.import_module(m.name)
    seen = {}
    import gc  # noqa: F401  (classes already imported above)
    def walk(cls):
        for sub in cls.__subclasses__():
            if sub.__module__.startswith("mmlspark_tpu"):
                seen[sub] = True
            walk(sub)
    walk(PipelineStage)
    return sorted(seen, key=lambda c: f"{c.__module__}.{c.__qualname__}")


def _is_abstract_base(cls) -> bool:
    name = cls.__qualname__
    if name.startswith("_") or name in ("Transformer", "DeviceTransformer",
                                        "Estimator", "Model"):
        return True
    # family bases that subclasses specialize
    if any(c.__qualname__ == name for c in ()):  # placeholder
        return False
    return name in ("LocalExplainer", "ServiceTransformer", "HasAsyncReply",
                    "TextAnalyticsBase", "VisionBase", "TranslatorBase",
                    "FormRecognizerBase", "AnomalyBase", "HTTPInputParser",
                    "HTTPOutputParser")


def test_every_stage_is_fuzzed_or_exempt():
    """The FuzzingTest coverage gate: unregistered concrete stages fail."""
    reg = _registry()
    missing = []
    for cls in _all_stage_classes():
        if _is_abstract_base(cls):
            continue
        if issubclass(cls, Model):
            continue  # models are fuzzed through their estimator's fit
        if cls in reg or cls.__qualname__ in EXEMPTIONS:
            continue
        missing.append(f"{cls.__module__}.{cls.__qualname__}")
    assert not missing, (
        "stages without a fuzzing TestObject or exemption:\n  "
        + "\n  ".join(missing))


_REG = _registry()
_IDS = sorted(_REG, key=lambda c: c.__qualname__)


@pytest.mark.parametrize("cls", _IDS, ids=[c.__qualname__ for c in _IDS])
def test_stage_fuzzing(cls, tmp_path):
    obj = _REG[cls]()
    if obj.experiment and (obj.fit_df is not None
                           or obj.transform_df is not None):
        experiment_fuzz(obj)
    if obj.serialization:
        serialization_fuzz(obj, tmp_path)
