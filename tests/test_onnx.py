import numpy as np
import pytest

import mmlspark_tpu.onnx as O
from mmlspark_tpu.core import DataFrame, PipelineStage


def mlp_model(din=8, dhid=16, dout=3, seed=0):
    rng = np.random.default_rng(seed)
    w1 = rng.normal(0, 0.5, (din, dhid)).astype(np.float32)
    b1 = rng.normal(0, 0.1, dhid).astype(np.float32)
    w2 = rng.normal(0, 0.5, (dhid, dout)).astype(np.float32)
    b2 = rng.normal(0, 0.1, dout).astype(np.float32)
    nodes = [
        O.make_node("MatMul", ["x", "w1"], ["h0"]),
        O.make_node("Add", ["h0", "b1"], ["h1"]),
        O.make_node("Relu", ["h1"], ["h2"]),
        O.make_node("Gemm", ["h2", "w2", "b2"], ["logits"], transB=0),
        O.make_node("Softmax", ["logits"], ["probs"], axis=-1),
    ]
    graph = O.make_graph(
        nodes, "mlp",
        inputs=[O.make_tensor_value_info("x", np.float32, ["N", din])],
        outputs=[O.make_tensor_value_info("logits", np.float32, ["N", dout]),
                 O.make_tensor_value_info("probs", np.float32, ["N", dout])],
        initializers={"w1": w1, "b1": b1, "w2": w2, "b2": b2})
    return O.make_model(graph), (w1, b1, w2, b2)


class TestWireRoundtrip:
    def test_parse_built_model(self):
        data, _ = mlp_model()
        m = O.parse_model(data)
        assert m.producer_name == "mmlspark_tpu"
        assert m.opset == 17
        g = m.graph
        assert [n.op_type for n in g.nodes] == ["MatMul", "Add", "Relu", "Gemm",
                                                "Softmax"]
        assert len(g.initializers) == 4
        assert g.inputs[0].name == "x"
        assert g.inputs[0].shape == ["N", 8]
        w1 = O.tensor_to_numpy(g.initializers[0])
        assert w1.shape == (8, 16) and w1.dtype == np.float32

    def test_negative_int_attr(self):
        n = O.make_node("Softmax", ["x"], ["y"], axis=-1)
        g = O.make_graph([n], "g",
                         [O.make_tensor_value_info("x", np.float32, [2, 3])],
                         [O.make_tensor_value_info("y", np.float32, [2, 3])])
        m = O.parse_model(O.make_model(g))
        assert m.graph.nodes[0].attr("axis") == -1

    def test_tensor_dtypes(self):
        for arr in [np.arange(6, dtype=np.int64).reshape(2, 3),
                    np.ones((3,), dtype=np.bool_),
                    np.linspace(0, 1, 4, dtype=np.float64)]:
            enc = O.make_tensor("t", arr)
            dec = O.tensor_to_numpy(
                __import__("mmlspark_tpu.onnx.proto", fromlist=["TensorProto"])
                .TensorProto.parse(enc.to_bytes()))
            assert np.array_equal(dec, arr)
            assert dec.dtype == arr.dtype


class TestConverter:
    def test_mlp_vs_numpy(self):
        data, (w1, b1, w2, b2) = mlp_model()
        cm = O.convert_model(data)
        x = np.random.default_rng(1).normal(0, 1, (5, 8)).astype(np.float32)
        out = cm(cm.params, {"x": x})
        ref_h = np.maximum(x @ w1 + b1, 0)
        ref_logits = ref_h @ w2 + b2
        np.testing.assert_allclose(np.asarray(out["logits"]), ref_logits,
                                   rtol=1e-5, atol=1e-5)
        e = np.exp(ref_logits - ref_logits.max(-1, keepdims=True))
        np.testing.assert_allclose(np.asarray(out["probs"]),
                                   e / e.sum(-1, keepdims=True),
                                   rtol=1e-5, atol=1e-6)

    def test_mlp_vs_torch(self):
        import torch
        data, (w1, b1, w2, b2) = mlp_model()
        cm = O.convert_model(data)
        x = np.random.default_rng(2).normal(0, 1, (4, 8)).astype(np.float32)
        with torch.no_grad():
            t = torch.relu(torch.from_numpy(x) @ torch.from_numpy(w1)
                           + torch.from_numpy(b1))
            ref = (t @ torch.from_numpy(w2) + torch.from_numpy(b2)).numpy()
        out = cm(cm.params, {"x": x})
        np.testing.assert_allclose(np.asarray(out["logits"]), ref, rtol=1e-5,
                                   atol=1e-5)

    def test_conv_block_vs_torch(self):
        import torch
        import torch.nn.functional as F
        rng = np.random.default_rng(3)
        w = rng.normal(0, 0.2, (6, 3, 3, 3)).astype(np.float32)
        b = rng.normal(0, 0.1, 6).astype(np.float32)
        gamma = rng.uniform(0.5, 1.5, 6).astype(np.float32)
        beta = rng.normal(0, 0.1, 6).astype(np.float32)
        mean = rng.normal(0, 0.1, 6).astype(np.float32)
        var = rng.uniform(0.5, 1.5, 6).astype(np.float32)
        nodes = [
            O.make_node("Conv", ["x", "w", "b"], ["c"], strides=[2, 2],
                        pads=[1, 1, 1, 1], kernel_shape=[3, 3]),
            O.make_node("BatchNormalization",
                        ["c", "gamma", "beta", "mean", "var"], ["bn"],
                        epsilon=1e-5),
            O.make_node("Relu", ["bn"], ["r"]),
            O.make_node("MaxPool", ["r"], ["p"], kernel_shape=[2, 2],
                        strides=[2, 2]),
            O.make_node("GlobalAveragePool", ["p"], ["g"]),
            O.make_node("Flatten", ["g"], ["y"], axis=1),
        ]
        graph = O.make_graph(
            nodes, "convnet",
            [O.make_tensor_value_info("x", np.float32, ["N", 3, 16, 16])],
            [O.make_tensor_value_info("y", np.float32, ["N", 6])],
            initializers={"w": w, "b": b, "gamma": gamma, "beta": beta,
                          "mean": mean, "var": var})
        cm = O.convert_model(O.make_model(graph))
        x = rng.normal(0, 1, (2, 3, 16, 16)).astype(np.float32)
        with torch.no_grad():
            t = F.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                         torch.from_numpy(b), stride=2, padding=1)
            t = F.batch_norm(t, torch.from_numpy(mean), torch.from_numpy(var),
                             torch.from_numpy(gamma), torch.from_numpy(beta),
                             eps=1e-5)
            t = F.relu(t)
            t = F.max_pool2d(t, 2, 2)
            ref = t.mean(dim=(2, 3)).numpy()
        out = cm(cm.params, {"x": x})
        np.testing.assert_allclose(np.asarray(out["y"]), ref, rtol=1e-4,
                                   atol=1e-4)

    def test_shape_arithmetic_jits(self):
        import jax
        # BERT-style: y = reshape(x, [Shape(x)[0], -1]) then layernorm
        rng = np.random.default_rng(4)
        scale = np.ones(12, dtype=np.float32)
        bias = np.zeros(12, dtype=np.float32)
        nodes = [
            O.make_node("Shape", ["x"], ["shp"]),
            O.make_node("Gather", ["shp", "zero"], ["n"], axis=0),
            O.make_node("Unsqueeze", ["n", "zero_axes"], ["n1"]),
            O.make_node("Concat", ["n1", "negone"], ["target"], axis=0),
            O.make_node("Reshape", ["x", "target"], ["flat"]),
            O.make_node("LayerNormalization", ["flat", "scale", "bias"], ["y"],
                        axis=-1, epsilon=1e-5),
        ]
        graph = O.make_graph(
            nodes, "shapes",
            [O.make_tensor_value_info("x", np.float32, ["N", 3, 4])],
            [O.make_tensor_value_info("y", np.float32, ["N", 12])],
            initializers={"zero": np.array(0, dtype=np.int64),
                          "zero_axes": np.array([0], dtype=np.int64),
                          "negone": np.array([-1], dtype=np.int64),
                          "scale": scale, "bias": bias})
        cm = O.convert_model(O.make_model(graph))
        x = rng.normal(0, 1, (5, 3, 4)).astype(np.float32)
        jitted = jax.jit(cm.__call__)
        out = jitted(cm.params, {"x": x})
        flat = x.reshape(5, 12)
        ref = (flat - flat.mean(-1, keepdims=True)) / np.sqrt(
            flat.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(np.asarray(out["y"]), ref, rtol=1e-4,
                                   atol=1e-4)

    def test_ops_misc_vs_numpy(self):
        rng = np.random.default_rng(5)
        x = rng.normal(0, 1, (3, 4, 5)).astype(np.float32)
        cases = [
            (O.make_node("Transpose", ["x"], ["y"], perm=[2, 0, 1]),
             np.transpose(x, (2, 0, 1))),
            (O.make_node("ReduceMean", ["x"], ["y"], axes=[1], keepdims=0),
             x.mean(axis=1)),
            (O.make_node("Sigmoid", ["x"], ["y"]), 1 / (1 + np.exp(-x))),
            (O.make_node("Clip", ["x"], ["y"]), x),
        ]
        for node, expected in cases:
            g = O.make_graph(
                [node], "t",
                [O.make_tensor_value_info("x", np.float32, list(x.shape))],
                [O.make_tensor_value_info("y", np.float32, None or [])])
            cm = O.convert_model(O.make_model(g))
            out = cm(cm.params, {"x": x})
            np.testing.assert_allclose(np.asarray(out["y"]), expected,
                                       rtol=1e-5, atol=1e-5)

    def test_slice_gather_concat(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        nodes = [
            O.make_node("Slice", ["x", "starts", "ends", "axes"], ["s"]),
            O.make_node("Gather", ["x", "idx"], ["g"], axis=2),
            O.make_node("Concat", ["s", "s"], ["c"], axis=0),
        ]
        g = O.make_graph(
            nodes, "t",
            [O.make_tensor_value_info("x", np.float32, [2, 3, 4])],
            [O.make_tensor_value_info("s", np.float32, []),
             O.make_tensor_value_info("g", np.float32, []),
             O.make_tensor_value_info("c", np.float32, [])],
            initializers={"starts": np.array([1], dtype=np.int64),
                          "ends": np.array([3], dtype=np.int64),
                          "axes": np.array([1], dtype=np.int64),
                          "idx": np.array([0, 3], dtype=np.int64)})
        cm = O.convert_model(O.make_model(g))
        out = cm(cm.params, {"x": x})
        np.testing.assert_array_equal(np.asarray(out["s"]), x[:, 1:3])
        np.testing.assert_array_equal(np.asarray(out["g"]), x[:, :, [0, 3]])
        np.testing.assert_array_equal(np.asarray(out["c"]),
                                      np.concatenate([x[:, 1:3]] * 2, axis=0))

    def test_unsupported_op_message(self):
        g = O.make_graph(
            [O.make_node("FancyNewOp", ["x"], ["y"])], "t",
            [O.make_tensor_value_info("x", np.float32, [1])],
            [O.make_tensor_value_info("y", np.float32, [1])])
        cm = O.convert_model(O.make_model(g))
        with pytest.raises(NotImplementedError, match="FancyNewOp"):
            cm(cm.params, {"x": np.zeros(1, dtype=np.float32)})


class TestONNXModelTransformer:
    def test_transform_with_post_ops(self):
        from mmlspark_tpu.models.onnx_model import ONNXModel
        data, (w1, b1, w2, b2) = mlp_model()
        rng = np.random.default_rng(7)
        X = rng.normal(0, 1, (37, 8)).astype(np.float32)
        df = DataFrame({"feats": [X[i] for i in range(len(X))]}, npartitions=3)
        m = ONNXModel(data,
                      feed_dict={"x": "feats"},
                      fetch_dict={"logits_col": "logits"},
                      mini_batch_size=16,
                      softmax_dict={"probs_col": "logits_col"},
                      argmax_dict={"pred": "logits_col"})
        out = m.transform(df)
        assert len(out) == 37
        ref_logits = np.maximum(X @ w1 + b1, 0) @ w2 + b2
        got = np.stack(list(out["logits_col"]))
        np.testing.assert_allclose(got, ref_logits, rtol=1e-4, atol=1e-4)
        preds = out["pred"]
        np.testing.assert_array_equal(preds, ref_logits.argmax(1))
        p0 = out["probs_col"][0]
        assert abs(p0.sum() - 1.0) < 1e-6

    def test_save_load(self, tmp_save):
        from mmlspark_tpu.models.onnx_model import ONNXModel
        data, _ = mlp_model()
        m = ONNXModel(data, feed_dict={"x": "feats"},
                      fetch_dict={"out": "logits"}, mini_batch_size=8)
        m.save(tmp_save)
        m2 = PipelineStage.load(tmp_save)
        rng = np.random.default_rng(8)
        X = rng.normal(0, 1, (5, 8)).astype(np.float32)
        df = DataFrame({"feats": [X[i] for i in range(5)]})
        o1 = np.stack(list(m.transform(df)["out"]))
        o2 = np.stack(list(m2.transform(df)["out"]))
        np.testing.assert_allclose(o1, o2, rtol=1e-6)

    def test_metadata_without_session(self):
        from mmlspark_tpu.models.onnx_model import ONNXModel
        data, _ = mlp_model()
        m = ONNXModel(data)
        ins = m.model_inputs()
        outs = m.model_outputs()
        assert list(ins) == ["x"]
        assert ins["x"][1] == ("N", 8)
        assert set(outs) == {"logits", "probs"}


class TestDevicePrep:
    """uint8 feeds with on-device layout/cast/normalization
    (transpose_dict/normalize_dict) — the TPU-side answer to the
    reference's host-side ImageTransformer normalization
    (``opencv/.../ImageTransformer.scala:417+``)."""

    def test_uint8_transpose_normalize_matches_host_path(self):
        from mmlspark_tpu.models.onnx_model import ONNXModel
        # conv graph: input NCHW float; feed NHWC uint8 instead
        rng = np.random.default_rng(3)
        w = rng.normal(0, 0.2, (4, 3, 3, 3)).astype(np.float32)
        g = O.make_graph(
            [O.make_node("Conv", ["img", "w"], ["y"], pads=[1, 1, 1, 1])],
            "c",
            inputs=[O.make_tensor_value_info("img", np.float32,
                                             ["N", 3, 8, 8])],
            outputs=[O.make_tensor_value_info("y", np.float32,
                                              ["N", 4, 8, 8])],
            initializers={"w": w})
        data = O.make_model(g)
        mean, std = [0.485, 0.456, 0.406], [0.229, 0.224, 0.225]
        m = ONNXModel(data, feed_dict={"img": "image"},
                      fetch_dict={"y": "y"},
                      transpose_dict={"img": [0, 3, 1, 2]},
                      normalize_dict={"img": {"scale": 1 / 255.,
                                              "mean": mean, "std": std}},
                      mini_batch_size=4, pin_devices=False)
        X8 = rng.integers(0, 256, (6, 8, 8, 3), dtype=np.uint8)
        col = np.empty(6, dtype=object)
        for i in range(6):
            col[i] = X8[i]
        out = m.transform(DataFrame({"image": col}))
        Xf = (X8.astype(np.float32) / 255. - np.array(mean)) / np.array(std)
        Xf = np.ascontiguousarray(Xf.transpose(0, 3, 1, 2)).astype(np.float32)
        colf = np.empty(6, dtype=object)
        for i in range(6):
            colf[i] = Xf[i]
        m2 = ONNXModel(data, feed_dict={"img": "image"},
                       fetch_dict={"y": "y"},
                       mini_batch_size=4, pin_devices=False)
        ref = m2.transform(DataFrame({"image": colf}))
        np.testing.assert_allclose(np.stack(list(out["y"])),
                                   np.stack(list(ref["y"])),
                                   rtol=1e-4, atol=1e-4)

    def test_float_feed_transfers_in_source_dtype(self):
        """Host path must not cast floats to compute_dtype before transfer."""
        from mmlspark_tpu.models.onnx_model import ONNXModel
        data, _ = mlp_model()
        m = ONNXModel(data, feed_dict={"x": "feats"},
                      fetch_dict={"out": "logits"},
                      compute_dtype="bfloat16", pin_devices=False)
        arr = m._coerce(np.zeros((4, 8), dtype=np.float32), np.float32,
                        ("N", 8))
        assert arr.dtype == np.float32  # cast happens on device
        arr64 = m._coerce(np.zeros((4, 8), dtype=np.float64), np.float32,
                          ("N", 8))
        assert arr64.dtype == np.float32  # f64 halved for the wire
        arr8 = m._coerce(np.zeros((4, 8), dtype=np.uint8), np.float32,
                         ("N", 8))
        assert arr8.dtype == np.uint8  # ints ride the wire untouched


class TestNewElementwiseOps:
    """Mish/IsInf/ThresholdedRelu/Shrink/BitShift/ReverseSequence vs numpy."""

    def _run(self, node, feeds, out_dtype=np.float32, extra_inputs=()):
        ins = [O.make_tensor_value_info(n, a.dtype.type, list(a.shape))
               for n, a in feeds.items()]
        g = O.make_graph([node], "t", ins,
                         [O.make_tensor_value_info("y", out_dtype, [])])
        cm = O.convert_model(O.make_model(g))
        return np.asarray(cm(cm.params, feeds)["y"])

    def test_mish(self):
        x = np.linspace(-4, 4, 12, dtype=np.float32)
        got = self._run(O.make_node("Mish", ["x"], ["y"]), {"x": x})
        want = x * np.tanh(np.log1p(np.exp(x)))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_isinf_directions(self):
        x = np.array([1.0, np.inf, -np.inf, np.nan], dtype=np.float32)
        got = self._run(O.make_node("IsInf", ["x"], ["y"]), {"x": x},
                        out_dtype=np.bool_)
        np.testing.assert_array_equal(got, [False, True, True, False])
        pos_only = self._run(
            O.make_node("IsInf", ["x"], ["y"], detect_negative=0), {"x": x},
            out_dtype=np.bool_)
        np.testing.assert_array_equal(pos_only, [False, True, False, False])

    def test_thresholded_relu_and_shrink(self):
        x = np.array([-2.0, -0.3, 0.0, 0.4, 2.0], dtype=np.float32)
        got = self._run(O.make_node("ThresholdedRelu", ["x"], ["y"],
                                    alpha=0.5), {"x": x})
        np.testing.assert_allclose(got, np.where(x > 0.5, x, 0.0))
        got = self._run(O.make_node("Shrink", ["x"], ["y"], lambd=0.5,
                                    bias=0.1), {"x": x})
        want = np.where(x < -0.5, x + 0.1, np.where(x > 0.5, x - 0.1, 0.0))
        np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-6)

    def test_bitshift(self):
        x = np.array([1, 2, 8], dtype=np.uint32)
        s = np.array([1, 2, 2], dtype=np.uint32)
        got = self._run(O.make_node("BitShift", ["x", "s"], ["y"],
                                    direction="LEFT"),
                        {"x": x, "s": s}, out_dtype=np.uint32)
        np.testing.assert_array_equal(got, [2, 8, 32])
        got = self._run(O.make_node("BitShift", ["x", "s"], ["y"],
                                    direction="RIGHT"),
                        {"x": x, "s": s}, out_dtype=np.uint32)
        np.testing.assert_array_equal(got, [0, 0, 2])

    def test_reverse_sequence(self):
        # ONNX spec example: (time=4, batch=2), reverse each batch's prefix
        x = np.arange(8, dtype=np.float32).reshape(4, 2)
        lens = np.array([4, 2], dtype=np.int64)
        got = self._run(O.make_node("ReverseSequence", ["x", "l"], ["y"],
                                    batch_axis=1, time_axis=0),
                        {"x": x, "l": lens})
        want = x.copy()
        want[:4, 0] = x[:4, 0][::-1]
        want[:2, 1] = x[:2, 1][::-1]
        np.testing.assert_array_equal(got, want)


class TestMeshShardedInference:
    """SPMD batch-sharded ONNX inference over the default mesh."""

    def _model(self):
        rng = np.random.default_rng(7)
        w = rng.normal(0, 0.5, (6, 4)).astype(np.float32)
        g = O.make_graph(
            [O.make_node("MatMul", ["x", "w"], ["h"]),
             O.make_node("Relu", ["h"], ["y"])],
            "mlp",
            inputs=[O.make_tensor_value_info("x", np.float32, ["N", 6])],
            outputs=[O.make_tensor_value_info("y", np.float32, ["N", 4])],
            initializers={"w": w})
        return O.make_model(g), w

    def test_matches_unsharded_and_pads_odd_batches(self):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.onnx_model import ONNXModel
        from mmlspark_tpu.parallel.mesh import MeshContext

        mb, w = self._model()
        rng = np.random.default_rng(0)
        X = rng.normal(0, 1, (37, 6)).astype(np.float32)  # odd: 37 % 8 != 0
        col = np.empty(len(X), object)
        col[:] = list(X)
        df = DataFrame({"x": col})
        plain = ONNXModel(mb, feed_dict={"x": "x"}, fetch_dict={"y": "y"},
                          mini_batch_size=16, pin_devices=False)
        want = np.stack(list(plain.transform(df)["y"]))
        with MeshContext({"data": 8}):
            sharded = ONNXModel(mb, feed_dict={"x": "x"},
                                fetch_dict={"y": "y"}, mini_batch_size=16,
                                mesh_sharded=True)
            got = np.stack(list(sharded.transform(df)["y"]))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(got, np.maximum(X @ w, 0), rtol=1e-5,
                                   atol=1e-5)

    def test_without_default_mesh_falls_back(self):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.onnx_model import ONNXModel

        mb, w = self._model()
        X = np.random.default_rng(1).normal(0, 1, (8, 6)).astype(np.float32)
        col = np.empty(len(X), object)
        col[:] = list(X)
        m = ONNXModel(mb, feed_dict={"x": "x"}, fetch_dict={"y": "y"},
                      mesh_sharded=True)   # no default mesh installed
        out = np.stack(list(m.transform(DataFrame({"x": col}))["y"]))
        np.testing.assert_allclose(out, np.maximum(X @ w, 0), rtol=1e-5,
                                   atol=1e-5)


class TestConvNHWCMode:
    """MMLSPARK_TPU_CONV_NHWC=1 (the on-TPU default) must be numerically
    identical to the NCHW lowering — CI runs on CPU where 'auto' is off,
    so this forces the branch."""

    @pytest.mark.parametrize("case", [
        dict(x=(2, 3, 16, 16), w=(8, 3, 3, 3), strides=[1, 1], group=1),
        dict(x=(2, 4, 15, 15), w=(6, 4, 5, 5), strides=[2, 2], group=1),
        dict(x=(1, 8, 9, 9), w=(8, 4, 3, 3), strides=[1, 1], group=2),
        dict(x=(2, 3, 14, 14), w=(4, 3, 3, 3), strides=[2, 2], group=1,
             auto_pad="SAME_UPPER"),
        dict(x=(1, 2, 12, 12), w=(3, 2, 3, 3), strides=[1, 1], group=1,
             dilations=[2, 2]),
    ])
    def test_matches_nchw(self, case, monkeypatch):
        rng = np.random.default_rng(0)
        x = rng.normal(0, 1, case["x"]).astype(np.float32)
        w = rng.normal(0, 1, case["w"]).astype(np.float32)
        b = rng.normal(0, 1, (case["w"][0],)).astype(np.float32)
        attrs = {"strides": case["strides"], "group": case["group"]}
        if "auto_pad" in case:
            attrs["auto_pad"] = case["auto_pad"]
        if "dilations" in case:
            attrs["dilations"] = case["dilations"]
        g = O.make_graph(
            [O.make_node("Conv", ["x", "w", "b"], ["y"], **attrs)],
            "conv_layouts",
            inputs=[O.make_tensor_value_info(
                "x", np.float32, list(case["x"]))],
            outputs=[O.make_tensor_value_info(
                "y", np.float32, ["N", "C", "H", "W"])],
            initializers={"w": w, "b": b})
        model = O.make_model(g)

        monkeypatch.setenv("MMLSPARK_TPU_CONV_NHWC", "0")
        cm0 = O.convert_model(model)
        ref = np.asarray(cm0(cm0.params, {"x": x})["y"])
        monkeypatch.setenv("MMLSPARK_TPU_CONV_NHWC", "1")
        cm1 = O.convert_model(model)
        got = np.asarray(cm1(cm1.params, {"x": x})["y"])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
