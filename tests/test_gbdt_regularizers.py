"""Tree-level regularizers (LightGBM parity params).

Covers ``extra_trees`` (one random threshold per node x feature),
``feature_fraction_bynode`` (per-node feature draws),
``path_smooth`` (node outputs pulled toward the parent's),
``interaction_constraints`` (per-branch feature-group restriction),
``boost_from_average``, and the categorical regularizers ``cat_smooth`` /
``min_data_per_group``.

Reference parity surface: LightGBM's params of the same names, reached
through ``lightgbm/.../params/LightGBMParams.scala`` in the reference.
The tests pin structural invariants checkable from the fitted arrays —
the reference's own strategy of verifying semantics rather than exact
native outputs (``benchmarks_VerifyLightGBMClassifier.csv``).
"""

import numpy as np
import pytest

from mmlspark_tpu.models.gbdt.categorical import CategoricalEncoder
from mmlspark_tpu.models.gbdt.train import train

BASE = {"objective": "regression", "num_iterations": 12, "num_leaves": 15,
        "learning_rate": 0.2, "seed": 3}


def _data(n=900, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.1 * rng.normal(size=n)
    return X, y


class TestExtraTrees:
    def test_deterministic_and_different(self):
        X, y = _data()
        a = train(dict(BASE, extra_trees=True), X, y)
        b = train(dict(BASE, extra_trees=True), X, y)
        c = train(BASE, X, y)
        assert np.array_equal(a.predict(X), b.predict(X))
        assert not np.array_equal(a.predict(X), c.predict(X))

    def test_still_learns(self):
        X, y = _data()
        m = train(dict(BASE, extra_trees=True, num_iterations=40), X, y)
        mse = float(np.mean((m.predict(X) - y) ** 2))
        assert mse < 0.5 * float(np.var(y))

    def test_seed_changes_thresholds(self):
        X, y = _data()
        a = train(dict(BASE, extra_trees=True, seed=1), X, y)
        b = train(dict(BASE, extra_trees=True, seed=2), X, y)
        assert not np.array_equal(a.thr_raw, b.thr_raw)

    def test_low_cardinality_features_stay_eligible(self):
        # the random threshold draws within each feature's OWN bin range —
        # a binary flag must still win splits next to a 255-bin continuous
        # column (a global-range draw would give it ~1/254 eligibility)
        rng = np.random.default_rng(5)
        n = 1200
        flag = (rng.random(n) > 0.5).astype(np.float32)
        noise = rng.normal(size=n).astype(np.float32)
        X = np.stack([noise, flag], axis=1)
        y = 3.0 * flag + 0.1 * rng.normal(size=n)
        m = train(dict(BASE, extra_trees=True, num_iterations=10), X, y)
        used = np.asarray(m.feats)
        assert (used == 1).sum() > 0
        mse = float(np.mean((m.predict(X) - y) ** 2))
        assert mse < 0.25 * float(np.var(y))


class TestFeatureFractionByNode:
    def test_deterministic_and_learns(self):
        X, y = _data()
        a = train(dict(BASE, feature_fraction_bynode=0.5), X, y)
        b = train(dict(BASE, feature_fraction_bynode=0.5), X, y)
        assert np.array_equal(a.predict(X), b.predict(X))
        mse = float(np.mean((a.predict(X) - y) ** 2))
        assert mse < float(np.var(y))

    def test_single_feature_per_node(self):
        X, y = _data(f=8)
        m = train(dict(BASE, feature_fraction_bynode=1.0 / 8), X, y)
        # nodes exist and split on more than one distinct feature overall
        used = np.unique(m.feats[m.feats >= 0])
        assert len(used) > 1

    def test_composes_with_per_tree_fraction(self):
        X, y = _data()
        m = train(dict(BASE, feature_fraction=0.5,
                       feature_fraction_bynode=0.5), X, y)
        assert m.num_trees == BASE["num_iterations"]

    def test_validation(self):
        X, y = _data(n=50)
        with pytest.raises(ValueError, match="feature_fraction_bynode"):
            train(dict(BASE, feature_fraction_bynode=0.0), X, y)


class TestPathSmooth:
    def test_zero_is_bitwise_baseline(self):
        X, y = _data()
        a = train(dict(BASE, path_smooth=0.0), X, y)
        b = train(BASE, X, y)
        assert np.array_equal(a.predict(X), b.predict(X))

    def test_huge_smoothing_flattens(self):
        X, y = _data()
        m = train(dict(BASE, path_smooth=1e9), X, y)
        assert float(np.std(m.predict(X))) < 1e-3

    def test_moderate_smoothing_shrinks_leaf_spread(self):
        X, y = _data()
        a = train(BASE, X, y)
        b = train(dict(BASE, path_smooth=50.0), X, y)
        assert float(np.std(b.leaf_values)) < float(np.std(a.leaf_values))
        # still learns
        mse = float(np.mean((b.predict(X) - y) ** 2))
        assert mse < float(np.var(y))

    def test_negative_rejected(self):
        X, y = _data(n=50)
        with pytest.raises(ValueError, match="path_smooth"):
            train(dict(BASE, path_smooth=-1.0), X, y)


class TestInteractionConstraints:
    def _paths_within_groups(self, m, groups):
        depth = m.depth
        for tree in np.asarray(m.feats):
            for leaf in range(2 ** depth):
                idx, used = 0, set()
                for d in range(depth):
                    f = tree[idx]
                    if f >= 0:
                        used.add(int(f))
                    bit = (leaf >> (depth - 1 - d)) & 1
                    idx = 2 * idx + 1 + bit
                if used and not any(used <= set(g) for g in groups):
                    return False
        return True

    def test_paths_respect_groups(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(1200, 6)).astype(np.float32)
        y = X[:, 0] * X[:, 1] + X[:, 2] * X[:, 3] \
            + 0.05 * rng.normal(size=1200)
        groups = [[0, 1], [2, 3]]
        m = train(dict(BASE, interaction_constraints=groups), X, y)
        assert self._paths_within_groups(m, groups)
        # features in no group are never used (LightGBM semantics)
        assert not np.isin(m.feats, [4, 5]).any()

    def test_overlapping_groups(self):
        X, y = _data(f=4)
        groups = [[0, 1, 2], [2, 3]]
        m = train(dict(BASE, interaction_constraints=groups), X, y)
        assert self._paths_within_groups(m, groups)

    def test_validation(self):
        X, y = _data(n=50, f=4)
        with pytest.raises(ValueError, match="outside"):
            train(dict(BASE, interaction_constraints=[[0, 9]]), X, y)
        with pytest.raises(ValueError, match="non-empty"):
            train(dict(BASE, interaction_constraints=[[]]), X, y)


class TestBoostFromAverage:
    def test_off_starts_at_zero(self):
        X, y = _data()
        y = y + 100.0                      # far-from-zero target
        on = train(BASE, X, y)
        off = train(dict(BASE, boost_from_average=False), X, y)
        assert on.base_score == pytest.approx(float(np.mean(y)))
        assert off.base_score == 0.0
        # with enough iterations both still reach the target's scale
        m = train(dict(BASE, boost_from_average=False,
                       num_iterations=60), X, y)
        assert abs(float(np.mean(m.predict(X))) - 100.0) < 5.0


class TestCategoricalRegularizers:
    def test_cat_smooth_tames_rare_categories(self):
        # five well-populated categories with means 0..4 and one 2-row
        # category whose raw mean (4.5) tops the ordering; 50 pseudo-counts
        # of the global mean (~2) pull only the RARE category's mean inward
        # (common categories, ~100 rows each, barely move) so its rank
        # drops below the top common categories
        cats = np.repeat(np.arange(5.0), 100)
        y = cats.copy()
        cats = np.concatenate([cats, [7.0, 7.0]])
        y = np.concatenate([y, [4.5, 4.5]])
        X = cats[:, None]
        raw = CategoricalEncoder([0], cat_smooth=0.0,
                                 min_data_per_group=0).fit(X, y)
        sm = CategoricalEncoder([0], cat_smooth=50.0,
                                min_data_per_group=0).fit(X, y)
        r_raw = dict(zip(raw.values[0], raw.ranks[0]))
        r_sm = dict(zip(sm.values[0], sm.ranks[0]))
        assert r_raw[7.0] == max(r_raw.values())
        assert r_sm[7.0] < max(r_sm.values())

    def test_min_data_per_group_pools_rare(self):
        rng = np.random.default_rng(2)
        n = 400
        cats = rng.integers(0, 4, size=n).astype(np.float64)
        cats[:3] = [10.0, 11.0, 12.0]      # three singleton categories
        y = cats.copy()
        enc = CategoricalEncoder([0], cat_smooth=0.0,
                                 min_data_per_group=5).fit(cats[:, None], y)
        r = dict(zip(enc.values[0], enc.ranks[0]))
        # pooled: all rare categories share one rank (inseparable)
        assert r[10.0] == r[11.0] == r[12.0]
        # common categories keep distinct ranks
        assert len({r[c] for c in (0.0, 1.0, 2.0, 3.0)}) == 4

    def test_params_flow_from_train(self):
        rng = np.random.default_rng(3)
        n = 600
        c = rng.integers(0, 6, size=n).astype(np.float64)
        X = np.stack([c, rng.normal(size=n)], axis=1).astype(np.float32)
        y = (c % 3) + 0.1 * rng.normal(size=n)
        m = train(dict(BASE, categorical_feature=[0], cat_smooth=5.0,
                       min_data_per_group=10), X, y)
        assert m.cat_encoder is not None
        assert m.cat_encoder.cat_smooth == 5.0
        assert m.cat_encoder.min_data_per_group == 10
        mse = float(np.mean((m.predict(X) - y) ** 2))
        assert mse < float(np.var(y))


class TestMeshParity:
    def test_data_parallel_matches_serial(self):
        # the new regularizers must stay bitwise-deterministic across the
        # mesh: the replicated rng key draws identical masks on every shard
        import jax
        from jax.sharding import Mesh

        X, y = _data(n=512)
        params = dict(BASE, extra_trees=True, feature_fraction_bynode=0.6,
                      path_smooth=3.0)
        serial = train(params, X, y)
        devs = np.array(jax.devices()[:4])
        with Mesh(devs, ("data",)):
            mesh = Mesh(devs, ("data",))
            dp = train(dict(params, tree_learner="data_parallel"), X, y,
                       mesh=mesh)
        np.testing.assert_allclose(serial.predict(X), dp.predict(X),
                                   rtol=2e-4, atol=2e-5)

    def test_voting_rejects_regularizers(self):
        import jax
        from jax.sharding import Mesh

        X, y = _data(n=256, f=30)
        devs = np.array(jax.devices()[:4])
        mesh = Mesh(devs, ("data",))
        with pytest.raises(ValueError, match="data_parallel"):
            train(dict(BASE, extra_trees=True, top_k=3,
                       tree_learner="voting_parallel"), X, y, mesh=mesh)


class TestEvalParity:
    """Eval-side LightGBM parity: per-set eval weights and metric lists."""

    def test_valid_weights_drive_the_logged_metric(self):
        X, y = _data(n=600)
        Xv, yv = _data(n=200, seed=9)
        w = np.where(np.arange(200) < 100, 10.0, 0.1)
        log = []
        train(dict(BASE, metric="l2"), X, y, valid_sets=[(Xv, yv)],
              valid_weights=[w], eval_log=log)
        m = train(dict(BASE, metric="l2"), X, y, valid_sets=[(Xv, yv)],
                  eval_log=[])
        pred = m.predict(Xv)
        # the last logged l2 equals the weighted mean squared error of the
        # final model, not the unweighted one
        want = float(np.sum(w * (pred - yv) ** 2) / np.sum(w))
        got = log[-1]["l2"]
        assert got == pytest.approx(want, rel=1e-5)
        plain = float(np.mean((pred - yv) ** 2))
        assert abs(got - plain) > 1e-9       # the weights actually matter

    def test_valid_weights_validation(self):
        X, y = _data(n=100)
        Xv, yv = _data(n=50, seed=1)
        with pytest.raises(ValueError, match="valid_weights"):
            train(BASE, X, y, valid_sets=[(Xv, yv)],
                  valid_weights=[np.ones(3), np.ones(50)])
        with pytest.raises(ValueError, match="rows"):
            train(BASE, X, y, valid_sets=[(Xv, yv)],
                  valid_weights=[np.ones(7)])

    def test_metric_list_logs_every_metric(self):
        X, y = _data(n=500)
        yb = (y > np.median(y)).astype(np.float64)
        Xv, yv = _data(n=150, seed=3)
        yvb = (yv > np.median(yv)).astype(np.float64)
        log = []
        m = train(dict(BASE, objective="binary",
                       metric=["auc", "binary_logloss"],
                       early_stopping_round=0),
                  X, yb, valid_sets=[(Xv, yvb)], eval_log=log)
        per_set = [e for e in log if "valid_set" in e]
        assert any("auc" in e for e in per_set)
        assert any("binary_logloss" in e for e in per_set)
        # every per-set (set, metric) pair is self-describing; the
        # early-stopping summary entry is distinctly tagged so consumers
        # counting entries don't conflate it with the per-set series
        summaries = [e for e in log if "valid_set" not in e]
        assert summaries and all(e.get("primary") for e in summaries)
        assert all("auc" in e for e in summaries)
        # early stopping / best tracking follows the FIRST metric
        assert m.num_trees == BASE["num_iterations"]

    def test_unknown_metric_in_list_rejected(self):
        X, y = _data(n=100)
        with pytest.raises(ValueError, match="unknown metric"):
            train(dict(BASE, metric=["l2", "nope"]), X, y,
                  valid_sets=[(X, y)])


class TestEstimatorEvalPlumbing:
    def test_weight_col_reaches_validation_eval(self, monkeypatch):
        """The estimator forwards the validation split's weight rows as
        valid_weights (LightGBM Dataset-weight eval semantics)."""
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.models.gbdt import estimators as E

        captured = {}
        real_train = E.train

        def spy(params, X, y, **kw):
            captured.update(kw)
            return real_train(params, X, y, **kw)

        monkeypatch.setattr(E, "train", spy)
        rng = np.random.default_rng(0)
        n = 120
        Xr = rng.normal(size=(n, 4))
        feats = np.empty(n, object)
        feats[:] = list(Xr)
        df = DataFrame({"features": feats,
                        "label": (Xr[:, 0] > 0).astype(np.float64),
                        "w": rng.uniform(0.5, 2.0, n),
                        "is_val": np.arange(n) >= 90})
        E.LightGBMClassifier(num_iterations=3, weight_col="w",
                             validation_indicator_col="is_val").fit(df)
        vw = captured["valid_weights"]
        assert vw is not None and len(vw) == 1 and len(vw[0]) == 30
        np.testing.assert_allclose(vw[0], np.asarray(df["w"])[90:])

    def test_metric_param_rejects_scalars_and_dicts(self):
        from mmlspark_tpu.models.gbdt import LightGBMRegressor
        with pytest.raises(TypeError, match="str or list"):
            LightGBMRegressor(metric=5)
        with pytest.raises(TypeError, match="str or list"):
            LightGBMRegressor(metric={"l2": True})
        m = LightGBMRegressor(metric=["l2", "l1"])
        assert m.metric == ["l2", "l1"]
