"""Streaming speech tests: websocket transport, audio streams, and the
continuous-recognition session/stage against an in-process fake ASR server
(parity: ``SpeechToTextSDK.scala:579`` + ``AudioStreams.scala:94``)."""

import json
import socket
import struct
import threading

import numpy as np
import pytest

from mmlspark_tpu.io.ws import (OP_BINARY, OP_CLOSE, OP_TEXT, client_connect,
                                server_handshake)
from mmlspark_tpu.services.audio import (AudioFormat, PullAudioStream,
                                         PushAudioStream, parse_wav)
from mmlspark_tpu.services.speech_streaming import (SpeechRecognitionSession,
                                                    SpeechToTextStreaming)


# ---------------------------------------------------------------------------
# fake streaming ASR server: emits a hypothesis per frame and a final phrase
# per 4 frames (and at end-of-audio)
# ---------------------------------------------------------------------------

def _fake_asr_server():
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    port = srv.getsockname()[1]

    def handle(conn_sock):
        head = b""
        while b"\r\n\r\n" not in head:
            chunk = conn_sock.recv(4096)
            if not chunk:
                return
            head += chunk
        ws, _path = server_handshake(conn_sock, head)
        frames, utt, offset = 0, 0, 0
        cfg = None
        while True:
            opcode, payload = ws.recv()
            if opcode == OP_CLOSE:
                return
            if opcode == OP_TEXT:
                msg = json.loads(payload.decode())
                if msg["type"] == "speech.config":
                    cfg = msg["format"]
                elif msg["type"] == "audio.end":
                    if frames % 4:
                        ws.send_text(json.dumps(
                            {"type": "speech.phrase",
                             "text": f"utterance {utt}",
                             "offset": offset, "duration": frames % 4}))
                    ws.send_text(json.dumps({"type": "speech.end",
                                             "config_seen": cfg is not None}))
                    return
            elif opcode == OP_BINARY:
                frames += 1
                ws.send_text(json.dumps({"type": "speech.hypothesis",
                                         "text": f"hyp {frames}"}))
                if frames % 4 == 0:
                    ws.send_text(json.dumps(
                        {"type": "speech.phrase", "text": f"utterance {utt}",
                         "offset": offset, "duration": 4}))
                    utt += 1
                    offset = frames

    def loop():
        while True:
            try:
                c, _ = srv.accept()
            except OSError:
                return
            threading.Thread(target=handle, args=(c,), daemon=True).start()

    threading.Thread(target=loop, daemon=True).start()
    return srv, port


@pytest.fixture(scope="module")
def asr():
    srv, port = _fake_asr_server()
    yield f"ws://127.0.0.1:{port}/stt"
    srv.close()


def _wav(n_samples=32000, rate=16000):  # 2s at 16kHz → 20 100ms frames
    pcm = (np.sin(np.linspace(0, 100, n_samples)) * 3000).astype("<i2")
    body = pcm.tobytes()
    fmt = struct.pack("<HHIIHH", 1, 1, rate, rate * 2, 2, 16)
    chunks = b"fmt " + struct.pack("<I", len(fmt)) + fmt \
        + b"data" + struct.pack("<I", len(body)) + body
    return b"RIFF" + struct.pack("<I", 4 + len(chunks)) + b"WAVE" + chunks


class TestAudio:
    def test_parse_wav_roundtrip(self):
        fmt, payload = parse_wav(_wav())
        assert fmt == AudioFormat(16000, 16, 1)
        assert len(payload) == 64000  # 2s of 16-bit mono

    def test_parse_wav_rejects_non_pcm(self):
        bad = _wav()
        # codec field (2 bytes at fmt body start) → 7 (mu-law)
        i = bad.index(b"fmt ") + 8
        bad = bad[:i] + struct.pack("<H", 7) + bad[i + 2:]
        with pytest.raises(ValueError, match="codec"):
            parse_wav(bad)

    def test_push_stream_blocks_until_close(self):
        s = PushAudioStream()
        got = []
        t = threading.Thread(target=lambda: got.append(s.read(4, timeout=5)))
        t.start()
        s.write(b"abcd")
        t.join(5)
        assert got == [b"abcd"]
        s.close()
        assert s.read(4) == b""

    def test_frame_bytes_sample_aligned(self):
        fmt = AudioFormat(16000, 16, 2)  # 4 bytes per sample step
        assert fmt.frame_bytes(100) % 4 == 0


class TestWebSocket:
    def test_echo_roundtrip(self, asr):
        # large (>64KB) frame exercises the 64-bit length path
        from urllib.parse import urlparse
        u = urlparse(asr)
        ws = client_connect(u.hostname, u.port, u.path)
        ws.send_text(json.dumps({"type": "speech.config", "format": {}}))
        ws.send_binary(b"x" * 70000)
        op, payload = ws.recv()
        assert op == OP_TEXT
        assert json.loads(payload)["type"] == "speech.hypothesis"
        ws.close()


class TestSession:
    def test_continuous_recognition_phrases_and_interims(self, asr):
        fmt, payload = parse_wav(_wav())
        interims = []
        sess = SpeechRecognitionSession(
            asr, frame_millis=100,
            recognizing=lambda e: interims.append(e["text"]))
        phrases = sess.run(PullAudioStream(payload, fmt))
        # 2s of audio at 100ms frames = 20 frames → 5 phrases
        assert [p["text"] for p in phrases] == [f"utterance {i}"
                                                for i in range(5)]
        assert len(interims) == 20
        assert phrases[1]["offset"] == 4

    def test_push_stream_live(self, asr):
        fmt = AudioFormat()
        stream = PushAudioStream(fmt)
        sess = SpeechRecognitionSession(asr, frame_millis=100)
        out = []
        t = threading.Thread(target=lambda: out.append(sess.run(stream)))
        t.start()
        frame = fmt.frame_bytes(100)
        for _ in range(8):
            stream.write(b"\0" * frame)
        stream.close()
        t.join(15)
        assert len(out) == 1 and len(out[0]) == 2  # 8 frames → 2 phrases


class TestStage:
    def test_transform_rows(self, asr):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.core.dataframe import object_col
        wav = _wav()
        col = object_col([wav, None, wav])
        df = DataFrame({"audio": col})
        t = (SpeechToTextStreaming(url=asr, output_col="utts",
                                   error_col="err", interim_col="hyps")
             .set_vector_param("audio_data", "audio"))
        out = t.transform(df)
        assert [p["text"] for p in out["utts"][0]] == \
            [f"utterance {i}" for i in range(5)]
        assert out["utts"][1] is None
        assert len(out["hyps"][2]) == 20
        assert out["err"][0] is None

    def test_transform_error_column(self):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.core.dataframe import object_col
        df = DataFrame({"audio": object_col([_wav()])})
        t = (SpeechToTextStreaming(url="ws://127.0.0.1:9/none",
                                   output_col="utts", error_col="err",
                                   timeout=2)
             .set_vector_param("audio_data", "audio"))
        out = t.transform(df)
        assert out["utts"][0] is None
        assert "error" in out["err"][0]

    def test_transform_concurrent_sessions(self, asr):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.core.dataframe import object_col
        wav = _wav()
        df = DataFrame({"audio": object_col([wav] * 6)})
        t = (SpeechToTextStreaming(url=asr, output_col="utts",
                                   error_col="err", concurrency=3)
             .set_vector_param("audio_data", "audio"))
        out = t.transform(df)
        for i in range(6):
            assert [p["text"] for p in out["utts"][i]] == \
                [f"utterance {k}" for k in range(5)]
