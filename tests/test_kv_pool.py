"""Paged KV cache (``serving/kv_pool.py``) and the paged decode path.

The invariants this file pins, in order of importance:

1. PARITY — the paged gather/scatter step (``impl="gather"``, pinned
   here: bitwise is the GATHER path's contract) is bitwise-equal to the
   contiguous ragged step it replaced, and the engine built on it stays
   token-identical to ``generate_cached`` under either attention impl
   (the default Pallas kernel's f32-tolerance drift never flips these
   seeds' argmaxes; kernel-vs-gather tolerance parity lives in
   tests/test_paged_attention.py). Paging changes WHERE bytes live,
   never what the model computes.
2. EXACTNESS — alloc/free are page-exact: no leaks, no double-frees, the
   free list plus live pages always tile [1, num_pages) (page 0 is the
   trash page and never handed out).
3. SHARING — two requests with a common prompt prefix physically share
   the strictly-common pages (counter-asserted, block tables compared),
   copy-on-write at the boundary.
4. BOUNDING — chunked prefill never lets one engine tick run a prompt
   window larger than the chunk budget; long prompts interleave with
   live decodes instead of freezing them.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mmlspark_tpu.models.zoo.transformer import (
    TransformerConfig, decode_step_paged, decode_step_ragged,
    decode_window_paged, decode_window_ragged, generate_cached,
    init_kv_cache, init_paged_cache, init_transformer, paged_gather,
    paged_scatter_rows, prefill_cache)
from mmlspark_tpu.serving.continuous import ContinuousDecoder
from mmlspark_tpu.serving.kv_pool import (KVAutotuner, PagedKVPool,
                                          PoolExhausted, prefix_hash)

CFG = TransformerConfig(vocab=128, layers=2, d_model=64, heads=4, d_ff=128,
                        max_len=64, causal=True, norm="rmsnorm",
                        position="rope", dtype=jnp.float32)
D_CFG = TransformerConfig(vocab=128, layers=1, d_model=32, heads=2, d_ff=64,
                          max_len=64, causal=True, norm="rmsnorm",
                          position="rope", dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_transformer(CFG, seed=0)


@pytest.fixture(scope="module")
def d_params():
    return init_transformer(D_CFG, seed=1)


def _pool(num_pages=16, page_size=4, **kw):
    kw.setdefault("residency", False)
    return PagedKVPool(CFG, num_pages=num_pages, page_size=page_size, **kw)


class TestPoolAllocFree:
    def test_alloc_lowest_first_and_exact(self):
        pool = _pool(num_pages=8)
        a = pool.alloc(3)
        assert a == [1, 2, 3]              # page 0 reserved for trash
        b = pool.alloc(2)
        assert b == [4, 5]
        assert pool.pages_in_use == 5
        pool.free(a)
        assert pool.pages_in_use == 2
        # freed pages are reissued lowest-first, keeping the live span dense
        assert pool.alloc(2) == [1, 2]

    def test_exhaustion_has_no_partial_effect(self):
        pool = _pool(num_pages=4)          # 3 allocatable
        got = pool.alloc(3)
        with pytest.raises(PoolExhausted):
            pool.alloc(1)
        assert pool.stats["alloc_failures"] == 1
        assert pool.pages_in_use == 3
        pool.free(got)
        assert pool.pages_in_use == 0
        # the failed alloc must not have corrupted the free list
        assert sorted(pool.alloc(3)) == [1, 2, 3]

    def test_double_free_raises(self):
        pool = _pool(num_pages=8)
        a = pool.alloc(1)
        pool.free(a)
        with pytest.raises(ValueError):
            pool.free(a)

    def test_refcounted_shared_pages_survive_one_free(self):
        pool = _pool(num_pages=8)
        a = pool.alloc(2)
        pool.incref(a)
        pool.free(a)
        assert pool.pages_in_use == 2      # second holder keeps them live
        pool.free(a)
        assert pool.pages_in_use == 0

    def test_high_water_tracks_peak(self):
        pool = _pool(num_pages=16)
        a = pool.alloc(5)
        pool.free(a)
        pool.alloc(2)
        assert pool.high_water == 5

    def test_pressure_retry_allocs_do_not_count_as_failures(self):
        """alloc_failures means 'failed even after prefix eviction';
        pressure-loop retries suppress the count and report the terminal
        failure explicitly."""
        pool = _pool(num_pages=4)
        pool.alloc(3)
        with pytest.raises(PoolExhausted):
            pool.alloc(1, count_failure=False)
        assert pool.stats["alloc_failures"] == 0
        pool.note_alloc_failure()
        assert pool.stats["alloc_failures"] == 1


class TestPagedParity:
    """Block-table gather vs the contiguous path: bitwise, not approx."""

    def _contig_state(self, params, B, L, steps, rng):
        cache = init_kv_cache(CFG, B, L)
        toks = jnp.asarray(rng.integers(0, CFG.vocab, (steps, B)))
        logits = None
        for t in range(steps):
            logits, cache = decode_step_ragged(
                params, toks[t], jnp.full((B,), t, jnp.int32), cache, CFG)
        return toks, logits, cache

    def test_decode_step_bitwise_equal(self, params):
        B, L, page = 3, 16, 4
        rng = np.random.default_rng(0)
        steps = 5
        toks, _, contig = self._contig_state(params, B, L, steps, rng)
        n_pages = L // page
        bt = jnp.asarray(
            1 + np.arange(B)[:, None] * n_pages + np.arange(n_pages),
            jnp.int32)
        pages = init_paged_cache(CFG, 1 + B * n_pages, page)
        rows = [{"k": c["k"], "v": c["v"]} for c in contig]
        pages = paged_scatter_rows(pages, rows, bt, page)
        # gather round-trips the scatter exactly
        for got, want in zip(paged_gather(pages, bt, L), contig):
            assert np.array_equal(np.asarray(got["k"]),
                                  np.asarray(want["k"]))
        tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
        pos = jnp.full((B,), steps, jnp.int32)
        want_logits, want_cache = decode_step_ragged(
            params, tok, pos, contig, CFG)
        got_logits, pages = decode_step_paged(
            params, tok, pos, pages, bt, CFG, page_size=page, length=L,
            impl="gather")
        assert np.array_equal(np.asarray(got_logits),
                              np.asarray(want_logits))
        for got, want in zip(paged_gather(pages, bt, L), want_cache):
            assert np.array_equal(np.asarray(got["k"]),
                                  np.asarray(want["k"]))
            assert np.array_equal(np.asarray(got["v"]),
                                  np.asarray(want["v"]))

    def test_decode_window_bitwise_equal(self, params):
        B, L, page, W = 2, 16, 4, 3
        rng = np.random.default_rng(1)
        _, _, contig = self._contig_state(params, B, L, 4, rng)
        n_pages = L // page
        bt = jnp.asarray(
            1 + np.arange(B)[:, None] * n_pages + np.arange(n_pages),
            jnp.int32)
        pages = paged_scatter_rows(
            init_paged_cache(CFG, 1 + B * n_pages, page),
            [{"k": c["k"], "v": c["v"]} for c in contig], bt, page)
        wtoks = jnp.asarray(rng.integers(0, CFG.vocab, (B, W)))
        pos = jnp.asarray([4, 2], jnp.int32)
        want_logits, want_cache = decode_window_ragged(
            params, wtoks, pos, contig, CFG)
        got_logits, pages = decode_window_paged(
            params, wtoks, pos, pages, bt, CFG, page_size=page, length=L,
            impl="gather")
        assert np.array_equal(np.asarray(got_logits),
                              np.asarray(want_logits))
        for got, want in zip(paged_gather(pages, bt, L), want_cache):
            assert np.array_equal(np.asarray(got["k"]),
                                  np.asarray(want["k"]))

    def test_inactive_rows_write_trash_not_pages(self, params):
        """A freed slot's block-table row may point at pages now owned by
        another request; inactive rows must land in trash page 0."""
        B, L, page = 2, 16, 4
        rng = np.random.default_rng(2)
        _, _, contig = self._contig_state(params, B, L, 3, rng)
        n_pages = L // page
        bt = jnp.asarray(
            1 + np.arange(B)[:, None] * n_pages + np.arange(n_pages),
            jnp.int32)
        pages = paged_scatter_rows(
            init_paged_cache(CFG, 1 + B * n_pages, page),
            [{"k": c["k"], "v": c["v"]} for c in contig], bt, page)
        before = [np.asarray(c["k"]).copy() for c in pages]
        tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
        active = jnp.asarray([True, False])
        _, pages = decode_step_paged(
            params, tok, jnp.full((B,), 3, jnp.int32), pages, bt, CFG,
            page_size=page, length=L, active=active, impl="gather")
        for lyr, b4 in zip(pages, before):
            after = np.asarray(lyr["k"])
            # row 1's pages are untouched; only row 0's write position and
            # the trash page may differ
            assert np.array_equal(after[1 + n_pages:], b4[1 + n_pages:])

    def test_engine_greedy_parity_vs_generate_cached(self, params):
        """End-to-end: the paged engine's greedy output is token-identical
        to the single-request reference path."""
        eng = ContinuousDecoder(params, CFG, max_slots=3, max_len=48,
                                page_size=4)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, CFG.vocab, n).astype(np.int32)
                   for n in (3, 7, 12)]
        reqs = [eng.submit(p, max_new_tokens=9) for p in prompts]
        while any(r is not None for r in eng._slot_req) or eng._waiting:
            eng.step()
        for p, r in zip(prompts, reqs):
            want = generate_cached(params, p[None, :], CFG,
                                      max_new_tokens=9)
            assert r.tokens == list(np.asarray(want)[0, len(p):])
        # every page returned to the pool on retirement
        assert eng._kv.pages_in_use == 0


class TestPrefixSharing:
    def test_pool_cow_registry(self):
        pool = _pool(num_pages=16)
        toks = np.arange(8, dtype=np.int32)
        h = prefix_hash(toks)
        pages = pool.alloc(2)
        pool.register_prefix(h, pages, 8)
        got, plen = pool.acquire_prefix(h, 2)
        assert got == tuple(pages) and plen == 8
        assert pool.stats["prefix_share_hits"] == 2
        pool.free(list(got))               # the acquirer's handle
        assert pool.pages_in_use == 2      # registry still holds them
        pool.release_prefix(h)
        assert pool.pages_in_use == 2      # the creator's own ref remains
        pool.free(pages)
        assert pool.pages_in_use == 0

    def test_registry_counts_registrations_per_hash(self):
        """Two engine keys with token-identical prefixes share one hash;
        the registry entry must survive until BOTH have released it."""
        pool = _pool(num_pages=16)
        h = prefix_hash(np.arange(8, dtype=np.int32))
        pages = pool.alloc(2)
        pool.register_prefix(h, pages, 8)
        pool.register_prefix(h, pages, 8)      # second key, same tokens
        pool.release_prefix(h)                 # first key evicted
        got, plen = pool.acquire_prefix(h, 2)  # second key still hits
        assert got == tuple(pages) and plen == 8
        pool.free(list(got))                   # the acquirer's handle
        pool.release_prefix(h)                 # last registration frees
        pool.free(pages)                       # the creator's own ref
        assert pool.pages_in_use == 0
        pool.release_prefix(h)                 # unknown hash: no-op

    def test_token_identical_prefixes_under_distinct_keys(self, params):
        """Store-cap eviction of one key must not dangle another key
        whose stored prefix is token-identical (same pool hash): the
        surviving key's next hit used to KeyError in acquire_prefix."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, prefix_cache_size=1)
        rng = np.random.default_rng(13)
        prompt = rng.integers(1, CFG.vocab, 8).astype(np.int32)
        want = None
        # "b"'s miss re-registers the same hash, and its cap eviction of
        # "a" releases one registration; the second "b" submit must hit
        for key in ("a", "b", "b"):
            r = eng.submit(prompt.copy(), max_new_tokens=6, prefix_key=key)
            while not r.done:
                eng.step()
            assert r.error is None
            if want is None:
                want = list(r.tokens)
            assert r.tokens == want
        assert eng.stats["prefix_hits"] >= 1

    def test_pressure_eviction_is_not_an_alloc_failure(self, params):
        """An admission resolved by evicting a cached prefix is a
        success: the terminal-failure counter stays untouched."""
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=32,
                                page_size=4, kv_pages=9)
        rng = np.random.default_rng(14)
        ra = eng.submit(rng.integers(1, CFG.vocab, 8).astype(np.int32),
                        max_new_tokens=4, prefix_key="sys")
        while not ra.done:
            eng.step()
        assert eng._kv.pages_in_use == 2       # the cached prefix
        # 8 usable pages, 2 held by the prefix, next request needs all 8
        prompt = rng.integers(1, CFG.vocab, 20).astype(np.int32)
        rb = eng.submit(prompt, max_new_tokens=12)
        while not rb.done:
            eng.step()
        assert eng._kv.stats["alloc_failures"] == 0
        want = generate_cached(params, prompt[None, :], CFG,
                                  max_new_tokens=12)
        assert rb.tokens == list(np.asarray(want)[0, len(prompt):])

    def test_engine_shares_physical_pages_until_divergence(self, params):
        """Two requests with a common prefix: strictly-common full pages
        are the SAME physical pages (block tables compared), the boundary
        page is copied (CoW), and the share counter counts the reuse."""
        page = 4
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=page)
        rng = np.random.default_rng(4)
        prefix = rng.integers(1, CFG.vocab, 10).astype(np.int32)  # 2.5 pages
        p_a = prefix
        p_b = np.concatenate([prefix,
                              rng.integers(1, CFG.vocab, 3).astype(np.int32)])
        ra = eng.submit(p_a, max_new_tokens=6, prefix_key="sys")
        while not ra.done:
            eng.step()
        shared_before = eng._kv.stats["prefix_share_hits"]
        rb = eng.submit(p_b, max_new_tokens=6, prefix_key="sys")
        # keep A's slot state around: retire it first so B admits alone
        while not rb.done:
            eng.step()
        # strictly-below-boundary pages: 10 tokens / page 4 → s0 = 2 full
        # shared pages, boundary page copied
        assert eng._kv.stats["prefix_share_hits"] - shared_before == 2
        assert eng.stats["prefix_hits"] >= 1
        # outputs both match the reference — sharing never changes tokens
        for p, r in ((p_a, ra), (p_b, rb)):
            want = generate_cached(params, p[None, :], CFG,
                                      max_new_tokens=6)
            assert r.tokens == list(np.asarray(want)[0, len(p):])

    def test_engine_shared_pages_same_physical_ids(self, params):
        """Counter-assert the physical identity, not just the counter:
        while both requests are live, B's first block-table entries are
        A's page ids."""
        page = 4
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=page, prefill_ahead=0)
        rng = np.random.default_rng(5)
        prefix = rng.integers(1, CFG.vocab, 8).astype(np.int32)  # 2 pages
        ra = eng.submit(prefix, max_new_tokens=20, prefix_key="sys")
        eng.step()                          # admit + prefill A
        slot_a = next(i for i, r in enumerate(eng._slot_req)
                      if r is not None and r.rid == ra.rid)
        a_pages = list(eng._slot_pages[slot_a])
        rb = eng.submit(
            np.concatenate([prefix,
                            rng.integers(1, CFG.vocab, 5).astype(np.int32)]),
            max_new_tokens=4, prefix_key="sys")
        while not rb.done:
            eng.step()
        slot_b = next(i for i, r in enumerate(eng._slot_req)
                      if r is not None and r.rid == rb.rid) \
            if not rb.done else None
        # B retired already; its block table row was a_pages[0] at admit —
        # assert via the share counter plus A's pages still being A's
        assert eng._kv.stats["prefix_share_hits"] >= 2
        assert eng._slot_pages[slot_a][:2] == a_pages[:2]
        while not ra.done:
            eng.step()
        assert eng._kv.pages_in_use <= 2    # only the registry's prefix

    def test_engine_divergent_pages_not_shared(self, params):
        """Writes past the prefix NEVER land in shared pages: A keeps
        decoding long after B admitted against its prefix, and B's output
        still matches the reference."""
        page = 4
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=page)
        rng = np.random.default_rng(6)
        prefix = rng.integers(1, CFG.vocab, 8).astype(np.int32)
        ra = eng.submit(prefix, max_new_tokens=24, prefix_key="sys")
        rb = eng.submit(prefix.copy(), max_new_tokens=24, prefix_key="sys")
        while not (ra.done and rb.done):
            eng.step()
        want = generate_cached(params, prefix[None, :], CFG,
                                  max_new_tokens=24)
        want = list(np.asarray(want)[0, len(prefix):])
        assert ra.tokens == want
        assert rb.tokens == want


class TestDefrag:
    def test_pool_compact_remaps_live_pages(self):
        pool = _pool(num_pages=16)
        a = pool.alloc(2)                  # [1, 2]
        b = pool.alloc(2)                  # [3, 4]
        c = pool.alloc(2)                  # [5, 6]
        pool.free(a)
        pool.free(c)
        assert pool.fragmentation() == 2   # span 4, live 2
        remap = pool.compact()
        assert remap is not None
        # b's pages slide down to [1, 2]; identity elsewhere
        assert list(remap[[3, 4]]) == [1, 2]
        assert remap[0] == 0
        assert pool.stats["defrag_moves"] == 2
        assert pool.fragmentation() == 0
        assert pool.compact() is None      # already dense
        pool.free([int(remap[p]) for p in b])
        assert pool.pages_in_use == 0

    def test_engine_defrag_on_retire_preserves_decode(self, params):
        """Retiring an early request compacts the pool; the survivor's
        remaining decode is unaffected (output still reference-equal)."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, defrag_threshold=1)
        rng = np.random.default_rng(7)
        p_short = rng.integers(1, CFG.vocab, 5).astype(np.int32)
        p_long = rng.integers(1, CFG.vocab, 9).astype(np.int32)
        rs = eng.submit(p_short, max_new_tokens=3)
        rl = eng.submit(p_long, max_new_tokens=24)
        while not (rs.done and rl.done):
            eng.step()
        want = generate_cached(params, p_long[None, :], CFG,
                                  max_new_tokens=24)
        assert rl.tokens == list(np.asarray(want)[0, len(p_long):])
        assert eng._kv.stats["defrag_moves"] > 0
        assert eng._kv.pages_in_use == 0


class TestChunkedPrefill:
    def test_no_tick_exceeds_chunk_budget(self, params):
        """Deterministic: a prompt much longer than the chunk budget is
        prefilled across ticks, every per-tick window ≤ the budget, and
        the output is still reference-equal."""
        budget = 8
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=64,
                                page_size=4, prefill_chunk=budget)
        rng = np.random.default_rng(8)
        prompt = rng.integers(1, CFG.vocab, 37).astype(np.int32)
        req = eng.submit(prompt, max_new_tokens=8)
        while not req.done:
            eng.step()
        assert eng._chunk_trace, "long prompt must take the chunked path"
        assert max(eng._chunk_trace) <= budget
        assert eng._kv.stats["prefill_chunks"] == len(eng._chunk_trace)
        want = generate_cached(params, prompt[None, :], CFG,
                                  max_new_tokens=8)
        assert req.tokens == list(np.asarray(want)[0, len(prompt):])

    def test_chunked_prefill_interleaves_with_decode(self, params):
        """A live decode keeps emitting while a long prompt prefills in
        chunks — the head-of-line stall this PR removes."""
        budget = 8
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=64,
                                page_size=4, prefill_chunk=budget)
        rng = np.random.default_rng(9)
        r_live = eng.submit(rng.integers(1, CFG.vocab, 4).astype(np.int32),
                            max_new_tokens=30)
        eng.step()                          # r_live admitted, decoding
        emitted_before = len(r_live.tokens)
        prompt = rng.integers(1, CFG.vocab, 37).astype(np.int32)
        r_long = eng.submit(prompt, max_new_tokens=4)
        # during the long prompt's chunked prefill the live stream advances
        for _ in range(3):
            eng.step()
        assert r_long.rid not in [r.rid for r in eng._waiting]
        assert len(r_live.tokens) > emitted_before
        while not (r_live.done and r_long.done):
            eng.step()
        for p, r in ((prompt, r_long),):
            want = generate_cached(params, p[None, :], CFG,
                                      max_new_tokens=4)
            assert r.tokens == list(np.asarray(want)[0, len(p):])

    def test_short_prompts_skip_chunking(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=64,
                                page_size=4, prefill_chunk=32)
        rng = np.random.default_rng(10)
        req = eng.submit(rng.integers(1, CFG.vocab, 6).astype(np.int32),
                         max_new_tokens=4)
        while not req.done:
            eng.step()
        assert eng._chunk_trace == []
        assert eng._kv.stats["prefill_chunks"] == 0


class TestAdmissionBackout:
    def test_pool_exhaustion_requeues_every_uninserted_request(self, params):
        """When a later bucket group's insertion hits an exhausted pool,
        every assigned-but-uninserted request (that group, remaining
        prefixed, chunked) must return to the queue — a request left in
        a slot with no pages would replay stale device lanes as a
        'successful' garbage completion — and then complete correctly
        once pages free up."""
        rng = np.random.default_rng(15)
        eng = ContinuousDecoder(params, CFG, max_slots=3, max_len=32,
                                page_size=4, kv_pages=9)  # 8 usable pages
        p1 = rng.integers(1, CFG.vocab, 3).astype(np.int32)   # bucket 8
        p2 = rng.integers(1, CFG.vocab, 12).astype(np.int32)  # bucket 16
        p3 = rng.integers(1, CFG.vocab, 8).astype(np.int32)
        r1 = eng.submit(p1, max_new_tokens=12)            # 4 pages
        r2 = eng.submit(p2, max_new_tokens=12)            # 6 pages: fails
        r3 = eng.submit(p3, max_new_tokens=8,             # 4 pages: fails
                        prefix_key="sys")
        for _ in range(500):
            if r1.done and r2.done and r3.done:
                break
            eng.step()
        for p, r in ((p1, r1), (p2, r2), (p3, r3)):
            assert r.done and r.error is None
            want = generate_cached(params, p[None, :], CFG,
                                      max_new_tokens=r.max_new)
            assert r.tokens == list(np.asarray(want)[0, len(p):])
        # only the registered prefix survives the retirements
        assert eng._kv.pages_in_use == 2


class TestSpeculativePaged:
    def test_spec_engine_greedy_parity(self, params, d_params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, draft_params=d_params,
                                draft_cfg=D_CFG, gamma=3)
        rng = np.random.default_rng(11)
        prompts = [rng.integers(1, CFG.vocab, n).astype(np.int32)
                   for n in (4, 9)]
        reqs = [eng.submit(p, max_new_tokens=10) for p in prompts]
        while not all(r.done for r in reqs):
            eng.step()
        for p, r in zip(prompts, reqs):
            want = generate_cached(params, p[None, :], CFG,
                                      max_new_tokens=10)
            assert r.tokens == list(np.asarray(want)[0, len(p):])
        assert eng._kv.pages_in_use == 0

    def test_acceptance_counters_cover_the_same_drained_window(self, params):
        """spec_round_slots is accounted at drain time from the same
        block as spec_emitted. With the draft IDENTICAL to the target,
        acceptance is exactly 1.0: 8 post-insert tokens in 2 rounds —
        dispatch-time accounting would also count the pipeline-depth
        dispatches issued after the slot retired on device and hold the
        measured acceptance below its true value."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, draft_params=params,
                                draft_cfg=CFG, gamma=3, pipeline_depth=2)
        rng = np.random.default_rng(16)
        prompt = rng.integers(1, CFG.vocab, 5).astype(np.int32)
        req = eng.submit(prompt, max_new_tokens=9)
        for _ in range(200):
            if req.done:
                break
            eng.step()
        eng.flush()
        want = generate_cached(params, prompt[None, :], CFG,
                                  max_new_tokens=9)
        assert req.tokens == list(np.asarray(want)[0, len(prompt):])
        assert eng.stats["spec_emitted"] == 8
        assert eng.stats["spec_round_slots"] == 2


class TestAutotuner:
    def test_gamma_raises_on_high_acceptance(self):
        t = KVAutotuner(gamma=2, gamma_max=6, chunk=64, interval=4)
        for _ in range(4):
            # 2 slots/round, every round emits gamma+1 per slot → acc=1.0
            t.observe(2, 4, spec_emitted=(t.gamma + 1) * 2 * 100,
                      spec_round_slots=2 * 100)
        assert t.gamma == 3
        assert t.history and t.history[0]["knob"] == "gamma"

    def test_gamma_drops_on_low_acceptance(self):
        t = KVAutotuner(gamma=3, gamma_max=6, chunk=64, interval=4)
        for _ in range(4):
            t.observe(2, 4, spec_emitted=100, spec_round_slots=100)
        assert t.gamma == 2

    def test_chunk_tracks_occupancy(self):
        t = KVAutotuner(gamma=2, gamma_max=4, chunk=128, interval=2,
                        chunk_min=32, chunk_max=512)
        for _ in range(2):
            t.observe(1, 8)                # 12.5% occupied → grow chunk
        assert t.chunk == 256
        for _ in range(2):
            t.observe(8, 8)                # saturated → shrink
        assert t.chunk == 128

    def test_bounds_respected(self):
        t = KVAutotuner(gamma=1, gamma_max=2, chunk=32, interval=1,
                        chunk_min=32, chunk_max=64)
        t.observe(8, 8, spec_emitted=100, spec_round_slots=100)
        assert t.gamma == 1 and t.chunk == 32

    def test_engine_autotune_smoke(self, params, d_params):
        """autotune=True end-to-end: knobs move, outputs stay reference-
        equal (gamma only changes speed, never tokens)."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=4, draft_params=d_params,
                                draft_cfg=D_CFG, gamma=2, autotune=True)
        rng = np.random.default_rng(12)
        prompt = rng.integers(1, CFG.vocab, 5).astype(np.int32)
        req = eng.submit(prompt, max_new_tokens=20)
        while not req.done:
            eng.step()
        want = generate_cached(params, prompt[None, :], CFG,
                                  max_new_tokens=20)
        assert req.tokens == list(np.asarray(want)[0, len(prompt):])
        assert eng._tuner is not None


class TestResidencyIntegration:
    def test_pool_reserves_and_releases_budget_bytes(self):
        from mmlspark_tpu.core.residency import residency_stats
        before = residency_stats().get("reserved_bytes", 0)
        pool = PagedKVPool(CFG, num_pages=8, page_size=4, residency=True)
        expect = (8 * CFG.heads * 4 * (CFG.d_model // CFG.heads)
                  * jnp.dtype(CFG.dtype).itemsize * 2 * CFG.layers)
        assert residency_stats()["reserved_bytes"] - before == expect
        pool.close()
        assert residency_stats().get("reserved_bytes", 0) == before
