"""Stall watchdog: exactly-once firing per stall, atomic black-box
bundles with the stalled thread's stack, beat() re-arming, the disabled
fast path, and the env knobs. Everything runs against ``scan_once()``
with an injected clock — no daemon timing, no real sleeps.
"""

import glob
import json
import os
import threading

import pytest

from mmlspark_tpu.observability import reset_all, snapshot
from mmlspark_tpu.observability.watchdog import (_NULL_WATCH, BUDGET_ENV,
                                                 DIAG_DIR_ENV, INTERVAL_ENV,
                                                 WATCHDOG_ENV, Watchdog,
                                                 configure, get_watchdog,
                                                 reset_watchdog,
                                                 set_watchdog, watch)


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    for env in (WATCHDOG_ENV, DIAG_DIR_ENV, BUDGET_ENV, INTERVAL_ENV):
        monkeypatch.delenv(env, raising=False)
    reset_watchdog()
    reset_all()
    yield
    reset_watchdog()
    reset_all()


def _make(tmp_path, **kwargs):
    """An enabled watchdog driven entirely by a fake clock; the scan
    interval is huge so the daemon thread never races scan_once()."""
    now = [0.0]
    kwargs.setdefault("enabled", True)
    kwargs.setdefault("interval", 3600.0)
    kwargs.setdefault("default_budget", 1.0)
    wd = Watchdog(diag_dir=str(tmp_path), clock=lambda: now[0], **kwargs)
    return wd, now


def _stall_count(site):
    metric = snapshot().get("mmlspark_watchdog_stalls_total")
    if not metric:
        return 0.0
    return sum(s["value"] for s in metric["series"]
               if s["labels"].get("site") == site)


def test_stall_fires_exactly_once(tmp_path):
    wd, now = _make(tmp_path)
    with wd.watch("device_run", budget_seconds=1.0):
        now[0] = 5.0                       # heartbeat is 5s stale, budget 1s
        records = wd.scan_once()
        assert len(records) == 1
        rec = records[0]
        assert rec["site"] == "device_run"
        assert rec["budget_seconds"] == 1.0
        assert rec["stalled_seconds"] == pytest.approx(5.0)
        assert rec["thread"]["ident"] == threading.get_ident()
        # the same stall does NOT fire again on later scans
        now[0] = 50.0
        assert wd.scan_once() == []
        assert wd.scan_once() == []
    assert _stall_count("device_run") == 1.0
    assert len(glob.glob(str(tmp_path / "watchdog_*.json"))) == 1


def test_beat_rearms_the_trigger(tmp_path):
    wd, now = _make(tmp_path)
    with wd.watch("decoder_decode", budget_seconds=1.0) as w:
        now[0] = 5.0
        assert len(wd.scan_once()) == 1    # first stall
        w.beat()                           # loop recovered
        assert wd.scan_once() == []
        now[0] = 20.0                      # ...and wedged again
        assert len(wd.scan_once()) == 1
    assert _stall_count("decoder_decode") == 2.0
    assert len(glob.glob(str(tmp_path / "watchdog_*.json"))) == 2


def test_bundle_is_atomic_and_has_the_stalled_stack(tmp_path):
    wd, now = _make(tmp_path)

    def _the_wedged_device_call():
        with wd.watch("runner_drain", budget_seconds=1.0):
            now[0] = 10.0
            return wd.scan_once()

    (rec,) = _the_wedged_device_call()
    path = rec["bundle"]
    assert os.path.dirname(path) == str(tmp_path)
    # atomic write: the bundle is complete JSON and no torn .tmp remains
    assert glob.glob(str(tmp_path / "*.tmp")) == []
    with open(path, encoding="utf-8") as fh:
        bundle = json.load(fh)
    assert bundle["site"] == "runner_drain"
    assert bundle["pid"] == os.getpid()
    # the stalled thread's stack is in the bundle, wedged frame included
    key = [k for k in bundle["stacks"]
           if k.startswith(str(rec["thread"]["ident"]))]
    assert key, bundle["stacks"].keys()
    assert "_the_wedged_device_call" in "".join(bundle["stacks"][key[0]])
    assert "faulthandler" in bundle
    # the metrics snapshot rides along for post-mortems
    assert "mmlspark_watchdog_stalls_total" in bundle["metrics"]


def test_clean_exit_writes_nothing(tmp_path):
    wd, now = _make(tmp_path)
    with wd.watch("compile_warmup", budget_seconds=1.0):
        pass                               # finished within budget
    now[0] = 100.0
    assert wd.scan_once() == []            # exited watches are unregistered
    assert glob.glob(str(tmp_path / "*")) == []
    assert _stall_count("compile_warmup") == 0.0


def test_disabled_watch_is_the_shared_noop():
    # default-constructed (env unset) watchdog is disabled
    wd = Watchdog()
    assert wd.enabled is False
    assert wd.watch("x") is _NULL_WATCH
    # the module-level hot path: no watchdog installed -> same no-op,
    # without even constructing the global
    assert watch("x") is _NULL_WATCH
    set_watchdog(wd)
    assert watch("x") is _NULL_WATCH
    # and it is a working context manager with a no-op beat
    with watch("x") as w:
        w.beat()


def test_module_watch_routes_to_enabled_global(tmp_path):
    wd = configure(enabled=True, interval=3600.0,
                   diag_dir=str(tmp_path))
    assert get_watchdog() is wd
    with watch("bench_generation", budget_seconds=99.0) as w:
        assert w is not _NULL_WATCH
        assert len(wd._watches) == 1
        assert w.site == "bench_generation"
    assert len(wd._watches) == 0


def test_on_stall_callbacks_and_last_stall_age(tmp_path):
    wd, now = _make(tmp_path)
    assert wd.last_stall_age() is None
    seen = []
    wd.on_stall(seen.append)
    with wd.watch("device_run", budget_seconds=1.0):
        now[0] = 4.0
        wd.scan_once()
    assert len(seen) == 1
    assert seen[0]["site"] == "device_run"
    assert os.path.isfile(seen[0]["bundle"])
    assert wd.last_stall is not None and wd.last_stall["site"] == "device_run"
    now[0] = 10.0
    assert wd.last_stall_age() == pytest.approx(6.0)


def test_env_knobs(monkeypatch, tmp_path):
    monkeypatch.setenv(WATCHDOG_ENV, "1")
    monkeypatch.setenv(BUDGET_ENV, "7.5")
    monkeypatch.setenv(INTERVAL_ENV, "0.25")
    monkeypatch.setenv(DIAG_DIR_ENV, str(tmp_path / "diag"))
    reset_watchdog()
    wd = get_watchdog()
    assert wd.enabled is True
    assert wd.default_budget == 7.5
    assert wd.interval == 0.25
    assert wd.diag_dir() == str(tmp_path / "diag")
    assert os.path.isdir(wd.diag_dir())


def test_budget_falls_back_to_default(tmp_path):
    wd, now = _make(tmp_path, default_budget=2.0)
    with wd.watch("site_a") as w:          # no explicit budget
        assert w.budget == 2.0
        now[0] = 1.5
        assert wd.scan_once() == []        # under budget: quiet
        now[0] = 3.0
        assert len(wd.scan_once()) == 1
