"""Device residency: the one-h2d/one-d2h pipeline contract, LRU spill,
staging slabs, and the serving/runner integration points.

Every transfer assertion reads the ``mmlspark_residency_*`` counters — the
same numbers bench.py embeds — so these tests pin the *accounting* as well
as the behavior."""

import numpy as np
import pytest

import mmlspark_tpu.core.residency as R
from mmlspark_tpu.core import DataFrame, Pipeline, concat
from mmlspark_tpu.core import schema as S
from mmlspark_tpu.core.pipeline import DeviceTransformer
from mmlspark_tpu.core.residency import (DeviceColumn, HostMirror,
                                         configure_residency,
                                         get_residency_manager,
                                         residency_stats)
from mmlspark_tpu.models.runner import StagingSlabPool
from mmlspark_tpu.observability import reset_all
from mmlspark_tpu.ops.padding import pad_axis_device


@pytest.fixture(autouse=True)
def _clean_slate():
    # drop any chunks earlier tests left resident, zero the counters, and
    # run unbudgeted unless a test configures otherwise
    get_residency_manager().spill_all()
    configure_residency(0)
    reset_all()
    yield
    configure_residency(0)


def _h2d(site):
    return R.M_H2D.labels(site=site).get()


def _d2h(site):
    return R.M_D2H.labels(site=site).get()


class Scale(DeviceTransformer):
    def _transform_device(self, arrays):
        return {n: a * 2.0 for n, a in arrays.items()}


# ---------------------------------------------------------------------------
# the tentpole contract: one h2d at ingest, one d2h at the sink


def test_three_stage_pipeline_moves_data_exactly_twice():
    df = DataFrame({"x": np.arange(8, dtype=np.float32)})
    model = Pipeline(stages=[Scale(input_cols=["x"]),
                             Scale(input_cols=["x"]),
                             Scale(input_cols=["x"])]).fit(df)
    reset_all()   # fit's pass-through transforms staged their own copy
    out = model.transform(df)

    # stage 1 staged the column (one miss, one ingest transfer op);
    # stages 2 and 3 found it resident (hits, zero transfers)
    assert _h2d("ingest") == 1
    assert _h2d("restage") == 0
    assert R.M_MISSES.labels().get() == 1
    assert R.M_HITS.labels().get() == 2
    assert _d2h("sink") == 0     # nothing has left the device yet

    host = out.to_host()
    assert _d2h("sink") == 1     # ONE batched fetch at the sink
    assert _d2h("materialize") == 0
    np.testing.assert_allclose(host["x"], np.arange(8) * 8.0)

    stats = residency_stats()
    assert stats["residency_hit_rate"] == pytest.approx(2 / 3)


def test_device_put_is_idempotent():
    df = DataFrame({"x": np.arange(4, dtype=np.float32)})
    staged = df.device_put(["x"])
    again = staged.device_put(["x"])
    assert again.is_resident("x")
    assert _h2d("ingest") == 1
    assert R.M_HITS.labels().get() == 1
    assert R.M_MISSES.labels().get() == 1


def test_row_ops_stay_resident_and_keep_metadata():
    df = DataFrame({"x": np.arange(12, dtype=np.float32)}, npartitions=3)
    df = S.set_categorical_metadata(df, "x", ["lo", "hi"])
    df = df.device_put(["x"])

    out = (df.filter(np.arange(12) % 2 == 0)
             .take([0, 2, 4])
             .sort_values("x", ascending=False)
             .repartition(2)
             .head(2))
    assert out.is_resident("x")
    assert S.get_categorical_levels(out, "x") == ["lo", "hi"]
    # the whole chain ran on device: still the single ingest transfer,
    # nothing pulled back to host
    assert _h2d("ingest") == 1
    assert _d2h("sink") == 0 and _d2h("materialize") == 0
    # evens -> take rows 0/2/4 of them ([0, 4, 8]) -> sorted descending
    np.testing.assert_allclose(out.to_host()["x"], [8.0, 4.0])


def test_concat_of_resident_frames_stays_resident():
    df = DataFrame({"x": np.arange(6, dtype=np.float32)},
                   npartitions=2).device_put(["x"])
    parts = list(df.partitions())
    back = concat(parts)
    assert back.is_resident("x")
    assert _d2h("sink") == 0 and _d2h("materialize") == 0
    np.testing.assert_allclose(back.to_host()["x"], np.arange(6))


# ---------------------------------------------------------------------------
# LRU spill under a device-memory budget


def test_lru_spill_respects_budget_and_restages_on_access():
    df = DataFrame({"x": np.zeros(16, dtype=np.float32)}, npartitions=4)
    df = df.device_put(["x"])        # 4 chunks x 16 bytes
    col = df.device_column("x")
    assert col.chunk_states() == ["device"] * 4

    configure_residency(32)          # room for 2 of the 4 chunks
    assert col.chunk_states() == ["spilled", "spilled", "device", "device"]
    stats = get_residency_manager().stats()
    assert stats["resident_bytes"] <= 32
    assert R.M_SPILLS.labels().get() == 2
    # ingest-staged chunks kept their host view — spilling them is free
    assert _d2h("spill") == 0

    # touching the column restages the spilled chunks (counted) and the
    # data survives the round trip
    assert len(col.device_array()) == 16
    assert _h2d("restage") > 0


def test_spill_is_lru_ordered():
    df = DataFrame({"x": np.zeros(16, dtype=np.float32)}, npartitions=4)
    df = df.device_put(["x"])
    col = df.device_column("x")
    # touch chunk 0 so it is most-recently-used before the squeeze
    col.slice_rows(0, 4).device_array()
    configure_residency(32)
    states = col.chunk_states()
    assert states[0] == "device"     # recently used: survived
    assert states.count("spilled") == 2


# ---------------------------------------------------------------------------
# HostMirror: device-born columns materialize lazily, once, counted


def test_host_mirror_materializes_once_and_is_counted():
    import jax.numpy as jnp
    df = DataFrame({"x": np.arange(4, dtype=np.float32)})
    df = df.with_device_column("y", jnp.arange(4, dtype=jnp.float32) + 1)
    assert df.is_resident("y")
    assert _d2h("materialize") == 0  # shape/dtype queries are free

    first = df["y"]
    assert _d2h("materialize") == 1
    assert R.M_MATERIALIZE.labels(op="materialize").get() == 1
    np.testing.assert_allclose(first, [1, 2, 3, 4])
    df["y"]                          # cached: no second transfer
    assert _d2h("materialize") == 1


def test_to_host_returns_plain_frame():
    df = DataFrame({"x": np.arange(4, dtype=np.float32)}).device_put(["x"])
    host = df.to_host()
    assert not host.resident_columns
    assert isinstance(host["x"], np.ndarray)


# ---------------------------------------------------------------------------
# serving: already-resident inputs are not re-staged


def test_serving_stage_ingest_skips_resident_input():
    from mmlspark_tpu.serving.engine import ServingEngine
    eng = ServingEngine(transform_fn=lambda df: df,
                        schema={"x": float}, device_ingest=["x"])
    try:
        parsed = DataFrame({"x": np.arange(4, dtype=np.float32)})
        staged = eng._stage_ingest(parsed)
        assert staged.is_resident("x")
        assert _h2d("ingest") == 1 and R.M_MISSES.labels().get() == 1

        again = eng._stage_ingest(staged)
        assert again.is_resident("x")
        assert _h2d("ingest") == 1          # no re-stage
        assert R.M_HITS.labels().get() == 1
    finally:
        eng.server.close()


# ---------------------------------------------------------------------------
# runner integration: resident columns feed device slices, zero h2d payload


def test_jax_model_feeds_resident_column_without_host_roundtrip():
    from mmlspark_tpu.models.jax_model import JaxModel
    m = JaxModel(apply_fn=lambda p, f: {"y": f["input"] * 3.0},
                 feed_dict={"input": "x"}, mini_batch_size=4,
                 prefetch_depth=0)
    df = DataFrame({"x": np.arange(8, dtype=np.float32)}).device_put(["x"])
    out = m.transform(df)
    np.testing.assert_allclose(out["y"], np.arange(8) * 3.0)
    # the runner counted one residency hit per device-fed batch and moved
    # zero payload bytes over the h2d stage
    assert R.M_HITS.labels().get() >= 2      # 8 rows / 4 per batch
    assert m.stage_counters.snapshot()["h2d"]["bytes"] == 0


# ---------------------------------------------------------------------------
# staging slabs + device padding


def test_staging_slab_pool_reuses_and_caps():
    pool = StagingSlabPool(depth=2)
    a = pool.acquire((4, 2), np.float32)
    b = pool.acquire((4, 2), np.float32)
    assert pool.stats()["allocs"] == 2
    pool.release(a)
    c = pool.acquire((4, 2), np.float32)
    assert c is a and pool.stats()["reuses"] == 1
    # foreign arrays are ignored, issued slabs recirculate at most `depth`
    assert not pool.release(np.zeros((4, 2), np.float32))
    for arr in (b, c):
        assert pool.release(arr)
    assert not pool.release(c)               # double release is a no-op


def test_pad_axis_device_stays_on_device():
    import jax
    arr = jax.device_put(np.arange(6, dtype=np.float32))
    padded = pad_axis_device(arr, 8)
    assert R.is_device_array(padded)
    assert padded.shape == (8,)
    np.testing.assert_allclose(np.asarray(padded)[6:], 0.0)
    assert pad_axis_device(arr, 6) is arr    # already at bucket: no-op


def test_device_column_transfer_batching():
    # a multi-partition ingest is ONE transfer op; a multi-chunk sink
    # fetch is ONE transfer op — the batched-put/get accounting bench
    # reports depends on this
    df = DataFrame({"x": np.arange(12, dtype=np.float32)}, npartitions=3)
    df = df.device_put(["x"])
    assert _h2d("ingest") == 1
    col = df.device_column("x")
    assert len(col.chunk_states()) == 3
    col.to_host()
    # ingest kept host views, so the sink fetch is free (no host-less
    # chunks); a device-born column pays exactly one
    dcol = DeviceColumn.from_device(
        [c * 1.0 for c in col.device_chunks()])
    dcol.to_host()
    assert _d2h("sink") == 1
