"""Pallas paged-attention kernel (``ops/paged_attention.py``).

The contract this file pins:

1. PARITY — the kernel path (``impl="kernel"``) agrees with PR 7's
   gather path to f32 accumulation-order tolerance on logits (decode
   step AND speculative windows gamma ∈ {1, 4, 16}), with ragged
   per-row positions that cross page boundaries. Greedy argmaxes are
   identical for these seeds, which is what lets the engine default to
   the kernel without perturbing token streams.
2. SCATTER — the fused variant's page writes are BITWISE identical to
   the gather path's separate ``_paged_writeback`` on the first layer
   (later layers inherit the logits' tolerance-level drift through the
   layer stack); inactive rows land in trash page 0, never in pages
   their stale block-table rows still reference.
3. MASKING — a row with zero cached keys (fully-masked fresh slot)
   yields zeros from the read-only kernel, and a ``pos == 0`` row in
   the fused kernel attends only its own window.
4. CI — the whole thing runs under ``JAX_PLATFORMS=cpu`` via Pallas
   interpret mode, and the ``ContinuousDecoder`` smoke test pays zero
   steady-state recompiles once its tick program is warm.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from mmlspark_tpu.models.zoo.transformer import (
    TransformerConfig, decode_step_paged, decode_step_ragged,
    decode_window_paged, generate_cached, init_kv_cache,
    init_paged_cache, init_transformer, paged_gather, paged_scatter_rows)
from mmlspark_tpu.ops.compile_cache import jit_cache_size
from mmlspark_tpu.ops.paged_attention import (
    ENV_KNOB, aligned_page_size, paged_attention, paged_attention_window,
    resolve_impl, sublane_multiple)
from mmlspark_tpu.serving.continuous import ContinuousDecoder

CFG = TransformerConfig(vocab=128, layers=2, d_model=64, heads=4, d_ff=128,
                        max_len=96, causal=True, norm="rmsnorm",
                        position="rope", dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_transformer(CFG, seed=0)


def _contig_state(params, B, L, steps, rng):
    """Decode `steps` random tokens through the contiguous ragged path."""
    cache = init_kv_cache(CFG, B, L)
    toks = jnp.asarray(rng.integers(0, CFG.vocab, (steps, B)))
    for t in range(steps):
        _, cache = decode_step_ragged(
            params, toks[t], jnp.full((B,), t, jnp.int32), cache, CFG)
    return cache


def _paged_state(params, B, L, page, steps, rng):
    """Contiguous warm-up scattered into a dense page pool + block table."""
    contig = _contig_state(params, B, L, steps, rng)
    n_pages = L // page
    bt = jnp.asarray(
        1 + np.arange(B)[:, None] * n_pages + np.arange(n_pages),
        jnp.int32)
    pages = paged_scatter_rows(
        init_paged_cache(CFG, 1 + B * n_pages, page),
        [{"k": c["k"], "v": c["v"]} for c in contig], bt, page)
    return pages, bt


class TestResolveImpl:
    def test_default_is_kernel(self, monkeypatch):
        monkeypatch.delenv(ENV_KNOB, raising=False)
        assert resolve_impl() == "kernel"

    def test_env_knob_selects_gather(self, monkeypatch):
        for alias in ("gather", "xla", "reference", " GATHER "):
            monkeypatch.setenv(ENV_KNOB, alias)
            assert resolve_impl() == "gather"
        for alias in ("kernel", "fused", "auto", "default"):
            monkeypatch.setenv(ENV_KNOB, alias)
            assert resolve_impl() == "kernel"

    def test_override_beats_env(self, monkeypatch):
        monkeypatch.setenv(ENV_KNOB, "gather")
        assert resolve_impl("kernel") == "kernel"

    def test_unknown_impl_raises(self, monkeypatch):
        monkeypatch.delenv(ENV_KNOB, raising=False)
        with pytest.raises(ValueError):
            resolve_impl("mystery")
        monkeypatch.setenv(ENV_KNOB, "mystery")
        with pytest.raises(ValueError):
            resolve_impl()

    def test_alignment_contract(self):
        # f32 sublane tile is 8; already-compliant sizes are identity
        assert sublane_multiple(jnp.float32) == 8
        assert sublane_multiple(jnp.bfloat16) == 16
        assert aligned_page_size(4, jnp.float32) == 8
        assert aligned_page_size(16, jnp.float32) == 16


class TestOpsKernel:
    """The raw kernel vs a plain-numpy reference (no transformer around
    it) — interpret mode, which is what CI exercises."""

    def _pool(self, rng, N, H, page, hd):
        k = jnp.asarray(rng.normal(0, 1, (N, H, page, hd)), jnp.float32)
        v = jnp.asarray(rng.normal(0, 1, (N, H, page, hd)), jnp.float32)
        return k, v

    def _reference(self, q, kc, vc, lengths):
        """(B,H,W,hd) queries over contiguous (B,H,L,hd) keys, first
        lengths[b] valid; zeros for lengths[b]==0."""
        B, H, W, hd = q.shape
        L = kc.shape[2]
        out = np.zeros_like(q)
        for b in range(B):
            n = int(lengths[b])
            if n == 0:
                continue
            s = np.einsum("hwd,hkd->hwk", q[b], kc[b, :, :n]) / np.sqrt(hd)
            s = s - s.max(-1, keepdims=True)
            p = np.exp(s)
            p /= p.sum(-1, keepdims=True)
            out[b] = np.einsum("hwk,hkd->hwd", p, vc[b, :, :n])
        return out

    def test_read_kernel_ragged_lengths_cross_pages(self):
        B, H, page, hd, P = 4, 2, 4, 8, 3
        rng = np.random.default_rng(7)
        kp, vp = self._pool(rng, 1 + B * P, H, page, hd)
        bt = jnp.asarray(
            1 + np.arange(B)[:, None] * P + np.arange(P), jnp.int32)
        # 0 = fully-masked fresh slot; 3 = mid-page; 4 = exact boundary;
        # 11 = crosses two boundaries into the last page's tail
        lengths = jnp.asarray([0, 3, 4, 11], jnp.int32)
        q = jnp.asarray(rng.normal(0, 1, (B, H, 1, hd)), jnp.float32)
        got = paged_attention(q, kp, vp, bt, lengths, interpret=True)
        kc = np.asarray(kp)[np.asarray(bt)].transpose(0, 2, 1, 3, 4)
        kc = kc.reshape(B, H, P * page, hd)
        vc = np.asarray(vp)[np.asarray(bt)].transpose(0, 2, 1, 3, 4)
        vc = vc.reshape(B, H, P * page, hd)
        want = self._reference(np.asarray(q), kc, vc, np.asarray(lengths))
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=2e-6, atol=2e-6)
        assert np.all(np.asarray(got)[0] == 0.0)   # lengths==0 → zeros

    def test_window_kernel_scatters_and_masks_causally(self):
        B, H, page, hd, P, W = 2, 2, 4, 8, 4, 5
        rng = np.random.default_rng(8)
        kp, vp = self._pool(rng, 1 + B * P, H, page, hd)
        bt = jnp.asarray(
            1 + np.arange(B)[:, None] * P + np.arange(P), jnp.int32)
        # pos=7: window 7..11 straddles a page boundary; pos=0: fresh
        # slot, the window is the row's entire visible context
        pos = jnp.asarray([7, 0], jnp.int32)
        q = jnp.asarray(rng.normal(0, 1, (B, H, W, hd)), jnp.float32)
        kn = jnp.asarray(rng.normal(0, 1, (B, H, W, hd)), jnp.float32)
        vn = jnp.asarray(rng.normal(0, 1, (B, H, W, hd)), jnp.float32)
        ctx, kp2, vp2 = paged_attention_window(
            q, kn, vn, kp, vp, bt, pos, interpret=True)
        # reference: contiguous overlay of window rows at pos..pos+W-1
        kc = np.asarray(kp)[np.asarray(bt)].transpose(0, 2, 1, 3, 4)
        kc = kc.reshape(B, H, P * page, hd).copy()
        vc = np.asarray(vp)[np.asarray(bt)].transpose(0, 2, 1, 3, 4)
        vc = vc.reshape(B, H, P * page, hd).copy()
        for b in range(B):
            p0 = int(pos[b])
            kc[b, :, p0:p0 + W] = np.asarray(kn)[b]
            vc[b, :, p0:p0 + W] = np.asarray(vn)[b]
        for j in range(W):
            want = self._reference(
                np.asarray(q)[:, :, j:j + 1], kc, vc,
                np.asarray(pos) + j + 1)
            np.testing.assert_allclose(
                np.asarray(ctx)[:, :, j:j + 1], want, rtol=3e-6, atol=3e-6)
        # the scatter itself is bitwise: pool rows at pos..pos+W-1 now
        # hold exactly k_new/v_new
        kp2n, vp2n = np.asarray(kp2), np.asarray(vp2)
        for b in range(B):
            for j in range(W):
                t = int(pos[b]) + j
                pg, off = int(bt[b, t // page]), t % page
                assert np.array_equal(kp2n[pg, :, off], np.asarray(kn)[b, :, j])
                assert np.array_equal(vp2n[pg, :, off], np.asarray(vn)[b, :, j])

    def test_window_inactive_rows_only_touch_trash(self):
        B, H, page, hd, P, W = 2, 2, 4, 8, 2, 2
        rng = np.random.default_rng(9)
        kp, vp = self._pool(rng, 1 + B * P, H, page, hd)
        bt = jnp.asarray(
            1 + np.arange(B)[:, None] * P + np.arange(P), jnp.int32)
        pos = jnp.asarray([3, 2], jnp.int32)
        active = jnp.asarray([True, False])
        before_k = np.asarray(kp).copy()
        q = jnp.asarray(rng.normal(0, 1, (B, H, W, hd)), jnp.float32)
        kn = jnp.asarray(rng.normal(0, 1, (B, H, W, hd)), jnp.float32)
        vn = jnp.asarray(rng.normal(0, 1, (B, H, W, hd)), jnp.float32)
        _, kp2, _ = paged_attention_window(
            q, kn, vn, kp, vp, bt, pos, active=active, interpret=True)
        after_k = np.asarray(kp2)
        # row 1's pages (ids 3..4) are untouched; only row 0's pages and
        # the trash page may differ
        assert np.array_equal(after_k[1 + P:], before_k[1 + P:])
        assert not np.array_equal(after_k[1:1 + P], before_k[1:1 + P])


class TestDecodeParity:
    """Kernel vs gather through the full transformer decode paths."""

    def test_decode_step_kernel_vs_gather(self, params):
        B, L, page = 3, 16, 4
        rng = np.random.default_rng(0)
        pages, bt = _paged_state(params, B, L, page, 5, rng)
        tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
        # 3 = mid-page write, 4 = page-boundary write, 0 = fresh slot
        pos = jnp.asarray([3, 4, 0], jnp.int32)
        want, want_pages = decode_step_paged(
            params, tok, pos, pages, bt, CFG, page_size=page, length=L,
            impl="gather")
        got, got_pages = decode_step_paged(
            params, tok, pos, pages, bt, CFG, page_size=page, length=L,
            impl="kernel")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        assert np.array_equal(np.argmax(np.asarray(got), -1),
                              np.argmax(np.asarray(want), -1))
        # layer 0's page writes are bitwise (same projection inputs);
        # deeper layers inherit the context drift, tolerance there
        assert np.array_equal(np.asarray(got_pages[0]["k"]),
                              np.asarray(want_pages[0]["k"]))
        assert np.array_equal(np.asarray(got_pages[0]["v"]),
                              np.asarray(want_pages[0]["v"]))
        for g, w in zip(got_pages[1:], want_pages[1:]):
            np.testing.assert_allclose(np.asarray(g["k"]),
                                       np.asarray(w["k"]),
                                       rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("gamma", [1, 4, 16])
    def test_decode_window_kernel_vs_gather(self, params, gamma):
        """Speculative verify windows: gamma+1 query rows, ragged pos
        crossing page boundaries."""
        B, L, page = 2, 64, 4
        W = gamma + 1
        rng = np.random.default_rng(gamma)
        pages, bt = _paged_state(params, B, L, page, 20, rng)
        wtoks = jnp.asarray(rng.integers(0, CFG.vocab, (B, W)))
        pos = jnp.asarray([7, 0], jnp.int32)   # page-crossing + fresh
        want, want_pages = decode_window_paged(
            params, wtoks, pos, pages, bt, CFG, page_size=page, length=L,
            impl="gather")
        got, got_pages = decode_window_paged(
            params, wtoks, pos, pages, bt, CFG, page_size=page, length=L,
            impl="kernel")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        assert np.array_equal(np.argmax(np.asarray(got), -1),
                              np.argmax(np.asarray(want), -1))
        assert np.array_equal(np.asarray(got_pages[0]["k"]),
                              np.asarray(want_pages[0]["k"]))

    def test_inactive_rows_write_trash_not_pages_kernel(self, params):
        B, L, page = 2, 16, 4
        rng = np.random.default_rng(2)
        pages, bt = _paged_state(params, B, L, page, 3, rng)
        n_pages = L // page
        before = [np.asarray(c["k"]).copy() for c in pages]
        tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
        active = jnp.asarray([True, False])
        _, pages = decode_step_paged(
            params, tok, jnp.full((B,), 3, jnp.int32), pages, bt, CFG,
            page_size=page, length=L, active=active, impl="kernel")
        for lyr, b4 in zip(pages, before):
            after = np.asarray(lyr["k"])
            assert np.array_equal(after[1 + n_pages:], b4[1 + n_pages:])


class TestEngineSmoke:
    def test_engine_kernel_token_parity_and_zero_recompiles(self, params):
        """The engine on the kernel impl: token-identical to the
        reference path, and same-shape batches after the first are pure
        jit-cache hits (zero steady-state recompiles)."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                page_size=8, paged_attn="kernel")
        assert eng._attn_impl == "kernel"
        rng = np.random.default_rng(11)

        def run(prompt, n=6):
            r = eng.submit(prompt, max_new_tokens=n)
            while not r.done:
                eng.step()
            assert r.error is None
            return r

        p1 = rng.integers(1, CFG.vocab, 5).astype(np.int32)
        r1 = run(p1)
        want = generate_cached(params, p1[None, :], CFG, max_new_tokens=6)
        assert r1.tokens == list(np.asarray(want)[0, len(p1):])

        warm = jit_cache_size(eng._tick)
        run(rng.integers(1, CFG.vocab, 5).astype(np.int32))
        run(rng.integers(1, CFG.vocab, 5).astype(np.int32))
        after = jit_cache_size(eng._tick)
        if warm is not None:                    # introspection available
            assert after == warm
        # every tick was accounted to the kernel impl, zero gather bytes
        assert eng._kv.stats["attn_ticks_kernel"] > 0
        assert eng._kv.stats["attn_ticks_gather"] == 0
        assert eng._kv.stats["gather_bytes"] == 0
        assert eng._kv.pages_in_use == 0

    def test_engine_gather_fallback_counts_bytes(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=32,
                                page_size=4, paged_attn="gather")
        assert eng._attn_impl == "gather"
        rng = np.random.default_rng(12)
        r = eng.submit(rng.integers(1, CFG.vocab, 4).astype(np.int32),
                       max_new_tokens=4)
        while not r.done:
            eng.step()
        assert eng._kv.stats["attn_ticks_gather"] > 0
        assert eng._kv.stats["gather_bytes"] > 0

    def test_engine_env_knob_reaches_engine(self, params, monkeypatch):
        monkeypatch.setenv(ENV_KNOB, "gather")
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=32,
                                page_size=4)
        assert eng._attn_impl == "gather"
