"""Expert-parallel MoE tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mmlspark_tpu.parallel.moe import (init_moe_params, moe_capacity,
                                       moe_ffn_local, moe_ffn_sharded,
                                       moe_shardings)

E, D, F = 8, 16, 32


def _mesh(ep):
    devs = jax.devices()[:ep]
    return Mesh(np.array(devs), ("ep",))


def _dense_reference(x, params):
    """Every token through its argmax expert, no capacity limit."""
    logits = x @ params["gate"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = np.argmax(np.asarray(probs), axis=-1)
    out = np.zeros_like(np.asarray(x))
    for t in range(x.shape[0]):
        e = expert[t]
        h = np.asarray(jax.nn.gelu(
            np.asarray(x)[t] @ params["w1"][e] + params["b1"][e]))
        out[t] = (h @ params["w2"][e] + params["b2"][e]) \
            * float(probs[t, e])
    return out


class TestMoE:
    def test_local_matches_dense_reference(self):
        rng = np.random.default_rng(0)
        params = init_moe_params(D, F, E, seed=1)
        x = jnp.asarray(rng.normal(0, 1, (24, D)).astype(np.float32))
        y, dropped = moe_ffn_local(x, params, E, capacity=24)
        assert float(dropped) == 0
        np.testing.assert_allclose(np.asarray(y), _dense_reference(x, params),
                                   rtol=1e-4, atol=1e-5)

    def test_sharded_matches_local_when_nothing_drops(self):
        ep = 4
        mesh = _mesh(ep)
        rng = np.random.default_rng(0)
        params = init_moe_params(D, F, E, seed=1)
        T = 32  # 8 tokens per shard
        x = jnp.asarray(rng.normal(0, 1, (T, D)).astype(np.float32))
        params_d = jax.device_put(params, moe_shardings(mesh))
        xd = jax.device_put(x, NamedSharding(mesh, P("ep", None)))
        cap = T // ep  # generous: every local token could hit one expert
        y_sh, dropped = jax.jit(
            lambda x, p: moe_ffn_sharded(x, p, mesh, E, cap))(xd, params_d)
        assert float(dropped) == 0
        y_loc, _ = moe_ffn_local(x, params, E, capacity=T)
        np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_loc),
                                   rtol=1e-4, atol=1e-5)

    def test_capacity_drops_overflow_tokens(self):
        params = init_moe_params(D, F, E, seed=1)
        # force every token to one expert: huge gate bias via weights
        params["gate"][:] = 0
        params["gate"][:, 3] = 10.0
        x = jnp.ones((10, D), jnp.float32)
        y, dropped = moe_ffn_local(x, params, E, capacity=4)
        assert float(dropped) == 6  # 10 routed, 4 kept
        # every over-capacity token (4..9) contributes zero output
        assert np.abs(np.asarray(y)[4:]).sum() == 0

    def test_gradients_flow_through_all_to_all(self):
        ep = 2
        mesh = _mesh(ep)
        params = init_moe_params(D, F, E, seed=2)
        params_d = jax.device_put(params, moe_shardings(mesh))
        rng = np.random.default_rng(3)
        x = jax.device_put(
            jnp.asarray(rng.normal(0, 1, (8, D)).astype(np.float32)),
            NamedSharding(mesh, P("ep", None)))

        def loss(p, x):
            y, _ = moe_ffn_sharded(x, p, mesh, E, capacity=8)
            return jnp.sum(y ** 2)

        grads = jax.jit(jax.grad(loss))(params_d, x)
        gw1 = np.asarray(grads["w1"])
        assert np.isfinite(gw1).all()
        assert np.abs(gw1).sum() > 0  # experts actually received tokens

    def test_capacity_helper(self):
        assert moe_capacity(64, 8, 1.25) == 10
        assert moe_capacity(1, 8, 1.0) == 1
