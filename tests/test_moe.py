"""Expert-parallel MoE tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mmlspark_tpu.parallel.moe import (init_moe_params, moe_capacity,
                                       moe_ffn_local, moe_ffn_sharded,
                                       moe_shardings)

E, D, F = 8, 16, 32


def _mesh(ep):
    devs = jax.devices()[:ep]
    return Mesh(np.array(devs), ("ep",))


def _dense_reference(x, params):
    """Every token through its argmax expert, no capacity limit."""
    logits = x @ params["gate"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = np.argmax(np.asarray(probs), axis=-1)
    out = np.zeros_like(np.asarray(x))
    for t in range(x.shape[0]):
        e = expert[t]
        h = np.asarray(jax.nn.gelu(
            np.asarray(x)[t] @ params["w1"][e] + params["b1"][e]))
        out[t] = (h @ params["w2"][e] + params["b2"][e]) \
            * float(probs[t, e])
    return out


class TestMoE:
    def test_local_matches_dense_reference(self):
        rng = np.random.default_rng(0)
        params = init_moe_params(D, F, E, seed=1)
        x = jnp.asarray(rng.normal(0, 1, (24, D)).astype(np.float32))
        y, aux = moe_ffn_local(x, params, E, capacity=24)
        assert float(aux["dropped"]) == 0
        np.testing.assert_allclose(np.asarray(y), _dense_reference(x, params),
                                   rtol=1e-4, atol=1e-5)

    def test_sharded_matches_local_when_nothing_drops(self):
        ep = 4
        mesh = _mesh(ep)
        rng = np.random.default_rng(0)
        params = init_moe_params(D, F, E, seed=1)
        T = 32  # 8 tokens per shard
        x = jnp.asarray(rng.normal(0, 1, (T, D)).astype(np.float32))
        params_d = jax.device_put(params, moe_shardings(mesh))
        xd = jax.device_put(x, NamedSharding(mesh, P("ep", None)))
        cap = T // ep  # generous: every local token could hit one expert
        y_sh, aux = jax.jit(
            lambda x, p: moe_ffn_sharded(x, p, mesh, E, cap))(xd, params_d)
        assert float(aux["dropped"]) == 0
        y_loc, _ = moe_ffn_local(x, params, E, capacity=T)
        np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_loc),
                                   rtol=1e-4, atol=1e-5)

    def test_capacity_drops_overflow_tokens(self):
        params = init_moe_params(D, F, E, seed=1)
        # force every token to one expert: huge gate bias via weights
        params["gate"][:] = 0
        params["gate"][:, 3] = 10.0
        x = jnp.ones((10, D), jnp.float32)
        y, aux = moe_ffn_local(x, params, E, capacity=4)
        assert float(aux["dropped"]) == 6  # 10 routed, 4 kept
        assert float(aux["balance_loss"]) > 1.0  # fully collapsed router
        # every over-capacity token (4..9) contributes zero output
        assert np.abs(np.asarray(y)[4:]).sum() == 0

    def test_gradients_flow_through_all_to_all(self):
        ep = 2
        mesh = _mesh(ep)
        params = init_moe_params(D, F, E, seed=2)
        params_d = jax.device_put(params, moe_shardings(mesh))
        rng = np.random.default_rng(3)
        x = jax.device_put(
            jnp.asarray(rng.normal(0, 1, (8, D)).astype(np.float32)),
            NamedSharding(mesh, P("ep", None)))

        def loss(p, x):
            y, _ = moe_ffn_sharded(x, p, mesh, E, capacity=8)
            return jnp.sum(y ** 2)

        grads = jax.jit(jax.grad(loss))(params_d, x)
        gw1 = np.asarray(grads["w1"])
        assert np.isfinite(gw1).all()
        assert np.abs(gw1).sum() > 0  # experts actually received tokens

    def test_capacity_helper(self):
        assert moe_capacity(64, 8, 1.25) == 10
        assert moe_capacity(1, 8, 1.0) == 1


class TestMoEGspmd:
    def test_gspmd_matches_local_per_group(self):
        """The constraint-style variant must equal the local reference
        applied per group (no drops)."""
        rng = np.random.default_rng(5)
        params = init_moe_params(D, F, E, seed=6)
        G, Tg = 4, 12
        t = jnp.asarray(rng.normal(0, 1, (G, Tg, D)).astype(np.float32))
        from mmlspark_tpu.parallel.moe import moe_ffn_gspmd
        y, aux = jax.jit(
            lambda t, p: moe_ffn_gspmd(t, p, E, capacity=Tg))(t, params)
        assert float(aux["dropped"]) == 0
        assert float(aux["balance_loss"]) >= 1.0  # E*sum(f*P) >= 1 always
        for g in range(G):
            y_ref, _ = moe_ffn_local(t[g], params, E, capacity=Tg)
            np.testing.assert_allclose(np.asarray(y[g]), np.asarray(y_ref),
                                       rtol=1e-4, atol=1e-5)

    def test_gspmd_sharded_equals_unsharded(self):
        """Mesh constraints change layout, not values."""
        from mmlspark_tpu.parallel.moe import moe_ffn_gspmd
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dp", "tp"))
        rng = np.random.default_rng(7)
        params = init_moe_params(D, F, E, seed=8)
        t = jnp.asarray(rng.normal(0, 1, (8, 6, D)).astype(np.float32))
        y0, _ = jax.jit(lambda t, p: moe_ffn_gspmd(t, p, E, 6))(t, params)
        pd = jax.device_put(params, {
            "gate": NamedSharding(mesh, P()),
            "w1": NamedSharding(mesh, P("dp", None, "tp")),
            "b1": NamedSharding(mesh, P("dp", "tp")),
            "w2": NamedSharding(mesh, P("dp", "tp", None)),
            "b2": NamedSharding(mesh, P("dp", None))})
        td = jax.device_put(t, NamedSharding(mesh, P("dp", None, None)))
        y1, _ = jax.jit(lambda t, p: moe_ffn_gspmd(
            t, p, E, 6, mesh=mesh, ep_axis="dp", tp_axis="tp"))(td, pd)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                                   rtol=1e-4, atol=1e-5)
