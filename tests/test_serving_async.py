"""Event-loop (asyncio) serving transport: one IO thread multiplexes all
connections (the selector-based shape of the reference's
``com.sun.net.httpserver``, ``HTTPSourceV2.scala:476-697``), replies cross
from dispatcher threads via ``call_soon_threadsafe``."""

import http.client
import json
import threading

import pytest

from mmlspark_tpu.io.http.schema import (EntityData, HTTPResponseData,
                                         StatusLineData)
from mmlspark_tpu.serving.engine import ServingEngine
from mmlspark_tpu.serving.server import WorkerServer


def _resp(payload, status=200):
    return HTTPResponseData(entity=EntityData.from_string(json.dumps(payload)),
                            status_line=StatusLineData(status_code=status))


def test_async_roundtrip_keepalive():
    """Sequential keep-alive requests on ONE connection, answered by a
    dispatcher thread."""
    ws = WorkerServer(transport="async", reply_timeout=10.0)
    stop = threading.Event()

    def engine():
        while not stop.is_set():
            for c in ws.get_batch(16, timeout=0.05):
                body = json.loads(c.request.entity.string_content())
                ws.reply(c.request_id, _resp({"double": body["x"] * 2}))

    t = threading.Thread(target=engine, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
        for i in range(5):
            conn.request("POST", "/", json.dumps({"x": i}).encode(),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            assert r.status == 200
            assert json.loads(r.read()) == {"double": i * 2}
        conn.close()
    finally:
        stop.set()
        t.join(timeout=5)
        ws.close()


def test_async_chunked_request_body():
    ws = WorkerServer(transport="async", reply_timeout=10.0)
    stop = threading.Event()

    def engine():
        while not stop.is_set():
            for c in ws.get_batch(16, timeout=0.05):
                ws.reply(c.request_id, _resp(
                    {"len": len(c.request.entity.content)}))

    t = threading.Thread(target=engine, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
        conn.putrequest("POST", "/")
        conn.putheader("Transfer-Encoding", "chunked")
        conn.endheaders()
        for chunk in (b"hello ", b"chunked ", b"world"):
            conn.send(b"%x\r\n%s\r\n" % (len(chunk), chunk))
        conn.send(b"0\r\n\r\n")
        r = conn.getresponse()
        assert json.loads(r.read()) == {"len": len(b"hello chunked world")}
        conn.close()
    finally:
        stop.set()
        t.join(timeout=5)
        ws.close()


def test_async_control_route_bypasses_queue():
    ws = WorkerServer(transport="async")
    ws.control_routes["/ctrl"] = lambda req: _resp({"ctrl": True})
    try:
        conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
        conn.request("POST", "/ctrl/ping", b"{}")
        assert json.loads(conn.getresponse().read()) == {"ctrl": True}
        assert ws.pending_count() == 0      # never parked
        conn.close()
    finally:
        ws.close()


def test_async_malformed_request_gets_400():
    import socket as _socket
    ws = WorkerServer(transport="async")
    try:
        s = _socket.create_connection(("127.0.0.1", ws.port), timeout=10)
        s.sendall(b"POST / HTTP/1.1\r\nContent-Length: abc\r\n\r\n")
        data = s.recv(4096)
        assert data.startswith(b"HTTP/1.1 400"), data[:60]
        s.close()
    finally:
        ws.close()


def test_async_reply_timeout_504():
    ws = WorkerServer(transport="async", reply_timeout=0.3)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
        conn.request("POST", "/", b'{"q": 1}')
        r = conn.getresponse()
        assert r.status == 504
        r.read()
        conn.close()
    finally:
        ws.close()


def test_async_engine_many_connections():
    """64 concurrent keep-alive connections through the full engine — the
    regime where thread-per-connection convoys; must complete error-free."""
    def transform(df):
        return df.with_column("reply", [{"ok": True} for _ in df["x"]])

    with ServingEngine(transform, schema={"x": float}, poll_timeout=0.005,
                       n_dispatchers=2, transport="async") as eng:
        errors, lock = [0], threading.Lock()

        def client():
            conn = http.client.HTTPConnection("127.0.0.1", eng.server.port,
                                              timeout=30)
            e = 0
            for i in range(5):
                try:
                    conn.request("POST", "/", json.dumps({"x": i}).encode())
                    r = conn.getresponse()
                    r.read()
                    if r.status != 200:
                        e += 1
                except Exception:
                    e += 1
            conn.close()
            with lock:
                errors[0] += e

        ts = [threading.Thread(target=client) for _ in range(64)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        # a deadlocked transport would leave threads alive with errors
        # still 0 — that must fail, not pass
        assert not any(t.is_alive() for t in ts)
        assert errors[0] == 0


def test_async_with_journal_rehydrates(tmp_path):
    """Async transport + durable journal compose: requests journaled by an
    async server are rehydrated by a fresh (threaded or async) server."""
    jp = str(tmp_path / "a.jsonl")
    ws = WorkerServer(transport="async", journal_path=jp, reply_timeout=1.0)
    conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
    conn.request("POST", "/", b'{"k": 9}')
    r = conn.getresponse()      # times out -> 504; stays in journal
    assert r.status == 504
    r.read()
    conn.close()
    ws.close()
    ws2 = WorkerServer(transport="async", journal_path=jp)
    try:
        batch = ws2.get_batch(4, timeout=1.0)
        assert len(batch) == 1 and batch[0].replayed
        assert json.loads(batch[0].request.entity.string_content()) == {"k": 9}
    finally:
        ws2.close()


def test_async_expect_100_continue():
    """A client sending ``Expect: 100-continue`` (curl does for any body
    over 1 KB) must get the interim response, then the real one — the bug
    class here is the interim write crashing the connection handler."""
    import socket as _socket
    ws = WorkerServer(transport="async", reply_timeout=10.0)
    stop = threading.Event()

    def engine():
        while not stop.is_set():
            for c in ws.get_batch(16, timeout=0.05):
                ws.reply(c.request_id, _resp(
                    {"len": len(c.request.entity.content)}))

    t = threading.Thread(target=engine, daemon=True)
    t.start()
    try:
        body = b"x" * 2048
        s = _socket.create_connection(("127.0.0.1", ws.port), timeout=10)
        s.sendall(b"POST / HTTP/1.1\r\nHost: h\r\n"
                  b"Content-Length: %d\r\nExpect: 100-continue\r\n\r\n"
                  % len(body))
        interim = s.recv(64)
        assert b"100 Continue" in interim
        s.sendall(body)
        data = b""
        while b"\r\n\r\n" not in data or not data.endswith(b"}"):
            part = s.recv(4096)
            if not part:
                break
            data += part
        assert b"200" in data.split(b"\r\n", 1)[0]
        assert json.loads(data.split(b"\r\n\r\n", 1)[1]) == {"len": 2048}
        s.close()
    finally:
        stop.set()
        t.join(timeout=5)
        ws.close()


def test_journal_append_after_close_is_dropped(tmp_path):
    """A dispatcher that outlives engine.stop()'s join timeout replies into
    a closed journal — that must warn-and-drop, not ValueError the thread."""
    from mmlspark_tpu.serving.journal import ServingJournal
    j = ServingJournal(str(tmp_path / "j.jsonl"))
    j.record_epoch(1)
    j.close()
    with pytest.warns(RuntimeWarning):
        j.record_reply("some-id")       # must not raise


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_stream_roundtrip_and_timeout(transport):
    """reply_stream on both transports: the first chunk is observable on
    the wire BEFORE the stream closes (incremental delivery, not one
    flush at close), a closed stream ends the response, and a stream
    that goes SILENT past reply_timeout gets an explicit final error
    event (never a silently truncated 200 that reads as success)."""
    ws = WorkerServer(transport=transport, reply_timeout=30.0)
    try:
        may_close = threading.Event()

        def answer():
            (cached,) = ws.get_batch(1, timeout=5.0)
            h = ws.reply_stream(cached.request_id)
            h.send_event({"tokens": [1, 2]})
            may_close.wait(10)              # close only after the client
            h.send_event({"tokens": [3]})   # has SEEN the first event
            h.close()

        t = threading.Thread(target=answer)
        t.start()
        conn = http.client.HTTPConnection("127.0.0.1", ws.port, timeout=10)
        conn.request("POST", "/", b"{}")
        r = conn.getresponse()
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        first = b""
        while b"\n\n" not in first:        # incremental: close not called
            first += r.read1(256)
        assert json.loads(first.split(b"\n\n")[0][6:]) == {"tokens": [1, 2]}
        may_close.set()
        rest = r.read().decode()
        t.join(timeout=5)
        events = [json.loads(b[6:]) for b in rest.split("\n\n")
                  if b.startswith("data: ")]
        assert events == [{"tokens": [3]}]
        conn.close()
    finally:
        ws.close()

    # timeout path on its OWN server: the stream opens and goes silent
    ws2 = WorkerServer(transport=transport, reply_timeout=0.5)
    try:
        def answer_silent():
            (cached,) = ws2.get_batch(1, timeout=5.0)
            ws2.reply_stream(cached.request_id)      # never sends
        t2 = threading.Thread(target=answer_silent)
        t2.start()
        conn2 = http.client.HTTPConnection("127.0.0.1", ws2.port, timeout=10)
        conn2.request("POST", "/", b"{}")
        r2 = conn2.getresponse()
        body2 = r2.read().decode()
        t2.join(timeout=5)
        events2 = [json.loads(b[6:]) for b in body2.split("\n\n")
                   if b.startswith("data: ")]
        assert events2 and events2[-1] == {"error": "stream reply timeout"}
        conn2.close()
    finally:
        ws2.close()
