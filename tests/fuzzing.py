"""Generic per-stage fuzzing harness.

Parity surface: the reference's ``core/src/test/.../core/test/fuzzing/Fuzzing.scala``:

* :class:`TestObject` — a stage plus the DataFrames to fit/transform it with
  (``Fuzzing.scala:29-45``).
* :func:`experiment_fuzz` — fit+transform runs and must be deterministic
  across two executions (``ExperimentFuzzing``, ``:216-244``).
* :func:`serialization_fuzz` — save/load of the raw stage, the fitted model,
  and a wrapping Pipeline must reproduce identical outputs
  (``SerializationFuzzing``, ``:246-322``).

Coverage enforcement lives in ``test_fuzzing.py`` (the analogue of the
root-module ``FuzzingTest`` that reflectively fails on unregistered stages).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.pipeline import Estimator, Pipeline, PipelineStage


@dataclass
class TestObject:
    stage: PipelineStage
    fit_df: Optional[DataFrame] = None        # estimators only
    transform_df: Optional[DataFrame] = None  # defaults to fit_df
    #: run the experiment (execution determinism) fuzzer
    experiment: bool = True
    #: run the serialization round-trip fuzzer
    serialization: bool = True
    #: run the behavior (fitted-pipeline) half of the serialization fuzzer;
    #: False for stages with transient callables that cannot reload
    roundtrip_behavior: bool = True
    #: columns excluded from output comparison (e.g. wall-time columns)
    ignore_cols: tuple = ()

    def frames(self):
        fit_df = self.fit_df
        t_df = self.transform_df if self.transform_df is not None else fit_df
        return fit_df, t_df


def assert_frames_equal(a: DataFrame, b: DataFrame, rtol=1e-5, atol=1e-6,
                        ignore=()):
    """Column-wise equality, tolerant for floats and nested arrays —
    the role of the reference's ``DataFrameEquality``."""
    cols_a = [c for c in a.columns if c not in ignore]
    cols_b = [c for c in b.columns if c not in ignore]
    assert cols_a == cols_b, f"columns differ: {cols_a} vs {cols_b}"
    for c in cols_a:
        va, vb = a[c], b[c]
        assert len(va) == len(vb), f"column {c}: length {len(va)} vs {len(vb)}"
        if getattr(va, "dtype", None) == object or getattr(vb, "dtype", None) == object:
            for i, (x, y) in enumerate(zip(va, vb)):
                _assert_value_equal(x, y, f"{c}[{i}]", rtol, atol)
        elif np.issubdtype(np.asarray(va).dtype, np.floating):
            np.testing.assert_allclose(va, vb, rtol=rtol, atol=atol,
                                       err_msg=f"column {c}")
        else:
            np.testing.assert_array_equal(va, vb, err_msg=f"column {c}")


def _assert_value_equal(x, y, where, rtol, atol):
    if x is None or y is None:
        assert x is None and y is None, f"{where}: {x!r} vs {y!r}"
        return
    if isinstance(x, dict) and isinstance(y, dict):
        assert set(x) == set(y), f"{where}: keys {set(x)} vs {set(y)}"
        for k in x:
            _assert_value_equal(x[k], y[k], f"{where}.{k}", rtol, atol)
        return
    if isinstance(x, (list, tuple)) and isinstance(y, (list, tuple)):
        assert len(x) == len(y), f"{where}: len {len(x)} vs {len(y)}"
        for i, (xi, yi) in enumerate(zip(x, y)):
            _assert_value_equal(xi, yi, f"{where}[{i}]", rtol, atol)
        return
    xa, ya = np.asarray(x), np.asarray(y)
    if xa.dtype == object or ya.dtype == object:
        assert str(x) == str(y), f"{where}: {x!r} vs {y!r}"
    elif np.issubdtype(xa.dtype, np.floating) or np.issubdtype(ya.dtype, np.floating):
        np.testing.assert_allclose(xa, ya, rtol=rtol, atol=atol, err_msg=where)
    else:
        np.testing.assert_array_equal(xa, ya, err_msg=where)


def _run(stage: PipelineStage, fit_df, t_df):
    if isinstance(stage, Estimator):
        model = stage.fit(fit_df)
        return model, model.transform(t_df)
    return None, stage.transform(t_df)


def experiment_fuzz(obj: TestObject):
    """Run twice; outputs must match (``ExperimentFuzzing`` determinism)."""
    fit_df, t_df = obj.frames()
    _, out1 = _run(obj.stage, fit_df, t_df)
    _, out2 = _run(obj.stage.copy(), fit_df, t_df)
    assert_frames_equal(out1, out2, ignore=obj.ignore_cols)
    return out1


def serialization_fuzz(obj: TestObject, tmp_path):
    """Save/load round-trips: raw stage, fitted model, wrapping pipeline."""
    stage = obj.stage
    fit_df, t_df = obj.frames()

    # 1. raw stage round-trip: params must survive
    p1 = os.path.join(str(tmp_path), "raw")
    stage.save(p1)
    again = PipelineStage.load(p1)
    assert type(again) is type(stage)
    _assert_params_match(stage, again)

    if not obj.experiment or not obj.roundtrip_behavior or t_df is None:
        return

    # 2. behavior round-trip through a wrapping Pipeline (covers stage-list
    # serialization and, for estimators, fitted-model serialization)
    pipe = Pipeline([stage.copy()])
    model = pipe.fit(fit_df if fit_df is not None else t_df)
    ref_out = model.transform(t_df)
    p2 = os.path.join(str(tmp_path), "fitted")
    model.save(p2)
    from mmlspark_tpu.core.pipeline import PipelineModel
    model2 = PipelineModel.load(p2)
    assert_frames_equal(ref_out, model2.transform(t_df),
                        ignore=obj.ignore_cols)

    # 3. portable-artifact round-trip: the mlflow leg of the reference's
    # generated fuzzing (Fuzzing.scala:135-140) — every fitted model must
    # reload through the generic save_model/load_model.predict entry
    from mmlspark_tpu.mlflow import load_model, save_model
    p3 = os.path.join(str(tmp_path), "artifact")
    save_model(model, p3)
    assert_frames_equal(ref_out, load_model(p3).predict(t_df),
                        ignore=obj.ignore_cols)


def _assert_params_match(a: PipelineStage, b: PipelineStage):
    from mmlspark_tpu.core.params import ComplexParam
    for name, p in a.params().items():
        if not a.is_set(name):
            continue
        va = a.get(name)
        if isinstance(p, ComplexParam):
            if callable(va) and not isinstance(va, PipelineStage):
                continue  # transient (documented: re-set after load)
            if not b.is_set(name):
                continue  # transient values are dropped on save
            vb = b.get(name)
            if isinstance(va, np.ndarray):
                np.testing.assert_array_equal(va, vb, err_msg=name)
            elif isinstance(va, PipelineStage):
                assert type(vb) is type(va), name
            elif isinstance(va, (list, tuple)) and va and \
                    isinstance(va[0], PipelineStage):
                assert [type(s) for s in vb] == [type(s) for s in va], name
            continue
        assert b.is_set(name) or b.param(name).has_default, name
        vb = b.get(name)
        if isinstance(va, float):
            assert abs(va - vb) < 1e-12, f"param {name}: {va} vs {vb}"
        else:
            assert va == vb, f"param {name}: {va!r} vs {vb!r}"
