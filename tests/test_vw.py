"""VW-equivalent module tests: hashing, featurizer, interactions, learners,
contextual bandit, distributed pass-averaged training."""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.vw import (VowpalWabbitClassifier, VowpalWabbitClassifierModel,
                             VowpalWabbitContextualBandit, VowpalWabbitFeaturizer,
                             VowpalWabbitInteractions, VowpalWabbitRegressor)
from mmlspark_tpu.vw.featurizer import NUM_BITS_KEY, sparse_column
from mmlspark_tpu.vw.learners import pad_sparse
from mmlspark_tpu.vw.murmur import combine_hashes, murmur3_32


def test_murmur3_known_vectors():
    # public MurmurHash3 x86_32 test vectors
    assert murmur3_32(b"", 0) == 0
    assert murmur3_32(b"", 1) == 0x514E28B7
    assert murmur3_32(b"hello", 0) == 0x248BFA47
    assert murmur3_32(b"hello, world", 0) == 0x149BBB7F
    assert murmur3_32(b"The quick brown fox jumps over the lazy dog", 0) \
        == 0x2E4FF723


def test_featurizer_types_and_determinism():
    df = DataFrame({
        "num": np.array([1.5, 0.0, -2.0]),
        "cat": np.array(["a", "b", "a"], dtype=object),
        "txt": np.array(["red green", "blue", ""], dtype=object),
    })
    f = VowpalWabbitFeaturizer(input_cols=["num", "cat", "txt"],
                               string_split_cols=["txt"], num_bits=15)
    out = f.transform(df)
    feats = out["features"]
    assert out.column_metadata("features")[NUM_BITS_KEY] == 15
    # row 0: num + cat + 2 tokens = 4 features; row 1 drops the zero numeric
    assert len(feats[0][0]) == 4
    assert len(feats[1][0]) == 2
    # same cat value in rows 0 and 2 hashes identically
    i0 = set(feats[0][0].tolist())
    i2 = set(feats[2][0].tolist())
    assert len(i0 & i2) >= 1
    assert np.all(feats[0][0] < (1 << 15))
    # deterministic
    again = f.transform(df)["features"]
    np.testing.assert_array_equal(again[0][0], feats[0][0])


def test_featurizer_dict_and_vector():
    df = DataFrame({
        "m": sparse_column([{"a": 2.0, "b": 0.0}, {"c": 1.0}]),
        "v": sparse_column([np.array([1.0, 0.0, 3.0]), np.array([0.0, 0.0, 0.0])]),
    })
    out = VowpalWabbitFeaturizer(input_cols=["m", "v"]).transform(df)
    idx0, val0 = out["features"][0]
    # dict drops the zero-valued key; vector keeps 2 nonzeros
    assert len(idx0) == 3
    assert set(np.round(val0, 3)) == {2.0, 1.0, 3.0}
    idx1, _ = out["features"][1]
    assert len(idx1) == 1


def test_interactions_cross():
    f = VowpalWabbitFeaturizer(input_cols=["a"], output_col="fa")
    g = VowpalWabbitFeaturizer(input_cols=["b"], output_col="fb")
    df = DataFrame({"a": np.array(["x", "y"], dtype=object),
                    "b": np.array(["u", "u"], dtype=object)})
    df = g.transform(f.transform(df))
    out = VowpalWabbitInteractions(input_cols=["fa", "fb"]).transform(df)
    i0, v0 = out["interactions"][0]
    i1, v1 = out["interactions"][1]
    assert len(i0) == 1 and v0[0] == 1.0
    # different 'a' value → different crossed index despite same 'b'
    assert i0[0] != i1[0]
    # combine is order-sensitive (h1*prime ^ h2)
    assert combine_hashes(3, 7, 0xFFFF) != combine_hashes(7, 3, 0xFFFF)


def _binary_df(rng, n=400, bits=12):
    """Linearly separable hashed problem built through the featurizer."""
    words = np.array(["w%d" % i for i in range(20)], dtype=object)
    pos_words, neg_words = words[:10], words[10:]
    texts, labels = [], []
    for i in range(n):
        if rng.random() < 0.5:
            toks = rng.choice(pos_words, size=3, replace=False)
            labels.append(1.0)
        else:
            toks = rng.choice(neg_words, size=3, replace=False)
            labels.append(0.0)
        texts.append(" ".join(toks))
    df = DataFrame({"text": np.array(texts, dtype=object),
                    "label": np.array(labels)})
    return VowpalWabbitFeaturizer(input_cols=["text"],
                                  string_split_cols=["text"],
                                  num_bits=bits).transform(df)


def test_classifier_learns(rng):
    df = _binary_df(rng)
    clf = VowpalWabbitClassifier(num_passes=5, mini_batch=32,
                                 use_all_reduce=False)
    model = clf.fit(df)
    out = model.transform(df)
    acc = (out["prediction"] == df["label"]).mean()
    assert acc > 0.95
    assert out["probability"].min() >= 0 and out["probability"].max() <= 1
    # TrainingStats parity table
    stats = model.performance_statistics
    assert "passes" in stats.columns and stats["weightsNonZero"][0] > 0


def test_classifier_save_load_roundtrip(rng, tmp_save):
    df = _binary_df(rng, n=100)
    model = VowpalWabbitClassifier(num_passes=2, use_all_reduce=False).fit(df)
    model.save(tmp_save)
    again = VowpalWabbitClassifierModel.load(tmp_save)
    np.testing.assert_array_equal(again.transform(df)["prediction"],
                                  model.transform(df)["prediction"])


def test_regressor_quantile_and_warm_start(rng):
    n, bits = 300, 10
    df = _binary_df(rng, n=n, bits=bits)
    y = rng.normal(2.0, 0.1, n)
    df = df.with_column("target", y)
    reg = VowpalWabbitRegressor(label_col="target", num_passes=8,
                                learning_rate=1.0, use_all_reduce=False)
    m1 = reg.fit(df)
    p1 = m1.transform(df)["prediction"]
    assert abs(np.mean(p1) - 2.0) < 0.5
    # warm start with weights + adagrad state (VW --save_resume parity):
    # one extra pass must not degrade the converged fit
    warm = VowpalWabbitRegressor(
        label_col="target", num_passes=1,
        initial_model=np.asarray(m1.get("weights")),
        initial_adaptive_state=np.asarray(m1.get("adaptive_state")),
        use_all_reduce=False)
    pw = warm.fit(df).transform(df)["prediction"]
    assert np.mean((pw - y) ** 2) <= np.mean((p1 - y) ** 2) + 1e-3
    # quantile loss runs
    q = VowpalWabbitRegressor(label_col="target", loss_function="quantile",
                              quantile_tau=0.9, num_passes=3,
                              use_all_reduce=False).fit(df)
    assert np.isfinite(q.transform(df)["prediction"]).all()


def test_distributed_allreduce_matches_single(rng):
    """Sharded training with per-pass pmean stays close to single-device."""
    import jax
    from mmlspark_tpu.parallel.mesh import MeshContext

    df = _binary_df(rng, n=256, bits=10)
    single = VowpalWabbitClassifier(num_passes=4, mini_batch=32,
                                    use_all_reduce=False).fit(df)
    with MeshContext({"data": min(4, len(jax.devices()))}):
        sharded = VowpalWabbitClassifier(num_passes=4, mini_batch=32,
                                         use_all_reduce=True).fit(df)
    assert int(sharded.performance_statistics["partitionId"].max()) >= 1
    a1 = (single.transform(df)["prediction"] == df["label"]).mean()
    a2 = (sharded.transform(df)["prediction"] == df["label"]).mean()
    assert a2 > 0.9 and abs(a1 - a2) < 0.1


def test_contextual_bandit(rng):
    """Bandit picks the action whose features predict low cost."""
    n, k, bits = 300, 3, 12
    mask = (1 << bits) - 1
    # shared context: one of two user types; action features: arm id
    shared_rows, action_rows, chosen, cost, prob = [], [], [], [], []
    for i in range(n):
        user = int(rng.random() < 0.5)
        shared_rows.append((np.array([100 + user], dtype=np.uint32),
                            np.array([1.0], dtype=np.float32)))
        acts = [(np.array([200 + a], dtype=np.uint32),
                 np.array([1.0], dtype=np.float32)) for a in range(k)]
        action_rows.append(acts)
        a = int(rng.integers(0, k))
        chosen.append(a + 1)
        # best arm = user type; cost 0 when matched, 1 otherwise (noisy)
        c = 0.0 if a == user else 1.0
        cost.append(c + rng.normal(0, 0.05))
        prob.append(1.0 / k)
    df = DataFrame({
        "shared": sparse_column(shared_rows),
        "features": sparse_column(action_rows),
        "chosenAction": np.array(chosen),
        "label": np.array(cost, dtype=np.float32),
        "probability": np.array(prob, dtype=np.float32),
    }).with_column_metadata("features", {NUM_BITS_KEY: bits})

    cb = VowpalWabbitContextualBandit(num_passes=10, learning_rate=0.5,
                                      epsilon=0.1)
    model = cb.fit(df)
    out = model.transform(df)
    # the predicted best arm should match the user type most of the time
    users = np.array([int(s[0][0] - 100) for s in df["shared"]])
    agree = (out["prediction"] - 1 == users).mean()
    assert agree > 0.9
    pmf0 = out["pmf"][0]
    assert pytest.approx(pmf0.sum(), abs=1e-5) == 1.0
    assert len(out["scores"][0]) == k


def test_fit_multiple_parallel(rng):
    df = _binary_df(rng, n=80, bits=10)
    cb_df_cols = None  # not needed; use classifier param sweep via fit_multiple
    clf = VowpalWabbitClassifier(num_passes=1, use_all_reduce=False)
    models = clf.fit_multiple(df, [{"learning_rate": 0.1},
                                   {"learning_rate": 1.0}])
    assert len(models) == 2
    assert not np.allclose(np.asarray(models[0].get("weights")),
                           np.asarray(models[1].get("weights")))


def test_pad_sparse_shapes():
    col = sparse_column([(np.array([1, 2], np.uint32), np.array([1., 2.], np.float32)),
                         (np.array([], np.uint32), np.array([], np.float32))])
    idx, val = pad_sparse(col)
    assert idx.shape == (2, 2) and val.shape == (2, 2)
    assert val[1].sum() == 0


def test_contextual_bandit_validates_inputs(rng):
    """chosen_action is 1-based and action lists must be non-empty
    (ADVICE r1: silent actions[-1] indexing / opaque argmin crash)."""
    bits = 10
    sh = sparse_column([(np.array([1], np.uint32), np.array([1.], np.float32))])
    acts = sparse_column([[(np.array([2], np.uint32), np.array([1.], np.float32))]])
    base = {
        "shared": sh, "features": acts,
        "chosenAction": np.array([0]),               # invalid: 0 is not 1-based
        "label": np.array([0.5], dtype=np.float32),
        "probability": np.array([0.5], dtype=np.float32),
    }
    df = DataFrame(base).with_column_metadata("features", {NUM_BITS_KEY: bits})
    with pytest.raises(ValueError, match="out of range"):
        VowpalWabbitContextualBandit().fit(df)

    empty = DataFrame({**base, "chosenAction": np.array([1]),
                       "features": sparse_column([[]])}) \
        .with_column_metadata("features", {NUM_BITS_KEY: bits})
    with pytest.raises(ValueError, match="empty action list"):
        VowpalWabbitContextualBandit().fit(empty)

    # transform-time: empty action list raises a clear error too
    m = VowpalWabbitContextualBandit(num_passes=1).fit(
        DataFrame({**base, "chosenAction": np.array([1])})
        .with_column_metadata("features", {NUM_BITS_KEY: bits}))
    with pytest.raises(ValueError, match="empty action list"):
        m.transform(DataFrame({"shared": sh, "features": sparse_column([[]])}))
