"""Tests for utility stages (reference test model: per-stage experiment +
serialization fuzzing, SURVEY.md §4)."""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.stages import (Cacher, ClassBalancer, DropColumns,
                                 EnsembleByKey, Explode, Lambda,
                                 MultiColumnAdapter, PartitionConsolidator,
                                 RenameColumn, Repartition, SelectColumns,
                                 StratifiedRepartition, SummarizeData,
                                 TextPreprocessor, Timer, UDFTransformer,
                                 UnicodeNormalize)


@pytest.fixture
def df():
    return DataFrame({
        "a": np.array([1.0, 2.0, 3.0, 4.0]),
        "b": np.array([10, 20, 30, 40]),
        "label": np.array([0, 0, 0, 1]),
        "text": ["Hello World", "FOO bar", "baz", "QUX quux"],
    })


def test_column_ops(df):
    assert SelectColumns(["a", "b"]).transform(df).columns == ["a", "b"]
    assert "a" not in DropColumns(["a"]).transform(df).columns
    out = RenameColumn(input_col="a", output_col="alpha").transform(df)
    assert "alpha" in out.columns and "a" not in out.columns
    assert Repartition(n=2).transform(df).npartitions == 2
    assert Cacher().transform(df) is not None


def test_explode():
    df = DataFrame({"id": [1, 2], "vals": [[1, 2, 3], [4]]})
    out = Explode(input_col="vals", output_col="v").transform(df)
    assert len(out) == 4
    assert list(out["id"]) == [1, 1, 1, 2]
    assert list(out["v"]) == [1, 2, 3, 4]


def test_lambda_and_udf(df):
    lam = Lambda(lambda d: d.with_column("c", d["a"] * 2))
    assert list(lam.transform(df)["c"]) == [2.0, 4.0, 6.0, 8.0]

    udf = UDFTransformer(lambda x: x + 1, input_col="b", output_col="b1")
    assert list(udf.transform(df)["b1"]) == [11, 21, 31, 41]

    vec = UDFTransformer(lambda x: x * 10, input_col="b", output_col="b10",
                         vectorized=True)
    assert list(vec.transform(df)["b10"]) == [100, 200, 300, 400]


def test_multi_column_adapter(df):
    inner = UnicodeNormalize(lower=True)
    stage = MultiColumnAdapter(base_stage=inner, input_cols=["text"],
                               output_cols=["text_lower"])
    out = stage.transform(df)
    assert out["text_lower"][0] == "hello world"


def test_class_balancer(df):
    model = ClassBalancer(input_col="label", output_col="w").fit(df)
    out = model.transform(df)
    w = out["w"]
    # minority class (label 1, count 1) gets weight 3; majority gets 1
    assert w[3] == 3.0 and w[0] == 1.0


def test_class_balancer_roundtrip(df, tmp_save):
    model = ClassBalancer(input_col="label", output_col="w").fit(df)
    model.save(tmp_save)
    from mmlspark_tpu.stages import ClassBalancerModel
    loaded = ClassBalancerModel.load(tmp_save)
    np.testing.assert_allclose(loaded.transform(df)["w"],
                               model.transform(df)["w"])


def test_ensemble_by_key():
    df = DataFrame({"k": ["x", "x", "y"], "score": [1.0, 3.0, 5.0]})
    out = EnsembleByKey(keys=["k"], cols=["score"]).transform(df)
    got = dict(zip(out["k"], out["mean(score)"]))
    assert got == {"x": 2.0, "y": 5.0}
    wide = EnsembleByKey(keys=["k"], cols=["score"],
                         collapse_group=False).transform(df)
    assert list(wide["mean(score)"]) == [2.0, 2.0, 5.0]


def test_stratified_repartition():
    df = DataFrame({"label": [0] * 6 + [1] * 2, "x": list(range(8))},
                   npartitions=2)
    out = StratifiedRepartition(label_col="label").transform(df).repartition(2)
    for part in out.partitions():
        assert set(np.unique(part["label"])) == {0, 1}


def test_stratified_repartition_uneven_labels():
    # bucket sizes that don't divide evenly must still give every partition
    # every label (labels with >= npartitions rows)
    df = DataFrame({"label": [0] * 5 + [1] * 5 + [2] * 2,
                    "x": list(range(12))}, npartitions=2)
    out = StratifiedRepartition(label_col="label").transform(df)
    for part in out.partitions():
        assert set(np.unique(part["label"])) == {0, 1, 2}


def test_summarize_data(df):
    out = SummarizeData().transform(df)
    assert set(out["feature"]) == {"a", "b", "label", "text"}
    row = {f: out["mean"][i] for i, f in enumerate(out["feature"])}
    assert row["a"] == 2.5


def test_text_preprocessor():
    df = DataFrame({"text": ["I luv u"]})
    stage = TextPreprocessor(input_col="text", output_col="out",
                             map={"luv": "love", "u": "you"})
    # longest-match: "luv" wins over "u" inside it; the standalone "u" maps too
    assert stage.transform(df)["out"][0] == "I love you"


def test_unicode_normalize():
    df = DataFrame({"text": ["Ｈｅｌｌｏ"]})
    out = UnicodeNormalize(input_col="text", output_col="n").transform(df)
    assert out["n"][0] == "hello"


def test_timer(df):
    inner = ClassBalancer(input_col="label", output_col="w")
    timer = Timer(stage=inner)
    model = timer.fit(df)
    assert timer.last_fit_seconds is not None and timer.last_fit_seconds >= 0
    out = model.transform(df)
    assert "w" in out.columns
    assert model.last_transform_seconds >= 0


def test_partition_consolidator(df):
    out = PartitionConsolidator().transform(df.repartition(4))
    assert out.npartitions == 1 and len(out) == len(df)
