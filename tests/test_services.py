"""Tests for the service-transformer framework + families against a local
mock server — the reference tests cognitive services the same way (recorded
replies / live endpoints)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.dataframe import object_col
from mmlspark_tpu.services import (AnalyzeImage, BingImageSearch,
                                   DetectAnomalies, DictionaryLookup,
                                   LanguageDetector, OCR,
                                   SimpleDetectAnomalies, TextSentiment,
                                   Translate)
from mmlspark_tpu.services.search import AzureSearchWriter

_state = {"ops": {}, "search_docs": [], "op_counter": 0}


class _MockService(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _reply(self, obj, status=200, headers=()):
        out = json.dumps(obj).encode()
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def do_GET(self):
        path = urlparse(self.path)
        q = parse_qs(path.query)
        if path.path.startswith("/operations/"):
            op = path.path.rsplit("/", 1)[1]
            n = _state["ops"].get(op, 0)
            _state["ops"][op] = n + 1
            if n < 2:  # not ready the first two polls
                self._reply({"status": "running"})
            else:
                self._reply({"status": "succeeded",
                             "analyzeResult": {"lines": ["hello world"]}})
        elif path.path == "/images/search":
            self._reply({"value": [{"contentUrl": "http://x/img.png",
                                    "name": q["q"][0]}]})
        elif path.path.startswith("/maps/batch/"):
            op = path.path.rsplit("/", 1)[1]
            n = _state["ops"].get(op, 0)
            _state["ops"][op] = n + 1
            if n < 2:                      # still running: 202, no body
                self._reply({}, 202)
            else:
                self._reply(_state[f"result_{op}"])
        else:
            self._reply({"error": "not found"}, 404)

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n)
        path = urlparse(self.path)
        q = parse_qs(path.query)
        body = json.loads(raw) if raw and raw[:1] in (b"{", b"[") else raw
        if path.path == "/echo_query":
            self._reply({"query": q})
        elif path.path == "/text/sentiment":
            assert self.headers.get("Ocp-Apim-Subscription-Key") == "secret"
            doc = body["documents"][0]
            sent = "positive" if "good" in doc["text"] else "negative"
            self._reply({"documents": [
                {"id": doc["id"], "sentiment": sent,
                 "confidenceScores": {"positive": 0.9}}]})
        elif path.path == "/text/languages":
            self._reply({"documents": [
                {"id": "0", "detectedLanguage": {"iso6391Name": "fr"}}]})
        elif path.path == "/translate":
            to = q["to"][0]
            self._reply([{"translations":
                          [{"text": f"<{to}>{d['Text']}", "to": to}]}
                         for d in body])
        elif path.path == "/dictionary/lookup":
            assert q["from"][0] == "en" and q["to"][0] == "es"
            self._reply([{"normalizedSource": d["Text"].lower(),
                          "translations": [{"normalizedTarget": "volar"}]}
                         for d in body])
        elif path.path == "/dictionary/examples":
            assert q["from"][0] == "en" and q["to"][0] == "es"
            self._reply([{"normalizedSource": d["Text"],
                          "normalizedTarget": d["Translation"],
                          "examples": [{"sourceTerm": d["Text"],
                                        "targetTerm": d["Translation"]}]}
                         for d in body])
        elif path.path == "/vision/analyze":
            assert "visualFeatures" in q
            self._reply({"categories": [{"name": "outdoor", "score": 0.9}],
                         "url_seen": body.get("url")})
        elif path.path == "/vision/ocr":
            _state["op_counter"] += 1
            op = f"op{_state['op_counter']}"
            _state["ops"][op] = 0
            host = self.headers["Host"]
            self._reply({}, status=202,
                        headers=[("Operation-Location",
                                  f"http://{host}/operations/{op}")])
        elif path.path == "/vision/read":
            assert q.get("language", ["en"])[0] in ("en", "de")
            _state["op_counter"] += 1
            op = f"op{_state['op_counter']}"
            _state["ops"][op] = 0
            host = self.headers["Host"]
            self._reply({}, status=202,
                        headers=[("Operation-Location",
                                  f"http://{host}/operations/{op}")])
        elif path.path == "/vision/thumb":
            assert q["width"][0] == "40" and q["height"][0] == "30"
            png = b"\x89PNG-fake-thumb"
            self.send_response(200)
            self.send_header("Content-Type", "image/png")
            self.send_header("Content-Length", str(len(png)))
            self.end_headers()
            self.wfile.write(png)
        elif path.path.startswith("/vision/models/"):
            # /vision/models/{model}/analyze — the per-row URL segment
            model = path.path.split("/")[3]
            self._reply({"result": {"celebrities": [
                {"name": f"famous-{model}", "confidence": 0.99}]}})
        elif path.path == "/maps/geocode":
            # Azure-Maps batch convention: 202 + Location header, poll
            # until the result flips to 200 (no JSON status field)
            _state["op_counter"] += 1
            op = f"maps{_state['op_counter']}"
            _state["ops"][op] = 0
            host = self.headers["Host"]
            items = [{"response": {"results": [
                {"position": {"lat": 47.6, "lon": -122.1},
                 "query": it["query"]}]}}
                for it in body.get("batchItems", [])]
            _state[f"result_{op}"] = {"batchItems": items}
            self._reply({}, status=202,
                        headers=[("Location",
                                  f"http://{host}/maps/batch/{op}")])
        elif path.path == "/anomaly/entire":
            series = body["series"]
            vals = [p["value"] for p in series]
            med = sorted(vals)[len(vals) // 2]
            self._reply({"isAnomaly": [abs(v - med) > 50 for v in vals]})
        elif path.path == "/search/index":
            assert self.headers.get("api-key") == "sk"
            _state["search_docs"].extend(body["value"])
            self._reply({"value": []})
        else:
            self._reply({"error": "not found"}, 404)


@pytest.fixture(scope="module")
def svc():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _MockService)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def test_text_sentiment_scalar_and_vector_params(svc):
    df = DataFrame({"txt": object_col(["good day", "bad day", None])})
    t = TextSentiment(url=svc + "/text/sentiment", output_col="out",
                      error_col="err", concurrency=2)
    t.set_scalar_param("subscription_key", "secret")
    t.set_vector_param("text", "txt")
    out = t.transform(df)
    assert out["out"][0]["sentiment"] == "positive"
    assert out["out"][1]["sentiment"] == "negative"
    # null required param → skipped row: null output AND null error
    assert out["out"][2] is None and out["err"][2] is None


def test_language_detector(svc):
    df = DataFrame({"text": object_col(["bonjour"])})
    t = LanguageDetector(url=svc + "/text/languages", output_col="lang")
    t.set_vector_param("text", "text")
    out = t.transform(df)
    assert out["lang"][0]["iso6391Name"] == "fr"


def test_translate_url_params(svc):
    df = DataFrame({"text": object_col(["hello"])})
    t = Translate(url=svc + "/translate", output_col="tr")
    t.set_vector_param("text", "text")
    t.set_scalar_param("to_language", "de")
    out = t.transform(df)
    assert out["tr"][0][0]["text"] == "<de>hello"


def test_translate_multi_target_and_text_batch(svc):
    """A list-valued text is one request with positional results; a
    to_language list joins with commas (reference toValueString)."""
    df = DataFrame({"texts": object_col([["hello", "bye"]])})
    t = Translate(url=svc + "/translate", output_col="tr")
    t.set_vector_param("text", "texts")
    t.set_scalar_param("to_language", ["de", "it"])
    out = t.transform(df)
    # mock echoes the first 'to'; both texts come back positionally
    assert [r[0]["text"] for r in out["tr"][0]] == ["<de,it>hello",
                                                    "<de,it>bye"]


def test_dictionary_lookup(svc):
    df = DataFrame({"w": object_col(["Fly"])})
    t = DictionaryLookup(url=svc + "/dictionary/lookup", output_col="out")
    t.set_vector_param("text", "w")
    t.set_scalar_param("from_language", "en")
    t.set_scalar_param("to_language", "es")
    out = t.transform(df)
    assert out["out"][0]["normalizedSource"] == "fly"
    assert out["out"][0]["translations"][0]["normalizedTarget"] == "volar"


def test_dictionary_examples_pairs(svc):
    from mmlspark_tpu.services import DictionaryExamples
    df = DataFrame({"pair": object_col([("fly", "volar")])})
    t = DictionaryExamples(url=svc + "/dictionary/examples",
                           output_col="out")
    t.set_vector_param("text_and_translation", "pair")
    t.set_scalar_param("from_language", "en")
    t.set_scalar_param("to_language", "es")
    out = t.transform(df)
    # single pair → single result object
    assert out["out"][0]["examples"][0]["targetTerm"] == "volar"
    # list of pairs → positional array
    df2 = DataFrame({"pair": object_col(
        [[("fly", "volar"), ("run", "correr")]])})
    out2 = t.transform(df2)
    assert [r["normalizedTarget"] for r in out2["out"][0]] \
        == ["volar", "correr"]


def test_analyze_image(svc):
    df = DataFrame({"url": object_col(["http://images/1.png"])})
    t = AnalyzeImage(url=svc + "/vision/analyze", output_col="an")
    t.set_vector_param("image_url", "url")
    t.set_scalar_param("visual_features", "Categories,Tags")
    out = t.transform(df)
    assert out["an"][0]["categories"][0]["name"] == "outdoor"
    assert out["an"][0]["url_seen"] == "http://images/1.png"


def test_ocr_async_polling(svc):
    df = DataFrame({"url": object_col(["http://images/2.png"])})
    t = OCR(url=svc + "/vision/ocr", output_col="ocr", polling_delay_ms=20)
    t.set_vector_param("image_url", "url")
    out = t.transform(df)
    assert out["ocr"][0]["status"] == "succeeded"
    assert out["ocr"][0]["analyzeResult"]["lines"] == ["hello world"]


def test_read_image_async_and_flatten(svc):
    from mmlspark_tpu.services import ReadImage, flatten_read
    t = ReadImage(url=svc + "/vision/read", output_col="read",
                  polling_delay_ms=20, language="de")
    df = DataFrame({"image_url": ["http://x/a.png"]})
    t.set_vector_param("image_url", "image_url")
    out = t.transform(df)
    assert out["read"][0]["status"] == "succeeded"
    # flatten on a canned Read v3 payload shape
    payload = {"analyzeResult": {"readResults": [
        {"lines": [{"text": "hello"}, {"text": "world"}]}]}}
    assert flatten_read(np.asarray([payload, None], dtype=object))[0] \
        == "hello world"


def test_read_image_language_validated(svc):
    # an invalid per-row param value is a PER-ROW failure: it lands in the
    # error column and the other rows still succeed
    from mmlspark_tpu.services import ReadImage
    t = ReadImage(url=svc + "/vision/read", output_col="o",
                  polling_delay_ms=20)
    t.set_vector_param("image_url", "u")
    t.set_vector_param("language", "lang")
    out = t.transform(DataFrame({"u": ["http://x/a.png", "http://x/b.png"],
                                 "lang": ["xx", "de"]}))
    assert out["o"][0] is None
    assert "language" in out[t.get("error_col")][0]["reasonPhrase"]
    assert out["o"][1]["status"] == "succeeded"


def test_recognize_text_mode_validated(svc):
    from mmlspark_tpu.services import RecognizeText
    t = RecognizeText(url=svc + "/vision/ocr", output_col="o",
                      polling_delay_ms=20, mode="Handwritten")
    t.set_vector_param("image_url", "u")
    out = t.transform(DataFrame({"u": ["http://x/a.png"]}))
    assert out["o"][0]["status"] == "succeeded"
    bad = RecognizeText(url=svc + "/vision/ocr", output_col="o",
                        mode="Cursive")
    bad.set_vector_param("image_url", "u")
    out = bad.transform(DataFrame({"u": ["http://x/a.png"]}))
    assert out["o"][0] is None
    assert "mode" in out[bad.get("error_col")][0]["reasonPhrase"]


def test_generate_thumbnails_binary_output(svc):
    from mmlspark_tpu.services import GenerateThumbnails
    t = GenerateThumbnails(url=svc + "/vision/thumb", output_col="thumb",
                           width=40, height=30, smart_cropping=True)
    t.set_vector_param("image_url", "u")
    out = t.transform(DataFrame({"u": ["http://x/a.png"]}))
    assert out["thumb"][0] == b"\x89PNG-fake-thumb"     # raw bytes, not JSON


def test_domain_specific_content_url_per_row(svc):
    from mmlspark_tpu.services import RecognizeDomainSpecificContent
    t = RecognizeDomainSpecificContent(url=svc + "/vision",
                                       output_col="celebs")
    t.set_vector_param("image_url", "u")
    t.set_vector_param("model", "m")
    out = t.transform(DataFrame({"u": ["http://x/a.png", "http://x/b.png"],
                                 "m": ["celebrities", "landmarks"]}))
    assert out["celebs"][0]["result"]["celebrities"][0]["name"] \
        == "famous-celebrities"
    assert out["celebs"][1]["result"]["celebrities"][0]["name"] \
        == "famous-landmarks"


def test_maps_geocoder_batch_async(svc):
    from mmlspark_tpu.services.geospatial import AddressGeocoder
    t = AddressGeocoder(url=svc + "/maps/geocode", output_col="geo",
                        polling_delay_ms=20, subscription_key="mk")
    col = np.empty(1, dtype=object)
    col[0] = ["1 Main St", "2 Side Ave"]
    t.set_vector_param("address", "addrs")
    out = t.transform(DataFrame({"addrs": col}))
    items = out["geo"][0]
    assert len(items) == 2
    assert items[0]["response"]["results"][0]["position"]["lat"] == 47.6


def test_detect_anomalies_service(svc):
    series = [{"timestamp": str(i), "value": float(v)}
              for i, v in enumerate([1, 2, 1, 2, 99, 2])]
    df = DataFrame({"s": object_col([series])})
    t = DetectAnomalies(url=svc + "/anomaly/entire", output_col="an")
    t.set_vector_param("series", "s")
    out = t.transform(df)
    assert out["an"][0]["isAnomaly"] == [False, False, False, False, True, False]


def test_simple_detect_anomalies_grouped_service(svc):
    n = 6
    df = DataFrame({
        "group": object_col(["a"] * n + ["b"] * n),
        "timestamp": np.arange(2 * n),
        "value": np.asarray([1, 2, 1, 2, 99, 2] + [5, 5, 5, 5, 5, -80],
                            dtype=np.float64),
    })
    t = SimpleDetectAnomalies(url=svc + "/anomaly/entire", output_col="an")
    out = t.transform(df)
    flags = [v["isAnomaly"] for v in out["an"]]
    assert flags[4] is True and flags[11] is True
    assert sum(flags) == 2


def test_simple_detect_anomalies_local():
    vals = np.asarray([1, 1.1, 0.9, 1, 25.0, 1.05, 0.98, 1.02], np.float64)
    df = DataFrame({"group": object_col(["g"] * len(vals)),
                    "timestamp": np.arange(len(vals)),
                    "value": vals})
    t = SimpleDetectAnomalies(output_col="an")  # no url → local MAD detector
    out = t.transform(df)
    flags = [v["isAnomaly"] for v in out["an"]]
    assert flags == [False, False, False, False, True, False, False, False]


def test_bing_image_search_get(svc):
    df = DataFrame({"q": object_col(["cats"])})
    t = BingImageSearch(url=svc + "/images/search", output_col="imgs")
    t.set_vector_param("query", "q")
    out = t.transform(df)
    assert out["imgs"][0][0]["name"] == "cats"


def test_azure_search_writer(svc):
    _state["search_docs"].clear()
    df = DataFrame({"id": object_col(["1", "2", "3"]),
                    "score": np.asarray([0.1, 0.2, 0.3])})
    w = AzureSearchWriter(svc + "/search/index", api_key="sk", batch_size=2)
    n = w.write(df)
    assert n == 2
    assert len(_state["search_docs"]) == 3
    assert _state["search_docs"][0]["@search.action"] == "upload"


def test_service_transformer_save_load(tmp_path, svc):
    t = TextSentiment(url=svc + "/text/sentiment", output_col="out",
                      error_col="err")
    t.set_scalar_param("subscription_key", "secret")
    t.set_vector_param("text", "txt")
    t.save(str(tmp_path / "svc"))
    t2 = TextSentiment.load(str(tmp_path / "svc"))
    df = DataFrame({"txt": object_col(["good"])})
    assert t2.transform(df)["out"][0]["sentiment"] == "positive"


def test_error_column_on_bad_endpoint(svc):
    df = DataFrame({"txt": object_col(["x"])})
    t = TextSentiment(url=svc + "/nope", output_col="out", error_col="err")
    t.set_vector_param("text", "txt")
    out = t.transform(df)
    assert out["out"][0] is None
    assert out["err"][0]["statusCode"] == 404


def test_malformed_url_lands_in_error_column():
    """A transport-level failure (bad URL) must not crash the transform."""
    df = DataFrame({"txt": object_col(["x", "y"])})
    t = TextSentiment(url="notaurl", output_col="out", error_col="err",
                      timeout=2.0)
    t.set_vector_param("text", "txt")
    out = t.transform(df)
    assert out["out"][0] is None and out["out"][1] is None
    assert out["err"][0]["reasonPhrase"] == "request failed"


def test_bool_url_params_lowercase(svc):
    """Bool URL params render as JSON-style true/false, not Python True."""
    from mmlspark_tpu.services.base import ServiceParam, ServiceTransformer

    class _BoolSvc(ServiceTransformer):
        flag = ServiceParam(bool, default=True, is_url_param=True,
                            payload_name="returnFaceId")
        text = ServiceParam(str, is_required=True)

    t = _BoolSvc(url=svc + "/echo_query", output_col="out", error_col="err")
    t.set_vector_param("text", "txt")
    df = DataFrame({"txt": object_col(["a"])})
    out = t.transform(df)
    assert out["err"][0] is None
    assert out["out"][0]["query"]["returnFaceId"] == ["true"]

    # column-bound flag: rows yield np.bool_, which must also lowercase
    t2 = _BoolSvc(url=svc + "/echo_query", output_col="out", error_col="err")
    t2.set_vector_param("text", "txt")
    t2.set_vector_param("flag", "flagcol")
    df2 = DataFrame({"txt": object_col(["a"]), "flagcol": [False]})
    out2 = t2.transform(df2)
    assert out2["err"][0] is None
    assert out2["out"][0]["query"]["returnFaceId"] == ["false"]


def test_find_similar_face_target_validation(svc):
    """FindSimilarFace requires exactly one candidate source (reference
    Face.scala:96-182); violations land in the error column per row."""
    from mmlspark_tpu.services import FindSimilarFace

    df = DataFrame({"fid": object_col(["f-1"])})
    t = FindSimilarFace(url=svc + "/echo_query", output_col="out",
                        error_col="err", method="POST")
    t.set_vector_param("face_id", "fid")
    out = t.transform(df)            # no candidate source at all
    assert out["out"][0] is None
    assert "exactly one" in out["err"][0]["reasonPhrase"]

    t.set_scalar_param("face_list_id", "fl")
    t.set_scalar_param("face_ids", ["a", "b"])
    out = t.transform(df)            # two candidate sources
    assert "exactly one" in out["err"][0]["reasonPhrase"]

    ok = FindSimilarFace(url=svc + "/echo_query", output_col="out",
                         error_col="err")
    ok.set_vector_param("face_id", "fid")
    ok.set_scalar_param("face_list_id", "fl")
    ok.set_scalar_param("mode", "matchFace")
    res = ok.transform(df)
    assert res["err"][0] is None

    bad = FindSimilarFace(url=svc + "/echo_query", output_col="out",
                          error_col="err")
    bad.set_vector_param("face_id", "fid")
    bad.set_scalar_param("face_list_id", "fl")
    bad.set_scalar_param("mode", "bestMatch")
    res = bad.transform(df)
    assert "matchPerson" in res["err"][0]["reasonPhrase"]
