"""Request tracing: W3C traceparent parsing, span trees, contextvars
propagation across thread hops (the prefetch-worker regression), the
flight recorder's ring + slow-keep tiers, OpenMetrics exemplars, trace
stamping on events/journal, and serving end-to-end on both transports.

The E2E test is the PR's acceptance bar: a POST carrying a traceparent
must come back with X-Request-Id / traceparent echo headers AND leave a
/debug/traces entry whose tree nests server.request → engine.batch →
runner.* stage spans — including the coerce/pad spans that run on the
prefetch WORKER thread (the old ``threading.local`` dead-end dropped
those silently).
"""

import json
import logging
import threading
import time

import numpy as np
import pytest

from mmlspark_tpu import observability as obs
from mmlspark_tpu.observability import tracing as tr

TID = "ab" * 16
SID = "cd" * 8


@pytest.fixture(autouse=True)
def _clean_slate():
    obs.reset_all()
    tr.get_flight_recorder().clear()
    tr.configure_recorder(capacity=64, slow_threshold=1.0, slow_keep=32)
    yield
    tr.set_exemplars(False)
    tr.get_flight_recorder().clear()
    tr.configure_recorder(capacity=64, slow_threshold=1.0, slow_keep=32)
    obs.reset_all()


# ---------------------------------------------------------------------------
# traceparent


def test_parse_traceparent_roundtrip_and_normalization():
    assert tr.parse_traceparent(f"00-{TID}-{SID}-01") == (TID, SID)
    # input is case-normalized; trailing/leading whitespace tolerated
    assert tr.parse_traceparent(f" 00-{TID.upper()}-{SID}-00 ") == (TID, SID)
    # a future version may carry extra fields after flags
    assert tr.parse_traceparent(f"cc-{TID}-{SID}-01-extra") == (TID, SID)


@pytest.mark.parametrize("header", [
    None, "", "garbage", f"00-{TID}-{SID}",            # too few parts
    f"00-{'0' * 32}-{SID}-01",                         # all-zero trace id
    f"00-{TID}-{'0' * 16}-01",                         # all-zero span id
    f"ff-{TID}-{SID}-01",                              # forbidden version
    f"00-{TID}-{SID}-01-extra",                        # v00 is exactly 4 parts
    f"00-{TID[:-2]}-{SID}-01",                         # short trace id
    f"00-{TID}-{SID}zz"[:len(f'00-{TID}-{SID}-01')],   # non-hex
])
def test_parse_traceparent_rejects_malformed(header):
    assert tr.parse_traceparent(header) is None


def test_start_trace_continues_inbound_context():
    root = tr.start_trace("server.request", traceparent=f"00-{TID}-{SID}-01")
    assert root.trace_id == TID
    assert root.parent_id == SID
    assert root.trace.remote_parent_id == SID
    # the echo header advertises OUR span as the parent of downstream work
    echoed = tr.format_traceparent(root)
    assert echoed == f"00-{TID}-{root.span_id}-01"
    # malformed inbound → brand-new trace, never an error
    fresh = tr.start_trace("server.request", traceparent="ff-bogus")
    assert fresh.trace_id != TID and fresh.parent_id is None


# ---------------------------------------------------------------------------
# span trees


def test_span_tree_nesting_and_events():
    root = tr.start_trace("req", request_id="rid-1")
    with tr.activate(root):
        assert tr.current_trace_id() == root.trace_id
        assert tr.current_request_id() == "rid-1"
        with tr.start_span("outer", k="v") as outer:
            tr.add_event("milestone", n=1)
            with tr.start_span("inner"):
                pass
        assert outer.ended
    assert root.end(status=200)
    doc = root.trace.to_dict()
    assert doc["name"] == "req" and doc["request_id"] == "rid-1"
    (troot,) = doc["roots"]
    assert troot["name"] == "req"
    (child,) = troot["children"]
    assert child["name"] == "outer" and child["attrs"] == {"k": "v"}
    assert child["events"][0]["name"] == "milestone"
    (grand,) = child["children"]
    assert grand["name"] == "inner" and grand["children"] == []


def test_span_end_is_idempotent():
    root = tr.start_trace("req")
    assert root.end() is True
    dur = root.duration
    time.sleep(0.01)
    assert root.end() is False          # late double-close is harmless
    assert root.duration == dur


def test_start_span_inert_outside_a_trace():
    with tr.start_span("orphan") as s:
        assert s is None
        tr.add_event("nothing")         # no-op, must not raise
    assert tr.current_span() is None


def test_span_cap_drops_not_grows():
    root = tr.start_trace("req")
    with tr.activate(root):
        for i in range(tr.MAX_SPANS_PER_TRACE + 10):
            with tr.start_span(f"s{i}"):
                pass
    root.end()
    assert len(root.trace.spans) == tr.MAX_SPANS_PER_TRACE
    assert root.trace.dropped == 11
    assert root.trace.summary()["dropped"] == 11


def test_propagate_carries_context_into_plain_thread():
    seen = {}
    root = tr.start_trace("req", request_id="rid-2")

    def worker():
        seen["trace_id"] = tr.current_trace_id()
        seen["request_id"] = tr.current_request_id()
        with tr.start_span("worker.step"):
            pass

    with tr.activate(root):
        t = threading.Thread(target=tr.propagate(worker))
        t.start()
        t.join(5)
        bare = threading.Thread(target=worker)  # un-propagated control
    root.end()
    assert seen == {"trace_id": root.trace_id, "request_id": "rid-2"}
    assert "worker.step" in [s.name for s in root.trace.spans]
    bare.start()
    bare.join(5)
    assert seen["trace_id"] is None     # empty context without propagate()


# ---------------------------------------------------------------------------
# prefetch-worker regression (utils/profiling.py satellite)


def _make_runner(mini_batch_size=2, prefetch_depth=2, n=8):
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models.runner import BatchRunner

    data = np.arange(n, dtype=np.float32)

    def kernel(params, feeds):
        return {"y": feeds["x"] * params["w"]}

    return BatchRunner(jax.jit(kernel), {"w": jnp.float32(2.0)},
                       coerce=lambda sl: {"x": data[sl]},
                       put=jax.device_put,
                       mini_batch_size=mini_batch_size,
                       prefetch_depth=prefetch_depth), n


def test_prefetch_worker_spans_land_in_parent_tracer():
    """The regression the contextvars migration fixes: coerce/pad run on
    the PrefetchIterator worker thread, and a SpanTracer installed on the
    dispatch thread must still record them (threading.local lost them)."""
    from mmlspark_tpu.utils.profiling import SpanTracer
    runner, n = _make_runner(mini_batch_size=2, prefetch_depth=2, n=8)
    with SpanTracer() as t:
        out = runner.run_and_drain(n)
    assert sum(b for _, b in out) == n
    names = [e["name"] for e in t.events]
    assert names.count("runner.coerce") == 4
    assert names.count("runner.pad") == 4
    assert "runner.run" in names and "runner.d2h" in names


def test_prefetch_worker_spans_join_request_trace():
    runner, n = _make_runner(mini_batch_size=2, prefetch_depth=2, n=8)
    root = tr.start_trace("req")
    with tr.activate(root):
        runner.run_and_drain(n)
    root.end()
    spans = root.trace.spans
    coerce = [s for s in spans if s.name == "runner.coerce"]
    assert len(coerce) == 4
    # ... and they really ran off-thread: the prefetch worker's name, not
    # the dispatch thread that owns the root span
    assert {s.thread for s in coerce} != {root.thread}
    events = [e["name"] for s in spans for e in s.events]
    assert "pad_bucket" in events
    assert "cache_hit" in events or "cache_miss" in events


# ---------------------------------------------------------------------------
# flight recorder


def _ended_trace(duration=None):
    root = tr.start_trace("req")
    root.end()
    if duration is not None:
        root._dur = duration            # deterministic tier selection
    return root.trace


def test_recorder_ring_wraps_but_slow_traces_survive():
    rec = tr.FlightRecorder(capacity=4, slow_threshold=0.5, slow_keep=2)
    slow = _ended_trace(duration=2.0)
    rec.record(slow)
    fast = [_ended_trace(duration=0.001) for _ in range(10)]
    for t in fast:
        rec.record(t)
    # the ring wrapped ten fast traces through capacity 4 ...
    ids = [t.trace_id for t in rec.traces()]
    assert len(ids) == 5
    # ... newest first, slow-kept ahead of the ring, the slow one intact
    assert ids[0] == slow.trace_id
    assert ids[1:] == [t.trace_id for t in reversed(fast[-4:])]
    assert rec.get(slow.trace_id) is slow
    assert rec.get(fast[0].trace_id) is None          # evicted


def test_recorder_slow_keep_evicts_oldest_slow():
    rec = tr.FlightRecorder(capacity=4, slow_threshold=0.5, slow_keep=2)
    slows = [_ended_trace(duration=1.0 + i) for i in range(3)]
    for t in slows:
        rec.record(t)
    assert rec.get(slows[0].trace_id) is None
    assert [t.trace_id for t in rec.traces()] == [
        slows[2].trace_id, slows[1].trace_id]


def test_trace_to_chrome_shape():
    root = tr.start_trace("req")
    with tr.activate(root):
        with tr.start_span("stage", rows=3):
            pass
    root.end()
    doc = root.trace.to_chrome()
    assert doc["displayTimeUnit"] == "ms"
    stage = next(e for e in doc["traceEvents"] if e["name"] == "stage")
    assert stage["ph"] == "X" and stage["pid"] == 0
    assert stage["args"]["rows"] == 3
    assert stage["args"]["trace_id"] == root.trace_id


# ---------------------------------------------------------------------------
# exemplars


def test_metrics_unchanged_until_exemplars_enabled():
    h = obs.histogram("t_exemplar_seconds", "t", ())
    root = tr.start_trace("req")
    with tr.activate(root):
        h.observe(0.01)
    root.end()
    text = obs.render()
    assert "# {" not in text            # byte-identical Prometheus 0.0.4
    assert not tr.exemplars_enabled()

    tr.set_exemplars(True)
    assert tr.exemplars_enabled()
    root2 = tr.start_trace("req2")
    with tr.activate(root2):
        h.observe(0.02)
    root2.end()
    enabled = obs.render()
    assert f'# {{trace_id="{root2.trace_id}"}}' in enabled

    # flipping back off hides them again — scrape format reverts cleanly
    tr.set_exemplars(False)
    assert "# {" not in obs.render()


def test_exemplars_skip_observations_outside_a_trace():
    tr.set_exemplars(True)
    h = obs.histogram("t_exemplar2_seconds", "t", ())
    h.observe(0.01)                     # no active span → no exemplar
    assert "# {" not in obs.render()


# ---------------------------------------------------------------------------
# event log + journal stamping


def test_event_log_stamps_trace_and_request_id(caplog):
    root = tr.start_trace("req", request_id="rid-9")
    with caplog.at_level(logging.INFO, logger="mmlspark_tpu.events"):
        with tr.activate(root):
            obs.log_event("inside", x=1)
        obs.log_event("outside")
    root.end()
    inside, outside = [json.loads(r.getMessage()) for r in caplog.records]
    assert inside["event"] == "inside"
    assert inside["trace_id"] == root.trace_id
    assert inside["request_id"] == "rid-9"
    assert "trace_id" not in outside and "request_id" not in outside


def test_journal_persists_trace_id_through_compaction(tmp_path):
    from mmlspark_tpu.io.http.schema import EntityData, HTTPRequestData
    from mmlspark_tpu.serving.journal import ServingJournal

    def _req(body):
        return HTTPRequestData(entity=EntityData.from_string(body))

    p = str(tmp_path / "j.jsonl")
    j = ServingJournal(p)
    j.record_request("a", 0, _req("one"), trace_id=TID)
    j.record_request("b", 0, _req("two"))
    j.record_reply("b")
    recs = [json.loads(ln) for ln in open(p).read().splitlines()]
    assert recs[0]["trace"] == TID
    assert "trace" not in recs[1]
    # compaction rewrites the journal from raw records — the trace join
    # key must survive for replayed (crash-recovered) requests
    assert j.maybe_compact(epoch=1, min_lines=1)
    recs = [json.loads(ln) for ln in open(p).read().splitlines()]
    (live,) = [r for r in recs if r.get("t") == "req"]
    assert live["id"] == "a" and live["trace"] == TID
    j.close()


# ---------------------------------------------------------------------------
# serving end-to-end


def test_healthz_uptime_and_build_info():
    import requests
    from mmlspark_tpu.serving import WorkerServer
    server = WorkerServer()
    try:
        body = requests.get(
            f"http://127.0.0.1:{server.port}/healthz", timeout=10).json()
        assert body["status"] == "ok"
        assert body["uptime_seconds"] >= 0.0
        snap = obs.snapshot()
        (series,) = snap["mmlspark_build_info"]["series"]
        assert series["value"] == 1
        assert set(series["labels"]) == {"version", "jax", "backend"}
        assert series["labels"]["version"] not in ("", None)
    finally:
        server.close()


@pytest.mark.parametrize("transport", ["threaded", "async"])
def test_traced_request_end_to_end(transport):
    """Acceptance: POST with a traceparent through a real ServingEngine →
    echo headers on the response, and /debug/traces serves the span tree
    server.request → engine.batch → runner.* with prefetch-worker spans."""
    import jax
    import jax.numpy as jnp
    import requests
    from mmlspark_tpu.models.runner import BatchRunner
    from mmlspark_tpu.serving import ServingEngine

    def kernel(params, feeds):
        return {"y": feeds["x"] * params["w"]}

    jitted = jax.jit(kernel)
    params = {"w": jnp.float32(2.0)}

    def pipeline(df):
        x = np.asarray(df["x"], dtype=np.float32)
        # repeat each row so even a 1-row request spans several
        # minibatches and the prefetch worker thread actually runs
        rep = np.repeat(x, 8)
        runner = BatchRunner(jitted, params,
                             coerce=lambda sl: {"x": rep[sl]},
                             put=jax.device_put,
                             mini_batch_size=2, prefetch_depth=2)
        outs = runner.run_and_drain(len(rep))
        vals = np.concatenate([np.asarray(o["y"])[:b] for o, b in outs])
        return df.with_column("reply", vals[::8][:len(x)].astype(float))

    sent = f"00-{TID}-{SID}-01"
    with ServingEngine(pipeline, schema={"x": float},
                       transport=transport) as eng:
        r = requests.post(eng.address, json={"x": 21.0},
                          headers={"traceparent": sent}, timeout=30)
        assert r.status_code == 200 and r.json() == 42.0
        # echo headers: the request id for log joins, OUR root span as the
        # downstream parent of the caller's trace
        rid = r.headers["X-Request-Id"]
        echoed = tr.parse_traceparent(r.headers["traceparent"])
        assert echoed is not None and echoed[0] == TID

        base = f"http://127.0.0.1:{eng.server.port}/debug/traces"
        listing = requests.get(base, timeout=10).json()
        assert listing["slow_threshold_seconds"] == pytest.approx(
            tr.get_flight_recorder().slow_threshold)
        summary = next(t for t in listing["traces"]
                       if t["trace_id"] == TID)
        assert summary["request_id"] == rid
        assert summary["duration_s"] > 0

        doc = requests.get(f"{base}/{TID}", timeout=10).json()
        (troot,) = doc["roots"]
        assert troot["name"] == "server.request"
        assert troot["parent_id"] == SID            # continued, not minted
        assert troot["attrs"]["request_id"] == rid
        batch = next(c for c in troot["children"]
                     if c["name"] == "engine.batch")
        run = next(c for c in batch["children"] if c["name"] == "runner.run")
        flat, stack = [], [run]
        while stack:
            node = stack.pop()
            flat.append(node)
            stack.extend(node["children"])
        names = [n["name"] for n in flat]
        assert "runner.coerce" in names and "runner.pad" in names
        assert "runner.d2h" in [c["name"] for c in batch["children"]] \
            or "runner.d2h" in names
        # the coerce spans ran on the prefetch worker thread
        coerce_threads = {n["thread"] for n in flat
                          if n["name"] == "runner.coerce"}
        assert coerce_threads and coerce_threads != {troot["thread"]}

        chrome = requests.get(f"{base}/{TID}?format=chrome",
                              timeout=10).json()
        assert chrome["displayTimeUnit"] == "ms"
        assert any(e["name"] == "server.request"
                   for e in chrome["traceEvents"])

        missing = requests.get(f"{base}/{'9' * 32}", timeout=10)
        assert missing.status_code == 404
        assert missing.json()["error"] == "unknown trace_id"


def test_request_without_traceparent_mints_fresh_trace():
    import requests
    from mmlspark_tpu.serving import ServingEngine

    def pipeline(df):
        return df.with_column("reply", np.asarray(df["x"]) + 1.0)

    with ServingEngine(pipeline, schema={"x": float}) as eng:
        r = requests.post(eng.address, json={"x": 1.0}, timeout=30)
        assert r.status_code == 200
        echoed = tr.parse_traceparent(r.headers["traceparent"])
        assert echoed is not None
        trace = tr.get_flight_recorder().get(echoed[0])
        assert trace is not None
        assert trace.root.attrs["request_id"] == r.headers["X-Request-Id"]
