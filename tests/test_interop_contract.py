"""Exercise the PySpark wiring WITHOUT pyspark: a minimal stub of the
mapInPandas contract + pyspark.sql.types, so the only untested branch left
is the physical Spark cluster the image cannot host.

The contract being pinned (pyspark's documented semantics):
- ``mapInPandas(fn, schema)`` calls ``fn`` with an ITERATOR of
  pandas.DataFrame batches and expects an iterator of pandas.DataFrame out;
- the declared schema must match what the reference's generated wrappers
  would declare (``ONNXModel.scala:606-653`` reads model metadata; here a
  probe row infers it);
- arrow serialization rejects ndarray cells — they must cross as lists.
"""

import sys
import types
from dataclasses import dataclass, field
from typing import List

import numpy as np
import pandas as pd
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.interop import (make_pandas_udf_fn, spark_schema_for,
                                  spark_transform, transform_pandas)


# -- pyspark stub ------------------------------------------------------------

@dataclass
class _Type:
    name: str = ""

    def __eq__(self, other):
        return type(self) is type(other) and vars(self) == vars(other)


class BooleanType(_Type):
    pass


class LongType(_Type):
    pass


class FloatType(_Type):
    pass


class DoubleType(_Type):
    pass


class StringType(_Type):
    pass


@dataclass
class ArrayType:
    elementType: object = None

    def __eq__(self, other):
        return (isinstance(other, ArrayType)
                and self.elementType == other.elementType)


@dataclass
class StructField:
    name: str = ""
    dataType: object = None

    def __init__(self, name, dataType):
        self.name = name
        self.dataType = dataType

    def __eq__(self, other):
        return (isinstance(other, StructField) and self.name == other.name
                and self.dataType == other.dataType)


@dataclass
class StructType:
    fields: List = field(default_factory=list)


class FakeSparkDataFrame:
    """The mapInPandas half of the contract: slice into an ITERATOR of
    pandas batches, feed the user fn, demand an iterator back, concat."""

    def __init__(self, pdf: pd.DataFrame, batch_size: int = 2):
        self.pdf = pdf
        self.batch_size = batch_size
        self.declared_schema = None

    def mapInPandas(self, fn, schema):
        self.declared_schema = schema

        def batches():
            for i in range(0, len(self.pdf), self.batch_size):
                yield self.pdf.iloc[i:i + self.batch_size].reset_index(
                    drop=True)

        out_iter = fn(batches())
        assert hasattr(out_iter, "__next__") or hasattr(out_iter, "__iter__")
        parts = list(out_iter)
        assert all(isinstance(p, pd.DataFrame) for p in parts)
        # arrow's rule: object cells must be plain python (lists), never
        # ndarrays — enforce it like the real serializer would
        for p in parts:
            for c in p.columns:
                if p[c].dtype == object:
                    for v in p[c]:
                        assert not isinstance(v, np.ndarray), \
                            f"ndarray cell leaked to arrow in column {c!r}"
        return pd.concat(parts, ignore_index=True)


@pytest.fixture()
def pyspark_stub(monkeypatch):
    root = types.ModuleType("pyspark")
    sql = types.ModuleType("pyspark.sql")
    typ = types.ModuleType("pyspark.sql.types")
    for cls in (BooleanType, LongType, FloatType, DoubleType, StringType,
                ArrayType, StructField, StructType):
        setattr(typ, cls.__name__, cls)
    root.sql = sql
    sql.types = typ
    monkeypatch.setitem(sys.modules, "pyspark", root)
    monkeypatch.setitem(sys.modules, "pyspark.sql", sql)
    monkeypatch.setitem(sys.modules, "pyspark.sql.types", typ)
    return root


# -- a small real stage ------------------------------------------------------

class _Scorer(Transformer):
    """Adds score = sum(features) (float32) and label_str columns."""

    def _transform(self, df: DataFrame) -> DataFrame:
        import numpy as np
        feats = df["features"]
        scores = np.asarray([np.float32(np.sum(v)) for v in feats],
                            np.float32)
        labels = np.empty(len(scores), object)
        labels[:] = ["hi" if s > 0 else "lo" for s in scores]
        vecs = np.empty(len(scores), object)
        vecs[:] = [np.asarray([s, -s], np.float32) for s in scores]
        out = df.with_column("score", scores)
        out = out.with_column("label_str", labels)
        return out.with_column("vec", vecs)


def _pdf(n=5):
    rng = np.random.default_rng(0)
    return pd.DataFrame({
        "features": [rng.normal(size=3).astype(np.float32) for _ in range(n)],
        "idx": np.arange(n, dtype=np.int64),
    })


def test_iterator_of_batches_protocol(pyspark_stub):
    """spark_transform through the full mapInPandas contract: iterator in,
    iterator out, multiple batches, ndarray→list conversion, row order."""
    pdf = _pdf(7)
    sdf = FakeSparkDataFrame(pdf, batch_size=3)    # 3 uneven batches
    out = spark_transform(_Scorer(), sdf, sample_pdf=pdf.head(2))
    assert len(out) == 7
    want = [float(np.sum(v)) for v in pdf["features"]]
    np.testing.assert_allclose(out["score"].to_numpy(), want, rtol=1e-6)
    assert list(out["idx"]) == list(range(7))      # order preserved
    assert isinstance(out["vec"][0], list)         # arrow-safe cells
    assert sdf.declared_schema is not None


def test_schema_inference_matches_contract(pyspark_stub):
    pdf = _pdf(3)
    schema = spark_schema_for(_Scorer(), pdf)
    by_name = {f.name: f.dataType for f in schema.fields}
    assert by_name["idx"] == LongType()
    assert by_name["score"] == FloatType()
    assert by_name["label_str"] == StringType()
    assert by_name["vec"] == ArrayType(FloatType())
    assert by_name["features"] == ArrayType(FloatType())


def test_schema_nested_array_and_output_cols(pyspark_stub):
    class _Mat(Transformer):
        def _transform(self, df):
            n = len(df["x"])
            mats = np.empty(n, object)
            mats[:] = [np.zeros((2, 2), np.float64) for _ in range(n)]
            return df.with_column("mat", mats)

    pdf = pd.DataFrame({"x": np.arange(3, dtype=np.int64)})
    schema = spark_schema_for(_Mat(), pdf, output_cols=["mat"])
    assert [f.name for f in schema.fields] == ["mat"]
    assert schema.fields[0].dataType == ArrayType(ArrayType(DoubleType()))


def test_explicit_schema_skips_inference(pyspark_stub):
    pdf = _pdf(4)
    sdf = FakeSparkDataFrame(pdf, batch_size=2)
    schema = StructType([StructField("score", FloatType())])
    out = spark_transform(_Scorer(), sdf, output_cols=["score"],
                          schema=schema)
    assert list(out.columns) == ["score"]
    assert sdf.declared_schema is schema


def test_missing_schema_and_sample_rejected(pyspark_stub):
    with pytest.raises(ValueError, match="schema"):
        spark_transform(_Scorer(), FakeSparkDataFrame(_pdf()), None)


def test_pyspark_gate_message_without_stub():
    """Without the stub (and without real pyspark) the gate raises the
    guidance error, not an opaque ModuleNotFoundError."""
    if "pyspark" in sys.modules and not isinstance(
            sys.modules["pyspark"].__dict__.get("sql"), types.ModuleType):
        pytest.skip("real pyspark present")
    assert "pyspark" not in sys.modules or True
    try:
        import pyspark     # noqa: F401
        pytest.skip("real pyspark importable in this image")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="transform_pandas"):
        spark_transform(_Scorer(), object())


def test_udf_fn_is_reusable_across_batches(pyspark_stub):
    fn = make_pandas_udf_fn(_Scorer(), output_cols=["score"])
    a = fn(_pdf(2))
    b = fn(_pdf(3))
    assert list(a.columns) == ["score"] and len(a) == 2 and len(b) == 3
