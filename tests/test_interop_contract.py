"""Exercise the PySpark wiring WITHOUT pyspark: a minimal stub of the
mapInPandas contract + pyspark.sql.types, so the only untested branch left
is the physical Spark cluster the image cannot host.

The contract being pinned (pyspark's documented semantics):
- ``mapInPandas(fn, schema)`` calls ``fn`` with an ITERATOR of
  pandas.DataFrame batches and expects an iterator of pandas.DataFrame out;
- the declared schema must match what the reference's generated wrappers
  would declare (``ONNXModel.scala:606-653`` reads model metadata; here a
  probe row infers it);
- every yielded batch crosses GENUINE pyarrow IPC bytes under the declared
  schema (the ArrowStreamPandasUDFSerializer step) — ndarray cells, dtype
  mismatches, and missing columns fail exactly where a real cluster would;
- execution is lazy (the udf runs at toPandas()/collect()) and udf errors
  surface as the PythonException shape: message + worker traceback.
"""

import sys
import types
from dataclasses import dataclass, field
from typing import List

import numpy as np
import pandas as pd
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.interop import make_pandas_udf_fn, spark_schema_for, spark_transform

# -- pyspark stub ------------------------------------------------------------

@dataclass
class _Type:
    name: str = ""

    def __eq__(self, other):
        return type(self) is type(other) and vars(self) == vars(other)


class BooleanType(_Type):
    pass


class LongType(_Type):
    pass


class FloatType(_Type):
    pass


class DoubleType(_Type):
    pass


class StringType(_Type):
    pass


@dataclass
class ArrayType:
    elementType: object = None

    def __eq__(self, other):
        return (isinstance(other, ArrayType)
                and self.elementType == other.elementType)


@dataclass
class StructField:
    name: str = ""
    dataType: object = None

    def __init__(self, name, dataType):
        self.name = name
        self.dataType = dataType

    def __eq__(self, other):
        return (isinstance(other, StructField) and self.name == other.name
                and self.dataType == other.dataType)


@dataclass
class StructType:
    fields: List = field(default_factory=list)


def _arrow_type(t):
    """Declared Spark SQL type → the arrow type Spark's serializer maps it
    to (`pyspark/sql/pandas/types.py::to_arrow_type` semantics)."""
    import pyarrow as pa
    if isinstance(t, BooleanType):
        return pa.bool_()
    if isinstance(t, LongType):
        return pa.int64()
    if isinstance(t, FloatType):
        return pa.float32()
    if isinstance(t, DoubleType):
        return pa.float64()
    if isinstance(t, StringType):
        return pa.string()
    if isinstance(t, ArrayType):
        return pa.list_(_arrow_type(t.elementType))
    raise TypeError(f"no arrow mapping for {t!r}")


class FakeSparkException(Exception):
    """Stands in for pyspark's PythonException: carries the worker-side
    traceback text the way Spark surfaces udf failures on collect()."""

    def __init__(self, cause: BaseException, tb_text: str):
        super().__init__(f"{type(cause).__name__}: {cause}")
        self.cause = cause
        self.tb_text = tb_text


class FakeSparkDataFrame:
    """The mapInPandas half of the contract: slice into an ITERATOR of
    pandas batches, feed the user fn, demand an iterator back, and push
    every yielded batch through GENUINE arrow IPC against the declared
    schema — the exact wire step Spark's ArrowStreamPandasUDFSerializer
    performs, so a schema/data mismatch fails here like it would on a real
    cluster. Errors raised inside the udf surface as FakeSparkException
    with the worker traceback (pyspark's PythonException shape)."""

    def __init__(self, pdf: pd.DataFrame, batch_size: int = 2):
        self.pdf = pdf
        self.batch_size = batch_size
        self.declared_schema = None

    def mapInPandas(self, fn, schema):
        self.declared_schema = schema
        return _FakeLazyResult(self, fn, schema)

    def _execute(self, fn, schema):
        import io
        import traceback

        import pyarrow as pa

        arrow_schema = pa.schema(
            [(f.name, _arrow_type(f.dataType)) for f in schema.fields])

        def batches():
            for i in range(0, len(self.pdf), self.batch_size):
                yield self.pdf.iloc[i:i + self.batch_size].reset_index(
                    drop=True)

        try:
            out_iter = fn(batches())
            assert hasattr(out_iter, "__next__") \
                or hasattr(out_iter, "__iter__")
            buf = io.BytesIO()
            writer = None
            n_parts = 0
            for p in out_iter:
                assert isinstance(p, pd.DataFrame)
                n_parts += 1
                # THE serialization step: pandas → arrow RecordBatch under
                # the declared schema (raises on ndarray cells, wrong
                # dtypes, missing columns), then actual IPC bytes
                rb = pa.RecordBatch.from_pandas(
                    p, schema=arrow_schema, preserve_index=False)
                if writer is None:
                    writer = pa.ipc.new_stream(buf, arrow_schema)
                writer.write_batch(rb)
        except Exception as e:      # noqa: BLE001 — udf errors become
            raise FakeSparkException(e, traceback.format_exc()) from e
        if n_parts == 0:
            # real Spark returns an arrow-typed empty frame (float32 for
            # FloatType etc.), never object columns
            return pa.Table.from_batches([], schema=arrow_schema).to_pandas()
        writer.close()
        buf.seek(0)
        table = pa.ipc.open_stream(buf).read_all()
        return table.to_pandas()


class _FakeLazyResult:
    """Spark is lazy: mapInPandas returns a plan; the udf only runs at an
    action. collect()/toPandas() triggers execution here the same way."""

    def __init__(self, src, fn, schema):
        self._src, self._fn, self._schema = src, fn, schema

    def toPandas(self) -> pd.DataFrame:
        return self._src._execute(self._fn, self._schema)

    def collect(self):
        pdf = self.toPandas()
        return list(pdf.itertuples(index=False))


@pytest.fixture()
def pyspark_stub(monkeypatch):
    root = types.ModuleType("pyspark")
    sql = types.ModuleType("pyspark.sql")
    typ = types.ModuleType("pyspark.sql.types")
    for cls in (BooleanType, LongType, FloatType, DoubleType, StringType,
                ArrayType, StructField, StructType):
        setattr(typ, cls.__name__, cls)
    root.sql = sql
    sql.types = typ
    monkeypatch.setitem(sys.modules, "pyspark", root)
    monkeypatch.setitem(sys.modules, "pyspark.sql", sql)
    monkeypatch.setitem(sys.modules, "pyspark.sql.types", typ)
    return root


# -- a small real stage ------------------------------------------------------

class _Scorer(Transformer):
    """Adds score = sum(features) (float32) and label_str columns."""

    def _transform(self, df: DataFrame) -> DataFrame:
        import numpy as np
        feats = df["features"]
        scores = np.asarray([np.float32(np.sum(v)) for v in feats],
                            np.float32)
        labels = np.empty(len(scores), object)
        labels[:] = ["hi" if s > 0 else "lo" for s in scores]
        vecs = np.empty(len(scores), object)
        vecs[:] = [np.asarray([s, -s], np.float32) for s in scores]
        out = df.with_column("score", scores)
        out = out.with_column("label_str", labels)
        return out.with_column("vec", vecs)


def _pdf(n=5):
    rng = np.random.default_rng(0)
    return pd.DataFrame({
        "features": [rng.normal(size=3).astype(np.float32) for _ in range(n)],
        "idx": np.arange(n, dtype=np.int64),
    })


def test_iterator_of_batches_protocol(pyspark_stub):
    """spark_transform through the full mapInPandas contract: iterator in,
    iterator out, multiple batches, ndarray→list conversion, row order."""
    pdf = _pdf(7)
    sdf = FakeSparkDataFrame(pdf, batch_size=3)    # 3 uneven batches
    out = spark_transform(_Scorer(), sdf, sample_pdf=pdf.head(2)).toPandas()
    assert len(out) == 7
    want = [float(np.sum(v)) for v in pdf["features"]]
    np.testing.assert_allclose(out["score"].to_numpy(), want, rtol=1e-6)
    assert list(out["idx"]) == list(range(7))      # order preserved
    # cells surviving genuine arrow IPC proves they were arrow-safe
    s0 = float(np.sum(pdf["features"][0]))
    np.testing.assert_allclose(np.asarray(out["vec"][0]), [s0, -s0],
                               rtol=1e-6)
    assert sdf.declared_schema is not None


def test_schema_inference_matches_contract(pyspark_stub):
    pdf = _pdf(3)
    schema = spark_schema_for(_Scorer(), pdf)
    by_name = {f.name: f.dataType for f in schema.fields}
    assert by_name["idx"] == LongType()
    assert by_name["score"] == FloatType()
    assert by_name["label_str"] == StringType()
    assert by_name["vec"] == ArrayType(FloatType())
    assert by_name["features"] == ArrayType(FloatType())


def test_schema_nested_array_and_output_cols(pyspark_stub):
    class _Mat(Transformer):
        def _transform(self, df):
            n = len(df["x"])
            mats = np.empty(n, object)
            mats[:] = [np.zeros((2, 2), np.float64) for _ in range(n)]
            return df.with_column("mat", mats)

    pdf = pd.DataFrame({"x": np.arange(3, dtype=np.int64)})
    schema = spark_schema_for(_Mat(), pdf, output_cols=["mat"])
    assert [f.name for f in schema.fields] == ["mat"]
    assert schema.fields[0].dataType == ArrayType(ArrayType(DoubleType()))


def test_explicit_schema_skips_inference(pyspark_stub):
    pdf = _pdf(4)
    sdf = FakeSparkDataFrame(pdf, batch_size=2)
    schema = StructType([StructField("score", FloatType())])
    out = spark_transform(_Scorer(), sdf, output_cols=["score"],
                          schema=schema).toPandas()
    assert list(out.columns) == ["score"]
    assert out["score"].dtype == np.float32    # FloatType held through IPC
    assert sdf.declared_schema is schema


def test_missing_schema_and_sample_rejected(pyspark_stub):
    with pytest.raises(ValueError, match="schema"):
        spark_transform(_Scorer(), FakeSparkDataFrame(_pdf()), None)


def test_pyspark_gate_message_without_stub():
    """Without the stub (and without real pyspark) the gate raises the
    guidance error, not an opaque ModuleNotFoundError."""
    if "pyspark" in sys.modules and not isinstance(
            sys.modules["pyspark"].__dict__.get("sql"), types.ModuleType):
        pytest.skip("real pyspark present")
    assert "pyspark" not in sys.modules or True
    try:
        import pyspark     # noqa: F401
        pytest.skip("real pyspark importable in this image")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="transform_pandas"):
        spark_transform(_Scorer(), object())


def test_udf_fn_is_reusable_across_batches(pyspark_stub):
    fn = make_pandas_udf_fn(_Scorer(), output_cols=["score"])
    a = fn(_pdf(2))
    b = fn(_pdf(3))
    assert list(a.columns) == ["score"] and len(a) == 2 and len(b) == 3


def test_udf_error_propagates_with_worker_traceback(pyspark_stub):
    """Errors inside the udf must surface at the ACTION as the pyspark
    PythonException shape — message plus worker traceback — not vanish
    into the iterator."""
    class _Boom(Transformer):
        def _transform(self, df):
            raise RuntimeError("bad rows in partition")

    sdf = FakeSparkDataFrame(_pdf(4), batch_size=2)
    schema = StructType([StructField("score", FloatType())])
    plan = spark_transform(_Boom(), sdf, schema=schema)
    with pytest.raises(FakeSparkException,
                       match="bad rows in partition") as ei:
        plan.toPandas()
    assert "RuntimeError" in ei.value.tb_text
    assert "_transform" in ei.value.tb_text      # worker frames included


def test_arrow_rejects_wrong_schema_declaration(pyspark_stub):
    """Declaring a schema the data cannot serialize under must fail at the
    arrow step (as on a real cluster), not silently coerce."""
    sdf = FakeSparkDataFrame(_pdf(4), batch_size=2)
    schema = StructType([StructField("score", ArrayType(FloatType()))])
    with pytest.raises(FakeSparkException):
        spark_transform(_Scorer(), sdf, output_cols=["score"],
                        schema=schema).toPandas()


def test_lazy_until_action(pyspark_stub):
    """mapInPandas returns a plan; the udf runs only at collect()."""
    calls = []

    class _Count(Transformer):
        def _transform(self, df):
            calls.append(1)
            return df

    sdf = FakeSparkDataFrame(pd.DataFrame({"x": np.array([1.0, 2.0])}),
                             batch_size=1)
    schema = StructType([StructField("x", DoubleType())])
    plan = spark_transform(_Count(), sdf, schema=schema)
    assert calls == []                 # nothing ran yet
    rows = plan.collect()
    assert len(rows) == 2 and calls    # executed at the action


def test_empty_input_yields_empty_frame_with_schema(pyspark_stub):
    sdf = FakeSparkDataFrame(_pdf(0), batch_size=2)
    schema = StructType([StructField("score", FloatType())])
    out = spark_transform(_Scorer(), sdf, output_cols=["score"],
                          schema=schema).toPandas()
    assert len(out) == 0 and list(out.columns) == ["score"]
    assert out["score"].dtype == np.float32    # arrow-typed, not object
