"""Distributed serving: registry, cross-worker reply routing, forwarding,
kill-and-replay — the round-1 missing piece (parity:
``HTTPSourceV2.scala:476-697``, ``DriverServiceUtils:134-195``)."""

import json
import threading
import time
import urllib.request

from mmlspark_tpu.serving.distributed import (DistributedWorker,
                                              DriverRegistry, ServingCluster)


def _post(url, payload, timeout=20.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode() or "{}")


def _client(url, payload, out, idx):
    try:
        out[idx] = _post(url, payload)
    except Exception as e:  # pragma: no cover - surfaced via assert
        out[idx] = e


def test_registry_register_recover_liveness():
    reg = DriverRegistry(liveness_timeout=0.5)
    try:
        info = reg.register("w0", "http://127.0.0.1:1")
        assert not info["recovered"]
        info2 = reg.register("w0", "http://127.0.0.1:2")  # restart, same id
        assert info2["recovered"]
        assert reg.routing_table()["w0"] == "http://127.0.0.1:2"
        assert info2["generation"] > info["generation"]
        time.sleep(0.6)  # no heartbeat → drops from the routing table
        assert "w0" not in reg.routing_table()
        assert not reg.heartbeat("nobody")
    finally:
        reg.close()


def test_cross_worker_reply_routing():
    """Request parked on worker A; the reply is issued *through worker B*
    (the engine ran on B's host) and must route back over HTTP to A."""
    cluster = ServingCluster(2, reply_timeout=15.0)
    try:
        wa, wb = cluster.workers
        out = [None]
        t = threading.Thread(target=_client,
                             args=(wa.server.address, {"x": 1}, out, 0))
        t.start()
        batch = []
        deadline = time.time() + 10
        while not batch and time.time() < deadline:
            batch = wa.get_batch(4, timeout=0.2)
        assert batch, "request never reached worker A's queue"
        owner_id, cached = batch[0]
        assert owner_id == wa.worker_id
        from mmlspark_tpu.io.http.schema import (EntityData,
                                                 HTTPResponseData,
                                                 StatusLineData)
        resp = HTTPResponseData(
            entity=EntityData.from_string(json.dumps({"answered_by": "B"})),
            status_line=StatusLineData(status_code=200))
        ok = wb.reply(owner_id, cached.request_id, resp)  # remote route
        assert ok
        t.join(timeout=15)
        status, payload = out[0]
        assert status == 200 and payload == {"answered_by": "B"}
    finally:
        cluster.close()


def test_forwarding_round_robin():
    """Worker A has no engine: public requests forward to peers and the
    client still gets the answer through A (load-balancer parity)."""
    cluster = ServingCluster(3, reply_timeout=15.0)
    try:
        wa = cluster.workers[0]
        wa.enable_forwarding()
        for w in cluster.workers:
            w.refresh_peers()

        stop = threading.Event()
        seen_urls = []

        def engine():
            while not stop.is_set():
                for owner, cached in cluster.get_batch(8, timeout=0.05):
                    seen_urls.append((cached.request.url,
                                      cached.request.method))
                    cluster.reply(owner, cached.request_id, _json_resp(
                        {"served": owner}))

        eng = threading.Thread(target=engine, daemon=True)
        eng.start()
        outs = [None, None, None, None]
        threads = [threading.Thread(target=_client,
                                    args=(wa.server.address.rstrip("/")
                                          + f"/score?i={i}", {"i": i},
                                          outs, i))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        stop.set()
        eng.join(timeout=5)
        served = set()
        for o in outs:
            assert isinstance(o, tuple), f"client failed: {o!r}"
            status, payload = o
            assert status == 200
            served.add(payload["served"])
        # A forwards round-robin → both B and C served something
        assert served == {"worker-1", "worker-2"}
        # the client's original path/query and method survive the hop
        assert all(u.startswith("/score?i=") and m == "POST"
                   for u, m in seen_urls), seen_urls
    finally:
        cluster.close()


def _json_resp(payload, status=200):
    from mmlspark_tpu.io.http.schema import (EntityData, HTTPResponseData,
                                             StatusLineData)
    return HTTPResponseData(
        entity=EntityData.from_string(json.dumps(payload)),
        status_line=StatusLineData(status_code=status))


def test_kill_and_replay():
    """An engine takes a batch and dies without replying; after the worker
    re-registers and replays, a second engine answers the SAME parked client
    connection (parity: registerPartition rehydration :489-506)."""
    reg = DriverRegistry()
    try:
        w = DistributedWorker(reg.url, "w0", reply_timeout=20.0)
        out = [None]
        t = threading.Thread(target=_client,
                             args=(w.server.address, {"q": 42}, out, 0))
        t.start()
        batch = []
        deadline = time.time() + 10
        while not batch and time.time() < deadline:
            batch = w.get_batch(4, timeout=0.2)
        assert batch
        # engine 1 crashes here — no reply. Simulate task retry:
        w2_info_recovered = DistributedWorker(reg.url, "w0",
                                              reply_timeout=20.0)
        assert w2_info_recovered.recovered  # driver saw the same worker id
        w2_info_recovered.close(deregister=False)
        n = w.server.replay_unanswered()
        assert n == 1
        batch2 = w.get_batch(4, timeout=1.0)
        assert len(batch2) == 1
        owner, cached = batch2[0]
        assert cached.request_id == batch[0][1].request_id
        assert w.reply(owner, cached.request_id, _json_resp({"ok": True}))
        t.join(timeout=20)
        status, payload = out[0]
        assert status == 200 and payload == {"ok": True}
        w.close()
    finally:
        reg.close()


def test_remote_reply_closes_root_span_exactly_once():
    """The forwarded reply path (engine on B, connection parked on A) must
    end A's root span at the first reply and leave it untouched on a
    late duplicate — a double-close would corrupt the recorded duration
    and re-record the trace in the flight recorder."""
    from mmlspark_tpu.observability import tracing as tr
    cluster = ServingCluster(2, reply_timeout=15.0)
    try:
        wa, wb = cluster.workers
        out = [None]
        t = threading.Thread(target=_client,
                             args=(wa.server.address, {"x": 1}, out, 0))
        t.start()
        batch = []
        deadline = time.time() + 10
        while not batch and time.time() < deadline:
            batch = wa.get_batch(4, timeout=0.2)
        assert batch
        owner_id, cached = batch[0]
        root = wa.server.trace_span(cached.request_id)
        assert root is not None and not root.ended
        assert wb.reply(owner_id, cached.request_id, _json_resp({"n": 1}))
        t.join(timeout=15)
        assert out[0][0] == 200
        assert root.ended
        dur = root.duration
        # duplicate reply: dropped (routing entry gone), span untouched
        assert not wb.reply(owner_id, cached.request_id, _json_resp({"n": 2}))
        assert root.duration == dur
        assert tr.get_flight_recorder().get(root.trace_id) is not None
    finally:
        cluster.close()


def test_request_counted_on_owning_worker_only():
    """One logical request crossing workers bills ONE increment of
    mmlspark_serving_requests_total: the /_reply (and /_forward) internal
    hops are skipped, so per-worker counters still sum to true traffic."""
    from mmlspark_tpu import observability as obs
    obs.reset_all()
    cluster = ServingCluster(2, reply_timeout=15.0)
    try:
        wa, wb = cluster.workers
        out = [None]
        t = threading.Thread(target=_client,
                             args=(wa.server.address, {"x": 1}, out, 0))
        t.start()
        batch = []
        deadline = time.time() + 10
        while not batch and time.time() < deadline:
            batch = wa.get_batch(4, timeout=0.2)
        assert batch
        owner_id, cached = batch[0]
        assert wb.reply(owner_id, cached.request_id, _json_resp({"ok": 1}))
        t.join(timeout=15)
        assert out[0][0] == 200
        snap = obs.snapshot()
        series = snap["mmlspark_serving_requests_total"]["series"]
        total = sum(s["value"] for s in series)
        assert total == 1, series          # the /_reply hop is not billed
    finally:
        cluster.close()
        obs.reset_all()


# ---------------------------------------------------------------------------
# reliability layer (PR 5): chaos, failover, deadlines, heartbeat visibility


def _reliability_sandbox():
    """Fresh metrics/breakers/faults for tests that assert on them."""
    from mmlspark_tpu import observability as obs
    from mmlspark_tpu.reliability import get_injector, reset_breakers
    obs.reset_all()
    reset_breakers()
    get_injector().clear()


def test_cluster_reply_skips_closed_workers():
    """Satellite fix: an unknown owner must be routed through the first
    OPEN worker — the old hardcoded workers[0] fallback dead-ends when
    that worker happens to be the closed one."""
    _reliability_sandbox()
    cluster = ServingCluster(3, reply_timeout=15.0)
    try:
        w0, w1, w2 = cluster.workers
        out = [None]
        t = threading.Thread(target=_client,
                             args=(w2.server.address, {"q": 1}, out, 0))
        t.start()
        batch = []
        deadline = time.time() + 10
        while not batch and time.time() < deadline:
            batch = w2.get_batch(4, timeout=0.2)
        assert batch
        owner, cached = batch[0]
        # registry drift: the cluster record of worker-2 is gone, and the
        # old fallback (workers[0]) is closed
        cluster.workers.remove(w2)
        w0.close(deregister=False)
        ok = cluster.reply(owner, cached.request_id,
                           _json_resp({"via": "fallback"}))
        assert ok, "reply must route via the first open worker (w1)"
        t.join(timeout=15)
        status, payload = out[0]
        assert status == 200 and payload == {"via": "fallback"}
        w2.close()
    finally:
        cluster.close()


def test_cluster_reply_all_closed_returns_false():
    _reliability_sandbox()
    cluster = ServingCluster(2, reply_timeout=5.0)
    try:
        for w in cluster.workers:
            w.close(deregister=False)
        assert cluster.reply("ghost", "nope", _json_resp({})) is False
    finally:
        cluster.close()


def test_heartbeat_reregister_failure_is_visible():
    """Satellite fix: the heartbeat loop used to swallow re-register
    failures with a bare except/pass — now it retries under RetryPolicy
    and, once exhausted, bumps mmlspark_heartbeat_failures_total."""
    from mmlspark_tpu import observability as obs

    def _failures():
        snap = obs.snapshot().get("mmlspark_heartbeat_failures_total", {})
        return sum(s["value"] for s in snap.get("series", []))

    _reliability_sandbox()
    reg = DriverRegistry()
    w = DistributedWorker(reg.url, "w0", heartbeat_interval=0.05)
    try:
        before = _failures()
        reg.close()  # driver gone: heartbeat fails → re-register fails
        deadline = time.time() + 15
        while _failures() <= before and time.time() < deadline:
            time.sleep(0.05)
        assert _failures() > before, "exhausted re-register never surfaced"
    finally:
        w.close(deregister=False)


def test_forward_fails_over_and_opens_circuit():
    """A forwarding worker must fail over past a dead peer (no 502 while
    another peer can answer) and, after enough failures, skip it via an
    OPEN circuit without re-dialing."""
    from mmlspark_tpu.reliability import breaker_for
    _reliability_sandbox()
    cluster = ServingCluster(3, reply_timeout=15.0)
    try:
        wa, wb, wc = cluster.workers
        wa.enable_forwarding()
        dead_addr = wb.advertised_address
        wb.close(deregister=False)  # crash: still in wa's peer table

        def engine():
            deadline = time.time() + 20
            answered = 0
            while answered < 6 and time.time() < deadline:
                for owner, cached in wc.get_batch(8, timeout=0.1):
                    wc.reply(owner, cached.request_id,
                             _json_resp({"served": "worker-2"}))
                    answered += 1

        eng = threading.Thread(target=engine, daemon=True)
        eng.start()
        outs = [None] * 6
        for i in range(6):
            wa._rr = 0  # always try the dead peer (worker-1) first
            _client(wa.server.address, {"i": i}, outs, i)
        eng.join(timeout=20)
        for o in outs:
            assert isinstance(o, tuple), f"client failed: {o!r}"
            status, payload = o
            assert status == 200 and payload == {"served": "worker-2"}
        # five consecutive dial failures opened worker-1's circuit
        assert breaker_for(dead_addr).state == "open"
    finally:
        cluster.close()


def test_forwarded_request_honors_propagated_deadline():
    """X-Mmlspark-Deadline must cap the wait on the peer that parks the
    forwarded request — nobody waits out the 15s reply_timeout."""
    _reliability_sandbox()
    cluster = ServingCluster(2, reply_timeout=15.0)
    try:
        wa, wb = cluster.workers
        wa.enable_forwarding()   # no engine draining: parked until budget
        req = urllib.request.Request(
            wa.server.address, data=json.dumps({"q": 1}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Mmlspark-Deadline": "0.5"})
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(req, timeout=12.0) as r:
                status = r.status
        except urllib.error.HTTPError as e:
            status = e.code
        elapsed = time.monotonic() - t0
        assert status == 504
        assert elapsed < 8.0, f"deadline not propagated ({elapsed:.1f}s)"
    finally:
        cluster.close()


def test_chaos_faults_and_worker_restart_complete_every_request():
    """Acceptance chaos drill: 200 requests over a 3-worker cluster while
    the injector drops 30% of peer_http hops and worker-1 is killed and
    re-registered mid-run. Every request must RESOLVE (200 normally, 504
    for requests orphaned by the kill, 429 if shed) — zero client hangs —
    and /metrics must show nonzero retry and breaker-transition counters."""
    import re as _re
    from mmlspark_tpu import observability as obs
    from mmlspark_tpu.reliability import (RetryPolicy, breaker_for,
                                          get_injector)
    from mmlspark_tpu.serving.distributed import _http_json

    _reliability_sandbox()
    cluster = ServingCluster(3, reply_timeout=6.0)
    stop = threading.Event()
    injector = get_injector()
    try:
        # engine: drain everywhere, reply THROUGH a non-owner worker so
        # every answer crosses a faultable peer_http hop; a hop the faults
        # ate falls back to the cluster aggregate (open-worker routing)
        def engine():
            while not stop.is_set():
                for owner, cached in cluster.get_batch(64, timeout=0.05):
                    body = json.loads(cached.request.entity.content
                                      if cached.request.entity else b"{}")
                    resp = _json_resp({"n": body.get("n")})
                    sender = next(
                        (w for w in cluster.workers
                         if w.worker_id != owner and not w.server.closed),
                        None)
                    ok = (sender.reply(owner, cached.request_id, resp)
                          if sender is not None else False)
                    if not ok:
                        cluster.reply(owner, cached.request_id, resp)

        eng = threading.Thread(target=engine, daemon=True)
        eng.start()
        injector.add("peer_http", "error", p=0.3, seed=42)

        n_clients, per_client = 8, 25
        results = [[None] * per_client for _ in range(n_clients)]
        done = threading.Semaphore(0)

        def client(tid):
            for i in range(per_client):
                target = cluster.workers[(tid + i) % len(cluster.workers)]
                url = target.server.address
                status = None
                for _ in range(5):   # ride out the restart window
                    try:
                        status, _ = _post(url, {"n": tid * 100 + i},
                                          timeout=20.0)
                        break
                    except urllib.error.HTTPError as e:
                        status = e.code
                        break
                    except Exception:
                        time.sleep(0.2)
                        url = cluster.workers[
                            (tid + i) % len(cluster.workers)].server.address
                results[tid][i] = status
                done.release()

        threads = [threading.Thread(target=client, args=(tid,), daemon=True)
                   for tid in range(n_clients)]
        for t in threads:
            t.start()
        # kill worker-1 ungracefully mid-run and bring it back same-id
        for _ in range(60):
            done.acquire()
        old_addr = cluster.worker("worker-1").advertised_address
        cluster.restart_worker("worker-1")
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads), "client hang"

        statuses = [s for row in results for s in row]
        assert len(statuses) == n_clients * per_client
        assert all(s in (200, 429, 504) for s in statuses), (
            sorted({s for s in statuses if s not in (200, 429, 504)}))
        assert statuses.count(200) >= 150, statuses.count(200)

        # deterministically exercise the breaker against the dead
        # incarnation's address (stale-route shape): refused dials push the
        # sliding window past the failure ratio — it may already hold
        # successes from replies routed there before the kill
        injector.clear()
        brk = breaker_for(old_addr)
        one_shot = RetryPolicy(max_attempts=1)
        for _ in range(25):
            if brk.state == "open":
                break
            try:
                _http_json(old_addr + "/_reply",
                           {"request_id": "stale", "response": {}},
                           timeout=1.0, retry=one_shot, breaker=brk)
            except Exception:
                pass
        assert brk.state == "open"

        snap = obs.snapshot()

        def total(name):
            return sum(s["value"]
                       for s in snap.get(name, {}).get("series", []))

        assert total("mmlspark_retry_attempts_total") > 0
        assert total("mmlspark_faults_injected_total") > 0
        assert total("mmlspark_breaker_transitions_total") > 0
        # and the same series are visible on the wire at /metrics
        live = next(w for w in cluster.workers if not w.server.closed)
        with urllib.request.urlopen(live.server.address + "metrics",
                                    timeout=5) as r:
            text = r.read().decode()
        for name in ("mmlspark_retry_attempts_total",
                     "mmlspark_breaker_transitions_total"):
            values = [float(m.group(1)) for m in _re.finditer(
                _re.escape(name) + r"\{[^}]*\} ([0-9.e+-]+)", text)]
            assert sum(values) > 0, f"{name} not on /metrics"
    finally:
        injector.clear()
        stop.set()
        cluster.close()
