"""Distributed serving: registry, cross-worker reply routing, forwarding,
kill-and-replay — the round-1 missing piece (parity:
``HTTPSourceV2.scala:476-697``, ``DriverServiceUtils:134-195``)."""

import json
import threading
import time
import urllib.request

from mmlspark_tpu.serving.distributed import (DistributedWorker,
                                              DriverRegistry, ServingCluster)


def _post(url, payload, timeout=20.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode() or "{}")


def _client(url, payload, out, idx):
    try:
        out[idx] = _post(url, payload)
    except Exception as e:  # pragma: no cover - surfaced via assert
        out[idx] = e


def test_registry_register_recover_liveness():
    reg = DriverRegistry(liveness_timeout=0.5)
    try:
        info = reg.register("w0", "http://127.0.0.1:1")
        assert not info["recovered"]
        info2 = reg.register("w0", "http://127.0.0.1:2")  # restart, same id
        assert info2["recovered"]
        assert reg.routing_table()["w0"] == "http://127.0.0.1:2"
        assert info2["generation"] > info["generation"]
        time.sleep(0.6)  # no heartbeat → drops from the routing table
        assert "w0" not in reg.routing_table()
        assert not reg.heartbeat("nobody")
    finally:
        reg.close()


def test_cross_worker_reply_routing():
    """Request parked on worker A; the reply is issued *through worker B*
    (the engine ran on B's host) and must route back over HTTP to A."""
    cluster = ServingCluster(2, reply_timeout=15.0)
    try:
        wa, wb = cluster.workers
        out = [None]
        t = threading.Thread(target=_client,
                             args=(wa.server.address, {"x": 1}, out, 0))
        t.start()
        batch = []
        deadline = time.time() + 10
        while not batch and time.time() < deadline:
            batch = wa.get_batch(4, timeout=0.2)
        assert batch, "request never reached worker A's queue"
        owner_id, cached = batch[0]
        assert owner_id == wa.worker_id
        from mmlspark_tpu.io.http.schema import (EntityData,
                                                 HTTPResponseData,
                                                 StatusLineData)
        resp = HTTPResponseData(
            entity=EntityData.from_string(json.dumps({"answered_by": "B"})),
            status_line=StatusLineData(status_code=200))
        ok = wb.reply(owner_id, cached.request_id, resp)  # remote route
        assert ok
        t.join(timeout=15)
        status, payload = out[0]
        assert status == 200 and payload == {"answered_by": "B"}
    finally:
        cluster.close()


def test_forwarding_round_robin():
    """Worker A has no engine: public requests forward to peers and the
    client still gets the answer through A (load-balancer parity)."""
    cluster = ServingCluster(3, reply_timeout=15.0)
    try:
        wa = cluster.workers[0]
        wa.enable_forwarding()
        for w in cluster.workers:
            w.refresh_peers()

        stop = threading.Event()
        seen_urls = []

        def engine():
            while not stop.is_set():
                for owner, cached in cluster.get_batch(8, timeout=0.05):
                    seen_urls.append((cached.request.url,
                                      cached.request.method))
                    cluster.reply(owner, cached.request_id, _json_resp(
                        {"served": owner}))

        eng = threading.Thread(target=engine, daemon=True)
        eng.start()
        outs = [None, None, None, None]
        threads = [threading.Thread(target=_client,
                                    args=(wa.server.address.rstrip("/")
                                          + f"/score?i={i}", {"i": i},
                                          outs, i))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        stop.set()
        eng.join(timeout=5)
        served = set()
        for o in outs:
            assert isinstance(o, tuple), f"client failed: {o!r}"
            status, payload = o
            assert status == 200
            served.add(payload["served"])
        # A forwards round-robin → both B and C served something
        assert served == {"worker-1", "worker-2"}
        # the client's original path/query and method survive the hop
        assert all(u.startswith("/score?i=") and m == "POST"
                   for u, m in seen_urls), seen_urls
    finally:
        cluster.close()


def _json_resp(payload, status=200):
    from mmlspark_tpu.io.http.schema import (EntityData, HTTPResponseData,
                                             StatusLineData)
    return HTTPResponseData(
        entity=EntityData.from_string(json.dumps(payload)),
        status_line=StatusLineData(status_code=status))


def test_kill_and_replay():
    """An engine takes a batch and dies without replying; after the worker
    re-registers and replays, a second engine answers the SAME parked client
    connection (parity: registerPartition rehydration :489-506)."""
    reg = DriverRegistry()
    try:
        w = DistributedWorker(reg.url, "w0", reply_timeout=20.0)
        out = [None]
        t = threading.Thread(target=_client,
                             args=(w.server.address, {"q": 42}, out, 0))
        t.start()
        batch = []
        deadline = time.time() + 10
        while not batch and time.time() < deadline:
            batch = w.get_batch(4, timeout=0.2)
        assert batch
        # engine 1 crashes here — no reply. Simulate task retry:
        w2_info_recovered = DistributedWorker(reg.url, "w0",
                                              reply_timeout=20.0)
        assert w2_info_recovered.recovered  # driver saw the same worker id
        w2_info_recovered.close(deregister=False)
        n = w.server.replay_unanswered()
        assert n == 1
        batch2 = w.get_batch(4, timeout=1.0)
        assert len(batch2) == 1
        owner, cached = batch2[0]
        assert cached.request_id == batch[0][1].request_id
        assert w.reply(owner, cached.request_id, _json_resp({"ok": True}))
        t.join(timeout=20)
        status, payload = out[0]
        assert status == 200 and payload == {"ok": True}
        w.close()
    finally:
        reg.close()


def test_remote_reply_closes_root_span_exactly_once():
    """The forwarded reply path (engine on B, connection parked on A) must
    end A's root span at the first reply and leave it untouched on a
    late duplicate — a double-close would corrupt the recorded duration
    and re-record the trace in the flight recorder."""
    from mmlspark_tpu.observability import tracing as tr
    cluster = ServingCluster(2, reply_timeout=15.0)
    try:
        wa, wb = cluster.workers
        out = [None]
        t = threading.Thread(target=_client,
                             args=(wa.server.address, {"x": 1}, out, 0))
        t.start()
        batch = []
        deadline = time.time() + 10
        while not batch and time.time() < deadline:
            batch = wa.get_batch(4, timeout=0.2)
        assert batch
        owner_id, cached = batch[0]
        root = wa.server.trace_span(cached.request_id)
        assert root is not None and not root.ended
        assert wb.reply(owner_id, cached.request_id, _json_resp({"n": 1}))
        t.join(timeout=15)
        assert out[0][0] == 200
        assert root.ended
        dur = root.duration
        # duplicate reply: dropped (routing entry gone), span untouched
        assert not wb.reply(owner_id, cached.request_id, _json_resp({"n": 2}))
        assert root.duration == dur
        assert tr.get_flight_recorder().get(root.trace_id) is not None
    finally:
        cluster.close()


def test_request_counted_on_owning_worker_only():
    """One logical request crossing workers bills ONE increment of
    mmlspark_serving_requests_total: the /_reply (and /_forward) internal
    hops are skipped, so per-worker counters still sum to true traffic."""
    from mmlspark_tpu import observability as obs
    obs.reset_all()
    cluster = ServingCluster(2, reply_timeout=15.0)
    try:
        wa, wb = cluster.workers
        out = [None]
        t = threading.Thread(target=_client,
                             args=(wa.server.address, {"x": 1}, out, 0))
        t.start()
        batch = []
        deadline = time.time() + 10
        while not batch and time.time() < deadline:
            batch = wa.get_batch(4, timeout=0.2)
        assert batch
        owner_id, cached = batch[0]
        assert wb.reply(owner_id, cached.request_id, _json_resp({"ok": 1}))
        t.join(timeout=15)
        assert out[0][0] == 200
        snap = obs.snapshot()
        series = snap["mmlspark_serving_requests_total"]["series"]
        total = sum(s["value"] for s in series)
        assert total == 1, series          # the /_reply hop is not billed
    finally:
        cluster.close()
        obs.reset_all()
