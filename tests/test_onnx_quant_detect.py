"""Quantized (QLinear*) and detection (NMS / RoiAlign / GridSample) ONNX ops.

The reference runs int8-quantized and detection graphs through ORT
(`deep-learning/.../onnx/ONNXModel.scala:330`); these exercise the
TPU-native handlers against float dequant references, hand cases, and
torch.nn.functional.grid_sample (torch CPU ships in-image).
"""

import numpy as np
import pytest

from mmlspark_tpu.onnx.builder import (make_graph, make_model, make_node,
                                       make_tensor_value_info)
from mmlspark_tpu.onnx.convert import UnsupportedOp, convert_model


def _run(nodes, feeds, feed_infos, inits=None, out_names=("y",)):
    g = make_graph(
        nodes, "t", feed_infos,
        [make_tensor_value_info(o, np.float32, []) for o in out_names],
        initializers=inits or {})
    cm = convert_model(make_model(g))
    res = cm(cm.params, feeds)
    return [np.asarray(res[o]) for o in out_names]


def _quant(x, scale, zp, dtype):
    info = np.iinfo(dtype)
    return np.clip(np.round(x / scale) + zp, info.min, info.max).astype(dtype)


class TestQLinearOps:
    def test_qlinear_matmul_matches_dequant_reference(self, rng):
        a_f = rng.normal(0, 1, (4, 8)).astype(np.float32)
        b_f = rng.normal(0, 1, (8, 6)).astype(np.float32)
        a_s, b_s, y_s = 0.02, 0.015, 0.05
        a_q = _quant(a_f, a_s, 3, np.uint8)
        b_q = _quant(b_f, b_s, 0, np.int8)
        feeds = {"a": a_q}
        ins = [make_tensor_value_info("a", np.uint8, [4, 8])]
        inits = {"as_": np.float32(a_s), "azp": np.uint8(3),
                 "b": b_q, "bs": np.float32(b_s), "bzp": np.int8(0),
                 "ys": np.float32(y_s), "yzp": np.int8(0)}
        (got,) = _run([make_node("QLinearMatMul",
                                 ["a", "as_", "azp", "b", "bs", "bzp",
                                  "ys", "yzp"], ["y"])],
                      feeds, ins, inits)
        acc = (a_q.astype(np.int32) - 3) @ b_q.astype(np.int32)
        want = np.clip(np.round(acc * (a_s * b_s / y_s)), -128, 127)
        np.testing.assert_array_equal(got, want.astype(np.int8))

    def test_qlinear_conv_per_channel_scale_and_bias(self, rng):
        x_f = rng.normal(0, 1, (1, 3, 8, 8)).astype(np.float32)
        w_f = rng.normal(0, 0.3, (4, 3, 3, 3)).astype(np.float32)
        x_s, y_s = 0.03, 0.1
        w_s = np.asarray([0.01, 0.02, 0.015, 0.025], np.float32)
        x_q = _quant(x_f, x_s, 128, np.uint8)
        w_q = np.stack([_quant(w_f[i], w_s[i], 0, np.int8)
                        for i in range(4)])
        bias = rng.integers(-50, 50, (4,)).astype(np.int32)
        ins = [make_tensor_value_info("x", np.uint8, [1, 3, 8, 8])]
        inits = {"xs": np.float32(x_s), "xzp": np.uint8(128),
                 "w": w_q, "ws": w_s, "wzp": np.int8(0),
                 "ys": np.float32(y_s), "yzp": np.uint8(120), "b": bias}
        (got,) = _run([make_node("QLinearConv",
                                 ["x", "xs", "xzp", "w", "ws", "wzp",
                                  "ys", "yzp", "b"], ["y"],
                                 pads=[1, 1, 1, 1])],
                      {"x": x_q}, ins, inits)
        # float reference on the dequantized tensors, requantized at the end
        import torch
        import torch.nn.functional as F
        xd = (x_q.astype(np.float32) - 128) * x_s
        wd = w_q.astype(np.float32) * w_s[:, None, None, None]
        ref = F.conv2d(torch.from_numpy(xd), torch.from_numpy(wd),
                       bias=torch.from_numpy(bias.astype(np.float32) * x_s
                                             * w_s),
                       padding=1).numpy()
        want = np.clip(np.round(ref / y_s) + 120, 0, 255)
        # integer accumulation is exact; the only rounding is the final
        # requantize, so allow off-by-one on ties
        assert got.shape == want.shape == (1, 4, 8, 8)
        assert np.abs(got.astype(np.int32) - want.astype(np.int32)).max() <= 1

    def test_qlinear_conv_mixed_uint8_int8_zero_points(self, rng):
        """uint8 activations + int8 weights, both zero points 0 — ORT's
        standard post-ReLU static-quantization layout; must widen instead
        of feeding mixed dtypes to lax.conv."""
        x_q = rng.integers(0, 255, (1, 2, 5, 5)).astype(np.uint8)
        w_q = rng.integers(-127, 127, (3, 2, 3, 3)).astype(np.int8)
        ins = [make_tensor_value_info("x", np.uint8, [1, 2, 5, 5])]
        inits = {"xs": np.float32(0.02), "xzp": np.uint8(0),
                 "w": w_q, "ws": np.float32(0.01), "wzp": np.int8(0),
                 "ys": np.float32(0.7), "yzp": np.uint8(0)}
        (got,) = _run([make_node("QLinearConv",
                                 ["x", "xs", "xzp", "w", "ws", "wzp",
                                  "ys", "yzp"], ["y"])],
                      {"x": x_q}, ins, inits)
        import torch
        import torch.nn.functional as F
        ref = F.conv2d(torch.from_numpy(x_q.astype(np.float32) * 0.02),
                       torch.from_numpy(w_q.astype(np.float32) * 0.01)
                       ).numpy()
        want = np.clip(np.round(ref / 0.7), 0, 255)
        assert np.abs(got.astype(np.int32) - want.astype(np.int32)).max() <= 1

    def test_qgemm_float_output(self, rng):
        a_f = rng.normal(0, 1, (3, 5)).astype(np.float32)
        b_f = rng.normal(0, 1, (4, 5)).astype(np.float32)   # transB form
        a_s, b_s = 0.02, 0.03
        a_q = _quant(a_f, a_s, 0, np.int8)
        b_q = _quant(b_f, b_s, 0, np.int8)
        ins = [make_tensor_value_info("a", np.int8, [3, 5])]
        inits = {"as_": np.float32(a_s), "azp": np.int8(0),
                 "b": b_q, "bs": np.float32(b_s), "bzp": np.int8(0)}
        (got,) = _run([make_node("QGemm",
                                 ["a", "as_", "azp", "b", "bs", "bzp"],
                                 ["y"], domain="com.microsoft",
                                 alpha=2.0, transB=1)],
                      {"a": a_q}, ins, inits)
        want = 2.0 * a_s * b_s * (a_q.astype(np.int32)
                                  @ b_q.astype(np.int32).T)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_qlinear_add_skip_connection(self, rng):
        a_f = rng.normal(0, 1, (2, 8)).astype(np.float32)
        b_f = rng.normal(0, 1, (2, 8)).astype(np.float32)
        a_q = _quant(a_f, 0.05, 10, np.int8)
        b_q = _quant(b_f, 0.04, -5, np.int8)
        ins = [make_tensor_value_info("a", np.int8, [2, 8])]
        inits = {"as_": np.float32(0.05), "azp": np.int8(10),
                 "b": b_q, "bs": np.float32(0.04), "bzp": np.int8(-5),
                 "ys": np.float32(0.08), "yzp": np.int8(0)}
        (got,) = _run([make_node("QLinearAdd",
                                 ["a", "as_", "azp", "b", "bs", "bzp",
                                  "ys", "yzp"], ["y"],
                                 domain="com.microsoft")],
                      {"a": a_q}, ins, inits)
        ad = (a_q.astype(np.float32) - 10) * 0.05
        bd = (b_q.astype(np.float32) + 5) * 0.04
        want = np.clip(np.round((ad + bd) / 0.08), -128, 127).astype(np.int8)
        assert np.abs(got.astype(np.int32)
                      - want.astype(np.int32)).max() <= 1

    def test_qlinear_global_average_pool(self, rng):
        x_f = rng.normal(0, 1, (2, 3, 5, 5)).astype(np.float32)
        x_q = _quant(x_f, 0.1, 20, np.uint8)
        ins = [make_tensor_value_info("x", np.uint8, [2, 3, 5, 5])]
        inits = {"xs": np.float32(0.1), "xzp": np.uint8(20),
                 "ys": np.float32(0.12), "yzp": np.uint8(15)}
        (got,) = _run([make_node("QLinearGlobalAveragePool",
                                 ["x", "xs", "xzp", "ys", "yzp"], ["y"],
                                 domain="com.microsoft")],
                      {"x": x_q}, ins, inits)
        mean = (x_q.astype(np.float32) - 20).mean(axis=(2, 3),
                                                  keepdims=True) * 0.1
        want = np.clip(np.round(mean / 0.12) + 15, 0, 255).astype(np.uint8)
        assert got.shape == (2, 3, 1, 1)
        assert np.abs(got.astype(np.int32)
                      - want.astype(np.int32)).max() <= 1

    def test_quantized_mlp_end_to_end(self, rng):
        """Q/DQ boundary + two QLinear layers: the full pattern ORT's
        static quantizer emits, run through one graph."""
        x = rng.normal(0, 1, (4, 16)).astype(np.float32)
        w1 = _quant(rng.normal(0, 0.5, (16, 32)).astype(np.float32),
                    0.01, 0, np.int8)
        w2 = _quant(rng.normal(0, 0.5, (32, 8)).astype(np.float32),
                    0.01, 0, np.int8)
        ins = [make_tensor_value_info("x", np.float32, [4, 16])]
        inits = {"xs": np.float32(0.02), "xzp": np.int8(0),
                 "w1": w1, "w1s": np.float32(0.01), "w1zp": np.int8(0),
                 "h1s": np.float32(0.12), "h1zp": np.int8(0),
                 "w2": w2, "w2s": np.float32(0.01), "w2zp": np.int8(0),
                 "h2s": np.float32(0.12), "h2zp": np.int8(0)}
        nodes = [
            make_node("QuantizeLinear", ["x", "xs", "xzp"], ["xq"]),
            make_node("QLinearMatMul",
                      ["xq", "xs", "xzp", "w1", "w1s", "w1zp",
                       "h1s", "h1zp"], ["h1"]),
            make_node("QLinearMatMul",
                      ["h1", "h1s", "h1zp", "w2", "w2s", "w2zp",
                       "h2s", "h2zp"], ["h2"]),
            make_node("DequantizeLinear", ["h2", "h2s", "h2zp"], ["y"]),
        ]
        (got,) = _run(nodes, {"x": x}, ins, inits)
        # loose float check: two quantization stages, int8 resolution
        want = (x @ (w1.astype(np.float32) * 0.01)) \
            @ (w2.astype(np.float32) * 0.01)
        assert got.shape == (4, 8)
        assert np.abs(got - want).max() < 0.5


class TestNonMaxSuppression:
    def _nms(self, boxes, scores, max_out=10, iou=0.5, score_thr=None,
             **attrs):
        ins = [make_tensor_value_info("b", np.float32, list(boxes.shape)),
               make_tensor_value_info("s", np.float32, list(scores.shape))]
        names = ["b", "s", "m", "i"] + (["t"] if score_thr is not None else [])
        inits = {"m": np.int64(max_out), "i": np.float32(iou)}
        if score_thr is not None:
            inits["t"] = np.float32(score_thr)
        (got,) = _run([make_node("NonMaxSuppression", names, ["y"], **attrs)],
                      {"b": boxes, "s": scores}, ins, inits)
        return got

    def test_suppresses_overlaps_keeps_disjoint(self):
        boxes = np.asarray([[[0, 0, 10, 10], [1, 1, 11, 11],
                             [50, 50, 60, 60]]], np.float32)
        scores = np.asarray([[[0.9, 0.8, 0.7]]], np.float32)
        got = self._nms(boxes, scores, iou=0.5)
        # box 1 overlaps box 0 (IoU ~0.68) -> suppressed; box 2 disjoint
        np.testing.assert_array_equal(got, [[0, 0, 0], [0, 0, 2]])

    def test_score_threshold_and_max_out(self):
        boxes = np.asarray([[[0, 0, 1, 1], [10, 10, 11, 11],
                             [20, 20, 21, 21], [30, 30, 31, 31]]],
                           np.float32)
        scores = np.asarray([[[0.9, 0.8, 0.05, 0.7]]], np.float32)
        got = self._nms(boxes, scores, max_out=2, iou=0.5, score_thr=0.1)
        np.testing.assert_array_equal(got, [[0, 0, 0], [0, 0, 1]])

    def test_max_out_zero_means_empty(self):
        # spec: max_output_boxes_per_class "Default to 0, which means no
        # output" — NOT unlimited
        boxes = np.asarray([[[0, 0, 1, 1]]], np.float32)
        scores = np.asarray([[[0.9]]], np.float32)
        got = self._nms(boxes, scores, max_out=0)
        assert got.shape == (0, 3)

    def test_center_point_boxes_and_multiclass(self):
        boxes = np.asarray([[[5, 5, 10, 10], [5.5, 5.5, 10, 10],
                             [30, 30, 4, 4]]], np.float32)
        scores = np.asarray([[[0.9, 0.85, 0.1], [0.2, 0.95, 0.3]]],
                            np.float32)
        got = self._nms(boxes, scores, iou=0.4, center_point_box=1)
        # class 0: box 0 wins, box 1 suppressed (heavy overlap), box 2 kept
        # class 1: box 1 wins, box 0 suppressed, box 2 kept
        np.testing.assert_array_equal(
            got, [[0, 0, 0], [0, 0, 2], [0, 1, 1], [0, 1, 2]])


class TestRoiAlign:
    def test_unit_roi_identity(self):
        """A 2x2 ROI exactly covering a 2x2 output grid with one centered
        sample per bin reads back the pixel values."""
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        rois = np.asarray([[0.0, 0.0, 2.0, 2.0]], np.float32)
        ins = [make_tensor_value_info("x", np.float32, [1, 1, 4, 4]),
               make_tensor_value_info("r", np.float32, [1, 4]),
               make_tensor_value_info("bi", np.int64, [1])]
        (got,) = _run(
            [make_node("RoiAlign", ["x", "r", "bi"], ["y"],
                       output_height=2, output_width=2, sampling_ratio=1,
                       spatial_scale=1.0,
                       coordinate_transformation_mode="half_pixel")],
            {"x": x, "r": rois, "bi": np.asarray([0], np.int64)}, ins)
        # half_pixel: bin centers land at continuous (0.0, 0.0) ... (1, 1)
        # -> bilinear at exact pixel centers 0, 1
        np.testing.assert_allclose(
            got[0, 0], [[x[0, 0, 0, 0], x[0, 0, 0, 1]],
                        [x[0, 0, 1, 0], x[0, 0, 1, 1]]], atol=1e-5)

    def test_avg_matches_dense_numpy_reference(self, rng):
        x = rng.normal(0, 1, (2, 3, 16, 16)).astype(np.float32)
        rois = np.asarray([[1.0, 2.0, 9.0, 12.0],
                           [0.0, 0.0, 16.0, 16.0]], np.float32)
        bi = np.asarray([1, 0], np.int64)
        oh, ow, sr, scale = 4, 4, 2, 0.5
        ins = [make_tensor_value_info("x", np.float32, [2, 3, 16, 16]),
               make_tensor_value_info("r", np.float32, [2, 4]),
               make_tensor_value_info("bi", np.int64, [2])]
        (got,) = _run(
            [make_node("RoiAlign", ["x", "r", "bi"], ["y"],
                       output_height=oh, output_width=ow, sampling_ratio=sr,
                       spatial_scale=scale,
                       coordinate_transformation_mode="half_pixel")],
            {"x": x, "r": rois, "bi": bi}, ins)

        def bilinear(img, y, xq):
            H, W = img.shape[-2:]
            if y < -1 or y > H or xq < -1 or xq > W:
                return np.zeros(img.shape[0], img.dtype)
            y = min(max(y, 0), H - 1)
            xq = min(max(xq, 0), W - 1)
            y0, x0 = int(np.floor(y)), int(np.floor(xq))
            y1, x1 = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
            fy, fx = y - y0, xq - x0
            return ((1 - fy) * (1 - fx) * img[:, y0, x0]
                    + (1 - fy) * fx * img[:, y0, x1]
                    + fy * (1 - fx) * img[:, y1, x0]
                    + fy * fx * img[:, y1, x1])

        want = np.zeros_like(got)
        for r in range(2):
            x1c, y1c, x2c, y2c = rois[r] * scale - 0.5
            bh, bw = (y2c - y1c) / oh, (x2c - x1c) / ow
            for ph in range(oh):
                for pw in range(ow):
                    acc = np.zeros(3, np.float32)
                    for iy in range(sr):
                        for ix in range(sr):
                            yy = y1c + (ph + (iy + 0.5) / sr) * bh
                            xx = x1c + (pw + (ix + 0.5) / sr) * bw
                            acc += bilinear(x[bi[r]], yy, xx)
                    want[r, :, ph, pw] = acc / (sr * sr)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_adaptive_sampling_rejected(self):
        x = np.zeros((1, 1, 4, 4), np.float32)
        ins = [make_tensor_value_info("x", np.float32, [1, 1, 4, 4]),
               make_tensor_value_info("r", np.float32, [1, 4]),
               make_tensor_value_info("bi", np.int64, [1])]
        with pytest.raises(UnsupportedOp):
            _run([make_node("RoiAlign", ["x", "r", "bi"], ["y"],
                            output_height=2, output_width=2)],
                 {"x": x, "r": np.zeros((1, 4), np.float32),
                  "bi": np.zeros(1, np.int64)}, ins)


class TestGridSample:
    @pytest.mark.parametrize("mode,pad,align", [
        ("bilinear", "zeros", 0),
        ("bilinear", "border", 1),
        ("nearest", "zeros", 0),
        ("bilinear", "reflection", 0),
    ])
    def test_matches_torch(self, rng, mode, pad, align):
        import torch
        import torch.nn.functional as F
        x = rng.normal(0, 1, (2, 3, 7, 9)).astype(np.float32)
        grid = rng.uniform(-1.3, 1.3, (2, 5, 6, 2)).astype(np.float32)
        ins = [make_tensor_value_info("x", np.float32, [2, 3, 7, 9]),
               make_tensor_value_info("g", np.float32, [2, 5, 6, 2])]
        (got,) = _run(
            [make_node("GridSample", ["x", "g"], ["y"], mode=mode,
                       padding_mode=pad, align_corners=align)],
            {"x": x, "g": grid}, ins)
        want = F.grid_sample(torch.from_numpy(x), torch.from_numpy(grid),
                             mode=mode, padding_mode=pad,
                             align_corners=bool(align)).numpy()
        if mode == "nearest":
            # ties round differently at exact .5 boundaries; compare the
            # overwhelming majority and bound the tie disagreement
            close = np.isclose(got, want, atol=1e-5)
            assert close.mean() > 0.97, close.mean()
        else:
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
