"""Real-dataset model-quality regression benchmarks.

The reference pins per-dataset metric values for LightGBM in committed CSVs
(``lightgbm/src/test/resources/benchmarks/benchmarks_VerifyLightGBMClassifier.csv:1-12``,
checked by the ``Benchmarks`` trait ``Benchmarks.scala:15-85``). Synthetic
AUC≈1 regressions catch almost nothing, so these use the real datasets
bundled with scikit-learn (breast_cancer, wine, digits, diabetes) and also
record sklearn's HistGradientBoosting (a LightGBM-style learner) on the same
split as an external yardstick: our metric must stay within tolerance of the
pinned value AND within 5pts of the yardstick.
"""

import csv
import os

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.models.gbdt import LightGBMClassifier, LightGBMRegressor

CSV = os.path.join(os.path.dirname(__file__), "benchmarks",
                   "benchmarks_quality_real.csv")

sklearn = pytest.importorskip("sklearn")


def _rows():
    with open(CSV) as f:
        return list(csv.DictReader(f))


def _vec(X):
    o = np.empty(len(X), dtype=object)
    for i, r in enumerate(X):
        o[i] = r.astype(np.float64)
    return o


def _df(X, y):
    return DataFrame({"features": _vec(X), "label": y.astype(np.float64)})


def _split(name):
    from sklearn.datasets import (load_breast_cancer, load_diabetes,
                                  load_digits, load_wine)
    from sklearn.model_selection import train_test_split
    d = {"breast_cancer": load_breast_cancer, "wine": load_wine,
         "digits": load_digits, "diabetes": load_diabetes}[name]()
    strat = d.target if name != "diabetes" else None
    return train_test_split(d.data, d.target, test_size=0.3, random_state=7,
                            stratify=strat)


@pytest.mark.parametrize(
    "row", _rows(),
    ids=lambda r: f"{r['dataset']}-{r.get('boosting', 'gbdt')}")
def test_quality_real(row):
    from sklearn.metrics import accuracy_score, r2_score, roc_auc_score
    Xtr, Xte, ytr, yte = _split(row["dataset"])
    task, metric = row["task"], row["metric"]
    boosting = row.get("boosting", "gbdt") or "gbdt"
    extra = {"boosting_type": boosting}
    if boosting == "rf":
        # LightGBM's own rule: rf mode requires bagging
        extra.update(bagging_fraction=0.632, bagging_freq=1,
                     feature_fraction=0.7)
    if task == "regression":
        m = LightGBMRegressor(num_iterations=200, learning_rate=0.05,
                              num_leaves=31, **extra).fit(_df(Xtr, ytr))
        got = r2_score(yte, m.transform(_df(Xte, yte))["prediction"])
    else:
        m = LightGBMClassifier(num_iterations=150, learning_rate=0.1,
                               num_leaves=31, **extra).fit(_df(Xtr, ytr))
        out = m.transform(_df(Xte, yte))
        if metric == "auc":
            prob = np.stack(list(out["probability"]))
            got = roc_auc_score(yte, prob[:, 1] if prob.ndim > 1 else prob)
        else:
            got = accuracy_score(yte, out["prediction"])
    pinned, tol = float(row["value"]), float(row["tolerance"])
    yardstick = float(row["yardstick_sklearn_hgb"])
    assert got >= pinned - tol, \
        f"{row['dataset']} {metric} regressed: {got:.4f} < {pinned} - {tol}"
    assert got >= yardstick - 0.05, \
        f"{row['dataset']} {metric} {got:.4f} trails sklearn HGB {yardstick}"


def test_onnx_roundtrip_quality_breast_cancer():
    """Real-dataset end-to-end through the EXPORTED artifact: GBDT trained
    on breast_cancer, serialized to ONNX TreeEnsemble, served by ONNXModel
    — held-out AUC must match the native model to float tolerance, plus a
    coarse absolute floor as a gross-regression tripwire. (The exact
    CSV-pinned value covers the NATIVE path in test_quality_real; the
    equality assert here transfers that pin to the exported path.)"""
    from sklearn.metrics import roc_auc_score

    from mmlspark_tpu.models.onnx_model import ONNXModel

    Xtr, Xte, ytr, yte = _split("breast_cancer")
    m = LightGBMClassifier(num_iterations=100, learning_rate=0.1,
                           num_leaves=31).fit(_df(Xtr, ytr))
    native_p1 = m.booster.predict(Xte.astype(np.float32))

    stage = ONNXModel(m.to_onnx(),
                      feed_dict={"features": "features"},
                      fetch_dict={"proba": "probabilities"},
                      mini_batch_size=128, pin_devices=False)
    out = stage.transform(DataFrame({"features": _vec(Xte)}))
    onnx_p1 = np.stack(list(out["proba"]))[:, 1]

    np.testing.assert_allclose(onnx_p1, native_p1, rtol=1e-4, atol=1e-5)
    auc = roc_auc_score(yte, onnx_p1)
    assert auc > 0.98, auc       # native path pins 0.9971 ± tolerance
