"""Continuous-batching decoder serving (``serving/continuous.py``).

The invariant everything here pins: continuous batching changes THROUGHPUT,
never results — every request's greedy output must equal running
``generate_cached`` on its prompt alone, no matter how requests are
staggered, how slots are contended, or where prompts land in the pad
bucket. (The reference has no autoregressive serving; the stateless
analogue is replay determinism, ``HTTPSourceV2.scala:489-506``.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from mmlspark_tpu.models.zoo.transformer import (
    TransformerConfig, decode_step, decode_step_ragged,
    decode_window_ragged, generate_cached, init_kv_cache,
    init_transformer, prefill_cache)
from mmlspark_tpu.serving.continuous import ContinuousDecoder

CFG = TransformerConfig(vocab=128, layers=2, d_model=64, heads=4, d_ff=128,
                        max_len=64, causal=True, norm="rmsnorm",
                        position="rope", dtype=jnp.float32)
CFG_LEARNED = CFG._replace(position="learned", norm="layernorm")


@pytest.fixture(scope="module")
def params():
    return init_transformer(CFG, seed=0)


class TestDecodeStepRagged:
    @pytest.mark.parametrize("cfg_name", ["rope", "learned"])
    def test_uniform_pos_matches_decode_step(self, cfg_name, params):
        cfg = CFG if cfg_name == "rope" else CFG_LEARNED
        p = params if cfg_name == "rope" else init_transformer(cfg, seed=0)
        B, L, pos = 3, 16, 5
        cache = init_kv_cache(cfg, B, L)
        rng = np.random.default_rng(0)
        # warm the cache at positions 0..4 so the step attends over history
        for t in range(pos):
            tok = jnp.asarray(rng.integers(0, cfg.vocab, B))
            _, cache = decode_step(p, tok, t, cache, cfg)
        tok = jnp.asarray(rng.integers(0, cfg.vocab, B))
        want_logits, want_cache = decode_step(p, tok, pos, cache, cfg)
        got_logits, got_cache = decode_step_ragged(
            p, tok, jnp.full((B,), pos, jnp.int32), cache, cfg)
        np.testing.assert_allclose(np.asarray(got_logits),
                                   np.asarray(want_logits),
                                   rtol=1e-5, atol=1e-5)
        for gc, wc in zip(got_cache, want_cache):
            np.testing.assert_allclose(np.asarray(gc["k"]),
                                       np.asarray(wc["k"]),
                                       rtol=1e-5, atol=1e-5)

    def test_mixed_pos_matches_per_row_decode(self, params):
        """Rows at DIFFERENT depths in one ragged step == each row stepped
        alone at its own depth (the continuous-batching soundness core)."""
        B, L = 3, 32
        positions = [2, 7, 13]
        rng = np.random.default_rng(1)
        rows = []
        for pos in positions:
            cache1 = init_kv_cache(CFG, 1, L)
            hist = rng.integers(0, CFG.vocab, pos + 1)
            for t in range(pos):
                _, cache1 = decode_step(params, jnp.asarray(hist[t:t + 1]),
                                        t, cache1, CFG)
            rows.append((hist, cache1))
        # assemble the batch: per-row histories in one (B, …) cache
        cache = [{kk: jnp.concatenate([r[1][i][kk] for r in rows])
                  for kk in ("k", "v")} for i in range(CFG.layers)]
        toks = jnp.asarray([r[0][-1] for r in rows])
        got_logits, got_cache = decode_step_ragged(
            params, toks, jnp.asarray(positions, jnp.int32), cache, CFG)
        for b, pos in enumerate(positions):
            want_logits, want_cache = decode_step(
                params, toks[b:b + 1], pos, [
                    {kk: c[kk][b:b + 1] for kk in ("k", "v")}
                    for c in cache], CFG)
            np.testing.assert_allclose(np.asarray(got_logits[b]),
                                       np.asarray(want_logits[0]),
                                       rtol=1e-5, atol=1e-5)
            for gc, wc in zip(got_cache, want_cache):
                np.testing.assert_allclose(np.asarray(gc["k"][b]),
                                           np.asarray(wc["k"][0]),
                                           rtol=1e-5, atol=1e-5)

    def test_inactive_rows_keep_cache_and_position(self, params):
        B, L = 2, 16
        cache = init_kv_cache(CFG, B, L)
        rng = np.random.default_rng(2)
        for t in range(3):
            _, cache = decode_step(params, jnp.asarray(
                rng.integers(0, CFG.vocab, B)), t, cache, CFG)
        tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
        active = jnp.asarray([True, False])
        _, new_cache = decode_step_ragged(
            params, tok, jnp.asarray([3, 3], jnp.int32), cache, CFG,
            active)
        # row 1 untouched everywhere, row 0 updated at position 3
        for nc, c in zip(new_cache, cache):
            np.testing.assert_array_equal(np.asarray(nc["k"][1]),
                                          np.asarray(c["k"][1]))
            assert not np.array_equal(np.asarray(nc["k"][0, :, 3]),
                                      np.asarray(c["k"][0, :, 3]))


class TestPrefillCache:
    @pytest.mark.parametrize("cfg_name", ["rope", "learned"])
    def test_matches_token_by_token_prefill(self, cfg_name):
        cfg = CFG if cfg_name == "rope" else CFG_LEARNED
        p = init_transformer(cfg, seed=3)
        P, L = 6, 24
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, cfg.vocab, (1, P))
        logits, cache = prefill_cache(p, jnp.asarray(prompt),
                                      jnp.asarray([P]), cfg, L)
        want_cache = init_kv_cache(cfg, 1, L)
        for t in range(P):
            want_logits, want_cache = decode_step(
                p, jnp.asarray(prompt[:, t]), t, want_cache, cfg)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(want_logits),
                                   rtol=1e-4, atol=1e-4)
        for gc, wc in zip(cache, want_cache):
            np.testing.assert_allclose(np.asarray(gc["k"][:, :, :P]),
                                       np.asarray(wc["k"][:, :, :P]),
                                       rtol=1e-4, atol=1e-4)

    def test_right_padding_does_not_change_result(self):
        p = init_transformer(CFG, seed=4)
        P, pad_to, L = 5, 12, 24
        rng = np.random.default_rng(4)
        prompt = rng.integers(0, CFG.vocab, (1, P))
        padded = np.zeros((1, pad_to), np.int64)
        padded[0, :P] = prompt
        a, cache_a = prefill_cache(p, jnp.asarray(prompt),
                                   jnp.asarray([P]), CFG, L)
        b, cache_b = prefill_cache(p, jnp.asarray(padded),
                                   jnp.asarray([P]), CFG, L)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)
        # the REAL region of the cache is pad-invariant (positions >= P
        # hold pad garbage that the ragged step's key mask never exposes
        # before it is overwritten)
        np.testing.assert_allclose(np.asarray(cache_a[0]["k"][:, :, :P]),
                                   np.asarray(cache_b[0]["k"][:, :, :P]),
                                   rtol=1e-5, atol=1e-5)


class TestDecodeWindowRagged:
    """decode_window_ragged == decode_window per row at that row's scalar
    start == W sequential ragged steps — the speculative-verify soundness
    core for the slot pool."""

    @pytest.mark.parametrize("cfg_name", ["rope", "learned"])
    def test_matches_per_row_scalar_window(self, cfg_name, params):
        from mmlspark_tpu.models.zoo.transformer import decode_window
        cfg = CFG if cfg_name == "rope" else CFG_LEARNED
        p = params if cfg_name == "rope" else init_transformer(cfg, seed=0)
        B, W, L = 3, 4, 32
        starts = [5, 2, 9]
        rng = np.random.default_rng(11)
        # warm each row's cache to its own depth with its own history
        cache = init_kv_cache(cfg, B, L)
        for t in range(max(starts)):
            tok = jnp.asarray(rng.integers(0, cfg.vocab, B))
            stepped = jnp.asarray([t < s for s in starts])
            _, cache = decode_step_ragged(
                p, tok, jnp.full((B,), t, jnp.int32), cache, cfg, stepped)
        wtoks = jnp.asarray(rng.integers(0, cfg.vocab, (B, W)))
        got, got_cache = decode_window_ragged(
            p, wtoks, jnp.asarray(starts, jnp.int32), cache, cfg)
        for b in range(B):
            row_cache = [{kk: c[kk][b:b + 1] for kk in ("k", "v")}
                         for c in cache]
            want, want_cache = decode_window(
                p, wtoks[b:b + 1], starts[b], row_cache, cfg)
            np.testing.assert_allclose(np.asarray(got[b]),
                                       np.asarray(want[0]),
                                       rtol=2e-4, atol=2e-4)
            lo, hi = starts[b], starts[b] + W
            np.testing.assert_allclose(
                np.asarray(got_cache[0]["k"][b, :, lo:hi]),
                np.asarray(want_cache[0]["k"][0, :, lo:hi]),
                rtol=2e-4, atol=2e-4)

    def test_matches_sequential_ragged_steps(self, params):
        B, W, L = 2, 3, 32
        starts = jnp.asarray([4, 7], jnp.int32)
        rng = np.random.default_rng(12)
        cache = init_kv_cache(CFG, B, L)
        for t in range(7):
            tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
            stepped = starts > t
            _, cache = decode_step_ragged(
                params, tok, jnp.full((B,), t, jnp.int32), cache, CFG,
                stepped)
        wtoks = jnp.asarray(rng.integers(0, CFG.vocab, (B, W)))
        got, _ = decode_window_ragged(params, wtoks, starts, cache, CFG)
        ref_cache = cache
        for j in range(W):
            want_j, ref_cache = decode_step_ragged(
                params, wtoks[:, j], starts + j, ref_cache, CFG)
            np.testing.assert_allclose(np.asarray(got[:, j]),
                                       np.asarray(want_j),
                                       rtol=2e-4, atol=2e-4)

    def test_inactive_rows_keep_cache(self, params):
        B, W, L = 2, 3, 32
        starts = jnp.asarray([4, 6], jnp.int32)
        rng = np.random.default_rng(13)
        cache = init_kv_cache(CFG, B, L)
        for t in range(6):
            tok = jnp.asarray(rng.integers(0, CFG.vocab, B))
            _, cache = decode_step_ragged(
                params, tok, jnp.full((B,), t, jnp.int32), cache, CFG,
                starts > t)
        wtoks = jnp.asarray(rng.integers(0, CFG.vocab, (B, W)))
        active = jnp.asarray([True, False])
        _, got_cache = decode_window_ragged(params, wtoks, starts, cache,
                                            CFG, active)
        np.testing.assert_array_equal(np.asarray(got_cache[0]["k"][1]),
                                      np.asarray(cache[0]["k"][1]))
        assert not np.array_equal(np.asarray(got_cache[0]["k"][0]),
                                  np.asarray(cache[0]["k"][0]))


def _reference_tokens(params, prompt, max_new):
    ids = generate_cached(params, np.asarray(prompt)[None], CFG,
                          max_new_tokens=max_new)
    return list(np.asarray(ids)[0, len(prompt):])


class TestContinuousDecoder:
    def test_single_request_matches_generate_cached(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, CFG.vocab, 7)
        req = eng.submit(prompt, max_new_tokens=9)
        while not req.done:
            eng.step()
        assert eng.result(req) == _reference_tokens(params, prompt, 9)

    def test_staggered_requests_all_match(self, params):
        """Requests of different lengths admitted at different ticks, with
        slot contention (3 requests, 2 slots), all greedy-exact."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, CFG.vocab, n) for n in (3, 9, 5)]
        max_new = [6, 4, 8]
        reqs = [eng.submit(prompts[0], max_new[0])]
        eng.step()
        reqs.append(eng.submit(prompts[1], max_new[1]))
        eng.step()
        reqs.append(eng.submit(prompts[2], max_new[2]))
        for _ in range(80):
            if all(r.done for r in reqs):
                break
            eng.step()
        for prompt, mn, req in zip(prompts, max_new, reqs):
            assert req.done
            assert eng.result(req) == _reference_tokens(params, prompt, mn)

    def test_eos_stops_early_and_frees_slot(self, params):
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, CFG.vocab, 4)
        full = _reference_tokens(params, prompt, 10)
        eos = full[3]                      # force a stop after 4 tokens
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=48,
                                eos_id=eos)
        req = eng.submit(prompt, max_new_tokens=10)
        while not req.done:
            eng.step()
        got = eng.result(req)
        assert got == full[:4]
        assert eng._slot_req == [None]     # slot released

    def test_more_requests_than_slots_queue_and_finish(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, CFG.vocab, 2 + i) for i in range(5)]
        reqs = [eng.submit(p, 5) for p in prompts]
        for _ in range(200):
            if all(r.done for r in reqs):
                break
            eng.step()
        for p, r in zip(prompts, reqs):
            assert eng.result(r) == _reference_tokens(params, p, 5)

    def test_background_thread_and_timing_fields(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        t = eng.start()
        try:
            rng = np.random.default_rng(9)
            prompt = rng.integers(0, CFG.vocab, 6)
            req = eng.submit(prompt, 5)
            got = eng.result(req, timeout=60)
            assert got == _reference_tokens(params, prompt, 5)
            assert req.first_token_at is not None
            assert req.finished_at >= req.first_token_at
        finally:
            eng.stop()
            t.join(timeout=10)
            assert not t.is_alive()

    @pytest.mark.parametrize("sampling", [
        dict(temperature=0.8, seed=7),
        dict(temperature=1.2, top_k=5, seed=11),
        dict(temperature=0.9, top_p=0.7, seed=3),
        dict(temperature=1.0, top_k=12, top_p=0.85, seed=0),
    ])
    def test_sampled_requests_match_generate_cached(self, params, sampling):
        """Sampling rides the same parity invariant as greedy: per-request
        seed + the generate_cached key schedule (fold_in by absolute emit
        position) make slot-pool sampling request-for-request identical to
        the offline generator."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(12)
        prompt = rng.integers(0, CFG.vocab, 6)
        req = eng.submit(prompt, max_new_tokens=8, **sampling)
        for _ in range(20):
            if req.done:
                break
            eng.step()
        ids = generate_cached(params, np.asarray(prompt)[None], CFG,
                              max_new_tokens=8, **sampling)
        assert eng.result(req) == list(np.asarray(ids)[0, 6:])

    def test_mixed_greedy_and_sampled_slots(self, params):
        """Greedy and sampled requests share one pool; each stays exact."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(13)
        p_greedy = rng.integers(0, CFG.vocab, 5)
        p_sampled = rng.integers(0, CFG.vocab, 7)
        r1 = eng.submit(p_greedy, max_new_tokens=6)
        r2 = eng.submit(p_sampled, max_new_tokens=6, temperature=0.9,
                        top_k=8, seed=5)
        for _ in range(30):
            if r1.done and r2.done:
                break
            eng.step()
        assert eng.result(r1) == _reference_tokens(params, p_greedy, 6)
        ids = generate_cached(params, np.asarray(p_sampled)[None], CFG,
                              max_new_tokens=6, temperature=0.9, top_k=8,
                              seed=5)
        assert eng.result(r2) == list(np.asarray(ids)[0, 7:])

    def test_two_sampled_requests_independent_seeds(self, params):
        """Two sampled requests with different seeds in the same pool each
        match their own offline run (per-slot keys don't cross-talk)."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(14)
        prompts = [rng.integers(0, CFG.vocab, 4),
                   rng.integers(0, CFG.vocab, 9)]
        reqs = [eng.submit(prompts[0], 7, temperature=1.1, seed=21),
                eng.submit(prompts[1], 7, temperature=1.1, seed=22)]
        for _ in range(40):
            if all(r.done for r in reqs):
                break
            eng.step()
        for prompt, req, seed in zip(prompts, reqs, (21, 22)):
            ids = generate_cached(params, np.asarray(prompt)[None], CFG,
                                  max_new_tokens=7, temperature=1.1,
                                  seed=seed)
            assert eng.result(req) == list(
                np.asarray(ids)[0, len(prompt):])

    def test_tensor_parallel_mesh_matches_unsharded(self, params):
        """Continuous decoding over a tp mesh (Megatron params, KV heads
        sharded) is token-for-token the single-device engine — GSPMD
        propagation through the ragged step, greedy AND sampled."""
        mesh = Mesh(np.array(jax.devices()[:4]), ("tp",))
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                mesh=mesh)
        rng = np.random.default_rng(16)
        p1 = rng.integers(0, CFG.vocab, 5)
        p2 = rng.integers(0, CFG.vocab, 8)
        r1 = eng.submit(p1, 6)
        r2 = eng.submit(p2, 6, temperature=0.9, top_k=8, seed=5)
        for _ in range(30):
            if r1.done and r2.done:
                break
            eng.step()
        assert eng.result(r1) == _reference_tokens(params, p1, 6)
        ids = generate_cached(params, np.asarray(p2)[None], CFG,
                              max_new_tokens=6, temperature=0.9, top_k=8,
                              seed=5)
        assert eng.result(r2) == list(np.asarray(ids)[0, 8:])

    def test_dp_tp_mesh_with_sharded_slots(self, params):
        """dp×tp mesh: slots shard over dp (request data parallelism),
        heads over tp; cancel_all keeps the shardings."""
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("dp", "tp"))
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                mesh=mesh)
        rng = np.random.default_rng(17)
        prompt = rng.integers(0, CFG.vocab, 6)
        req = eng.submit(prompt, 5)
        for _ in range(10):
            if req.done:
                break
            eng.step()
        assert eng.result(req) == _reference_tokens(params, prompt, 5)
        eng.cancel_all()                       # must keep mesh shardings
        req2 = eng.submit(prompt, 5)
        for _ in range(10):
            if req2.done:
                break
            eng.step()
        assert eng.result(req2) == _reference_tokens(params, prompt, 5)

    def test_dp_only_mesh_replicates_params(self, params):
        """Code-review regression: a mesh without a tp axis (pure request
        data parallelism) must work, not die in NamedSharding."""
        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                mesh=mesh)
        rng = np.random.default_rng(18)
        prompt = rng.integers(0, CFG.vocab, 5)
        req = eng.submit(prompt, 5)
        for _ in range(10):
            if req.done:
                break
            eng.step()
        assert eng.result(req) == _reference_tokens(params, prompt, 5)

    def test_mesh_heads_divisibility_rejected(self, params):
        mesh = Mesh(np.array(jax.devices()[:8]), ("tp",))
        with pytest.raises(ValueError, match="divisible"):
            ContinuousDecoder(params, CFG, max_slots=1, max_len=16,
                              mesh=mesh)          # heads=4, tp=8

    def test_submit_validation(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=16)
        with pytest.raises(ValueError, match="empty"):
            eng.submit([])
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit(np.arange(10), max_new_tokens=10)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(np.arange(4), max_new_tokens=0)
        with pytest.raises(ValueError, match="token ids"):
            eng.submit([0, CFG.vocab], max_new_tokens=2)
        with pytest.raises(ValueError, match="token ids"):
            eng.submit([-1, 3], max_new_tokens=2)
        with pytest.raises(ValueError, match="top_p"):
            eng.submit([1, 2], max_new_tokens=2, top_p=0.0)

    def test_cancel_all_races_serve_forever_safely(self, params):
        """Code-review regression: cancel_all from another thread must not
        crash the driver thread mid-step, and the pool must be fully
        usable afterwards (all device state rebuilt)."""
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        t = eng.start()
        try:
            rng = np.random.default_rng(15)
            for _ in range(3):
                reqs = [eng.submit(rng.integers(0, CFG.vocab, 5), 40)
                        for _ in range(3)]
                import time as _t
                _t.sleep(0.02)            # let the driver get mid-stream
                eng.cancel_all()
                # every request resolved (cancelled mid-flight or finished
                # first — the race is the point); the driver survived
                for r in reqs:
                    assert r.done
                assert t.is_alive()
            # pool fully functional after repeated cancels
            prompt = rng.integers(0, CFG.vocab, 4)
            req = eng.submit(prompt, 5)
            assert eng.result(req, timeout=60) == _reference_tokens(
                params, prompt, 5)
        finally:
            eng.stop()
            t.join(timeout=10)

    def test_prompt_near_max_len_does_not_overflow_pad_bucket(self, params):
        """Code-review regression: a 40-token prompt in a 48-len cache must
        not inflate to a 64-wide prefill (bucket capped at max_len)."""
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=48)
        rng = np.random.default_rng(10)
        prompt = rng.integers(0, CFG.vocab, 40)
        req = eng.submit(prompt, max_new_tokens=8)
        for _ in range(20):
            if req.done:
                break
            eng.step()
        assert eng.result(req) == _reference_tokens(params, prompt, 8)

    def test_learned_positions_guard_max_len(self):
        """A cache longer than the learned position table would CLAMP
        gathers past the table and silently diverge — rejected up front."""
        p = init_transformer(CFG_LEARNED, seed=0)
        with pytest.raises(ValueError, match="position table"):
            ContinuousDecoder(p, CFG_LEARNED, max_slots=1,
                              max_len=CFG_LEARNED.max_len + 1)
        # at the limit it works
        eng = ContinuousDecoder(p, CFG_LEARNED, max_slots=1,
                                max_len=CFG_LEARNED.max_len)
        rng = np.random.default_rng(11)
        prompt = rng.integers(0, CFG_LEARNED.vocab, 5)
        req = eng.submit(prompt, max_new_tokens=4)
        for _ in range(10):
            if req.done:
                break
            eng.step()
        ids = generate_cached(p, np.asarray(prompt)[None], CFG_LEARNED,
                              max_new_tokens=4)
        assert eng.result(req) == list(np.asarray(ids)[0, 5:])


class TestPrefixCaching:
    def _run(self, eng, prompt, n=6, **kw):
        req = eng.submit(prompt, max_new_tokens=n, **kw)
        while not req.done:
            eng.step()
        return eng.result(req)

    def test_prefix_hit_matches_uncached(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(20)
        sys_prompt = rng.integers(0, CFG.vocab, 9)
        suffixes = [rng.integers(0, CFG.vocab, n) for n in (4, 7, 1)]
        plain = [self._run(eng, np.concatenate([sys_prompt, s]))
                 for s in suffixes]
        assert eng.stats["prefix_hits"] == 0
        cached = [self._run(eng, np.concatenate([sys_prompt, s]),
                            prefix_key="sys", prefix_len=len(sys_prompt))
                  for s in suffixes]
        assert cached == plain              # greedy outputs unchanged
        assert eng.stats["prefix_hits"] == len(suffixes) - 1

    def test_whole_prompt_hit(self, params):
        # a later request whose ENTIRE prompt is the stored prefix
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(21)
        prompt = rng.integers(0, CFG.vocab, 8)
        a = self._run(eng, prompt, prefix_key="p")
        b = self._run(eng, prompt, prefix_key="p")
        assert a == b == _reference_tokens(params, prompt, 6)
        assert eng.stats == {"prefills": 1, "prefix_hits": 1}

    def test_mismatched_prefix_fails_alone(self, params):
        # a bad request must not poison the engine: it fails with its own
        # error while concurrent requests keep decoding correctly
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(22)
        prompt = rng.integers(0, CFG.vocab, 8)
        self._run(eng, prompt, prefix_key="k")
        other = (prompt + 1) % CFG.vocab
        bad = eng.submit(other, max_new_tokens=4, prefix_key="k")
        good = eng.submit(prompt, max_new_tokens=4)
        while not (bad.done and good.done):
            eng.step()
        with pytest.raises(ValueError, match="stored"):
            eng.result(bad)
        assert eng.result(good) == _reference_tokens(params, prompt, 4)

    def test_shorter_declared_prefix_len_on_hit(self, params):
        # stored key covers the whole first prompt; a later caller reuses
        # only its declared (shorter) shared prefix
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(25)
        first = rng.integers(0, CFG.vocab, 12)
        a = self._run(eng, first, prefix_key="sys")   # stores plen=12
        second = np.concatenate([first[:6],
                                 rng.integers(0, CFG.vocab, 4)])
        b = self._run(eng, second, prefix_key="sys", prefix_len=6)
        assert b == _reference_tokens(params, second, 6)
        assert eng.stats["prefix_hits"] == 1

    def test_prefix_len_validation(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        prompt = np.arange(5) % CFG.vocab
        with pytest.raises(ValueError, match="prefix_len without"):
            eng.submit(prompt, max_new_tokens=2, prefix_len=3)
        with pytest.raises(ValueError, match="out of range"):
            eng.submit(prompt, max_new_tokens=2, prefix_key="x",
                       prefix_len=9)

    def test_store_eviction(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                prefix_cache_size=2)
        rng = np.random.default_rng(23)
        prompts = {f"k{i}": rng.integers(0, CFG.vocab, 6)
                   for i in range(3)}
        for key, p in prompts.items():
            self._run(eng, p, prefix_key=key, n=2)
        assert len(eng._prefix_store) == 2
        assert "k0" not in eng._prefix_store   # FIFO evicted

    def test_sampled_requests_with_prefix(self, params):
        # sampling composes with prefix reuse (same seed → same tokens)
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(24)
        sys_prompt = rng.integers(0, CFG.vocab, 6)
        prompt = np.concatenate([sys_prompt, rng.integers(0, CFG.vocab, 3)])
        a = self._run(eng, prompt, temperature=0.8, seed=11)
        b = self._run(eng, prompt, temperature=0.8, seed=11,
                      prefix_key="s", prefix_len=len(sys_prompt))
        c = self._run(eng, prompt, temperature=0.8, seed=11,
                      prefix_key="s", prefix_len=len(sys_prompt))
        assert a == b == c
        assert eng.stats["prefix_hits"] == 1

    def test_prefix_cache_disabled_by_cap_zero(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                prefix_cache_size=0)
        rng = np.random.default_rng(26)
        prompt = rng.integers(0, CFG.vocab, 7)
        a = self._run(eng, prompt, prefix_key="k")   # store disabled, no crash
        b = self._run(eng, prompt, prefix_key="k")
        assert a == b == _reference_tokens(params, prompt, 6)
        assert eng.stats == {"prefills": 2, "prefix_hits": 0}

    def test_unhashable_prefix_key_rejected_at_submit(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        with pytest.raises(ValueError, match="must be a string"):
            eng.submit(np.arange(4) % CFG.vocab, max_new_tokens=2,
                       prefix_key=["a"])


class TestGenerateEos:
    def test_eos_repeats_and_paths_agree(self, params):
        from mmlspark_tpu.models.zoo.transformer import generate
        rng = np.random.default_rng(60)
        prompt = jnp.asarray(rng.integers(0, CFG.vocab, (2, 5)))
        # pick the greedy first token of row 0 as the eos: it must fire
        base = np.asarray(generate_cached(params, prompt, CFG,
                                          max_new_tokens=8))
        eos = int(base[0, 5])
        a = np.asarray(generate(params, prompt, CFG, max_new_tokens=8,
                                eos_id=eos))
        b = np.asarray(generate_cached(params, prompt, CFG,
                                       max_new_tokens=8, eos_id=eos))
        np.testing.assert_array_equal(a, b)      # paths stay compatible
        assert (a[0, 5:] == eos).all()           # fired at first emit
        # rows that never hit eos match the unconstrained run
        if not (base[1, 5:] == eos).any():
            np.testing.assert_array_equal(a[1], base[1])

    def test_eos_none_unchanged(self, params):
        rng = np.random.default_rng(61)
        prompt = jnp.asarray(rng.integers(0, CFG.vocab, (1, 4)))
        a = np.asarray(generate_cached(params, prompt, CFG,
                                       max_new_tokens=6))
        b = np.asarray(generate_cached(params, prompt, CFG,
                                       max_new_tokens=6, eos_id=None))
        np.testing.assert_array_equal(a, b)


class TestBatchedAdmission:
    def test_same_bucket_prompts_prefill_once(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=4, max_len=48)
        rng = np.random.default_rng(70)
        prompts = [rng.integers(0, CFG.vocab, 6) for _ in range(3)]
        reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
        while not all(r.done for r in reqs):
            eng.step()
        assert eng.stats["prefills"] == 1          # one batched call
        for p, r in zip(prompts, reqs):
            assert eng.result(r) == _reference_tokens(params, p, 5)

    def test_mixed_buckets_and_sampling(self, params):
        eng = ContinuousDecoder(params, CFG, max_slots=4, max_len=48)
        rng = np.random.default_rng(71)
        short = rng.integers(0, CFG.vocab, 3)       # bucket 8
        long_ = rng.integers(0, CFG.vocab, 12)      # bucket 16
        r1 = eng.submit(short, max_new_tokens=4, temperature=0.7, seed=5)
        r2 = eng.submit(long_, max_new_tokens=4)
        while not (r1.done and r2.done):
            eng.step()
        assert eng.stats["prefills"] == 2           # one per bucket
        # sampled request matches the offline generator seed-for-seed
        want = generate_cached(params, np.asarray(short)[None], CFG,
                               max_new_tokens=4, temperature=0.7, seed=5)
        assert eng.result(r1) == list(np.asarray(want)[0, 3:])
        assert eng.result(r2) == _reference_tokens(params, long_, 4)

    def test_many_instant_requests_no_recursion_blowup(self, params):
        # hundreds of instantly-finishing requests must admit in constant
        # stack (regression: tail-recursive re-admission)
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48)
        rng = np.random.default_rng(72)
        reqs = [eng.submit(rng.integers(0, CFG.vocab, 4), max_new_tokens=1)
                for _ in range(300)]
        while not all(r.done for r in reqs):
            eng.step()
        assert all(len(r.tokens) == 1 for r in reqs)


class TestMultiStepDispatch:
    """``steps_per_dispatch=k``: k ragged decode steps fused into ONE
    device dispatch (lax.scan) — behind a network-attached chip every
    dispatch pays ~RTT, so the single-step engine is RTT-bound regardless
    of chip speed. Token streams must be identical to k single-step
    ticks: retirement (remaining counter + eos) happens inside the scan."""

    def _run(self, params, k, prompts, maxnews, eos=None, **subkw):
        eng = ContinuousDecoder(params, CFG, max_slots=3, max_len=48,
                                steps_per_dispatch=k, eos_id=eos)
        reqs = [eng.submit(p, max_new_tokens=m, **subkw)
                for p, m in zip(prompts, maxnews)]
        for _ in range(300):
            if all(r.done for r in reqs):
                break
            eng.step()
        return [eng.result(r, timeout=5) for r in reqs]

    def _workload(self, seed=0):
        rng = np.random.default_rng(seed)
        prompts = [rng.integers(0, CFG.vocab, int(rng.integers(3, 10)))
                   for _ in range(7)]
        return prompts, [5, 1, 9, 3, 12, 7, 2]

    def test_greedy_identical_across_k(self, params):
        prompts, maxnews = self._workload()
        a = self._run(params, 1, prompts, maxnews)
        assert self._run(params, 4, prompts, maxnews) == a
        assert self._run(params, 7, prompts, maxnews) == a
        # and each stream matches the offline generator
        for p, m, got in zip(prompts, maxnews, a):
            assert got == _reference_tokens(params, p, m)

    def test_eos_retires_mid_scan(self, params):
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, CFG.vocab, 4)
        full = _reference_tokens(params, prompt, 12)
        # an eos whose FIRST occurrence is mid-scan for k=4 (index != 3)
        stop = next(j for j in range(1, 12)
                    if full[j] not in full[:j] and j % 4 != 3)
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=48,
                                steps_per_dispatch=4, eos_id=full[stop])
        req = eng.submit(prompt, max_new_tokens=12)
        while not req.done:
            eng.step()
        assert eng.result(req) == full[:stop + 1]
        assert eng._slot_req == [None]

    def test_sampled_identical_across_k(self, params):
        prompts, maxnews = self._workload(seed=3)
        a = self._run(params, 1, prompts, maxnews,
                      temperature=0.8, top_k=10, seed=11)
        b = self._run(params, 4, prompts, maxnews,
                      temperature=0.8, top_k=10, seed=11)
        assert a == b

    def test_slot_turnover_with_queueing(self, params):
        # more requests than slots: freed slots re-admit at dispatch
        # granularity, results still exact
        rng = np.random.default_rng(9)
        prompts = [rng.integers(0, CFG.vocab, 3 + i % 5) for i in range(9)]
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                steps_per_dispatch=5)
        reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        for _ in range(300):
            if all(r.done for r in reqs):
                break
            eng.step()
        for p, r in zip(prompts, reqs):
            assert eng.result(r) == _reference_tokens(params, p, 6)

    def test_validation(self, params):
        import pytest
        with pytest.raises(ValueError, match="steps_per_dispatch"):
            ContinuousDecoder(params, CFG, max_slots=1, max_len=16,
                              steps_per_dispatch=0)


class TestPipelinedDispatch:
    """``pipeline_depth=d``: up to d token blocks stay in flight while the
    host drains the oldest — the fetch was the only sync on the decode
    path and serialized every tick at ~RTT. Outputs must be identical at
    every depth (device-side retirement makes the host's lagged view
    safe), and ``flush()`` must surface all emitted tokens."""

    def _run(self, params, depth, prompts, maxnews, k=3, eos=None):
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                steps_per_dispatch=k, eos_id=eos,
                                pipeline_depth=depth)
        reqs = [eng.submit(p, max_new_tokens=m)
                for p, m in zip(prompts, maxnews)]
        for _ in range(400):
            if all(r.done for r in reqs):
                break
            eng.step()
        return [eng.result(r, timeout=5) for r in reqs]

    def test_identical_across_depths(self, params):
        rng = np.random.default_rng(21)
        prompts = [rng.integers(0, CFG.vocab, int(rng.integers(3, 9)))
                   for _ in range(6)]
        maxnews = [6, 2, 9, 4, 11, 7]
        a = self._run(params, 0, prompts, maxnews)     # fully synchronous
        assert self._run(params, 2, prompts, maxnews) == a
        assert self._run(params, 4, prompts, maxnews) == a
        for p, m, got in zip(prompts, maxnews, a):
            assert got == _reference_tokens(params, p, m)

    def test_flush_drains_outstanding_blocks(self, params):
        rng = np.random.default_rng(22)
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=48,
                                steps_per_dispatch=2, pipeline_depth=3)
        req = eng.submit(rng.integers(0, CFG.vocab, 4), max_new_tokens=8)
        # step a few times WITHOUT letting the drain catch up fully
        for _ in range(3):
            eng.step()
        pending_before = len(eng._pending)
        eng.flush()
        assert not eng._pending
        # prefill emits 1 + 2 tokens per drained tick block
        assert len(req.tokens) >= min(8, 1 + 2 * pending_before)
        while not req.done:
            eng.step()
        assert eng.result(req) == _reference_tokens(
            params, np.asarray(req.prompt), 8)

    def test_negative_depth_rejected(self, params):
        import pytest
        with pytest.raises(ValueError, match="pipeline_depth"):
            ContinuousDecoder(params, CFG, max_slots=1, max_len=16,
                              pipeline_depth=-1)

    def test_saturated_pool_drains_eagerly(self, params):
        # with a backlog and a full pool, the engine drains outstanding
        # blocks to free slots NOW rather than pipeline_depth ticks later
        # (r5 sweep: depth was monotone harmful at k=8 because retiring
        # requests held slots k*depth extra steps). Deep pipelines must
        # not cost extra engine steps under saturation — and outputs stay
        # identical.
        rng = np.random.default_rng(23)
        prompts = [rng.integers(0, CFG.vocab, 4) for _ in range(3)]

        def steps_until_done(depth):
            eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=32,
                                    steps_per_dispatch=2,
                                    pipeline_depth=depth)
            reqs = [eng.submit(p, max_new_tokens=3) for p in prompts]
            n = 0
            for _ in range(200):
                if all(r.done for r in reqs):
                    break
                eng.step()
                n += 1
            assert all(r.done for r in reqs)
            return n, [eng.result(r) for r in reqs]

        n0, out0 = steps_until_done(0)
        n4, out4 = steps_until_done(4)
        assert out4 == out0
        # one depth-sized drain lag is paid once at the tail (the last
        # request has no backlog behind it to trigger the eager drain);
        # WITHOUT the eager drain every request would pay it:
        # ~len(prompts) * (depth + 1) steps ≈ 15 here
        assert n4 <= n0 + 4 + 1, (n4, n0)


class TestPrefillAhead:
    """``prefill_ahead=N``: waiting prompts prefill while every slot is
    occupied and park on device; a retiring wave re-fills with one insert
    dispatch and first tokens ride the drain pipeline. THE invariant:
    outputs are identical to the unstaged engine for every request."""

    def _run(self, params, ahead, prompts, maxnews, *, slots=2, k=3,
             depth=2, eos=None, sampling=None):
        eng = ContinuousDecoder(params, CFG, max_slots=slots, max_len=48,
                                steps_per_dispatch=k, pipeline_depth=depth,
                                eos_id=eos, prefill_ahead=ahead)
        reqs = []
        for i, (p, m) in enumerate(zip(prompts, maxnews)):
            kw = dict(sampling or {})
            if sampling:
                kw["seed"] = i
            reqs.append(eng.submit(p, max_new_tokens=m, **kw))
        for _ in range(600):
            if all(r.done for r in reqs):
                break
            eng.step()
        return [eng.result(r, timeout=5) for r in reqs], eng

    def test_greedy_identical_with_and_without_staging(self, params):
        rng = np.random.default_rng(31)
        prompts = [rng.integers(0, CFG.vocab, int(rng.integers(3, 10)))
                   for _ in range(7)]
        maxnews = [5, 9, 2, 7, 4, 11, 6]
        base, _ = self._run(params, 0, prompts, maxnews)
        staged, eng = self._run(params, 6, prompts, maxnews)
        assert staged == base
        assert eng.stats.get("staged_prefills", 0) > 0  # path exercised
        for p, m, got in zip(prompts, maxnews, base):
            assert got == _reference_tokens(params, p, m)

    def test_partial_unit_insertion_across_waves(self, params):
        """A staged unit larger than the freed-slot count inserts across
        several admissions (slots=2, 5 one-bucket prompts, budget 4)."""
        rng = np.random.default_rng(32)
        prompts = [rng.integers(0, CFG.vocab, 5) for _ in range(5)]
        maxnews = [3, 3, 4, 4, 5]
        staged, eng = self._run(params, 4, prompts, maxnews)
        assert not eng._staged                      # fully consumed
        for p, m, got in zip(prompts, maxnews, staged):
            assert got == _reference_tokens(params, p, m)

    def test_sampled_requests_identical_with_staging(self, params):
        rng = np.random.default_rng(33)
        prompts = [rng.integers(0, CFG.vocab, 6) for _ in range(5)]
        maxnews = [6, 5, 7, 4, 6]
        sampling = dict(temperature=0.9, top_k=8)
        base, _ = self._run(params, 0, prompts, maxnews, sampling=sampling)
        staged, _ = self._run(params, 5, prompts, maxnews,
                              sampling=sampling)
        assert staged == base

    def test_eos_retirement_with_staging(self, params):
        rng = np.random.default_rng(34)
        prompts = [rng.integers(0, CFG.vocab, 4) for _ in range(4)]
        full = [_reference_tokens(params, p, 10) for p in prompts]
        eos = full[0][2]
        base, _ = self._run(params, 0, prompts, [10] * 4, slots=1,
                            eos=eos)
        staged, _ = self._run(params, 4, prompts, [10] * 4, slots=1,
                              eos=eos)
        assert staged == base

    def test_cancel_all_fails_staged_requests(self, params):
        rng = np.random.default_rng(35)
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=48,
                                prefill_ahead=4)
        reqs = [eng.submit(rng.integers(0, CFG.vocab, 4), 8)
                for _ in range(4)]
        eng.step()                      # admit one, stage the rest
        assert eng._staged
        cancelled = eng.cancel_all()
        assert set(map(id, cancelled)) == set(map(id, reqs))
        assert all(r.done for r in reqs)
        assert not eng._staged

    def test_prefix_requests_not_staged_and_fifo_holds(self, params):
        """Staging stops at the first prefix-cache request so FIFO order
        (and the per-request suffix path) is preserved; everything still
        matches the reference."""
        rng = np.random.default_rng(36)
        pre = rng.integers(0, CFG.vocab, 6)
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=48,
                                prefill_ahead=4)
        plain = [rng.integers(0, CFG.vocab, 4) for _ in range(2)]
        r0 = eng.submit(plain[0], 4)
        rp = eng.submit(pre, 4, prefix_key="sys")
        r1 = eng.submit(plain[1], 4)
        for _ in range(200):
            if all(r.done for r in (r0, rp, r1)):
                break
            eng.step()
        assert eng.result(r0) == _reference_tokens(params, plain[0], 4)
        assert eng.result(rp) == _reference_tokens(params, pre, 4)
        assert eng.result(r1) == _reference_tokens(params, plain[1], 4)

    def test_negative_budget_rejected(self, params):
        import pytest
        with pytest.raises(ValueError, match="prefill_ahead"):
            ContinuousDecoder(params, CFG, max_slots=1, max_len=16,
                              prefill_ahead=-1)

    def test_mixed_bucket_fifo_order_preserved(self, params):
        """Staging stops at a pad-bucket change, so a later-bucket prompt
        can never be admitted before an earlier-submitted one (first-token
        timestamps must follow submission order with slots=1)."""
        rng = np.random.default_rng(37)
        lengths = [5, 20, 5, 20]          # alternating pad buckets
        prompts = [rng.integers(0, CFG.vocab, n) for n in lengths]
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=48,
                                prefill_ahead=8)
        reqs = [eng.submit(p, 4) for p in prompts]
        for _ in range(400):
            if all(r.done for r in reqs):
                break
            eng.step()
        stamps = [r.first_token_at for r in reqs]
        assert stamps == sorted(stamps)
        for p, r in zip(prompts, reqs):
            assert eng.result(r) == _reference_tokens(params, p, 4)

    def test_budget_charges_padded_rows(self, params):
        """A staged unit holds its power-of-two padded row buffer until it
        fully drains, so the budget charges padded rows: 5 same-bucket
        prompts under prefill_ahead=5 stage 4 (padded 4 <= 5; a fifth
        would repad to 8)."""
        rng = np.random.default_rng(38)
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=48,
                                prefill_ahead=5)
        reqs = [eng.submit(rng.integers(0, CFG.vocab, 5), 6)
                for _ in range(6)]
        eng.step()          # admit 1st; stage from the remaining 5
        assert sum(len(u[0]) for u in eng._staged) == 4
        for _ in range(400):
            if all(r.done for r in reqs):
                break
            eng.step()
        for r in reqs:
            assert eng.result(r) == _reference_tokens(
                params, np.asarray(r.prompt), 6)

    def test_failed_staged_prefill_restores_waiting(self, params):
        """A background prefill that raises must put its requests back at
        the head of _waiting (order intact) so cancel_all can reach them
        — not strand them outside every queue."""
        rng = np.random.default_rng(39)
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=48,
                                prefill_ahead=4)
        reqs = [eng.submit(rng.integers(0, CFG.vocab, 4), 6)
                for _ in range(3)]
        boom = RuntimeError("device fell over")
        real = eng._prefill
        calls = {"n": 0}

        def flaky(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 2:           # the background staging call
                raise boom
            return real(*a, **kw)

        eng._prefill = flaky
        import pytest
        with pytest.raises(RuntimeError, match="fell over"):
            eng.step()
        waiting_ids = [r.rid for r in eng._waiting]
        assert waiting_ids == [reqs[1].rid, reqs[2].rid]
        cancelled = eng.cancel_all()
        assert all(r.done for r in reqs)
        assert {r.rid for r in cancelled} == {r.rid for r in reqs}


class TestSpeculativePool:
    """Speculative decoding inside the slot pool: per-slot draft→verify
    rounds. THE invariant: greedy outputs are request-identical to the
    plain engine (accepted tokens are the target's own greedy choices) —
    for a perfect draft, a garbage draft, and anything between; the draft
    only changes throughput."""

    D_CFG = TransformerConfig(vocab=128, layers=1, d_model=32, heads=2,
                              d_ff=64, max_len=64, causal=True,
                              norm="rmsnorm", position="rope",
                              dtype=jnp.float32)

    def _run(self, params, draft, prompts, maxnews, *, slots=2, k=2,
             gamma=3, depth=2, ahead=0, eos=None, d_cfg=None):
        eng = ContinuousDecoder(params, CFG, max_slots=slots, max_len=48,
                                steps_per_dispatch=k, pipeline_depth=depth,
                                prefill_ahead=ahead, eos_id=eos,
                                draft_params=draft,
                                draft_cfg=d_cfg or self.D_CFG,
                                gamma=gamma)
        reqs = [eng.submit(p, max_new_tokens=m)
                for p, m in zip(prompts, maxnews)]
        for _ in range(600):
            if all(r.done for r in reqs):
                break
            eng.step()
        return [eng.result(r, timeout=5) for r in reqs], eng

    def test_perfect_draft_identical_and_accepts(self, params):
        """Draft == target: full acceptance, outputs still reference."""
        rng = np.random.default_rng(41)
        prompts = [rng.integers(0, CFG.vocab, int(rng.integers(3, 9)))
                   for _ in range(5)]
        maxnews = [7, 3, 9, 5, 8]
        got, eng = self._run(params, params, prompts, maxnews,
                             d_cfg=CFG)
        for p, m, g in zip(prompts, maxnews, got):
            assert g == _reference_tokens(params, p, m)
        # perfect draft: every round advances gamma+1 per live slot
        acc = (eng.stats["spec_emitted"]
               / max(eng.stats["spec_round_slots"], 1))
        assert acc > 1.5, eng.stats    # well beyond 1 token/round

    def test_weak_draft_identical(self, params):
        """A differently-initialized 1-layer draft: low acceptance, but
        outputs must not change by a single token."""
        rng = np.random.default_rng(42)
        draft = init_transformer(self.D_CFG, seed=99)
        prompts = [rng.integers(0, CFG.vocab, int(rng.integers(3, 10)))
                   for _ in range(6)]
        maxnews = [6, 2, 9, 4, 1, 7]
        got, _ = self._run(params, draft, prompts, maxnews)
        for p, m, g in zip(prompts, maxnews, got):
            assert g == _reference_tokens(params, p, m)

    def test_staggered_and_contended(self, params):
        rng = np.random.default_rng(43)
        draft = init_transformer(self.D_CFG, seed=7)
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                steps_per_dispatch=2, gamma=3,
                                draft_params=draft, draft_cfg=self.D_CFG)
        prompts = [rng.integers(0, CFG.vocab, n) for n in (3, 9, 5, 7)]
        maxnews = [6, 4, 8, 5]
        reqs = [eng.submit(prompts[0], maxnews[0])]
        eng.step()
        reqs += [eng.submit(p, m)
                 for p, m in zip(prompts[1:], maxnews[1:])]
        for _ in range(400):
            if all(r.done for r in reqs):
                break
            eng.step()
        for p, m, r in zip(prompts, maxnews, reqs):
            assert eng.result(r, timeout=5) == _reference_tokens(
                params, p, m)

    def test_eos_truncates_inside_accepted_prefix(self, params):
        rng = np.random.default_rng(44)
        prompts = [rng.integers(0, CFG.vocab, 4) for _ in range(3)]
        full = [_reference_tokens(params, p, 12) for p in prompts]
        eos = full[0][2]
        # perfect draft maximizes the chance the eos lands mid-window
        got, _ = self._run(params, params, prompts, [12] * 3, slots=2,
                           gamma=4, eos=eos, d_cfg=CFG)
        for p, g in zip(prompts, got):
            want = _reference_tokens(params, p, 12)
            stop = want.index(eos) + 1 if eos in want else 12
            assert g == want[:stop]

    def test_prefill_ahead_composes(self, params):
        rng = np.random.default_rng(45)
        draft = init_transformer(self.D_CFG, seed=3)
        prompts = [rng.integers(0, CFG.vocab, 5) for _ in range(6)]
        maxnews = [5, 7, 4, 6, 8, 3]
        base, _ = self._run(params, draft, prompts, maxnews)
        staged, eng = self._run(params, draft, prompts, maxnews, ahead=4)
        assert staged == base
        assert eng.stats.get("staged_prefills", 0) > 0

    def test_validation(self, params):
        import pytest
        draft = init_transformer(self.D_CFG, seed=1)
        with pytest.raises(ValueError, match="draft_cfg"):
            ContinuousDecoder(params, CFG, max_slots=1, max_len=16,
                              draft_params=draft)
        bad = self.D_CFG._replace(vocab=64)
        with pytest.raises(ValueError, match="vocab"):
            ContinuousDecoder(params, CFG, max_slots=1, max_len=16,
                              draft_params=init_transformer(bad, seed=1),
                              draft_cfg=bad)
        eng = ContinuousDecoder(params, CFG, max_slots=1, max_len=32,
                                draft_params=draft, draft_cfg=self.D_CFG)
        # sampled submits are allowed (per-slot rejection correction);
        # only the unsupported top-k/top-p warps are rejected
        eng.submit(np.asarray([1, 2, 3]), 4, temperature=0.5)

    def test_prefix_caching_composes(self, params):
        """Prefix-cache requests work in spec mode: the target reuses the
        stored prefix (prefix_hits increments), the draft re-prefills the
        whole prompt, outputs stay reference-exact."""
        rng = np.random.default_rng(46)
        draft = init_transformer(self.D_CFG, seed=5)
        eng = ContinuousDecoder(params, CFG, max_slots=2, max_len=48,
                                steps_per_dispatch=2, gamma=3,
                                draft_params=draft, draft_cfg=self.D_CFG)
        sys_prefix = rng.integers(0, CFG.vocab, 6)
        prompts = [np.concatenate([sys_prefix,
                                   rng.integers(0, CFG.vocab, 3)])
                   for _ in range(3)]
        reqs = [eng.submit(p, 5, prefix_key="sys", prefix_len=6)
                for p in prompts]
        for _ in range(300):
            if all(r.done for r in reqs):
                break
            eng.step()
        for p, r in zip(prompts, reqs):
            assert eng.result(r, timeout=5) == _reference_tokens(
                params, p, 5)
        assert eng.stats["prefix_hits"] == 2   # req 1 stores; 2 and 3 hit


class TestSpeculativePoolSampled:
    """Sampled requests in the speculative pool: per-slot rejection
    correction. Contract is DISTRIBUTIONAL (exactly target-distributed;
    bit-identity to the plain engine is impossible), so the test checks
    empirical marginals against enumerated target probabilities; greedy
    requests in the same pool stay bit-exact."""

    V_CFG = TransformerConfig(vocab=32, layers=2, d_model=32, heads=4,
                              d_ff=64, max_len=64, causal=True,
                              norm="rmsnorm", position="rope",
                              dtype=jnp.float32)
    D32 = V_CFG._replace(layers=1, d_model=16, heads=2, d_ff=32)
    TEMP = 1.3

    def test_sampled_marginals_match_target(self):
        from mmlspark_tpu.models.zoo.transformer import prefill_cache
        t_params = init_transformer(self.V_CFG, seed=1)
        d_params = init_transformer(self.D32, seed=7)
        prompt = np.asarray([3, 11, 4, 17], np.int32)
        N, V = 512, self.V_CFG.vocab
        eng = ContinuousDecoder(t_params, self.V_CFG, max_slots=16,
                                max_len=32, steps_per_dispatch=2,
                                draft_params=d_params, draft_cfg=self.D32,
                                gamma=2)
        reqs = [eng.submit(prompt, 2, temperature=self.TEMP, seed=i)
                for i in range(N)]
        for _ in range(4000):
            if all(r.done for r in reqs):
                break
            eng.step()
        toks = np.asarray([r.tokens for r in reqs])          # (N, 2)
        # exact marginals by enumeration (same recipe as the zoo test)
        lengths = jnp.asarray([4], jnp.int32)
        logits, cache = prefill_cache(t_params, jnp.asarray(prompt[None]),
                                      lengths, self.V_CFG, 8)
        p1 = np.asarray(jax.nn.softmax(
            logits.astype(jnp.float32) / self.TEMP, -1))[0]
        cacheV = [{k: jnp.repeat(c[k], V, axis=0) for k in ("k", "v")}
                  for c in cache]
        l2, _ = decode_step(t_params, jnp.arange(V, dtype=jnp.int32),
                            4, cacheV, self.V_CFG)
        p2_given = np.asarray(jax.nn.softmax(
            l2.astype(jnp.float32) / self.TEMP, -1))
        p2 = p1 @ p2_given
        emp1 = np.bincount(toks[:, 0], minlength=V) / N
        emp2 = np.bincount(toks[:, 1], minlength=V) / N
        assert np.abs(emp1 - p1).max() < 0.055, np.abs(emp1 - p1).max()
        assert np.abs(emp2 - p2).max() < 0.055, np.abs(emp2 - p2).max()

    def test_mixed_pool_keeps_greedy_bit_exact(self, params):
        draft = init_transformer(
            CFG._replace(layers=1, d_model=32, heads=2, d_ff=64), seed=5)
        eng = ContinuousDecoder(
            params, CFG, max_slots=2, max_len=48, steps_per_dispatch=2,
            draft_params=draft,
            draft_cfg=CFG._replace(layers=1, d_model=32, heads=2,
                                   d_ff=64), gamma=3)
        rng = np.random.default_rng(51)
        g_prompt = rng.integers(0, CFG.vocab, 5)
        s_prompt = rng.integers(0, CFG.vocab, 6)
        g = eng.submit(g_prompt, 7)                       # greedy
        s = eng.submit(s_prompt, 7, temperature=0.9, seed=4)  # sampled
        for _ in range(200):
            if g.done and s.done:
                break
            eng.step()
        assert eng.result(g, timeout=5) == _reference_tokens(
            params, g_prompt, 7)
        assert len(eng.result(s, timeout=5)) == 7
        assert all(0 <= t < CFG.vocab for t in s.tokens)

    def test_eos_with_sampled_spec(self, params):
        draft = init_transformer(
            CFG._replace(layers=1, d_model=32, heads=2, d_ff=64), seed=5)
        eng = ContinuousDecoder(
            params, CFG, max_slots=1, max_len=48, steps_per_dispatch=2,
            eos_id=7, draft_params=draft,
            draft_cfg=CFG._replace(layers=1, d_model=32, heads=2,
                                   d_ff=64), gamma=2)
        rng = np.random.default_rng(52)
        req = eng.submit(rng.integers(0, CFG.vocab, 4), 20,
                         temperature=1.5, seed=9)
        for _ in range(200):
            if req.done:
                break
            eng.step()
        got = eng.result(req, timeout=5)
        assert 1 <= len(got) <= 20
        assert 7 not in got[:-1]          # eos only ever terminal

    def test_topk_marginals_match_warped_target(self):
        """top-k sampling under speculation: the warp applies to BOTH
        distributions before the ratio test, so outputs are exactly
        top-k-warped-target distributed — checked against enumerated
        warped marginals for the second token (the first goes through
        the plain admission sampler)."""
        from mmlspark_tpu.models.zoo.transformer import prefill_cache
        t_params = init_transformer(self.V_CFG, seed=1)
        d_params = init_transformer(self.D32, seed=7)
        prompt = np.asarray([3, 11, 4, 17], np.int32)
        N, V, TOPK = 512, self.V_CFG.vocab, 3
        eng = ContinuousDecoder(t_params, self.V_CFG, max_slots=16,
                                max_len=32, steps_per_dispatch=2,
                                draft_params=d_params, draft_cfg=self.D32,
                                gamma=2)
        reqs = [eng.submit(prompt, 2, temperature=self.TEMP, top_k=TOPK,
                           seed=i) for i in range(N)]
        for _ in range(4000):
            if all(r.done for r in reqs):
                break
            eng.step()
        toks = np.asarray([r.tokens for r in reqs])

        def warp(logits_row):
            scaled = logits_row / self.TEMP
            kth = np.sort(scaled)[::-1][TOPK - 1]
            keep = scaled >= kth
            e = np.where(keep, np.exp(scaled - scaled.max()), 0.0)
            return e / e.sum()

        lengths = jnp.asarray([4], jnp.int32)
        logits, cache = prefill_cache(t_params, jnp.asarray(prompt[None]),
                                      lengths, self.V_CFG, 8)
        p1 = warp(np.asarray(logits, np.float64)[0])
        cacheV = [{k: jnp.repeat(c[k], V, axis=0) for k in ("k", "v")}
                  for c in cache]
        l2, _ = decode_step(t_params, jnp.arange(V, dtype=jnp.int32),
                            4, cacheV, self.V_CFG)
        p2_given = np.stack([warp(row)
                             for row in np.asarray(l2, np.float64)])
        p2 = p1 @ p2_given
        emp1 = np.bincount(toks[:, 0], minlength=V) / N
        emp2 = np.bincount(toks[:, 1], minlength=V) / N
        assert np.abs(emp1 - p1).max() < 0.06, np.abs(emp1 - p1).max()
        assert np.abs(emp2 - p2).max() < 0.06, np.abs(emp2 - p2).max()
        # the warp is real: nothing outside the reachable top-k sets
        assert set(np.unique(toks[:, 0])) <= set(np.nonzero(p1)[0])
        assert set(np.unique(toks[:, 1])) <= set(np.nonzero(p2)[0])

    def test_topp_marginals_match_warped_target(self):
        """Nucleus (top-p) sampling under speculation: exact
        warped-target marginals, HF convention (cutoff over the sorted
        renormalized mass, keep through the crossing token)."""
        from mmlspark_tpu.models.zoo.transformer import prefill_cache
        t_params = init_transformer(self.V_CFG, seed=1)
        d_params = init_transformer(self.D32, seed=7)
        prompt = np.asarray([3, 11, 4, 17], np.int32)
        N, V, TOPP = 512, self.V_CFG.vocab, 0.6
        eng = ContinuousDecoder(t_params, self.V_CFG, max_slots=16,
                                max_len=32, steps_per_dispatch=2,
                                draft_params=d_params, draft_cfg=self.D32,
                                gamma=2)
        reqs = [eng.submit(prompt, 2, temperature=self.TEMP, top_p=TOPP,
                           seed=i) for i in range(N)]
        for _ in range(4000):
            if all(r.done for r in reqs):
                break
            eng.step()
        toks = np.asarray([r.tokens for r in reqs])

        def warp(logits_row):
            scaled = np.asarray(logits_row, np.float64) / self.TEMP
            probs = np.exp(scaled - scaled.max())
            probs /= probs.sum()
            order = np.argsort(-scaled)
            cum = np.cumsum(probs[order])
            keep_n = int(np.sum(cum < TOPP)) + 1   # through the crossing
            kept = order[:keep_n]
            out = np.zeros_like(probs)
            out[kept] = probs[kept] / probs[kept].sum()
            return out

        lengths = jnp.asarray([4], jnp.int32)
        logits, cache = prefill_cache(t_params, jnp.asarray(prompt[None]),
                                      lengths, self.V_CFG, 8)
        p1 = warp(np.asarray(logits)[0])
        cacheV = [{k: jnp.repeat(c[k], V, axis=0) for k in ("k", "v")}
                  for c in cache]
        l2, _ = decode_step(t_params, jnp.arange(V, dtype=jnp.int32),
                            4, cacheV, self.V_CFG)
        p2_given = np.stack([warp(row) for row in np.asarray(l2)])
        p2 = p1 @ p2_given
        emp1 = np.bincount(toks[:, 0], minlength=V) / N
        emp2 = np.bincount(toks[:, 1], minlength=V) / N
        assert np.abs(emp1 - p1).max() < 0.06, np.abs(emp1 - p1).max()
        assert np.abs(emp2 - p2).max() < 0.06, np.abs(emp2 - p2).max()
        assert set(np.unique(toks[:, 0])) <= set(np.nonzero(p1)[0])
        assert set(np.unique(toks[:, 1])) <= set(np.nonzero(p2)[0])
