"""Converter vs foreign-exporter graph patterns.

Round-1 verdict: the ONNX importer was only ever tested on its own builder's
clean graphs. These tests exercise what real exporters emit (torch-style):
opset 11/13/17 attribute-vs-input variants, decomposed LayerNorm/GELU,
dynamic batch axes (dim_param), Shape-arithmetic reshapes, attention-mask
subgraphs, and external-data initializers — parity target:
``ONNXModel.scala:195-245`` type coverage against real ORT-consumable models.
"""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.models.onnx_model import ONNXModel
from mmlspark_tpu.models.zoo.bert_onnx import (BertOnnxConfig, bert_reference,
                                               export_bert_onnx,
                                               init_bert_params)
from mmlspark_tpu.onnx.builder import (make_graph, make_model, make_node,
                                       make_tensor_value_info)
from mmlspark_tpu.onnx.convert import convert_model

CFG = BertOnnxConfig(vocab=97, layers=2, d_model=48, heads=4, d_ff=96,
                     max_len=32)


def _bert_io(seed=0, B=3, S=17):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, CFG.vocab, (B, S))
    mask = np.ones((B, S), dtype=np.int64)
    mask[0, S - 4:] = 0  # ragged row
    mask[2, S - 1:] = 0
    return ids.astype(np.int64), mask


@pytest.mark.parametrize("opset", [11, 13, 17])
def test_bert_torch_style_matches_reference(opset):
    """The full attention pattern — Shape arithmetic, decomposed LN/GELU,
    mask bias — must match a numpy re-implementation at every opset."""
    params = init_bert_params(CFG, seed=1)
    mb = export_bert_onnx(CFG, opset=opset, params=params)
    cm = convert_model(mb)
    assert cm.input_names == ["input_ids", "attention_mask"]
    ids, mask = _bert_io()
    out = cm(cm.params, {"input_ids": ids, "attention_mask": mask})
    got = np.asarray(out["last_hidden_state"])
    want = bert_reference(params, ids, mask, CFG)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_bert_dynamic_batch_axes():
    """dim_param inputs: the same converted model must serve several batch
    and sequence sizes (bucketed jit, no fixed shapes baked in)."""
    params = init_bert_params(CFG, seed=2)
    cm = convert_model(export_bert_onnx(CFG, params=params))
    vi = {v.name: v for v in cm.inputs}
    assert vi["input_ids"].shape == ["batch", "seq"]
    for B, S in [(1, 5), (4, 12), (2, 32)]:
        ids, mask = np.ones((B, S), np.int64), np.ones((B, S), np.int64)
        out = np.asarray(cm(cm.params, {"input_ids": ids,
                                        "attention_mask": mask})["last_hidden_state"])
        assert out.shape == (B, S, CFG.d_model)
        np.testing.assert_allclose(out, bert_reference(params, ids, mask, CFG),
                                   rtol=2e-4, atol=2e-5)


def test_bert_external_data(tmp_path):
    """External-data initializers (torch save_as_external_data layout):
    offset-packed single sidecar file."""
    params = init_bert_params(CFG, seed=3)
    d = str(tmp_path)
    mb = export_bert_onnx(CFG, params=params, external_data_dir=d)
    assert (tmp_path / "weights.bin").stat().st_size > 0
    # without the dir the converter must fail loudly, not silently zero-fill
    with pytest.raises(ValueError, match="external"):
        convert_model(mb)
    cm = convert_model(mb, external_data_dir=d)
    ids, mask = _bert_io(seed=4)
    got = np.asarray(cm(cm.params, {"input_ids": ids,
                                    "attention_mask": mask})["last_hidden_state"])
    np.testing.assert_allclose(got, bert_reference(params, ids, mask, CFG),
                               rtol=2e-4, atol=2e-5)


def test_external_data_path_escape_rejected(tmp_path):
    from mmlspark_tpu.onnx.proto import TensorProto, tensor_to_numpy
    t = TensorProto(dims=[2], data_type=1, name="w",
                    data_location=TensorProto.EXTERNAL,
                    external_data={"location": "../../etc/passwd"})
    with pytest.raises(ValueError, match="escapes"):
        tensor_to_numpy(t, str(tmp_path))


def test_onnx_model_stage_external_data(tmp_path):
    """ONNXModel end-to-end with external weights through the DataFrame API."""
    params = init_bert_params(CFG, seed=5)
    d = str(tmp_path)
    mb = export_bert_onnx(CFG, params=params, external_data_dir=d)
    m = ONNXModel(mb, feed_dict={"input_ids": "ids", "attention_mask": "mask"},
                  fetch_dict={"emb": "last_hidden_state"},
                  mini_batch_size=4, external_data_dir=d, pin_devices=False)
    ids, mask = _bert_io(seed=6, B=6, S=9)
    def col(a):
        o = np.empty(len(a), dtype=object)
        for i, r in enumerate(a):
            o[i] = r
        return o
    out = m.transform(DataFrame({"ids": col(ids), "mask": col(mask)}))
    got = np.stack(list(out["emb"]))
    np.testing.assert_allclose(got, bert_reference(params, ids, mask, CFG),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("opset", [11, 13, 18])
def test_opset_attr_vs_input_variants(opset):
    """Squeeze/Unsqueeze/ReduceSum/Clip/Split across their opset boundary
    forms, in one graph, numerically checked."""
    from mmlspark_tpu.onnx.builder import make_tensor  # noqa: F401
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    nodes, inits = [], {}

    def c(name, arr):
        inits[name] = np.asarray(arr)
        return name

    if opset >= 13:
        nodes.append(make_node("Unsqueeze", ["x", c("ax0", np.array([0], np.int64))], ["u"]))
        nodes.append(make_node("Squeeze", ["u", c("ax1", np.array([0], np.int64))], ["s"]))
    else:
        nodes.append(make_node("Unsqueeze", ["x"], ["u"], axes=[0]))
        nodes.append(make_node("Squeeze", ["u"], ["s"], axes=[0]))
    if opset >= 13:
        nodes.append(make_node("ReduceSum", ["s", c("ax2", np.array([2], np.int64))], ["r"], keepdims=0))
    else:
        nodes.append(make_node("ReduceSum", ["s"], ["r"], axes=[2], keepdims=0))
    if opset >= 11:
        nodes.append(make_node("Clip", ["r", c("lo", np.array(5.0, np.float32)),
                                        c("hi", np.array(60.0, np.float32))], ["cl"]))
    else:
        nodes.append(make_node("Clip", ["r"], ["cl"], min=5.0, max=60.0))
    if opset >= 13:
        nodes.append(make_node("Split", ["cl", c("sp", np.array([1, 1], np.int64))],
                               ["a", "b"], axis=0))
    else:
        nodes.append(make_node("Split", ["cl"], ["a", "b"], axis=0, split=[1, 1]))
    nodes.append(make_node("Concat", ["a", "b"], ["y"], axis=0))

    graph = make_graph(nodes, "variants",
                       inputs=[make_tensor_value_info("x", np.float32, (2, 3, 4))],
                       outputs=[make_tensor_value_info("y", np.float32, (2, 3))],
                       initializers=inits)
    cm = convert_model(make_model(graph, opset=opset))
    got = np.asarray(cm(cm.params, {"x": x})["y"])
    want = np.clip(x.sum(axis=2), 5.0, 60.0)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_softmax_pre13_coercion_semantics():
    """Opset<13 Softmax flattens trailing dims from `axis` — different from
    the 13+ per-axis semantics when axis is not the last dim."""
    x = np.random.default_rng(0).normal(0, 1, (2, 3, 4)).astype(np.float32)
    nodes = [make_node("Softmax", ["x"], ["y"], axis=1)]
    graph = make_graph(nodes, "sm",
                       inputs=[make_tensor_value_info("x", np.float32, (2, 3, 4))],
                       outputs=[make_tensor_value_info("y", np.float32, (2, 3, 4))])
    cm = convert_model(make_model(graph, opset=11))
    got = np.asarray(cm({}, {"x": x})["y"])
    flat = x.reshape(2, 12)
    e = np.exp(flat - flat.max(-1, keepdims=True))
    want = (e / e.sum(-1, keepdims=True)).reshape(2, 3, 4)
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.parametrize("opset", [11, 13, 17])
def test_bert_pooled_sentence_embedding(opset):
    """The `pooled` output is the mask-weighted mean of last_hidden_state
    over non-padding positions — the sentence-transformers mean_pooling
    pattern, (B, D) instead of (B, S, D)."""
    params = init_bert_params(CFG, seed=3)
    cm = convert_model(export_bert_onnx(CFG, opset=opset, params=params))
    ids, mask = _bert_io()
    out = cm(cm.params, {"input_ids": ids, "attention_mask": mask})
    hidden = np.asarray(out["last_hidden_state"])
    pooled = np.asarray(out["pooled"])
    m = mask[..., None].astype(np.float32)
    want = (hidden * m).sum(axis=1) / m.sum(axis=1)
    assert pooled.shape == (ids.shape[0], CFG.d_model)
    np.testing.assert_allclose(pooled, want, rtol=2e-4, atol=2e-5)
