"""Converter vs foreign-exporter graph patterns.

Round-1 verdict: the ONNX importer was only ever tested on its own builder's
clean graphs. These tests exercise what real exporters emit (torch-style):
opset 11/13/17 attribute-vs-input variants, decomposed LayerNorm/GELU,
dynamic batch axes (dim_param), Shape-arithmetic reshapes, attention-mask
subgraphs, and external-data initializers — parity target:
``ONNXModel.scala:195-245`` type coverage against real ORT-consumable models.
"""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.models.onnx_model import ONNXModel
from mmlspark_tpu.models.zoo.bert_onnx import (BertOnnxConfig, bert_reference,
                                               export_bert_onnx,
                                               init_bert_params)
from mmlspark_tpu.onnx.builder import (make_graph, make_model, make_node,
                                       make_tensor_value_info)
from mmlspark_tpu.onnx.convert import convert_model

CFG = BertOnnxConfig(vocab=97, layers=2, d_model=48, heads=4, d_ff=96,
                     max_len=32)


def _bert_io(seed=0, B=3, S=17):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, CFG.vocab, (B, S))
    mask = np.ones((B, S), dtype=np.int64)
    mask[0, S - 4:] = 0  # ragged row
    mask[2, S - 1:] = 0
    return ids.astype(np.int64), mask


@pytest.mark.parametrize("opset", [11, 13, 17])
def test_bert_torch_style_matches_reference(opset):
    """The full attention pattern — Shape arithmetic, decomposed LN/GELU,
    mask bias — must match a numpy re-implementation at every opset."""
    params = init_bert_params(CFG, seed=1)
    mb = export_bert_onnx(CFG, opset=opset, params=params)
    cm = convert_model(mb)
    assert cm.input_names == ["input_ids", "attention_mask"]
    ids, mask = _bert_io()
    out = cm(cm.params, {"input_ids": ids, "attention_mask": mask})
    got = np.asarray(out["last_hidden_state"])
    want = bert_reference(params, ids, mask, CFG)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_bert_dynamic_batch_axes():
    """dim_param inputs: the same converted model must serve several batch
    and sequence sizes (bucketed jit, no fixed shapes baked in)."""
    params = init_bert_params(CFG, seed=2)
    cm = convert_model(export_bert_onnx(CFG, params=params))
    vi = {v.name: v for v in cm.inputs}
    assert vi["input_ids"].shape == ["batch", "seq"]
    for B, S in [(1, 5), (4, 12), (2, 32)]:
        ids, mask = np.ones((B, S), np.int64), np.ones((B, S), np.int64)
        out = np.asarray(cm(cm.params, {"input_ids": ids,
                                        "attention_mask": mask})["last_hidden_state"])
        assert out.shape == (B, S, CFG.d_model)
        np.testing.assert_allclose(out, bert_reference(params, ids, mask, CFG),
                                   rtol=2e-4, atol=2e-5)


def test_bert_external_data(tmp_path):
    """External-data initializers (torch save_as_external_data layout):
    offset-packed single sidecar file."""
    params = init_bert_params(CFG, seed=3)
    d = str(tmp_path)
    mb = export_bert_onnx(CFG, params=params, external_data_dir=d)
    assert (tmp_path / "weights.bin").stat().st_size > 0
    # without the dir the converter must fail loudly, not silently zero-fill
    with pytest.raises(ValueError, match="external"):
        convert_model(mb)
    cm = convert_model(mb, external_data_dir=d)
    ids, mask = _bert_io(seed=4)
    got = np.asarray(cm(cm.params, {"input_ids": ids,
                                    "attention_mask": mask})["last_hidden_state"])
    np.testing.assert_allclose(got, bert_reference(params, ids, mask, CFG),
                               rtol=2e-4, atol=2e-5)


def test_external_data_path_escape_rejected(tmp_path):
    from mmlspark_tpu.onnx.proto import TensorProto, tensor_to_numpy
    t = TensorProto(dims=[2], data_type=1, name="w",
                    data_location=TensorProto.EXTERNAL,
                    external_data={"location": "../../etc/passwd"})
    with pytest.raises(ValueError, match="escapes"):
        tensor_to_numpy(t, str(tmp_path))


def test_onnx_model_stage_external_data(tmp_path):
    """ONNXModel end-to-end with external weights through the DataFrame API."""
    params = init_bert_params(CFG, seed=5)
    d = str(tmp_path)
    mb = export_bert_onnx(CFG, params=params, external_data_dir=d)
    m = ONNXModel(mb, feed_dict={"input_ids": "ids", "attention_mask": "mask"},
                  fetch_dict={"emb": "last_hidden_state"},
                  mini_batch_size=4, external_data_dir=d, pin_devices=False)
    ids, mask = _bert_io(seed=6, B=6, S=9)
    def col(a):
        o = np.empty(len(a), dtype=object)
        for i, r in enumerate(a):
            o[i] = r
        return o
    out = m.transform(DataFrame({"ids": col(ids), "mask": col(mask)}))
    got = np.stack(list(out["emb"]))
    np.testing.assert_allclose(got, bert_reference(params, ids, mask, CFG),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("opset", [11, 13, 18])
def test_opset_attr_vs_input_variants(opset):
    """Squeeze/Unsqueeze/ReduceSum/Clip/Split across their opset boundary
    forms, in one graph, numerically checked."""
    from mmlspark_tpu.onnx.builder import make_tensor  # noqa: F401
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    nodes, inits = [], {}

    def c(name, arr):
        inits[name] = np.asarray(arr)
        return name

    if opset >= 13:
        nodes.append(make_node("Unsqueeze", ["x", c("ax0", np.array([0], np.int64))], ["u"]))
        nodes.append(make_node("Squeeze", ["u", c("ax1", np.array([0], np.int64))], ["s"]))
    else:
        nodes.append(make_node("Unsqueeze", ["x"], ["u"], axes=[0]))
        nodes.append(make_node("Squeeze", ["u"], ["s"], axes=[0]))
    if opset >= 13:
        nodes.append(make_node("ReduceSum", ["s", c("ax2", np.array([2], np.int64))], ["r"], keepdims=0))
    else:
        nodes.append(make_node("ReduceSum", ["s"], ["r"], axes=[2], keepdims=0))
    if opset >= 11:
        nodes.append(make_node("Clip", ["r", c("lo", np.array(5.0, np.float32)),
                                        c("hi", np.array(60.0, np.float32))], ["cl"]))
    else:
        nodes.append(make_node("Clip", ["r"], ["cl"], min=5.0, max=60.0))
    if opset >= 13:
        nodes.append(make_node("Split", ["cl", c("sp", np.array([1, 1], np.int64))],
                               ["a", "b"], axis=0))
    else:
        nodes.append(make_node("Split", ["cl"], ["a", "b"], axis=0, split=[1, 1]))
    nodes.append(make_node("Concat", ["a", "b"], ["y"], axis=0))

    graph = make_graph(nodes, "variants",
                       inputs=[make_tensor_value_info("x", np.float32, (2, 3, 4))],
                       outputs=[make_tensor_value_info("y", np.float32, (2, 3))],
                       initializers=inits)
    cm = convert_model(make_model(graph, opset=opset))
    got = np.asarray(cm(cm.params, {"x": x})["y"])
    want = np.clip(x.sum(axis=2), 5.0, 60.0)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_softmax_pre13_coercion_semantics():
    """Opset<13 Softmax flattens trailing dims from `axis` — different from
    the 13+ per-axis semantics when axis is not the last dim."""
    x = np.random.default_rng(0).normal(0, 1, (2, 3, 4)).astype(np.float32)
    nodes = [make_node("Softmax", ["x"], ["y"], axis=1)]
    graph = make_graph(nodes, "sm",
                       inputs=[make_tensor_value_info("x", np.float32, (2, 3, 4))],
                       outputs=[make_tensor_value_info("y", np.float32, (2, 3, 4))])
    cm = convert_model(make_model(graph, opset=11))
    got = np.asarray(cm({}, {"x": x})["y"])
    flat = x.reshape(2, 12)
    e = np.exp(flat - flat.max(-1, keepdims=True))
    want = (e / e.sum(-1, keepdims=True)).reshape(2, 3, 4)
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.parametrize("opset", [11, 13, 17])
def test_bert_pooled_sentence_embedding(opset):
    """The `pooled` output is the mask-weighted mean of last_hidden_state
    over non-padding positions — the sentence-transformers mean_pooling
    pattern, (B, D) instead of (B, S, D)."""
    params = init_bert_params(CFG, seed=3)
    cm = convert_model(export_bert_onnx(CFG, opset=opset, params=params))
    ids, mask = _bert_io()
    out = cm(cm.params, {"input_ids": ids, "attention_mask": mask})
    hidden = np.asarray(out["last_hidden_state"])
    pooled = np.asarray(out["pooled"])
    m = mask[..., None].astype(np.float32)
    want = (hidden * m).sum(axis=1) / m.sum(axis=1)
    assert pooled.shape == (ids.shape[0], CFG.d_model)
    np.testing.assert_allclose(pooled, want, rtol=2e-4, atol=2e-5)


class TestMicrosoftContribOps:
    """ORT transformer-optimizer fused ops (com.microsoft domain) — what
    real optimized BERT exports contain."""

    def _run(self, nodes, feeds, inits, outs):
        ins = [make_tensor_value_info(n, a.dtype.type, list(a.shape))
               for n, a in feeds.items()]
        g = make_graph(nodes, "t", ins,
                       [make_tensor_value_info(o, np.float32, []) for o in outs],
                       initializers=inits)
        cm = convert_model(make_model(g))
        r = cm(cm.params, feeds)
        return {o: np.asarray(r[o]) for o in outs}

    def test_fused_matmul_and_gelus(self):
        rng = np.random.default_rng(0)
        a = rng.normal(0, 1, (3, 4)).astype(np.float32)
        b = rng.normal(0, 1, (5, 4)).astype(np.float32)
        bias = rng.normal(0, 1, (5,)).astype(np.float32)
        out = self._run(
            [make_node("FusedMatMul", ["a", "b"], ["mm"], transB=1, alpha=0.5),
             make_node("BiasGelu", ["mm", "bias"], ["bg"]),
             make_node("FastGelu", ["mm", "bias"], ["fg"]),
             make_node("QuickGelu", ["mm"], ["qg"])],
            {"a": a}, {"b": b, "bias": bias}, ["bg", "fg", "qg"])
        import math
        mm = 0.5 * (a @ b.T)
        x = mm + bias
        erf = np.vectorize(math.erf)
        want_bg = x * 0.5 * (1.0 + erf(x / np.sqrt(2.0)))
        np.testing.assert_allclose(out["bg"], want_bg, rtol=1e-5, atol=1e-5)
        want_fg = 0.5 * x * (1 + np.tanh(np.sqrt(2 / np.pi)
                                         * (x + 0.044715 * x ** 3)))
        np.testing.assert_allclose(out["fg"], want_fg, rtol=1e-4, atol=1e-4)
        want_qg = mm / (1 + np.exp(-1.702 * mm))
        np.testing.assert_allclose(out["qg"], want_qg, rtol=1e-5, atol=1e-5)

    def test_skip_layernorm(self):
        rng = np.random.default_rng(1)
        x = rng.normal(0, 1, (2, 3, 8)).astype(np.float32)
        skip = rng.normal(0, 1, (2, 3, 8)).astype(np.float32)
        gamma = rng.normal(1, 0.1, (8,)).astype(np.float32)
        beta = rng.normal(0, 0.1, (8,)).astype(np.float32)
        bias = rng.normal(0, 0.1, (8,)).astype(np.float32)
        out = self._run(
            [make_node("SkipLayerNormalization", ["x", "s", "g", "b", "bi"],
                       ["y"], epsilon=1e-5)],
            {"x": x, "s": skip}, {"g": gamma, "b": beta, "bi": bias}, ["y"])
        t = x + skip + bias
        mu = t.mean(-1, keepdims=True)
        want = (t - mu) / np.sqrt(t.var(-1, keepdims=True) + 1e-5) * gamma + beta
        np.testing.assert_allclose(out["y"], want, rtol=1e-4, atol=1e-4)

    def test_embed_layernorm(self):
        rng = np.random.default_rng(2)
        V, P, H = 20, 10, 8
        ids = rng.integers(0, V, (2, 6)).astype(np.int64)
        seg = rng.integers(0, 2, (2, 6)).astype(np.int64)
        mask = np.ones((2, 6), np.int64); mask[0, 4:] = 0
        we = rng.normal(0, 1, (V, H)).astype(np.float32)
        pe = rng.normal(0, 1, (P, H)).astype(np.float32)
        se = rng.normal(0, 1, (2, H)).astype(np.float32)
        gamma = np.ones(H, np.float32); beta = np.zeros(H, np.float32)
        ins = [make_tensor_value_info("ids", np.int64, [2, 6]),
               make_tensor_value_info("seg", np.int64, [2, 6]),
               make_tensor_value_info("mask", np.int64, [2, 6])]
        g = make_graph(
            [make_node("EmbedLayerNormalization",
                       ["ids", "seg", "we", "pe", "se", "g", "b", "mask"],
                       ["y", "mi"])],
            "t", ins,
            [make_tensor_value_info("y", np.float32, []),
             make_tensor_value_info("mi", np.int32, [])],
            initializers={"we": we, "pe": pe, "se": se, "g": gamma, "b": beta})
        cm = convert_model(make_model(g))
        r = cm(cm.params, {"ids": ids, "seg": seg, "mask": mask})
        emb = we[ids] + pe[:6][None] + se[seg]
        mu = emb.mean(-1, keepdims=True)
        want = (emb - mu) / np.sqrt(emb.var(-1, keepdims=True) + 1e-12)
        np.testing.assert_allclose(np.asarray(r["y"]), want, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_array_equal(np.asarray(r["mi"]), [4, 6])

    def test_fused_attention_matches_reference(self):
        rng = np.random.default_rng(3)
        B, S, H, heads = 2, 5, 8, 2
        x = rng.normal(0, 1, (B, S, H)).astype(np.float32)
        w = rng.normal(0, 0.3, (H, 3 * H)).astype(np.float32)
        b = rng.normal(0, 0.1, (3 * H,)).astype(np.float32)
        lens = np.array([3, 5], np.int32)   # (B,) right-pad lengths form
        ins = [make_tensor_value_info("x", np.float32, [B, S, H]),
               make_tensor_value_info("lens", np.int32, [B])]
        g = make_graph(
            [make_node("Attention", ["x", "w", "b", "lens"], ["y"],
                       domain="com.microsoft", num_heads=heads)],
            "t", ins, [make_tensor_value_info("y", np.float32, [])],
            initializers={"w": w, "b": b})
        cm = convert_model(make_model(g))
        got = np.asarray(cm(cm.params, {"x": x, "lens": lens})["y"])
        # numpy reference
        qkv = x @ w + b
        q, k, v = np.split(qkv, 3, axis=-1)
        D = H // heads
        def sh(t):
            return t.reshape(B, S, heads, D).transpose(0, 2, 1, 3)
        q, k, v = sh(q), sh(k), sh(v)
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        kvm = np.arange(S)[None, :] < lens[:, None]
        s = np.where(kvm[:, None, None, :], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        want = np.einsum("bhqk,bhkd->bhqd", p, v).transpose(0, 2, 1, 3).reshape(B, S, H)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_attention_scale_zero_means_default(self):
        # ORT reads GetAttrOrDefault("scale", 0.0f) and substitutes
        # 1/sqrt(head_size) when the serialized value is 0 — a graph that
        # explicitly stores scale=0.0 must NOT zero the logits
        rng = np.random.default_rng(7)
        B, S, H, heads = 1, 4, 8, 2
        x = rng.normal(0, 1, (B, S, H)).astype(np.float32)
        w = rng.normal(0, 0.3, (H, 3 * H)).astype(np.float32)
        ins = [make_tensor_value_info("x", np.float32, [B, S, H])]

        def run(**attrs):
            g = make_graph(
                [make_node("Attention", ["x", "w"], ["y"],
                           domain="com.microsoft", num_heads=heads, **attrs)],
                "t", ins, [make_tensor_value_info("y", np.float32, [])],
                initializers={"w": w})
            cm = convert_model(make_model(g))
            return np.asarray(cm(cm.params, {"x": x})["y"])

        np.testing.assert_allclose(run(scale=0.0), run(), rtol=1e-6)

    def test_attention_rejects_past_state(self):
        import pytest as _pt
        from mmlspark_tpu.onnx.convert import UnsupportedOp
        x = np.zeros((1, 2, 4), np.float32)
        w = np.zeros((4, 12), np.float32)
        b = np.zeros(12, np.float32)
        past = np.zeros((2, 1, 2, 2, 2), np.float32)
        ins = [make_tensor_value_info("x", np.float32, [1, 2, 4]),
               make_tensor_value_info("past", np.float32, list(past.shape))]
        g = make_graph(
            [make_node("Attention", ["x", "w", "b", "", "past"], ["y"],
                       domain="com.microsoft", num_heads=2)],
            "t", ins, [make_tensor_value_info("y", np.float32, [])],
            initializers={"w": w, "b": b})
        cm = convert_model(make_model(g))
        with _pt.raises(UnsupportedOp):
            cm(cm.params, {"x": x, "past": past})


class TestLlamaEraContribOps:
    """SimplifiedLayerNorm (RMS), RotaryEmbedding, MultiHeadAttention —
    what ORT emits for Llama/GQA-era models."""

    def _cm(self, nodes, feed_infos, inits, out_names):
        g = make_graph(nodes, "t", feed_infos,
                       [make_tensor_value_info(o, np.float32, [])
                        for o in out_names],
                       initializers=inits)
        return convert_model(make_model(g))

    def test_rms_norm_variants(self):
        rng = np.random.default_rng(0)
        x = rng.normal(0, 1, (2, 3, 8)).astype(np.float32)
        skip = rng.normal(0, 1, (2, 3, 8)).astype(np.float32)
        gamma = rng.normal(1, 0.1, (8,)).astype(np.float32)
        cm = self._cm(
            [make_node("SimplifiedLayerNormalization", ["x", "g"], ["a"],
                       epsilon=1e-6),
             make_node("RMSNormalization", ["x", "g"], ["b"], epsilon=1e-6),
             make_node("SkipSimplifiedLayerNormalization",
                       ["x", "s", "g"], ["c"], epsilon=1e-6)],
            [make_tensor_value_info("x", np.float32, [2, 3, 8]),
             make_tensor_value_info("s", np.float32, [2, 3, 8])],
            {"g": gamma}, ["a", "b", "c"])
        r = cm(cm.params, {"x": x, "s": skip})

        def rms(t):
            return t / np.sqrt((t * t).mean(-1, keepdims=True) + 1e-6) * gamma

        np.testing.assert_allclose(np.asarray(r["a"]), rms(x), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(r["b"]), rms(x), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(r["c"]), rms(x + skip),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("interleaved", [0, 1])
    def test_rotary_embedding(self, interleaved):
        rng = np.random.default_rng(1)
        B, NH, S, D = 1, 2, 4, 6
        x = rng.normal(0, 1, (B, NH, S, D)).astype(np.float32)
        pos = np.arange(S, dtype=np.int64)[None, :].repeat(B, 0)
        inv = 1.0 / (10000 ** (np.arange(0, D, 2) / D))
        ang = np.arange(16)[:, None] * inv[None, :]
        cos_c = np.cos(ang).astype(np.float32)
        sin_c = np.sin(ang).astype(np.float32)
        cm = self._cm(
            [make_node("RotaryEmbedding", ["x", "p", "c", "s"], ["y"],
                       domain="com.microsoft", interleaved=interleaved)],
            [make_tensor_value_info("x", np.float32, [B, NH, S, D]),
             make_tensor_value_info("p", np.int64, [B, S])],
            {"c": cos_c, "s": sin_c}, ["y"])
        got = np.asarray(cm(cm.params, {"x": x, "p": pos})["y"])
        cos = cos_c[pos][:, None]; sin = sin_c[pos][:, None]
        if interleaved:
            x0, x1 = x[..., 0::2], x[..., 1::2]
            want = np.stack([x0 * cos - x1 * sin,
                             x0 * sin + x1 * cos], -1).reshape(x.shape)
        else:
            h = D // 2
            x0, x1 = x[..., :h], x[..., h:]
            want = np.concatenate([x0 * cos - x1 * sin,
                                   x0 * sin + x1 * cos], -1)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_multi_head_attention(self):
        rng = np.random.default_rng(2)
        B, S, H, heads = 2, 5, 8, 2
        q = rng.normal(0, 1, (B, S, H)).astype(np.float32)
        k = rng.normal(0, 1, (B, S, H)).astype(np.float32)
        v = rng.normal(0, 1, (B, S, H)).astype(np.float32)
        mask = np.ones((B, S), np.int32); mask[0, 3:] = 0
        cm = self._cm(
            [make_node("MultiHeadAttention", ["q", "k", "v", "", "m"], ["y"],
                       domain="com.microsoft", num_heads=heads)],
            [make_tensor_value_info("q", np.float32, [B, S, H]),
             make_tensor_value_info("k", np.float32, [B, S, H]),
             make_tensor_value_info("v", np.float32, [B, S, H]),
             make_tensor_value_info("m", np.int32, [B, S])],
            {}, ["y"])
        got = np.asarray(cm(cm.params, {"q": q, "k": k, "v": v, "m": mask})["y"])
        D = H // heads
        def sh(t):
            return t.reshape(B, S, heads, D).transpose(0, 2, 1, 3)
        s = np.einsum("bhqd,bhkd->bhqk", sh(q), sh(k)) / np.sqrt(D)
        s = np.where(mask.astype(bool)[:, None, None, :], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("bhqk,bhkd->bhqd", p, sh(v)).transpose(0, 2, 1, 3).reshape(B, S, H)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_mha_unidirectional_and_rotary_offset():
    # causal MHA (review regression) + RotaryEmbedding (1,)-offset form
    rng = np.random.default_rng(4)
    B, S, H, heads = 1, 4, 8, 2
    q = rng.normal(0, 1, (B, S, H)).astype(np.float32)
    g = make_graph(
        [make_node("MultiHeadAttention", ["q", "q", "q"], ["y"],
                   domain="com.microsoft", num_heads=heads,
                   unidirectional=1)],
        "t", [make_tensor_value_info("q", np.float32, [B, S, H])],
        [make_tensor_value_info("y", np.float32, [])])
    cm = convert_model(make_model(g))
    got = np.asarray(cm(cm.params, {"q": q})["y"])
    D = H // heads
    def sh(t):
        return t.reshape(B, S, heads, D).transpose(0, 2, 1, 3)
    s = np.einsum("bhqd,bhkd->bhqk", sh(q), sh(q)) / np.sqrt(D)
    s = np.where(np.tril(np.ones((S, S), bool))[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True)); p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, sh(q)).transpose(0, 2, 1, 3).reshape(B, S, H)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    # rotary offset: (1,) position_ids means pos = offset + arange(S)
    NH, D2 = 2, 6
    x = rng.normal(0, 1, (1, NH, S, D2)).astype(np.float32)
    inv = 1.0 / (10000 ** (np.arange(0, D2, 2) / D2))
    ang = np.arange(16)[:, None] * inv[None, :]
    cos_c, sin_c = np.cos(ang).astype(np.float32), np.sin(ang).astype(np.float32)
    off = np.array([3], np.int64)
    g2 = make_graph(
        [make_node("RotaryEmbedding", ["x", "p", "c", "s"], ["y"],
                   domain="com.microsoft")],
        "t", [make_tensor_value_info("x", np.float32, [1, NH, S, D2]),
              make_tensor_value_info("p", np.int64, [1])],
        [make_tensor_value_info("y", np.float32, [])],
        initializers={"c": cos_c, "s": sin_c})
    cm2 = convert_model(make_model(g2))
    got2 = np.asarray(cm2(cm2.params, {"x": x, "p": off})["y"])
    pos = (3 + np.arange(S))[None, :]
    cos = cos_c[pos][:, None]; sin = sin_c[pos][:, None]
    h = D2 // 2
    x0, x1 = x[..., :h], x[..., h:]
    want2 = np.concatenate([x0 * cos - x1 * sin, x0 * sin + x1 * cos], -1)
    np.testing.assert_allclose(got2, want2, rtol=1e-5, atol=1e-5)


def test_standard_attention_qkv_and_gqa():
    rng = np.random.default_rng(5)
    B, Hq, Hkv, S, D = 1, 4, 2, 6, 4
    q = rng.normal(0, 1, (B, Hq, S, D)).astype(np.float32)
    k = rng.normal(0, 1, (B, Hkv, S, D)).astype(np.float32)
    v = rng.normal(0, 1, (B, Hkv, S, D)).astype(np.float32)
    # standard ai.onnx Attention (domain ""), 4-D GQA form, causal
    g = make_graph(
        [make_node("Attention", ["q", "k", "v"], ["y"], is_causal=1)],
        "t", [make_tensor_value_info(n, np.float32, list(t.shape))
              for n, t in [("q", q), ("k", k), ("v", v)]],
        [make_tensor_value_info("y", np.float32, [])])
    cm = convert_model(make_model(g))
    got = np.asarray(cm(cm.params, {"q": q, "k": k, "v": v})["y"])
    kr = np.repeat(k, Hq // Hkv, 1)
    vr = np.repeat(v, Hq // Hkv, 1)
    s = np.einsum("bhqd,bhkd->bhqk", q, kr) / np.sqrt(D)
    s = np.where(np.tril(np.ones((S, S), bool))[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True)); p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, vr)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    # com.microsoft GroupQueryAttention, (B, S, H) packed-row form
    q2 = rng.normal(0, 1, (B, S, Hq * D)).astype(np.float32)
    k2 = rng.normal(0, 1, (B, S, Hkv * D)).astype(np.float32)
    v2 = rng.normal(0, 1, (B, S, Hkv * D)).astype(np.float32)
    g2 = make_graph(
        [make_node("GroupQueryAttention", ["q", "k", "v"], ["y"],
                   domain="com.microsoft", num_heads=Hq, kv_num_heads=Hkv)],
        "t", [make_tensor_value_info(n, np.float32, list(t.shape))
              for n, t in [("q", q2), ("k", k2), ("v", v2)]],
        [make_tensor_value_info("y", np.float32, [])])
    cm2 = convert_model(make_model(g2))
    got2 = np.asarray(cm2(cm2.params, {"q": q2, "k": k2, "v": v2})["y"])
    def sh(t, nh):
        return t.reshape(B, S, nh, D).transpose(0, 2, 1, 3)
    qh = sh(q2, Hq)
    kh = np.repeat(sh(k2, Hkv), Hq // Hkv, 1)
    vh = np.repeat(sh(v2, Hkv), Hq // Hkv, 1)
    s2 = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(D)
    s2 = np.where(np.tril(np.ones((S, S), bool))[None, None], s2, -1e30)
    p2 = np.exp(s2 - s2.max(-1, keepdims=True)); p2 /= p2.sum(-1, keepdims=True)
    want2 = np.einsum("bhqk,bhkd->bhqd", p2, vh).transpose(0, 2, 1, 3).reshape(B, S, Hq * D)
    np.testing.assert_allclose(got2, want2, rtol=1e-4, atol=1e-4)


def test_gqa_seqlens_and_std_attention_pair_mask():
    rng = np.random.default_rng(6)
    B, Hq, Hkv, S, D = 2, 4, 2, 6, 4
    # GQA with the always-present seqlens_k / total_sequence_length inputs
    q2 = rng.normal(0, 1, (B, S, Hq * D)).astype(np.float32)
    k2 = rng.normal(0, 1, (B, S, Hkv * D)).astype(np.float32)
    v2 = rng.normal(0, 1, (B, S, Hkv * D)).astype(np.float32)
    seqlens = np.array([3, 5], np.int32)     # valid keys = seqlens + 1
    total = np.array(S, np.int32)
    g = make_graph(
        [make_node("GroupQueryAttention",
                   ["q", "k", "v", "", "", "sl", "tl"], ["y"],
                   domain="com.microsoft", num_heads=Hq, kv_num_heads=Hkv)],
        "t", [make_tensor_value_info("q", np.float32, [B, S, Hq * D]),
              make_tensor_value_info("k", np.float32, [B, S, Hkv * D]),
              make_tensor_value_info("v", np.float32, [B, S, Hkv * D]),
              make_tensor_value_info("sl", np.int32, [B]),
              make_tensor_value_info("tl", np.int32, [])],
        [make_tensor_value_info("y", np.float32, [])])
    cm = convert_model(make_model(g))
    got = np.asarray(cm(cm.params, {"q": q2, "k": k2, "v": v2,
                                    "sl": seqlens, "tl": total})["y"])

    def sh(t, nh):
        return t.reshape(B, S, nh, D).transpose(0, 2, 1, 3)
    qh = sh(q2, Hq)
    kh = np.repeat(sh(k2, Hkv), Hq // Hkv, 1)
    vh = np.repeat(sh(v2, Hkv), Hq // Hkv, 1)
    s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(D)
    kvm = np.arange(S)[None, :] <= seqlens[:, None]
    s = np.where(kvm[:, None, None, :], s, -1e30)
    s = np.where(np.tril(np.ones((S, S), bool))[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True)); p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, vh).transpose(0, 2, 1, 3).reshape(B, S, Hq * D)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    # standard Attention with a 2-D (Sq, Skv) pair mask (banded)
    q4 = rng.normal(0, 1, (1, 2, S, D)).astype(np.float32)
    band = np.abs(np.arange(S)[:, None] - np.arange(S)[None, :]) <= 2
    g2 = make_graph(
        [make_node("Attention", ["q", "q", "q", "m"], ["y"])],
        "t", [make_tensor_value_info("q", np.float32, [1, 2, S, D]),
              make_tensor_value_info("m", np.bool_, [S, S])],
        [make_tensor_value_info("y", np.float32, [])])
    cm2 = convert_model(make_model(g2))
    got2 = np.asarray(cm2(cm2.params, {"q": q4, "m": band})["y"])
    s2 = np.einsum("bhqd,bhkd->bhqk", q4, q4) / np.sqrt(D)
    s2 = np.where(band[None, None], s2, -1e30)
    p2 = np.exp(s2 - s2.max(-1, keepdims=True)); p2 /= p2.sum(-1, keepdims=True)
    want2 = np.einsum("bhqk,bhkd->bhqd", p2, q4)
    np.testing.assert_allclose(got2, want2, rtol=1e-4, atol=1e-4)


def _np_gqa_full(q2, k2, v2, Hq, Hkv, valid_last):
    """Dense numpy GQA over the full sequence with per-batch valid length
    (keys j <= valid_last[b]) and causal masking — the oracle."""
    B, S, _ = q2.shape
    D = q2.shape[2] // Hq

    def sh(t, nh):
        return t.reshape(B, S, nh, D).transpose(0, 2, 1, 3)

    qh = sh(q2, Hq)
    kh = np.repeat(sh(k2, Hkv), Hq // Hkv, 1)
    vh = np.repeat(sh(v2, Hkv), Hq // Hkv, 1)
    s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(D)
    kvm = np.arange(S)[None, :] <= np.asarray(valid_last)[:, None]
    s = np.where(kvm[:, None, None, :], s, -1e30)
    s = np.where(np.tril(np.ones((S, S), bool))[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bhqk,bhkd->bhqd", p, vh)
    return out.transpose(0, 2, 1, 3).reshape(B, S, Hq * D)


def test_gqa_kv_cache_decode_matches_full_prefill():
    """Decode form: one new token + static past buffers must reproduce the
    last row of full-sequence attention, and the present outputs must carry
    the updated cache."""
    rng = np.random.default_rng(7)
    B, Hq, Hkv, D, S_max = 2, 4, 2, 4, 8
    S_past, S_new = 5, 1
    S_tot = S_past + S_new
    q_all = rng.normal(0, 1, (B, S_tot, Hq * D)).astype(np.float32)
    k_all = rng.normal(0, 1, (B, S_tot, Hkv * D)).astype(np.float32)
    v_all = rng.normal(0, 1, (B, S_tot, Hkv * D)).astype(np.float32)
    want_full = _np_gqa_full(q_all, k_all, v_all, Hq, Hkv,
                             [S_tot - 1] * B)

    def heads(t, nh):
        return t.reshape(B, S_tot, nh, D).transpose(0, 2, 1, 3)

    # static cache buffers: valid rows 0..S_past-1, garbage beyond
    past_k = np.full((B, Hkv, S_max, D), 1e3, np.float32)
    past_v = np.full((B, Hkv, S_max, D), -1e3, np.float32)
    past_k[:, :, :S_past] = heads(k_all, Hkv)[:, :, :S_past]
    past_v[:, :, :S_past] = heads(v_all, Hkv)[:, :, :S_past]
    seqlens = np.full(B, S_tot - 1, np.int32)   # total valid - 1
    total = np.array(S_tot, np.int32)

    g = make_graph(
        [make_node("GroupQueryAttention",
                   ["q", "k", "v", "pk", "pv", "sl", "tl"],
                   ["y", "ok", "ov"],
                   domain="com.microsoft", num_heads=Hq, kv_num_heads=Hkv)],
        "t",
        [make_tensor_value_info("q", np.float32, [B, S_new, Hq * D]),
         make_tensor_value_info("k", np.float32, [B, S_new, Hkv * D]),
         make_tensor_value_info("v", np.float32, [B, S_new, Hkv * D]),
         make_tensor_value_info("pk", np.float32, [B, Hkv, S_max, D]),
         make_tensor_value_info("pv", np.float32, [B, Hkv, S_max, D]),
         make_tensor_value_info("sl", np.int32, [B]),
         make_tensor_value_info("tl", np.int32, [])],
        [make_tensor_value_info("y", np.float32, []),
         make_tensor_value_info("ok", np.float32, []),
         make_tensor_value_info("ov", np.float32, [])])
    cm = convert_model(make_model(g))
    got = cm(cm.params, {
        "q": q_all[:, S_past:], "k": k_all[:, S_past:],
        "v": v_all[:, S_past:], "pk": past_k, "pv": past_v,
        "sl": seqlens, "tl": total})
    np.testing.assert_allclose(np.asarray(got["y"])[:, 0],
                               want_full[:, S_past], rtol=1e-4, atol=1e-4)
    # present caches: new row written in place at position S_past,
    # earlier rows untouched, buffer shape static
    ok = np.asarray(got["ok"])
    assert ok.shape == (B, Hkv, S_max, D)
    np.testing.assert_allclose(ok[:, :, :S_past], past_k[:, :, :S_past])
    np.testing.assert_allclose(
        ok[:, :, S_past],
        heads(k_all, Hkv)[:, :, S_past], rtol=1e-5, atol=1e-5)


def test_gqa_packed_qkv_and_softcap():
    rng = np.random.default_rng(8)
    B, Hq, Hkv, D, S = 2, 4, 2, 4, 6
    packed = rng.normal(0, 1, (B, S, (Hq + 2 * Hkv) * D)).astype(np.float32)
    seqlens = np.full(B, S - 1, np.int32)
    g = make_graph(
        [make_node("GroupQueryAttention",
                   ["q", "", "", "", "", "sl", "tl"], ["y"],
                   domain="com.microsoft", num_heads=Hq, kv_num_heads=Hkv,
                   softcap=30.0)],
        "t", [make_tensor_value_info("q", np.float32, list(packed.shape)),
              make_tensor_value_info("sl", np.int32, [B]),
              make_tensor_value_info("tl", np.int32, [])],
        [make_tensor_value_info("y", np.float32, [])])
    cm = convert_model(make_model(g))
    got = np.asarray(cm(cm.params, {"q": packed, "sl": seqlens,
                                    "tl": np.array(S, np.int32)})["y"])
    q2 = packed[:, :, :Hq * D]
    k2 = packed[:, :, Hq * D:(Hq + Hkv) * D]
    v2 = packed[:, :, (Hq + Hkv) * D:]
    # exact capped oracle: a deliberately small cap (value 2.0 below would
    # be wrong for a real model but makes an uncapped implementation fail
    # this assert by a wide margin)
    want = _np_gqa_capped(q2, k2, v2, Hq, Hkv, softcap=30.0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    assert got.shape == (B, S, Hq * D)

    # tight cap: uncapped vs capped differ grossly, pinning the tanh math
    g3 = make_graph(
        [make_node("GroupQueryAttention",
                   ["q", "", "", "", "", "sl", "tl"], ["y"],
                   domain="com.microsoft", num_heads=Hq, kv_num_heads=Hkv,
                   softcap=0.5)],
        "t", [make_tensor_value_info("q", np.float32, list(packed.shape)),
              make_tensor_value_info("sl", np.int32, [B]),
              make_tensor_value_info("tl", np.int32, [])],
        [make_tensor_value_info("y", np.float32, [])])
    cm3 = convert_model(make_model(g3))
    got3 = np.asarray(cm3(cm3.params, {"q": packed, "sl": seqlens,
                                       "tl": np.array(S, np.int32)})["y"])
    want3 = _np_gqa_capped(q2, k2, v2, Hq, Hkv, softcap=0.5)
    np.testing.assert_allclose(got3, want3, rtol=1e-4, atol=1e-4)
    uncapped = _np_gqa_full(q2, k2, v2, Hq, Hkv, [S - 1] * B)
    assert np.abs(got3 - uncapped).max() > 1e-3   # the cap actually bites


def _np_gqa_capped(q2, k2, v2, Hq, Hkv, softcap, smooth=False):
    B, S, _ = q2.shape
    D = q2.shape[2] // Hq

    def sh(t, nh):
        return t.reshape(B, S, nh, D).transpose(0, 2, 1, 3)

    qh = sh(q2, Hq)
    kh = np.repeat(sh(k2, Hkv), Hq // Hkv, 1)
    vh = np.repeat(sh(v2, Hkv), Hq // Hkv, 1)
    s = np.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(D)
    if softcap:
        s = softcap * np.tanh(s / softcap)
    s = np.where(np.tril(np.ones((S, S), bool))[None, None], s, -1e30)
    e = np.exp(s)
    denom = e.sum(-1, keepdims=True) + (1.0 if smooth else 0.0)
    p = e / denom
    out = np.einsum("bhqk,bhkd->bhqd", p, vh)
    return out.transpose(0, 2, 1, 3).reshape(B, S, Hq * D)


def test_gqa_smooth_softmax():
    """smooth_softmax=1: ORT's implicit extra zero logit in the softmax
    denominator (Phi-3-style graphs)."""
    rng = np.random.default_rng(11)
    B, Hq, Hkv, D, S = 2, 2, 1, 4, 5
    q2 = rng.normal(0, 1, (B, S, Hq * D)).astype(np.float32)
    k2 = rng.normal(0, 1, (B, S, Hkv * D)).astype(np.float32)
    v2 = rng.normal(0, 1, (B, S, Hkv * D)).astype(np.float32)
    seqlens = np.full(B, S - 1, np.int32)
    g = make_graph(
        [make_node("GroupQueryAttention",
                   ["q", "k", "v", "", "", "sl", "tl"], ["y"],
                   domain="com.microsoft", num_heads=Hq, kv_num_heads=Hkv,
                   smooth_softmax=1)],
        "t", [make_tensor_value_info("q", np.float32, list(q2.shape)),
              make_tensor_value_info("k", np.float32, list(k2.shape)),
              make_tensor_value_info("v", np.float32, list(v2.shape)),
              make_tensor_value_info("sl", np.int32, [B]),
              make_tensor_value_info("tl", np.int32, [])],
        [make_tensor_value_info("y", np.float32, [])])
    cm = convert_model(make_model(g))
    got = np.asarray(cm(cm.params, {"q": q2, "k": k2, "v": v2,
                                    "sl": seqlens,
                                    "tl": np.array(S, np.int32)})["y"])
    want = _np_gqa_capped(q2, k2, v2, Hq, Hkv, softcap=0.0, smooth=True)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    plain = _np_gqa_capped(q2, k2, v2, Hq, Hkv, softcap=0.0, smooth=False)
    assert np.abs(got - plain).max() > 1e-3


def test_std_attention_softcap():
    rng = np.random.default_rng(12)
    B, H, S, D = 1, 2, 5, 4
    q = rng.normal(0, 2, (B, H, S, D)).astype(np.float32)
    g = make_graph(
        [make_node("Attention", ["q", "q", "q"], ["y"], softcap=0.7)],
        "t", [make_tensor_value_info("q", np.float32, list(q.shape))],
        [make_tensor_value_info("y", np.float32, [])])
    cm = convert_model(make_model(g))
    got = np.asarray(cm(cm.params, {"q": q})["y"])
    s = np.einsum("bhqd,bhkd->bhqk", q, q) / np.sqrt(D)
    s = 0.7 * np.tanh(s / 0.7)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, q)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_gqa_rotary_fused():
    """do_rotary=1: q/k rotate at absolute positions before attention —
    must equal a separate RotaryEmbedding + plain GQA pipeline."""
    rng = np.random.default_rng(9)
    B, Hq, Hkv, D, S = 1, 2, 1, 8, 5
    q2 = rng.normal(0, 1, (B, S, Hq * D)).astype(np.float32)
    k2 = rng.normal(0, 1, (B, S, Hkv * D)).astype(np.float32)
    v2 = rng.normal(0, 1, (B, S, Hkv * D)).astype(np.float32)
    max_pos, half = 16, D // 2
    inv = 1.0 / (10000.0 ** (np.arange(half) / half))
    ang = np.arange(max_pos)[:, None] * inv[None, :]
    cos_c = np.cos(ang).astype(np.float32)
    sin_c = np.sin(ang).astype(np.float32)
    seqlens = np.full(B, S - 1, np.int32)
    g = make_graph(
        [make_node("GroupQueryAttention",
                   ["q", "k", "v", "", "", "sl", "tl", "cc", "sc"], ["y"],
                   domain="com.microsoft", num_heads=Hq, kv_num_heads=Hkv,
                   do_rotary=1)],
        "t", [make_tensor_value_info("q", np.float32, list(q2.shape)),
              make_tensor_value_info("k", np.float32, list(k2.shape)),
              make_tensor_value_info("v", np.float32, list(v2.shape)),
              make_tensor_value_info("sl", np.int32, [B]),
              make_tensor_value_info("tl", np.int32, [])],
        [make_tensor_value_info("y", np.float32, [])],
        initializers={"cc": cos_c, "sc": sin_c})
    cm = convert_model(make_model(g))
    got = np.asarray(cm(cm.params, {"q": q2, "k": k2, "v": v2,
                                    "sl": seqlens,
                                    "tl": np.array(S, np.int32)})["y"])

    def rope(t2, nh):
        t = t2.reshape(B, S, nh, D).transpose(0, 2, 1, 3)
        cos = cos_c[np.arange(S)][None, None]
        sin = sin_c[np.arange(S)][None, None]
        x0, x1 = t[..., :half], t[..., half:]
        return np.concatenate([x0 * cos - x1 * sin,
                               x0 * sin + x1 * cos], -1) \
            .transpose(0, 2, 1, 3).reshape(B, S, nh * D)

    want = _np_gqa_full(rope(q2, Hq), rope(k2, Hkv), v2, Hq, Hkv,
                        [S - 1] * B)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_std_attention_3d_layout_and_past():
    rng = np.random.default_rng(10)
    B, H, D, S, Sp = 2, 2, 4, 3, 4
    # 3-D layout with q_num_heads/kv_num_heads attributes
    q3 = rng.normal(0, 1, (B, S, H * D)).astype(np.float32)
    k3 = rng.normal(0, 1, (B, S, H * D)).astype(np.float32)
    v3 = rng.normal(0, 1, (B, S, H * D)).astype(np.float32)
    g = make_graph(
        [make_node("Attention", ["q", "k", "v"], ["y"],
                   q_num_heads=H, kv_num_heads=H)],
        "t", [make_tensor_value_info(n, np.float32, list(t.shape))
              for n, t in [("q", q3), ("k", k3), ("v", v3)]],
        [make_tensor_value_info("y", np.float32, [])])
    cm = convert_model(make_model(g))
    got = np.asarray(cm(cm.params, {"q": q3, "k": k3, "v": v3})["y"])
    assert got.shape == (B, S, H * D)

    def sh(t):
        return t.reshape(B, S, H, D).transpose(0, 2, 1, 3)

    s = np.einsum("bhqd,bhkd->bhqk", sh(q3), sh(k3)) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, sh(v3)) \
        .transpose(0, 2, 1, 3).reshape(B, S, H * D)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    # 4-D with past_key/past_value: present = concat(past, current)
    q4 = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
    k4 = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
    v4 = rng.normal(0, 1, (B, H, S, D)).astype(np.float32)
    pk = rng.normal(0, 1, (B, H, Sp, D)).astype(np.float32)
    pv = rng.normal(0, 1, (B, H, Sp, D)).astype(np.float32)
    g2 = make_graph(
        [make_node("Attention", ["q", "k", "v", "", "pk", "pv"],
                   ["y", "ck", "cv"])],
        "t", [make_tensor_value_info(n, np.float32, list(t.shape))
              for n, t in [("q", q4), ("k", k4), ("v", v4),
                           ("pk", pk), ("pv", pv)]],
        [make_tensor_value_info("y", np.float32, []),
         make_tensor_value_info("ck", np.float32, []),
         make_tensor_value_info("cv", np.float32, [])])
    cm2 = convert_model(make_model(g2))
    got2 = cm2(cm2.params, {"q": q4, "k": k4, "v": v4, "pk": pk, "pv": pv})
    kc = np.concatenate([pk, k4], axis=2)
    vc = np.concatenate([pv, v4], axis=2)
    s2 = np.einsum("bhqd,bhkd->bhqk", q4, kc) / np.sqrt(D)
    p2 = np.exp(s2 - s2.max(-1, keepdims=True))
    p2 /= p2.sum(-1, keepdims=True)
    want2 = np.einsum("bhqk,bhkd->bhqd", p2, vc)
    np.testing.assert_allclose(np.asarray(got2["y"]), want2,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(got2["ck"]), kc, rtol=1e-6,
                               atol=1e-6)


def test_gqa_right_padded_prefill_positions():
    """Right-padded prefill (valid < S): new tokens sit at positions
    0..valid-1 with the tail masked — rope positions must NOT go negative
    and the padded row must match a shorter unpadded run."""
    rng = np.random.default_rng(13)
    B, Hq, Hkv, D, S, valid = 1, 2, 1, 8, 6, 4
    q2 = rng.normal(0, 1, (B, S, Hq * D)).astype(np.float32)
    k2 = rng.normal(0, 1, (B, S, Hkv * D)).astype(np.float32)
    v2 = rng.normal(0, 1, (B, S, Hkv * D)).astype(np.float32)
    max_pos, half = 16, D // 2
    inv = 1.0 / (10000.0 ** (np.arange(half) / half))
    ang = np.arange(max_pos)[:, None] * inv[None, :]
    cos_c = np.cos(ang).astype(np.float32)
    sin_c = np.sin(ang).astype(np.float32)

    def run(S_in, q_, k_, v_, valid_):
        g = make_graph(
            [make_node("GroupQueryAttention",
                       ["q", "k", "v", "", "", "sl", "tl", "cc", "sc"],
                       ["y"], domain="com.microsoft", num_heads=Hq,
                       kv_num_heads=Hkv, do_rotary=1)],
            "t", [make_tensor_value_info("q", np.float32, list(q_.shape)),
                  make_tensor_value_info("k", np.float32, list(k_.shape)),
                  make_tensor_value_info("v", np.float32, list(v_.shape)),
                  make_tensor_value_info("sl", np.int32, [B]),
                  make_tensor_value_info("tl", np.int32, [])],
            [make_tensor_value_info("y", np.float32, [])],
            initializers={"cc": cos_c, "sc": sin_c})
        cm = convert_model(make_model(g))
        return np.asarray(cm(cm.params, {
            "q": q_, "k": k_, "v": v_,
            "sl": np.full(B, valid_ - 1, np.int32),
            "tl": np.array(S_in, np.int32)})["y"])

    padded = run(S, q2, k2, v2, valid)
    short = run(valid, q2[:, :valid], k2[:, :valid], v2[:, :valid], valid)
    # the first `valid` rows of the padded run == the unpadded short run
    np.testing.assert_allclose(padded[:, :valid], short, rtol=1e-4,
                               atol=1e-4)


def test_mha_attention_bias_and_past():
    """com.microsoft MultiHeadAttention: additive attention_bias plus
    concat-grow past_key/past_value with present outputs."""
    rng = np.random.default_rng(14)
    B, H, D, S, Sp = 2, 2, 4, 3, 2
    hid = H * D
    q2 = rng.normal(0, 1, (B, S, hid)).astype(np.float32)
    k2 = rng.normal(0, 1, (B, S, hid)).astype(np.float32)
    v2 = rng.normal(0, 1, (B, S, hid)).astype(np.float32)
    ab = rng.normal(0, 1, (1, H, S, Sp + S)).astype(np.float32)
    pk = rng.normal(0, 1, (B, H, Sp, D)).astype(np.float32)
    pv = rng.normal(0, 1, (B, H, Sp, D)).astype(np.float32)
    g = make_graph(
        [make_node("MultiHeadAttention",
                   ["q", "k", "v", "", "", "ab", "pk", "pv"],
                   ["y", "ok", "ov"],
                   domain="com.microsoft", num_heads=H)],
        "t", [make_tensor_value_info(n, np.float32, list(t.shape))
              for n, t in [("q", q2), ("k", k2), ("v", v2), ("ab", ab),
                           ("pk", pk), ("pv", pv)]],
        [make_tensor_value_info("y", np.float32, []),
         make_tensor_value_info("ok", np.float32, []),
         make_tensor_value_info("ov", np.float32, [])])
    cm = convert_model(make_model(g))
    got = cm(cm.params, {"q": q2, "k": k2, "v": v2, "ab": ab,
                         "pk": pk, "pv": pv})

    def sh(t):
        return t.reshape(B, S, H, D).transpose(0, 2, 1, 3)

    kc = np.concatenate([pk, sh(k2)], axis=2)
    vc = np.concatenate([pv, sh(v2)], axis=2)
    s = np.einsum("bhqd,bhkd->bhqk", sh(q2), kc) / np.sqrt(D) + ab
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, vc) \
        .transpose(0, 2, 1, 3).reshape(B, S, hid)
    np.testing.assert_allclose(np.asarray(got["y"]), want, rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(got["ok"]), kc, rtol=1e-5,
                               atol=1e-5)


def test_fused_attention_extra_add_qk():
    """ORT fused Attention with the additive attention_bias (extra_add_qk)
    input — relative-position-bias graphs (T5-style exports)."""
    rng = np.random.default_rng(15)
    B, H, D, S = 1, 2, 4, 5
    hid = H * D
    x = rng.normal(0, 1, (B, S, hid)).astype(np.float32)
    w = rng.normal(0, 0.3, (hid, 3 * hid)).astype(np.float32)
    ab = rng.normal(0, 1, (1, H, S, S)).astype(np.float32)
    g = make_graph(
        [make_node("Attention", ["x", "w", "", "", "", "ab"], ["y"],
                   domain="com.microsoft", num_heads=H)],
        "t", [make_tensor_value_info("x", np.float32, list(x.shape)),
              make_tensor_value_info("ab", np.float32, list(ab.shape))],
        [make_tensor_value_info("y", np.float32, [])],
        initializers={"w": w})
    cm = convert_model(make_model(g))
    got = np.asarray(cm(cm.params, {"x": x, "ab": ab})["y"])
    qkv = x @ w
    q, k, v = np.split(qkv, 3, axis=-1)

    def sh(t):
        return t.reshape(B, S, H, D).transpose(0, 2, 1, 3)

    s = np.einsum("bhqd,bhkd->bhqk", sh(q), sh(k)) / np.sqrt(D) + ab
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, sh(v)) \
        .transpose(0, 2, 1, 3).reshape(B, S, hid)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
