"""Tests for train wrappers, metrics, linear learners, and automl
(reference: VerifyTrainClassifier / TuneHyperparameters suites)."""

import numpy as np
import pytest

from mmlspark_tpu.automl import (DiscreteHyperParam, FindBestModel, GridSpace,
                                 HyperparamBuilder, RandomSpace,
                                 RangeHyperParam, TuneHyperparameters)
from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.models.linear import LinearRegression, LogisticRegression
from mmlspark_tpu.train import (ComputeModelStatistics,
                                ComputePerInstanceStatistics, TrainClassifier,
                                TrainRegressor, roc_auc)


def _cls_df(n=80, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, 3))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.int64)
    col = np.empty(n, dtype=object)
    for i in range(n):
        col[i] = X[i]
    return DataFrame({"features": col, "label": y})


def test_logistic_regression_learns():
    df = _cls_df()
    model = LogisticRegression(max_iter=300).fit(df)
    out = model.transform(df)
    acc = (out["prediction"] == df["label"]).mean()
    assert acc > 0.9
    assert out["probability"][0].shape == (2,)


def test_linear_regression_learns():
    rng = np.random.default_rng(0)
    X = rng.normal(0, 1, (100, 2))
    y = 2.0 * X[:, 0] - 1.0 * X[:, 1] + 0.5
    col = np.empty(100, dtype=object)
    for i in range(100):
        col[i] = X[i]
    df = DataFrame({"features": col, "label": y})
    model = LinearRegression(max_iter=500, learning_rate=0.2).fit(df)
    pred = model.transform(df)["prediction"]
    assert np.mean((pred - y) ** 2) < 0.05


def test_train_classifier_auto_featurize():
    rng = np.random.default_rng(1)
    n = 60
    df = DataFrame({
        "num": rng.normal(0, 1, n),
        "cat": np.where(rng.random(n) > 0.5, "a", "b"),
        "label": np.where(rng.random(n) > 0.5, "yes", "no"),
    })
    # make label learnable from cat
    labels = np.where(df["cat"] == "a", "yes", "no")
    df = df.with_column("label", labels)
    model = TrainClassifier(model=LogisticRegression(max_iter=300)).fit(df)
    out = model.transform(df)
    assert set(np.unique(out["prediction"])) <= {"yes", "no"}
    acc = (out["prediction"] == labels).mean()
    assert acc > 0.95


def test_train_regressor_and_stats():
    rng = np.random.default_rng(2)
    n = 80
    x = rng.normal(0, 1, n)
    df = DataFrame({"x": x, "label": 3.0 * x + 1.0})
    model = TrainRegressor(model=LinearRegression(max_iter=500,
                                                  learning_rate=0.2)).fit(df)
    scored = model.transform(df)
    stats = ComputeModelStatistics(label_col="label").transform(scored)
    assert stats["R^2"][0] > 0.95
    per = ComputePerInstanceStatistics(label_col="label").transform(scored)
    assert "L2_loss" in per.columns


def test_classification_stats_and_auc():
    df = _cls_df()
    model = LogisticRegression(max_iter=300).fit(df)
    scored = model.transform(df)
    stats = ComputeModelStatistics(label_col="label").transform(scored)
    assert stats["accuracy"][0] > 0.9
    assert stats["AUC"][0] > 0.9
    cm = stats["confusion_matrix"][0]
    assert cm.sum() == len(df)


def test_metrics_with_subset_eval_labels():
    # model trained on 3 classes, eval frame holds only 2: probability
    # indexing must follow the model's class order (via label metadata)
    rng = np.random.default_rng(5)
    n = 90
    X = rng.normal(0, 1, (n, 2))
    y = np.where(X[:, 0] > 0.5, 2, np.where(X[:, 0] < -0.5, 0, 1))
    col = np.empty(n, dtype=object)
    for i in range(n):
        col[i] = X[i]
    df = DataFrame({"features": col, "label": y})
    model = LogisticRegression(max_iter=300).fit(df)
    sub = df.filter(df["label"] != 1)
    scored = model.transform(sub)
    per = ComputePerInstanceStatistics(label_col="label").transform(scored)
    # correct indexing: log-loss for well-separated rows must be small
    assert np.median(per["log_loss"]) < 0.7
    stats = ComputeModelStatistics(label_col="label").transform(scored)
    assert stats["confusion_matrix"][0].shape == (3, 3)


def test_roc_auc_known_value():
    y = np.array([0, 0, 1, 1])
    s = np.array([0.1, 0.4, 0.35, 0.8])
    assert abs(roc_auc(y, s) - 0.75) < 1e-9
    assert roc_auc(np.array([1, 1]), np.array([0.5, 0.5])) != roc_auc(
        np.array([0, 1]), np.array([0.5, 0.5]))  # nan vs 0.5


def test_tune_hyperparameters_random():
    df = _cls_df(n=60)
    space = (HyperparamBuilder()
             .add_hyperparam("learning_rate", RangeHyperParam(0.01, 0.5, is_log=True))
             .add_hyperparam("max_iter", DiscreteHyperParam([50, 150]))
             .build())
    tuner = TuneHyperparameters(
        model=LogisticRegression(), search_space=RandomSpace(space, seed=3),
        number_of_iterations=4, evaluation_metric="accuracy",
        label_col="label", parallelism=2)
    best = tuner.fit(df)
    assert tuner.best_metric is not None and tuner.best_metric > 0.6
    assert set(tuner.best_params) == {"learning_rate", "max_iter"}
    assert "prediction" in best.transform(df).columns


def test_tune_grid_space_enumeration():
    space = (HyperparamBuilder()
             .add_hyperparam("a", DiscreteHyperParam([1, 2]))
             .add_hyperparam("b", DiscreteHyperParam(["x", "y"]))
             .build())
    maps = list(GridSpace(space).param_maps())
    assert len(maps) == 4


def test_find_best_model():
    df = _cls_df(n=60)
    good = LogisticRegression(max_iter=300).fit(df)
    bad = LogisticRegression(max_iter=1).fit(df)
    result = FindBestModel([bad, good], label_col="label").fit(df)
    metrics = dict((i, m) for i, m in result.get("all_model_metrics"))
    assert result.get("best_model") is good or metrics[1] >= metrics[0]
    assert "prediction" in result.transform(df).columns


def test_tune_successive_halving():
    df = _cls_df(n=80)
    space = (HyperparamBuilder()
             .add_hyperparam("learning_rate",
                             RangeHyperParam(0.01, 0.5, is_log=True))
             .build())
    fitted_iters = []

    class Spy(LogisticRegression):
        def _fit(self, d):
            fitted_iters.append(self.get("max_iter"))
            return super()._fit(d)

    tuner = TuneHyperparameters(
        model=Spy(), search_space=RandomSpace(space, seed=5),
        number_of_iterations=6, evaluation_metric="accuracy",
        label_col="label", parallelism=2,
        search_strategy="halving", resource_param="max_iter",
        min_resource=5, max_resource=40, halving_factor=2)
    best = tuner.fit(df)
    assert tuner.best_metric is not None and tuner.best_metric > 0.6
    # rung structure: 6 trials @5, 3 @10, 1 @40 (final rung at max budget)
    assert fitted_iters.count(5) == 6
    assert fitted_iters.count(10) == 3
    assert fitted_iters.count(40) == 1
    assert set(tuner.best_params) == {"learning_rate"}
    assert "prediction" in best.transform(df).columns
    # halving fits 10 models; full search at max budget would cost 6x40
    assert len(fitted_iters) == 10


def test_tune_halving_rejects_bad_config():
    import pytest as _pt
    space = (HyperparamBuilder()
             .add_hyperparam("max_iter", DiscreteHyperParam([10, 20]))
             .build())
    df = _cls_df(n=40)
    t = TuneHyperparameters(
        model=LogisticRegression(), search_space=RandomSpace(space, seed=0),
        number_of_iterations=2, label_col="label",
        search_strategy="halving", resource_param="max_iter")
    with _pt.raises(ValueError, match="halving controls"):
        t.fit(df)
    t2 = TuneHyperparameters(
        model=LogisticRegression(),
        search_space=RandomSpace((HyperparamBuilder().add_hyperparam(
            "learning_rate", RangeHyperParam(0.01, 0.5)).build()), seed=0),
        number_of_iterations=2, label_col="label", search_strategy="halving",
        resource_param="max_iter", min_resource=32, max_resource=8)
    with _pt.raises(ValueError, match="min_resource"):
        t2.fit(df)


class TestPlot:
    """synapse.ml.plot parity (reference plot.py:17-62): confusion matrix
    and ROC computed from DataFrame columns, rendering optional."""

    def test_confusion_matrix_counts(self):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.plot import confusion_matrix
        df = DataFrame({"y":    np.array([0, 0, 1, 1, 1]),
                        "yhat": np.array([0, 1, 1, 1, 0])})
        cm = confusion_matrix(df, "y", "yhat", render=False)
        np.testing.assert_array_equal(cm, [[1, 1], [1, 2]])

    def test_roc_matches_known_curve(self):
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.plot import roc
        df = DataFrame({"y": np.array([0.0, 0.0, 1.0, 1.0]),
                        "score": np.array([0.1, 0.4, 0.35, 0.8])})
        fpr, tpr, thr = roc(df, "y", "score", render=False)
        # sklearn.roc_curve on the same data: fpr [0,0,.5,.5,1], tpr [0,.5,.5,1,1]
        np.testing.assert_allclose(fpr, [0, 0, 0.5, 0.5, 1.0])
        np.testing.assert_allclose(tpr, [0, 0.5, 0.5, 1.0, 1.0])
        auc = np.trapezoid(tpr, fpr)
        assert abs(auc - 0.75) < 1e-9

    def test_render_against_matplotlib(self):
        mpl = pytest.importorskip("matplotlib")
        mpl.use("Agg")
        import matplotlib.pyplot as plt
        from mmlspark_tpu.core import DataFrame
        from mmlspark_tpu.plot import confusion_matrix, roc
        df = DataFrame({"y": np.array([0, 1, 1]),
                        "s": np.array([0.2, 0.7, 0.9]),
                        "yhat": np.array([0, 1, 0])})
        fig, ax = plt.subplots()
        confusion_matrix(df, "y", "yhat", ax=ax)
        roc(df, "y", "s", ax=ax)
        plt.close(fig)


class TestTPE:
    """search_strategy='tpe': Parzen-estimator proposals concentrate
    trials near what already scores well (beyond the reference's
    random/grid search)."""

    def test_sampler_concentrates_on_optimum(self):
        # pure sampler test on a known quadratic: after warmup, proposals
        # must cluster far closer to the optimum than the random phase
        from mmlspark_tpu.automl.hyperparam import RangeHyperParam
        from mmlspark_tpu.automl.tpe import TPESampler
        space = {"x": RangeHyperParam(0.0, 1.0)}
        s = TPESampler(space, seed=0, n_startup=8, maximize=False)
        early, late = [], []
        for i in range(60):
            (pm,) = s.propose(1)
            s.tell(pm, (pm["x"] - 0.3) ** 2)
            (early if i < 10 else late if i >= 50 else []).append(pm["x"])
        d = lambda xs: float(np.mean(np.abs(np.asarray(xs) - 0.3)))  # noqa: E731
        assert d(late) < 0.5 * d(early), (d(early), d(late))

    def test_categorical_and_log_dims(self):
        from mmlspark_tpu.automl.hyperparam import (DiscreteHyperParam,
                                                    RangeHyperParam)
        from mmlspark_tpu.automl.tpe import TPESampler
        space = {"lr": RangeHyperParam(1e-4, 1.0, is_log=True),
                 "kind": DiscreteHyperParam(["a", "b", "c"]),
                 "k": RangeHyperParam(1, 32, is_int=True)}
        s = TPESampler(space, seed=1, n_startup=6, maximize=True)
        # objective favors kind == "b" and lr near 1e-2
        for _ in range(40):
            (pm,) = s.propose(1)
            score = -abs(np.log10(pm["lr"]) + 2) + (1.0 if pm["kind"] == "b"
                                                    else 0.0)
            s.tell(pm, score)
        tail = s.propose(10)
        kinds = [p["kind"] for p in tail]
        assert kinds.count("b") >= 5
        assert all(isinstance(p["k"], int) and 1 <= p["k"] <= 32
                   for p in tail)
        assert all(1e-4 <= p["lr"] <= 1.0 for p in tail)

    def test_tune_hyperparameters_tpe_end_to_end(self):
        df = _cls_df(n=60)
        space = (HyperparamBuilder()
                 .add_hyperparam("learning_rate",
                                 RangeHyperParam(0.001, 0.5, is_log=True))
                 .add_hyperparam("max_iter", DiscreteHyperParam([50, 150]))
                 .build())
        tuner = TuneHyperparameters(
            model=LogisticRegression(), search_space=space,
            search_strategy="tpe", number_of_iterations=8,
            tpe_startup_trials=4, evaluation_metric="accuracy",
            label_col="label", parallelism=2, seed=5)
        best = tuner.fit(df)
        assert tuner.best_metric is not None and tuner.best_metric > 0.6
        assert set(tuner.best_params) == {"learning_rate", "max_iter"}
        assert "prediction" in best.transform(df).columns

    def test_tpe_rejects_grid_space(self):
        import pytest
        space = (HyperparamBuilder()
                 .add_hyperparam("a", DiscreteHyperParam([1, 2])).build())
        with pytest.raises(ValueError, match="tpe"):
            TuneHyperparameters(
                model=LogisticRegression(), search_space=GridSpace(space),
                search_strategy="tpe", label_col="label").fit(_cls_df(30))
