"""Beam search over the cached decoder (``zoo.transformer.generate_beam``).

Correctness is pinned exactly where it CAN be exact: with num_beams =
vocab and two generated tokens, the beam keeps every length-1 prefix, so
its answer must equal brute-force enumeration of all vocab^2
continuations; W=1 must equal greedy; and a wider beam can never score
worse than greedy on total log-probability. Static-shape invariants
(eos banking into the fixed (B, W) pool, batch independence under one
fused program) mirror the engine tests' style.
"""

import itertools

import numpy as np
import pytest

import jax.numpy as jnp
from mmlspark_tpu.models.zoo.transformer import (TransformerConfig,
                                                 generate_beam,
                                                 generate_cached,
                                                 init_transformer,
                                                 transformer_apply)

CFG = TransformerConfig(vocab=6, d_model=16, heads=2, layers=1, d_ff=32,
                        max_len=32, causal=True, norm="rmsnorm",
                        position="rope", dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_transformer(CFG, seed=0)


PROMPT = np.array([[1, 2, 3]])


def _seq_logprob(params, prompt_row, seq):
    from scipy.special import logsumexp
    ids = np.concatenate([prompt_row, np.asarray(seq, np.int64)])[None]
    h = transformer_apply(params, jnp.asarray(ids), CFG)
    logits = np.asarray(h.astype(jnp.float32) @ params["lm_head"]["w"])
    lp = 0.0
    for i in range(len(seq)):
        row = logits[0, len(prompt_row) + i - 1]
        lp += row[seq[i]] - logsumexp(row)
    return float(lp)


class TestBeamSearch:
    def test_w1_equals_greedy(self, params):
        beam, _ = generate_beam(params, PROMPT, CFG, max_new_tokens=5,
                                num_beams=1)
        greedy = generate_cached(params, PROMPT, CFG, max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(beam), np.asarray(greedy))

    def test_exact_at_full_width(self, params):
        # W = vocab keeps every length-1 prefix alive, so two-token beam
        # search must return the global argmax over all vocab^2 sequences
        best = max(itertools.product(range(CFG.vocab), repeat=2),
                   key=lambda s: _seq_logprob(params, PROMPT[0], list(s)))
        got, score = generate_beam(params, PROMPT, CFG, max_new_tokens=2,
                                   num_beams=CFG.vocab)
        assert tuple(int(t) for t in np.asarray(got)[0, 3:]) == best
        # reported score is the length-penalized mean (HF convention,
        # length_penalty=1 → sum/len)
        assert score[0] == pytest.approx(
            _seq_logprob(params, PROMPT[0], list(best)) / 2, rel=1e-4)

    def test_never_worse_than_greedy(self, params):
        greedy = generate_cached(params, PROMPT, CFG, max_new_tokens=5)
        g_lp = _seq_logprob(params, PROMPT[0],
                            list(np.asarray(greedy)[0, 3:]))
        beam, _ = generate_beam(params, PROMPT, CFG, max_new_tokens=5,
                                num_beams=4)
        b_lp = _seq_logprob(params, PROMPT[0],
                            list(np.asarray(beam)[0, 3:]))
        assert b_lp >= g_lp - 1e-5

    def test_eos_pads_tail(self, params):
        out, _ = generate_beam(params, PROMPT, CFG, max_new_tokens=6,
                               num_beams=4, eos_id=2)
        seq = [int(t) for t in np.asarray(out)[0, 3:]]
        if 2 in seq:
            i = seq.index(2)
            assert all(t == 2 for t in seq[i:])

    def test_eos_prefers_banked_hypothesis(self, params):
        # log-probs are negative, so score = sum / len**alpha with a
        # NEGATIVE alpha multiplies the (negative) sum by len**|alpha| —
        # longer sequences score strictly worse and the 1-token banked
        # eos hypothesis must win over every full-length live beam
        out, score = generate_beam(params, PROMPT, CFG, max_new_tokens=8,
                                   num_beams=CFG.vocab, eos_id=2,
                                   length_penalty=-4.0)
        seq = [int(t) for t in np.asarray(out)[0, 3:]]
        assert seq[0] == 2          # the 1-token eos hypothesis wins
        assert np.isfinite(float(score[0]))

    def test_first_step_eos_refills_live_beam(self, params):
        # eos = the argmax first token: it must BANK and the live slot
        # must refill from the next-best non-eos token (top-2W at step 0
        # too) — with a long-favoring penalty the live hypothesis wins,
        # which is impossible if the beam died at step 0
        greedy = generate_cached(params, PROMPT, CFG, max_new_tokens=1)
        eos = int(np.asarray(greedy)[0, 3])
        out, score = generate_beam(params, PROMPT, CFG, max_new_tokens=4,
                                   num_beams=1, eos_id=eos,
                                   length_penalty=4.0)
        seq = [int(t) for t in np.asarray(out)[0, 3:]]
        assert seq[0] != eos
        assert np.isfinite(float(score[0]))

    def test_batch_rows_independent(self, params):
        pb = np.array([[1, 2, 3], [4, 5, 1]])
        both, _ = generate_beam(params, pb, CFG, max_new_tokens=4,
                                num_beams=3)
        for r in range(2):
            solo, _ = generate_beam(params, pb[r:r + 1], CFG,
                                    max_new_tokens=4, num_beams=3)
            np.testing.assert_array_equal(np.asarray(both)[r],
                                          np.asarray(solo)[0])

    def test_validation(self, params):
        with pytest.raises(ValueError, match="num_beams"):
            generate_beam(params, PROMPT, CFG, num_beams=0)
        with pytest.raises(ValueError, match="vocab"):
            generate_beam(params, PROMPT, CFG, num_beams=CFG.vocab + 1)
        with pytest.raises(ValueError, match="causal"):
            generate_beam(params, PROMPT, CFG._replace(causal=False))
