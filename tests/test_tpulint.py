"""tpulint: per-rule positive/negative fixtures, suppression, baseline,
CLI exit codes, and a self-scan of the shipped tree.

Each rule gets at least one fixture that MUST fire and one that MUST stay
quiet — the quiet ones encode the false-positive fixes (static_argnames,
.shape reads, non-device dirs) so a regression re-introducing the noise
fails here, not in CI triage.
"""

import io
import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.tpulint import baseline as baseline_mod
from tools.tpulint.cli import main as cli_main
from tools.tpulint.core import (analyze_project, analyze_source, fingerprint,
                                load_project)


def codes(findings):
    return [f.rule for f in findings]


def run_fixture(source, relpath="pkg/mod.py", keep_suppressed=False):
    findings, suppressed = analyze_source(
        textwrap.dedent(source), relpath, keep_suppressed=keep_suppressed)
    return findings, suppressed


# ---------------------------------------------------------------------------
# TPU001 host-sync-in-jit


def test_tpu001_device_get_in_jit_fires():
    findings, _ = run_fixture("""\
        import jax

        @jax.jit
        def f(x):
            return jax.device_get(x)
        """)
    assert "TPU001" in codes(findings)
    (f,) = [f for f in findings if f.rule == "TPU001"]
    assert f.severity == "error" and f.line == 5


def test_tpu001_float_of_tracer_fires_but_shape_read_does_not():
    findings, _ = run_fixture("""\
        import jax

        @jax.jit
        def f(x):
            return float(x) + 1.0
        """)
    assert "TPU001" in codes(findings)

    findings, _ = run_fixture("""\
        import jax

        @jax.jit
        def f(x):
            scale = float(x.shape[0])
            return x * scale
        """)
    assert "TPU001" not in codes(findings)


def test_tpu001_static_argname_is_not_a_tracer():
    # the trees.py ff_bynode false positive: int(round(...)) over a static
    findings, _ = run_fixture("""\
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("k",))
        def f(x, k):
            kk = max(1, int(round(k * 0.5)))
            return x[:kk]
        """)
    assert "TPU001" not in codes(findings)


def test_tpu001_per_iteration_fence_warns():
    findings, _ = run_fixture("""\
        import jax

        def run(fn, xs):
            outs = []
            for x in xs:
                out = fn(x)
                jax.block_until_ready(out)
                outs.append(out)
            return outs
        """)
    hits = [f for f in findings if f.rule == "TPU001"]
    assert hits and hits[0].severity == "warning"


def test_tpu001_fence_outside_loop_is_quiet():
    findings, _ = run_fixture("""\
        import jax

        def run(fn, xs):
            outs = [fn(x) for x in xs]
            jax.block_until_ready(outs)
            return outs
        """)
    assert "TPU001" not in codes(findings)


# ---------------------------------------------------------------------------
# TPU002 jit-in-loop


def test_tpu002_jit_inside_loop_fires():
    findings, _ = run_fixture("""\
        import jax

        def run(fns, x):
            for fn in fns:
                jf = jax.jit(fn)
                x = jf(x)
            return x
        """)
    assert "TPU002" in codes(findings)


def test_tpu002_jit_before_loop_is_quiet():
    findings, _ = run_fixture("""\
        import jax

        def run(fn, xs):
            jf = jax.jit(fn)
            out = [jf(x) for x in xs]
            return out
        """)
    assert "TPU002" not in codes(findings)


def test_tpu002_loop_header_does_not_count_as_body():
    # the jit call produces the iterable ONCE; only the body repeats
    findings, _ = run_fixture("""\
        import jax

        def run(fn, x):
            for y in jax.jit(fn)(x):
                print(y)
        """)
    assert "TPU002" not in codes(findings)


# ---------------------------------------------------------------------------
# TPU003 tracer-branch


def test_tpu003_tracer_if_fires():
    findings, _ = run_fixture("""\
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
        """)
    assert "TPU003" in codes(findings)


def test_tpu003_shape_branch_is_quiet():
    findings, _ = run_fixture("""\
        import jax

        @jax.jit
        def f(x):
            if x.ndim > 1:
                return x.sum(axis=-1)
            return x
        """)
    assert "TPU003" not in codes(findings)


def test_tpu003_static_argnames_via_name_wrap():
    # the linear.py pattern: statics declared at the jax.jit(...) wrap site
    findings, _ = run_fixture("""\
        import jax

        def _run(x, kind):
            if kind == "logistic":
                return x * 2
            return x

        run = jax.jit(_run, static_argnames=("kind",))
        """)
    assert "TPU003" not in codes(findings)


def test_tpu003_while_tracer_test_fires():
    findings, _ = run_fixture("""\
        import jax

        @jax.jit
        def f(x):
            while x > 0:
                x = x - 1
            return x
        """)
    assert "TPU003" in codes(findings)


# ---------------------------------------------------------------------------
# TPU004 dtype-leak (device dirs only)


def test_tpu004_f64_in_ops_dir_fires():
    findings, _ = run_fixture("""\
        import numpy as np

        def pad(v):
            return np.asarray(v, dtype=np.float64)
        """, relpath="pkg/ops/pad.py")
    assert "TPU004" in codes(findings)


def test_tpu004_same_source_outside_device_dirs_is_quiet():
    findings, _ = run_fixture("""\
        import numpy as np

        def pad(v):
            return np.asarray(v, dtype=np.float64)
        """, relpath="pkg/metrics/pad.py")
    assert "TPU004" not in codes(findings)


def test_tpu004_dtypeless_asarray_in_device_dir_fires():
    findings, _ = run_fixture("""\
        import numpy as np

        def coerce(v):
            return np.asarray(v)
        """, relpath="pkg/nn/x.py")
    assert "TPU004" in codes(findings)


def test_tpu004_dtype_comparison_is_quiet():
    # `arr.dtype == np.float64` is a CHECK, not a leak
    findings, _ = run_fixture("""\
        import numpy as np

        def coerce(arr):
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            return arr
        """, relpath="pkg/ops/x.py")
    assert "TPU004" not in codes(findings)


def test_tpu004_sci_literal_in_jit_is_info():
    findings, _ = run_fixture("""\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.maximum(x, 1e-38)
        """, relpath="pkg/ops/x.py")
    hits = [f for f in findings if f.rule == "TPU004"]
    assert hits and all(f.severity == "info" for f in hits)


# ---------------------------------------------------------------------------
# TPU005 op-registry drift (project scope, tmp packages)


def _write_pkg(tmp_path, files):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return str(tmp_path)


CONVERT_SRC = """\
    OP_HANDLERS = {}

    def register_op(name):
        def deco(fn):
            OP_HANDLERS[name] = fn
            return fn
        return deco

    @register_op("Add")
    def _add(node, inputs, ctx):
        return inputs

    from . import extra
    """


def _scan_pkg(root):
    project = load_project([os.path.join(root, "pkg")], root)
    from tools.tpulint.core import all_rules
    return analyze_project(project, rules=all_rules(["TPU005"]))[0]


def test_tpu005_duplicate_registration_fires(tmp_path):
    root = _write_pkg(tmp_path, {
        "convert.py": CONVERT_SRC,
        "extra.py": """\
            from .convert import register_op

            @register_op("Add")
            def _add2(node, inputs, ctx):
                return inputs
            """,
    })
    findings = _scan_pkg(root)
    assert any(f.rule == "TPU005" and "Add" in f.message for f in findings)


def test_tpu005_distinct_ops_are_quiet(tmp_path):
    root = _write_pkg(tmp_path, {
        "convert.py": CONVERT_SRC,
        "extra.py": """\
            from .convert import register_op

            @register_op("Mul")
            def _mul(node, inputs, ctx):
                return inputs
            """,
    })
    assert not _scan_pkg(root)


def test_tpu005_dangling_handler_name_fires(tmp_path):
    root = _write_pkg(tmp_path, {
        "convert.py": CONVERT_SRC.replace(
            "from . import extra",
            'OP_HANDLERS["Mul"] = _missing_handler\n    from . import extra'),
        "extra.py": "from .convert import register_op\n",
    })
    findings = _scan_pkg(root)
    assert any(f.rule == "TPU005" and "_missing_handler" in f.message
               for f in findings)


def test_tpu005_unimported_registering_module_fires(tmp_path):
    root = _write_pkg(tmp_path, {
        "convert.py": CONVERT_SRC.replace("from . import extra\n", ""),
        "extra.py": """\
            from .convert import register_op

            @register_op("Mul")
            def _mul(node, inputs, ctx):
                return inputs
            """,
    })
    findings = _scan_pkg(root)
    assert any(f.rule == "TPU005" and f.path.endswith("extra.py")
               and "never imported" in f.message for f in findings)


# ---------------------------------------------------------------------------
# TPU006 stub drift (project scope, module + .pyi pair)


def _scan_stub(tmp_path, mod_src, stub_src):
    (tmp_path / "mod.py").write_text(textwrap.dedent(mod_src))
    (tmp_path / "mod.pyi").write_text(textwrap.dedent(stub_src))
    project = load_project([str(tmp_path)], str(tmp_path))
    from tools.tpulint.core import all_rules
    return analyze_project(project, rules=all_rules(["TPU006"]))[0]


def test_tpu006_stub_only_name_fires(tmp_path):
    findings = _scan_stub(
        tmp_path,
        "def foo():\n    return 1\n",
        "def foo() -> int: ...\ndef bar() -> int: ...\n")
    assert any(f.rule == "TPU006" and "bar" in f.message for f in findings)


def test_tpu006_stub_subset_is_quiet(tmp_path):
    findings = _scan_stub(
        tmp_path,
        "def foo():\n    return 1\n\ndef extra():\n    return 2\n",
        "def foo() -> int: ...\n")
    assert not findings


# ---------------------------------------------------------------------------
# TPU007 adhoc-telemetry

_TIMER_CLASS = """\
    import time

    class Prof:
        def __init__(self):
            self.totals = {}

        def mark(self, name):
            now = time.perf_counter()
            self.totals[name] = self.totals.get(name, 0.0) + (now - self._t0)
            self._t0 = now
    """


def test_tpu007_adhoc_timer_class_fires_inside_package():
    findings, _ = run_fixture(_TIMER_CLASS, relpath="mmlspark_tpu/x/mod.py")
    assert "TPU007" in codes(findings)
    (f,) = [f for f in findings if f.rule == "TPU007"]
    assert f.severity == "warning" and "Prof" in f.message


def test_tpu007_quiet_outside_package_and_in_observability():
    findings, _ = run_fixture(_TIMER_CLASS, relpath="scripts/mod.py")
    assert "TPU007" not in codes(findings)
    findings, _ = run_fixture(
        _TIMER_CLASS, relpath="mmlspark_tpu/observability/registry.py")
    assert "TPU007" not in codes(findings)


def test_tpu007_quiet_when_module_mirrors_into_registry():
    findings, _ = run_fixture("""\
        import time
        from ..observability import histogram as _metric_histogram

        class Prof:
            def mark(self, name):
                now = time.perf_counter()
                self.totals[name] = self.totals.get(name, 0.0) + (now - self._t0)
        """, relpath="mmlspark_tpu/x/mod.py")
    assert "TPU007" not in codes(findings)


def test_tpu007_quiet_on_plain_timestamp_store():
    # a heartbeat/last-seen store reads the clock but accumulates nothing —
    # the rule requires delta arithmetic on a clock value
    findings, _ = run_fixture("""\
        import time

        class Registry:
            def register(self, worker_id, address):
                now = time.monotonic()
                self._workers[worker_id] = {"address": address,
                                            "last_seen": now}
        """, relpath="mmlspark_tpu/x/mod.py")
    assert "TPU007" not in codes(findings)


def test_tpu007_suppressible():
    findings, suppressed = run_fixture("""\
        import time

        class Watch:
            def stop(self):
                # tpulint: disable=TPU007 — reference-parity wall timer
                self.elapsed_ns += time.perf_counter_ns() - self._start
        """, relpath="mmlspark_tpu/x/mod.py", keep_suppressed=True)
    assert "TPU007" not in codes(findings)
    assert "TPU007" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU008 adhoc-id-minting


def test_tpu008_request_id_uuid4_fires():
    findings, _ = run_fixture("""\
        import uuid

        def enqueue(self, request):
            cached = CachedRequest(uuid.uuid4().hex, self._epoch, request)
            return cached
        """, relpath="mmlspark_tpu/serving/server.py")
    assert "TPU008" in codes(findings)
    (f,) = [f for f in findings if f.rule == "TPU008"]
    assert "new_request_id" in f.message


def test_tpu008_catches_from_import_and_trace_names():
    findings, _ = run_fixture("""\
        from uuid import uuid4

        def open_trace():
            trace_id = uuid4().hex
            return trace_id
        """, relpath="mmlspark_tpu/x/mod.py")
    assert "TPU008" in codes(findings)


def test_tpu008_quiet_on_non_id_uuid4_uses():
    # model artifact / run ids are not request-flow ids — the regexp gate
    # (request|trace|span) keeps mlflow-style minting quiet
    findings, _ = run_fixture("""\
        import uuid

        def log_model(model):
            model_uuid = uuid.uuid4().hex
            run_id = uuid.uuid4().hex[:12]
            return model_uuid, run_id
        """, relpath="mmlspark_tpu/x/mlflow.py")
    assert "TPU008" not in codes(findings)


def test_tpu008_quiet_in_tracing_module_and_outside_package():
    src = """\
        import uuid

        def new_request_id():
            return uuid.uuid4().hex
        """
    findings, _ = run_fixture(
        src, relpath="mmlspark_tpu/observability/tracing.py")
    assert "TPU008" not in codes(findings)
    findings, _ = run_fixture(src, relpath="scripts/tool.py")
    assert "TPU008" not in codes(findings)


def test_tpu008_suppressible():
    findings, suppressed = run_fixture("""\
        import uuid

        def mint():
            # tpulint: disable=TPU008 — wire-compat with legacy clients
            request_id = uuid.uuid4().hex
            return request_id
        """, relpath="mmlspark_tpu/x/mod.py", keep_suppressed=True)
    assert "TPU008" not in codes(findings)
    assert "TPU008" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU009 adhoc-resilience


def test_tpu009_adhoc_retry_loop_fires():
    findings, _ = run_fixture("""\
        import time

        def fetch(url):
            for attempt in range(5):
                try:
                    return get(url)
                except OSError:
                    time.sleep(0.5 * attempt)
        """, relpath="mmlspark_tpu/serving/mod.py")
    assert "TPU009" in codes(findings)
    (f,) = [f for f in findings if f.rule == "TPU009"]
    assert "RetryPolicy" in f.message


def test_tpu009_sleep_plus_continue_fires():
    findings, _ = run_fixture("""\
        import time

        def poll(q):
            while True:
                if not q.ready():
                    time.sleep(0.1)
                    continue
                return q.pop()
        """, relpath="mmlspark_tpu/io/http/mod.py")
    assert "TPU009" in codes(findings)


def test_tpu009_swallowed_exception_fires():
    findings, _ = run_fixture("""\
        def heartbeat(self):
            try:
                self.ping()
            except Exception:
                pass
        """, relpath="mmlspark_tpu/serving/mod.py")
    assert "TPU009" in codes(findings)
    (f,) = [f for f in findings if f.rule == "TPU009"]
    assert "log_event" in f.message


def test_tpu009_bare_except_pass_fires():
    findings, _ = run_fixture("""\
        def close(self):
            try:
                self.sock.close()
            except:
                pass
        """, relpath="mmlspark_tpu/io/mod.py")
    assert "TPU009" in codes(findings)


def test_tpu009_quiet_on_typed_or_logged_except():
    findings, _ = run_fixture("""\
        import logging

        def close(self):
            try:
                self.sock.close()
            except OSError:
                pass
            try:
                self.flush()
            except Exception:
                logging.warning("flush failed")
        """, relpath="mmlspark_tpu/serving/mod.py")
    assert "TPU009" not in codes(findings)


def test_tpu009_quiet_on_event_wait_backoff_and_plain_loops():
    # Event.wait-based backoff is interruptible (not time.sleep) and a
    # sleep in a loop without catch/continue is just pacing, not retry
    findings, _ = run_fixture("""\
        import time

        def run(self):
            while not self._stop.is_set():
                self.step()
                self._stop.wait(0.5)

        def pace(items):
            for it in items:
                emit(it)
                time.sleep(0.01)
        """, relpath="mmlspark_tpu/serving/mod.py")
    assert "TPU009" not in codes(findings)


def test_tpu009_sleep_in_nested_def_does_not_taint_loop():
    findings, _ = run_fixture("""\
        import time

        def build(jobs):
            for j in jobs:
                def waiter():
                    time.sleep(1.0)
                try:
                    j.submit(waiter)
                except ValueError:
                    record(j)
        """, relpath="mmlspark_tpu/serving/mod.py")
    assert "TPU009" not in codes(findings)


def test_tpu009_scoped_to_serving_and_io():
    src = """\
        import time

        def fetch(url):
            for attempt in range(3):
                try:
                    return get(url)
                except OSError:
                    time.sleep(1)
        """
    findings, _ = run_fixture(src, relpath="mmlspark_tpu/ops/x.py")
    assert "TPU009" not in codes(findings)
    # the reliability package implements the primitives — exempt
    findings, _ = run_fixture(src, relpath="mmlspark_tpu/reliability/policy.py")
    assert "TPU009" not in codes(findings)


def test_tpu009_suppressible():
    findings, suppressed = run_fixture("""\
        import time

        def fetch(url):
            # reference-parity ladder, semantics must not change
            while True:  # tpulint: disable=TPU009
                try:
                    return get(url)
                except OSError:
                    time.sleep(1)
        """, relpath="mmlspark_tpu/io/http/mod.py", keep_suppressed=True)
    assert "TPU009" not in codes(findings)
    assert "TPU009" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU010 host-roundtrip


def test_tpu010_asarray_on_sliced_input_in_transform_fires():
    findings, _ = run_fixture("""\
        import numpy as np

        class MyStage(Transformer):
            def _transform(self, df):
                x = np.asarray(df["x"][0:4])
                return df.with_column("y", x * 2)
        """)
    (f,) = [f for f in findings if f.rule == "TPU010"]
    assert f.severity == "warning" and f.line == 5


def test_tpu010_device_get_in_nested_closure_fires():
    # the per-batch closures a _transform builds ARE the hot path
    findings, _ = run_fixture("""\
        import jax

        class MyModel(core.pipeline.Model):
            def _transform(self, df):
                def coerce(sl):
                    return jax.device_get(df["x"][sl])
                return self._run(coerce)
        """)
    assert "TPU010" in codes(findings)


def test_tpu010_quiet_outside_stage_hot_paths():
    # not a stage class: quiet
    findings, _ = run_fixture("""\
        import numpy as np

        class Helper:
            def _transform(self, df):
                return np.asarray(df["x"][0:4])
        """)
    assert "TPU010" not in codes(findings)
    # a stage class, but not a transform method: quiet
    findings, _ = run_fixture("""\
        import numpy as np

        class MyStage(Transformer):
            def _fit(self, df):
                return np.asarray(df["x"][0:4])
        """)
    assert "TPU010" not in codes(findings)
    # unsubscripted arg (whole-object coercion, not a sliced input): quiet
    findings, _ = run_fixture("""\
        import numpy as np

        class MyStage(Transformer):
            def _transform(self, df):
                return np.asarray(meta_vector)
        """)
    assert "TPU010" not in codes(findings)


def test_tpu010_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        import numpy as np

        class MyStage(Transformer):
            def _transform(self, df):
                # label-table lookup: host-only metadata, never resident
                # tpulint: disable=TPU010
                idx = np.asarray([t[v] for v in df["y"][:]])
                return df.with_column("i", idx)
        """, keep_suppressed=True)
    assert "TPU010" not in codes(findings)
    assert "TPU010" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU011 adhoc-slo-window


def test_tpu011_sorted_quantile_index_fires():
    findings, _ = run_fixture("""\
        lat = []

        def report():
            lat.sort()
            return sorted(lat)[int(0.99 * len(lat))]
        """, relpath="mmlspark_tpu/serving/stats.py")
    (f,) = [f for f in findings if f.rule == "TPU011"]
    assert f.severity == "warning" and f.line == 5


def test_tpu011_timestamp_prune_loop_fires():
    findings, _ = run_fixture("""\
        import collections, time

        events = collections.deque()

        def observe(now):
            events.append(now)
            while now - events[0] > 60.0:
                events.popleft()
        """, relpath="mmlspark_tpu/serving/stats.py")
    (f,) = [f for f in findings if f.rule == "TPU011"]
    assert f.line == 7


def test_tpu011_quiet_in_observability_and_outside_package():
    src = """\
        lat = []

        def report():
            return sorted(lat)[int(0.99 * len(lat))]
        """
    # the SLO engine itself is the sanctioned home for window math
    findings, _ = run_fixture(
        src, relpath="mmlspark_tpu/observability/slo.py")
    assert "TPU011" not in codes(findings)
    # scripts/tools/tests are out of scope
    findings, _ = run_fixture(src, relpath="scripts/report.py")
    assert "TPU011" not in codes(findings)


def test_tpu011_quiet_on_benign_lookalikes():
    # capacity prune (no timestamp-age test) and a fraction-scaled size
    # (no len() in the same index) are not rolling-window math
    findings, _ = run_fixture("""\
        import collections

        q = collections.deque()
        F = 128

        def trim(cap):
            while len(q) > cap:
                q.popleft()
            return buckets[int(0.75 * F)]
        """, relpath="mmlspark_tpu/serving/stats.py")
    assert "TPU011" not in codes(findings)


def test_tpu011_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        def report(lat):
            # one-shot offline report, not a serving-path window
            # tpulint: disable=TPU011
            return sorted(lat)[int(0.5 * len(lat))]
        """, relpath="mmlspark_tpu/tuning/offline.py",
        keep_suppressed=True)
    assert "TPU011" not in codes(findings)
    assert "TPU011" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU015 unbounded-label-cardinality


def test_tpu015_request_derived_label_fires():
    findings, _ = run_fixture("""\
        from ..observability import counter

        M_REQS = counter("x_requests_total", "requests", labelnames=("path",))

        def handle(req):
            M_REQS.inc(path=req.url)
        """, relpath="mmlspark_tpu/serving/handlers.py")
    (f,) = [f for f in findings if f.rule == "TPU015"]
    assert f.severity == "warning" and f.line == 6
    assert "path" in f.message and "url" in f.message


def test_tpu015_header_value_into_labels_chain_fires():
    findings, _ = run_fixture("""\
        def observe(metric, request):
            metric.labels(tenant=request.headers.get("x-t")).observe(0.5)
        """, relpath="mmlspark_tpu/io/http/sink.py")
    assert "TPU015" in codes(findings)


def test_tpu015_classify_route_is_sanctioned():
    findings, _ = run_fixture("""\
        from ..observability import classify_route, counter

        M_REQS = counter("x_requests_total", "requests", labelnames=("route",))

        def handle(req):
            M_REQS.inc(route=classify_route(req.url))
        """, relpath="mmlspark_tpu/serving/handlers.py")
    assert "TPU015" not in codes(findings)


def test_tpu015_quiet_on_bounded_values_and_non_metric_set():
    # bounded label values (no request-derived identifier) stay quiet,
    # and a PipelineStage-style .set(url=...) param setter is not a metric
    findings, _ = run_fixture("""\
        M_TICKS = object()

        def tick(stage, impl, url):
            M_TICKS.inc(1, impl=impl)
            stage.set(url=url, timeout=30)
        """, relpath="mmlspark_tpu/serving/engine.py")
    assert "TPU015" not in codes(findings)


def test_tpu015_quiet_inside_observability_and_outside_package():
    src = """\
        def expose(m_hits, req):
            m_hits.inc(path=req.url)
        """
    # the observability package itself is the sanctioned home
    findings, _ = run_fixture(
        src, relpath="mmlspark_tpu/observability/exposition.py")
    assert "TPU015" not in codes(findings)
    findings, _ = run_fixture(src, relpath="scripts/report.py")
    assert "TPU015" not in codes(findings)


def test_tpu015_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        def record(m_debug, req):
            # bounded in practice: the bench harness replays 3 fixed URLs
            # tpulint: disable=TPU015
            m_debug.inc(path=req.url)
        """, relpath="mmlspark_tpu/serving/bench_hooks.py",
        keep_suppressed=True)
    assert "TPU015" not in codes(findings)
    assert "TPU015" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU016 adhoc-hash-routing


def test_tpu016_hash_modulo_peers_fires():
    findings, _ = run_fixture("""\
        def pick(self, key):
            return self._peers[hash(key) % len(self._peers)]
        """, relpath="mmlspark_tpu/serving/router.py")
    (f,) = [f for f in findings if f.rule == "TPU016"]
    assert f.severity == "warning"
    assert "ConsistentHashRing" in f.message


def test_tpu016_hexdigest_modulo_workers_fires():
    findings, _ = run_fixture("""\
        import hashlib

        def owner(key, workers):
            return workers[
                int(hashlib.sha1(key.encode()).hexdigest(), 16)
                % len(workers)]
        """, relpath="mmlspark_tpu/serving/placement.py")
    assert codes(findings).count("TPU016") == 1


def test_tpu016_quiet_for_round_robin_and_non_peer_pools():
    findings, _ = run_fixture("""\
        def next_peer(self):
            # rotation is not placement: no key is being mapped
            self._rr += 1
            return self._peers[self._rr % len(self._peers)]

        def bucket(self, key):
            # hash modulo a NON-peer collection (histogram buckets)
            return self.buckets[hash(key) % len(self.buckets)]
        """, relpath="mmlspark_tpu/serving/scheduler.py")
    assert "TPU016" not in codes(findings)


def test_tpu016_quiet_in_sanctioned_modules_and_outside_package():
    src = """\
        def _point(self, key):
            return hash(key) % len(self._members)
        """
    # admission.py owns ConsistentHashRing — its internals are exempt
    findings, _ = run_fixture(
        src, relpath="mmlspark_tpu/serving/admission.py")
    assert "TPU016" not in codes(findings)
    findings, _ = run_fixture(
        src, relpath="mmlspark_tpu/serving/registry.py")
    assert "TPU016" not in codes(findings)
    findings, _ = run_fixture(src, relpath="tools/somewhere.py")
    assert "TPU016" not in codes(findings)


def test_tpu016_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        def shard(self, key, nodes):
            # test-only deterministic placement for the fixture cluster
            # tpulint: disable=TPU016
            return nodes[hash(key) % len(nodes)]
        """, relpath="mmlspark_tpu/serving/testkit.py",
        keep_suppressed=True)
    assert "TPU016" not in codes(findings)
    assert "TPU016" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU017 unsharded-pallas-call


def test_tpu017_bare_pallas_in_mesh_jit_fires():
    findings, _ = run_fixture("""\
        import jax
        from jax.experimental import pallas as pl
        from jax.sharding import Mesh

        @jax.jit
        def run(mesh: Mesh, x):
            return pl.pallas_call(kern, out_shape=x)(x)
        """)
    (f,) = [f for f in findings if f.rule == "TPU017"]
    assert f.severity == "warning"
    assert "shard_map" in f.message


def test_tpu017_pallas_via_helper_fires():
    # the hazard hides one call deep: the jit entry takes the mesh, a
    # plain helper owns the pallas_call — reachability must catch it
    findings, _ = run_fixture("""\
        import jax
        from jax.experimental import pallas as pl

        def attend(x):
            return pl.pallas_call(kern, out_shape=x)(x)

        @jax.jit
        def serve(mesh, x):
            return attend(x)
        """)
    assert codes(findings).count("TPU017") == 1


def test_tpu017_sharding_annotation_counts_as_mesh():
    findings, _ = run_fixture("""\
        import jax
        from jax.experimental import pallas as pl
        from jax.sharding import NamedSharding

        @jax.jit
        def run(spec: NamedSharding, x):
            return pl.pallas_call(kern, out_shape=x)(x)
        """)
    assert codes(findings).count("TPU017") == 1


def test_tpu017_quiet_when_mounted_or_unmeshed():
    findings, _ = run_fixture("""\
        import jax
        from jax.experimental import pallas as pl

        @jax.jit
        def mounted(mesh, x):
            def shard(xs):
                return pl.pallas_call(kern, out_shape=xs)(xs)
            return jax.shard_map(shard, mesh=mesh, in_specs=None,
                                 out_specs=None)(x)

        @jax.jit
        def single_chip(x):
            return pl.pallas_call(kern, out_shape=x)(x)
        """)
    assert "TPU017" not in codes(findings)


def test_tpu017_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        import jax
        from jax.experimental import pallas as pl

        @jax.jit
        def run(mesh, x):
            # single-device submesh by contract here
            # tpulint: disable=TPU017
            return pl.pallas_call(kern, out_shape=x)(x)
        """, keep_suppressed=True)
    assert "TPU017" not in codes(findings)
    assert "TPU017" in codes(suppressed)


# ---------------------------------------------------------------------------
# Suppression


def test_inline_suppression():
    findings, suppressed = run_fixture("""\
        import jax

        @jax.jit
        def f(x):
            return jax.device_get(x)  # tpulint: disable=TPU001
        """, keep_suppressed=True)
    assert "TPU001" not in codes(findings)
    assert "TPU001" in codes(suppressed)


def test_comment_block_suppression_spans_multiple_lines():
    findings, suppressed = run_fixture("""\
        import jax

        @jax.jit
        def f(x):
            # tpulint: disable=TPU001 — the fence IS the measurement
            # in this opt-in profiling path
            return jax.device_get(x)
        """, keep_suppressed=True)
    assert "TPU001" not in codes(findings)
    assert "TPU001" in codes(suppressed)


def test_file_level_suppression():
    findings, suppressed = run_fixture("""\
        # tpulint: disable-file=TPU004 — host-side exact math by design
        import numpy as np

        def a(v):
            return np.asarray(v, dtype=np.float64)

        def b(v):
            return np.asarray(v)
        """, relpath="pkg/ops/x.py", keep_suppressed=True)
    assert "TPU004" not in codes(findings)
    assert codes(suppressed).count("TPU004") >= 2


def test_suppression_is_rule_specific():
    findings, _ = run_fixture("""\
        import jax

        @jax.jit
        def f(x):
            return jax.device_get(x)  # tpulint: disable=TPU002
        """)
    assert "TPU001" in codes(findings)  # wrong code: does not suppress


# ---------------------------------------------------------------------------
# Baseline


def _one_finding():
    findings, _ = run_fixture("""\
        import jax

        @jax.jit
        def f(x):
            return jax.device_get(x)
        """)
    return [f for f in findings if f.rule == "TPU001"]


def test_baseline_roundtrip(tmp_path):
    findings = _one_finding()
    path = str(tmp_path / "baseline.json")
    baseline_mod.dump(findings, path)
    known = baseline_mod.load(path)
    assert known == {fingerprint(findings[0]): 1}
    new, old, stale = baseline_mod.apply(findings, known)
    assert not new and old == findings and not stale


def test_baseline_is_line_number_free():
    # shifting the finding down a line must not invalidate the baseline
    f = _one_finding()[0]
    assert str(f.line) not in fingerprint(f).split("::")[0]
    shifted = run_fixture("""\
        import jax


        @jax.jit
        def f(x):
            return jax.device_get(x)
        """)[0]
    shifted = [x for x in shifted if x.rule == "TPU001"]
    assert fingerprint(shifted[0]) == fingerprint(f)


def test_baseline_count_budget_and_stale(tmp_path):
    findings = _one_finding()
    known = dict(baseline_mod.counts(findings))
    known["gone.py::TPU001::x"] = 2
    # duplicate the finding: budget of 1 covers only one occurrence
    new, old, stale = baseline_mod.apply(findings * 2, known)
    assert len(new) == 1 and len(old) == 1
    assert "gone.py::TPU001::x" in stale


def test_baseline_load_rejects_bad_version(tmp_path):
    path = tmp_path / "b.json"
    path.write_text(json.dumps({"version": 99, "fingerprints": {}}))
    try:
        baseline_mod.load(str(path))
    except ValueError:
        pass
    else:
        raise AssertionError("expected ValueError on unknown version")


# ---------------------------------------------------------------------------
# ---------------------------------------------------------------------------
# TPU018 unscaled-quant-cast


def test_tpu018_bare_int8_cast_on_kv_fires():
    findings, _ = run_fixture("""\
        import jax.numpy as jnp

        def write_rows(pool, k_new):
            return pool.at[0].set(k_new.astype(jnp.int8))
        """, relpath="mmlspark_tpu/serving/pool.py")
    (f,) = [f for f in findings if f.rule == "TPU018"]
    assert f.severity == "warning"
    assert "quantize_kv" in f.message


def test_tpu018_convert_element_type_on_cache_fires():
    findings, _ = run_fixture("""\
        import jax
        import jax.numpy as jnp

        def stash(cache_rows):
            return jax.lax.convert_element_type(cache_rows,
                                                jnp.float8_e4m3fn)
        """, relpath="mmlspark_tpu/serving/pool.py")
    assert codes(findings).count("TPU018") == 1


def test_tpu018_quiet_on_uint8_and_unrelated_names():
    # the dense image ingest column is raw bytes (uint8 is not a scaled
    # encoding), and int8 casts on non-KV tensors are out of scope
    findings, _ = run_fixture("""\
        import jax.numpy as jnp

        def ingest(img_batch):
            return img_batch.astype(jnp.uint8)

        def labels_to_i8(y):
            return y.astype(jnp.int8)
        """, relpath="mmlspark_tpu/image/io.py")
    assert "TPU018" not in codes(findings)


def test_tpu018_sanctioned_helper_module_exempt():
    findings, _ = run_fixture("""\
        import jax.numpy as jnp

        def quantize_kv(x, store_dtype):
            scale = jnp.max(jnp.abs(x), axis=-1) / 127.0
            return (x / scale[..., None]).astype(jnp.int8), scale
        """, relpath="mmlspark_tpu/ops/kv_quant.py")
    assert "TPU018" not in codes(findings)


def test_tpu018_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        import jax.numpy as jnp

        def debug_dump(k_rows):
            # lossy by design: a debug histogram, never read back
            # tpulint: disable=TPU018
            return k_rows.astype(jnp.int8)
        """, relpath="mmlspark_tpu/serving/pool.py",
        keep_suppressed=True)
    assert "TPU018" not in codes(findings)
    assert "TPU018" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU019 unknown-mesh-axis (sharding.py)


def test_tpu019_axis_typo_fires():
    findings, _ = run_fixture("""\
        import jax
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("dp", "tp"))
        row = P("dp", None)
        bad = P("tpp", None)
        """)
    (f,) = [f for f in findings if f.rule == "TPU019"]
    assert f.severity == "error"
    assert "'tpp'" in f.message and "dp" in f.message


def test_tpu019_quiet_when_no_mesh_constructed():
    # single-device trees never define a vocabulary; stay silent rather
    # than flag every axis string in sight
    findings, _ = run_fixture("""\
        from jax.sharding import PartitionSpec as P

        spec = P("model")
        """)
    assert "TPU019" not in codes(findings)


def test_tpu019_vocabulary_sources():
    # make_mesh dict keys, mesh.shape.get probes, and canonical
    # mesh_shape() strings all feed the axis vocabulary
    findings, _ = run_fixture("""\
        from mmlspark_tpu.parallel.mesh import make_mesh, mesh_shape
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh({"dp": 4})
        tp = mesh.shape.get("tp", 1)

        def route(m):
            if mesh_shape(m) == "dp4xsp2":
                return P("sp")
            return P("dp", "tp")
        """)
    assert "TPU019" not in codes(findings)


def test_tpu019_collective_axis_name_fires():
    findings, _ = run_fixture("""\
        import jax
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()), axis_names=("data",))

        def allreduce(x):
            return jax.lax.psum(x, axis_name="dta")
        """)
    assert codes(findings).count("TPU019") == 1


def test_tpu019_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        import jax
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        # axis exists only on the pod config loaded at runtime
        # tpulint: disable=TPU019
        wide = P("pod")
        """, keep_suppressed=True)
    assert "TPU019" not in codes(findings)
    assert "TPU019" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU020 spec-rank-mismatch


def test_tpu020_in_specs_arity_fires():
    findings, _ = run_fixture("""\
        import jax
        from jax.sharding import PartitionSpec as P

        def body(x):
            return x

        def mount(mesh):
            return jax.shard_map(body, mesh=mesh,
                                 in_specs=(P("dp"), P()),
                                 out_specs=P())
        """)
    (f,) = [f for f in findings if f.rule == "TPU020"]
    assert f.severity == "error"
    assert "binds 1..1" in f.message


def test_tpu020_quiet_through_partial_binding():
    # the pipeline.py idiom: partial-bound kwargs don't count against
    # the spec arity
    findings, _ = run_fixture("""\
        import functools
        import jax
        from jax.sharding import PartitionSpec as P

        def _body(params, x, *, stage_fn, pp_axis):
            return stage_fn(params, x, pp_axis)

        def mount(mesh, stage_fn):
            body = functools.partial(_body, stage_fn=stage_fn,
                                     pp_axis="pp")
            return jax.shard_map(body, mesh=mesh,
                                 in_specs=(P("pp"), P()),
                                 out_specs=P())
        """)
    assert "TPU020" not in codes(findings)


def test_tpu020_out_specs_tuple_arity_fires():
    findings, _ = run_fixture("""\
        import jax
        from jax.sharding import PartitionSpec as P

        def body(x):
            return x, x, x

        def mount(mesh):
            return jax.shard_map(body, mesh=mesh, in_specs=(P(),),
                                 out_specs=(P(), P()))
        """)
    (f,) = [f for f in findings if f.rule == "TPU020"]
    assert "3-tuple" in f.message


def test_tpu020_p_longer_than_literal_rank_fires():
    findings, _ = run_fixture("""\
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(mesh):
            x = jnp.zeros((4, 8))
            return jax.device_put(
                x, NamedSharding(mesh, P("dp", None, None)))
        """)
    (f,) = [f for f in findings if f.rule == "TPU020"]
    assert "rank 2" in f.message


def test_tpu020_annotation_rank_quiet_when_matching():
    findings, _ = run_fixture("""\
        import jax
        from jax.sharding import PartitionSpec as P

        def constrain(q: Float[Array, "b h d"]):
            return jax.lax.with_sharding_constraint(
                q, P("dp", "tp", None))
        """)
    assert "TPU020" not in codes(findings)


def test_tpu020_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        import jax
        from jax.sharding import PartitionSpec as P

        def body(x):
            return x

        def mount(mesh):
            # callee rebinds through a wrapper one-level expansion
            # cannot see
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(), P()),  # tpulint: disable=TPU020
                out_specs=P())
        """, keep_suppressed=True)
    assert "TPU020" not in codes(findings)
    assert "TPU020" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU021 unsharded-device-put


def test_tpu021_bare_device_put_under_mesh_fires():
    findings, _ = run_fixture("""\
        import jax

        def load(params, mesh):
            return jax.device_put(params)
        """)
    (f,) = [f for f in findings if f.rule == "TPU021"]
    assert f.severity == "warning"
    assert "replicates" in f.message


def test_tpu021_quiet_on_sharded_put_and_mesh_none_branch():
    findings, _ = run_fixture("""\
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def load(params, mesh):
            if mesh is None:
                return jax.device_put(params)
            return jax.device_put(params, NamedSharding(mesh, P()))
        """)
    assert "TPU021" not in codes(findings)


def test_tpu021_get_default_mesh_counts_as_mesh_in_scope():
    findings, _ = run_fixture("""\
        import jax
        from mmlspark_tpu.parallel.mesh import get_default_mesh

        def load(params):
            mesh = get_default_mesh()
            return jax.device_put(params)
        """)
    assert codes(findings).count("TPU021") == 1


def test_tpu021_quiet_without_mesh_in_scope():
    findings, _ = run_fixture("""\
        import jax

        def load(params):
            return jax.device_put(params)
        """)
    assert "TPU021" not in codes(findings)


def test_tpu021_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        import jax

        def load(params, mesh):
            # single-device branch by construction: the caller only
            # reaches this path with mesh unset
            # tpulint: disable=TPU021
            return jax.device_put(params)
        """, keep_suppressed=True)
    assert "TPU021" not in codes(findings)
    assert "TPU021" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU022 collective-in-loop


def test_tpu022_collective_in_python_loop_fires():
    findings, _ = run_fixture("""\
        import jax

        @jax.jit
        def ring(x):
            for _ in range(8):
                x = jax.lax.psum(x, "dp")
            return x
        """)
    (f,) = [f for f in findings if f.rule == "TPU022"]
    assert f.severity == "warning"
    assert "unrolls" in f.message


def test_tpu022_quiet_in_fori_loop_body_and_outside_jit():
    findings, _ = run_fixture("""\
        import jax

        @jax.jit
        def ring(x):
            def body(i, acc):
                return acc + jax.lax.psum(acc, "dp")
            return jax.lax.fori_loop(0, 8, body, x)

        def host_side(xs):
            for x in xs:
                jax.lax.psum(x, "dp")
        """)
    assert "TPU022" not in codes(findings)


def test_tpu022_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        import jax

        @jax.jit
        def warmup(x):
            for _ in range(2):
                # two-iteration handshake by design
                # tpulint: disable=TPU022
                x = jax.lax.ppermute(x, "dp", [(0, 1)])
            return x
        """, keep_suppressed=True)
    assert "TPU022" not in codes(findings)
    assert "TPU022" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU023 closed-loop-latency


CLOSED_LOOP_SRC = """\
    import time
    import urllib.request

    def bench(urls):
        lats = []
        for u in urls:
            t0 = time.perf_counter()
            with urllib.request.urlopen(u) as r:
                r.read()
            lats.append(time.perf_counter() - t0)
        return lats
    """


def test_tpu023_adhoc_closed_loop_fires():
    findings, _ = run_fixture(CLOSED_LOOP_SRC,
                              relpath="scripts/adhoc_bench.py")
    assert "TPU023" in codes(findings)


def test_tpu023_paced_loop_quiet():
    # an explicit pacing call means the loop schedules sends instead of
    # letting the reply throttle the generator — open-loop-ish, allowed
    findings, _ = run_fixture("""\
        import time
        import urllib.request

        def bench(urls):
            lats = []
            for u in urls:
                t0 = time.perf_counter()
                with urllib.request.urlopen(u) as r:
                    r.read()
                lats.append(time.perf_counter() - t0)
                time.sleep(0.01)
            return lats
        """, relpath="scripts/adhoc_bench.py")
    assert "TPU023" not in codes(findings)


def test_tpu023_single_clock_read_quiet():
    # one clock read is progress logging, not a latency measurement
    findings, _ = run_fixture("""\
        import time
        import urllib.request

        def drain(urls):
            start = time.monotonic()
            for u in urls:
                with urllib.request.urlopen(u) as r:
                    r.read()
            return time.monotonic() - start
        """, relpath="scripts/adhoc_bench.py")
    assert "TPU023" not in codes(findings)


def test_tpu023_loadgen_and_tests_exempt():
    # loadgen owns the sanctioned (labeled) closed-loop probe; tests
    # assert on single requests, not latency distributions
    for relpath in ("mmlspark_tpu/loadgen/scenarios.py",
                    "tests/test_serving.py",
                    "pkg/tests/test_x.py"):
        findings, _ = run_fixture(CLOSED_LOOP_SRC, relpath=relpath)
        assert "TPU023" not in codes(findings), relpath


def test_tpu023_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        import time
        import urllib.request

        def wait_ready(url):
            # polling for readiness while logging elapsed time — not a
            # latency benchmark, nothing is measured per request
            # tpulint: disable=TPU023
            while True:
                t0 = time.perf_counter()
                with urllib.request.urlopen(url) as r:
                    r.read()
                if time.perf_counter() - t0 >= 0:
                    return
        """, relpath="scripts/wait_ready.py", keep_suppressed=True)
    assert "TPU023" not in codes(findings)
    assert "TPU023" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU024 adhoc-timeseries


ADHOC_TS_SRC = """\
    import time

    class QueueMonitor:
        def __init__(self):
            self._history = []

        def sample(self, depth):
            self._history.append((time.monotonic(), depth))
    """


def test_tpu024_adhoc_timeseries_fires():
    findings, _ = run_fixture(ADHOC_TS_SRC,
                              relpath="mmlspark_tpu/serving/monitor.py")
    assert "TPU024" in codes(findings)


def test_tpu024_clock_via_local_fires():
    # the timestamp rides a local assigned from a clock read — same
    # accumulation, one hop removed
    findings, _ = run_fixture("""\
        import time

        class Runner:
            def __init__(self):
                self._samples = []

            def note(self, value):
                now = time.perf_counter()
                self._samples.append({"t": now, "v": value})
        """, relpath="mmlspark_tpu/serving/monitor.py")
    assert "TPU024" in codes(findings)


def test_tpu024_bounded_variants_quiet():
    # any in-class bounding evidence silences the rule: deque(maxlen=),
    # a tail-slice rebind, or a len-guarded pop drain
    for src in (
        """\
        import time
        from collections import deque

        class A:
            def __init__(self):
                self._history = deque(maxlen=128)

            def sample(self, d):
                self._history.append((time.monotonic(), d))
        """,
        """\
        import time

        class B:
            def __init__(self):
                self._history = []

            def sample(self, d):
                self._history.append((time.monotonic(), d))
                self._history = self._history[-128:]
        """,
        """\
        import time

        class C:
            def __init__(self):
                self._history = []

            def sample(self, d):
                self._history.append((time.monotonic(), d))
                while len(self._history) > 128:
                    self._history.pop(0)
        """,
    ):
        findings, _ = run_fixture(
            src, relpath="mmlspark_tpu/serving/monitor.py")
        assert "TPU024" not in codes(findings), src


def test_tpu024_scalar_append_quiet():
    # a bare scalar append is a worklist, not a (timestamp, value) series
    findings, _ = run_fixture("""\
        import time

        class Q:
            def __init__(self):
                self._items = []

            def put(self, item):
                self._items.append(item)
        """, relpath="mmlspark_tpu/serving/monitor.py")
    assert "TPU024" not in codes(findings)


def test_tpu024_observability_and_tests_exempt():
    # the store's own package holds the sanctioned rings; tests build
    # tiny traces on purpose
    for relpath in ("mmlspark_tpu/observability/timeseries.py",
                    "tests/test_monitor.py",
                    "pkg/tests/test_x.py"):
        findings, _ = run_fixture(ADHOC_TS_SRC, relpath=relpath)
        assert "TPU024" not in codes(findings), relpath


def test_tpu024_suppressible_with_justification():
    findings, suppressed = run_fixture("""\
        import time

        class R:
            def __init__(self):
                self._marks = []

            def mark(self, v):
                # trimmed by the flush helper outside this class
                # tpulint: disable=TPU024
                self._marks.append((time.monotonic(), v))
        """, relpath="mmlspark_tpu/serving/monitor.py",
        keep_suppressed=True)
    assert "TPU024" not in codes(findings)
    assert "TPU024" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU025 unsupervised-daemon-loop


DAEMON_LOOP_SRC = """\
    import threading

    class Worker:
        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            while True:
                self.tick()
    """


def test_tpu025_bare_daemon_loop_fires():
    findings, _ = run_fixture(DAEMON_LOOP_SRC,
                              relpath="mmlspark_tpu/serving/worker.py")
    assert "TPU025" in codes(findings)
    (f,) = [f for f in findings if f.rule == "TPU025"]
    assert f.severity == "warning"
    assert "_run" in f.message


def test_tpu025_module_level_function_target_fires():
    findings, _ = run_fixture("""\
        import threading

        def pump(q):
            while True:
                q.get()

        t = threading.Thread(target=pump, daemon=True)
        """, relpath="mmlspark_tpu/serving/worker.py")
    assert "TPU025" in codes(findings)


def test_tpu025_supervised_variants_quiet():
    for src in (
        # started through the supervision helper — the blessed idiom
        """\
        from mmlspark_tpu.reliability import start_supervised

        class A:
            def start(self):
                self._t = start_supervised(self._tick, name="a",
                                           stop=self._stop, interval=0.1)

            def _tick(self):
                self.poll()
        """,
        # try/except INSIDE the loop contains each iteration's crash
        """\
        import threading

        class B:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while not self._stop.is_set():
                    try:
                        self.tick()
                    except Exception:
                        continue
        """,
        # non-daemon thread: a crash is loud at join/shutdown
        """\
        import threading

        class C:
            def start(self):
                self._t = threading.Thread(target=self._run)

            def _run(self):
                while True:
                    self.tick()
        """,
        # target without a loop: run-once threads finish and die anyway
        """\
        import threading

        class D:
            def start(self):
                self._t = threading.Thread(target=self._once, daemon=True)

            def _once(self):
                self.tick()
        """,
    ):
        findings, _ = run_fixture(src,
                                  relpath="mmlspark_tpu/serving/worker.py")
        assert "TPU025" not in codes(findings), src


def test_tpu025_unresolvable_target_is_skipped():
    # a lambda / computed target can't be resolved to a function body —
    # skipped, not flagged (no false positives on dynamic dispatch)
    findings, _ = run_fixture("""\
        import threading

        class E:
            def start(self, fn):
                self._t = threading.Thread(target=lambda: fn(),
                                           daemon=True)
        """, relpath="mmlspark_tpu/serving/worker.py")
    assert "TPU025" not in codes(findings)


def test_tpu025_exempt_paths_quiet():
    # the reliability package (home of the supervisor itself) and tests
    # are exempt by path prefix
    for relpath in ("mmlspark_tpu/reliability/loops.py",
                    "tests/test_threads.py"):
        findings, _ = run_fixture(DAEMON_LOOP_SRC, relpath=relpath)
        assert "TPU025" not in codes(findings), relpath


def test_tpu025_suppression_comment_respected():
    findings, suppressed = run_fixture("""\
        import threading

        class F:
            def start(self):
                # session-scoped: dies with the request, crash captured
                # tpulint: disable=TPU025
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                while True:
                    self.tick()
        """, relpath="mmlspark_tpu/serving/worker.py",
        keep_suppressed=True)
    assert "TPU025" not in codes(findings)
    assert "TPU025" in codes(suppressed)


# CLI exit codes


def _cli(args):
    out = io.StringIO()
    rc = cli_main(args, stdout=out)
    return rc, out.getvalue()


def test_cli_clean_file_exits_zero(tmp_path):
    p = tmp_path / "clean.py"
    p.write_text("def f(x):\n    return x\n")
    rc, out = _cli([str(p)])
    assert rc == 0 and "no findings" in out


def test_cli_positive_fixtures_exit_nonzero(tmp_path):
    # one gating fixture per line-scope rule
    fixtures = {
        "TPU001": "import jax\n\n@jax.jit\ndef f(x):\n"
                  "    return jax.device_get(x)\n",
        "TPU002": "import jax\n\ndef r(fns, x):\n    for fn in fns:\n"
                  "        x = jax.jit(fn)(x)\n    return x\n",
        "TPU003": "import jax\n\n@jax.jit\ndef f(x):\n    if x > 0:\n"
                  "        return x\n    return -x\n",
        "TPU018": "import jax.numpy as jnp\n\ndef w(k_rows):\n"
                  "    return k_rows.astype(jnp.int8)\n",
        "TPU020": "import jax\nfrom jax.sharding import "
                  "PartitionSpec as P\n\ndef body(x):\n    return x\n\n"
                  "def mount(mesh):\n    return jax.shard_map(\n"
                  "        body, mesh=mesh, in_specs=(P(), P()),\n"
                  "        out_specs=P())\n",
        "TPU021": "import jax\n\ndef load(params, mesh):\n"
                  "    return jax.device_put(params)\n",
        "TPU022": "import jax\n\n@jax.jit\ndef ring(x):\n"
                  "    for _ in range(4):\n"
                  "        x = jax.lax.psum(x, \"dp\")\n    return x\n",
        "TPU023": "import time\nimport urllib.request\n\n"
                  "def bench(urls):\n    lats = []\n"
                  "    for u in urls:\n"
                  "        t0 = time.perf_counter()\n"
                  "        with urllib.request.urlopen(u) as r:\n"
                  "            r.read()\n"
                  "        lats.append(time.perf_counter() - t0)\n"
                  "    return lats\n",
        "TPU024": "import time\n\nclass M:\n"
                  "    def __init__(self):\n"
                  "        self._history = []\n\n"
                  "    def sample(self, d):\n"
                  "        self._history.append((time.monotonic(), d))\n",
    }
    for rule, src in fixtures.items():
        p = tmp_path / f"{rule.lower()}.py"
        p.write_text(src)
        rc, out = _cli([str(p)])
        assert rc == 1 and rule in out, (rule, out)


def test_cli_tpu005_duplicate_exits_nonzero(tmp_path):
    root = _write_pkg(tmp_path, {
        "convert.py": CONVERT_SRC,
        "extra.py": """\
            from .convert import register_op

            @register_op("Add")
            def _add2(node, inputs, ctx):
                return inputs
            """,
    })
    rc, out = _cli([os.path.join(root, "pkg")])
    assert rc == 1 and "TPU005" in out


def test_cli_tpu006_stub_drift_exits_nonzero(tmp_path):
    (tmp_path / "mod.py").write_text("def foo():\n    return 1\n")
    (tmp_path / "mod.pyi").write_text(
        "def foo() -> int: ...\ndef gone() -> int: ...\n")
    rc, out = _cli([str(tmp_path)])
    assert rc == 1 and "TPU006" in out and "gone" in out


def test_cli_tpu019_axis_typo_exits_nonzero(tmp_path):
    # project-scope rule: the mesh in one module defines the vocabulary
    # the spec in another is checked against
    (tmp_path / "meshes.py").write_text(
        "import jax\nimport numpy as np\n"
        "from jax.sharding import Mesh\n\n"
        "mesh = Mesh(np.array(jax.devices()), (\"dp\", \"tp\"))\n")
    (tmp_path / "specs.py").write_text(
        "from jax.sharding import PartitionSpec as P\n\n"
        "row = P(\"dpp\", None)\n")
    rc, out = _cli([str(tmp_path)])
    assert rc == 1 and "TPU019" in out and "dpp" in out


def test_cli_tpu004_warning_gates_but_info_does_not(tmp_path):
    ops = tmp_path / "ops"
    ops.mkdir()
    p = ops / "x.py"
    p.write_text("import numpy as np\n\ndef f(v):\n"
                 "    return np.asarray(v, dtype=np.float64)\n")
    rc, out = _cli([str(p)])
    assert rc == 1 and "TPU004" in out

    p.write_text("import jax\nimport jax.numpy as jnp\n\n@jax.jit\n"
                 "def f(x):\n    return jnp.maximum(x, 1e-38)\n")
    rc, out = _cli([str(p)])
    assert rc == 0 and "TPU004" in out  # reported, not gating


def test_cli_unknown_rule_exits_two(tmp_path):
    p = tmp_path / "x.py"
    p.write_text("x = 1\n")
    rc, _ = _cli([str(p), "--rules", "NOPE"])
    assert rc == 2


def test_cli_no_paths_exits_two():
    rc, _ = _cli([])
    assert rc == 2


def test_cli_parse_error_exits_one(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text("def f(:\n")
    rc, out = _cli([str(p)])
    assert rc == 1 and "parse" in out.lower()


def test_cli_baseline_swallows_known_findings(tmp_path):
    p = tmp_path / "x.py"
    p.write_text("import jax\n\n@jax.jit\ndef f(x):\n"
                 "    return jax.device_get(x)\n")
    bl = str(tmp_path / "baseline.json")
    rc, _ = _cli([str(p), "--write-baseline", bl])
    assert rc == 0
    rc, out = _cli([str(p), "--baseline", bl])
    assert rc == 0 and "baselined" in out


def test_cli_json_format(tmp_path):
    p = tmp_path / "x.py"
    p.write_text("import jax\n\n@jax.jit\ndef f(x):\n"
                 "    return jax.device_get(x)\n")
    rc, out = _cli([str(p), "--format", "json"])
    doc = json.loads(out)
    assert rc == 1 and doc["findings"][0]["rule"] == "TPU001"


def test_cli_list_rules():
    rc, out = _cli(["--list-rules"])
    assert rc == 0
    for code in ("TPU001", "TPU002", "TPU003", "TPU004", "TPU005", "TPU006",
                 "TPU010", "TPU011", "TPU012", "TPU013", "TPU014",
                 "TPU015"):
        assert code in out


# ---------------------------------------------------------------------------
# TPU012 unguarded-shared-mutation


_GUARDED_CLASS = """\
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, v):
            with self._lock:
                self._items.append(v)

        def drop(self, v):
            with self._lock:
                self._items.remove(v)

        def rogue(self, v):
            self._items.append(v)
    """


def test_tpu012_bare_write_to_inferred_guarded_field_fires():
    findings, _ = run_fixture(_GUARDED_CLASS)
    hits = [f for f in findings if f.rule == "TPU012"]
    assert len(hits) == 1
    assert "Pool._items" in hits[0].message
    assert "_lock" in hits[0].message


def test_tpu012_quiet_when_every_write_is_guarded_and_init_is_free():
    findings, _ = run_fixture("""\
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []   # pre-publication write: never counted

            def add(self, v):
                with self._lock:
                    self._items.append(v)

            def drop(self, v):
                with self._lock:
                    self._items.remove(v)
        """)
    assert "TPU012" not in codes(findings)


def test_tpu012_locked_suffix_method_counts_as_guarded():
    # the _prune_locked convention: caller holds the class lock
    findings, _ = run_fixture("""\
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def add(self, v):
                with self._lock:
                    self._items.append(v)
                    self._prune_locked()

            def drop(self, v):
                with self._lock:
                    self._items.remove(v)

            def _prune_locked(self):
                self._items.pop()
        """)
    assert "TPU012" not in codes(findings)


def test_tpu012_module_global_under_module_lock():
    findings, _ = run_fixture("""\
        import threading

        _LOCK = threading.Lock()
        _CACHE = {}

        def put(k, v):
            with _LOCK:
                _CACHE[k] = v

        def drop(k):
            with _LOCK:
                _CACHE.pop(k)

        def rogue(k, v):
            _CACHE[k] = v
        """)
    hits = [f for f in findings if f.rule == "TPU012"]
    assert len(hits) == 1 and "_CACHE" in hits[0].message


def test_tpu012_discovers_sanitizer_factory_locks():
    # adoption must not blind the analysis: new_lock() IS a lock
    findings, _ = run_fixture("""\
        from mmlspark_tpu.reliability.lock_sanitizer import new_lock

        class Pool:
            def __init__(self):
                self._lock = new_lock("pool")
                self._items = []

            def add(self, v):
                with self._lock:
                    self._items.append(v)

            def drop(self, v):
                with self._lock:
                    self._items.remove(v)

            def rogue(self, v):
                self._items.append(v)
        """)
    assert "TPU012" in codes(findings)


def test_tpu012_suppressible_with_justification():
    findings, suppressed = run_fixture(
        _GUARDED_CLASS.replace(
            "self._items.append(v)\n    ",
            "self._items.append(v)  # tpulint: disable=TPU012\n    "),
        keep_suppressed=True)
    assert "TPU012" not in codes(findings)
    assert "TPU012" in codes(suppressed)


# ---------------------------------------------------------------------------
# TPU013 lock-order-inversion


def test_tpu013_ab_ba_inversion_fires_with_both_sites():
    findings, _ = run_fixture("""\
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def forward():
            with A:
                with B:
                    pass

        def backward():
            with B:
                with A:
                    pass
        """)
    hits = [f for f in findings if f.rule == "TPU013"]
    assert len(hits) == 1
    assert hits[0].severity == "error"
    assert "A" in hits[0].message and "B" in hits[0].message
    # the report names both conflicting locations
    assert "forward" in hits[0].message or "backward" in hits[0].message


def test_tpu013_consistent_order_is_quiet():
    findings, _ = run_fixture("""\
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def one():
            with A:
                with B:
                    pass

        def two():
            with A:
                with B:
                    pass
        """)
    assert "TPU013" not in codes(findings)


def test_tpu013_nonreentrant_self_reacquire_through_call_fires():
    findings, _ = run_fixture("""\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        """)
    hits = [f for f in findings if f.rule == "TPU013"]
    assert len(hits) == 1 and "self-deadlock" in hits[0].message


def test_tpu013_rlock_self_reacquire_is_quiet():
    findings, _ = run_fixture("""\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        """)
    assert "TPU013" not in codes(findings)


# ---------------------------------------------------------------------------
# TPU014 blocking-call-under-lock


def test_tpu014_sleep_and_device_sync_under_lock_fire():
    findings, _ = run_fixture("""\
        import threading
        import time
        import jax

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def a(self):
                with self._lock:
                    time.sleep(1)

            def b(self, x):
                with self._lock:
                    return jax.device_get(x)
        """)
    hits = [f for f in findings if f.rule == "TPU014"]
    assert len(hits) == 2
    assert any("time.sleep" in f.message for f in hits)
    assert any("jax.device_get" in f.message for f in hits)


def test_tpu014_blocking_outside_lock_is_quiet():
    findings, _ = run_fixture("""\
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def a(self):
                with self._lock:
                    n = 1
                time.sleep(n)
        """)
    assert "TPU014" not in codes(findings)


def test_tpu014_sees_through_one_call_level():
    # with self._lock: self._pull() — the sync lives in the helper
    findings, _ = run_fixture("""\
        import threading
        import jax

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def run(self):
                with self._lock:
                    self._pull()

            def _pull(self):
                return jax.device_get(1)
        """)
    hits = [f for f in findings if f.rule == "TPU014"]
    assert len(hits) == 1 and "jax.device_get" in hits[0].message


def test_tpu014_condition_wait_and_nonblocking_get_are_quiet():
    # cond.wait releases the lock it is tied to; get(block=False) returns
    findings, _ = run_fixture("""\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._q = object()

            def a(self):
                with self._lock:
                    self._cond.wait()

            def b(self):
                with self._lock:
                    return self._q.get(block=False)
        """)
    assert "TPU014" not in codes(findings)


def test_tpu014_queue_wait_under_lock_fires():
    findings, _ = run_fixture("""\
        import queue
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._queue = queue.Queue(4)

            def a(self, v):
                with self._lock:
                    self._queue.put(v)
        """)
    hits = [f for f in findings if f.rule == "TPU014"]
    assert len(hits) == 1 and "queue" in hits[0].message


def test_tpu014_findings_are_baselinable():
    findings, _ = run_fixture("""\
        import threading
        import time

        _L = threading.Lock()

        def a():
            with _L:
                time.sleep(1)
        """)
    hits = [f for f in findings if f.rule == "TPU014"]
    assert hits
    known = baseline_mod.counts(hits)
    fresh, baselined, stale = baseline_mod.apply(hits, known)
    assert fresh == [] and len(baselined) == len(hits) and not stale


# ---------------------------------------------------------------------------
# --jobs parallel scan


def test_jobs_parallel_scan_matches_serial(tmp_path):
    for i in range(8):
        (tmp_path / f"m{i}.py").write_text(
            "import jax\n\n@jax.jit\ndef f(x):\n"
            "    return jax.device_get(x)\n")
    serial = analyze_project(load_project([str(tmp_path)], jobs=1))[0]
    threaded = analyze_project(load_project([str(tmp_path)], jobs=4),
                               jobs=4)[0]
    assert [(f.path, f.line, f.rule) for f in serial] \
        == [(f.path, f.line, f.rule) for f in threaded]
    assert len(serial) == 8


def test_cli_jobs_flag(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text("import jax\n\n@jax.jit\ndef f(x):\n"
                 "    return jax.device_get(x)\n")
    rc, out = _cli([str(p), "--jobs", "4"])
    assert rc == 1 and "TPU001" in out
    rc, _ = _cli([str(p), "--jobs", "0"])
    assert rc == 2


# ---------------------------------------------------------------------------
# Self-scan: the shipped tree is clean modulo the checked-in baseline


def test_self_scan_shipped_tree_is_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "run_tpulint.py"),
         "mmlspark_tpu"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
