"""Tests for the featurize package (reference: featurize/* test suites)."""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.featurize import (IDF, CleanMissingData, CountSelector,
                                    DataConversion, Featurize, HashingTF,
                                    IndexToValue, MultiNGram, PageSplitter,
                                    TextFeaturizer, Tokenizer, ValueIndexer)


def test_clean_missing_mean():
    df = DataFrame({"x": [1.0, np.nan, 3.0]})
    model = CleanMissingData(["x"], ["x_clean"]).fit(df)
    out = model.transform(df)
    np.testing.assert_allclose(out["x_clean"], [1.0, 2.0, 3.0])


def test_clean_missing_custom_roundtrip(tmp_save):
    df = DataFrame({"x": [1.0, np.nan]})
    model = CleanMissingData(["x"], ["x"], cleaning_mode="Custom",
                            custom_value=-1.0).fit(df)
    model.save(tmp_save)
    from mmlspark_tpu.featurize import CleanMissingDataModel
    loaded = CleanMissingDataModel.load(tmp_save)
    np.testing.assert_allclose(loaded.transform(df)["x"], [1.0, -1.0])


def test_value_indexer_roundtrip():
    df = DataFrame({"cat": ["b", "a", "b", "c"]})
    model = ValueIndexer(input_col="cat", output_col="idx").fit(df)
    out = model.transform(df)
    assert list(out["idx"]) == [1, 0, 1, 2]
    back = IndexToValue(input_col="idx", output_col="orig").transform(out)
    assert list(back["orig"]) == ["b", "a", "b", "c"]


def test_value_indexer_unseen_raises():
    model = ValueIndexer(input_col="c", output_col="i").fit(
        DataFrame({"c": ["a"]}))
    with pytest.raises(ValueError):
        model.transform(DataFrame({"c": ["zzz"]}))


def test_data_conversion_casts():
    df = DataFrame({"x": [1.5, 2.5]})
    out = DataConversion(input_cols=["x"], convert_to="integer").transform(df)
    assert out["x"].dtype == np.int32


def test_count_selector():
    col = np.empty(2, dtype=object)
    col[0] = np.array([1.0, 0.0, 2.0])
    col[1] = np.array([3.0, 0.0, 0.0])
    df = DataFrame({"features": col})
    model = CountSelector(input_col="features", output_col="out").fit(df)
    out = model.transform(df)
    np.testing.assert_allclose(out["out"][0], [1.0, 2.0])


def test_tokenizer_ngram_hashing_idf():
    df = DataFrame({"text": ["the cat sat", "the dog ran fast"]})
    toks = Tokenizer(input_col="text", output_col="toks").transform(df)
    assert toks["toks"][0] == ["the", "cat", "sat"]
    grams = MultiNGram(input_col="toks", output_col="grams",
                       lengths=[1, 2]).transform(toks)
    assert "the cat" in grams["grams"][0]
    tf = HashingTF(input_col="toks", output_col="tf",
                   num_features=64).transform(toks)
    assert tf["tf"][0].sum() == 3.0
    idf_model = IDF(input_col="tf", output_col="tfidf").fit(tf)
    out = idf_model.transform(tf)
    assert out["tfidf"][0].shape == (64,)


def test_text_featurizer_end_to_end(tmp_save):
    df = DataFrame({"text": ["good movie great plot", "bad film poor plot",
                             "great film good acting"]})
    model = TextFeaturizer(input_col="text", output_col="features",
                           num_features=128).fit(df)
    out = model.transform(df)
    assert out["features"][0].shape == (128,)
    assert "_tf_tokens" not in out.columns
    model.save(tmp_save)
    from mmlspark_tpu.featurize import TextFeaturizerModel
    loaded = TextFeaturizerModel.load(tmp_save)
    np.testing.assert_allclose(loaded.transform(df)["features"][1],
                               out["features"][1])


def test_hashing_tf_sparse_matches_dense():
    import scipy.sparse as sp
    df = DataFrame({"text": ["the cat sat on the mat", "a dog", ""]})
    toks = Tokenizer(input_col="text", output_col="toks").transform(df)
    dense = HashingTF(input_col="toks", output_col="tf",
                      num_features=64).transform(toks)
    sparse = HashingTF(input_col="toks", output_col="tf",
                       num_features=64, sparse=True).transform(toks)
    for i in range(len(df)):
        assert sp.issparse(sparse["tf"][i])
        np.testing.assert_allclose(
            np.asarray(sparse["tf"][i].todense()).ravel(), dense["tf"][i])
    # binary mode too
    db = HashingTF(input_col="toks", output_col="tf", num_features=64,
                   binary=True).transform(toks)
    sb = HashingTF(input_col="toks", output_col="tf", num_features=64,
                   binary=True, sparse=True).transform(toks)
    np.testing.assert_allclose(
        np.asarray(sb["tf"][0].todense()).ravel(), db["tf"][0])


def test_idf_sparse_matches_dense():
    import scipy.sparse as sp
    df = DataFrame({"text": ["good movie great plot", "bad film poor plot",
                             "great film good acting"]})
    toks = Tokenizer(input_col="text", output_col="toks").transform(df)
    tf_d = HashingTF(input_col="toks", output_col="tf",
                     num_features=128).transform(toks)
    tf_s = HashingTF(input_col="toks", output_col="tf", num_features=128,
                     sparse=True).transform(toks)
    m_d = IDF(input_col="tf", output_col="tfidf").fit(tf_d)
    m_s = IDF(input_col="tf", output_col="tfidf").fit(tf_s)
    np.testing.assert_allclose(np.asarray(m_s.get("idf")),
                               np.asarray(m_d.get("idf")))
    out_s = m_s.transform(tf_s)
    out_d = m_d.transform(tf_d)
    for i in range(len(df)):
        assert sp.issparse(out_s["tfidf"][i])
        np.testing.assert_allclose(
            np.asarray(out_s["tfidf"][i].todense()).ravel(),
            out_d["tfidf"][i], rtol=1e-6)


def test_text_featurizer_sparse_to_gbdt():
    # the end-to-end story the sparse path exists for: text → hashed
    # sparse features (reference-scale hash space) → GBDT with EFB
    import scipy.sparse as sp
    from mmlspark_tpu.models.gbdt import LightGBMClassifier
    rng = np.random.default_rng(0)
    pos = ["great amazing wonderful", "superb brilliant fine",
           "great fine acting", "wonderful superb plot"]
    neg = ["bad awful terrible", "poor dreadful plot",
           "terrible poor acting", "awful dreadful film"]
    texts = []
    for i in range(120):
        words = (pos if i % 2 == 0 else neg)[rng.integers(0, 4)].split()
        texts.append(" ".join(rng.permutation(words)))
    y = np.array([1.0 if i % 2 == 0 else 0.0 for i in range(120)])
    df = DataFrame({"text": np.array(texts, dtype=object), "label": y})
    feats = TextFeaturizer(input_col="text", output_col="features",
                           num_features=1 << 15, sparse=True).fit(df) \
        .transform(df)
    assert sp.issparse(feats["features"][0])
    assert feats["features"][0].shape == (1, 1 << 15)
    m = LightGBMClassifier(num_iterations=20, num_leaves=7,
                           min_data_in_leaf=5).fit(feats)
    pred = np.asarray(m.transform(feats)["prediction"], dtype=np.float64)
    assert (pred == y).mean() > 0.9


def test_page_splitter():
    df = DataFrame({"doc": ["word " * 100]})
    out = PageSplitter(input_col="doc", output_col="pages",
                       minimum_page_length=50,
                       maximum_page_length=100).transform(df)
    pages = out["pages"][0]
    assert all(len(p) <= 100 for p in pages)
    assert "".join(pages) == "word " * 100


def test_featurize_mixed_types():
    df = DataFrame({
        "num": np.array([1.0, np.nan, 3.0]),
        "cat": ["a", "b", "a"],
        "vec": [np.array([1.0, 2.0]), np.array([3.0, 4.0]),
                np.array([5.0, 6.0])],
    })
    model = Featurize(["num", "cat", "vec"]).fit(df)
    out = model.transform(df)
    X = np.stack(list(out["features"]))
    # 1 numeric + 2 one-hot + 2 vector slots
    assert X.shape == (3, 5)
    assert X[1, 0] == 2.0  # mean-imputed


def test_featurize_roundtrip(tmp_save):
    df = DataFrame({"num": [1.0, 2.0], "cat": ["x", "y"]})
    model = Featurize(["num", "cat"]).fit(df)
    model.save(tmp_save)
    from mmlspark_tpu.featurize import FeaturizeModel
    loaded = FeaturizeModel.load(tmp_save)
    np.testing.assert_allclose(
        np.stack(list(loaded.transform(df)["features"])),
        np.stack(list(model.transform(df)["features"])))


class TestVectorAssembler:
    """Parity: FastVectorAssembler (columnar concat, no per-row metadata)."""

    def _df(self):
        from mmlspark_tpu.core.dataframe import object_col
        return DataFrame({
            "a": np.array([1.0, 2.0, 3.0]),
            "v": object_col([np.array([10.0, 20.0]),
                             np.array([30.0, 40.0]),
                             np.array([50.0, 60.0])]),
            "m": np.arange(6, dtype=np.float32).reshape(3, 2),
        })

    def test_concatenates_scalars_vectors_and_dense(self):
        from mmlspark_tpu.featurize.featurize import VectorAssembler
        out = VectorAssembler(input_cols=["a", "v", "m"],
                              output_col="features").transform(self._df())
        X = np.stack(list(out["features"]))
        np.testing.assert_allclose(
            X, [[1, 10, 20, 0, 1], [2, 30, 40, 2, 3], [3, 50, 60, 4, 5]])

    def test_error_on_nan_default(self):
        from mmlspark_tpu.featurize.featurize import VectorAssembler
        df = DataFrame({"a": np.array([1.0, np.nan])})
        va = VectorAssembler(input_cols=["a"], output_col="f")
        with pytest.raises(ValueError, match="non-finite"):
            va.transform(df)
        va.set(handle_invalid="keep")
        out = va.transform(df)
        assert np.isnan(out["f"][1][0])

    def test_ragged_vector_rejected(self):
        from mmlspark_tpu.core.dataframe import object_col
        from mmlspark_tpu.featurize.featurize import VectorAssembler
        df = DataFrame({"v": object_col([np.ones(2), np.ones(3)])})
        with pytest.raises(ValueError, match="fixed-width"):
            VectorAssembler(input_cols=["v"], output_col="f").transform(df)

    def test_all_none_column_rejected(self):
        from mmlspark_tpu.core.dataframe import object_col
        from mmlspark_tpu.featurize.featurize import VectorAssembler
        df = DataFrame({"v": object_col([None, None])})
        with pytest.raises(ValueError, match="entirely None"):
            VectorAssembler(input_cols=["v"], output_col="f",
                            handle_invalid="keep").transform(df)

    def test_none_rows_become_nan_with_keep(self):
        from mmlspark_tpu.core.dataframe import object_col
        from mmlspark_tpu.featurize.featurize import VectorAssembler
        df = DataFrame({"v": object_col([np.array([1.0, 2.0]), None])})
        out = VectorAssembler(input_cols=["v"], output_col="f",
                              handle_invalid="keep").transform(df)
        assert np.isnan(out["f"][1]).all() and len(out["f"][1]) == 2

    def test_empty_object_column_rejected(self):
        """A 0-row frame has no width evidence — assembling must not change
        output width between empty and non-empty inputs."""
        from mmlspark_tpu.core.dataframe import object_col
        from mmlspark_tpu.featurize.featurize import VectorAssembler
        df = DataFrame({"v": object_col([])})
        with pytest.raises(ValueError, match="width is undefined"):
            VectorAssembler(input_cols=["v"], output_col="f").transform(df)
