"""Mock-server tests for the speech / MVAD / geospatial / doc-translation /
form-ontology service families."""

import json
import threading

import numpy as np
import pytest
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.dataframe import object_col
from mmlspark_tpu.services import (AddressGeocoder, CheckPointInPolygon,
                                   DetectMultivariateAnomaly,
                                   DocumentTranslator, FitMultivariateAnomaly,
                                   FormOntologyLearner, ReverseAddressGeocoder,
                                   SpeechToText, SpeechToTextSDK, TextToSpeech)

_state = {"mvad_polls": {}, "docop_polls": {}}


class _Mock(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _reply(self, obj, status=200, headers=(), raw=None):
        out = raw if raw is not None else json.dumps(obj).encode()
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Type",
                         "application/json" if raw is None else
                         "application/octet-stream")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def do_GET(self):
        path = urlparse(self.path)
        q = parse_qs(path.query)
        if path.path.startswith("/mvad/models/"):
            mid = path.path.rsplit("/", 1)[1]
            n = _state["mvad_polls"].get(mid, 0)
            _state["mvad_polls"][mid] = n + 1
            status = "READY" if n >= 1 else "CREATED"
            self._reply({"modelInfo": {"status": status}})
        elif path.path.startswith("/mvad/results/"):
            self._reply({"summary": {"status": "READY"},
                         "results": [
                             {"timestamp": "t0", "value": {"isAnomaly": False}},
                             {"timestamp": "t1", "value": {"isAnomaly": True}}]})
        elif path.path == "/geofence":
            lat = float(q["lat"][0])
            self._reply({"result": {"isInside": lat < 50.0}})
        elif path.path.startswith("/docop/"):
            op = path.path.rsplit("/", 1)[1]
            n = _state["docop_polls"].get(op, 0)
            _state["docop_polls"][op] = n + 1
            self._reply({"status": "Succeeded" if n >= 1 else "Running",
                         "summary": {"success": 1}})
        else:
            self._reply({"error": "nf"}, 404)

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n)
        path = urlparse(self.path)
        q = parse_qs(path.query)
        if path.path == "/stt":
            assert self.headers["Content-Type"].startswith("audio/wav")
            text = f"heard {len(raw)} bytes in {q['language'][0]}"
            self._reply({"RecognitionStatus": "Success",
                         "DisplayText": text})
        elif path.path == "/tts":
            assert self.headers["Content-Type"].startswith("application/ssml")
            assert self.headers.get("X-Microsoft-OutputFormat")
            self._reply(None, raw=b"RIFFfakeaudio" + raw[:8])
        elif path.path == "/mvad/models":
            self._reply({}, status=201,
                        headers=[("Location", "http://x/mvad/models/m123")])
        elif path.path == "/mvad/models/m123/detect":
            self._reply({}, status=201,
                        headers=[("Location", "http://x/mvad/results/r99")])
        elif path.path == "/geocode":
            body = json.loads(raw)
            items = [{"response": {"ok": True, "q": it["query"]}}
                     for it in body["batchItems"]]
            self._reply({"batchItems": items})
        elif path.path == "/search/index/docs":
            body = json.loads(raw)
            assert self.headers.get("api-key") == "sk"
            self._reply({"value": [
                {"key": str(i), "status": True,
                 "action_seen": d.get("@search.action"),
                 "fields_seen": sorted(d.keys())}
                for i, d in enumerate(body["value"])]})
        elif path.path == "/transcribe":
            assert q.get("participants", [""])[0].startswith("[")
            text = f"speaker0 said {len(raw)} bytes"
            self._reply({"RecognitionStatus": "Success",
                         "DisplayText": text, "SpeakerId": "guest-0"})
        elif path.path == "/docbatches":
            body = json.loads(raw)
            assert body["inputs"][0]["targets"][0]["language"] == "fr"
            self._reply({}, status=202,
                        headers=[("Operation-Location",
                                  f"{_state['base']}/docop/op7")])
        else:
            self._reply({"error": "nf"}, 404)


@pytest.fixture(scope="module")
def svc():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Mock)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    _state["base"] = base
    yield base
    httpd.shutdown()
    httpd.server_close()


def test_speech_to_text(svc):
    df = DataFrame({"audio": object_col([b"\x00" * 100, None])})
    t = SpeechToText(url=svc + "/stt", output_col="out", error_col="err")
    t.set_vector_param("audio_data", "audio")
    out = t.transform(df)
    assert out["out"][0]["DisplayText"] == "heard 100 bytes in en-US"
    assert out["out"][1] is None   # null audio → skipped row


def test_speech_to_text_sdk_chunks(svc):
    df = DataFrame({"audio": object_col([b"\x01" * 70000])})
    t = SpeechToTextSDK(url=svc + "/stt", chunk_bytes=32768,
                        output_col="out", error_col="err")
    t.set_vector_param("audio_data", "audio")
    out = t.transform(df)
    results = out["out"][0]
    assert len(results) == 3       # 70000 / 32768 → 3 chunks
    assert results[0]["DisplayText"].startswith("heard 32768")
    assert results[2]["DisplayText"].startswith("heard 4464")


def test_text_to_speech_writes_files(svc, tmp_path):
    paths = [str(tmp_path / "a.wav"), str(tmp_path / "b.wav")]
    df = DataFrame({"text": object_col(["hello", "world"]),
                    "outputFile": object_col(paths)})
    t = TextToSpeech(url=svc + "/tts", error_col="err")
    t.set_vector_param("text", "text")
    out = t.transform(df)
    assert out["err"][0] is None and out["err"][1] is None
    for p in paths:
        with open(p, "rb") as f:
            assert f.read().startswith(b"RIFF")


def test_mvad_fit_and_detect(svc):
    est = FitMultivariateAnomaly(url=svc + "/mvad/models",
                                 source="http://blob/x.zip",
                                 start_time="t0", end_time="t9",
                                 polling_delay_ms=10)
    df = DataFrame({"timestamp": object_col(["t0", "t1"])})
    model = est.fit(df)
    assert model.get("model_id") == "m123"
    out = model.transform(df)
    assert out["result"][0] == {"isAnomaly": False}
    assert out["result"][1] == {"isAnomaly": True}
    assert out["error"][0] is None


def test_mvad_model_roundtrip(svc, tmp_path):
    est = FitMultivariateAnomaly(url=svc + "/mvad/models",
                                 polling_delay_ms=10)
    model = est.fit(DataFrame({"timestamp": object_col(["t0"])}))
    p = str(tmp_path / "mvad")
    model.save(p)
    again = DetectMultivariateAnomaly.load(p)
    assert again.get("model_id") == "m123"


def test_address_geocoder_batch(svc):
    df = DataFrame({"addr": object_col([["1 Main St", "2 High St"]])})
    g = AddressGeocoder(url=svc + "/geocode", output_col="out",
                        error_col="err", subscription_key="k")
    g.set_vector_param("address", "addr")
    out = g.transform(df)
    assert len(out["out"][0]) == 2
    assert out["out"][0][0]["response"]["ok"]


def test_reverse_geocoder_and_key_in_url(svc):
    df = DataFrame({"pts": object_col([[[47.6, -122.3]]])})
    g = ReverseAddressGeocoder(url=svc + "/geocode", output_col="out",
                               error_col="err", subscription_key="secret")
    g.set_vector_param("coordinates", "pts")
    out = g.transform(df)
    assert out["out"][0][0]["response"]["q"] == "?query=47.6,-122.3"


def test_point_in_polygon(svc):
    df = DataFrame({"la": np.array([47.6, 80.0]), "lo": np.array([1.0, 2.0])})
    c = CheckPointInPolygon(url=svc + "/geofence", output_col="out",
                            error_col="err")
    c.set_vector_param("lat", "la")
    c.set_vector_param("lon", "lo")
    out = c.transform(df)
    assert out["out"][0]["result"]["isInside"] is True
    assert out["out"][1]["result"]["isInside"] is False


def test_document_translator_polls(svc):
    df = DataFrame({"src": object_col(["http://blob/in"])})
    t = DocumentTranslator(url=svc + "/docbatches", output_col="out",
                           error_col="err", polling_delay_ms=10,
                           target_url="http://blob/out",
                           target_language="fr")
    t.set_vector_param("source_url", "src")
    out = t.transform(df)
    assert out["err"][0] is None
    assert out["out"][0]["status"] == "Succeeded"


def test_form_ontology_learner():
    forms = [
        {"analyzeResult": {"documentResults": [{"fields": {
            "Total": {"type": "number", "valueNumber": 12.5, "text": "12.5"},
            "Vendor": {"type": "string", "valueString": "ACME"}}}]}},
        {"analyzeResult": {"documentResults": [{"fields": {
            "Total": {"type": "number", "valueNumber": 3.0},
            "Date": {"type": "date", "valueDate": "2021-01-01"}}}]}},
    ]
    df = DataFrame({"form": object_col(forms)})
    model = FormOntologyLearner(input_col="form", output_col="onto").fit(df)
    assert set(model.get("ontology")) == {"Total", "Vendor", "Date"}
    out = model.transform(df)
    assert out["onto"][0] == {"Total": 12.5, "Vendor": "ACME", "Date": None}
    assert out["onto"][1]["Date"] == "2021-01-01"


def test_tts_escapes_xml(svc, tmp_path):
    p = str(tmp_path / "amp.wav")
    df = DataFrame({"text": object_col(["AT&T <rocks>"]),
                    "outputFile": object_col([p])})
    t = TextToSpeech(url=svc + "/tts", error_col="err")
    t.set_vector_param("text", "text")
    out = t.transform(df)
    assert out["err"][0] is None     # mock asserts valid ssml content-type


def test_stt_sdk_column_bound_language(svc):
    df = DataFrame({"audio": object_col([b"\x02" * 40000]),
                    "lang": object_col(["de-DE"])})
    t = SpeechToTextSDK(url=svc + "/stt", chunk_bytes=32768,
                        output_col="out", error_col="err")
    t.set_vector_param("audio_data", "audio")
    t.set_vector_param("language", "lang")
    out = t.transform(df)
    results = out["out"][0]
    assert len(results) == 2
    assert results[0]["DisplayText"].endswith("de-DE")


def test_custom_model_urls_and_flatteners():
    """Custom-model trio builds /{modelId} URLs per row (reference
    prepareUrl, FormRecognizer.scala:284-360); flatteners mirror
    FormsFlatteners (:84-166)."""
    from mmlspark_tpu.core.dataframe import object_col
    from mmlspark_tpu.services import (AnalyzeCustomModel, GetCustomModel,
                                       flatten_document_results,
                                       flatten_model_list,
                                       flatten_page_results,
                                       flatten_read_results)

    a = AnalyzeCustomModel(url="http://h/custom/models")
    a.set_scalar_param("model_id", "m-1")
    a.set_scalar_param("include_text_details", True)
    assert a._full_url({}) == \
        "http://h/custom/models/m-1/analyze?includeTextDetails=true"

    g = GetCustomModel(url="http://h/custom/models")
    g.set_scalar_param("model_id", "m-2")
    g.set_scalar_param("include_keys", True)
    assert g._full_url({}) == "http://h/custom/models/m-2?includeKeys=true"
    assert g.get("method") == "GET"

    resp = {"analyzeResult": {
        "readResults": [{"lines": [{"text": "Total"}, {"text": "42"}]}],
        "pageResults": [{"keyValuePairs": [
            {"key": {"text": "Total"}, "value": {"text": "42"}}],
            "tables": [{"cells": [{"text": "a"}, {"text": "b"}]}]}],
        "documentResults": [{"fields": {
            "Total": {"type": "number", "valueNumber": 42.0}}}]}}
    col = object_col([resp, None])
    assert flatten_read_results(col)[0] == "Total 42"
    assert flatten_read_results(col)[1] is None
    pages = flatten_page_results(col)[0]
    assert "key: Total value: 42" in pages and "a | b" in pages
    docs = flatten_document_results(col)[0]
    assert '"valueNumber": 42.0' in docs
    models = object_col([{"modelList": [{"modelId": "m1"},
                                        {"modelId": "m2"}]}])
    assert flatten_model_list(models)[0] == "m1 m2"


def test_add_documents_batches_and_actions(svc):
    """AddDocuments uploads {"value": [...]} batches with api-key auth and
    every row of a batch receives the batch's indexing response
    (reference AzureSearch.scala AddDocuments)."""
    from mmlspark_tpu.services import AddDocuments

    df = DataFrame({"id": object_col(["a", "b", "c"]),
                    "@search.action": object_col(
                        ["upload", "merge", "upload"])})
    t = AddDocuments(url=svc + "/search/index/docs", output_col="out",
                     error_col="err", batch_size=2)
    t.set_scalar_param("subscription_key", "sk")
    out = t.transform(df)
    # batch 1 = rows 0,1; batch 2 = row 2 — actions echo per doc
    assert out["out"][0]["value"][1]["action_seen"] == "merge"
    assert out["out"][0] == out["out"][1]          # same batch response
    assert len(out["out"][2]["value"]) == 1
    assert all(e is None for e in out["err"])


def test_conversation_transcription_chunks_with_participants(svc):
    """ConversationTranscription streams chunks like SpeechToTextSDK and
    forwards the validated participants declaration on each request."""
    from mmlspark_tpu.services import ConversationTranscription

    wav = bytes(range(256)) * 300          # 76,800 bytes → 3 chunks @32768
    df = DataFrame({"audio": object_col([wav])})
    t = ConversationTranscription(url=svc + "/transcribe",
                                  output_col="out", error_col="err")
    t.set_vector_param("audio_data", "audio")
    t.set_scalar_param(
        "participants_json",
        '[{"name": "ana", "preferredLanguage": "en-US"}]')
    out = t.transform(df)
    assert out["err"][0] is None
    assert len(out["out"][0]) == 3
    assert all(r["SpeakerId"] == "guest-0" for r in out["out"][0])

    bad = ConversationTranscription(url=svc + "/transcribe",
                                    output_col="out", error_col="err")
    bad.set_vector_param("audio_data", "audio")
    bad.set_scalar_param("participants_json", "{not json")
    res = bad.transform(df)
    assert "not valid JSON" in res["err"][0]["reasonPhrase"]


def test_add_documents_excludes_column_bound_key(svc):
    """A column-bound API key must never be uploaded into the index."""
    from mmlspark_tpu.services import AddDocuments

    df = DataFrame({"id": object_col(["a"]),
                    "keycol": object_col(["sk"])})
    t = AddDocuments(url=svc + "/search/index/docs", output_col="out",
                     error_col="err")
    t.set_vector_param("subscription_key", "keycol")
    out = t.transform(df)
    assert out["err"][0] is None
    # the doc carries id + defaulted action, NOT the key column
    assert out["out"][0]["value"][0]["fields_seen"] == ["@search.action",
                                                        "id"]
    # and the search convention header is the class default
    assert AddDocuments(url="http://x/").get("key_header") == "api-key"


def test_dictionary_examples_malformed_row_lands_in_error_col(svc):
    """A non-pair value errors its own row instead of aborting the batch
    (the framework's one-malformed-row invariant)."""
    from mmlspark_tpu.services import DictionaryExamples

    df = DataFrame({"pair": object_col([5, ("fly", "volar")])})
    t = DictionaryExamples(url=svc + "/dictionary-unused",
                           output_col="out", error_col="err")
    t.set_vector_param("text_and_translation", "pair")
    t.set_scalar_param("from_language", "en")
    t.set_scalar_param("to_language", "es")
    out = t.transform(df)
    assert out["out"][0] is None
    assert "pair" in out["err"][0]["reasonPhrase"]
    # row 1 proceeded to a real request (404 from the fake path, not a crash)
    assert out["err"][1] is not None


def test_find_similar_face_null_required_param_skips():
    """Null face_id is a skip (null/null), not a validation 400."""
    from mmlspark_tpu.services import FindSimilarFace

    df = DataFrame({"fid": object_col([None])})
    t = FindSimilarFace(url="http://localhost:1/x", output_col="out",
                        error_col="err")
    t.set_vector_param("face_id", "fid")
    out = t.transform(df)
    assert out["out"][0] is None and out["err"][0] is None


def test_model_url_escapes_and_merges_query():
    from mmlspark_tpu.services.form import _model_url

    assert _model_url("http://h/models?api-version=2.1", "m 1/x",
                      {"includeKeys": "true"}, suffix="/analyze") == \
        "http://h/models/m%201%2Fx/analyze?api-version=2.1&includeKeys=true"


def test_flatten_page_results_tolerates_null_key():
    from mmlspark_tpu.services import flatten_page_results

    col = object_col([{"analyzeResult": {"pageResults": [
        {"keyValuePairs": [{"key": None, "value": {"text": "x"}}]}]}}])
    out = flatten_page_results(col)[0]
    assert "value: x" in out
