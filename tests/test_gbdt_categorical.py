"""GBDT categorical feature handling (parity: LightGBMBase.scala:168-199 →
native categorical_feature; here a label-ordered rank encoding makes
threshold splits select contiguous runs of label-sorted categories)."""

import numpy as np
import pytest

from mmlspark_tpu.core import DataFrame
from mmlspark_tpu.core.pipeline import PipelineStage
from mmlspark_tpu.models.gbdt.categorical import CategoricalEncoder
from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier


class TestCategoricalEncoder:
    def test_label_ordering(self):
        # categories 0..3 with mean targets 0.9, 0.1, 0.8, 0.2
        X = np.array([[0], [0], [1], [1], [2], [2], [3], [3]], np.float64)
        y = np.array([1, 0.8, 0.1, 0.1, 0.9, 0.7, 0.2, 0.2])
        enc = CategoricalEncoder([0]).fit(X, y)
        t = enc.transform(X)[:, 0]
        # ranks order: 1 (lowest mean) < 3 < 2 < 0
        assert t[2] < t[6] < t[4] and t[4] < t[0]

    def test_unseen_becomes_nan(self):
        X = np.array([[1.0], [2.0]])
        enc = CategoricalEncoder([0]).fit(X, np.array([0.0, 1.0]))
        out = enc.transform(np.array([[3.0], [1.0]]))
        assert np.isnan(out[0, 0]) and out[1, 0] == 0.0

    def test_roundtrip_dict(self):
        X = np.array([[5.0], [7.0], [5.0]])
        enc = CategoricalEncoder([0]).fit(X, np.array([1.0, 0.0, 1.0]))
        enc2 = CategoricalEncoder.from_dict(enc.to_dict())
        np.testing.assert_array_equal(enc2.transform(X), enc.transform(X))


def _interleaved_problem(n=400, seed=0):
    """y = 1 for categories {0, 2}, 0 for {1, 3} — in code order the classes
    interleave, so ONE ordinal threshold cannot separate them; the label
    ordering groups {0,2} | {1,3} and a single split suffices."""
    rng = np.random.default_rng(seed)
    cat = rng.integers(0, 4, n).astype(np.float64)
    y = np.isin(cat, [0, 2]).astype(np.float64)
    feats = np.stack([cat, rng.normal(0, 1, n)], axis=1)
    return DataFrame({"features": [f for f in feats], "label": y}), y


class TestCategoricalTraining:
    def test_single_split_separates_interleaved_categories(self):
        df, y = _interleaved_problem()
        # depth 1, one tree: only the categorical encoding can win here
        cat = LightGBMClassifier(num_iterations=1, max_depth=1,
                                 min_data_in_leaf=1,
                                 categorical_feature=[0]).fit(df)
        acc_cat = (np.asarray(cat.transform(df)["prediction"]) == y).mean()
        plain = LightGBMClassifier(num_iterations=1, max_depth=1,
                                   min_data_in_leaf=1).fit(df)
        acc_plain = (np.asarray(plain.transform(df)["prediction"])
                     == y).mean()
        assert acc_cat == 1.0
        assert acc_plain < 0.8  # a single ordinal threshold cannot do it

    def test_save_load_preserves_encoding(self, tmp_path):
        df, y = _interleaved_problem(seed=1)
        model = LightGBMClassifier(num_iterations=3, max_depth=2,
                                   categorical_feature=[0]).fit(df)
        expect = np.stack([np.asarray(v) for v in
                           model.transform(df)["probability"]])
        model.save(str(tmp_path / "m"))
        m2 = PipelineStage.load(str(tmp_path / "m"))
        got = np.stack([np.asarray(v) for v in
                        m2.transform(df)["probability"]])
        np.testing.assert_allclose(got, expect, rtol=1e-6)

    def test_shap_and_leaf_paths_consistent(self):
        df, y = _interleaved_problem(seed=2)
        model = LightGBMClassifier(num_iterations=2, max_depth=2,
                                   categorical_feature=[0],
                                   features_shap_col="shap",
                                   leaf_prediction_col="leaf").fit(df)
        out = model.transform(df)
        shap = np.stack(list(out["shap"]))
        raw = model._booster.raw_score(
            np.stack(list(df["features"])).astype(np.float32))
        # SHAP efficiency: contributions + expected value sum to raw score
        np.testing.assert_allclose(shap.sum(axis=1), raw, rtol=1e-3,
                                   atol=1e-3)

    def test_valid_set_eval_uses_encoding(self):
        df, y = _interleaved_problem(seed=3)
        ind = np.zeros(len(df), dtype=bool)
        ind[300:] = True
        df2 = df.with_column("is_valid", ind)
        from mmlspark_tpu.models.gbdt.train import train as gbdt_train
        X = np.stack(list(df2["features"]))
        eval_log = []
        gbdt_train({"objective": "binary", "num_iterations": 5,
                    "max_depth": 1, "min_data_in_leaf": 1,
                    "categorical_feature": [0], "metric": "auc"},
                   X[:300], y[:300],
                   valid_sets=[(X[300:], y[300:])], eval_log=eval_log)
        assert eval_log and eval_log[-1]["auc"] > 0.95


class TestReviewRegressions:
    def test_early_stopping_keeps_encoder(self):
        df, y = _interleaved_problem(seed=5)
        from mmlspark_tpu.models.gbdt.train import train as gbdt_train
        X = np.stack(list(df["features"]))
        booster = gbdt_train(
            {"objective": "binary", "num_iterations": 30, "max_depth": 1,
             "min_data_in_leaf": 1, "categorical_feature": [0],
             "early_stopping_round": 2, "metric": "auc"},
            X[:300], y[:300], valid_sets=[(X[300:], y[300:])])
        assert booster.cat_encoder is not None  # survives truncation
        pred = (booster.predict(X.astype(np.float32)) > 0.5).astype(float)
        assert (pred == y).mean() == 1.0

    def test_merge_keeps_encoder(self):
        df, y = _interleaved_problem(seed=6)
        from mmlspark_tpu.models.gbdt.train import train as gbdt_train
        X = np.stack(list(df["features"]))
        params = {"objective": "binary", "num_iterations": 2, "max_depth": 1,
                  "min_data_in_leaf": 1, "categorical_feature": [0]}
        b = gbdt_train(params, X, y)
        merged = b.merge(b.truncated(1))
        assert merged.cat_encoder is not None

    def test_warm_start_without_encoder_rejected(self):
        df, y = _interleaved_problem(seed=7)
        from mmlspark_tpu.models.gbdt.train import train as gbdt_train
        X = np.stack(list(df["features"]))
        plain = gbdt_train({"objective": "binary", "num_iterations": 2,
                            "max_depth": 1}, X, y)
        with pytest.raises(ValueError, match="warm-start"):
            gbdt_train({"objective": "binary", "num_iterations": 2,
                        "max_depth": 1, "categorical_feature": [0]},
                       X, y, init_model=plain)

    def test_transform_preserves_float32(self):
        X = np.array([[1.0, 5.0], [2.0, 6.0]], dtype=np.float32)
        enc = CategoricalEncoder([0]).fit(X, np.array([0.0, 1.0]))
        assert enc.transform(X).dtype == np.float32
