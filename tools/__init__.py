# repo-local tooling namespace (not shipped in the wheel)
