"""Text and JSON reporters for tpulint findings."""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, Optional, Sequence, TextIO

from .core import Finding, all_rules

def text_report(findings: Sequence[Finding], stream: TextIO,
                baselined: Sequence[Finding] = (),
                stale: Optional[Dict[str, int]] = None,
                parse_errors: Sequence[tuple] = ()) -> None:
    for rel, err in parse_errors:
        stream.write(f"{rel}:1:1: PARSE error: {err}\n")
    for f in findings:
        stream.write(f"{f.location()}: {f.rule} {f.severity}: {f.message}\n")
        if f.snippet:
            stream.write(f"    {f.snippet}\n")
    by_sev = Counter(f.severity for f in findings)
    summary = ", ".join(f"{n} {sev}" for sev, n in sorted(by_sev.items())) \
        or "no findings"
    stream.write(f"tpulint: {summary}")
    if baselined:
        stream.write(f" ({len(baselined)} baselined)")
    stream.write("\n")
    if stale:
        stream.write(f"tpulint: {sum(stale.values())} stale baseline "
                     f"entr{'y' if sum(stale.values()) == 1 else 'ies'} "
                     f"(fixed findings) — regenerate with "
                     f"scripts/gen_tpulint_baseline.py:\n")
        for fp in sorted(stale):
            stream.write(f"    {fp} x{stale[fp]}\n")

def json_report(findings: Sequence[Finding], stream: TextIO,
                baselined: Sequence[Finding] = (),
                stale: Optional[Dict[str, int]] = None,
                parse_errors: Sequence[tuple] = ()) -> None:
    def row(f: Finding) -> dict:
        return {"rule": f.rule, "path": f.path, "line": f.line,
                "col": f.col + 1, "severity": f.severity,
                "message": f.message, "snippet": f.snippet}

    payload = {
        "findings": [row(f) for f in findings],
        "baselined": [row(f) for f in baselined],
        "stale_baseline": dict(sorted((stale or {}).items())),
        "parse_errors": [{"path": p, "error": e} for p, e in parse_errors],
        "summary": dict(Counter(f.severity for f in findings)),
    }
    json.dump(payload, stream, indent=2)
    stream.write("\n")

def rule_catalog(stream: TextIO) -> None:
    """``--list-rules``: the registry, one rule per stanza."""
    for rule in all_rules():
        scope = "project" if rule.project_scope else "module"
        stream.write(f"{rule.code} {rule.name} "
                     f"[{rule.severity}, {scope}-scope]\n")
        for line in rule.doc.splitlines():
            stream.write(f"    {line.strip()}\n")

REPORTERS = {"text": text_report, "json": json_report}
