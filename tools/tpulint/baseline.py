"""Baseline file: accepted pre-existing findings, by fingerprint count.

Format (JSON, sorted keys, stable for diffs):

    {"version": 1,
     "fingerprints": {"mmlspark_tpu/ops/x.py::TPU004::np.asarray(v)": 2}}

Fingerprints carry no line numbers (see :func:`tpulint.core.fingerprint`),
so edits elsewhere in a file do not churn the baseline; counts let the same
hazardous line appear N times without masking an N+1th copy.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, List, Sequence, Tuple

from .core import Finding, fingerprint

VERSION = 1


def counts(findings: Sequence[Finding]) -> Dict[str, int]:
    return dict(Counter(fingerprint(f) for f in findings))


def dump(findings: Sequence[Finding], path: str) -> None:
    payload = {"version": VERSION,
               "fingerprints": dict(sorted(counts(findings).items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def load(path: str) -> Dict[str, int]:
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    if payload.get("version") != VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{payload.get('version')!r}")
    fps = payload.get("fingerprints", {})
    if not all(isinstance(v, int) and v > 0 for v in fps.values()):
        raise ValueError(f"malformed baseline counts in {path}")
    return dict(fps)


def apply(findings: Sequence[Finding], baseline: Dict[str, int],
          ) -> Tuple[List[Finding], List[Finding], Dict[str, int]]:
    """Split findings into (new, baselined) and report stale entries.

    Occurrences of a fingerprint beyond its baselined count are *new*;
    baseline entries with no surviving occurrences are *stale* (the hazard
    was fixed — regenerate the baseline to shrink it).
    """
    budget = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:          # findings arrive location-sorted
        fp = fingerprint(f)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = {fp: n for fp, n in budget.items() if n > 0}
    return new, old, stale
