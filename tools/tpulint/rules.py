"""Per-module rules: the jit-boundary hazards (TPU001-TPU004), the
ad-hoc-telemetry check (TPU007), the ad-hoc-id-minting check (TPU008),
the observability-hygiene checks (TPU010, TPU011, TPU015), the
ad-hoc-hash-routing check (TPU016), and the unsharded-pallas-call
check (TPU017).

Each rule is an ``ast.NodeVisitor`` that tracks two context stacks while it
walks a module — the innermost *jit context* (entered through a
``@jax.jit`` decoration, a ``functools.partial(jax.jit, ...)`` decoration,
a name later wrapped as ``jax.jit(fn)``, or a ``jax.jit(lambda ...)``
argument) and the *loop depth* (reset at function boundaries: work inside a
nested ``def`` is not per-iteration work of the enclosing loop).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from .core import (Finding, ModuleInfo, Rule, jit_call_target,
                   jit_decoration, register_rule)

#: attribute reads that are static under tracing (safe to branch on)
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                "weak_type"}

#: calls whose result is trace-time static even on tracer arguments
SAFE_TEST_CALLS = {"len", "isinstance", "hasattr", "getattr", "callable",
                   "jax.core.is_concrete"}

_SCI_RE = re.compile(r"\d[eE][-+]?\d")


class _JitCtx:
    __slots__ = ("tracer_params", "static_params")

    def __init__(self, tracer_params: Set[str], static_params: Set[str]):
        self.tracer_params = tracer_params
        self.static_params = static_params


class _ContextVisitor(ast.NodeVisitor):
    """Shared walk: maintains jit-context and loop-depth stacks."""

    def __init__(self, module: ModuleInfo):
        self.module = module
        self.findings: List[Finding] = []
        self._jit_stack: List[_JitCtx] = []
        self._loop_stack: List[int] = [0]   # per-function loop depth

    # -- context accessors ---------------------------------------------------
    @property
    def jit_ctx(self) -> Optional[_JitCtx]:
        return self._jit_stack[-1] if self._jit_stack else None

    @property
    def loop_depth(self) -> int:
        return self._loop_stack[-1]

    # -- stack maintenance ---------------------------------------------------
    def _function_params(self, fn, static: Set[str]) -> Set[str]:
        args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        tracers = {n for n in names if n not in static
                   and n not in ("self", "cls")}
        return tracers

    def visit_FunctionDef(self, node):
        static = jit_decoration(self.module, node)
        entered_jit = False
        if static is not None:
            self._jit_stack.append(
                _JitCtx(self._function_params(node, static), static))
            entered_jit = True
        self._loop_stack.append(0)
        self.enter_function(node, entered_jit)
        self.generic_visit(node)
        self._loop_stack.pop()
        if entered_jit:
            self._jit_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def enter_function(self, node, entered_jit: bool) -> None:
        """Hook for rules that care about function entry."""

    def visit_Lambda(self, node):
        self._loop_stack.append(0)
        self.generic_visit(node)
        self._loop_stack.pop()

    def _visit_loop(self, node):
        self.handle_loop(node)
        # the loop header (iter/test) is NOT per-iteration host work at the
        # same rank as the body; only the body/orelse run per iteration
        for header in ("target", "iter", "test"):
            child = getattr(node, header, None)
            if child is not None:
                self.visit(child)
        self._loop_stack[-1] += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self._loop_stack[-1] -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop
    visit_AsyncFor = _visit_loop

    def handle_loop(self, node) -> None:
        """Hook for rules that care about loop statements themselves."""

    # -- jitted lambdas ------------------------------------------------------
    def visit_Call(self, node):
        inner = jit_call_target(self.module, node)
        handled = False
        if inner is not None:
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    params = {a.arg for a in arg.args.posonlyargs
                              + arg.args.args + arg.args.kwonlyargs}
                    self._jit_stack.append(_JitCtx(params, set()))
                    self._loop_stack.append(0)
                    self.generic_visit(arg)
                    self._loop_stack.pop()
                    self._jit_stack.pop()
                    handled = True
        self.handle_call(node)
        if not handled:
            self.generic_visit(node)
        else:
            # non-lambda children (keywords, func expr) still get walked
            self.visit(node.func)
            for arg in node.args:
                if not isinstance(arg, ast.Lambda):
                    self.visit(arg)
            for kw in node.keywords:
                self.visit(kw.value)

    def handle_call(self, node: ast.Call) -> None:
        """Hook for rules that care about calls."""


# ---------------------------------------------------------------------------
# TPU001 — host sync inside jitted code or per-batch loops
# ---------------------------------------------------------------------------

#: calls that force a device→host round-trip (or concretize a tracer)
HOST_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready",
                   "numpy.asarray", "numpy.array",
                   "numpy.ascontiguousarray", "numpy.copy"}
#: method names that concretize/serialize when hit on a traced/device array
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready", "copy_to_host",
                     "__array__"}
#: the loop-context subset: per-iteration syncs that serialize the pipeline
LOOP_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
BUILTIN_CASTS = {"float", "int", "bool", "complex"}


@register_rule
class HostSyncInJit(Rule):
    code = "TPU001"
    name = "host-sync-in-jit"
    severity = "error"
    doc = ("jax.device_get / np.asarray / float() / .item() on arrays "
           "inside jitted functions (concretization error or silent "
           "constant-folding), and per-iteration device_get / "
           "block_until_ready inside batch loops (serializes the feed/drain "
           "pipeline the runner pipelines; drain once at the end instead).")

    def check(self, module: ModuleInfo):
        visitor = _TPU001(module, self)
        visitor.visit(module.tree)
        return iter(visitor.findings)


class _TPU001(_ContextVisitor):
    def __init__(self, module, rule):
        super().__init__(module)
        self.rule = rule

    def handle_call(self, node: ast.Call):
        name = self.module.dotted(node.func)
        if self.jit_ctx is not None:
            if name in HOST_SYNC_CALLS:
                self.findings.append(self.rule.finding(
                    self.module, node,
                    f"{name}() inside jitted code forces a host sync / "
                    f"concretization at trace time; keep data on device "
                    f"(jnp.*) or hoist the host read out of the jit"))
                return
            if name in BUILTIN_CASTS and node.args \
                    and _tracer_reads(node.args[0],
                                      self.jit_ctx.tracer_params,
                                      self.module):
                self.findings.append(self.rule.finding(
                    self.module, node,
                    f"{name}() on a traced value concretizes it "
                    f"(ConcretizationTypeError at best, a baked-in "
                    f"constant at worst); use jnp casts or mark the "
                    f"argument static"))
                return
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in HOST_SYNC_METHODS:
                self.findings.append(self.rule.finding(
                    self.module, node,
                    f".{node.func.attr}() inside jitted code pulls the "
                    f"value to host; jit output should stay a device "
                    f"array"))
                return
        if self.loop_depth > 0:
            per_iter = (name in LOOP_SYNC_CALLS
                        or (isinstance(node.func, ast.Attribute)
                            and node.func.attr == "block_until_ready"))
            if per_iter:
                self.findings.append(self.rule.finding(
                    self.module, node,
                    "per-iteration host sync "
                    f"({name or node.func.attr}) serializes host and "
                    "device; batch the drain after the loop "
                    "(copy_to_host_async + one device_get), "
                    "severity=warning", severity="warning"))


# ---------------------------------------------------------------------------
# TPU002 — jax.jit constructed inside a loop body
# ---------------------------------------------------------------------------

@register_rule
class JitInLoop(Rule):
    code = "TPU002"
    name = "jit-in-loop"
    severity = "error"
    doc = ("jax.jit(...) (or functools.partial(jax.jit, ...)) constructed "
           "inside a loop body: every iteration builds a fresh callable "
           "with an empty executable cache, so steady state recompiles "
           "forever — exactly what tests/test_recompile_probe.py probes "
           "dynamically. Hoist the jit out of the loop or cache it.")

    def check(self, module: ModuleInfo):
        visitor = _TPU002(module, self)
        visitor.visit(module.tree)
        return iter(visitor.findings)


class _TPU002(_ContextVisitor):
    def __init__(self, module, rule):
        super().__init__(module)
        self.rule = rule

    def handle_call(self, node: ast.Call):
        if self.loop_depth > 0 and jit_call_target(self.module, node):
            self.findings.append(self.rule.finding(
                self.module, node,
                "jax.jit constructed inside a loop body — a fresh jit "
                "cache per iteration means a recompile per iteration; "
                "hoist the jitted callable out of the loop"))


# ---------------------------------------------------------------------------
# TPU003 — Python control flow on traced parameters
# ---------------------------------------------------------------------------

@register_rule
class TracerBranch(Rule):
    code = "TPU003"
    name = "tracer-branch"
    severity = "error"
    doc = ("Python if/while on a traced parameter of a jitted function: "
           "the branch either raises ConcretizationTypeError or silently "
           "bakes one path into the executable. Branch on static metadata "
           "(.shape/.dtype/static_argnames) or use lax.cond / "
           "lax.while_loop / jnp.where.")

    def check(self, module: ModuleInfo):
        visitor = _TPU003(module, self)
        visitor.visit(module.tree)
        return iter(visitor.findings)


class _TPU003(_ContextVisitor):
    def __init__(self, module, rule):
        super().__init__(module)
        self.rule = rule

    def visit_If(self, node):
        self._check_test(node, node.test, "if")
        self.generic_visit(node)

    def handle_loop(self, node):
        test = getattr(node, "test", None)
        if test is not None:
            self._check_test(node, test, "while")

    def _check_test(self, stmt, test: ast.AST, kind: str):
        ctx = self.jit_ctx
        if ctx is None or not ctx.tracer_params:
            return
        hits = sorted(_tracer_reads(test, ctx.tracer_params, self.module))
        if hits:
            self.findings.append(self.rule.finding(
                self.module, stmt,
                f"`{kind}` on traced parameter(s) {', '.join(hits)} inside "
                f"jitted code; use lax.cond/lax.while_loop/jnp.where, or "
                f"declare the argument in static_argnames if it is truly "
                f"host-static"))


def _tracer_reads(node: ast.AST, tracers: Set[str],
                  module: ModuleInfo) -> Set[str]:
    """Names of tracer params read *as values* in a test expression.

    Reads under trace-time-static contexts do not count: ``x.shape[0]``,
    ``x.dtype == ...``, ``len(x)``, ``isinstance(x, ...)``, ``x is None``.
    """
    out: Set[str] = set()

    def walk(n: ast.AST, safe: bool):
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            walk(n.value, True)
            return
        if isinstance(n, ast.Call):
            fname = module.dotted(n.func)
            child_safe = safe or fname in SAFE_TEST_CALLS
            walk(n.func, safe)
            for a in list(n.args) + [kw.value for kw in n.keywords]:
                walk(a, child_safe)
            return
        if isinstance(n, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
            # `x is None` tests pytree STRUCTURE, which is trace-static
            for child in ast.iter_child_nodes(n):
                walk(child, True)
            return
        if isinstance(n, ast.Name) and not safe and n.id in tracers:
            out.add(n.id)
            return
        for child in ast.iter_child_nodes(n):
            walk(child, safe)

    walk(node, False)
    return out


# ---------------------------------------------------------------------------
# TPU004 — float64 / python-float dtype leaks toward device code
# ---------------------------------------------------------------------------

#: directories whose modules feed devices directly — dtype-less host
#: coercions here leak float64 into the transfer path
DEVICE_DIRS = {"ops", "nn", "parallel"}

F64_NAMES = {"numpy.float64", "jax.numpy.float64", "float64"}
COERCE_CALLS = {"numpy.asarray", "numpy.array"}


@register_rule
class DtypeLeak(Rule):
    code = "TPU004"
    name = "dtype-leak"
    severity = "warning"
    doc = ("float64 creeping toward jitted code: explicit np.float64 / "
           "'float64' dtypes, dtype-less np.asarray/np.array in "
           "device-feed modules (a Python float list silently becomes "
           "float64 — a new jit signature and a 2x transfer), and bare "
           "scientific-notation float literals inside jitted functions "
           "(weak-typed; under jax_enable_x64 they widen the program).")

    def check(self, module: ModuleInfo):
        visitor = _TPU004(module, self)
        visitor.visit(module.tree)
        return iter(visitor.findings)


class _TPU004(_ContextVisitor):
    def __init__(self, module, rule):
        super().__init__(module)
        self.rule = rule
        parts = set(module.relpath.replace("\\", "/").split("/"))
        self.device_dir = bool(parts & DEVICE_DIRS)
        self._flagged = set()   # node ids, so nested calls don't double-report

    def handle_call(self, node: ast.Call):
        name = self.module.dotted(node.func)
        # float64 constructed or passed as a dtype in device-feed modules;
        # comparisons like ``arr.dtype == np.float64`` are checks, not
        # leaks, so only call-argument/constructor position counts
        if self.device_dir:
            f64_uses = [node.func] if name in F64_NAMES else []
            for sub in list(node.args) + [kw.value for kw in node.keywords]:
                for n in ast.walk(sub):
                    if isinstance(n, (ast.Attribute, ast.Name)) \
                            and self.module.dotted(n) in F64_NAMES:
                        f64_uses.append(n)
            for n in f64_uses:
                if id(n) in self._flagged:
                    continue
                self._flagged.add(id(n))
                self.findings.append(self.rule.finding(
                    self.module, n,
                    "explicit float64 on the device-feed path; TPUs have "
                    "no f64 ALU — use float32 (or bfloat16) unless this "
                    "is deliberate host-side math"))
        # astype("float64") / dtype="float64" string spellings
        for sub in (list(node.args) + [kw.value for kw in node.keywords]
                    if self.device_dir else []):
            if isinstance(sub, ast.Constant) and sub.value == "float64":
                self.findings.append(self.rule.finding(
                    self.module, sub,
                    "'float64' dtype string on the device-feed path; use "
                    "float32/bfloat16"))
        if self.device_dir and name in COERCE_CALLS:
            has_dtype = any(kw.arg == "dtype" for kw in node.keywords) \
                or len(node.args) > 1
            if not has_dtype:
                self.findings.append(self.rule.finding(
                    self.module, node,
                    f"dtype-less {name}() in a device-feed module: a "
                    f"Python float payload becomes float64 — a fresh jit "
                    f"signature and double transfer bytes; pass an "
                    f"explicit dtype or normalize f64→f32"))
        # bare scientific literals in jitted code (1e-6-style epsilons)
        if self.jit_ctx is not None and name is not None \
                and name.split(".")[0] in ("jax", "lax"):
            for sub in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, float):
                    seg = ast.get_source_segment(self.module.source, sub)
                    if seg and _SCI_RE.search(seg):
                        self.findings.append(self.rule.finding(
                            self.module, sub,
                            f"bare float literal {seg} in jitted code "
                            f"relies on weak-type promotion; under "
                            f"jax_enable_x64 it widens the program — pin "
                            f"it with a dtype-matched constant",
                            severity="info"))


# ---------------------------------------------------------------------------
# TPU007 — ad-hoc telemetry
# ---------------------------------------------------------------------------

#: wall-clock sources whose accumulated deltas belong in the registry
CLOCK_CALLS = {"time.perf_counter", "time.perf_counter_ns",
               "time.monotonic", "time.monotonic_ns",
               "time.time", "time.time_ns"}


def _clock_accumulation(module: ModuleInfo, fn: ast.AST) -> Optional[ast.AST]:
    """The statement where ``fn`` accumulates a wall-clock delta into
    object state, or None. Two shapes, both requiring the clock read and
    the store in the SAME method (calling out to a shared aggregator like
    ``StageCounters.add`` is not accumulation):

    - ``self.x += time.perf_counter() - t0`` / ``d[k] += now - last`` —
      an AugAssign onto an attribute/subscript whose RHS involves a clock
      value;
    - ``self.t[name] = self.t.get(name, 0) + (now - last)`` — an Assign
      onto a subscript whose RHS involves a clock value.

    "Involves a clock value" means the RHS does *arithmetic* (a BinOp) on
    a clock call or a local name assigned from one in this method — delta
    math like ``now - last``. Storing a bare timestamp
    (``{"last_seen": now}``, heartbeat registries) or unrelated state next
    to a clock read (``self._slot[i] = None``) stays quiet: those are
    state, not a metrics island.
    """
    clock_names: Set[str] = set()
    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call) \
                and module.dotted(stmt.value.func) in CLOCK_CALLS:
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    clock_names.add(t.id)

    def is_clock(sub: ast.AST) -> bool:
        return ((isinstance(sub, ast.Call)
                 and module.dotted(sub.func) in CLOCK_CALLS)
                or (isinstance(sub, ast.Name) and sub.id in clock_names))

    def clock_arithmetic(expr: ast.AST) -> bool:
        return any(isinstance(sub, ast.BinOp)
                   and any(is_clock(s) for s in ast.walk(sub))
                   for sub in ast.walk(expr))

    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.AugAssign) \
                and isinstance(stmt.target, (ast.Attribute, ast.Subscript)) \
                and clock_arithmetic(stmt.value):
            return stmt
        if isinstance(stmt, ast.Assign) \
                and any(isinstance(t, ast.Subscript) for t in stmt.targets) \
                and clock_arithmetic(stmt.value):
            return stmt
    return None


@register_rule
class AdhocTelemetry(Rule):
    code = "TPU007"
    name = "adhoc-telemetry"
    severity = "warning"
    doc = ("A class under mmlspark_tpu/ accumulating wall-clock deltas "
           "into its own state without touching mmlspark_tpu.observability "
           "— a private metrics island invisible to GET /metrics and "
           "bench telemetry (the pre-registry fragmentation this package "
           "exists to end). Mirror the measurement into a registry metric; "
           "importing the observability package marks the module as "
           "integrated and quiets the rule.")

    def check(self, module: ModuleInfo):
        rel = module.relpath.replace("\\", "/")
        if not rel.startswith("mmlspark_tpu/") \
                or rel.startswith("mmlspark_tpu/observability/"):
            return iter(())
        # a module that imports the observability package has a path for
        # its measurements to reach the registry — integrated, not ad hoc
        for target in module.aliases.values():
            if "observability" in target.split("."):
                return iter(())
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for fn in node.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                hit = _clock_accumulation(module, fn)
                if hit is not None:
                    findings.append(self.finding(
                        module, hit,
                        f"'{node.name}.{fn.name}' accumulates wall-clock "
                        f"deltas outside the metrics registry; mirror them "
                        f"into mmlspark_tpu.observability (Counter or "
                        f"Histogram) so /metrics and bench telemetry see "
                        f"them"))
                    break   # one finding per class is signal enough
        return iter(findings)


#: the id-shaped context TPU008 polices: a uuid4 minted into anything
#: named like a request/trace/span id
_ID_CONTEXT_RE = re.compile(r"request|trace|span", re.IGNORECASE)


@register_rule
class AdhocIdMinting(Rule):
    code = "TPU008"
    name = "adhoc-id-minting"
    severity = "warning"
    doc = ("A request/trace/span id minted with ``uuid.uuid4()`` outside "
           "mmlspark_tpu/observability/tracing.py. Ids minted ad hoc "
           "don't join the trace-context machinery: the routing table, "
           "journal, event log, and /debug/traces each end up keyed by "
           "ids nothing else can correlate. Mint through "
           "``tracing.new_request_id()`` / ``new_trace_id()`` / "
           "``new_span_id()`` instead. uuid4 uses with no request/trace/"
           "span context (model artifact ids, run ids) stay quiet.")

    #: the one module allowed to mint — THE id source the doc points at
    EXEMPT = "mmlspark_tpu/observability/tracing.py"

    def _stmt_text(self, module: ModuleInfo, stmt: ast.stmt) -> str:
        end = getattr(stmt, "end_lineno", stmt.lineno)
        return "\n".join(module.lines[stmt.lineno - 1:end])

    def check(self, module: ModuleInfo):
        rel = module.relpath.replace("\\", "/")
        if not rel.startswith("mmlspark_tpu/") or rel == self.EXEMPT:
            return iter(())
        findings: List[Finding] = []
        for stmt in ast.walk(module.tree):
            # simple statements only: a compound statement (If/For/def)
            # would re-flag every uuid4 its body already reported
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign, ast.Expr, ast.Return)):
                continue
            has_uuid4 = any(
                isinstance(sub, ast.Call)
                and module.dotted(sub.func) == "uuid.uuid4"
                for sub in ast.walk(stmt))
            if not has_uuid4:
                continue
            if not _ID_CONTEXT_RE.search(self._stmt_text(module, stmt)):
                continue
            findings.append(self.finding(
                module, stmt,
                "request/trace/span id minted with uuid.uuid4() outside "
                "observability/tracing.py; use tracing.new_request_id() / "
                "new_trace_id() / new_span_id() so the id joins the trace "
                "context (routing table, journal, /debug/traces)"))
        return iter(findings)


# TPU009 polices hand-rolled failure handling on the serving/io data
# planes; the reliability package is the sanctioned home for retry loops
# (and is outside both scopes anyway — listed for the doc, and as a guard
# should io/ or serving/ ever absorb it)
_RESILIENCE_SCOPES = ("mmlspark_tpu/serving/", "mmlspark_tpu/io/")
_RESILIENCE_EXEMPT = "mmlspark_tpu/reliability/"


def _loop_body_nodes(loop: ast.AST):
    """Every node inside a loop's body, excluding nested function/lambda
    bodies (their sleeps are not per-iteration work of this loop)."""
    stack = list(loop.body) + list(loop.orelse)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


@register_rule
class AdhocResilience(Rule):
    code = "TPU009"
    name = "adhoc-resilience"
    severity = "warning"
    doc = ("Hand-rolled failure handling on a serving/io path: a retry "
           "loop (a loop that time.sleep()s and also catches or "
           "continues past failures) outside mmlspark_tpu/reliability/, "
           "or a broad `except: pass` that swallows a failure leaving no "
           "metric or event behind. Route retries through "
           "reliability.RetryPolicy (budgeted backoff + jitter + "
           "mmlspark_retry_attempts_total) and surface swallowed "
           "failures through observability.log_event; genuinely-benign "
           "swallows and reference-parity retry ladders carry an inline "
           "disable comment with the justification.")

    def check(self, module: ModuleInfo):
        rel = module.relpath.replace("\\", "/")
        if (not rel.startswith(_RESILIENCE_SCOPES)
                or rel.startswith(_RESILIENCE_EXEMPT)):
            return iter(())
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ExceptHandler):
                broad = node.type is None or module.dotted(node.type) in (
                    "Exception", "BaseException")
                if broad and len(node.body) == 1 \
                        and isinstance(node.body[0], ast.Pass):
                    findings.append(self.finding(
                        module, node,
                        "broad except swallows the failure with `pass` — "
                        "no metric, no event, no log; emit "
                        "observability.log_event (or narrow the except) "
                        "so the failure stays diagnosable"))
            elif isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                sleeps = catches = continues = False
                for sub in _loop_body_nodes(node):
                    if isinstance(sub, ast.Call) \
                            and module.dotted(sub.func) == "time.sleep":
                        sleeps = True
                    elif isinstance(sub, ast.ExceptHandler):
                        catches = True
                    elif isinstance(sub, ast.Continue):
                        continues = True
                if sleeps and (catches or continues):
                    findings.append(self.finding(
                        module, node,
                        "ad-hoc retry loop (sleep + catch/continue); use "
                        "reliability.RetryPolicy — budgeted backoff with "
                        "full jitter, deadline-aware, and counted in "
                        "mmlspark_retry_attempts_total"))
        return iter(findings)


#: host materialization calls TPU010 polices inside stage hot paths
_HOST_ROUNDTRIP_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get"}
_STAGE_BASE_RE = re.compile(r"(Transformer|Model)$")
_STAGE_METHODS = {"transform", "_transform"}


@register_rule
class HostRoundtrip(Rule):
    code = "TPU010"
    name = "host-roundtrip"
    severity = "warning"
    doc = ("``np.asarray``/``np.array``/``jax.device_get`` applied to a "
           "subscripted stage input inside a pipeline stage's "
           "``transform``/``_transform`` hot path. On a device-resident "
           "column that call silently materializes the data on host — the "
           "per-stage d2h+h2d round-trip the residency layer exists to "
           "eliminate (one h2d at ingest, one d2h at the sink). Keep the "
           "slice on device: feed ``device_column(...).device_array()`` "
           "views (see BatchRunner's device-feed path) and defer host "
           "materialization to ``DataFrame.to_host``. Genuinely host-only "
           "sites (metadata vectors, index arrays) carry an inline "
           "disable comment with the justification.")

    def check(self, module: ModuleInfo):
        findings: List[Finding] = []
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if not any(_STAGE_BASE_RE.search(_terminal_name(b) or "")
                       for b in cls.bases):
                continue
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and fn.name in _STAGE_METHODS:
                    # ast.walk(fn) covers nested defs too: the per-batch
                    # closures these methods build ARE the hot path
                    self._scan(module, cls, fn, findings)
        return iter(findings)

    def _scan(self, module: ModuleInfo, cls: ast.ClassDef, fn,
              findings: List[Finding]) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = module.dotted(node.func)
            if name not in _HOST_ROUNDTRIP_CALLS:
                continue
            subscripted = any(isinstance(sub, ast.Subscript)
                              for arg in node.args
                              for sub in ast.walk(arg))
            if not subscripted:
                continue
            findings.append(self.finding(
                module, node,
                f"'{cls.name}.{fn.name}' materializes a sliced stage "
                f"input on host via {name}(...) — a per-stage round-trip "
                f"for resident columns; slice the device column instead "
                f"and let DataFrame.to_host pay the one sink transfer"))


def _terminal_name(base: ast.AST) -> Optional[str]:
    """Rightmost identifier of a base-class expression (``core.pipeline.
    Transformer`` → ``Transformer``)."""
    while isinstance(base, ast.Attribute):
        return base.attr
    return base.id if isinstance(base, ast.Name) else None


def _quantile_subscript(node: ast.Subscript) -> bool:
    """``sorted(lat)[int(0.99 * len(lat))]``-shaped indexing: the index
    expression does arithmetic on BOTH a quantile-looking float constant
    (strictly between 0 and 1) and a ``len(...)`` call. Plain fraction
    math (``int(0.75 * F)``) and plain indexing (``lat[0]``) stay quiet —
    both ingredients together are what spell "percentile by hand"."""
    idx = node.slice
    has_frac = any(isinstance(sub, ast.Constant)
                   and isinstance(sub.value, float)
                   and 0.0 < sub.value < 1.0
                   for sub in ast.walk(idx))
    has_len = any(isinstance(sub, ast.Call)
                  and isinstance(sub.func, ast.Name)
                  and sub.func.id == "len"
                  for sub in ast.walk(idx))
    return has_frac and has_len


def _timestamp_prune_loop(loop: ast.While) -> bool:
    """``while dq and now - dq[0] > window: dq.popleft()`` — a hand-rolled
    rolling window over a deque of timestamps. The test must age-compare
    the queue head (a ``[0]`` subscript inside subtraction arithmetic) and
    the body must drop it (``popleft()`` or ``pop(0)``); capacity-shaped
    prune loops (``while len(q) > cap``) have no subtraction on ``q[0]``
    and stay quiet."""
    head_aged = any(
        isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Sub)
        and any(isinstance(s, ast.Subscript)
                and isinstance(s.slice, ast.Constant)
                and s.slice.value == 0
                for s in ast.walk(sub))
        for sub in ast.walk(loop.test))
    if not head_aged:
        return False
    for sub in _loop_body_nodes(loop):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            if sub.func.attr == "popleft":
                return True
            if sub.func.attr == "pop" and sub.args \
                    and isinstance(sub.args[0], ast.Constant) \
                    and sub.args[0].value == 0:
                return True
    return False


@register_rule
class AdhocSloWindow(Rule):
    code = "TPU011"
    name = "adhoc-slo-window"
    severity = "warning"
    doc = ("A hand-rolled latency-quantile or rolling-window computation "
           "outside mmlspark_tpu/observability/: percentile-by-sorting "
           "(``sorted(lat)[int(0.99 * len(lat))]`` — O(n log n) per "
           "report, unbounded memory) or a timestamp-deque prune loop "
           "(``while now - dq[0] > window: dq.popleft()``). The SLO "
           "tracker (observability/slo.py) already keeps O(1)-memory "
           "time-bucketed windows with fixed-bucket latency sketches and "
           "serves them at GET /debug/slo — observe into "
           "``observability.get_tracker()`` (or a registry Histogram) "
           "instead of growing another private window.")

    def check(self, module: ModuleInfo):
        rel = module.relpath.replace("\\", "/")
        if not rel.startswith("mmlspark_tpu/") \
                or rel.startswith("mmlspark_tpu/observability/"):
            return iter(())
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Subscript) and _quantile_subscript(node):
                findings.append(self.finding(
                    module, node,
                    "latency quantile computed by sorted-list indexing; "
                    "observe samples into observability.get_tracker() (or "
                    "a registry Histogram) and read p50/p99 from the "
                    "scorecard instead of sorting per report"))
            elif isinstance(node, ast.While) \
                    and _timestamp_prune_loop(node):
                findings.append(self.finding(
                    module, node,
                    "hand-rolled rolling window (timestamp deque pruned "
                    "by age); the SLO tracker's time-bucketed ring keeps "
                    "the same window in O(1) memory — observe into "
                    "observability.get_tracker()"))
        return iter(findings)


#: metric mutators whose keyword arguments are label values
_LABEL_METHODS = {"inc", "set", "observe", "labels"}

#: identifier shapes that mean "this value came off the wire": a URL or
#: path, a header bag, a query string, or a request payload/body/entity
_REQUEST_SOURCE_RE = re.compile(
    r"(^|_)(url|path|headers?|query|payload|body|entity)(_|$)")


#: receiver identifiers that look like telemetry sinks — the repo's
#: ``M_FOO`` / ``_M_FOO`` metric-handle convention plus the obvious
#: metric/tracker/ledger spellings (keeps ``stage.set(url=...)`` param
#: setters and similar non-metric ``.set()`` calls out of scope)
_METRIC_RECEIVER_RE = re.compile(
    r"^_?m_|metric|counter|gauge|histogram|tracker|ledger")


def _metric_receiver(value: ast.AST) -> bool:
    """True when ``value`` (the mutator call's receiver) is plausibly a
    metric handle: a ``.labels(...)`` chain, or an identifier matching
    the metric-handle naming convention."""
    if isinstance(value, ast.Call) \
            and isinstance(value.func, ast.Attribute) \
            and value.func.attr == "labels":
        return True
    ident = None
    if isinstance(value, ast.Name):
        ident = value.id
    elif isinstance(value, ast.Attribute):
        ident = value.attr
    return ident is not None \
        and bool(_METRIC_RECEIVER_RE.search(ident.lower()))


def _request_source_in(module: ModuleInfo,
                       value: ast.AST) -> Optional[str]:
    """The first request-derived identifier feeding ``value``, skipping
    subtrees bounded by ``classify_route(...)`` (the sanctioned
    normalizer — its output is a small fixed route vocabulary)."""
    stack = [value]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            dotted = module.dotted(node.func) or ""
            if dotted.split(".")[-1] == "classify_route":
                continue
        ident = None
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        if ident is not None \
                and _REQUEST_SOURCE_RE.search(ident.lower()):
            return ident
        stack.extend(ast.iter_child_nodes(node))
    return None


@register_rule
class UnboundedLabelCardinality(Rule):
    code = "TPU015"
    name = "unbounded-label-cardinality"
    severity = "warning"
    doc = ("A request-derived string (URL, path, header, query, payload) "
           "used as a metric label value outside mmlspark_tpu/"
           "observability/. Every distinct label value mints a new "
           "time series that lives for the life of the process: labeling "
           "by raw request strings lets any client grow the registry "
           "without bound (memory, /metrics payload, and downstream "
           "Prometheus cardinality all follow). Normalize through "
           "``observability.classify_route()`` (bounded route "
           "vocabulary) or an explicit allow-list before labeling; "
           "classify_route-wrapped values are recognized and stay "
           "quiet. Scoped to metric-shaped receivers (``M_FOO`` handle "
           "naming, ``.labels()`` chains, tracker/ledger objects) so "
           "non-metric ``.set()`` calls don't trip it.")

    def check(self, module: ModuleInfo):
        rel = module.relpath.replace("\\", "/")
        if not rel.startswith("mmlspark_tpu/") \
                or rel.startswith("mmlspark_tpu/observability/"):
            return iter(())
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in _LABEL_METHODS \
                    or not _metric_receiver(node.func.value):
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                src = _request_source_in(module, kw.value)
                if src is not None:
                    findings.append(self.finding(
                        module, node,
                        f"metric label '{kw.arg}' takes the "
                        f"request-derived value '{src}' — each distinct "
                        f"request mints a new time series (unbounded "
                        f"cardinality); normalize through "
                        f"classify_route() or an explicit allow-list "
                        f"first"))
                    break   # one finding per call site is signal enough
        return iter(findings)


#: identifiers marking the left operand as hash-derived (builtin hash(),
#: hashlib digests, crc32, and local *_hash helpers all match)
_HASH_SOURCE_RE = re.compile(r"hash|digest|crc32|md5|sha1|sha256|fnv")
#: identifiers marking a collection as a peer pool worth routing over
_PEER_POOL_RE = re.compile(r"peer|worker|node|member|replica|backend|"
                           r"host|endpoint|addr|shard|server")
#: the sanctioned routing layer — ConsistentHashRing and its registry
#: consumer live here, and _ring_hash % internals are its implementation
_ROUTING_EXEMPT = ("mmlspark_tpu/serving/admission.py",
                   "mmlspark_tpu/serving/registry.py")


def _hash_ident_in(node: ast.AST) -> Optional[str]:
    """The first hash-flavored identifier feeding ``node``, or None."""
    for sub in ast.walk(node):
        ident = None
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        if ident is not None and _HASH_SOURCE_RE.search(ident.lower()):
            return ident
    return None


@register_rule
class AdhocHashRouting(Rule):
    code = "TPU016"
    name = "adhoc-hash-routing"
    severity = "warning"
    doc = ("Peer selection by ``hash(key) % len(peers)`` (or any "
           "hash-derived value modulo a peer-pool length) outside "
           "mmlspark_tpu/serving/admission.py and serving/registry.py. "
           "Modulo placement remaps nearly EVERY key whenever the pool "
           "size changes — one worker restart reshuffles the whole "
           "keyspace, losing prefix-cache affinity and stampeding cold "
           "workers. Route through serving.ConsistentHashRing instead: "
           "a membership change moves only ~1/n of the keys, and its "
           "bounded-load fallback absorbs hot keys. Non-hash modulo "
           "(round-robin cursors like ``self._rr % len(peers)``) stays "
           "quiet — rotation is not placement.")

    def check(self, module: ModuleInfo):
        rel = module.relpath.replace("\\", "/")
        if not rel.startswith("mmlspark_tpu/") or rel in _ROUTING_EXEMPT:
            return iter(())
        findings: List[Finding] = []
        for node in module.nodes(ast.BinOp):
            if not isinstance(node.op, ast.Mod):
                continue
            right = node.right
            if not (isinstance(right, ast.Call)
                    and module.dotted(right.func) == "len"
                    and right.args):
                continue
            pool = None
            for sub in ast.walk(right.args[0]):
                ident = None
                if isinstance(sub, ast.Name):
                    ident = sub.id
                elif isinstance(sub, ast.Attribute):
                    ident = sub.attr
                if ident is not None \
                        and _PEER_POOL_RE.search(ident.lower()):
                    pool = ident
                    break
            if pool is None:
                continue
            src = _hash_ident_in(node.left)
            if src is None:
                continue
            findings.append(self.finding(
                module, node,
                f"peer selected by '{src}' % len({pool}) — modulo "
                f"placement remaps ~every key when the pool resizes "
                f"(one restart reshuffles the keyspace and stampedes "
                f"cold caches); route through "
                f"serving.ConsistentHashRing, which moves only ~1/n of "
                f"keys per membership change"))
        return iter(findings)


def _mesh_param(module: ModuleInfo, fn: ast.AST) -> Optional[str]:
    """The parameter of ``fn`` that carries a mesh, or None: a parameter
    named ``mesh``, or one annotated with ``Mesh``/``NamedSharding``
    (including inside ``Optional[...]`` and string annotations)."""
    args = fn.args
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        if a.arg == "mesh":
            return a.arg
        if a.annotation is None:
            continue
        for sub in ast.walk(a.annotation):
            ident = None
            if isinstance(sub, ast.Name):
                ident = sub.id
            elif isinstance(sub, ast.Attribute):
                ident = sub.attr
            elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                if re.search(r"\b(Mesh|NamedSharding)\b", sub.value):
                    return a.arg
            if ident in ("Mesh", "NamedSharding"):
                return a.arg
    return None


@register_rule
class UnshardedPallasCall(Rule):
    code = "TPU017"
    name = "unsharded-pallas-call"
    severity = "warning"
    doc = ("A bare ``pallas_call`` reachable from a jitted function that "
           "takes a ``Mesh``/``NamedSharding`` argument, with no "
           "``shard_map`` mount anywhere on the path. A Pallas kernel is "
           "not GSPMD-partitionable: inside a sharded jit, XLA gathers "
           "every operand onto one device, silently serializing the "
           "'parallel' program and blowing per-device memory at scale. "
           "Mount the kernel with ``jax.shard_map`` (per-shard specs over "
           "the mesh axes) so each device runs it on its own slice — the "
           "pattern ops/paged_attention.py uses — or drop the mesh "
           "argument if the program is genuinely single-device.")

    def check(self, module: ModuleInfo):
        funcs = {}
        for fn in module.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            funcs.setdefault(fn.name, fn)
        # per function: bare pallas_call sites, whether a shard_map mount
        # appears anywhere inside (mounted subtrees are quiet — the mount
        # governs everything it wraps), and intra-module callees by name
        info = {}
        for name, fn in funcs.items():
            pallas: List[ast.Call] = []
            mounted = False
            callees: Set[str] = set()
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = module.dotted(sub.func)
                if dotted is not None:
                    if (dotted == "pallas_call"
                            or dotted.endswith(".pallas_call")):
                        pallas.append(sub)
                    if "shard_map" in dotted:
                        mounted = True
                if isinstance(sub.func, ast.Name):
                    callees.add(sub.func.id)
            info[name] = (pallas, mounted, callees)
        findings: List[Finding] = []
        flagged: Set[int] = set()
        for name, fn in funcs.items():
            if jit_decoration(module, fn) is None:
                continue
            mp = _mesh_param(module, fn)
            if mp is None:
                continue
            seen: Set[str] = set()
            stack = [name]
            while stack:
                cur = stack.pop()
                if cur in seen or cur not in info:
                    continue
                seen.add(cur)
                pallas, mounted, callees = info[cur]
                if mounted:
                    continue
                for node in pallas:
                    if id(node) in flagged:
                        continue
                    flagged.add(id(node))
                    findings.append(self.finding(
                        module, node,
                        f"bare pallas_call reachable from jitted "
                        f"'{fn.name}' (mesh argument '{mp}') with no "
                        f"shard_map mount — under a sharded jit XLA "
                        f"gathers the kernel's operands onto ONE device; "
                        f"mount it via jax.shard_map with per-shard "
                        f"specs, as ops/paged_attention.py does"))
                stack.extend(callees)
        return iter(findings)


#: receiver-name tokens that mark a tensor as KV-plane / activation data —
#: a bare low-bit cast on these loses the per-row scale a quantized page
#: needs to dequantize
_QUANT_TENSOR_TOKENS = {
    "k", "q", "v", "kv", "key", "keys", "val", "vals", "value", "values",
    "cache", "caches", "act", "acts", "activation", "activations",
    "row", "rows", "page", "pages", "hidden", "ctx", "attn", "logits",
}

#: modules sanctioned to cast to quantized storage dtypes — the scale-
#: carrying helpers every writer must route through
_QUANT_SANCTIONED = ("ops/kv_quant.py",)


def _is_quant_store_dtype(module: ModuleInfo, node: ast.AST) -> bool:
    """True when ``node`` names an int8/fp8 STORAGE dtype (``jnp.int8``,
    ``jnp.float8_e4m3fn``, a bare ``"int8"`` string...). ``uint8`` is NOT
    one — the dense image ingest column is a real byte payload, not a
    scaled quantization of anything."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        s = node.value.lower()
        return s == "int8" or s.startswith("float8") or s == "fp8"
    dotted = module.dotted(node)
    if dotted is None:
        return False
    leaf = dotted.rsplit(".", 1)[-1].lower()
    return leaf == "int8" or leaf.startswith("float8")


def _receiver_tokens(node: ast.AST) -> Set[str]:
    toks: Set[str] = set()
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name:
            toks.update(t for t in name.lower().split("_") if t)
    return toks


@register_rule
class UnscaledQuantCast(Rule):
    code = "TPU018"
    name = "unscaled-quant-cast"
    severity = "warning"
    doc = ("A bare ``.astype(int8/fp8)`` (or "
           "``lax.convert_element_type``) on a KV/activation tensor "
           "outside the sanctioned quant helpers (ops/kv_quant.py). A "
           "low-bit storage cast without a recorded scale either "
           "truncates the tensor to the [-1, 1]-ish integer lattice "
           "(silent catastrophic rounding) or, if a scale was applied "
           "inline, strands it where no reader can find it — the paged "
           "pools dequantize through the ``(N, H, page)`` scale arrays "
           "that ``quantize_kv`` produces. Route the cast through "
           "``mmlspark_tpu.ops.kv_quant.quantize_kv`` (absmax scale "
           "riding the same block-table index_map as the pages) so every "
           "writer and the in-kernel dequant agree byte-for-byte. "
           "``uint8`` is exempt: the dense image ingest column is raw "
           "bytes, not a scaled encoding.")

    def check(self, module: ModuleInfo):
        if module.relpath.replace("\\", "/").endswith(_QUANT_SANCTIONED):
            return iter(())
        findings: List[Finding] = []
        for call in module.nodes(ast.Call):
            target = None
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "astype" and call.args
                    and _is_quant_store_dtype(module, call.args[0])):
                target = call.func.value
            else:
                dotted = module.dotted(call.func)
                if (dotted is not None
                        and dotted.endswith("convert_element_type")
                        and len(call.args) >= 2
                        and _is_quant_store_dtype(module, call.args[1])):
                    target = call.args[0]
            if target is None:
                continue
            if not (_receiver_tokens(target) & _QUANT_TENSOR_TOKENS):
                continue
            findings.append(self.finding(
                module, call,
                "bare low-bit cast on a KV/activation tensor — the scale "
                "is lost (or stranded); quantize through "
                "mmlspark_tpu.ops.kv_quant.quantize_kv so the per-row "
                "absmax scale lands in the page-aligned scale pool the "
                "dequant kernel reads"))
        return iter(findings)


# -- TPU023 closed-loop-latency ----------------------------------------------

#: clock reads that bracket a timed request inside a loop
_CLOCK_CALLS = {"time.time", "time.monotonic", "time.perf_counter",
                "monotonic", "perf_counter"}
#: blocking send-and-wait calls: the reply gates the next iteration
_SEND_BLOCK_ATTRS = {"urlopen", "getresponse"}
#: pacing primitives: their presence means the loop schedules sends
#: instead of letting the reply throttle the generator
_PACING_ATTRS = {"sleep", "wait"}
#: paths allowed to run closed loops: the loadgen package (it owns the
#: sanctioned closed-loop probe, clearly labeled as the comparison
#: baseline) and tests (fixtures assert on single requests, not latency)
_CLOSED_LOOP_EXEMPT_PREFIXES = ("mmlspark_tpu/loadgen/", "tests/")


def _loop_call_profile(loop: ast.AST, module: ModuleInfo):
    """(clock_reads, send_blocks, paced) over one loop body, nested
    function bodies excluded (a worker fn defined in a loop is its own
    analysis scope, not this loop's per-iteration behavior)."""
    clocks = 0
    sends = 0
    paced = False
    stack = list(ast.iter_child_nodes(loop))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
        if not isinstance(node, ast.Call):
            continue
        dotted = module.dotted(node.func)
        attr = (node.func.attr if isinstance(node.func, ast.Attribute)
                else dotted)
        if dotted in _CLOCK_CALLS or (
                dotted is not None
                and dotted.rsplit(".", 1)[-1] in ("monotonic",
                                                  "perf_counter")):
            clocks += 1
        elif attr in _SEND_BLOCK_ATTRS:
            sends += 1
        elif attr in _PACING_ATTRS or dotted == "time.sleep":
            paced = True
    return clocks, sends, paced


@register_rule
class ClosedLoopLatency(Rule):
    code = "TPU023"
    name = "closed-loop-latency"
    severity = "warning"
    doc = ("An ad-hoc benchmark loop that reads a clock around a "
           "blocking send (``urlopen``/``getresponse``) with no pacing "
           "call — the closed-loop shape: the next request fires only "
           "after the last reply, so a slow server throttles its own "
           "load generator and the measured p99 never sees queueing "
           "delay (coordinated omission). Latency numbers from such "
           "loops are only comparable to other closed-loop numbers, yet "
           "they end up in records next to open-loop quantiles. Use "
           "``mmlspark_tpu.loadgen`` instead: arrivals are stamped with "
           "their scheduled send time and latency is measured from that "
           "instant. ``loadgen/`` itself (its labeled closed-loop probe "
           "is the sanctioned comparison baseline) and ``tests/`` are "
           "exempt. Suppress only for a loop that genuinely is not a "
           "latency measurement (e.g. polling until a condition holds "
           "while logging elapsed time).")

    def check(self, module: ModuleInfo):
        rel = module.relpath.replace("\\", "/")
        if rel.startswith(_CLOSED_LOOP_EXEMPT_PREFIXES) \
                or "/tests/" in rel:
            return iter(())
        findings: List[Finding] = []
        for loop in module.nodes(ast.For, ast.While):
            clocks, sends, paced = _loop_call_profile(loop, module)
            if clocks >= 2 and sends >= 1 and not paced:
                findings.append(self.finding(
                    module, loop,
                    "closed-loop latency measurement: this loop times a "
                    "blocking send and lets the reply gate the next "
                    "request, so queueing delay is invisible "
                    "(coordinated omission) — drive traffic through "
                    "mmlspark_tpu.loadgen (open-loop, scheduled-send "
                    "latency) or pace sends explicitly"))
        return iter(findings)


# -- TPU024 adhoc-timeseries ---------------------------------------------------

#: paths allowed to accumulate history: the observability package owns the
#: sanctioned fixed-memory TimeSeriesStore; tests build tiny ad-hoc traces
#: on purpose
_TIMESERIES_EXEMPT_PREFIXES = ("mmlspark_tpu/observability/", "tests/")


def _is_clock_call(module: ModuleInfo, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dotted = module.dotted(node.func)
    return dotted in _CLOCK_CALLS or (
        dotted is not None
        and dotted.rsplit(".", 1)[-1] in ("monotonic", "perf_counter"))


def _clock_bound_names(func: ast.AST, module: ModuleInfo):
    """Local names assigned directly from a clock read in this function."""
    names = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) \
                and _is_clock_call(module, node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _self_attr(node: ast.AST):
    """``'attr'`` when node is ``self.attr``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _bounded_attrs(cls: ast.ClassDef):
    """self-attributes with any in-class size-bounding evidence: a
    deque(maxlen=), pop/popleft/clear drains, del/slice reassignment, or
    a len() check (the usual trim-guard shape)."""
    bounded = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                attr = _self_attr(node.func.value)
                if attr and node.func.attr in ("pop", "popleft", "clear"):
                    bounded.add(attr)
            if (isinstance(node.func, ast.Name) and node.func.id == "len"
                    and node.args):
                attr = _self_attr(node.args[0])
                if attr:
                    bounded.add(attr)
        elif isinstance(node, ast.Assign):
            value = node.value
            if isinstance(value, ast.Call):
                fn = value.func
                fn_name = (fn.id if isinstance(fn, ast.Name)
                           else fn.attr if isinstance(fn, ast.Attribute)
                           else None)
                if fn_name == "deque" and any(
                        k.arg == "maxlen" for k in value.keywords):
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr:
                            bounded.add(attr)
            for t in node.targets:
                # self.attr[...] = ... (slice trim in place)
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr:
                        bounded.add(attr)
                # self.attr = self.attr[-n:] (rebind to a tail slice)
                attr = _self_attr(t)
                if (attr and isinstance(value, ast.Subscript)
                        and _self_attr(value.value) == attr):
                    bounded.add(attr)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr:
                        bounded.add(attr)
    return bounded


@register_rule
class AdhocTimeseries(Rule):
    code = "TPU024"
    name = "adhoc-timeseries"
    severity = "warning"
    doc = ("An instance attribute accumulating ``(timestamp, value)`` "
           "records via ``append`` with no size bound in sight — an "
           "ad-hoc time series. In a long-lived serving process such a "
           "list grows until the OOM killer becomes the retention "
           "policy, and every consumer reinvents windowing/rate/quantile "
           "math over it, badly. Record the series through "
           "``mmlspark_tpu.observability.timeseries.get_store()`` "
           "instead: fixed-memory ring tiers, spike-preserving "
           "downsampling, and query helpers (``range``/``rate``/"
           "``ewma``/``sustained``) shared with the alert engine. "
           "Bounding evidence in the same class silences the rule: a "
           "``deque(maxlen=)``, ``pop``/``popleft``/``clear`` drains, "
           "``del``/slice trims, or a ``len()`` guard. "
           "``mmlspark_tpu/observability/`` (the store's own home) and "
           "``tests/`` are exempt. Suppress only for genuinely bounded "
           "accumulation the heuristic cannot see (e.g. trimmed by a "
           "helper outside the class).")

    def check(self, module: ModuleInfo):
        rel = module.relpath.replace("\\", "/")
        if rel.startswith(_TIMESERIES_EXEMPT_PREFIXES) \
                or "/tests/" in rel:
            return iter(())
        findings: List[Finding] = []
        seen = set()
        for cls in module.nodes(ast.ClassDef):
            bounded = _bounded_attrs(cls)
            funcs = [n for n in ast.walk(cls)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            for func in funcs:
                clock_names = _clock_bound_names(func, module)
                for call in ast.walk(func):
                    if not (isinstance(call, ast.Call)
                            and isinstance(call.func, ast.Attribute)
                            and call.func.attr == "append"
                            and len(call.args) == 1):
                        continue
                    attr = _self_attr(call.func.value)
                    if attr is None or attr in bounded:
                        continue
                    arg = call.args[0]
                    # records, not scalars: a tuple/list/dict/call whose
                    # payload carries a clock read (direct or via a local
                    # assigned from one)
                    if not isinstance(arg, (ast.Tuple, ast.List,
                                            ast.Dict, ast.Call)):
                        continue
                    stamped = any(
                        _is_clock_call(module, sub)
                        or (isinstance(sub, ast.Name)
                            and sub.id in clock_names)
                        for sub in ast.walk(arg))
                    if not stamped or call.lineno in seen:
                        continue
                    seen.add(call.lineno)
                    findings.append(self.finding(
                        module, call,
                        f"unbounded (timestamp, value) accumulation on "
                        f"self.{attr} — an ad-hoc time series that grows "
                        f"for the life of the process; record it through "
                        f"observability.timeseries.get_store() (fixed-"
                        f"memory rings, shared trend queries) or bound "
                        f"it (deque(maxlen=), trim on append)"))
        return iter(findings)


# -- TPU025 unsupervised-daemon-loop -------------------------------------------

#: paths allowed to run bare daemon loops: the reliability package owns the
#: sanctioned supervisor (run_supervised IS the guard — it cannot wrap
#: itself), and tests spin short-lived helper threads on purpose
_DAEMON_EXEMPT_PREFIXES = ("mmlspark_tpu/reliability/", "tests/")


def _daemon_thread_target(call: ast.Call) -> Optional[str]:
    """The bare name of a ``Thread(daemon=True)`` target when it is
    resolvable inside this module: ``target=fn`` or ``target=self.fn``
    → ``'fn'``. Lambdas and bound methods of *other* objects
    (``httpd.serve_forever`` — analyzed where they are defined, or in the
    stdlib) return None and are skipped, not flagged."""
    daemon = any(kw.arg == "daemon"
                 and isinstance(kw.value, ast.Constant)
                 and kw.value.value is True
                 for kw in call.keywords)
    if not daemon:
        return None
    for kw in call.keywords:
        if kw.arg != "target":
            continue
        v = kw.value
        if isinstance(v, ast.Name):
            return v.id
        if (isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name)
                and v.value.id == "self"):
            return v.attr
        return None
    return None


def _loop_supervision(func: ast.AST) -> "tuple[bool, bool]":
    """(has_loop, guarded) for a thread-target function. Guarded means a
    ``try`` *inside* a loop body (each iteration's crash is contained, so
    the loop survives it) or any call to a ``*supervised*`` helper —
    a ``try`` wrapped *around* the loop still dies on first crash and
    does not count."""
    has_loop = False
    guarded = False
    for node in ast.walk(func):
        if isinstance(node, (ast.While, ast.For)):
            has_loop = True
            if any(isinstance(sub, ast.Try) for sub in ast.walk(node)):
                guarded = True
        elif isinstance(node, ast.Call):
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute) else "")
            if "supervised" in name:
                guarded = True
    return has_loop, guarded


@register_rule
class UnsupervisedDaemonLoop(Rule):
    code = "TPU025"
    name = "unsupervised-daemon-loop"
    severity = "warning"
    doc = ("A ``threading.Thread(daemon=True)`` whose target function "
           "loops with no crash containment — the serving stack's silent "
           "killer: one unhandled exception ends the thread, and the "
           "process limps on with its heartbeat/sweeper/engine tick gone. "
           "A dead heartbeat looks exactly like a dead worker to the "
           "driver's liveness sweeper, which then evicts a healthy worker "
           "and reassigns its sessions. Run the loop under "
           "``mmlspark_tpu.reliability.loops.start_supervised`` "
           "(contained crashes, exponential backoff, restarts counted in "
           "``mmlspark_supervised_loop_restarts_total{loop}``) or put a "
           "``try``/``except`` inside the loop body so an iteration's "
           "crash cannot end the loop. Targets that cannot be resolved in "
           "the same module (lambdas, ``httpd.serve_forever``) are "
           "skipped, not flagged. ``mmlspark_tpu/reliability/`` (the "
           "supervisor's own home) and ``tests/`` are exempt. Suppress "
           "only for a loop that genuinely must die on first failure "
           "(e.g. a run-once bootstrap on a background thread).")

    def check(self, module: ModuleInfo):
        rel = module.relpath.replace("\\", "/")
        if rel.startswith(_DAEMON_EXEMPT_PREFIXES) or "/tests/" in rel:
            return iter(())
        funcs = {}
        for fn in module.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            funcs.setdefault(fn.name, fn)
        findings: List[Finding] = []
        for call in module.nodes(ast.Call):
            dotted = module.dotted(call.func)
            ctor = dotted.rsplit(".", 1)[-1] if dotted else None
            if ctor != "Thread":
                continue
            target_name = _daemon_thread_target(call)
            if target_name is None:
                continue
            target = funcs.get(target_name)
            if target is None:
                continue
            has_loop, guarded = _loop_supervision(target)
            if has_loop and not guarded:
                findings.append(self.finding(
                    module, call,
                    f"daemon thread runs {target_name}()'s loop "
                    f"unsupervised — one unhandled exception silently "
                    f"kills the thread and the process limps on without "
                    f"it; start it via reliability.loops.start_supervised "
                    f"(contained crashes + backoff + restart accounting) "
                    f"or contain each iteration in try/except"))
        return iter(findings)
