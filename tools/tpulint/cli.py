"""tpulint command line: ``python -m tools.tpulint <paths>``.

Exit codes: 0 clean (modulo baseline and ``--fail-on`` threshold),
1 new findings at or above the threshold (or parse errors), 2 usage error.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from . import baseline as baseline_mod
from .core import all_rules, analyze_project, load_project
from .reporters import REPORTERS, rule_catalog

SEVERITY_RANK = {"error": 0, "warning": 1, "info": 2}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpulint",
        description="AST-based TPU-hazard analyzer (recompile, host-sync, "
                    "dtype-leak, op-registry drift).")
    p.add_argument("paths", nargs="*", help="files or directories to scan")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="baseline JSON; matching findings don't fail the run")
    p.add_argument("--write-baseline", metavar="FILE", default=None,
                   help="write current findings as the new baseline and exit")
    p.add_argument("--format", choices=sorted(REPORTERS), default="text")
    p.add_argument("--rules", metavar="CODES", default=None,
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--fail-on", choices=["error", "warning", "info"],
                   default="warning",
                   help="lowest severity that fails the run (default: "
                        "warning — info findings report but never gate)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also report inline-suppressed findings (never fail)")
    p.add_argument("--jobs", type=int, default=1, metavar="N",
                   help="parse files and run per-module rules on N threads "
                        "(default 1; project-scope rules stay serial)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv: Optional[List[str]] = None,
         stdout=None) -> int:
    stdout = stdout or sys.stdout
    args = build_parser().parse_args(argv)
    if args.list_rules:
        rule_catalog(stdout)
        return 0
    if not args.paths:
        build_parser().print_usage(sys.stderr)
        print("tpulint: error: no paths given", file=sys.stderr)
        return 2
    codes = [c.strip() for c in args.rules.split(",")] if args.rules else None
    try:
        rules = all_rules(codes)
    except ValueError as e:
        print(f"tpulint: error: {e}", file=sys.stderr)
        return 2

    if args.jobs < 1:
        print("tpulint: error: --jobs must be >= 1", file=sys.stderr)
        return 2
    project = load_project(args.paths, jobs=args.jobs)
    findings, suppressed = analyze_project(
        project, rules=rules, keep_suppressed=args.show_suppressed,
        jobs=args.jobs)

    if args.write_baseline:
        baseline_mod.dump(findings, args.write_baseline)
        stdout.write(f"tpulint: wrote {len(findings)} finding(s) "
                     f"({len(baseline_mod.counts(findings))} fingerprints) "
                     f"to {args.write_baseline}\n")
        return 0

    baselined, stale = [], {}
    if args.baseline:
        try:
            known = baseline_mod.load(args.baseline)
        except (OSError, ValueError) as e:
            print(f"tpulint: error: cannot read baseline: {e}",
                  file=sys.stderr)
            return 2
        findings, baselined, stale = baseline_mod.apply(findings, known)

    REPORTERS[args.format](findings, stdout, baselined=baselined,
                           stale=stale, parse_errors=project.parse_errors)
    if args.show_suppressed and suppressed:
        stdout.write(f"tpulint: {len(suppressed)} suppressed finding(s):\n")
        for f in suppressed:
            stdout.write(f"    {f.location()}: {f.rule}: {f.message}\n")

    threshold = SEVERITY_RANK[args.fail_on]
    gating = [f for f in findings
              if SEVERITY_RANK[f.severity] <= threshold]
    if project.parse_errors or gating:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
