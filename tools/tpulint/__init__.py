"""tpulint — AST-based TPU-hazard analyzer for the mmlspark_tpu codebase.

The JNI/socket failure modes of the reference became, after the jax.jit
rebuild, *compile-time-invisible Python patterns*: a host sync buried in a
per-batch loop, a ``jax.jit`` constructed in steady state, a Python branch
on a tracer, a float64 literal silently widening a jitted program, or an
ONNX op handler that never lands in the dispatch table. Every one of them
is mechanically detectable from the AST before anything executes — this
package is that detector.

Rules
-----
- **TPU001** host-sync-in-jit: ``jax.device_get`` / ``np.asarray`` /
  ``float()`` / ``.item()`` inside jitted functions, and per-iteration
  ``device_get``/``block_until_ready`` in batch loops.
- **TPU002** jit-in-loop: ``jax.jit(...)`` constructed inside a loop body —
  a fresh cache per iteration, i.e. steady-state recompiles.
- **TPU003** tracer-branch: Python ``if``/``while`` on traced parameters of
  jitted functions instead of ``lax.cond`` / ``lax.while_loop``.
- **TPU004** dtype-leak: ``np.float64`` references, dtype-less
  ``np.asarray``/``np.array`` in device-feed modules, and bare float
  literals in jitted code.
- **TPU005** op-registry-drift: the ONNX ``OP_HANDLERS`` dispatch table
  cross-checked against the handler modules (duplicates, dangling
  registrations, unregistered handlers, unreachable registry modules).
- **TPU006** stub-drift: ``.pyi`` stubs naming things their module no
  longer defines.

Entry points: ``scripts/run_tpulint.py`` (CI gate, baseline-diff mode) and
``scripts/gen_tpulint_baseline.py`` (baseline regeneration). See
``docs/static_analysis.md`` for the rule catalog and workflow.
"""

from .core import (Finding, ModuleInfo, Project, Rule, all_rules,
                   analyze_project, analyze_source, fingerprint,
                   register_rule)
from . import rules as _rules            # noqa: F401  (registers TPU001-004)
from . import project_rules as _prules   # noqa: F401  (registers TPU005-006)

__version__ = "0.1.0"

__all__ = ["Finding", "ModuleInfo", "Project", "Rule", "all_rules",
           "analyze_project", "analyze_source", "fingerprint",
           "register_rule", "__version__"]
