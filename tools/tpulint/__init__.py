"""tpulint — AST-based TPU-hazard analyzer for the mmlspark_tpu codebase.

The JNI/socket failure modes of the reference became, after the jax.jit
rebuild, *compile-time-invisible Python patterns*: a host sync buried in a
per-batch loop, a ``jax.jit`` constructed in steady state, a Python branch
on a tracer, a float64 literal silently widening a jitted program, or an
ONNX op handler that never lands in the dispatch table. Every one of them
is mechanically detectable from the AST before anything executes — this
package is that detector.

Rules
-----
- **TPU001** host-sync-in-jit: ``jax.device_get`` / ``np.asarray`` /
  ``float()`` / ``.item()`` inside jitted functions, and per-iteration
  ``device_get``/``block_until_ready`` in batch loops.
- **TPU002** jit-in-loop: ``jax.jit(...)`` constructed inside a loop body —
  a fresh cache per iteration, i.e. steady-state recompiles.
- **TPU003** tracer-branch: Python ``if``/``while`` on traced parameters of
  jitted functions instead of ``lax.cond`` / ``lax.while_loop``.
- **TPU004** dtype-leak: ``np.float64`` references, dtype-less
  ``np.asarray``/``np.array`` in device-feed modules, and bare float
  literals in jitted code.
- **TPU005** op-registry-drift: the ONNX ``OP_HANDLERS`` dispatch table
  cross-checked against the handler modules (duplicates, dangling
  registrations, unregistered handlers, unreachable registry modules).
- **TPU006** stub-drift: ``.pyi`` stubs naming things their module no
  longer defines.
- **TPU012** unguarded-shared-mutation: a write to an inferred-lock-guarded
  field or module global without holding the owning lock (the guard
  discipline is inferred from the code's own ``with self._lock:`` bodies).
- **TPU013** lock-order-inversion: a cycle in the project-wide static
  lock-acquisition graph, or nested re-acquisition of a non-reentrant
  ``threading.Lock`` — the static half of the deadlock story
  (``mmlspark_tpu.reliability.lock_sanitizer`` is the runtime half).
- **TPU014** blocking-call-under-lock: a device sync, sleep, HTTP dial,
  subprocess, queue wait, or thread join while holding a lock.
- **TPU019** unknown-mesh-axis: a ``P(...)``/``axis_name=`` axis that no
  mesh constructed anywhere in the project declares — the typo that
  silently replicates instead of sharding.
- **TPU020** spec-rank-mismatch: ``shard_map`` in/out specs that can't
  bind the mounted callee, or a ``P(...)`` longer than the array's rank.
- **TPU021** unsharded-device-put: a bare ``jax.device_put`` with a mesh
  in scope — full replication onto every device by default.
- **TPU022** collective-in-loop: ``psum``/``all_gather``/... inside a
  Python loop under jit — one trace-unrolled collective per iteration.
- **TPU023** closed-loop-latency: an ad-hoc benchmark loop that times a
  blocking send with no pacing — the reply throttles the generator, so
  the measured p99 never sees queueing delay (coordinated omission);
  drive traffic through ``mmlspark_tpu.loadgen`` instead.
- **TPU024** adhoc-timeseries: an instance attribute accumulating
  ``(timestamp, value)`` records by ``append`` with no size bound in the
  class — an ad-hoc history that grows for the life of the process;
  record through ``observability.timeseries.get_store()`` (fixed-memory
  rings, shared trend queries) or bound it explicitly.
- **TPU025** unsupervised-daemon-loop: a ``threading.Thread(daemon=True)``
  whose target function loops with no crash guard — one unhandled
  exception silently kills the thread (heartbeat, sweeper, engine tick)
  and the process limps on without it; run the loop under
  ``reliability.loops.start_supervised`` (contained crashes, backoff,
  restart accounting) or contain each iteration in ``try``/``except``.

The static half of the sharding story only; the runtime half is
``mmlspark_tpu.parallel.collective_audit``, which counts collectives in
compiled HLO against ``tools/tpulint/collective_budget.json`` (the CI
``collective-audit`` stage).

Entry points: ``scripts/run_tpulint.py`` (CI gate, baseline-diff mode) and
``scripts/gen_tpulint_baseline.py`` (baseline regeneration). See
``docs/static_analysis.md`` for the rule catalog and workflow.
"""

from .core import (Finding, ModuleInfo, Project, Rule, all_rules,
                   analyze_project, analyze_source, fingerprint,
                   register_rule)
from . import rules as _rules            # noqa: F401  (registers TPU001-004)
from . import project_rules as _prules   # noqa: F401  (registers TPU005-006)
from . import concurrency as _crules     # noqa: F401  (registers TPU012-014)
from . import sharding as _srules        # noqa: F401  (registers TPU019-022)

__version__ = "0.1.0"

__all__ = ["Finding", "ModuleInfo", "Project", "Rule", "all_rules",
           "analyze_project", "analyze_source", "fingerprint",
           "register_rule", "__version__"]
