"""Whole-program concurrency rules: lock discipline across the threaded plane.

The serving/data plane is a threaded Python program — WorkerServer request
threads, the BatchRunner prefetch worker, the ContinuousDecoder tick thread,
the Watchdog daemon, and process-global singletons (ResidencyManager,
MetricsRegistry, ObservationStore, SloTracker, breaker registry) touched by
all of them. Nothing in a conventional linter checks that this code keeps
its own locking promises; the runtime watchdog only sees a wedged thread
*after* it stalls. These rules see the hazard in the AST, before anything
runs — and, in the spirit of Automap (PAPERS.md), the invariant is
*inferred* from the code rather than hand-annotated: a class that mostly
mutates a field under ``with self._lock:`` has declared, mechanically, that
the field is lock-guarded; the outlier writes are the findings.

Three rules share one :class:`ConcurrencyModel` built per project:

- **TPU012 unguarded-shared-mutation** — a write to an inferred-guarded
  instance field (or module global) outside the owning lock.
- **TPU013 lock-order-inversion** — a cycle in the static lock-acquisition
  graph built from nested ``with``-lock scopes (including one level of
  same-class / same-module call expansion), plus nested re-acquisition of
  a non-reentrant ``threading.Lock``.
- **TPU014 blocking-call-under-lock** — a device sync
  (``jax.device_get`` / ``block_until_ready``), ``time.sleep``, HTTP dial,
  subprocess, or queue wait while a lock is held: every other thread that
  needs the lock now waits on the device/network too. This is exactly the
  bug class the watchdog can only report at runtime.

Conventions the model understands (and the codebase follows):

- ``self._lock = threading.Lock()`` / ``RLock`` / ``Condition`` in any
  method, module-level ``_X_LOCK = threading.Lock()``, dataclass
  ``field(default_factory=threading.Lock)``, and the sanitized factory
  (``reliability.lock_sanitizer.new_lock/new_rlock/new_condition``).
- Methods named ``*_locked`` are entered with the class lock held (the
  ``_prune_locked`` / ``_step_locked`` convention): writes inside them
  count as guarded and blocking calls inside them count as under-lock.
- ``__init__``/``__new__`` construct the object before it is shared;
  their writes never count against the discipline.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleInfo, Project, Rule, register_rule

#: constructors recognized as lock objects, by dotted-name tail
_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "multiprocessing.Lock": "lock",
    "multiprocessing.RLock": "rlock",
}
#: the sanitized factory (reliability/lock_sanitizer.py) — suffix-matched so
#: ``from ..reliability.lock_sanitizer import new_lock`` and
#: ``lock_sanitizer.new_lock`` both resolve
_LOCK_FACTORIES = {
    "new_lock": "lock",
    "new_rlock": "rlock",
    "new_condition": "condition",
}

#: mutating method names on containers — a call on a guarded field through
#: one of these is a write event
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "update", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "setdefault",
    "move_to_end", "sort", "reverse", "__setitem__",
}

#: calls that block on the device, the network, the disk, or the clock —
#: held locks turn them into convoy points (TPU014)
_BLOCKING_CALLS = {
    "time.sleep": "sleeps",
    "jax.device_get": "syncs the device",
    "jax.block_until_ready": "syncs the device",
    "jax.device_put": "stages to the device",
    "urllib.request.urlopen": "dials HTTP",
    "socket.create_connection": "dials a socket",
    "subprocess.run": "waits on a subprocess",
    "subprocess.call": "waits on a subprocess",
    "subprocess.check_call": "waits on a subprocess",
    "subprocess.check_output": "waits on a subprocess",
    "subprocess.Popen": "spawns a subprocess",
}
#: attribute-method spellings of the same hazards
_BLOCKING_METHODS = {
    "block_until_ready": "syncs the device",
    "copy_to_host": "syncs the device",
    "urlopen": "dials HTTP",
    "getresponse": "waits on an HTTP response",
    "recv": "waits on a socket",
    "accept": "waits on a socket",
    "sendall": "writes to a socket",
}
#: ``q.get()`` / ``q.put()`` are queue waits only when the receiver is
#: named like a queue (``self._queue.get`` yes, ``d.get(k)`` no)
_QUEUE_NAME_RE = re.compile(r"(^|_)q(ueue)?\d*$", re.IGNORECASE)
#: ``x.wait()`` blocks unless x is a condition tied to the held lock
#: (Condition.wait releases it) — condition-ish receivers stay quiet
_CONDITION_NAME_RE = re.compile(r"cond", re.IGNORECASE)
#: ``x.join()`` blocks on a thread; str.join is ubiquitous, so only
#: thread-ish receivers count
_THREAD_NAME_RE = re.compile(r"thread|worker", re.IGNORECASE)

_THREAD_TARGET_CTORS = {"threading.Thread", "Thread"}


@dataclass(frozen=True)
class LockId:
    """Identity of a lock *site*: one per class attribute or module global
    (instances share it — the granularity the discipline is written at)."""

    module: str          # relpath of the defining module
    owner: str           # class name, or "" for a module-level lock
    name: str            # attribute / global name
    kind: str = "lock"   # lock | rlock | condition

    def __str__(self) -> str:
        base = f"{self.owner}.{self.name}" if self.owner else self.name
        return f"{self.module}::{base}"


@dataclass
class WriteEvent:
    module: ModuleInfo
    node: ast.AST
    owner: str                    # class name or "" (module global)
    target: str                   # field / global name
    held: Tuple[LockId, ...]      # locks held at the write site
    func: str                     # enclosing function qualname
    assumed: bool                 # inside a *_locked method


@dataclass
class AcquireEvent:
    module: ModuleInfo
    node: ast.AST
    lock: LockId
    held: Tuple[LockId, ...]      # locks already held when acquiring
    func: str


@dataclass
class BlockingEvent:
    module: ModuleInfo
    node: ast.AST
    what: str                     # e.g. "jax.device_get"
    why: str                      # e.g. "syncs the device"
    held: Tuple[LockId, ...]
    func: str


@dataclass
class FunctionInfo:
    """Per-function summary used for the one-level call expansion."""

    qualname: str                 # "Class.method" or "function"
    module: str
    acquires: Set[LockId] = field(default_factory=set)
    #: (callee qualname as written, held locks at the call site, node)
    calls: List[Tuple[str, Tuple[LockId, ...], ast.AST]] = \
        field(default_factory=list)
    #: every blocking-ish call in the body regardless of local locks, as
    #: (what, why, node, locally-held locks) — consumed by the one-level
    #: call expansion so ``with lock: self._spill()`` sees the device
    #: sync inside ``_spill``
    blocking: List[Tuple[str, str, ast.AST, Tuple[LockId, ...]]] = \
        field(default_factory=list)


class ConcurrencyModel:
    """Everything the three rules need, built once per project."""

    def __init__(self, project: Project):
        self.project = project
        #: (module relpath, class name) -> {attr name: LockId}
        self.class_locks: Dict[Tuple[str, str], Dict[str, LockId]] = {}
        #: module relpath -> {global name: LockId}
        self.module_locks: Dict[str, Dict[str, LockId]] = {}
        #: function qualnames passed to Thread(target=...)/executor.submit
        self.thread_targets: Set[str] = set()
        self.writes: List[WriteEvent] = []
        self.acquires: List[AcquireEvent] = []
        self.blocking: List[BlockingEvent] = []
        self.functions: Dict[Tuple[str, str], FunctionInfo] = {}
        for m in project.modules:
            self._discover_locks(m)
        for m in project.modules:
            self._scan_module(m)
        self._expand_calls()

    # -- lock discovery ------------------------------------------------------
    def _lock_kind(self, module: ModuleInfo,
                   value: ast.AST) -> Optional[str]:
        """The lock kind constructed by ``value``, or None."""
        if not isinstance(value, ast.Call):
            return None
        name = module.dotted(value.func) or ""
        tail = name.split(".")[-1]
        if name in _LOCK_CTORS:
            return _LOCK_CTORS[name]
        if tail in ("Lock", "RLock", "Condition") \
                and name.split(".")[0] in ("threading", "multiprocessing"):
            return {"Lock": "lock", "RLock": "rlock",
                    "Condition": "condition"}[tail]
        if tail in _LOCK_FACTORIES:
            return _LOCK_FACTORIES[tail]
        # dataclasses.field(default_factory=threading.Lock)
        if tail == "field":
            for kw in value.keywords:
                if kw.arg == "default_factory":
                    factory = module.dotted(kw.value) or ""
                    if factory in _LOCK_CTORS:
                        return _LOCK_CTORS[factory]
        return None

    def _discover_locks(self, module: ModuleInfo) -> None:
        # module-level locks
        globals_here: Dict[str, LockId] = {}
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = self._lock_kind(module, node.value)
                if kind:
                    name = node.targets[0].id
                    globals_here[name] = LockId(module.relpath, "", name,
                                                kind)
        if globals_here:
            self.module_locks[module.relpath] = globals_here
        # class-attribute locks (``self._lock = ...`` in any method, or an
        # annotated dataclass field with a Lock default_factory)
        for cls in module.nodes(ast.ClassDef):
            attrs: Dict[str, LockId] = {}
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Attribute) \
                        and isinstance(node.targets[0].value, ast.Name) \
                        and node.targets[0].value.id == "self":
                    kind = self._lock_kind(module, node.value)
                    if kind:
                        attr = node.targets[0].attr
                        attrs[attr] = LockId(module.relpath, cls.name,
                                             attr, kind)
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name) \
                        and node.value is not None:
                    kind = self._lock_kind(module, node.value)
                    if kind:
                        attrs[node.target.id] = LockId(
                            module.relpath, cls.name, node.target.id, kind)
            if attrs:
                self.class_locks[(module.relpath, cls.name)] = attrs

    # -- per-module scan -----------------------------------------------------
    def _scan_module(self, module: ModuleInfo) -> None:
        # thread-entry discovery: Thread(target=f), executor.submit(f, ...)
        for call in module.nodes(ast.Call):
            name = module.dotted(call.func) or ""
            target = None
            if name in _THREAD_TARGET_CTORS or name.endswith(".Thread"):
                for kw in call.keywords:
                    if kw.arg == "target":
                        target = kw.value
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "submit" and call.args:
                target = call.args[0]
            if target is not None:
                dotted = module.dotted(target)
                if dotted:
                    self.thread_targets.add(dotted.split(".")[-1])
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                for fn in node.body:
                    if isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        self._scan_function(module, fn, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(module, node, "")

    def _resolve_lock(self, module: ModuleInfo, owner: str,
                      expr: ast.AST) -> Optional[LockId]:
        """The LockId acquired by a ``with <expr>:`` item, if any."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and owner:
            return self.class_locks.get(
                (module.relpath, owner), {}).get(expr.attr)
        if isinstance(expr, ast.Name):
            hit = self.module_locks.get(module.relpath, {}).get(expr.id)
            if hit is not None:
                return hit
            # ``from mod import _LOCK`` style cross-module locks
            alias = module.aliases.get(expr.id, "")
            tail = alias.split(".")[-1] if alias else expr.id
            for locks in self.module_locks.values():
                if tail in locks:
                    return locks[tail]
        return None

    def _scan_function(self, module: ModuleInfo, fn, owner: str) -> None:
        if fn.name in ("__init__", "__new__", "__del__"):
            return   # pre-publication writes: not part of the discipline
        qual = f"{owner}.{fn.name}" if owner else fn.name
        info = FunctionInfo(qualname=qual, module=module.relpath)
        self.functions[(module.relpath, qual)] = info
        assumed = fn.name.endswith("_locked")
        entry_held: Tuple[LockId, ...] = ()
        if assumed and owner:
            locks = self.class_locks.get((module.relpath, owner), {})
            if len(locks) == 1:
                entry_held = (next(iter(locks.values())),)
        self._walk_scope(module, fn, owner, qual, info,
                         list(entry_held), assumed, list(fn.body))

    def _walk_scope(self, module: ModuleInfo, fn, owner: str, qual: str,
                    info: FunctionInfo, held: List[LockId], assumed: bool,
                    stmts: Sequence[ast.AST]) -> None:
        for stmt in stmts:
            self._walk_stmt(module, stmt, owner, qual, info, held, assumed)

    def _walk_stmt(self, module: ModuleInfo, node: ast.AST, owner: str,
                   qual: str, info: FunctionInfo, held: List[LockId],
                   assumed: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested def does not run at this point in the enclosing
            # function — its body is not under these locks
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[LockId] = []
            for item in node.items:
                self._walk_expr(module, item.context_expr, owner, qual,
                                info, held)
                lock = self._resolve_lock(module, owner, item.context_expr)
                if lock is not None:
                    self.acquires.append(AcquireEvent(
                        module, item.context_expr, lock, tuple(held), qual))
                    info.acquires.add(lock)
                    held.append(lock)
                    acquired.append(lock)
            self._walk_scope(module, node, owner, qual, info, held,
                             assumed, node.body)
            for lock in acquired:
                held.remove(lock)
            return
        # expressions first (calls, writes live in child expressions)
        self._record_writes(module, node, owner, qual, held, assumed)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._walk_expr(module, child, owner, qual, info, held)
            elif isinstance(child, ast.stmt):
                self._walk_stmt(module, child, owner, qual, info, held,
                                assumed)
            else:
                # handlers, withitems of non-lock withs, etc.
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.stmt):
                        self._walk_stmt(module, sub, owner, qual, info,
                                        held, assumed)
                    elif isinstance(sub, ast.expr):
                        self._walk_expr(module, sub, owner, qual, info,
                                        held)

    def _walk_expr(self, module: ModuleInfo, node: ast.AST, owner: str,
                   qual: str, info: FunctionInfo,
                   held: List[LockId]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if isinstance(sub, ast.Call):
                self._record_call(module, sub, owner, qual, info, held)

    # -- events --------------------------------------------------------------
    def _is_declared_condition(self, module: ModuleInfo, owner: str,
                               recv: ast.AST) -> bool:
        """True when ``recv`` resolves to a field/global this model saw
        constructed as a ``threading.Condition`` — its ``.wait()``
        releases the tied lock regardless of how the field is named."""
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self" and owner:
            lock = self.class_locks.get(
                (module.relpath, owner), {}).get(recv.attr)
        elif isinstance(recv, ast.Name):
            lock = self.module_locks.get(module.relpath, {}).get(recv.id)
        else:
            return False
        return lock is not None and lock.kind == "condition"

    def _classify_blocking(self, module: ModuleInfo, call: ast.Call,
                           owner: str = "") -> Optional[Tuple[str, str]]:
        """(what, why) if this call blocks on device/network/clock/queue."""
        name = module.dotted(call.func) or ""
        if name in _BLOCKING_CALLS:
            return name, _BLOCKING_CALLS[name]
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            recv = call.func.value
            recv_name = ""
            if isinstance(recv, ast.Attribute):
                recv_name = recv.attr
            elif isinstance(recv, ast.Name):
                recv_name = recv.id
            if attr in _BLOCKING_METHODS:
                return f".{attr}()", _BLOCKING_METHODS[attr]
            if attr in ("get", "put") \
                    and _QUEUE_NAME_RE.search(recv_name) \
                    and not _is_nonblocking_queue_call(call):
                return f"{recv_name}.{attr}()", "waits on a queue"
            if attr == "join" and _THREAD_NAME_RE.search(recv_name):
                return f"{recv_name}.join()", "joins a thread"
            if attr == "wait" \
                    and not _CONDITION_NAME_RE.search(recv_name) \
                    and not self._is_declared_condition(module, owner,
                                                        recv):
                # Condition.wait releases the lock it is tied to
                # (recognized by cond-ish naming OR a seen
                # threading.Condition construction); a bare Event.wait
                # under someone ELSE's lock does not
                return f"{recv_name}.wait()", "waits on an event"
        return None

    def _record_call(self, module: ModuleInfo, call: ast.Call, owner: str,
                     qual: str, info: FunctionInfo,
                     held: List[LockId]) -> None:
        held_t = tuple(held)
        # call expansion targets: self.m() and bare module functions
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id == "self" and owner:
            info.calls.append((f"{owner}.{call.func.attr}", held_t, call))
        elif isinstance(call.func, ast.Name):
            info.calls.append((call.func.id, held_t, call))
        blk = self._classify_blocking(module, call, owner)
        if blk is not None:
            info.blocking.append((blk[0], blk[1], call, held_t))
            if held:
                self.blocking.append(BlockingEvent(
                    module, call, blk[0], blk[1], held_t, qual))

    def _record_writes(self, module: ModuleInfo, stmt: ast.AST, owner: str,
                       qual: str, held: List[LockId],
                       assumed: bool) -> None:
        held_t = tuple(held)
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in _MUTATORS:
                recv = call.func.value
                field_name = self._field_of(recv, owner)
                if field_name is not None:
                    self.writes.append(WriteEvent(
                        module, call, owner, field_name, held_t, qual,
                        assumed))
                g = self._global_of(module, recv)
                if g is not None:
                    self.writes.append(WriteEvent(
                        module, call, "", g, held_t, qual, assumed))
            return
        for t in targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            field_name = self._field_of(base, owner)
            if field_name is not None:
                self.writes.append(WriteEvent(
                    module, t, owner, field_name, held_t, qual, assumed))
            g = self._global_of(module, base)
            if g is not None:
                # direct Name assignment only counts as a global write
                # when the function declares ``global g`` — otherwise it
                # just binds a local; subscript/attr writes always count
                if isinstance(t, ast.Name) \
                        and not self._declares_global(module, qual, t.id):
                    continue
                self.writes.append(WriteEvent(
                    module, t, "", g, held_t, qual, assumed))

    def _field_of(self, node: ast.AST, owner: str) -> Optional[str]:
        if owner and isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    def _global_of(self, module: ModuleInfo,
                   node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name) \
                and node.id in self._module_globals(module):
            return node.id
        return None

    def _module_globals(self, module: ModuleInfo) -> Set[str]:
        cached = getattr(module, "_conc_globals", None)
        if cached is None:
            cached = set()
            for node in module.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            cached.add(t.id)
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    cached.add(node.target.id)
            module._conc_globals = cached
        return cached

    def _declares_global(self, module: ModuleInfo, qual: str,
                         name: str) -> bool:
        key = (module.relpath, qual)
        cached = getattr(module, "_conc_global_decls", None)
        if cached is None:
            cached = {}
            module._conc_global_decls = cached
        if key not in cached:
            decls: Set[str] = set()
            fn = self._find_function(module, qual)
            if fn is not None:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Global):
                        decls.update(node.names)
            cached[key] = decls
        return name in cached[key]

    def _find_function(self, module: ModuleInfo, qual: str):
        parts = qual.split(".")
        body = module.tree.body
        if len(parts) == 2:
            for node in body:
                if isinstance(node, ast.ClassDef) and node.name == parts[0]:
                    body = node.body
                    break
            else:
                return None
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == parts[-1]:
                return node
        return None

    # -- one-level call expansion (TPU013/TPU014 edges through helpers) ------
    def _expand_calls(self) -> None:
        for info in self.functions.values():
            for callee, held, node in info.calls:
                if not held:
                    continue
                target = self.functions.get((info.module, callee))
                if target is None:
                    continue
                caller_module = self.project.module(info.module)
                chain = f"{info.qualname} -> {callee}"
                for lock in target.acquires:
                    if caller_module is not None:
                        self.acquires.append(AcquireEvent(
                            caller_module, node, lock, held, chain))
                # the callee's blocking calls now run under the caller's
                # locks: ``with self._lock: self._spill(...)`` convoys on
                # the device_get inside _spill
                callee_module = self.project.module(target.module)
                if callee_module is None:
                    continue
                for what, why, blk_node, inner in target.blocking:
                    combined = held + tuple(
                        lk for lk in inner if lk not in held)
                    self.blocking.append(BlockingEvent(
                        callee_module, blk_node, what, why, combined,
                        chain))

    # -- inference -----------------------------------------------------------
    def guarded_fields(self) -> Dict[Tuple[str, str, str], LockId]:
        """{(module, owner, field): owning lock} for fields whose write
        discipline says "guarded": at least two lock-held writes and at
        least as many held as bare ones. Writes in ``*_locked`` methods
        count toward the held side without voting for a specific lock."""
        stats: Dict[Tuple[str, str, str], Dict] = {}
        for w in self.writes:
            key = (w.module.relpath, w.owner, w.target)
            s = stats.setdefault(key, {"held": 0, "bare": 0, "locks": {}})
            owning = self._owning_lock(w)
            if owning is not None:
                s["held"] += 1
                s["locks"][owning] = s["locks"].get(owning, 0) + 1
            elif w.assumed:
                s["held"] += 1
            else:
                s["bare"] += 1
        out: Dict[Tuple[str, str, str], LockId] = {}
        for key, s in stats.items():
            if s["held"] >= 2 and s["held"] >= s["bare"] and s["locks"]:
                out[key] = max(s["locks"].items(), key=lambda kv: kv[1])[0]
        return out

    def _owning_lock(self, w: WriteEvent) -> Optional[LockId]:
        """The innermost held lock eligible to own this write's target:
        a same-class lock for fields, a same-module lock for globals."""
        for lock in reversed(w.held):
            if w.owner and lock.owner == w.owner \
                    and lock.module == w.module.relpath:
                return lock
            if not w.owner and not lock.owner:
                return lock
        return None


def get_model(project: Project) -> ConcurrencyModel:
    """The per-project model, built once and shared by the three rules."""
    model = getattr(project, "_concurrency_model", None)
    if model is None or model.project is not project:
        model = ConcurrencyModel(project)
        project._concurrency_model = model
    return model


def _is_nonblocking_queue_call(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    # q.get(0)-style immediate timeouts stay flagged: they still park the
    # holder for the timeout under contention
    return False


@register_rule
class UnguardedSharedMutation(Rule):
    code = "TPU012"
    name = "unguarded-shared-mutation"
    severity = "warning"
    project_scope = True
    doc = ("A write to a lock-guarded field outside the owning lock. The "
           "guard discipline is *inferred* from the code itself: a field "
           "mutated at least twice under ``with self._lock:`` (or a module "
           "global under a module lock) is declared guarded, and the "
           "outlier bare writes are reported. ``__init__`` writes and "
           "``*_locked``-suffixed methods (entered with the lock held, "
           "the ``_prune_locked`` convention) don't count as outliers. "
           "Intentional lock-free paths (single-writer fields, "
           "publish-only races) carry an inline disable with the "
           "justification.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        model = get_model(project)
        guarded = model.guarded_fields()
        findings: List[Finding] = []
        for w in model.writes:
            key = (w.module.relpath, w.owner, w.target)
            lock = guarded.get(key)
            if lock is None or w.assumed:
                continue
            if model._owning_lock(w) is not None:
                continue
            where = f"{w.owner}.{w.target}" if w.owner else w.target
            findings.append(self.finding(
                w.module, w.node,
                f"'{where}' is written under {lock} elsewhere but "
                f"mutated here (in {w.func}) without holding it — a "
                f"racing thread sees partial state; take the lock or "
                f"justify the lock-free path inline"))
        return iter(findings)


@register_rule
class LockOrderInversion(Rule):
    code = "TPU013"
    name = "lock-order-inversion"
    severity = "error"
    project_scope = True
    doc = ("A cycle in the static lock-acquisition graph: somewhere the "
           "program takes lock A then B (nested ``with`` scopes, "
           "including one level of same-class/same-module call "
           "expansion), somewhere else B then A — two threads running "
           "those paths concurrently deadlock. Also flags nested "
           "re-acquisition of the same non-reentrant ``threading.Lock`` "
           "through a self-call chain (guaranteed self-deadlock). The "
           "runtime counterpart is reliability.lock_sanitizer, which "
           "catches orders the static nesting cannot see.")

    def check_project(self, project: Project) -> Iterator[Finding]:
        model = get_model(project)
        findings: List[Finding] = []
        edges: Dict[Tuple[LockId, LockId], AcquireEvent] = {}
        for ev in model.acquires:
            for held in ev.held:
                if held == ev.lock:
                    if ev.lock.kind == "lock":
                        findings.append(self.finding(
                            ev.module, ev.node,
                            f"{ev.lock} is acquired while already held "
                            f"(via {ev.func}) and it is a non-reentrant "
                            f"threading.Lock — this path self-deadlocks; "
                            f"use an RLock or split the method into a "
                            f"*_locked inner"))
                    continue
                edges.setdefault((held, ev.lock), ev)
        reported: Set[frozenset] = set()
        for (a, b), ev in sorted(edges.items(),
                                 key=lambda kv: str(kv[0])):
            back = edges.get((b, a))
            if back is None:
                continue
            pair = frozenset((a, b))
            if pair in reported:
                continue
            reported.add(pair)
            findings.append(self.finding(
                ev.module, ev.node,
                f"lock-order inversion: {a} -> {b} here (in {ev.func}) "
                f"but {b} -> {a} at {back.module.relpath}:"
                f"{getattr(back.node, 'lineno', '?')} (in {back.func}) — "
                f"two threads interleaving these paths deadlock; pick one "
                f"global order"))
        return iter(findings)


@register_rule
class BlockingCallUnderLock(Rule):
    code = "TPU014"
    name = "blocking-call-under-lock"
    severity = "warning"
    project_scope = True
    doc = ("A blocking call while holding a lock: jax.device_get / "
           "block_until_ready (device sync), time.sleep, an HTTP dial, a "
           "subprocess wait, a queue get/put, a thread join, or an "
           "Event.wait inside a ``with <lock>:`` scope (or a ``*_locked`` "
           "method). Every thread that needs the lock now waits on the "
           "device or the network too — the convoy the stall watchdog "
           "only sees at runtime. Move the slow call outside the critical "
           "section (snapshot under lock, block outside), or justify the "
           "hold inline (e.g. a spill that must be atomic with its LRU "
           "bookkeeping).")

    def check_project(self, project: Project) -> Iterator[Finding]:
        model = get_model(project)
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, int]] = set()
        for ev in model.blocking:
            loc = (ev.module.relpath, getattr(ev.node, "lineno", 0),
                   getattr(ev.node, "col_offset", 0))
            if loc in seen:   # direct event wins over call-expanded echo
                continue
            seen.add(loc)
            locks = ", ".join(str(lk) for lk in ev.held)
            findings.append(self.finding(
                ev.module, ev.node,
                f"{ev.what} {ev.why} while holding {locks} (in {ev.func}) "
                f"— lock waiters convoy behind the slow call; snapshot "
                f"state under the lock and block outside it"))
        return iter(findings)
