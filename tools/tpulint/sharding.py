"""Whole-program sharding analysis: TPU019–TPU022.

The mesh data plane (PR 15/16) made sharding *correctness* the thing a
typo breaks: an axis name no mesh defines fails only at trace time on a
real mesh, a ``shard_map`` spec tuple that drifted from its callee's
signature fails the same way, a bare ``jax.device_put`` under a mesh
silently replicates a buffer onto every chip, and a collective inside a
Python loop trace-unrolls into a collective storm. All four are visible
in the AST. This module discovers the program's mesh constructions and
axis-name vocabulary (``parallel/mesh.py`` factories, literal
``Mesh(...)`` tuples, ``mesh.shape``/``axis_names`` contract probes,
canonical ``mesh_shape()`` strings), then threads
``PartitionSpec``/``shard_map`` specs through import aliases and one
level of name/``functools.partial`` expansion to power the rules:

- **TPU019** unknown-mesh-axis: a literal axis name in ``P(...)``, a
  collective's ``axis_name``, or an ``*_axis=`` keyword that no
  reachable mesh construction or axis-contract probe defines.
- **TPU020** spec-rank-mismatch: ``shard_map`` ``in_specs`` arity
  inconsistent with the mounted callee's positional parameters (through
  one level of ``partial``), ``out_specs`` arity vs the callee's literal
  tuple returns, and ``P(...)`` specs longer than the rank of the array
  they constrain (literal-shape constructors and jaxtyping-style
  ``Float[Array, "b h d"]`` annotations, including in sibling stubs).
- **TPU021** unsharded-device-put: a single-argument ``jax.device_put``
  in a function with a mesh in scope — under a mesh the default
  placement fully replicates the buffer onto every device.
- **TPU022** collective-in-loop: ``psum``/``all_gather``/``ppermute``/…
  lexically inside a Python loop in a jitted function — the trace
  unrolls one collective per iteration (``lax.fori_loop``/``scan``
  bodies are traced once and stay quiet).

The static half of the sharding story; the runtime half is
``mmlspark_tpu/parallel/collective_audit.py``, which walks the compiled
HLO and gates CI on per-program collective budgets.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import (Finding, ModuleInfo, Project, Rule, jit_decoration,
                   register_rule)
from .rules import _ContextVisitor, _mesh_param

#: collective primitives whose axis argument must name a live mesh axis
COLLECTIVE_NAMES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                    "all_to_all", "ppermute", "pshuffle", "psum_scatter"}

#: canonical mesh_shape() string, e.g. "dp4xtp2" / "data8"
_MESH_SHAPE_RE = re.compile(r"^[a-z]{1,12}\d+(?:x[a-z]{1,12}\d+)*$")
#: the "x" separator always follows the size digits, and axis names
#: never start with one — split there, then strip each segment's size
_MESH_SHAPE_SEP_RE = re.compile(r"(?<=\d)x")
_MESH_SHAPE_AXIS_RE = re.compile(r"^([a-z]+)\d+$")


def _is_partition_spec(module: ModuleInfo, call: ast.Call) -> bool:
    name = module.dotted(call.func)
    return bool(name) and (name == "PartitionSpec"
                           or name.endswith(".PartitionSpec"))


def _str_consts(node: ast.AST) -> Iterable[Tuple[ast.AST, str]]:
    """String constants in ``node`` — itself, or elements of a literal
    tuple/list (a P dim may carry several axes: ``P(("dp", "tp"))``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node, node.value
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                yield e, e.value


def _resolve_name(module: ModuleInfo, node: ast.AST,
                  scope: Optional[ast.AST] = None) -> ast.AST:
    """One-level name expansion: if ``node`` is a Name assigned exactly
    once by a simple ``name = value`` (searching ``scope`` first, then
    the whole module), return the assigned value, else ``node``."""
    if not isinstance(node, ast.Name):
        return node
    for tree in ([scope] if scope is not None else []) + [module.tree]:
        hits = [a.value for a in ast.walk(tree)
                if isinstance(a, ast.Assign) and len(a.targets) == 1
                and isinstance(a.targets[0], ast.Name)
                and a.targets[0].id == node.id]
        if len(hits) == 1:
            return hits[0]
        if hits:
            return node          # ambiguous: don't guess
    return node


def _is_collective(name: Optional[str]) -> bool:
    if not name:
        return False
    base = name.rsplit(".", 1)[-1]
    if base not in COLLECTIVE_NAMES:
        return False
    return name == base or "lax" in name or name.startswith("jax.")


# ---------------------------------------------------------------------------
# mesh-axis vocabulary discovery (shared by TPU019)
# ---------------------------------------------------------------------------

_MESH_FACTORIES = ("make_mesh", "MeshContext")


def declared_axes(module: ModuleInfo) -> Set[str]:
    """Axis names this module's mesh constructions and contract probes
    define: literal ``Mesh(devs, ("dp", "tp"))`` tuples /
    ``axis_names=`` keywords, dict-literal keys fed to
    ``make_mesh``/``MeshContext`` (through one level of name
    resolution), ``mesh.shape.get("tp")`` / ``mesh.shape["tp"]`` /
    ``"tp" in mesh.axis_names`` contract probes, and the axis segments
    of canonical ``mesh_shape()`` strings compared against a
    ``mesh_shape(...)`` call."""
    axes: Set[str] = set()
    for call in module.nodes(ast.Call):
        name = module.dotted(call.func) or ""
        base = name.rsplit(".", 1)[-1]
        if base == "Mesh" or name.endswith("sharding.Mesh"):
            cand = [kw.value for kw in call.keywords
                    if kw.arg == "axis_names"]
            if not cand and len(call.args) >= 2:
                cand = [call.args[1]]
            for c in cand:
                axes.update(v for _, v in _str_consts(c))
        elif base in _MESH_FACTORIES:
            cand = call.args[:1] + [kw.value for kw in call.keywords
                                    if kw.arg == "axis_shapes"]
            if not cand:
                axes.add("data")   # make_mesh() default 1-D data mesh
            for c in cand:
                c = _resolve_name(module, c)
                if isinstance(c, ast.Dict):
                    axes.update(k.value for k in c.keys
                                if isinstance(k, ast.Constant)
                                and isinstance(k.value, str))
                elif isinstance(c, ast.Constant) and c.value is None:
                    axes.add("data")
        elif (isinstance(call.func, ast.Attribute)
              and call.func.attr == "get"
              and isinstance(call.func.value, ast.Attribute)
              and call.func.value.attr == "shape" and call.args):
            # mesh.shape.get("tp", ...) — the engine's axis contract
            axes.update(v for _, v in _str_consts(call.args[0]))
    for sub in module.nodes(ast.Subscript):
        # mesh.shape["tp"]
        if isinstance(sub.value, ast.Attribute) and sub.value.attr == "shape":
            axes.update(v for _, v in _str_consts(sub.slice))
    for cmp in module.nodes(ast.Compare):
        operands = [cmp.left] + list(cmp.comparators)
        # "tp" in mesh.axis_names
        if any(isinstance(op, ast.In) for op in cmp.ops):
            if any(isinstance(o, ast.Attribute) and o.attr == "axis_names"
                   for o in operands):
                for o in operands:
                    axes.update(v for _, v in _str_consts(o))
        # mesh_shape(m) == "dp4xtp2" — parse the canonical string's axes
        if any(isinstance(o, ast.Call)
               and (module.dotted(o.func) or "").endswith("mesh_shape")
               for o in operands):
            for o in operands:
                for _, v in _str_consts(o):
                    if _MESH_SHAPE_RE.match(v):
                        for seg in _MESH_SHAPE_SEP_RE.split(v):
                            m_ax = _MESH_SHAPE_AXIS_RE.match(seg)
                            if m_ax:
                                axes.add(m_ax.group(1))
    return axes


def _axis_uses(module: ModuleInfo):
    """Yield ``(node, axis)`` for every literal axis-name usage: ``P``
    positional dims, collective axis arguments, and ``axis_name=`` /
    ``*_axis=`` keywords."""
    for call in module.nodes(ast.Call):
        name = module.dotted(call.func)
        if name and _is_partition_spec(module, call):
            for arg in call.args:
                yield from _str_consts(arg)
        elif _is_collective(name):
            cand = list(call.args[1:2]) + [kw.value for kw in call.keywords
                                           if kw.arg == "axis_name"]
            for c in cand:
                yield from _str_consts(c)
        for kw in call.keywords:
            if kw.arg and (kw.arg == "axis_name"
                           or kw.arg.endswith("_axis")):
                yield from _str_consts(kw.value)


@register_rule
class UnknownMeshAxis(Rule):
    code = "TPU019"
    name = "unknown-mesh-axis"
    severity = "error"
    project_scope = True
    doc = ("A literal mesh-axis name — in a ``P(...)`` spec, a "
           "collective's ``axis_name``, or an ``*_axis=`` keyword — that "
           "no reachable mesh construction defines. The vocabulary is "
           "discovered whole-program: literal ``Mesh(..., names)`` "
           "tuples, ``make_mesh``/``MeshContext`` axis dicts, "
           "``mesh.shape.get(axis)``/``'axis' in mesh.axis_names`` "
           "contract probes, and canonical ``mesh_shape()`` strings. An "
           "axis typo compiles fine and fails only at trace time on a "
           "real mesh — usually the TPU pod run the bench queue waited "
           "a week for. Quiet when the project constructs no meshes.")

    def check_project(self, project: Project):
        vocab: Set[str] = set()
        for m in project.modules:
            vocab |= declared_axes(m)
        if not vocab:
            return iter(())
        findings: List[Finding] = []
        seen: Set[int] = set()
        for m in project.modules:
            for node, axis in _axis_uses(m):
                if axis in vocab or id(node) in seen:
                    continue
                seen.add(id(node))
                findings.append(self.finding(
                    m, node,
                    f"axis name '{axis}' is not defined by any mesh this "
                    f"program constructs (known axes: "
                    f"{', '.join(sorted(vocab))}) — a sharding spec "
                    f"naming a nonexistent axis fails only at trace "
                    f"time on a real mesh"))
        return iter(findings)


# ---------------------------------------------------------------------------
# TPU020 spec-rank-mismatch
# ---------------------------------------------------------------------------

#: array constructors whose first literal tuple argument fixes the rank
_SHAPE_CTORS = {"zeros", "ones", "full", "empty"}


def _annotation_rank(annotation: Optional[ast.AST]) -> Optional[int]:
    """Rank from a jaxtyping-style annotation — ``Float[Array, "b h d"]``
    → 3. None when the annotation carries no shape string."""
    if not isinstance(annotation, ast.Subscript):
        return None
    sl = annotation.slice
    elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            toks = e.value.split()
            if toks and all(re.match(r"^[#*]?[A-Za-z0-9_]+$", t)
                            for t in toks):
                return len(toks)
    return None


def _spec_len(node: ast.AST) -> Optional[int]:
    """Number of dims a literal ``P(...)`` call constrains."""
    if isinstance(node, ast.Call) and isinstance(node.func, (ast.Name,
                                                             ast.Attribute)):
        return len(node.args)
    return None


def _partial_parts(module: ModuleInfo, node: ast.AST):
    """Decompose ``functools.partial(fn, a, kw=...)`` → (fn node,
    n_bound_positional, bound_kwarg_names); identity for anything else."""
    if isinstance(node, ast.Call) \
            and module.dotted(node.func) in ("functools.partial", "partial") \
            and node.args:
        return (node.args[0], len(node.args) - 1,
                {kw.arg for kw in node.keywords if kw.arg})
    return node, 0, set()


def _pick_def(defs: List[ast.FunctionDef], name: str,
              scope: Optional[ast.AST],
              before_line: int) -> Optional[ast.FunctionDef]:
    """The def ``name`` resolves to at ``before_line``: prefer defs
    nested in the enclosing ``scope``, then the nearest one above the
    use site — local ``def fn`` shadows an earlier same-named def, so a
    module-wide first-match would bind the wrong signature."""
    cands = [f for f in defs if f.name == name]
    if not cands:
        return None
    if len(cands) == 1:
        return cands[0]
    if scope is not None:
        in_scope = {id(n) for n in ast.walk(scope)}
        scoped = [f for f in cands if id(f) in in_scope]
        if scoped:
            cands = scoped
    preceding = [f for f in cands if f.lineno <= before_line]
    return max(preceding or cands, key=lambda f: f.lineno)


def _callee_fn(module: ModuleInfo, defs: List[ast.FunctionDef],
               node: ast.AST, scope: Optional[ast.AST], use_line: int):
    """Resolve the mounted callee through one level of name assignment
    and one level of ``partial``; returns (FunctionDef | None,
    n_bound_positional, bound_kwargs)."""
    node = _resolve_name(module, node, scope)
    node, n_pos, kws = _partial_parts(module, node)
    node = _resolve_name(module, node, scope)
    if isinstance(node, ast.Name):
        return _pick_def(defs, node.id, scope, use_line), n_pos, kws
    return None, n_pos, kws


def _literal_tuple_returns(fn: ast.FunctionDef) -> Optional[int]:
    """If every ``return`` at ``fn``'s own level is a literal tuple of
    one consistent length, that length; else None."""
    lengths: Set[int] = set()
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Return):
            if not isinstance(node.value, ast.Tuple):
                return None
            lengths.add(len(node.value.elts))
        stack.extend(ast.iter_child_nodes(node))
    return lengths.pop() if len(lengths) == 1 else None


@register_rule
class SpecRankMismatch(Rule):
    code = "TPU020"
    name = "spec-rank-mismatch"
    severity = "error"
    doc = ("A sharding spec structurally inconsistent with what it "
           "shards: a ``shard_map`` ``in_specs`` tuple whose arity "
           "cannot bind the mounted callee's positional parameters "
           "(resolved through one level of name assignment and "
           "``functools.partial``), an ``out_specs`` tuple whose arity "
           "differs from the callee's literal tuple returns, or a "
           "``P(...)`` spec with more dims than the rank of the array "
           "it constrains (literal-shape constructors like "
           "``jnp.zeros((4, 8))``, or a jaxtyping-style "
           "``Float[Array, \"b h d\"]`` annotation — module or sibling "
           "``.pyi`` stub). Every one of these traces as a shape error "
           "only once a mesh is live.")

    def check(self, module: ModuleInfo):
        defs = [fn for fn in module.nodes(ast.FunctionDef,
                                          ast.AsyncFunctionDef)]
        findings: List[Finding] = []
        findings.extend(self._shard_map_checks(module, defs))
        findings.extend(self._rank_checks(module, defs))
        return iter(findings)

    # -- shard_map in/out_specs vs the mounted callee -----------------------
    def _shard_map_checks(self, module: ModuleInfo, defs):
        findings: List[Finding] = []
        enclosing: Dict[int, ast.AST] = {}
        for fn in defs:
            for sub in ast.walk(fn):
                enclosing.setdefault(id(sub), fn)
        for call in module.nodes(ast.Call):
            name = module.dotted(call.func) or ""
            if "shard_map" not in name:
                continue
            scope = enclosing.get(id(call))
            kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
            callee_node = call.args[0] if call.args else None
            # decorator form: @partial(jax.shard_map, mesh=..., ...)
            decorated = None
            for fn in defs:
                for dec in fn.decorator_list:
                    for sub in ast.walk(dec):
                        if sub is call:
                            decorated = fn
            if decorated is not None:
                callee, n_pos, bound = decorated, 0, set()
            else:
                callee, n_pos, bound = _callee_fn(module, defs,
                                                  callee_node, scope,
                                                  call.lineno)
            in_specs = _resolve_name(module, kwargs.get("in_specs"), scope) \
                if "in_specs" in kwargs else None
            if callee is not None and isinstance(in_specs, ast.Tuple) \
                    and callee.args.vararg is None:
                params = callee.args.posonlyargs + callee.args.args
                names = [a.arg for a in params if a.arg not in ("self",
                                                                "cls")]
                free = [n for n in names[n_pos:] if n not in bound]
                n_default = len(callee.args.defaults)
                required = [n for n in names[:len(names) - n_default]
                            if n not in bound][n_pos:]
                n = len(in_specs.elts)
                if n > len(free) or n < len(required):
                    findings.append(self.finding(
                        module, in_specs,
                        f"shard_map in_specs has {n} spec(s) but mounted "
                        f"callee '{callee.name}' binds "
                        f"{len(required)}..{len(free)} positional "
                        f"argument(s) — the mount fails at trace time "
                        f"on a live mesh"))
            out_specs = _resolve_name(module, kwargs.get("out_specs"),
                                      scope) if "out_specs" in kwargs \
                else None
            if callee is not None and isinstance(out_specs, ast.Tuple):
                ret_n = _literal_tuple_returns(callee)
                if ret_n is not None and ret_n != len(out_specs.elts):
                    findings.append(self.finding(
                        module, out_specs,
                        f"shard_map out_specs has {len(out_specs.elts)} "
                        f"spec(s) but mounted callee '{callee.name}' "
                        f"returns a {ret_n}-tuple"))
        return findings

    # -- P(...) longer than the constrained array's rank --------------------
    _CONSTRAINERS = ("with_sharding_constraint", "device_put",
                     "NamedSharding")

    def _rank_checks(self, module: ModuleInfo, funcs):
        findings: List[Finding] = []
        # parameter ranks from jaxtyping-style annotations (module body,
        # or the sibling .pyi stub parsed into the same project by the
        # caller — stubs re-declare the signatures, so scanning both
        # costs nothing and keeps hand-written stubs load-bearing)
        for call in module.nodes(ast.Call):
            name = module.dotted(call.func) or ""
            base = name.rsplit(".", 1)[-1]
            if base not in ("with_sharding_constraint", "device_put"):
                continue
            if len(call.args) < 2:
                continue
            target, spec = call.args[0], call.args[1]
            if isinstance(spec, ast.Call):
                sname = module.dotted(spec.func) or ""
                if sname.rsplit(".", 1)[-1] == "NamedSharding" \
                        and len(spec.args) >= 2:
                    spec = spec.args[1]
            n_spec = (_spec_len(spec)
                      if isinstance(spec, ast.Call)
                      and _is_partition_spec(module, spec) else None)
            rank = self._rank_of(module, funcs, call, target)
            if n_spec is not None and rank is not None and n_spec > rank:
                findings.append(self.finding(
                    module, spec,
                    f"P(...) names {n_spec} dims but the constrained "
                    f"array has rank {rank} — the spec cannot bind"))
        return findings

    def _rank_of(self, module, funcs, call, target) -> Optional[int]:
        enclosing = None
        for fn in module.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            for sub in ast.walk(fn):
                if sub is call:
                    enclosing = fn
        node = _resolve_name(module, target, enclosing)
        if isinstance(node, ast.Call):
            cname = module.dotted(node.func) or ""
            if cname.rsplit(".", 1)[-1] in _SHAPE_CTORS and node.args \
                    and isinstance(node.args[0], ast.Tuple):
                return len(node.args[0].elts)
        if isinstance(target, ast.Name) and enclosing is not None:
            for a in (enclosing.args.posonlyargs + enclosing.args.args
                      + enclosing.args.kwonlyargs):
                if a.arg == target.id:
                    return _annotation_rank(a.annotation)
        return None


# ---------------------------------------------------------------------------
# TPU021 unsharded-device-put
# ---------------------------------------------------------------------------

_DEVICE_PUT = ("jax.device_put", "device_put")


def _mesh_none_exempt(fn: ast.AST, mesh_name: str) -> Set[int]:
    """Node ids of subtrees where ``mesh`` is knowably absent: the body
    of ``if mesh is None:`` (and the matching arm of an ``IfExp``), the
    orelse of ``if mesh is not None:``."""
    exempt: Set[int] = set()

    def test_kind(test: ast.AST) -> Optional[bool]:
        # True → "is None", False → "is not None", None → unrelated
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.left, ast.Name) \
                and test.left.id == mesh_name \
                and isinstance(test.comparators[0], ast.Constant) \
                and test.comparators[0].value is None:
            if isinstance(test.ops[0], ast.Is):
                return True
            if isinstance(test.ops[0], ast.IsNot):
                return False
        return None

    def mark(node: ast.AST):
        for sub in ast.walk(node):
            exempt.add(id(sub))

    for sub in ast.walk(fn):
        if isinstance(sub, ast.If):
            kind = test_kind(sub.test)
            if kind is True:
                for stmt in sub.body:
                    mark(stmt)
            elif kind is False:
                for stmt in sub.orelse:
                    mark(stmt)
        elif isinstance(sub, ast.IfExp):
            kind = test_kind(sub.test)
            if kind is True:
                mark(sub.body)
            elif kind is False:
                mark(sub.orelse)
    return exempt


@register_rule
class UnshardedDevicePut(Rule):
    code = "TPU021"
    name = "unsharded-device-put"
    severity = "warning"
    doc = ("A single-argument ``jax.device_put`` in a function with a "
           "mesh in scope (a ``mesh`` parameter, a "
           "``Mesh``/``NamedSharding`` annotation, or a "
           "``get_default_mesh()`` read). With no placement argument "
           "the array lands replicated on every device — N silent "
           "copies of the buffer and an all-gather the moment a sharded "
           "consumer touches it. Pass ``NamedSharding(mesh, P(...))`` "
           "(or the placement's ``put``); code on the ``mesh is None`` "
           "branch is recognized and stays quiet.")

    def check(self, module: ModuleInfo):
        findings: List[Finding] = []
        flagged: Set[int] = set()
        for fn in module.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            mesh = _mesh_param(module, fn)
            if mesh is None:
                has_default = any(
                    (module.dotted(c.func) or "").endswith(
                        "get_default_mesh")
                    for c in ast.walk(fn) if isinstance(c, ast.Call))
                if not has_default:
                    continue
                mesh = "mesh"
            exempt = _mesh_none_exempt(fn, mesh)
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call) or id(sub) in flagged \
                        or id(sub) in exempt:
                    continue
                if module.dotted(sub.func) not in _DEVICE_PUT:
                    continue
                if len(sub.args) != 1 or any(
                        kw.arg in ("device", "sharding", "src")
                        for kw in sub.keywords):
                    continue
                flagged.add(id(sub))
                findings.append(self.finding(
                    module, sub,
                    f"device_put with no placement inside "
                    f"'{fn.name}' (mesh '{mesh}' in scope) — the array "
                    f"replicates onto every device by default; pass a "
                    f"NamedSharding(mesh, P(...)) or route through the "
                    f"resolved Placement.put"))
        return iter(findings)


# ---------------------------------------------------------------------------
# TPU022 collective-in-loop
# ---------------------------------------------------------------------------

@register_rule
class CollectiveInLoop(Rule):
    code = "TPU022"
    name = "collective-in-loop"
    severity = "warning"
    doc = ("A collective (``psum``/``all_gather``/``ppermute``/"
           "``all_to_all``/…) lexically inside a Python loop in a "
           "jitted function. The trace unrolls the loop, so N "
           "iterations emit N independent collectives — an ICI storm "
           "the profiler shows as a wall of tiny all-reduces. Hoist the "
           "collective out of the loop or convert the loop to "
           "``lax.fori_loop``/``lax.scan`` (whose bodies trace once and "
           "stay quiet here).")

    def check(self, module: ModuleInfo):
        visitor = _TPU022(module, self)
        visitor.visit(module.tree)
        return iter(visitor.findings)


class _TPU022(_ContextVisitor):
    def __init__(self, module, rule):
        super().__init__(module)
        self.rule = rule

    def handle_call(self, node: ast.Call):
        if self.jit_ctx is None or self.loop_depth == 0:
            return
        name = self.module.dotted(node.func)
        if _is_collective(name):
            self.findings.append(self.rule.finding(
                self.module, node,
                f"collective '{name}' inside a Python loop in a jitted "
                f"function — the trace unrolls one collective per "
                f"iteration; hoist it, or use lax.fori_loop/lax.scan "
                f"(bodies trace once)"))
