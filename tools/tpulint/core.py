"""tpulint core: findings, rule registry, module model, analysis driver.

Stdlib-only (``ast`` + ``tokenize``-free line scanning): the analyzer must
run in the CI image with zero extra dependencies, and import none of the
code it inspects — a module with a hazard at import time still gets linted.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

SEVERITIES = ("error", "warning", "info")

#: ``# tpulint: disable=TPU001`` / ``disable=TPU001,TPU004`` / ``disable=all``
_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\s]+|all)")
#: ``# tpulint: disable-file=TPU004`` — whole-module suppression, for host
#: modules that live in a device-feed directory (justify in the comment)
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*tpulint:\s*disable-file=([A-Za-z0-9_,\s]+|all)")


@dataclass(frozen=True)
class Finding:
    """One diagnostic: a rule firing at a source location."""

    rule: str                  # "TPU001"
    path: str                  # repo-relative path of the offending file
    line: int                  # 1-based
    col: int                   # 0-based
    severity: str              # error | warning | info
    message: str
    snippet: str = ""          # stripped source line (fingerprint material)

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}"


def fingerprint(f: Finding) -> str:
    """Line-number-free identity for baseline matching.

    Keyed on (path, rule, snippet) so unrelated edits that shift line
    numbers do not churn the baseline; duplicate identical lines in one
    file collapse into a count (the baseline stores occurrence counts).
    """
    return f"{f.path}::{f.rule}::{f.snippet}"


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

class Rule:
    """Base class: subclass, set ``code``/``name``/``severity``/``doc``,
    implement :meth:`check` (per module) or :meth:`check_project`."""

    code: str = ""
    name: str = ""
    severity: str = "warning"
    doc: str = ""
    #: project-scope rules see every module at once (cross-file checks)
    project_scope: bool = False

    def check(self, module: "ModuleInfo") -> Iterator[Finding]:
        return iter(())

    def check_project(self, project: "Project") -> Iterator[Finding]:
        return iter(())

    def finding(self, module: "ModuleInfo", node: ast.AST, message: str,
                severity: Optional[str] = None) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=self.code, path=module.relpath, line=line,
                       col=getattr(node, "col_offset", 0),
                       severity=severity or self.severity, message=message,
                       snippet=module.line(line))


_REGISTRY: Dict[str, type] = {}


def register_rule(cls: type) -> type:
    if not cls.code:
        raise ValueError(f"rule {cls.__name__} has no code")
    if cls.code in _REGISTRY:
        raise ValueError(f"duplicate rule code {cls.code}")
    _REGISTRY[cls.code] = cls
    return cls


def all_rules(codes: Optional[Iterable[str]] = None) -> List[Rule]:
    """Instantiate the registered rules (optionally a subset by code)."""
    wanted = set(codes) if codes is not None else None
    unknown = (wanted or set()) - set(_REGISTRY)
    if unknown:
        raise ValueError(f"unknown rule codes: {sorted(unknown)}")
    return [cls() for code, cls in sorted(_REGISTRY.items())
            if wanted is None or code in wanted]


# ---------------------------------------------------------------------------
# module model
# ---------------------------------------------------------------------------

class ModuleInfo:
    """One parsed source file plus the precomputed context rules share:
    import alias map, per-line suppressions, names jitted by call."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        #: node-type index built on first :meth:`nodes` call — every rule
        #: that used to ``ast.walk`` the whole tree for one node type now
        #: shares a single walk per module
        self._node_index: Optional[Dict[type, List[ast.AST]]] = None
        self.aliases = _import_aliases(self.tree)
        self.suppressions = _parse_suppressions(self.lines)
        self.file_suppressions = _parse_file_suppressions(self.lines)
        #: {function name: wrapping jit Call} for names wrapped by a jit
        #: call somewhere in the module (``self._jitted = jax.jit(run)``
        #: marks ``run`` as jitted, keeping its static_argnames reachable)
        self.jit_wrapped_names = _jit_wrapped_names(self)

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def nodes(self, *types: type) -> List[ast.AST]:
        """All nodes of the given types, from a per-module index built by
        one full walk and reused by every rule (the shared AST cache —
        previously each of the ~dozen rules re-walked the tree)."""
        if self._node_index is None:
            index: Dict[type, List[ast.AST]] = {}
            for node in ast.walk(self.tree):
                index.setdefault(type(node), []).append(node)
            self._node_index = index
        if len(types) == 1:
            return list(self._node_index.get(types[0], ()))
        out: List[ast.AST] = []
        for t in types:
            out.extend(self._node_index.get(t, ()))
        return out

    # -- name canonicalization ---------------------------------------------
    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, with the module's
        import aliases resolved (``jnp.asarray`` → ``jax.numpy.asarray``)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])

    def is_suppressed(self, f: Finding) -> bool:
        if "all" in self.file_suppressions \
                or f.rule in self.file_suppressions:
            return True

        def matches(lineno: int) -> bool:
            rules = self.suppressions.get(lineno, ())
            return "all" in rules or f.rule in rules

        if matches(f.line):
            return True
        # a pragma anywhere in the standalone-comment block immediately
        # above the finding line applies (multi-line justifications);
        # a trailing pragma on a previous CODE line does not spill down
        lineno = f.line - 1
        while lineno >= 1 and self.line(lineno).startswith("#"):
            if matches(lineno):
                return True
            lineno -= 1
        return False


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    """{local name: canonical dotted prefix} from the module's imports."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            # relative imports keep the tail (``from .convert import
            # register_op`` → ``convert.register_op``) — enough for
            # suffix-matched names like OP_HANDLERS/register_op
            for a in node.names:
                if a.name == "*":
                    continue
                prefix = f"{node.module}." if node.module else ""
                out[a.asname or a.name] = f"{prefix}{a.name}"
    return out


def _parse_file_suppressions(lines: Sequence[str]) -> Set[str]:
    out: Set[str] = set()
    for text in lines:
        m = _SUPPRESS_FILE_RE.search(text)
        if m:
            spec = m.group(1).strip()
            out |= ({"all"} if spec == "all"
                    else {s.strip() for s in spec.split(",") if s.strip()})
    return out


def _parse_suppressions(lines: Sequence[str]) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            spec = m.group(1).strip()
            out[i] = ({"all"} if spec == "all"
                      else {s.strip() for s in spec.split(",") if s.strip()})
    return out


# -- jit detection shared by the rules --------------------------------------

JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
             "pjit.pjit", "jit", "pjit"}
PARTIAL_NAMES = {"functools.partial", "partial"}


def jit_call_target(module: ModuleInfo, call: ast.Call) -> Optional[ast.Call]:
    """If ``call`` constructs a jitted callable — ``jax.jit(...)`` or
    ``functools.partial(jax.jit, ...)`` — return the inner jit Call-like
    node carrying the keywords, else None."""
    name = module.dotted(call.func)
    if name in JIT_NAMES:
        return call
    if name in PARTIAL_NAMES and call.args \
            and module.dotted(call.args[0]) in JIT_NAMES:
        return call
    return None


def _jit_wrapped_names(module: ModuleInfo) -> Dict[str, ast.Call]:
    out: Dict[str, ast.Call] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and jit_call_target(module, node):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    out[arg.id] = node
    return out


def jit_decoration(module: ModuleInfo, fn: ast.AST) -> Optional[Set[str]]:
    """If ``fn`` (FunctionDef) is jit-decorated or jit-wrapped by name,
    return its set of STATIC parameter names (empty set when none are
    declared); None when the function is not jitted."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            inner = jit_call_target(module, dec)
            if inner is not None:
                return _static_param_names(fn, inner)
        elif module.dotted(dec) in JIT_NAMES:
            return set()
    wrap = module.jit_wrapped_names.get(fn.name)
    if wrap is not None:
        return _static_param_names(fn, wrap)
    return None


def _static_param_names(fn, jit_call: ast.Call) -> Set[str]:
    """static_argnames / static_argnums keywords → parameter-name set."""
    static: Set[str] = set()
    pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in jit_call.keywords:
        if kw.arg == "static_argnames":
            for v in _const_elements(kw.value):
                if isinstance(v, str):
                    static.add(v)
        elif kw.arg == "static_argnums":
            for v in _const_elements(kw.value):
                if isinstance(v, int) and 0 <= v < len(pos):
                    static.add(pos[v])
    return static


def _const_elements(node: ast.AST) -> List[object]:
    if isinstance(node, ast.Constant):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts if isinstance(e, ast.Constant)]
    return []


# ---------------------------------------------------------------------------
# project model + driver
# ---------------------------------------------------------------------------

@dataclass
class Project:
    """Everything the analyzer saw: parsed modules plus sibling stubs."""

    root: str
    modules: List[ModuleInfo] = field(default_factory=list)
    #: {module relpath: stub relpath} for modules with a sibling ``.pyi``
    stubs: Dict[str, str] = field(default_factory=dict)
    #: files that failed to parse, as (relpath, error) — reported, not fatal
    parse_errors: List[tuple] = field(default_factory=list)

    def module(self, relpath: str) -> Optional[ModuleInfo]:
        for m in self.modules:
            if m.relpath == relpath:
                return m
        return None


def load_project(paths: Sequence[str], root: Optional[str] = None,
                 jobs: int = 1) -> Project:
    """Parse every ``*.py`` under ``paths`` (files or directories).

    ``jobs > 1`` reads and parses files on a thread pool — ``ast.parse``
    holds the GIL, so the win is mostly overlapped file I/O, but the
    results are identical and order is restored after the fan-out.
    """
    root = os.path.abspath(root or os.getcwd())
    project = Project(root=root)
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            files.append(p)
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))

    def parse_one(path: str):
        relpath = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            return path, relpath, ModuleInfo(relpath, source), None
        except (OSError, SyntaxError, ValueError) as e:
            return path, relpath, None, str(e)

    ordered = sorted(set(files))
    if jobs > 1 and len(ordered) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=jobs) as pool:
            results = list(pool.map(parse_one, ordered))
    else:
        results = [parse_one(p) for p in ordered]
    for path, relpath, module, error in results:
        if module is None:
            project.parse_errors.append((relpath, error))
            continue
        project.modules.append(module)
        stub = os.path.splitext(path)[0] + ".pyi"
        if os.path.exists(stub):
            project.stubs[relpath] = os.path.relpath(stub, root)
    return project


def analyze_project(project: Project,
                    rules: Optional[Sequence[Rule]] = None,
                    keep_suppressed: bool = False,
                    jobs: int = 1):
    """Run the rules; returns (findings, suppressed) sorted by location.

    ``jobs > 1`` runs the per-module rules across modules on a thread
    pool (each module's rule set is independent); project-scope rules
    stay serial — they see the whole project at once by design.
    """
    rules = list(rules) if rules is not None else all_rules()
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    by_relpath = {m.relpath: m for m in project.modules}
    module_rules = [r for r in rules if not r.project_scope]
    project_rules = [r for r in rules if r.project_scope]

    def check_module(module: ModuleInfo) -> List[Finding]:
        out: List[Finding] = []
        for rule in module_rules:
            out.extend(rule.check(module))
        return out

    raw: List[Finding] = []
    if jobs > 1 and len(project.modules) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=jobs) as pool:
            for batch in pool.map(check_module, project.modules):
                raw.extend(batch)
    else:
        for module in project.modules:
            raw.extend(check_module(module))
    for rule in project_rules:
        raw.extend(rule.check_project(project))
    for f in raw:
        module = by_relpath.get(f.path)
        if module is not None and module.is_suppressed(f):
            suppressed.append(f)
        else:
            findings.append(f)
    key = lambda f: (f.path, f.line, f.col, f.rule)   # noqa: E731
    findings.sort(key=key)
    suppressed.sort(key=key)
    return (findings, suppressed) if keep_suppressed else (findings, [])


def analyze_source(source: str, relpath: str = "<string>",
                   rules: Optional[Sequence[Rule]] = None,
                   keep_suppressed: bool = False):
    """Analyze one in-memory snippet (the test-fixture entry point).
    Project-scope rules see a single-module project."""
    module = ModuleInfo(relpath, source)
    project = Project(root=os.getcwd(), modules=[module])
    return analyze_project(project, rules=rules,
                           keep_suppressed=keep_suppressed)
