"""``python -m tools.tpulint`` entry point."""

import sys

from .cli import main

sys.exit(main())
