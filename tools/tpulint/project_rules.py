"""Project-scope rules: cross-file checks over the whole parsed tree.

TPU005 cross-checks the ONNX ``OP_HANDLERS`` dispatch table against every
module that registers into it; TPU006 cross-checks ``.pyi`` stubs against
the modules they describe.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from .core import Finding, ModuleInfo, Project, Rule, register_rule

REGISTRY_NAME = "OP_HANDLERS"
DECORATOR_NAME = "register_op"


class Registration(NamedTuple):
    op: str                 # ONNX op name ("Add")
    module: ModuleInfo
    node: ast.AST           # the registering statement / decorator
    value: Optional[ast.AST]  # RHS expression when known (None for loops)


def _registry_subscript(module: ModuleInfo, node: ast.AST) -> bool:
    return (isinstance(node, ast.Subscript)
            and (module.dotted(node.value) or "").split(".")[-1]
            == REGISTRY_NAME)


def _top_level_names(tree: ast.AST) -> Set[str]:
    """Names bound at module top level (defs, classes, assigns, imports,
    for-loop targets — loop registrations bind ``_name``/``_fn``)."""
    out: Set[str] = set()
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out.add(node.name)
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                if a.name != "*":
                    out.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, (ast.For, ast.While, ast.If, ast.Try,
                               ast.With)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Store):
                    out.add(sub.id)
                elif isinstance(sub, (ast.FunctionDef, ast.ClassDef)):
                    out.add(sub.name)
    return out


def _collect_registrations(module: ModuleInfo) -> List[Registration]:
    regs: List[Registration] = []
    for node in ast.walk(module.tree):
        # @register_op("X") decorating a handler
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) \
                        and (module.dotted(dec.func) or "").split(".")[-1] \
                        == DECORATOR_NAME \
                        and dec.args \
                        and isinstance(dec.args[0], ast.Constant) \
                        and isinstance(dec.args[0].value, str):
                    regs.append(Registration(dec.args[0].value, module,
                                             dec, None))
        # register_op("X")(handler) called directly (not as a decorator)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Call) \
                and (module.dotted(node.func.func) or "").split(".")[-1] \
                == DECORATOR_NAME \
                and node.func.args \
                and isinstance(node.func.args[0], ast.Constant) \
                and isinstance(node.func.args[0].value, str):
            regs.append(Registration(node.func.args[0].value, module,
                                     node, node.args[0] if node.args
                                     else None))
        # OP_HANDLERS["X"] = handler
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and _registry_subscript(module, node.targets[0]):
            sub = node.targets[0]
            key = sub.slice
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                regs.append(Registration(key.value, module, node,
                                         node.value))
        # for _name, _fn in [("Add", jnp.add), ...]: OP_HANDLERS[_name] = ...
        elif isinstance(node, ast.For) \
                and isinstance(node.iter, (ast.List, ast.Tuple)):
            loop_keys: List[Tuple[str, ast.AST]] = []
            for elt in node.iter.elts:
                if isinstance(elt, (ast.Tuple, ast.List)) and elt.elts \
                        and isinstance(elt.elts[0], ast.Constant) \
                        and isinstance(elt.elts[0].value, str):
                    loop_keys.append((elt.elts[0].value, elt))
            if not loop_keys:
                continue
            writes_registry = any(
                isinstance(stmt, ast.Assign)
                and any(_registry_subscript(module, t)
                        and isinstance(t.slice, ast.Name)
                        for t in stmt.targets)
                for stmt in node.body)
            if writes_registry:
                for op, elt in loop_keys:
                    regs.append(Registration(op, module, elt, None))
    return regs


def _registers_ops(module: ModuleInfo) -> bool:
    """Does this module import (or define) the registry machinery?"""
    names = set(module.aliases)
    return REGISTRY_NAME in names or DECORATOR_NAME in names \
        or REGISTRY_NAME in _top_level_names(module.tree)


@register_rule
class OpRegistryDrift(Rule):
    code = "TPU005"
    name = "op-registry-drift"
    severity = "error"
    project_scope = True
    doc = ("The ONNX dispatch table (``OP_HANDLERS`` in onnx/convert.py) "
           "cross-checked against every module registering into it: "
           "duplicate/shadowed op names (second registration silently "
           "wins), dangling registrations (RHS name not defined in the "
           "module), handler-shaped functions never registered nor "
           "referenced (dead ops), and registering modules the defining "
           "module never imports (their ops never land in the table).")

    def check_project(self, project: Project):
        findings: List[Finding] = []
        defining: Optional[ModuleInfo] = None
        registering: List[ModuleInfo] = []
        for m in project.modules:
            has_def = any(
                isinstance(n, (ast.Assign, ast.AnnAssign))
                and any((m.dotted(t) or "") == REGISTRY_NAME
                        for t in (n.targets if isinstance(n, ast.Assign)
                                  else [n.target]))
                for n in m.tree.body)
            if has_def:
                defining = m
            if has_def or _registers_ops(m):
                registering.append(m)
        if not registering:
            return iter(())

        # 1. duplicate / shadowed op names -- the later write silently wins
        seen: Dict[str, Registration] = {}
        for m in registering:
            for reg in _collect_registrations(m):
                first = seen.get(reg.op)
                if first is not None:
                    findings.append(self.finding(
                        m, reg.node,
                        f"op '{reg.op}' registered twice (first at "
                        f"{first.module.relpath}:{first.node.lineno}); the "
                        f"later registration silently shadows the first"))
                else:
                    seen[reg.op] = reg

        # 2. dangling registrations -- bare-Name RHS not bound in module
        for m in registering:
            bound = _top_level_names(m.tree)
            for reg in _collect_registrations(m):
                if isinstance(reg.value, ast.Name) \
                        and reg.value.id not in bound:
                    findings.append(self.finding(
                        m, reg.node,
                        f"op '{reg.op}' registered to undefined name "
                        f"'{reg.value.id}' — dangling registration"))

        # 3. handler-shaped functions never registered nor referenced
        registered_ids: Set[int] = set()
        for m in registering:
            referenced = {n.id for n in ast.walk(m.tree)
                          if isinstance(n, ast.Name)
                          and isinstance(n.ctx, ast.Load)}
            decorated_or_assigned: Set[str] = set()
            for node in ast.walk(m.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node.decorator_list:
                    decorated_or_assigned.add(node.name)
            for node in m.tree.body:
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                args = node.args
                params = [a.arg for a in args.posonlyargs + args.args]
                handler_shaped = (len(params) == 3
                                  and not args.vararg and not args.kwarg
                                  and params[0] in ("node", "n"))
                if not handler_shaped:
                    continue
                if node.name in decorated_or_assigned \
                        or node.name in referenced:
                    continue
                findings.append(self.finding(
                    m, node,
                    f"handler-shaped function '{node.name}(node, inputs, "
                    f"ctx)' is neither registered via {DECORATOR_NAME} nor "
                    f"referenced — the op it implements is unreachable",
                    severity="warning"))
        del registered_ids

        # 4. registering modules the defining module never imports
        if defining is not None:
            pkg_dir = os.path.dirname(defining.relpath)
            reachable: Set[str] = set()
            importers = [defining]
            init = project.module(os.path.join(pkg_dir, "__init__.py")
                                  if pkg_dir else "__init__.py")
            if init is not None:
                importers.append(init)
            for imp in importers:
                for node in ast.walk(imp.tree):
                    if isinstance(node, ast.ImportFrom):
                        for a in node.names:
                            reachable.add(a.name)
                        if node.module:
                            reachable.add(node.module.split(".")[-1])
                    elif isinstance(node, ast.Import):
                        for a in node.names:
                            reachable.add(a.name.split(".")[-1])
            for m in registering:
                if m is defining:
                    continue
                if os.path.dirname(m.relpath) != pkg_dir:
                    continue
                basename = os.path.splitext(
                    os.path.basename(m.relpath))[0]
                if basename not in reachable and _collect_registrations(m):
                    findings.append(self.finding(
                        m, m.tree.body[0] if m.tree.body else m.tree,
                        f"module registers ops but is never imported by "
                        f"{defining.relpath} (or the package __init__) — "
                        f"its registrations never land in the dispatch "
                        f"table"))
        return iter(findings)


# ---------------------------------------------------------------------------
# TPU006 — stub drift
# ---------------------------------------------------------------------------

@register_rule
class StubDrift(Rule):
    code = "TPU006"
    name = "stub-drift"
    severity = "warning"
    project_scope = True
    doc = ("A sibling ``.pyi`` stub naming top-level classes/functions its "
           "module no longer defines. One-directional on purpose: the "
           "generated stubs end in a module ``__getattr__`` catch-all, so "
           "module names missing from a stub are fine — stub names missing "
           "from the module are lies.")

    def check_project(self, project: Project):
        findings: List[Finding] = []
        for mod_rel, stub_rel in sorted(project.stubs.items()):
            module = project.module(mod_rel)
            if module is None:
                continue
            stub_path = os.path.join(project.root, stub_rel)
            try:
                with open(stub_path, encoding="utf-8") as fh:
                    stub_source = fh.read()
                stub_tree = ast.parse(stub_source, filename=stub_rel)
                stub_lines = stub_source.splitlines()
            except (OSError, SyntaxError) as e:
                findings.append(Finding(
                    rule=self.code, path=stub_rel, line=1, col=0,
                    severity="error",
                    message=f"stub failed to parse: {e}", snippet=""))
                continue
            module_names = _top_level_names(module.tree)
            for node in stub_tree.body:
                names: List[Tuple[str, ast.AST]] = []
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    names.append((node.name, node))
                elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Name):
                            names.append((t.id, node))
                for name, at in names:
                    if name.startswith("__"):
                        continue  # __getattr__, __all__, __version__ ...
                    if name not in module_names:
                        lineno = getattr(at, "lineno", 1)
                        snippet = stub_lines[lineno - 1].strip() \
                            if 1 <= lineno <= len(stub_lines) else ""
                        findings.append(Finding(
                            rule=self.code, path=stub_rel,
                            line=lineno,
                            col=getattr(at, "col_offset", 0),
                            severity=self.severity,
                            message=(f"stub declares '{name}' but "
                                     f"{mod_rel} no longer defines it"),
                            snippet=snippet))
        return iter(findings)
